"""L4 — the client facade.

Reference: `Redisson.java` (`create(Config)` picks a ConnectionManager,
`Redisson.java:96-120`; 60+ typed getters bind objects to the shared
CommandSyncService). Here create() picks a backend by config mode, wraps it
with the RoutingBackend (sketch tier + structure tier), builds the executor
waist around it, and the getters hand out objects bound to it.
"""

from __future__ import annotations

import threading as _threading
from typing import Callable, Optional

from redisson_tpu.codecs import get_codec
from redisson_tpu.config import Config, TpuConfig
from redisson_tpu.eviction import EvictionScheduler
from redisson_tpu.executor import CommandExecutor
from redisson_tpu.models.batch import RBatch
from redisson_tpu.models.bitset import RBitSet
from redisson_tpu.models.bloomfilter import RBloomFilter
from redisson_tpu.models.bucket import RAtomicDouble, RAtomicLong, RBucket, RBuckets
from redisson_tpu.models.collections import RList, RSet
from redisson_tpu.models.geo import RGeo
from redisson_tpu.models.hyperloglog import RHyperLogLog
from redisson_tpu.models.keys import RKeys
from redisson_tpu.models.lock import (
    LockWatchdog,
    RCountDownLatch,
    RFairLock,
    RLock,
    RMultiLock,
    RReadWriteLock,
    RSemaphore,
    new_client_id,
)
from redisson_tpu.models.map import RMap
from redisson_tpu.models.mapcache import RMapCache, RSetCache
from redisson_tpu.models.multimap import RListMultimap, RSetMultimap
from redisson_tpu.models.queue import RBlockingDeque, RBlockingQueue, RDeque, RQueue
from redisson_tpu.models.scoredsortedset import RLexSortedSet, RScoredSortedSet
from redisson_tpu.models.sortedset import RSortedSet
from redisson_tpu.models.topic import RPatternTopic, RTopic
from redisson_tpu.routing import RoutingBackend
from redisson_tpu.store import SketchStore


class RedissonTPU:
    """The RedissonClient analogue."""

    def __init__(self, config: Optional[Config] = None):
        self.config = config or Config()
        mode = self._mode = self.config.mode()
        self._codec = get_codec(self.config.codec)
        self.id = new_client_id()  # connection-manager UUID analogue
        # Cluster tier handle (cluster/): the ClusterManager on a cluster
        # facade client, None everywhere else (including shard members).
        self.cluster = None

        ccfg = self.config.cluster
        if ccfg is not None and ccfg.shard_id == -1:
            # Slot-sharded namespace: this client is the FACADE — it builds
            # N shard clients (each one re-enters __init__ with shard_id
            # >= 0) and dispatches through the ClusterRouter instead of its
            # own executor. The compute section (local/tpu) configures the
            # per-shard stacks, not this client.
            self._init_cluster_mode()
            self._start_wire()
            return
        if mode == "redis":
            # Passthrough: every op translates to Redis commands over RESP —
            # the reference's own execution model (server executes, client
            # is stateless).
            self._init_redis_mode()
            self._start_wire()
            return
        # Device-backed modes compile kernels: persist them across processes
        # (~7 s per cold (op, shape) on the tunneled chip otherwise).
        from redisson_tpu.tpu_boot import enable_compilation_cache

        enable_compilation_cache()
        if mode == "pod":
            from redisson_tpu.parallel.backend_pod import PodBackend

            tcfg = self.config.pod
            if getattr(tcfg, "hll_hash", "murmur3") == "redis":
                raise NotImplementedError(
                    "hll_hash='redis' is a single-chip (local/tpu) mode "
                    "feature; the pod bank kernels and native pre-hash "
                    "implement the murmur3 family")
            sketch = PodBackend(tcfg)
            self._store = sketch.store
        else:
            # 'local' runs the same sketch engine on whatever platform jax
            # gives us (cpu in tests); 'tpu' expects a TPU device.
            import jax

            from redisson_tpu.backend_tpu import TpuBackend

            tcfg = self.config.tpu or TpuConfig()
            device = jax.devices()[min(tcfg.device_index, len(jax.devices()) - 1)]
            self._store = SketchStore(device=device)
            sketch = TpuBackend(
                self._store, hll_impl=tcfg.hll_impl, seed=tcfg.hash_seed,
                ingest=getattr(tcfg, "ingest", "auto"),
                hll_hash=getattr(tcfg, "hll_hash", "murmur3"),
                read_cache_entries=getattr(tcfg, "read_cache_entries", 1024),
            )
        self._routing = RoutingBackend(sketch)
        if ccfg is not None and ccfg.shard_id >= 0:
            # Shard member of a cluster: enforce slot ownership at the
            # dispatch waist. Installed HERE — before the executor and
            # before persist recovery — so replayed journal records cross
            # the same accept/reject boundary live traffic did.
            from redisson_tpu.cluster.shard import SlotOwnershipBackend

            self._routing = SlotOwnershipBackend(self._routing, ccfg.shard_id)
        elif ccfg is not None and ccfg.shard_id == -2:
            # Mesh data plane: this client is the ONE shared engine stack
            # behind every logical shard. Same waist, but the guard holds
            # the whole slot->shard table (MeshOwnershipBackend), and the
            # HLL bank goes onto a device mesh BEFORE any bank-touching
            # op — including persist recovery below — so every row the
            # engine ever materializes is mesh-sharded.
            from redisson_tpu.cluster.shard import MeshOwnershipBackend

            guard = MeshOwnershipBackend(self._routing, ccfg.num_shards)
            self._routing = guard
            if hasattr(sketch, "attach_mesh"):
                from redisson_tpu.parallel.mesh import SLOT_AXIS, get_mesh

                sketch.attach_mesh(get_mesh(axis=SLOT_AXIS),
                                   ccfg.num_shards, guard.shard_of_key)
        self._backend = self._routing
        self._widths = tuple(tcfg.key_width_buckets)
        from redisson_tpu.observability import MetricsRegistry

        self.metrics = MetricsRegistry()
        # memstat (memstat/): the exact byte ledger is ALWAYS on and must
        # be wired before any traffic can flow — persist recovery below
        # replays ops through the store seam, and those bytes must land
        # in the ledger like live traffic.
        from redisson_tpu.memstat import (MemLedger, MemoryReport,
                                          PressureMonitor)
        from redisson_tpu.observability import register_memstat

        self.memstat = MemLedger()
        self._store.accounting = self.memstat
        if hasattr(sketch, "_account_bank"):
            # Single-chip tier: bank lifecycle hooks + scratch meters.
            sketch.accounting = self.memstat
            sketch._account_bank()
            self.memstat.register_meter(
                "backend.bloom_mirrors",
                lambda s=sketch: s.scratch_bytes()["bloom_mirrors"],
                "scratch")
            self.memstat.register_meter(
                "backend.delta_scratch",
                lambda s=sketch: s.scratch_bytes()["delta_scratch"],
                "scratch")
        self._build_executor(self._routing, max_batch_keys=tcfg.max_batch_keys)
        if ccfg is not None and ccfg.shard_id >= 0:
            # Shard-tagged dispatch: pipeline_stats / traces carry which
            # shard's executor did the work (per-shard attribution).
            self._executor.shard_tag = ccfg.shard_id
        self.memstat.register_meter(
            "executor.staging", self._executor.staging_bytes, "staging")
        mcfg = self.config.memory
        self._pressure = None
        if mcfg is not None and mcfg.high_watermark_bytes > 0:
            self._pressure = PressureMonitor(self.memstat, mcfg)
        self._memreport = MemoryReport(
            self.memstat, store=self._store, backend=sketch,
            pressure=self._pressure)
        register_memstat(self.metrics, self.memstat, self._pressure)
        if self.serve is not None:
            self.serve.attach_memstat(self.memstat, self._pressure)
        if self.trace is not None:
            self.trace.attach_memstat(self.memstat)
        cache = getattr(sketch, "read_cache", None)
        if cache is not None:
            from redisson_tpu.observability import register_read_cache

            register_read_cache(self.metrics, cache)
            self.memstat.register_meter(
                "backend.read_cache", cache.content_bytes, "cache")
        if callable(getattr(sketch, "ingest_stats", None)):
            from redisson_tpu.observability import register_delta_ingest

            register_delta_ingest(self.metrics, sketch)
        self._pubsub = self._routing.pubsub
        self._watchdog = LockWatchdog(self._executor)
        self._eviction = EvictionScheduler(self._executor)

        # Durability tier: redis config alongside tpu/pod wires the flush
        # path (SURVEY.md §7 step 6); flush_interval_s > 0 starts the
        # periodic flusher.
        self._remote_services = {}
        self._durability = None
        self._resp = None
        # Native durability (persist/): journal + snapshots + auto-recover.
        # Wired BEFORE user traffic can flow (the getters don't exist yet)
        # and before the redis durability tier, so a recovered engine
        # flushes recovered state, not a partial one.
        self._persist = None
        pcfg = self.config.persist
        if pcfg is not None and pcfg.dir:
            from redisson_tpu.persist import PersistenceManager

            self._persist = PersistenceManager(self, pcfg)
            try:
                self._persist.start()
            except Exception:
                self.shutdown()
                raise
            if self.trace is not None:
                # Fsync durations feed LATENCY HISTORY + the fsync
                # histogram even for unsampled ops.
                journal = self._executor.journal
                if journal is not None:
                    journal.set_trace(self.trace)
            # On-disk byte meters (memstat 'disk' category): journal
            # segments + kept snapshot directories.
            journal = self._executor.journal
            if journal is not None:
                self.memstat.register_meter(
                    "persist.journal", journal.disk_bytes, "disk")
            if self._persist.snapshotter is not None:
                self.memstat.register_meter(
                    "persist.snapshots",
                    self._persist.snapshotter.disk_bytes, "disk")
        # Fault subsystem (fault/): taxonomy is always active (the backends
        # classify unconditionally); injection / watchdog / self-healing
        # rebuild only attach when Config.use_faults() was called. Wired
        # after persist so the rebuild path sees a recovered journal.
        self._fault = None
        fcfg = self.config.faults
        if fcfg is not None:
            from redisson_tpu.fault import FaultManager

            self._fault = FaultManager(self, fcfg)
            try:
                self._fault.start()
            except Exception:
                self.shutdown()
                raise
        # Read-replica fleet (replica/): N serving replicas tailing the
        # journal (persist IS the replication stream) + bounded-staleness
        # read routing + automatic failover. Wired after fault so the
        # DeviceLost trigger can chain onto the fault listener fan-out.
        self._replicas = None
        repcfg = self.config.replicas
        if repcfg is not None:
            if self._persist is None:
                self.shutdown()
                raise ValueError(
                    "Config.replicas requires Config.persist with a dir — "
                    "replicas tail that journal as the replication stream")
            from redisson_tpu.replica import ReplicaManager

            self._replicas = ReplicaManager(self, repcfg)
            try:
                self._replicas.start()
            except Exception:
                self.shutdown()
                raise
            # Model getters bind to _dispatch lazily, so every object
            # created from here on routes reads through the fleet.
            self._dispatch = self._replicas.router
            from redisson_tpu.observability import register_replica

            register_replica(self.metrics, self._replicas)
        # Geo-replication site (geo/): this engine becomes one active site
        # in a cross-site mesh; its journal ships to peers as CRDT delta
        # planes. Wired after replicas (both tail the same journal) —
        # peering happens at runtime via geo.connect_sites(...).
        self._geo = None
        gcfg = self.config.geo
        if gcfg is not None:
            if self._persist is None:
                self.shutdown()
                raise ValueError(
                    "Config.geo requires Config.persist with a dir — the "
                    "persist journal is the geo replication transport")
            from redisson_tpu.geo import GeoManager

            self._geo = GeoManager(self, gcfg)
            try:
                self._geo.start()
            except Exception:
                self.shutdown()
                raise
            from redisson_tpu.observability import register_geo

            register_geo(self.metrics, self._geo)
        if self.config.redis is not None and mode != "redis":
            try:
                self._connect_durability()
            except Exception:
                # Startup must not leak the already-running background
                # threads when the first dial fails.
                self.shutdown()
                raise
        # RESP wire front-end (wire/): the TCP listener real redis clients
        # connect to. Wired LAST so the first socket read finds the full
        # stack (serve admission, persist, replicas) already standing.
        self._start_wire()

    def _start_wire(self) -> None:
        """Start the wire front-end when `Config.wire` is set
        (PersistenceManager-style lifecycle: failures unwind the whole
        client). One WireServer in single-engine modes; the cluster facade
        starts one per shard behind a shared -MOVED/-ASK address table."""
        self.wire = None
        wcfg = self.config.wire
        if wcfg is None:
            return
        if self.cluster is not None:
            from redisson_tpu.wire import ClusterWireFrontend

            self.wire = ClusterWireFrontend(self, wcfg)
        else:
            from redisson_tpu.wire import WireServer

            self.wire = WireServer(self, wcfg)
        try:
            self.wire.start()
        except Exception:
            self.wire = None
            self.shutdown()
            raise
        if getattr(self, "metrics", None) is not None:
            from redisson_tpu.observability import register_wire

            register_wire(self.metrics, self.wire)

    def _build_executor(self, backend, max_batch_keys=None):
        """Build the executor waist and, when `Config.serve` is set, the QoS
        serving layer in front of it (shared by device and redis modes).

        Sets `self._executor` (the raw waist — internal maintenance traffic:
        lock watchdog renewals, eviction sweeps, durability flushes, which
        must never be shed or deadline-expired) and `self._dispatch` (what
        model getters bind to — the ServingLayer when configured, else the
        raw executor)."""
        from redisson_tpu.observability import ExecutorMetrics

        scfg = self.config.serve
        policy = None
        if scfg is not None:
            from redisson_tpu.serve import AdaptiveBatchPolicy, CostModel

            policy = AdaptiveBatchPolicy(
                CostModel(),
                max_linger_s=scfg.max_linger_s,
                target_batch_service_s=scfg.target_batch_service_s,
                min_batch_keys=scfg.min_batch_keys,
            )
        # Trace subsystem (trace/): built before the executor so every op —
        # including maintenance traffic — flows through the sampling hook;
        # the serving layer (below) picks it up off the executor for the
        # admission/retry annotations.
        self.trace = None
        trcfg = getattr(self.config, "trace", None)
        if trcfg is not None:
            from redisson_tpu.observability import register_trace
            from redisson_tpu.trace import TraceManager

            self.trace = TraceManager(trcfg, registry=self.metrics)
            register_trace(self.metrics, self.trace)
        kwargs = {}
        if max_batch_keys is not None:
            kwargs["max_batch_keys"] = max_batch_keys
        self._executor = CommandExecutor(
            backend, metrics=ExecutorMetrics(self.metrics), policy=policy,
            inflight_runs=getattr(self.config, "inflight_runs", 2),
            trace=self.trace,
            **kwargs)
        self.metrics.gauge("executor.queue_depth", self._executor.queue_depth)
        self.metrics.gauge(
            "executor.overlap_ratio",
            lambda: self._executor.pipeline_stats()["overlap_ratio"])
        if scfg is not None:
            from redisson_tpu.serve import ServingLayer

            self.serve = ServingLayer(self._executor, scfg,
                                      registry=self.metrics)
            self._dispatch = self.serve
        else:
            self.serve = None
            self._dispatch = self._executor

    def _make_resp_pool(self):
        """Connection pool to the configured redis endpoint — shared by
        passthrough traffic, blocking pops, coordination scripts and
        durability flushes (ConnectionPool.java role). With slave_addresses
        configured, a MasterSlaveRouter (write-to-master, balanced reads,
        freeze-driven promotion, MOVED/ASK redirects) wraps one pool per
        endpoint (MasterSlaveEntry.java:53-250)."""
        from urllib.parse import urlparse

        from redisson_tpu.interop.pool import RespConnectionPool

        rcfg = self.config.redis

        def factory(host: str, port: int) -> RespConnectionPool:
            return RespConnectionPool(
                host=host or "127.0.0.1",
                port=port or 6379,
                password=rcfg.password,
                db=rcfg.database,
                timeout=rcfg.timeout_ms / 1000.0,
                retry_attempts=rcfg.retry_attempts,
                retry_interval=rcfg.retry_interval_ms / 1000.0,
                size=rcfg.connection_pool_size,
                min_idle=rcfg.connection_minimum_idle_size,
                failed_attempts=rcfg.failed_attempts,
                reconnection_timeout=rcfg.reconnection_timeout_ms / 1000.0,
                idle_timeout=rcfg.idle_connection_timeout_ms / 1000.0,
            )

        u = urlparse(rcfg.address)
        if rcfg.cluster_addresses:
            from redisson_tpu.interop.topology_redis import (
                ClusterRouter, ClusterTopologyManager)

            router = ClusterRouter(factory, rcfg.cluster_addresses)
            mgr = ClusterTopologyManager(
                router,
                scan_interval_s=rcfg.cluster_scan_interval_ms / 1000.0)
            try:
                mgr.bootstrap()
            except Exception:
                # bootstrap dialed pools through the router; nobody above
                # holds a reference yet, so reclaim them (and the scan
                # thread) here or they leak per failed create().
                mgr.close()
                router.close()
                raise
            self._cluster_manager = mgr
            return router
        if rcfg.sentinel_addresses:
            from redisson_tpu.interop.resp_client import SyncPubSubClient
            from redisson_tpu.interop.topology_redis import SentinelManager

            def pubsub_factory(host: str, port: int) -> SyncPubSubClient:
                return SyncPubSubClient(
                    host=host, port=port, password=rcfg.password,
                    timeout=rcfg.timeout_ms / 1000.0)

            from redisson_tpu.interop.topology_redis import make_balancer

            return SentinelManager(
                factory, rcfg.sentinel_addresses, rcfg.master_name,
                read_mode=rcfg.read_mode, pubsub_factory=pubsub_factory,
                timeout=rcfg.timeout_ms / 1000.0,
                sentinel_password=rcfg.password,
                balancer=make_balancer(rcfg.load_balancer, rcfg.slave_weights,
                                       rcfg.default_slave_weight),
            )
        if rcfg.slave_addresses:
            from redisson_tpu.interop.topology_redis import (
                MasterSlaveRouter, RolePollingMonitor, make_balancer)

            router = MasterSlaveRouter(
                factory,
                f"{u.hostname or '127.0.0.1'}:{u.port or 6379}",
                rcfg.slave_addresses,
                read_mode=rcfg.read_mode,
                balancer=make_balancer(rcfg.load_balancer, rcfg.slave_weights,
                                       rcfg.default_slave_weight),
            )
            if rcfg.role_scan_interval_ms > 0:
                self._role_monitor = RolePollingMonitor(
                    router,
                    scan_interval_s=rcfg.role_scan_interval_ms / 1000.0,
                )
            return router
        pool = factory(u.hostname, u.port)
        return pool

    def _init_cluster_mode(self):
        from redisson_tpu.cluster import ClusterManager
        from redisson_tpu.observability import MetricsRegistry

        self._mode = "cluster"
        self.cluster = ClusterManager(self.config)
        # The router speaks the executor's narrow waist (execute_async /
        # execute_many / execute_sync / batch), so every model getter binds
        # to it unchanged — per-owner batch splitting and MOVED retries
        # happen below the models, like the reference's CommandAsyncService
        # hides slot routing from RBucket et al.
        self._dispatch = self._routing = self.cluster.router
        self._store = None
        self.metrics = MetricsRegistry()
        self.metrics.gauge("cluster.queue_depth",
                           self.cluster.router.queue_depth)
        # Per-shard subsystems (memstat / trace / serve / persist) live on
        # the shard clients — see ClusterManager.stats() for the rollup.
        self.memstat = None
        self._pressure = None
        self._memreport = None
        self.serve = None
        self.trace = None
        self._widths = (16, 32, 64, 128, 256)
        # Engine pub/sub and lock coordination are per-shard hubs; a
        # keyspace-wide topic surface needs a fan-out hub (future work), so
        # the facade declines rather than silently scoping to one shard.
        self._pubsub = None
        self._watchdog = None
        self._eviction = EvictionScheduler(self.cluster.router)
        self._remote_services = {}
        self._durability = None
        self._resp = None
        self._persist = None
        self._fault = None

    # -- CLUSTER command facade (cluster/; CLUSTER INFO/SLOTS/KEYSLOT) -------

    def _require_cluster(self, command: str):
        if self.cluster is None:
            raise RuntimeError(f"{command} requires Config.use_cluster()")
        return self.cluster

    def cluster_keyslot(self, key: str) -> int:
        """CLUSTER KEYSLOT analogue (hashtag-aware CRC16 slot)."""
        return self._require_cluster("CLUSTER KEYSLOT").cluster_keyslot(key)

    def cluster_slots(self):
        """CLUSTER SLOTS analogue: (start, end_inclusive, shard_id,
        replica_entries) ranges; each replica entry is {id, watermark, lag}
        for the owning shard's fleet, like redis lists replicas per range."""
        return self._require_cluster("CLUSTER SLOTS").cluster_slots()

    def cluster_info(self):
        """CLUSTER INFO analogue (cluster_state, slots_assigned, ...)."""
        return self._require_cluster("CLUSTER INFO").cluster_info()

    def _init_redis_mode(self):
        from redisson_tpu.interop.backend_redis import RedisBackend
        from redisson_tpu.observability import MetricsRegistry

        if self.config.persist is not None and self.config.persist.dir:
            raise NotImplementedError(
                "persist/ journals an engine-owned state tier; in redis "
                "passthrough mode the server owns the state (use the "
                "server's own AOF/RDB)")
        self._persist = None
        self._resp = self._make_resp_pool()
        try:
            self._resp.connect()
        except Exception:
            # Reclaim every background resource already started (the role
            # monitor thread would otherwise poll forever).
            if getattr(self, "_role_monitor", None) is not None:
                self._role_monitor.close()
                self._role_monitor = None
            if getattr(self, "_cluster_manager", None) is not None:
                self._cluster_manager.close()
                self._cluster_manager = None
            self._resp.close()  # reclaim the IO-loop thread
            raise
        self._backend = self._routing = RedisBackend(
            self._resp, hash_seed=getattr(self.config.redis, "hash_seed", 0))
        self._store = None
        self._widths = (16, 32, 64, 128, 256)
        # Passthrough mode holds no device state: the server owns memory
        # introspection (MEMORY USAGE et al. against the real server).
        self.memstat = None
        self._pressure = None
        self._memreport = None
        self.metrics = MetricsRegistry()
        self._build_executor(self._backend)
        # Observability for the blocking-pop silent-loss window (reply
        # window expires exactly as the server pops, or a mid-reply drop
        # forces a re-drive — r2 advisor finding): per-backend-instance so
        # two clients in one process never pool their counts.
        self.metrics.gauge("redis.blocking_pop_loss_windows",
                           lambda: self._backend.blocking_pop_loss_windows)
        # Engine-backed tiers are absent; coordination runs as server-side
        # Lua + pub/sub wake-ups instead (interop/coordination_redis.py) —
        # the reference's own execution model.
        self._pubsub = None
        self._watchdog = None
        # Redis-mode map caches register their Lua sweep here, so TTL
        # entries are physically removed without manual evict_expired calls
        # (the reference registers every map cache with EvictionScheduler,
        # RedissonMapCache.java:91-96; r2 advisor finding #3).
        self._eviction = EvictionScheduler()
        self._remote_services = {}
        self._durability = None
        from redisson_tpu.interop.coordination_redis import ScriptRunner

        self._redis_scripts = ScriptRunner(self._resp)
        self._redis_pubsub = None  # lazy: dedicated subscribe connection
        self._redis_watchdog = None  # lazy: lock lease renewal thread
        self._redis_coord_lock = _threading.Lock()

    def _redis_coordination(self):
        """(scripts, pubsub, watchdog) for redis-mode coordination objects;
        the subscribe connection and the renewal thread start on first use
        (the reference also dials pub/sub connections lazily,
        MasterSlaveConnectionManager.java:306-403)."""
        from urllib.parse import urlparse

        from redisson_tpu.interop.coordination_redis import RedisLockWatchdog
        from redisson_tpu.interop.resp_client import SyncPubSubClient

        with self._redis_coord_lock:
            if self._redis_pubsub is None:
                rcfg = self.config.redis
                u = urlparse(rcfg.address)
                # Follow master promotion: when a MasterSlaveRouter fronts
                # the endpoints, every (re)dial asks it for the current
                # master so lock wake-ups survive failover.
                addr_provider = None
                if getattr(self._resp, "master_address", None) is not None:
                    def addr_provider():
                        host, _, port = self._resp.master_address.rpartition(":")
                        return host, int(port)
                pubsub = SyncPubSubClient(
                    host=u.hostname or "127.0.0.1",
                    port=u.port or 6379,
                    password=rcfg.password,
                    timeout=rcfg.timeout_ms / 1000.0,
                    addr_provider=addr_provider,
                )
                try:
                    pubsub.connect()
                except Exception:
                    pubsub.close()  # reclaim the IO thread on a failed dial
                    raise
                self._redis_pubsub = pubsub
            if self._redis_watchdog is None:
                self._redis_watchdog = RedisLockWatchdog(self._redis_scripts)
            return self._redis_scripts, self._redis_pubsub, self._redis_watchdog

    def _connect_durability(self):
        from redisson_tpu.interop.durability import DurabilityManager

        self._resp = self._make_resp_pool()
        self._resp.connect()
        self._durability = DurabilityManager(
            self._store, self._resp,
            executor=self._executor, pod_backend=self._pod_backend(),
            hll_family=getattr(self._pod_backend(), "family", "m3"))
        if self.config.flush_interval_s > 0:
            self._durability.start_periodic(self.config.flush_interval_s)

    # -- durability / checkpoint --------------------------------------------

    @property
    def durability(self):
        """The DurabilityManager when a redis tier is configured, else None."""
        return self._durability

    @property
    def persist(self):
        """The PersistenceManager when Config.persist is set, else None."""
        return getattr(self, "_persist", None)

    @property
    def fault(self):
        """The FaultManager when Config.faults is set, else None."""
        return getattr(self, "_fault", None)

    @property
    def replicas(self):
        """The ReplicaManager when Config.replicas is set, else None."""
        return getattr(self, "_replicas", None)

    @property
    def geo(self):
        """The GeoManager when Config.geo is set, else None."""
        return getattr(self, "_geo", None)

    def wait_for_replicas(self, n: int, timeout_s: float = 5.0) -> int:
        """Redis WAIT analogue: block until n replicas have applied at
        least the primary's current committed journal seq; returns how
        many have (possibly < n on timeout)."""
        if self._replicas is None:
            raise RuntimeError("no replica fleet configured (Config.replicas)")
        return self._replicas.wait_for_replicas(n, timeout_s=timeout_s)

    def snapshot_now(self) -> str:
        """On-demand persistent snapshot (BGSAVE analogue): cuts through
        the dispatcher barrier, writes via checkpoint.py, truncates covered
        journal segments. Returns the snapshot directory."""
        if self._persist is None:
            raise RuntimeError("no persistence configured (Config.persist)")
        return self._persist.snapshot()

    def flush_to_redis(self, names=None) -> int:
        if self._durability is None:
            raise RuntimeError("no redis durability tier configured")
        return self._durability.flush(names)

    def _pod_backend(self):
        """The PodBackend when mode='pod' (it exposes bank_names), else None."""
        sketch = getattr(self._routing, "sketch", None) if self._routing else None
        return sketch if sketch is not None and hasattr(sketch, "bank_names") else None

    def save_checkpoint(self, path: str, names=None) -> int:
        """Snapshot sketch state to a local checkpoint directory. In pod
        mode, bank-resident HLL rows are exported (dispatcher-serialized)
        and saved alongside the store objects, so the flagship multi-chip
        state survives (VERDICT r1 item #5)."""
        from redisson_tpu import checkpoint

        self._require_store("checkpointing")
        extra = {}
        pod = self._pod_backend()
        if pod is not None:
            for n in pod.bank_names():
                if names is not None and n not in names:
                    continue
                exported = self._executor.execute_sync(n, "hll_export", None)
                if exported is not None:
                    regs, version = exported
                    extra[n] = ("hll", regs, {}, version)
            # Mesh-sharded bitsets/blooms live outside the store too (only
            # the pod backend has them; the single-chip TpuBackend also
            # passes the bank_names probe above but keeps bits in the store).
            for n in (pod.sharded_bits_names()
                      if hasattr(pod, "sharded_bits_names") else []):
                if names is not None and n not in names:
                    continue
                exported = self._executor.execute_sync(n, "bits_export", None)
                if exported is not None:
                    otype, host, meta, version = exported
                    extra[n] = (otype, host, meta, version)
        # Bloom barrier: host-mirror bits must reach device state before the
        # store snapshot reads it (same reason as the durability flush).
        from redisson_tpu.store import ObjectType

        for n in (names if names is not None else self._store.keys()):
            obj = self._store.get(n)
            if obj is not None and obj.otype == ObjectType.BLOOM:
                self._executor.execute_sync(n, "bloom_sync", None)
        return checkpoint.save(self._store, path, names, extra_objects=extra)

    def load_checkpoint(self, path: str, names=None) -> int:
        """Restore sketch state from a local checkpoint directory. HLLs are
        imported through the executor (pod mode: into bank rows; local/tpu:
        into the store) — checkpoints are portable across modes."""
        from redisson_tpu import checkpoint

        self._require_store("checkpointing")

        pod = self._pod_backend()

        def put(name, otype, host, meta) -> bool:
            if otype == "hll":
                self._executor.execute_sync(name, "hll_import", {"regs": host})
                if meta:
                    obj = self._store.get(name)
                    if obj is not None:
                        obj.meta.update(meta)
                return True
            if pod is not None and otype in ("bitset", "bloom"):
                # Pod mode: restore into a mesh-sharded array, not the
                # single-chip delegate store.
                self._executor.execute_sync(
                    name, "bits_import",
                    {"otype": otype, "array": host, "meta": meta})
                return True
            return False  # default store path

        restored = checkpoint.load(self._store, path, names, put=put)
        # Restore swaps state in UNDER the op path (store.swap), which the
        # epoch-stamped read cache and bloom host mirrors can't see — tell
        # the backend so stale cached reads/mirrors die with the old state.
        sketch = getattr(self._routing, "sketch", None)
        if sketch is not None and hasattr(sketch, "notify_restored"):
            for n in checkpoint.info(path).get("objects", {}):
                if names is not None and n not in names:
                    continue
                sketch.notify_restored(n)
        return restored

    def _require_store(self, feature: str) -> None:
        if self._store is None:
            raise NotImplementedError(
                f"{feature} needs a device-resident store; not available in "
                "redis passthrough mode")

    @classmethod
    def create(cls, config: Optional[Config] = None) -> "RedissonTPU":
        return cls(config)

    def _resolve_codec(self, codec):
        """Per-object codec: accepts a Codec instance or a registry name;
        falls back to the client default (Config.codec)."""
        return get_codec(codec) if codec is not None else self._codec

    # -- sketch objects (the TPU tier) --------------------------------------

    def get_hyper_log_log(self, name: str, codec=None) -> RHyperLogLog:
        return RHyperLogLog(name, self._dispatch, self._resolve_codec(codec), self._widths)

    def get_bit_set(self, name: str) -> RBitSet:
        return RBitSet(name, self._dispatch, self._codec, self._widths)

    def get_bloom_filter(self, name: str, codec=None) -> RBloomFilter:
        return RBloomFilter(name, self._dispatch, self._resolve_codec(codec), self._widths)

    def create_batch(self, **submit_kwargs) -> RBatch:
        """submit_kwargs (serving-layer mode: tenant= / timeout_s= /
        deadline=) budget the whole pipeline as one admission unit."""
        return RBatch(self._dispatch, self._codec, self._widths,
                      **submit_kwargs)

    # -- structure objects (the long-tail tier) -----------------------------

    def get_bucket(self, name: str, codec=None) -> RBucket:
        return RBucket(name, self._dispatch, self._resolve_codec(codec), self._widths)

    def get_buckets(self, codec=None) -> RBuckets:
        return RBuckets(self._dispatch, self._resolve_codec(codec))

    def get_atomic_long(self, name: str) -> RAtomicLong:
        return RAtomicLong(name, self._dispatch, self._codec, self._widths)

    def get_atomic_double(self, name: str) -> RAtomicDouble:
        return RAtomicDouble(name, self._dispatch, self._codec, self._widths)

    def get_map(self, name: str, codec=None) -> RMap:
        return RMap(name, self._dispatch, self._resolve_codec(codec), self._widths)

    def get_map_cache(self, name: str, codec=None) -> RMapCache:
        if self._mode == "redis":
            from redisson_tpu.interop.coordination_redis import RedisMapCache

            cache = RedisMapCache(name, self._redis_scripts, self._resolve_codec(codec))
            self._eviction.schedule(name, cache.evict_expired)
            return cache
        return RMapCache(
            name, self._dispatch, self._resolve_codec(codec), self._widths,
            eviction_scheduler=self._eviction,
        )

    def get_set(self, name: str, codec=None) -> RSet:
        return RSet(name, self._dispatch, self._resolve_codec(codec), self._widths)

    def get_set_cache(self, name: str, codec=None) -> RSetCache:
        return RSetCache(
            name, self._dispatch, self._resolve_codec(codec), self._widths,
            eviction_scheduler=self._eviction,
        )

    def get_list(self, name: str, codec=None) -> RList:
        return RList(name, self._dispatch, self._resolve_codec(codec), self._widths)

    def get_queue(self, name: str, codec=None) -> RQueue:
        return RQueue(name, self._dispatch, self._resolve_codec(codec), self._widths)

    def get_deque(self, name: str, codec=None) -> RDeque:
        return RDeque(name, self._dispatch, self._resolve_codec(codec), self._widths)

    def get_blocking_queue(self, name: str, codec=None) -> RBlockingQueue:
        return RBlockingQueue(name, self._dispatch, self._resolve_codec(codec), self._widths)

    def get_blocking_deque(self, name: str, codec=None) -> RBlockingDeque:
        return RBlockingDeque(name, self._dispatch, self._resolve_codec(codec), self._widths)

    def get_sorted_set(self, name: str, codec=None, key: Optional[Callable] = None) -> RSortedSet:
        return RSortedSet(
            name, self._dispatch, self._resolve_codec(codec), self._widths, key=key,
            guard_lock=self.get_lock(name + "__sortedset_guard"),
        )

    def get_scored_sorted_set(self, name: str, codec=None) -> RScoredSortedSet:
        return RScoredSortedSet(name, self._dispatch, self._resolve_codec(codec), self._widths)

    def get_lex_sorted_set(self, name: str) -> RLexSortedSet:
        return RLexSortedSet(name, self._dispatch, self._codec, self._widths)

    def get_set_multimap(self, name: str, codec=None) -> RSetMultimap:
        return RSetMultimap(name, self._dispatch, self._resolve_codec(codec), self._widths)

    def get_list_multimap(self, name: str, codec=None) -> RListMultimap:
        return RListMultimap(name, self._dispatch, self._resolve_codec(codec), self._widths)

    def get_set_multimap_cache(self, name: str, codec=None):
        from redisson_tpu.models.multimap import RSetMultimapCache

        return RSetMultimapCache(name, self._dispatch, self._resolve_codec(codec), self._widths)

    def get_list_multimap_cache(self, name: str, codec=None):
        from redisson_tpu.models.multimap import RListMultimapCache

        return RListMultimapCache(name, self._dispatch, self._resolve_codec(codec), self._widths)

    def get_geo(self, name: str, codec=None) -> RGeo:
        return RGeo(name, self._dispatch, self._resolve_codec(codec), self._widths)

    def get_topic(self, name: str, codec=None) -> RTopic:
        if self._mode == "redis":
            from redisson_tpu.interop.coordination_redis import RedisTopic

            _, pubsub, _ = self._redis_coordination()
            return RedisTopic(name, self._resp, pubsub, self._resolve_codec(codec))
        return RTopic(name, self._dispatch, self._resolve_codec(codec), self._require_pubsub("topics"))

    def get_pattern_topic(self, pattern: str, codec=None) -> RPatternTopic:
        if self._mode == "redis":
            from redisson_tpu.interop.coordination_redis import RedisPatternTopic

            _, pubsub, _ = self._redis_coordination()
            return RedisPatternTopic(pattern, self._resp, pubsub, self._resolve_codec(codec))
        return RPatternTopic(pattern, self._dispatch, self._resolve_codec(codec), self._require_pubsub("topics"))

    # -- coordination -------------------------------------------------------

    def _require_pubsub(self, feature: str):
        if self._pubsub is None:
            raise NotImplementedError(
                f"{feature} needs the in-process engine's pub/sub hub, which "
                "this mode does not run")
        return self._pubsub

    def get_lock(self, name: str) -> RLock:
        if self._mode == "redis":
            from redisson_tpu.interop.coordination_redis import RedisLock

            scripts, pubsub, watchdog = self._redis_coordination()
            return RedisLock(name, scripts, pubsub, self.id, watchdog)
        return RLock(name, self._dispatch, self._require_pubsub("locks"), self.id, self._watchdog)

    def get_fair_lock(self, name: str) -> RFairLock:
        if self._mode == "redis":
            from redisson_tpu.interop.coordination_redis import RedisFairLock

            scripts, pubsub, watchdog = self._redis_coordination()
            return RedisFairLock(name, scripts, pubsub, self.id, watchdog)
        return RFairLock(name, self._dispatch, self._require_pubsub("locks"), self.id, self._watchdog)

    def get_read_write_lock(self, name: str) -> RReadWriteLock:
        if self._mode == "redis":
            from redisson_tpu.interop.coordination_redis import RedisReadWriteLock

            scripts, pubsub, watchdog = self._redis_coordination()
            return RedisReadWriteLock(name, scripts, pubsub, self.id, watchdog)
        return RReadWriteLock(name, self._dispatch, self._require_pubsub("locks"), self.id, self._watchdog)

    def get_multi_lock(self, *locks: RLock) -> RMultiLock:
        return RMultiLock(*locks)

    def get_semaphore(self, name: str) -> RSemaphore:
        if self._mode == "redis":
            from redisson_tpu.interop.coordination_redis import RedisSemaphore

            scripts, pubsub, _ = self._redis_coordination()
            return RedisSemaphore(name, scripts, pubsub)
        return RSemaphore(name, self._dispatch, self._require_pubsub("semaphores"))

    def get_count_down_latch(self, name: str) -> RCountDownLatch:
        if self._mode == "redis":
            from redisson_tpu.interop.coordination_redis import RedisCountDownLatch

            scripts, pubsub, _ = self._redis_coordination()
            return RedisCountDownLatch(name, scripts, pubsub)
        return RCountDownLatch(name, self._dispatch, self._require_pubsub("latches"))

    def get_script(self):
        """Atomic scripting: python functions over the structure engine in
        local/tpu/pod mode (models/script.py), real server-side Lua
        (EVAL/EVALSHA) in redis mode (RedissonScript.java surface)."""
        if self._mode == "redis":
            from redisson_tpu.interop.coordination_redis import RedisScript

            return RedisScript(self._resp, self._codec)
        from redisson_tpu.models.script import RScript

        return RScript(self._dispatch)

    # -- bucket batch helpers (RedissonClient.java:174-192) -----------------

    def find_buckets(self, pattern: str):
        """Buckets whose names match the glob (reference findBuckets)."""
        return [self.get_bucket(n)
                for n in self.get_keys().get_keys_by_pattern(pattern)]

    def load_bucket_values(self, *keys):
        """name -> decoded value for existing keys (loadBucketValues);
        accepts names varargs or one iterable, like the reference's two
        overloads."""
        if len(keys) == 1 and not isinstance(keys[0], str):
            keys = tuple(keys[0])
        return self.get_buckets().get(*keys)

    def save_buckets(self, values) -> None:
        """Atomic multi-bucket MSET (saveBuckets)."""
        self.get_buckets().set(dict(values))

    # -- lifecycle / config introspection -----------------------------------

    def get_config(self) -> Config:
        """The live Config (reference getConfig)."""
        return self.config

    def is_shutdown(self) -> bool:
        return bool(getattr(self, "_is_shutdown", False))

    def is_shutting_down(self) -> bool:
        return bool(getattr(self, "_is_shutting_down", False))

    # -- observability ------------------------------------------------------

    def get_cluster_nodes_group(self):
        """Cluster-scoped health surface (reference getClusterNodesGroup);
        same node set — topology-specific nodes carry their role."""
        return self.get_nodes_group()

    def get_nodes_group(self):
        """Health/ping surface over compute devices + the redis tier
        (reference NodesGroup.pingAll, RedisNodes.java)."""
        from redisson_tpu.observability import NodesGroup

        return NodesGroup(self)

    def get_topology_manager(self, scan_interval_s: float = 1.0,
                             failed_attempts: int = 3):
        """Failure-detection poller pre-registered with this client's nodes
        (sentinel/cluster monitor analogue). Caller starts/stops it."""
        from redisson_tpu.parallel.topology import TopologyManager

        tm = TopologyManager(scan_interval_s, failed_attempts)
        for node in self.get_nodes_group().nodes():
            tm.add_node(node.ident, node.ping)
        return tm

    # -- services (L5b) -----------------------------------------------------

    def get_remote_service(self, name: str = "remote_service"):
        """RPC service registry/proxy factory (RRemoteService analogue).
        One cached instance per name; shut down with the client."""
        from redisson_tpu.services.remote import RRemoteService

        rs = self._remote_services.get(name)
        if rs is None:
            rs = self._remote_services[name] = RRemoteService(self, name)
        return rs

    def get_cache_manager(self, configs=None):
        """Spring-cache-manager analogue over RMap/RMapCache."""
        from redisson_tpu.services.cache_manager import CacheManager

        return CacheManager(self, configs)

    # -- keys facade (RKeys analogue) ---------------------------------------

    def get_keys(self) -> RKeys:
        return RKeys(self._dispatch, self._routing)

    def keys(self, pattern: str = "*"):
        return self._dispatch.execute_sync("", "keys", {"pattern": pattern})

    def flushall(self):
        # Routed through the executor so it serializes with in-flight ops on
        # the dispatcher thread (no mid-kernel store mutation).
        self._dispatch.execute_sync("", "flushall", None)

    def delete(self, name: str) -> bool:
        return self._dispatch.execute_sync(name, "delete", None)

    # -- memory facade (MEMORY command family; memstat/) ---------------------

    def _require_memreport(self, command: str):
        if self._memreport is None:
            raise RuntimeError(
                f"{command} requires a device-backed mode; in redis "
                "passthrough the server owns memory introspection")
        return self._memreport

    def memory_usage(self, name: str) -> Optional[int]:
        """MEMORY USAGE analogue: exact device bytes + metadata overhead
        for one key, or None when the key doesn't exist."""
        return self._require_memreport("MEMORY USAGE").memory_usage(name)

    def memory_stats(self):
        """MEMORY STATS analogue over the byte ledger."""
        return self._require_memreport("MEMORY STATS").memory_stats()

    def memory_doctor(self):
        """MEMORY DOCTOR analogue: rule-based findings dict."""
        return self._require_memreport("MEMORY DOCTOR").memory_doctor()

    def memory_verify(self):
        """Ledger invariant check: ledger totals vs. the sum of live
        Array.nbytes (zero drift when healthy)."""
        if self.memstat is None or self._store is None:
            raise RuntimeError("memory_verify requires a device-backed mode")
        sketch = getattr(self._routing, "sketch", self._routing)
        return self.memstat.verify(self._store, sketch)

    def info(self, section: Optional[str] = None):
        """INFO analogue: dict of section dicts (server, memory,
        persistence). `section` filters to one block, like INFO MEMORY."""
        sections = {
            "server": {"mode": self._mode, "client_id": str(self.id)},
        }
        if self._memreport is not None:
            sections["memory"] = self._memreport.info_memory()
        if getattr(self, "_persist", None) is not None:
            sections["persistence"] = self._persist.stats()
        replication = None
        if getattr(self, "_geo", None) is not None:
            replication = self._geo.info()
        elif getattr(self, "_replicas", None) is not None:
            replication = {"role": "primary"}
        if replication is not None:
            if getattr(self, "_replicas", None) is not None:
                replication["connected_replicas"] = len(
                    self._replicas.replicas)
            sections["replication"] = replication
        if self.cluster is not None:
            sections["cluster"] = self.cluster.cluster_info()
        if section is not None:
            key = section.lower()
            if key not in sections:
                raise ValueError(f"unknown INFO section '{section}'")
            return {key: sections[key]}
        return sections

    # -- lifecycle ----------------------------------------------------------

    def shutdown(self):
        self._is_shutting_down = True
        try:
            self._shutdown_inner()
        finally:
            # Flags must flip even when a teardown step raises — a client
            # permanently reporting "shutting down" would wedge callers.
            self._is_shutting_down = False
            self._is_shutdown = True

    def _shutdown_inner(self):
        if getattr(self, "wire", None) is not None:
            # Wire first, in every mode: stop accepting sockets and drain
            # the event loop before the dispatch stack underneath (serve /
            # executor / shard clients) starts rejecting its submissions.
            try:
                self.wire.stop()
            except Exception:
                pass
            self.wire = None
        if getattr(self, "cluster", None) is not None:
            # Cluster facade: the shard clients own every background
            # resource; the manager closes the router (its redirect worker)
            # then shuts each shard down through this same path.
            if self._eviction is not None:
                self._eviction.shutdown()
            self.cluster.shutdown()
            self.cluster = None
            return
        if getattr(self, "_fault", None) is not None:
            # First: stop the watchdog (it reads executor internals) and
            # wait out in-flight rebuilds while the executor still accepts
            # the replay traffic they submit.
            try:
                self._fault.stop()
            except Exception:
                pass
            self._fault = None
        if getattr(self, "_geo", None) is not None:
            # Geo site before replicas/persist: link threads read this
            # journal and peer appliers dispatch into this executor; both
            # must quiesce (and the LWW sidecar flush) while the stack
            # under them still accepts work.
            try:
                self._geo.close()
            except Exception:
                pass
            self._geo = None
        if getattr(self, "_replicas", None) is not None:
            # Replica fleet next: the prober must stop before the executor
            # it polls drains, and each replica shuts its own client down
            # (the promoted one tears its attached persistence down too).
            try:
                self._replicas.close()
            except Exception:
                pass
            self._replicas = None
        if getattr(self, "_persist", None) is not None:
            # Phase 1: stop the snapshotter before the executor drains (a
            # barrier cut submitted after shutdown would never dispatch);
            # the journal stays attached so drained ops still journal.
            self._persist.stop_background()
        for rs in self._remote_services.values():
            try:
                rs.shutdown(wait=False)
            except Exception:
                pass
        self._remote_services.clear()
        if self._durability is not None:
            self._durability.stop_periodic()
            try:
                self._durability.flush()  # final flush on clean shutdown
            except Exception:
                pass
            self._durability = None
        if getattr(self, "_role_monitor", None) is not None:
            self._role_monitor.close()
            self._role_monitor = None
        if getattr(self, "_cluster_manager", None) is not None:
            self._cluster_manager.close()
            self._cluster_manager = None
        if getattr(self, "_redis_watchdog", None) is not None:
            self._redis_watchdog.shutdown()
            self._redis_watchdog = None
        if getattr(self, "_redis_pubsub", None) is not None:
            try:
                self._redis_pubsub.close()
            except Exception:
                pass
            self._redis_pubsub = None
        if self._resp is not None:
            try:
                self._resp.close()
            except Exception:
                # A wedged IO loop must not abort the rest of shutdown.
                pass
            self._resp = None
        if self._eviction is not None:
            self._eviction.shutdown()
        if self._watchdog is not None:
            self._watchdog.shutdown()
        if getattr(self, "serve", None) is not None:
            # Closes the retry timer first (pending retries resolve their
            # outer futures with CancelledError), then the executor itself.
            self.serve.shutdown()
        else:
            self._executor.shutdown()
        if getattr(self, "_persist", None) is not None:
            # Phase 2: executor drained — every dispatched op has journaled;
            # final flush + fsync, then release the segment files.
            try:
                self._persist.close()
            except Exception:
                pass
            self._persist = None
        sketch = getattr(getattr(self, "_routing", None), "sketch", None)
        completer = getattr(sketch, "completer", None)
        if completer is not None:
            # Resolve every future whose device result is still in flight
            # before tearing the rest down (the dispatcher only dispatches;
            # materialization happens on the completer thread).
            completer.shutdown()
        if getattr(self._routing, "structures", None) is not None:
            # Dispatcher has exited: release threads parked in blocking pops.
            self._routing.structures.fail_waiters()
        if self._pubsub is not None:
            self._pubsub.shutdown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
