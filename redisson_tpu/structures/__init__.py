"""The structures tier: host-side data-structure engine for the long tail of
L3 objects (maps, sets, lists, queues, zsets, caches, locks, topics).

The reference executes *every* object op remotely on Redis' C data-structure
engine; the TPU framework keeps sketch ops (HLL/BitSet/Bloom) on-device and
runs the rest on this in-process engine behind the same CommandExecutor
waist (SURVEY.md §7 "the long tail of L3 objects"). Atomicity falls out of
the single dispatcher thread exactly as the reference's falls out of Redis'
single-threaded command loop — compound ops that the reference expresses as
Lua scripts are single engine ops here.
"""

from redisson_tpu.structures.engine import PubSubHub, StructureBackend  # noqa: F401
