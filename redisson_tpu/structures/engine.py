"""The in-memory data-structure engine (the "embedded server").

Op interpreter over a typed keyspace, executed entirely on the executor's
dispatcher thread: every op is atomic with respect to every other, the same
guarantee the reference gets from Redis' single-threaded command loop. Ops
that the reference implements as Lua scripts (lock CAS `RedissonLock.java:
236-252`, map-cache TTL puts `RedissonMapCache.java:75-87`, semaphore
counters `RedissonSemaphore.java`) are single handler calls here.

Values are opaque bytes (the model layer applies codecs); equality is
byte-equality exactly as Redis compares serialized values. Scores are
floats. Expiry is lazy on access plus an EvictionScheduler sweep (see
redisson_tpu.eviction).

Blocking ops (BLPOP-family, `RedisCommands` blocking pops routed through the
reference's no-timeout L2 path `CommandAsyncService.java:491-497`) never
block the dispatcher: the handler either completes immediately or parks the
op's future in a per-key waiter queue; a later push fulfills the earliest
waiter in the same dispatch that performed the push. Client-side timeout
cancellation is itself an op, so the cancel/fulfill race is serialized away.
"""

from __future__ import annotations

import fnmatch
import itertools
import random
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from redisson_tpu.executor import Op
from redisson_tpu.store import WrongTypeError
from redisson_tpu.structures.extended import ExtendedOps


def now_ms() -> int:
    return int(time.time() * 1000)


# SRANDMEMBER randomness (os-entropy seeded; r2 used a now_ms()-derived
# window, so two calls in the same millisecond returned identical members).
_rand = random.Random()


class T:
    """Value types of the keyspace."""

    STRING = "string"
    HASH = "hash"
    SET = "set"
    ZSET = "zset"
    LIST = "list"
    MAPCACHE = "mapcache"
    SETCACHE = "setcache"
    MULTIMAP_SET = "multimap_set"
    MULTIMAP_LIST = "multimap_list"
    GEO = "geo"
    LOCK = "lock"
    RWLOCK = "rwlock"
    SEMAPHORE = "semaphore"
    LATCH = "latch"


@dataclass
class KV:
    otype: str
    value: Any
    expire_at: Optional[int] = None  # epoch ms
    # SCAN support: member -> monotonic stamp, assigned on first sight by a
    # scan, dropped on deletion, never renumbered (see Engine._scan_page).
    scan_seq: Optional[Dict[Any, int]] = None
    scan_next: int = 1
    # Multimap-cache per-key expiry (key -> deadline ms): the engine-side
    # analogue of the reference's timeout zset (RedissonMultimapCache.java).
    mm_expiry: Optional[Dict[Any, int]] = None


@dataclass
class Waiter:
    """A parked blocking pop (id, future, and how to fulfill it)."""

    wid: int
    op: Op
    side: str  # 'left' | 'right'
    dest: Optional[str] = None  # pollLastAndOfferFirstTo target


class PubSubHub:
    """In-process pub/sub: channel + pattern listeners, async delivery.

    Reference: the L0/L1 pub/sub registry (`RedisPubSubConnection`,
    `MasterSlaveConnectionManager.java:306-479`). Listener callbacks run on a
    dedicated delivery thread, never on the dispatcher (the reference
    likewise dispatches on netty event-loop threads, not the caller's).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._channels: Dict[str, Dict[int, Callable]] = {}
        self._patterns: Dict[str, Dict[int, Callable]] = {}
        self._queue: deque = deque()
        self._cv = threading.Condition()
        self._shutdown = False
        self._thread = threading.Thread(
            target=self._deliver_loop, name="redisson-tpu-pubsub", daemon=True
        )
        self._thread.start()

    def subscribe(self, channel: str, listener: Callable[[str, Any], None]) -> int:
        with self._lock:
            lid = next(self._ids)
            self._channels.setdefault(channel, {})[lid] = listener
            return lid

    def psubscribe(self, pattern: str, listener: Callable[[str, str, Any], None]) -> int:
        with self._lock:
            lid = next(self._ids)
            self._patterns.setdefault(pattern, {})[lid] = listener
            return lid

    def unsubscribe(self, channel: str, lid: Optional[int] = None) -> None:
        with self._lock:
            subs = self._channels.get(channel)
            if subs is None:
                return
            if lid is None:
                subs.clear()
            else:
                subs.pop(lid, None)
            if not subs:
                del self._channels[channel]

    def punsubscribe(self, pattern: str, lid: Optional[int] = None) -> None:
        with self._lock:
            subs = self._patterns.get(pattern)
            if subs is None:
                return
            if lid is None:
                subs.clear()
            else:
                subs.pop(lid, None)
            if not subs:
                del self._patterns[pattern]

    def publish(self, channel: str, message: Any) -> int:
        """Queue delivery; returns receiver count (PUBLISH reply)."""
        targets: List[Tuple[Callable, tuple]] = []
        with self._lock:
            for fn in list(self._channels.get(channel, {}).values()):
                targets.append((fn, (channel, message)))
            for pattern, subs in self._patterns.items():
                if fnmatch.fnmatchcase(channel, pattern):
                    for fn in list(subs.values()):
                        targets.append((fn, (pattern, channel, message)))
        if targets:
            with self._cv:
                self._queue.extend(targets)
                self._cv.notify()
        return len(targets)

    def _deliver_loop(self):
        while True:
            with self._cv:
                while not self._queue and not self._shutdown:
                    self._cv.wait()
                if self._shutdown and not self._queue:
                    return
                fn, args = self._queue.popleft()
            try:
                fn(*args)
            except Exception:
                pass  # listener errors never poison delivery (netty parity)

    def shutdown(self):
        with self._cv:
            self._shutdown = True
            self._cv.notify_all()
        self._thread.join(timeout=5)


class StructureBackend(ExtendedOps):
    """Op interpreter over the typed keyspace. Runs on the dispatcher thread."""

    def __init__(self, pubsub: Optional[PubSubHub] = None):
        self._data: Dict[str, KV] = {}
        self.pubsub = pubsub or PubSubHub()
        self._waiters: Dict[str, deque] = {}  # key -> Waiter FIFO
        self._waiter_ids = itertools.count(1)
        self._lock = threading.Lock()  # guards reads from non-dispatcher threads
        self._scripts: Dict[str, Callable] = {}  # sha -> fn (SCRIPT cache)

    # -- dispatch (same contract as TpuBackend.run) --------------------------

    def run(self, kind: str, target: str, ops: List[Op]) -> None:
        handler = getattr(self, "_op_" + kind, None)
        if handler is None:
            raise ValueError(f"unknown op kind: {kind}")
        for op in ops:
            try:
                handler(target, op)
            except Exception as exc:
                if not op.future.done():
                    op.future.set_exception(exc)

    def handles(self, kind: str) -> bool:
        return hasattr(self, "_op_" + kind)

    # -- keyspace helpers ----------------------------------------------------

    def _entry(self, key: str, otype: Optional[str] = None) -> Optional[KV]:
        kv = self._data.get(key)
        if kv is None:
            return None
        if kv.expire_at is not None and kv.expire_at <= now_ms():
            with self._lock:
                del self._data[key]
            return None
        if otype is not None and kv.otype != otype:
            raise WrongTypeError(f"key '{key}' holds {kv.otype}, operation needs {otype}")
        return kv

    def _create(self, key: str, otype: str, factory: Callable[[], Any]) -> KV:
        kv = self._entry(key, otype)
        if kv is None:
            kv = KV(otype, factory())
            with self._lock:
                self._data[key] = kv
        return kv

    def _drop(self, key: str) -> bool:
        with self._lock:
            return self._data.pop(key, None) is not None

    def _drop_if_empty(self, key: str, kv: KV) -> None:
        if not kv.value:
            self._drop(key)

    # generic store surface (mirrors SketchStore for the RoutingBackend)

    def exists(self, name: str) -> bool:
        return self._entry(name) is not None

    def delete(self, name: str) -> bool:
        return self._drop(name)

    def keys(self, pattern: Optional[str] = None) -> List[str]:
        with self._lock:
            items = list(self._data.items())
        t = now_ms()
        live = [k for k, kv in items if kv.expire_at is None or kv.expire_at > t]
        if pattern is None or pattern == "*":
            return live
        return [k for k in live if fnmatch.fnmatchcase(k, pattern)]

    def flushall(self) -> None:
        with self._lock:
            self._data.clear()

    # -- persistence (persist/snapshotter.py) --------------------------------

    def dump_state(self) -> bytes:
        """Serialize the whole keyspace (the structure-tier half of a
        snapshot cut). Must run on the dispatcher thread — the single
        mutator — so the pickle is a consistent point-in-time copy.
        Excluded on purpose: parked blocking-pop waiters (transient; their
        futures belong to the crashed process) and the SCRIPT cache
        (callables don't pickle; re-register via script_load after
        recovery, same as a restarted Redis loses its script cache)."""
        import pickle

        with self._lock:
            return pickle.dumps({"format": 1, "data": self._data},
                                protocol=pickle.HIGHEST_PROTOCOL)

    def load_state(self, blob: bytes) -> int:
        """Replace the keyspace with a dump_state() capture. Returns the
        number of keys restored. Dispatcher-thread (or pre-traffic) only."""
        import pickle

        payload = pickle.loads(blob)
        if payload.get("format") != 1:
            raise ValueError(f"unsupported structure dump format "
                             f"{payload.get('format')!r}")
        data = payload["data"]
        with self._lock:
            self._data = data
        return len(data)

    def load_keys(self, blob: bytes) -> int:
        """Merge a dump_state() capture into the live keyspace (same-named
        keys are overwritten; everything else is untouched). The slot
        migration bootstrap path: a target shard installs the migrating
        slots' keys without disturbing the keys it already owns. Runs as
        the journaled `migrate_install` op on the dispatcher thread."""
        import pickle

        payload = pickle.loads(blob)
        if payload.get("format") != 1:
            raise ValueError(f"unsupported structure dump format "
                             f"{payload.get('format')!r}")
        data = payload["data"]
        with self._lock:
            self._data.update(data)
        return len(data)

    # -- generic / expiry (RedissonExpirable surface) ------------------------

    def _op_delete(self, key: str, op: Op) -> None:
        op.future.set_result(self._drop(key))

    def _op_exists(self, key: str, op: Op) -> None:
        op.future.set_result(self._entry(key) is not None)

    def _op_flushall(self, key: str, op: Op) -> None:
        self.flushall()
        op.future.set_result(None)

    def _op_pexpire(self, key: str, op: Op) -> None:
        kv = self._entry(key)
        if kv is None:
            op.future.set_result(False)
            return
        kv.expire_at = now_ms() + int(op.payload["ms"])
        op.future.set_result(True)

    def _op_pexpireat(self, key: str, op: Op) -> None:
        kv = self._entry(key)
        if kv is None:
            op.future.set_result(False)
            return
        kv.expire_at = int(op.payload["ts_ms"])
        op.future.set_result(True)

    def _op_persist(self, key: str, op: Op) -> None:
        kv = self._entry(key)
        if kv is None or kv.expire_at is None:
            op.future.set_result(False)
            return
        kv.expire_at = None
        op.future.set_result(True)

    def _op_pttl(self, key: str, op: Op) -> None:
        """-2 = no key, -1 = no expiry (PTTL reply contract)."""
        kv = self._entry(key)
        if kv is None:
            op.future.set_result(-2)
        elif kv.expire_at is None:
            op.future.set_result(-1)
        else:
            op.future.set_result(max(0, kv.expire_at - now_ms()))

    # -- scripting (RScript / Lua-EVAL analogue) ------------------------------

    def _op_script_load(self, key: str, op: Op) -> None:
        from redisson_tpu.models.script import script_sha

        fn = op.payload["fn"]
        sha = script_sha(fn)
        self._scripts[sha] = fn
        op.future.set_result(sha)

    def _op_script_exists(self, key: str, op: Op) -> None:
        op.future.set_result([s in self._scripts for s in op.payload["shas"]])

    def _op_script_flush(self, key: str, op: Op) -> None:
        self._scripts.clear()
        op.future.set_result(None)

    def _op_script_eval(self, key: str, op: Op) -> None:
        """Runs the function on the dispatcher thread — atomic against every
        other op, the Lua-inside-Redis guarantee."""
        from redisson_tpu.models.script import ScriptContext, script_sha

        p = op.payload
        fn = p.get("fn")
        if fn is None:
            fn = self._scripts.get(p["sha"])
            if fn is None:
                raise ValueError(f"NOSCRIPT no script with sha {p['sha']}")
        else:
            self._scripts.setdefault(script_sha(fn), fn)
        op.future.set_result(fn(ScriptContext(self), p["keys"], p["args"]))

    def _op_rename(self, key: str, op: Op) -> None:
        """RENAME / RENAMENX (payload nx=True): atomic on the dispatcher."""
        kv = self._entry(key)
        if kv is None:
            raise KeyError(f"no such key '{key}'")
        with self._lock:
            if op.payload.get("nx") and op.payload["newkey"] in self._data:
                op.future.set_result(False)
                return
            del self._data[key]
            self._data[op.payload["newkey"]] = kv
        op.future.set_result(True)

    def _op_type(self, key: str, op: Op) -> None:
        kv = self._entry(key)
        op.future.set_result(None if kv is None else kv.otype)

    # -- string / bucket / atomics ------------------------------------------

    def _op_get(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.STRING)
        op.future.set_result(None if kv is None else kv.value)

    def _op_set(self, key: str, op: Op) -> None:
        kv = self._create(key, T.STRING, lambda: None)
        kv.value = op.payload["value"]
        ttl = op.payload.get("ttl_ms")
        kv.expire_at = None if not ttl else now_ms() + int(ttl)
        op.future.set_result(None)

    def _op_getset(self, key: str, op: Op) -> None:
        # A None value means ABSENT: getAndSet(null) deletes the key
        # (reference contract, RedissonBucketTest.java:33-43 — the bucket
        # must not exist afterwards).
        if op.payload["value"] is None:
            kv = self._entry(key, T.STRING)
            old = None if kv is None else kv.value
            self._drop(key)
            op.future.set_result(old)
            return
        kv = self._create(key, T.STRING, lambda: None)
        old, kv.value = kv.value, op.payload["value"]
        op.future.set_result(old)

    def _op_setnx(self, key: str, op: Op) -> None:
        """trySet (SETNX): only if absent."""
        if self._entry(key) is not None:
            op.future.set_result(False)
            return
        kv = self._create(key, T.STRING, lambda: None)
        kv.value = op.payload["value"]
        ttl = op.payload.get("ttl_ms")
        kv.expire_at = None if not ttl else now_ms() + int(ttl)
        op.future.set_result(True)

    def _op_compare_and_set(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.STRING)
        current = None if kv is None else kv.value
        if current != op.payload["expect"]:
            op.future.set_result(False)
            return
        # compareAndSet(expect, null) deletes on match (None == absent,
        # RedissonBucketTest.java:16-31).
        if op.payload["update"] is None:
            self._drop(key)
            op.future.set_result(True)
            return
        kv = self._create(key, T.STRING, lambda: None)
        kv.value = op.payload["update"]
        op.future.set_result(True)

    def _op_strlen(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.STRING)
        op.future.set_result(0 if kv is None or kv.value is None else len(kv.value))

    def _num(self, kv: Optional[KV], as_float: bool):
        if kv is None or kv.value is None:
            return 0.0 if as_float else 0
        return float(kv.value) if as_float else int(kv.value)

    def _op_incr(self, key: str, op: Op) -> None:
        """INCRBY/INCRBYFLOAT — atomics (RAtomicLong/RAtomicDouble)."""
        as_float = bool(op.payload.get("float"))
        kv = self._create(key, T.STRING, lambda: None)
        val = self._num(kv, as_float) + op.payload["by"]
        kv.value = repr(val).encode() if as_float else str(val).encode()
        op.future.set_result(val)

    def _op_num_get(self, key: str, op: Op) -> None:
        op.future.set_result(self._num(self._entry(key, T.STRING), bool(op.payload.get("float"))))

    def _op_num_cas(self, key: str, op: Op) -> None:
        as_float = bool(op.payload.get("float"))
        kv = self._entry(key, T.STRING)
        if self._num(kv, as_float) != op.payload["expect"]:
            op.future.set_result(False)
            return
        kv = self._create(key, T.STRING, lambda: None)
        v = op.payload["update"]
        kv.value = repr(v).encode() if as_float else str(v).encode()
        op.future.set_result(True)

    def _op_num_getandset(self, key: str, op: Op) -> None:
        as_float = bool(op.payload.get("float"))
        kv = self._create(key, T.STRING, lambda: None)
        old = self._num(kv, as_float)
        v = op.payload["value"]
        kv.value = repr(v).encode() if as_float else str(v).encode()
        op.future.set_result(old)

    def _op_mget(self, key: str, op: Op) -> None:
        out = {}
        for name in op.payload["names"]:
            kv = self._entry(name, T.STRING)
            if kv is not None and kv.value is not None:
                out[name] = kv.value
        op.future.set_result(out)

    def _op_mset(self, key: str, op: Op) -> None:
        for name, value in op.payload["pairs"].items():
            self._create(name, T.STRING, lambda: None).value = value
        op.future.set_result(None)

    def _op_msetnx(self, key: str, op: Op) -> None:
        pairs = op.payload["pairs"]
        if any(self._entry(n) is not None for n in pairs):
            op.future.set_result(False)
            return
        for name, value in pairs.items():
            self._create(name, T.STRING, lambda: None).value = value
        op.future.set_result(True)

    # -- hash (RMap) ---------------------------------------------------------

    def _op_hput(self, key: str, op: Op) -> None:
        kv = self._create(key, T.HASH, dict)
        old = kv.value.get(op.payload["field"])
        kv.value[op.payload["field"]] = op.payload["value"]
        op.future.set_result(old)

    def _op_hput_if_absent(self, key: str, op: Op) -> None:
        kv = self._create(key, T.HASH, dict)
        old = kv.value.get(op.payload["field"])
        if old is None:
            kv.value[op.payload["field"]] = op.payload["value"]
        op.future.set_result(old)

    def _op_hputall(self, key: str, op: Op) -> None:
        kv = self._create(key, T.HASH, dict)
        kv.value.update(op.payload["pairs"])
        op.future.set_result(None)

    def _op_hget(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.HASH)
        op.future.set_result(None if kv is None else kv.value.get(op.payload["field"]))

    def _op_hmget(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.HASH)
        fields = op.payload["fields"]
        if kv is None:
            op.future.set_result({})
            return
        op.future.set_result({f: kv.value[f] for f in fields if f in kv.value})

    def _op_hgetall(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.HASH)
        op.future.set_result({} if kv is None else dict(kv.value))

    def _op_hdel(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.HASH)
        if kv is None:
            op.future.set_result(0)
            return
        n = 0
        for f in op.payload["fields"]:
            if kv.value.pop(f, None) is not None:
                n += 1
        self._drop_if_empty(key, kv)
        op.future.set_result(n)

    def _op_hremove(self, key: str, op: Op) -> None:
        """remove(field) -> old value (reference RMap.remove)."""
        kv = self._entry(key, T.HASH)
        if kv is None:
            op.future.set_result(None)
            return
        old = kv.value.pop(op.payload["field"], None)
        self._drop_if_empty(key, kv)
        op.future.set_result(old)

    def _op_hremove_if(self, key: str, op: Op) -> None:
        """remove(field, value) -> bool (Lua in the reference)."""
        kv = self._entry(key, T.HASH)
        f = op.payload["field"]
        if kv is None or kv.value.get(f) != op.payload["value"]:
            op.future.set_result(False)
            return
        del kv.value[f]
        self._drop_if_empty(key, kv)
        op.future.set_result(True)

    def _op_hreplace(self, key: str, op: Op) -> None:
        """replace(field, value) -> old, only if present."""
        kv = self._entry(key, T.HASH)
        f = op.payload["field"]
        if kv is None or f not in kv.value:
            op.future.set_result(None)
            return
        old = kv.value[f]
        kv.value[f] = op.payload["value"]
        op.future.set_result(old)

    def _op_hreplace_if(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.HASH)
        f = op.payload["field"]
        if kv is None or kv.value.get(f) != op.payload["old"]:
            op.future.set_result(False)
            return
        kv.value[f] = op.payload["new"]
        op.future.set_result(True)

    def _op_hcontains_key(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.HASH)
        op.future.set_result(kv is not None and op.payload["field"] in kv.value)

    def _op_hcontains_value(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.HASH)
        op.future.set_result(kv is not None and op.payload["value"] in kv.value.values())

    def _op_hlen(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.HASH)
        op.future.set_result(0 if kv is None else len(kv.value))

    def _op_hkeys(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.HASH)
        op.future.set_result([] if kv is None else list(kv.value.keys()))

    def _op_hvals(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.HASH)
        op.future.set_result([] if kv is None else list(kv.value.values()))

    def _op_hincr(self, key: str, op: Op) -> None:
        """HINCRBY/HINCRBYFLOAT (RMap.addAndGet)."""
        kv = self._create(key, T.HASH, dict)
        f = op.payload["field"]
        as_float = bool(op.payload.get("float"))
        cur = kv.value.get(f)
        base = (float(cur) if as_float else int(cur)) if cur is not None else (0.0 if as_float else 0)
        val = base + op.payload["by"]
        kv.value[f] = repr(val).encode() if as_float else str(val).encode()
        op.future.set_result(val)

    def _scan_page(self, kv, cursor: int, count: int):
        """Stable-cursor SCAN page over a hash/set/zset entry.

        Members are stamped with a monotonic per-entry sequence number the
        first time a scan sees them; a page is the `count` live members with
        stamp > cursor, in stamp order. Deleting a member drops its stamp
        without renumbering the others, so an element present for the whole
        scan is returned exactly once regardless of concurrent mutation (the
        guarantee the reference's iterators rely on,
        `RedissonBaseIterator.java`); members added or re-added mid-scan
        stamp after the cursor and are seen at most once.
        """
        if kv.scan_seq is None:
            kv.scan_seq = {}
        seqs = kv.scan_seq
        members = kv.value  # dict (hash/zset field map) or set
        for m in [m for m in seqs if m not in members]:
            del seqs[m]
        for m in members:
            if m not in seqs:
                seqs[m] = kv.scan_next
                kv.scan_next += 1
        # seqs is insertion-ordered = stamp-ascending (new stamps append,
        # deletions don't reorder), so a page is one ordered walk — no sort.
        page: list = []
        more = False
        for m, s in seqs.items():
            if s <= cursor:
                continue
            if len(page) < count:
                page.append((s, m))
            else:
                more = True
                break
        if not more:
            # Scan complete: drop the stamp map so a scanned 1M-member set
            # doesn't carry a permanent member->stamp shadow. A concurrent
            # scan still in flight degrades to at-least-once (fresh stamps
            # may re-return members) — Redis SCAN's own guarantee.
            kv.scan_seq = None
            return 0, [m for _, m in page]
        return page[-1][0], [m for _, m in page]

    def _op_hscan(self, key: str, op: Op) -> None:
        """Cursor iteration (HSCAN): returns (next_cursor, [(f, v)...])."""
        kv = self._entry(key, T.HASH)
        cursor, count = op.payload["cursor"], op.payload.get("count", 10)
        if kv is None:
            op.future.set_result((0, []))
            return
        nxt, fields = self._scan_page(kv, cursor, count)
        op.future.set_result((nxt, [(f, kv.value[f]) for f in fields]))

    # -- set (RSet) ----------------------------------------------------------

    def _op_sadd(self, key: str, op: Op) -> None:
        kv = self._create(key, T.SET, set)
        before = len(kv.value)
        kv.value.update(op.payload["members"])
        op.future.set_result(len(kv.value) - before)

    def _op_srem(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.SET)
        if kv is None:
            op.future.set_result(0)
            return
        n = 0
        for m in op.payload["members"]:
            if m in kv.value:
                kv.value.discard(m)
                n += 1
        self._drop_if_empty(key, kv)
        op.future.set_result(n)

    def _op_sismember(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.SET)
        op.future.set_result(kv is not None and op.payload["member"] in kv.value)

    def _op_smembers(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.SET)
        op.future.set_result(set() if kv is None else set(kv.value))

    def _op_scard(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.SET)
        op.future.set_result(0 if kv is None else len(kv.value))

    def _op_spop(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.SET)
        if kv is None:
            op.future.set_result([])
            return
        count = op.payload.get("count", 1)
        out = []
        for _ in range(min(count, len(kv.value))):
            out.append(kv.value.pop())
        self._drop_if_empty(key, kv)
        op.future.set_result(out)

    def _op_srandmember(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.SET)
        if kv is None or not kv.value:
            op.future.set_result([])
            return
        count = op.payload.get("count", 1)
        members = list(kv.value)
        if count < 0:
            # Redis semantics: negative count samples with repetition.
            op.future.set_result(_rand.choices(members, k=-count))
            return
        op.future.set_result(_rand.sample(members, min(count, len(members))))

    def _op_smove(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.SET)
        m = op.payload["member"]
        if kv is None or m not in kv.value:
            op.future.set_result(False)
            return
        kv.value.discard(m)
        self._drop_if_empty(key, kv)
        self._create(op.payload["dst"], T.SET, set).value.add(m)
        op.future.set_result(True)

    def _sets_of(self, names) -> List[set]:
        out = []
        for n in names:
            kv = self._entry(n, T.SET)
            out.append(set() if kv is None else kv.value)
        return out

    def _op_sinter(self, key: str, op: Op) -> None:
        sets = self._sets_of([key, *op.payload["names"]])
        op.future.set_result(set.intersection(*sets) if sets else set())

    def _op_sunion(self, key: str, op: Op) -> None:
        op.future.set_result(set.union(*self._sets_of([key, *op.payload["names"]])))

    def _op_sdiff(self, key: str, op: Op) -> None:
        sets = self._sets_of([key, *op.payload["names"]])
        op.future.set_result(sets[0].difference(*sets[1:]) if sets else set())

    def _op_sstore(self, key: str, op: Op) -> None:
        """SINTERSTORE/SUNIONSTORE/SDIFFSTORE into target key."""
        which = op.payload["op"]
        sets = self._sets_of(op.payload["names"])
        if which == "inter":
            result = set.intersection(*sets) if sets else set()
        elif which == "union":
            result = set.union(*sets) if sets else set()
        else:
            result = sets[0].difference(*sets[1:]) if sets else set()
        if result:
            self._create(key, T.SET, set).value = result
        else:
            self._drop(key)
        op.future.set_result(len(result))

    def _op_sretain(self, key: str, op: Op) -> None:
        """retainAll (the reference's ×100-optimized path uses server-side
        set algebra, `CHANGELOG.md:53`); atomic single op here."""
        kv = self._entry(key, T.SET)
        if kv is None:
            op.future.set_result(False)
            return
        keep = set(op.payload["members"])
        before = len(kv.value)
        kv.value &= keep
        self._drop_if_empty(key, kv)
        op.future.set_result(len(kv.value) != before)

    def _op_sscan(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.SET)
        cursor, count = op.payload["cursor"], op.payload.get("count", 10)
        if kv is None:
            op.future.set_result((0, []))
            return
        op.future.set_result(self._scan_page(kv, cursor, count))

    # -- list (RList / RQueue / RDeque) --------------------------------------

    def _push(self, key: str, values, side: str) -> int:
        kv = self._create(key, T.LIST, deque)
        for v in values:
            if side == "left":
                kv.value.appendleft(v)
            else:
                kv.value.append(v)
        n = len(kv.value)
        self._serve_waiters(key)
        return n

    def _op_rpush(self, key: str, op: Op) -> None:
        op.future.set_result(self._push(key, op.payload["values"], "right"))

    def _op_lpush(self, key: str, op: Op) -> None:
        op.future.set_result(self._push(key, op.payload["values"], "left"))

    def _op_lrange(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.LIST)
        if kv is None:
            op.future.set_result([])
            return
        items = list(kv.value)
        start, stop = op.payload["start"], op.payload["stop"]
        n = len(items)
        if start < 0:
            start += n
        if stop < 0:
            stop += n
        op.future.set_result(items[max(0, start) : stop + 1])

    def _op_lindex(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.LIST)
        i = op.payload["index"]
        if kv is None or not -len(kv.value) <= i < len(kv.value):
            op.future.set_result(None)
            return
        op.future.set_result(kv.value[i])

    def _op_lset(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.LIST)
        i = op.payload["index"]
        if kv is None or not -len(kv.value) <= i < len(kv.value):
            raise IndexError(f"list index {i} out of range for '{key}'")
        old = kv.value[i]
        kv.value[i] = op.payload["value"]
        op.future.set_result(old)

    def _op_linsert_at(self, key: str, op: Op) -> None:
        """add(index, value) — the reference does LINSERT/Lua shuffling."""
        kv = self._create(key, T.LIST, deque)
        i = op.payload["index"]
        if i > len(kv.value):
            raise IndexError(f"insert index {i} beyond list size {len(kv.value)}")
        kv.value.insert(i, op.payload["value"])
        self._serve_waiters(key)
        op.future.set_result(True)

    def _op_lsplice(self, key: str, op: Op) -> None:
        """addAll(index, values) as ONE op, mirroring lretain: the old
        model-level loop of linsert_at let concurrent writers interleave
        between elements. Same bound rule as linsert_at (error past the
        current size, RedissonListTest.java:715-719)."""
        kv = self._create(key, T.LIST, deque)
        i = op.payload["index"]
        vals = op.payload["values"]
        if i > len(kv.value):
            self._drop_if_empty(key, kv)
            raise IndexError(
                f"insert index {i} beyond list size {len(kv.value)}")
        if not vals:
            self._drop_if_empty(key, kv)
            op.future.set_result(False)
            return
        items = list(kv.value)
        items[i:i] = vals
        kv.value.clear()
        kv.value.extend(items)
        self._serve_waiters(key)
        op.future.set_result(True)

    def _op_linsert(self, key: str, op: Op) -> None:
        """LINSERT BEFORE|AFTER pivot value -> new size | -1 if no pivot."""
        kv = self._entry(key, T.LIST)
        if kv is None:
            op.future.set_result(0)
            return
        pivot = op.payload["pivot"]
        try:
            idx = list(kv.value).index(pivot)
        except ValueError:
            op.future.set_result(-1)
            return
        kv.value.insert(idx if op.payload.get("before", True) else idx + 1, op.payload["value"])
        self._serve_waiters(key)
        op.future.set_result(len(kv.value))

    def _op_lrem(self, key: str, op: Op) -> None:
        """LREM count value -> removed count (count>0 head-first, <0 tail-first, 0 all)."""
        kv = self._entry(key, T.LIST)
        if kv is None:
            op.future.set_result(0)
            return
        count, value = op.payload.get("count", 0), op.payload["value"]
        items = list(kv.value)
        removed = 0
        limit = abs(count) if count else len(items)
        if count < 0:
            items.reverse()
        out = []
        for v in items:
            if v == value and removed < limit:
                removed += 1
            else:
                out.append(v)
        if count < 0:
            out.reverse()
        kv.value = deque(out)
        self._drop_if_empty(key, kv)
        op.future.set_result(removed)

    def _op_lretain(self, key: str, op: Op) -> None:
        """List retainAll: in-place filter keeping order/dups of kept
        elements — one atomic op, expiry untouched (review r5: the old
        model-level delete()+rpush dropped the TTL and exposed a transient
        empty list)."""
        kv = self._entry(key, T.LIST)
        if kv is None:
            op.future.set_result(False)
            return
        keep = set(op.payload["members"])
        out = deque(v for v in kv.value if v in keep)
        changed = len(out) != len(kv.value)
        kv.value.clear()
        kv.value.extend(out)
        self._drop_if_empty(key, kv)
        op.future.set_result(changed)

    def _op_lrem_index(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.LIST)
        i = op.payload["index"]
        if kv is None or not -len(kv.value) <= i < len(kv.value):
            op.future.set_result(None)
            return
        old = kv.value[i]
        del kv.value[i]
        self._drop_if_empty(key, kv)
        op.future.set_result(old)

    def _op_llen(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.LIST)
        op.future.set_result(0 if kv is None else len(kv.value))

    def _op_lindexof(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.LIST)
        if kv is None:
            op.future.set_result(-1)
            return
        items = list(kv.value)
        v = op.payload["value"]
        if op.payload.get("last"):
            for i in range(len(items) - 1, -1, -1):
                if items[i] == v:
                    op.future.set_result(i)
                    return
            op.future.set_result(-1)
            return
        try:
            op.future.set_result(items.index(v))
        except ValueError:
            op.future.set_result(-1)

    def _op_ltrim(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.LIST)
        if kv is None:
            op.future.set_result(None)
            return
        items = list(kv.value)
        start, stop = op.payload["start"], op.payload["stop"]
        n = len(items)
        if start < 0:
            start += n
        if stop < 0:
            stop += n
        kv.value = deque(items[max(0, start) : stop + 1])
        self._drop_if_empty(key, kv)
        op.future.set_result(None)

    def _pop(self, key: str, side: str):
        kv = self._entry(key, T.LIST)
        if kv is None or not kv.value:
            return None
        v = kv.value.popleft() if side == "left" else kv.value.pop()
        self._drop_if_empty(key, kv)
        return v

    def _op_lpop(self, key: str, op: Op) -> None:
        op.future.set_result(self._pop(key, "left"))

    def _op_rpop(self, key: str, op: Op) -> None:
        op.future.set_result(self._pop(key, "right"))

    def _op_rpoplpush(self, key: str, op: Op) -> None:
        v = self._pop(key, "right")
        if v is not None:
            self._push(op.payload["dst"], [v], "left")
        op.future.set_result(v)

    # -- blocking pops (waiter machinery) ------------------------------------

    def _serve_waiters(self, key: str) -> None:
        """Fulfill parked blocking pops right after a push — same dispatch,
        so push→wake is atomic (the reference rides BLPOP inside Redis)."""
        q = self._waiters.get(key)
        while q:
            kv = self._entry(key, T.LIST)
            if kv is None or not kv.value:
                break
            w = q.popleft()
            if w.op.future.done():
                continue  # cancelled
            v = kv.value.popleft() if w.side == "left" else kv.value.pop()
            self._drop_if_empty(key, kv)
            if w.dest is not None:
                self._push(w.dest, [v], "left")
            w.op.future.set_result(v)
        if q is not None and not q:
            self._waiters.pop(key, None)

    def _op_bpop(self, key: str, op: Op) -> None:
        """BLPOP/BRPOP/BRPOPLPUSH: immediate pop or park a waiter.

        The future stays pending; the client thread waits with its own
        timeout and then submits bpop_cancel (the reference's blocking pops
        ride the no-timeout L2 path, `CommandAsyncService.java:491-497`).
        """
        side = op.payload.get("side", "left")
        dest = op.payload.get("dest")
        v = self._pop(key, side)
        if v is not None:
            if dest is not None:
                self._push(dest, [v], "left")
            op.future.set_result(v)
            return
        wid = next(self._waiter_ids)
        op.payload["wid"] = wid
        self._waiters.setdefault(key, deque()).append(Waiter(wid, op, side, dest))

    def _op_bpop_cancel(self, key: str, op: Op) -> None:
        """Resolve the park/fulfill race on the dispatcher thread: if the
        waiter is still pending, complete it with None (timeout).

        The waiter id is read from the *original bpop payload* (shared by
        reference) at dispatch time — per-target FIFO guarantees the bpop
        handler already ran and wrote it.
        """
        wid = op.payload["ref"].get("wid", -1)
        q = self._waiters.get(key)
        if q is not None:
            for w in list(q):
                if w.wid == wid:
                    q.remove(w)
                    if not w.op.future.done():
                        w.op.future.set_result(None)
                    break
            if not q:
                self._waiters.pop(key, None)
        op.future.set_result(None)

    def fail_waiters(self, exc: Optional[Exception] = None) -> None:
        """Complete every parked blocking-pop future on shutdown so client
        threads blocked in take()/poll() don't hang forever. Called after
        the dispatcher has exited (no concurrent handler activity)."""
        exc = exc or RuntimeError("client shut down while blocked")
        for q in list(self._waiters.values()):
            for w in q:
                if not w.op.future.done():
                    w.op.future.set_exception(exc)
        self._waiters.clear()

    # -- zset (RScoredSortedSet / RLexSortedSet) -----------------------------

    @staticmethod
    def _zsorted(d: Dict[bytes, float]) -> List[Tuple[bytes, float]]:
        return sorted(d.items(), key=lambda kvp: (kvp[1], kvp[0]))

    def _op_zadd(self, key: str, op: Op) -> None:
        kv = self._create(key, T.ZSET, dict)
        added = 0
        only_if_absent = op.payload.get("nx", False)
        for member, score in op.payload["pairs"]:
            if member not in kv.value:
                added += 1
                kv.value[member] = float(score)
            elif not only_if_absent:
                kv.value[member] = float(score)
        op.future.set_result(added)

    def _op_zscore(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.ZSET)
        op.future.set_result(None if kv is None else kv.value.get(op.payload["member"]))

    def _op_zincrby(self, key: str, op: Op) -> None:
        kv = self._create(key, T.ZSET, dict)
        m = op.payload["member"]
        kv.value[m] = kv.value.get(m, 0.0) + float(op.payload["by"])
        op.future.set_result(kv.value[m])

    def _op_zrem(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.ZSET)
        if kv is None:
            op.future.set_result(0)
            return
        n = sum(1 for m in op.payload["members"] if kv.value.pop(m, None) is not None)
        self._drop_if_empty(key, kv)
        op.future.set_result(n)

    def _op_zcard(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.ZSET)
        op.future.set_result(0 if kv is None else len(kv.value))

    @staticmethod
    def _score_in(score, lo, hi, lo_inc, hi_inc) -> bool:
        if lo is not None and (score < lo or (score == lo and not lo_inc)):
            return False
        if hi is not None and (score > hi or (score == hi and not hi_inc)):
            return False
        return True

    def _op_zcount(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.ZSET)
        if kv is None:
            op.future.set_result(0)
            return
        p = op.payload
        op.future.set_result(
            sum(
                1
                for s in kv.value.values()
                if self._score_in(s, p.get("min"), p.get("max"), p.get("min_inc", True), p.get("max_inc", True))
            )
        )

    def _op_zrank(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.ZSET)
        if kv is None:
            op.future.set_result(None)
            return
        ordered = self._zsorted(kv.value)
        if op.payload.get("rev"):
            ordered = ordered[::-1]
        for i, (m, _) in enumerate(ordered):
            if m == op.payload["member"]:
                op.future.set_result(i)
                return
        op.future.set_result(None)

    def _op_zrange(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.ZSET)
        if kv is None:
            op.future.set_result([])
            return
        ordered = self._zsorted(kv.value)
        if op.payload.get("rev"):
            ordered = ordered[::-1]
        start, stop = op.payload["start"], op.payload["stop"]
        n = len(ordered)
        if start < 0:
            start += n
        if stop < 0:
            stop += n
        chunk = ordered[max(0, start) : stop + 1]
        if op.payload.get("withscores"):
            op.future.set_result(chunk)
        else:
            op.future.set_result([m for m, _ in chunk])

    def _op_zrangebyscore(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.ZSET)
        if kv is None:
            op.future.set_result([])
            return
        p = op.payload
        ordered = [
            (m, s)
            for m, s in self._zsorted(kv.value)
            if self._score_in(s, p.get("min"), p.get("max"), p.get("min_inc", True), p.get("max_inc", True))
        ]
        if p.get("rev"):
            ordered = ordered[::-1]
        off, cnt = p.get("offset", 0), p.get("count")
        ordered = ordered[off:] if cnt is None else ordered[off : off + cnt]
        if p.get("withscores"):
            op.future.set_result(ordered)
        else:
            op.future.set_result([m for m, _ in ordered])

    def _op_zremrangebyscore(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.ZSET)
        if kv is None:
            op.future.set_result(0)
            return
        p = op.payload
        doomed = [
            m
            for m, s in kv.value.items()
            if self._score_in(s, p.get("min"), p.get("max"), p.get("min_inc", True), p.get("max_inc", True))
        ]
        for m in doomed:
            del kv.value[m]
        self._drop_if_empty(key, kv)
        op.future.set_result(len(doomed))

    def _op_zremrangebyrank(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.ZSET)
        if kv is None:
            op.future.set_result(0)
            return
        ordered = self._zsorted(kv.value)
        start, stop = op.payload["start"], op.payload["stop"]
        n = len(ordered)
        if start < 0:
            start += n
        if stop < 0:
            stop += n
        doomed = ordered[max(0, start) : stop + 1]
        for m, _ in doomed:
            del kv.value[m]
        self._drop_if_empty(key, kv)
        op.future.set_result(len(doomed))

    def _op_zpop(self, key: str, op: Op) -> None:
        """pollFirst/pollLast."""
        kv = self._entry(key, T.ZSET)
        if kv is None or not kv.value:
            op.future.set_result(None)
            return
        ordered = self._zsorted(kv.value)
        m, s = ordered[-1] if op.payload.get("last") else ordered[0]
        del kv.value[m]
        self._drop_if_empty(key, kv)
        op.future.set_result((m, s))

    def _op_zmscore(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.ZSET)
        members = op.payload["members"]
        if kv is None:
            op.future.set_result([None] * len(members))
            return
        op.future.set_result([kv.value.get(m) for m in members])

    def _op_zstore(self, key: str, op: Op) -> None:
        """ZUNIONSTORE/ZINTERSTORE with SUM aggregation (reference union/intersection)."""
        which = op.payload["op"]
        maps: List[Dict[bytes, float]] = []
        for n in op.payload["names"]:
            kv = self._entry(n, T.ZSET)
            maps.append({} if kv is None else dict(kv.value))
        if which == "union":
            out: Dict[bytes, float] = {}
            for m in maps:
                for member, score in m.items():
                    out[member] = out.get(member, 0.0) + score
        else:
            common = set(maps[0]) if maps else set()
            for m in maps[1:]:
                common &= set(m)
            out = {member: sum(m.get(member, 0.0) for m in maps) for member in common}
        if out:
            self._create(key, T.ZSET, dict).value = out
        else:
            self._drop(key)
        op.future.set_result(len(out))

    # lex ranges over a zset where all scores are equal (RLexSortedSet)

    @staticmethod
    def _lex_in(m, lo, hi, lo_inc, hi_inc) -> bool:
        if lo is not None and (m < lo or (m == lo and not lo_inc)):
            return False
        if hi is not None and (m > hi or (m == hi and not hi_inc)):
            return False
        return True

    def _op_zrangebylex(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.ZSET)
        if kv is None:
            op.future.set_result([])
            return
        p = op.payload
        members = sorted(kv.value)
        out = [
            m
            for m in members
            if self._lex_in(m, p.get("min"), p.get("max"), p.get("min_inc", True), p.get("max_inc", True))
        ]
        if p.get("rev"):
            out = out[::-1]
        off, cnt = p.get("offset", 0), p.get("count")
        op.future.set_result(out[off:] if cnt is None else out[off : off + cnt])

    def _op_zremrangebylex(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.ZSET)
        if kv is None:
            op.future.set_result(0)
            return
        p = op.payload
        doomed = [
            m
            for m in kv.value
            if self._lex_in(m, p.get("min"), p.get("max"), p.get("min_inc", True), p.get("max_inc", True))
        ]
        for m in doomed:
            del kv.value[m]
        self._drop_if_empty(key, kv)
        op.future.set_result(len(doomed))

    def _op_zscan(self, key: str, op: Op) -> None:
        kv = self._entry(key, T.ZSET)
        cursor, count = op.payload["cursor"], op.payload.get("count", 10)
        if kv is None:
            op.future.set_result((0, []))
            return
        nxt, members = self._scan_page(kv, cursor, count)
        op.future.set_result((nxt, [(m, kv.value[m]) for m in members]))

    # -- pub/sub -------------------------------------------------------------

    def _op_publish(self, key: str, op: Op) -> None:
        op.future.set_result(self.pubsub.publish(op.payload["channel"], op.payload["message"]))


def filter_state_dump(blob: bytes, keep) -> Tuple[bytes, int]:
    """Project a dump_state() capture onto the keys `keep(name)` accepts,
    returning (filtered blob, kept count). Pure host-side pickle surgery —
    the slot migrator filters a source snapshot's structure sidecar down to
    the migrating slots before shipping it as a `migrate_install` op."""
    import pickle

    payload = pickle.loads(blob)
    if payload.get("format") != 1:
        raise ValueError(
            f"unsupported structure dump format {payload.get('format')!r}")
    data = {k: v for k, v in payload["data"].items() if keep(k)}
    return (pickle.dumps({"format": 1, "data": data},
                         protocol=pickle.HIGHEST_PROTOCOL), len(data))
