"""Extended structure ops: TTL caches, multimaps, geo, and coordination.

Mixed into StructureBackend. Each handler is one atomic op on the dispatcher
thread — the analogue of the reference's Lua scripts:

  * mapcache/setcache — per-entry TTL + maxIdle kept next to the value, the
    companion-zset design of `RedissonMapCache.java:75-87` collapsed into
    one record; evicted lazily + by the EvictionScheduler sweep op.
  * locks — hash field `uuid:thread` -> reentrancy count with a lease
    deadline (`RedissonLock.java:236-252`); unlock publishes to the lock
    channel to wake waiters (`:324-343`).
  * semaphore / countdownlatch — counters + publish
    (`RedissonSemaphore.java`, `RedissonCountDownLatch.java`).
  * multimap — key -> set|list of values (`RedissonSetMultimap` /
    `RedissonListMultimap` keep per-key sub-collections; one record here).
  * geo — member -> (lon, lat); radius/dist computed with vectorized
    numpy haversine over the whole structure (batch math, not a port of
    Redis' geohash zset encoding).
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from redisson_tpu.executor import Op

LOCK_CHANNEL_PREFIX = "redisson_lock__channel:"
SEMAPHORE_CHANNEL_PREFIX = "redisson_sem__channel:"
LATCH_CHANNEL_PREFIX = "redisson_latch__channel:"

UNLOCK_MESSAGE = 0
READ_UNLOCK_MESSAGE = 1
LATCH_ZERO_MESSAGE = "zero"


def _earth_m(unit: str) -> float:
    return {"m": 1.0, "km": 1000.0, "mi": 1609.344, "ft": 0.3048}[unit]


def _haversine_m(lon1, lat1, lon2, lat2):
    """Vectorized great-circle distance in meters (numpy arrays ok)."""
    lon1, lat1, lon2, lat2 = (np.radians(np.asarray(x, np.float64)) for x in (lon1, lat1, lon2, lat2))
    dlon, dlat = lon2 - lon1, lat2 - lat1
    a = np.sin(dlat / 2) ** 2 + np.cos(lat1) * np.cos(lat2) * np.sin(dlon / 2) ** 2
    return 6372797.560856 * 2 * np.arcsin(np.sqrt(a))


class ExtendedOps:
    """Mixin for StructureBackend (relies on _entry/_create/_drop/pubsub)."""

    # ==== mapcache (RMapCache) =============================================
    # value: dict[field] = [value, expire_at_ms|None, max_idle_ms|None, last_access_ms]

    def _mc_live(self, kv, field) -> Optional[list]:
        from redisson_tpu.structures.engine import now_ms

        rec = kv.value.get(field)
        if rec is None:
            return None
        t = now_ms()
        if (rec[1] is not None and rec[1] <= t) or (
            rec[2] is not None and rec[3] + rec[2] <= t
        ):
            del kv.value[field]
            return None
        return rec

    def _op_mc_put(self, key: str, op: Op) -> None:
        from redisson_tpu.structures.engine import T, now_ms

        kv = self._create(key, T.MAPCACHE, dict)
        t = now_ms()
        rec = self._mc_live(kv, op.payload["field"])
        old = None if rec is None else rec[0]
        if op.payload.get("if_absent") and old is not None:
            op.future.set_result(old)
            return
        ttl = op.payload.get("ttl_ms")
        idle = op.payload.get("max_idle_ms")
        kv.value[op.payload["field"]] = [
            op.payload["value"],
            None if not ttl else t + int(ttl),
            None if not idle else int(idle),
            t,
        ]
        op.future.set_result(old)

    def _op_mc_get(self, key: str, op: Op) -> None:
        from redisson_tpu.structures.engine import T, now_ms

        kv = self._entry(key, T.MAPCACHE)
        if kv is None:
            op.future.set_result(None)
            return
        rec = self._mc_live(kv, op.payload["field"])
        if rec is None:
            op.future.set_result(None)
            return
        rec[3] = now_ms()  # touch for maxIdle
        op.future.set_result(rec[0])

    def _op_mc_remove(self, key: str, op: Op) -> None:
        from redisson_tpu.structures.engine import T

        kv = self._entry(key, T.MAPCACHE)
        if kv is None:
            op.future.set_result(None)
            return
        rec = self._mc_live(kv, op.payload["field"])
        old = None if rec is None else rec[0]
        kv.value.pop(op.payload["field"], None)
        self._drop_if_empty(key, kv)
        op.future.set_result(old)

    def _op_mc_contains(self, key: str, op: Op) -> None:
        from redisson_tpu.structures.engine import T

        kv = self._entry(key, T.MAPCACHE)
        op.future.set_result(kv is not None and self._mc_live(kv, op.payload["field"]) is not None)

    def _op_mc_size(self, key: str, op: Op) -> None:
        from redisson_tpu.structures.engine import T

        kv = self._entry(key, T.MAPCACHE)
        if kv is None:
            op.future.set_result(0)
            return
        for f in list(kv.value):
            self._mc_live(kv, f)
        op.future.set_result(len(kv.value))

    def _op_mc_getall(self, key: str, op: Op) -> None:
        from redisson_tpu.structures.engine import T

        kv = self._entry(key, T.MAPCACHE)
        if kv is None:
            op.future.set_result({})
            return
        out = {}
        for f in list(kv.value):
            rec = self._mc_live(kv, f)
            if rec is not None:
                out[f] = rec[0]
        op.future.set_result(out)

    def _op_mc_evict_expired(self, key: str, op: Op) -> None:
        """The EvictionScheduler's sweep: delete up to `limit` expired
        entries, return the count (`EvictionScheduler.java:47-115` batches
        of <=300 via Lua)."""
        from redisson_tpu.structures.engine import T, now_ms

        kv = self._entry(key)
        if kv is None:
            op.future.set_result(0)
            return
        limit = op.payload.get("limit", 300)
        t = now_ms()
        n = 0
        if kv.otype == T.MAPCACHE:
            for f, rec in list(kv.value.items()):
                if n >= limit:
                    break
                if (rec[1] is not None and rec[1] <= t) or (
                    rec[2] is not None and rec[3] + rec[2] <= t
                ):
                    del kv.value[f]
                    n += 1
        elif kv.otype == T.SETCACHE:
            for m, exp in list(kv.value.items()):
                if n >= limit:
                    break
                if exp is not None and exp <= t:
                    del kv.value[m]
                    n += 1
        self._drop_if_empty(key, kv)
        op.future.set_result(n)

    # ==== setcache (RSetCache) =============================================
    # value: dict[member] = expire_at_ms | None

    def _op_sc_add(self, key: str, op: Op) -> None:
        from redisson_tpu.structures.engine import T, now_ms

        kv = self._create(key, T.SETCACHE, dict)
        m = op.payload["member"]
        ttl = op.payload.get("ttl_ms")
        exp = kv.value.get(m, 0)
        is_new = not (m in kv.value and (exp is None or exp > now_ms()))
        kv.value[m] = None if not ttl else now_ms() + int(ttl)
        op.future.set_result(is_new)

    def _op_sc_contains(self, key: str, op: Op) -> None:
        from redisson_tpu.structures.engine import T, now_ms

        kv = self._entry(key, T.SETCACHE)
        if kv is None:
            op.future.set_result(False)
            return
        m = op.payload["member"]
        exp = kv.value.get(m, 0)
        if m in kv.value and exp is not None and exp <= now_ms():
            del kv.value[m]
        op.future.set_result(m in kv.value)

    def _op_sc_remove(self, key: str, op: Op) -> None:
        from redisson_tpu.structures.engine import T

        kv = self._entry(key, T.SETCACHE)
        if kv is None:
            op.future.set_result(False)
            return
        removed = op.payload["member"] in kv.value
        kv.value.pop(op.payload["member"], None)
        self._drop_if_empty(key, kv)
        op.future.set_result(removed)

    def _op_sc_size(self, key: str, op: Op) -> None:
        from redisson_tpu.structures.engine import T, now_ms

        kv = self._entry(key, T.SETCACHE)
        if kv is None:
            op.future.set_result(0)
            return
        t = now_ms()
        for m, exp in list(kv.value.items()):
            if exp is not None and exp <= t:
                del kv.value[m]
        op.future.set_result(len(kv.value))

    def _op_sc_members(self, key: str, op: Op) -> None:
        from redisson_tpu.structures.engine import T, now_ms

        kv = self._entry(key, T.SETCACHE)
        if kv is None:
            op.future.set_result([])
            return
        t = now_ms()
        out = []
        for m, exp in list(kv.value.items()):
            if exp is not None and exp <= t:
                del kv.value[m]
            else:
                out.append(m)
        op.future.set_result(out)

    # ==== multimap =========================================================
    # value: dict[key_bytes] = set() | deque()

    def _mm_type(self, op: Op):
        from redisson_tpu.structures.engine import T

        return T.MULTIMAP_LIST if op.payload.get("list") else T.MULTIMAP_SET

    def _mm_reap(self, key: str, kv) -> None:
        """Drop multimap keys whose per-key TTL passed (the multimap-cache
        timeout-zset sweep, RedissonMultimapCache.java, done lazily); a
        multimap whose last key expires disappears, as in redis mode."""
        if kv is None or kv.mm_expiry is None:
            return
        from redisson_tpu.structures.engine import now_ms

        t = now_ms()
        for k in [k for k, dl in kv.mm_expiry.items() if dl <= t]:
            kv.value.pop(k, None)
            del kv.mm_expiry[k]
        self._drop_if_empty(key, kv)

    def _mm_entry(self, key: str, op: Op):
        kv = self._entry(key, self._mm_type(op))
        self._mm_reap(key, kv)
        return kv

    def _op_mm_delete(self, key: str, op: Op) -> None:
        """Delete the multimap + its TTL state (reference deleteAsync)."""
        kv = self._entry(key)
        op.future.set_result(kv is not None and self._drop(key))

    def _op_mm_expire_key(self, key: str, op: Op) -> None:
        """expireKey(key, ttl): per-key TTL, True only when the key exists
        (RedissonMultimapCache.expireKeyAsync contract)."""
        from redisson_tpu.structures.engine import now_ms

        kv = self._mm_entry(key, op)
        k = op.payload["key"]
        if kv is None or k not in kv.value:
            op.future.set_result(False)
            return
        ttl_ms = op.payload.get("ttl_ms")
        if not ttl_ms or ttl_ms <= 0:
            if kv.mm_expiry is not None:
                kv.mm_expiry.pop(k, None)
        else:
            if kv.mm_expiry is None:
                kv.mm_expiry = {}
            kv.mm_expiry[k] = now_ms() + int(ttl_ms)
        op.future.set_result(True)

    def _op_mm_put(self, key: str, op: Op) -> None:
        # Reap BEFORE _create: reaping afterwards could drop a newly
        # re-registered (emptied) multimap from the store and lose this
        # put into the detached KV.
        self._mm_reap(key, self._entry(key, self._mm_type(op)))
        kv = self._create(key, self._mm_type(op), dict)
        k = op.payload["key"]
        if op.payload.get("list"):
            bucket = kv.value.setdefault(k, deque())
            bucket.append(op.payload["value"])
            op.future.set_result(True)
        else:
            bucket = kv.value.setdefault(k, set())
            before = len(bucket)
            bucket.add(op.payload["value"])
            op.future.set_result(len(bucket) != before)

    def _op_mm_get_all(self, key: str, op: Op) -> None:
        kv = self._mm_entry(key, op)
        if kv is None:
            op.future.set_result([])
            return
        bucket = kv.value.get(op.payload["key"])
        op.future.set_result([] if bucket is None else list(bucket))

    def _op_mm_remove(self, key: str, op: Op) -> None:
        kv = self._mm_entry(key, op)
        if kv is None:
            op.future.set_result(False)
            return
        bucket = kv.value.get(op.payload["key"])
        if bucket is None:
            op.future.set_result(False)
            return
        try:
            bucket.remove(op.payload["value"])
            ok = True
        except (KeyError, ValueError):
            ok = False
        if not bucket:
            del kv.value[op.payload["key"]]
            if kv.mm_expiry is not None:
                kv.mm_expiry.pop(op.payload["key"], None)
        self._drop_if_empty(key, kv)
        op.future.set_result(ok)

    def _op_mm_remove_all(self, key: str, op: Op) -> None:
        kv = self._mm_entry(key, op)
        if kv is None:
            op.future.set_result([])
            return
        bucket = kv.value.pop(op.payload["key"], None)
        if kv.mm_expiry is not None:
            kv.mm_expiry.pop(op.payload["key"], None)
        self._drop_if_empty(key, kv)
        op.future.set_result([] if bucket is None else list(bucket))

    def _op_mm_keys(self, key: str, op: Op) -> None:
        kv = self._mm_entry(key, op)
        op.future.set_result([] if kv is None else list(kv.value.keys()))

    def _op_mm_size(self, key: str, op: Op) -> None:
        kv = self._mm_entry(key, op)
        op.future.set_result(0 if kv is None else sum(len(b) for b in kv.value.values()))

    def _op_mm_key_size(self, key: str, op: Op) -> None:
        kv = self._mm_entry(key, op)
        op.future.set_result(0 if kv is None else len(kv.value))

    def _op_mm_contains_key(self, key: str, op: Op) -> None:
        kv = self._mm_entry(key, op)
        op.future.set_result(kv is not None and op.payload["key"] in kv.value)

    def _op_mm_contains_value(self, key: str, op: Op) -> None:
        kv = self._mm_entry(key, op)
        v = op.payload["value"]
        op.future.set_result(kv is not None and any(v in b for b in kv.value.values()))

    def _op_mm_contains_entry(self, key: str, op: Op) -> None:
        kv = self._mm_entry(key, op)
        bucket = None if kv is None else kv.value.get(op.payload["key"])
        op.future.set_result(bucket is not None and op.payload["value"] in bucket)

    def _op_mm_entries(self, key: str, op: Op) -> None:
        kv = self._mm_entry(key, op)
        if kv is None:
            op.future.set_result([])
            return
        op.future.set_result([(k, v) for k, b in kv.value.items() for v in b])

    # ==== geo (RGeo) =======================================================
    # value: dict[member] = (lon, lat)

    def _op_geoadd(self, key: str, op: Op) -> None:
        from redisson_tpu.structures.engine import T

        kv = self._create(key, T.GEO, dict)
        added = 0
        for lon, lat, member in op.payload["entries"]:
            if member not in kv.value:
                added += 1
            kv.value[member] = (float(lon), float(lat))
        op.future.set_result(added)

    def _op_geopos(self, key: str, op: Op) -> None:
        from redisson_tpu.structures.engine import T

        kv = self._entry(key, T.GEO)
        members = op.payload["members"]
        if kv is None:
            op.future.set_result({})
            return
        op.future.set_result({m: kv.value[m] for m in members if m in kv.value})

    def _op_geodist(self, key: str, op: Op) -> None:
        from redisson_tpu.structures.engine import T

        kv = self._entry(key, T.GEO)
        a = None if kv is None else kv.value.get(op.payload["m1"])
        b = None if kv is None else kv.value.get(op.payload["m2"])
        if a is None or b is None:
            op.future.set_result(None)
            return
        d = float(_haversine_m(a[0], a[1], b[0], b[1]))
        op.future.set_result(d / _earth_m(op.payload.get("unit", "m")))

    def _op_georadius(self, key: str, op: Op) -> None:
        """GEORADIUS / GEORADIUSBYMEMBER: one vectorized haversine over all
        members (numpy batch — the Redis zset walk, done as array math)."""
        from redisson_tpu.structures.engine import T

        kv = self._entry(key, T.GEO)
        if kv is None or not kv.value:
            op.future.set_result([])
            return
        if "member" in op.payload:
            center = kv.value.get(op.payload["member"])
            if center is None:
                op.future.set_result([])
                return
            lon0, lat0 = center
        else:
            lon0, lat0 = op.payload["lon"], op.payload["lat"]
        members = list(kv.value.keys())
        coords = np.array([kv.value[m] for m in members], np.float64)
        dist_m = _haversine_m(lon0, lat0, coords[:, 0], coords[:, 1])
        radius_m = op.payload["radius"] * _earth_m(op.payload.get("unit", "m"))
        unit = _earth_m(op.payload.get("unit", "m"))
        hits = [
            (members[i], float(dist_m[i]) / unit, (float(coords[i, 0]), float(coords[i, 1])))
            for i in np.flatnonzero(dist_m <= radius_m)
        ]
        hits.sort(key=lambda h: h[1])
        count = op.payload.get("count")
        if count is not None:
            hits = hits[:count]
        op.future.set_result(hits)

    # ==== locks ============================================================
    # value: {"holds": {owner: {"write": n, "read": n}},
    #         "lease": {owner: deadline_ms|None},
    #         "queue": [[owner, deadline_ms], ...]}  (fair-lock waiters)
    #
    # The mode is derived: write if any owner holds a write count, read if
    # only read counts, free otherwise — so a writer taking a reentrant read
    # never downgrades exclusion.

    QUEUE_SLACK_MS = 5_000  # fair-queue entry TTL slack (threadWaitTime analogue)

    def _lock_state(self, key: str):
        from redisson_tpu.structures.engine import T

        return self._create(key, T.LOCK, lambda: {"holds": {}, "lease": {}, "queue": []})

    @staticmethod
    def _lock_mode(st) -> str:
        if any(h["write"] > 0 for h in st["holds"].values()):
            return "write"
        return "read" if st["holds"] else "free"

    def _lock_reap(self, kv) -> None:
        """Drop owners whose lease expired (watchdog missed = orphan lock;
        the reference relies on the Redis PEXPIRE, `RedissonLock.java:59-61`)
        and fair-queue entries whose wait deadline passed (abandoned waiters
        must not wedge the queue — the reference's fair-lock Lua expires
        queue entries by timeout)."""
        from redisson_tpu.structures.engine import now_ms

        t = now_ms()
        st = kv.value
        for o in [o for o, dl in st["lease"].items() if dl is not None and dl <= t]:
            st["holds"].pop(o, None)
            st["lease"].pop(o, None)
        st["queue"] = [e for e in st["queue"] if e[1] > t]

    def _op_lock_try(self, key: str, op: Op) -> None:
        """tryLockInner: None = acquired; else remaining ttl ms of the
        current holder (`RedissonLock.java:236-252` Lua contract).

        payload: owner, lease_ms, mode (write|read), fair, enqueue (register
        as a fair waiter when blocked), wait_ms (fair-queue entry TTL).
        """
        from redisson_tpu.structures.engine import now_ms

        kv = self._lock_state(key)
        self._lock_reap(kv)
        p = op.payload
        owner, mode = p["owner"], p.get("mode", "write")
        fair = p.get("fair", False)
        st = kv.value
        t = now_ms()

        def block():
            if fair and p.get("enqueue"):
                ttl_entry = t + int(p.get("wait_ms") or 0) + self.QUEUE_SLACK_MS
                for e in st["queue"]:
                    if e[0] == owner:
                        e[1] = ttl_entry  # refresh on retry
                        break
                else:
                    st["queue"].append([owner, ttl_entry])
            op.future.set_result(self._lock_ttl(st))

        # fair: only the queue head (or an existing holder re-entering) may
        # pass while others wait
        if (
            fair
            and st["queue"]
            and st["queue"][0][0] != owner
            and owner not in st["holds"]
        ):
            block()
            return

        cur_mode = self._lock_mode(st)
        if mode == "write":
            # exclusive: free, or this owner is the sole holder (reentrant /
            # upgrade)
            can = not st["holds"] or set(st["holds"]) == {owner}
        else:
            # shared: no *other* owner may hold write
            can = all(
                o == owner or h["write"] == 0 for o, h in st["holds"].items()
            )
        if not can:
            block()
            return

        if fair:
            st["queue"] = [e for e in st["queue"] if e[0] != owner]
        hold = st["holds"].setdefault(owner, {"write": 0, "read": 0})
        hold[mode] += 1
        lease = p.get("lease_ms")
        st["lease"][owner] = None if not lease else t + int(lease)
        op.future.set_result(None)

    @staticmethod
    def _lock_ttl(st) -> int:
        from redisson_tpu.structures.engine import now_ms

        deadlines = [d for d in st["lease"].values() if d is not None]
        if not deadlines:
            return -1  # held without lease
        return max(0, max(deadlines) - now_ms())

    def _op_lock_unlock(self, key: str, op: Op) -> None:
        """None = not owner (caller raises IllegalMonitorState analogue);
        False = still held (reentrant); True = this owner fully released
        (+ published if the lock went free)."""
        kv = self._lock_state(key)
        self._lock_reap(kv)
        owner, mode = op.payload["owner"], op.payload.get("mode", "write")
        st = kv.value
        hold = st["holds"].get(owner)
        if hold is None or hold[mode] <= 0:
            op.future.set_result(None)
            return
        hold[mode] -= 1
        if hold["write"] > 0 or hold["read"] > 0:
            op.future.set_result(False)
            return
        del st["holds"][owner]
        st["lease"].pop(owner, None)
        if not st["holds"]:
            if not st["queue"]:
                self._drop(key)
            self.pubsub.publish(
                LOCK_CHANNEL_PREFIX + key,
                READ_UNLOCK_MESSAGE if mode == "read" else UNLOCK_MESSAGE,
            )
        op.future.set_result(True)

    def _op_lock_queue_remove(self, key: str, op: Op) -> None:
        """A fair waiter giving up (try_lock timeout) dequeues itself."""
        from redisson_tpu.structures.engine import T

        kv = self._entry(key, T.LOCK)
        if kv is not None:
            kv.value["queue"] = [e for e in kv.value["queue"] if e[0] != op.payload["owner"]]
            if not kv.value["holds"] and not kv.value["queue"]:
                self._drop(key)
        op.future.set_result(None)

    def _op_lock_renew(self, key: str, op: Op) -> None:
        """Watchdog renewal (`RedissonLock.java:197-227`). Reads via _entry:
        a renewal racing an unlock must not resurrect the key."""
        from redisson_tpu.structures.engine import T, now_ms

        kv = self._entry(key, T.LOCK)
        owner = op.payload["owner"]
        if kv is None or owner not in kv.value["holds"]:
            op.future.set_result(False)
            return
        kv.value["lease"][owner] = now_ms() + int(op.payload["lease_ms"])
        op.future.set_result(True)

    def _op_lock_force_unlock(self, key: str, op: Op) -> None:
        existed = self._drop(key)
        self.pubsub.publish(LOCK_CHANNEL_PREFIX + key, UNLOCK_MESSAGE)
        op.future.set_result(existed)

    def _op_lock_state(self, key: str, op: Op) -> None:
        """(is_locked, hold_count_for_owner, mode) introspection."""
        from redisson_tpu.structures.engine import T

        kv = self._entry(key, T.LOCK)
        if kv is None:
            op.future.set_result((False, 0, "free"))
            return
        self._lock_reap(kv)
        st = kv.value
        owner = op.payload.get("owner")
        hold = st["holds"].get(owner) if owner else None
        count = 0 if hold is None else hold["write"] + hold["read"]
        op.future.set_result((bool(st["holds"]), count, self._lock_mode(st)))

    # ==== semaphore ========================================================
    # value: int available permits

    def _op_sem_try_set_permits(self, key: str, op: Op) -> None:
        from redisson_tpu.structures.engine import T

        kv = self._entry(key, T.SEMAPHORE)
        if kv is not None:
            op.future.set_result(False)
            return
        self._create(key, T.SEMAPHORE, lambda: int(op.payload["permits"]))
        op.future.set_result(True)

    def _op_sem_try_acquire(self, key: str, op: Op) -> None:
        from redisson_tpu.structures.engine import T

        kv = self._entry(key, T.SEMAPHORE)
        n = int(op.payload.get("permits", 1))
        if kv is None or kv.value < n:
            op.future.set_result(False)
            return
        kv.value -= n
        op.future.set_result(True)

    def _op_sem_release(self, key: str, op: Op) -> None:
        from redisson_tpu.structures.engine import T

        kv = self._create(key, T.SEMAPHORE, lambda: 0)
        kv.value += int(op.payload.get("permits", 1))
        self.pubsub.publish(SEMAPHORE_CHANNEL_PREFIX + key, kv.value)
        op.future.set_result(None)

    def _op_sem_available(self, key: str, op: Op) -> None:
        from redisson_tpu.structures.engine import T

        kv = self._entry(key, T.SEMAPHORE)
        op.future.set_result(0 if kv is None else int(kv.value))

    def _op_sem_drain(self, key: str, op: Op) -> None:
        from redisson_tpu.structures.engine import T

        kv = self._entry(key, T.SEMAPHORE)
        drained = 0 if kv is None else int(kv.value)
        if kv is not None:
            kv.value = 0
        op.future.set_result(drained)

    def _op_sem_set_permits(self, key: str, op: Op) -> None:
        from redisson_tpu.structures.engine import T

        kv = self._create(key, T.SEMAPHORE, lambda: 0)
        kv.value = int(op.payload["permits"])
        if kv.value > 0:
            self.pubsub.publish(SEMAPHORE_CHANNEL_PREFIX + key, kv.value)
        op.future.set_result(None)

    def _op_sem_add_permits(self, key: str, op: Op) -> None:
        from redisson_tpu.structures.engine import T

        kv = self._create(key, T.SEMAPHORE, lambda: 0)
        kv.value += int(op.payload["permits"])  # may go negative (reference reducePermits)
        if kv.value > 0:
            self.pubsub.publish(SEMAPHORE_CHANNEL_PREFIX + key, kv.value)
        op.future.set_result(None)

    # ==== countdownlatch ===================================================
    # value: int remaining count

    def _op_latch_try_set(self, key: str, op: Op) -> None:
        from redisson_tpu.structures.engine import T

        kv = self._entry(key, T.LATCH)
        if kv is not None and kv.value > 0:
            op.future.set_result(False)
            return
        self._create(key, T.LATCH, lambda: 0)
        self._entry(key, T.LATCH).value = int(op.payload["count"])
        op.future.set_result(True)

    def _op_latch_count_down(self, key: str, op: Op) -> None:
        from redisson_tpu.structures.engine import T

        kv = self._entry(key, T.LATCH)
        if kv is None or kv.value <= 0:
            op.future.set_result(0)
            return
        kv.value -= 1
        if kv.value == 0:
            self._drop(key)
            self.pubsub.publish(LATCH_CHANNEL_PREFIX + key, LATCH_ZERO_MESSAGE)
            op.future.set_result(0)
            return
        op.future.set_result(int(kv.value))

    def _op_latch_get(self, key: str, op: Op) -> None:
        from redisson_tpu.structures.engine import T

        kv = self._entry(key, T.LATCH)
        op.future.set_result(0 if kv is None else int(kv.value))
