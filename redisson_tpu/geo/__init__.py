"""Active-active geo-replication: CRDT site merge + anti-entropy.

N independent engine stacks ("sites") each accept local writes and
converge asynchronously. There is no cross-site consensus and no
leader: the persist journal IS the replication transport — each site's
``SiteLink`` (link.py) tails its own journal and ships folded **delta
planes** to every peer, and the receiver applies them through the same
fused ``delta_merge_stack`` path local ingest uses, so one batched
semilattice max per pipeline window absorbs a whole remote batch
regardless of how many origin ops it folds.

Convergence contract
--------------------
Two sites that have delivered the same set of messages hold
**bit-identical** sketch state. The guarantee splits by op class:

* **Semilattice writes** (PFADD / bloom add / SETBIT-to-1) commute:
  register max and bit OR are joins, so delivery order, duplication
  (anti-entropy re-ship), and folding granularity are all invisible.
  These converge with no arbitration and can never lose data.

* **Destructive writes** (DEL, FLUSHALL, RENAME, SETBIT-to-0) are NOT
  joins. They are arbitrated **last-writer-wins** on the total order of
  stamps ``(origin_journal_seq, site_id)`` (applier.py): a destructive
  op erases exactly the writes with smaller stamps, everywhere. A DEL
  racing a newer merge is *suppressed* at the site holding the newer
  write, which re-ships the key's full state so the deleting site
  resurrects it — the race resolves add-wins, deterministically, at
  every site. FLUSHALL resolves per key by the same rule: receivers
  wipe exactly the keys whose newest write predates the flush stamp
  and re-ship the survivors, resurrecting them at the flushing site.
  Consequence to document, not hide: a DEL acknowledged at
  site A may be overridden by a concurrent higher-stamped write at
  site B; "acked" for destructive ops means *locally durable*, not
  *globally final* until the sites have exchanged vectors.

* **Non-replicated kinds** (bitset NOT/AND/rotate, structure-tier ops,
  hll_merge, …) stay site-local; geo replicates the sketch-tier write
  kinds in ``SHIP_KINDS`` only.

Anti-entropy (manager.py) closes the loop: links rewind to the peer's
version-vector cursor after restarts, a compacted-away journal range
triggers full-state snapshot repair, and the LWW maps persist in a
``geo_state.json`` sidecar so arbitration survives a site crash.

Reads are always local and expose per-site staleness via
``client.info()['replication']`` (per-peer vector + link lag).
"""

from redisson_tpu.geo.applier import (
    DESTRUCTIVE_KINDS,
    GeoApplier,
    NEG_STAMP,
    SEMILATTICE_KINDS,
    SHIP_KINDS,
    stamp_of,
)
from redisson_tpu.geo.link import SiteLink
from redisson_tpu.geo.manager import GeoManager, connect_sites, converge

__all__ = [
    "DESTRUCTIVE_KINDS",
    "GeoApplier",
    "GeoManager",
    "NEG_STAMP",
    "SEMILATTICE_KINDS",
    "SHIP_KINDS",
    "SiteLink",
    "connect_sites",
    "converge",
    "stamp_of",
]
