"""SiteLink: the per-peer shipping thread — the journal IS the transport.

One link per (local site, peer) direction. Each tick it polls a
``JournalTail`` over the local persist journal, filters to the shippable
kinds, folds runs of semilattice writes into **delta planes** (the same
host folds the delta ingest path uses — what crosses the link is a
register/bit plane, not the key batch), and delivers the batch to the
peer's applier together with a vv watermark.

Fold groups are cut at destructive-op boundaries per key, so the shipped
message order preserves the origin's per-key op order: a DEL between two
PFADD runs ships as merge / delete / merge, never merge+merge / delete.
Destructive kinds transform at ship time:

  delete        -> tombstone message (receiver LWW-arbitrates)
  rename        -> delete(src) + full-state replace(dst) read at ship
                   time (the journal has the op, not the moved bytes)
  bitset_clear  -> full-state replace (clears are not a join; the plane
                   after the clear, stamped with the clear's seq, is)
  flushall      -> flush message (receiver resolves to a concrete
                   key list against its own LWW floors)

A ``JournalGap`` (our journal compacted past the peer's cursor — site
restart, segment GC) triggers **snapshot repair**: record the journal
head first, ship every local key's full state as repair merges stamped
with its last-write stamp plus the floor map as repair tombstones, then
resume tailing from the recorded head.

Fault injection: the ``geo_link`` seam fires at the top of every tick
with ``target=<peer site id>``; an injected fault models a cross-site
partition — the tick aborts, the cursor holds, and the backlog ships
after heal (anti-entropy semantics fall out of the cursor never
advancing past unshipped records).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

from redisson_tpu.fault import inject as fault_inject
from redisson_tpu.fault.taxonomy import Fault
from redisson_tpu.geo.applier import (
    DESTRUCTIVE_KINDS, SEMILATTICE_KINDS, SHIP_KINDS)
from redisson_tpu.ingest import delta as delta_mod
from redisson_tpu.persist.journal import JournalGap, JournalTail

GUARDED_BY = {
    "SiteLink.tail":
        "thread:link — only the link thread polls/rewinds the tail; "
        "close() joins before reading it",
    "SiteLink.stats":
        "thread:link writes; info()/lag() readers tolerate a one-tick-"
        "stale counter snapshot (monitoring, not control flow)",
    "SiteLink._last_progress_s":
        "thread:link writes; lag() readers see a monotonic float whose "
        "staleness only inflates the reported lag by one tick",
}


class SiteLink:
    """Ships this site's journal suffix to one peer's applier."""

    def __init__(self, manager, peer_manager):
        self._m = manager
        self.peer = peer_manager
        self._cfg = manager.cfg
        self._stop = threading.Event()
        # Start from what the peer already has from us (its vv entry for
        # this site) — a rejoining peer resumes mid-stream, a fresh peer
        # replays our whole surviving journal.
        start = peer_manager.applier.vv.get(manager.site_id, 0)
        self.tail = JournalTail(manager.journal_path, from_seq=start)
        self.stats: Dict[str, int] = {
            "shipped_msgs": 0, "shipped_records": 0, "link_bytes": 0,
            "raw_bytes": 0, "partitions": 0, "gaps": 0, "errors": 0,
            "repairs": 0,
        }
        self._last_progress_s = manager.monotonic()
        self._thread = threading.Thread(
            target=self._run,
            name=f"redisson-tpu-geo-{manager.site_id}->{peer_manager.site_id}",
            daemon=True)

    def start(self) -> None:
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)

    # -- lag (INFO replication / staleness) ---------------------------------

    def lag(self) -> Dict[str, float]:
        behind = self._m.journal_last_seq() - self.peer.applier.vv.get(
            self._m.site_id, 0)
        lag_s = 0.0
        if behind > 0:
            lag_s = max(0.0, self._m.monotonic() - self._last_progress_s)
        return {
            "records": max(0, behind),
            "seconds": lag_s,
            "link_bytes": self.stats["link_bytes"],
            "raw_bytes": self.stats["raw_bytes"],
        }

    # -- shipping loop ------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self._cfg.poll_interval_s):
            try:
                self._tick()
            except Fault:
                self.stats["partitions"] += 1
            except JournalGap:
                self.stats["gaps"] += 1
                try:
                    self._snapshot_repair()
                except Exception:
                    self.stats["errors"] += 1
            except Exception:
                # Peer mid-shutdown / transient executor refusal: the
                # cursor held, so the records re-ship next tick.
                self.stats["errors"] += 1

    def _tick(self) -> None:
        fault_inject.fire("geo_link", target=self.peer.site_id)
        # Anti-entropy rewind: if the peer's vv for us regressed below our
        # cursor (stale sidecar after its restart), back up and re-ship;
        # the applier dedups anything it already holds.
        want = self.peer.applier.vv.get(self._m.site_id, 0) + 1
        if want < self.tail.next_seq:
            self.tail = JournalTail(self._m.journal_path, from_seq=want - 1)
        records = self.tail.poll(max_records=self._cfg.batch_records)
        watermark = self.tail.next_seq - 1
        known = self.peer.applier.vv.get(self._m.site_id, 0)
        if not records and watermark <= known:
            return
        msgs = self._build_msgs(records)
        self.peer.deliver(msgs, self._m.site_id, watermark)
        self.stats["shipped_msgs"] += len(msgs)
        self.stats["shipped_records"] += len(records)
        self._last_progress_s = self._m.monotonic()

    # -- record batch -> message batch ---------------------------------------

    def _build_msgs(self, records) -> List[dict]:
        msgs: List[dict] = []
        # Insertion-ordered fold groups: target -> (inner_kind, payloads,
        # last_seq). Cut at destructive boundaries so per-key order holds.
        pending: Dict[str, list] = {}

        def flush(target: str) -> None:
            group = pending.pop(target, None)
            if group is None:
                return
            msg = self._fold_msg(target, group[0], group[1], group[2])
            if msg is not None:
                msgs.append(msg)

        def flush_all() -> None:
            for t in list(pending):
                flush(t)

        for r in records:
            if r.kind not in SHIP_KINDS:
                continue
            self.stats["raw_bytes"] += self._raw_bytes(r)
            stamp = (r.seq, self._m.site_id)
            if r.kind in SEMILATTICE_KINDS:
                group = pending.get(r.target)
                if group is not None and group[0] != r.kind:
                    flush(r.target)
                    group = None
                if group is None:
                    pending[r.target] = group = [r.kind, [], r.seq]
                group[1].append(r.payload)
                group[2] = r.seq
                continue
            assert r.kind in DESTRUCTIVE_KINDS
            if r.kind == "flushall":
                flush_all()
                msgs.append({"kind": "flush", "target": "", "stamp": stamp})
            elif r.kind == "delete":
                flush(r.target)
                msgs.append(
                    {"kind": "delete", "target": r.target, "stamp": stamp})
            elif r.kind == "rename":
                new = r.payload.get("newkey") if isinstance(
                    r.payload, dict) else None
                flush(r.target)
                if new:
                    flush(new)
                msgs.append(
                    {"kind": "delete", "target": r.target, "stamp": stamp})
                if new:
                    st = self._m.export_state(new)
                    if st is not None:
                        st.update({"kind": "replace", "target": new,
                                   "stamp": stamp})
                        self.stats["link_bytes"] += st.pop("_link_bytes", 0)
                        msgs.append(st)
            else:  # bitset_clear: ship the post-clear plane, LWW-stamped
                flush(r.target)
                st = self._m.export_state(r.target)
                if st is not None:
                    st.update({"kind": "replace", "target": r.target,
                               "stamp": stamp})
                    self.stats["link_bytes"] += st.pop("_link_bytes", 0)
                    msgs.append(st)
        flush_all()
        return msgs

    @staticmethod
    def _raw_bytes(record) -> int:
        if record.kind in SEMILATTICE_KINDS and isinstance(
                record.payload, dict):
            try:
                return delta_mod.payload_raw_bytes(record.kind,
                                                   record.payload)
            except Exception:
                return 0
        return 0

    def _fold_msg(self, target: str, kind: str, payloads: List[dict],
                  last_seq: int) -> Optional[dict]:
        """Fold one run of same-kind writes to a single merge message.
        Falls back to a full-state export merge when a payload form can't
        be host-folded (device-resident batches, native library absent) —
        the full plane is a coarser join of the same semilattice, always
        safe, just more bytes."""
        stamp = (last_seq, self._m.site_id)
        nkeys = 0
        for p in payloads:
            try:
                nkeys += delta_mod.payload_nkeys(kind, p)
            except Exception:
                pass
        if all(delta_mod.foldable(kind, p) for p in payloads):
            plane = meta = None
            cells = 0
            packed = True
            if kind == "hll_add":
                plane = delta_mod.fold_hll(payloads, self._m.seed)
                cells, packed, meta = delta_mod.HLL_M, False, None
            elif kind == "bloom_add":
                bm = self._m.bloom_meta(target)
                if bm is not None:
                    m, k = bm["size"], bm["hash_iterations"]
                    plane = delta_mod.fold_bloom(
                        payloads, k, m, self._m.seed)
                    cells, meta = m, bm
            else:  # bitset_set
                mx = max((int(p.get("max_idx", -1)) for p in payloads),
                         default=-1)
                if mx >= 0:
                    plane = delta_mod.fold_bitset(payloads, mx + 1)
                    cells, meta = mx + 1, {"max_idx": mx}
            if plane is not None:
                msg = self._plane_msg(kind, target, plane, cells, packed,
                                      meta, nkeys)
                msg.update({"kind": "merge", "target": target,
                            "stamp": stamp})
                return msg
        # Full-state fallback: export the key's current plane and ship it
        # as a join. A missing key means a later destructive record (also
        # in this journal) already removed it — nothing to ship.
        st = self._m.export_state(target)
        if st is None:
            return None
        st.update({"kind": "merge", "target": target, "stamp": stamp,
                   "nkeys": nkeys})
        self.stats["link_bytes"] += st.pop("_link_bytes", 0)
        return st

    def _plane_msg(self, kind: str, target: str, plane: np.ndarray,
                   cells: int, packed: bool, meta: Optional[dict],
                   nkeys: int) -> dict:
        dp = delta_mod.encode(kind, target, plane, cells=cells,
                              packed=packed, nkeys=nkeys,
                              raw_bytes=0)
        self.stats["link_bytes"] += dp.link_bytes
        msg = {"inner": kind, "cells": dp.cells,
               "plane_bytes": dp.plane_bytes, "nkeys": nkeys}
        if meta:
            msg["meta"] = dict(meta)
        if dp.sparse:
            msg["idx"] = dp.idx
            msg["val"] = dp.val
        else:
            msg["plane"] = dp.dense
        return msg

    # -- gap repair ----------------------------------------------------------

    def _snapshot_repair(self) -> None:
        """The peer's cursor fell off our journal's surviving history.
        Re-seed it from live state: record the journal head FIRST (writes
        racing the export get re-shipped by the tail later — merges are
        idempotent), ship full-state repair merges stamped with each
        key's last-write stamp, the floor map as repair tombstones, and
        the flush floor; then resume tailing from the recorded head."""
        self.stats["repairs"] += 1
        target_seq = self._m.journal_last_seq()
        applier = self._m.applier
        msgs: List[dict] = []
        for key in sorted(self._m.local_keys()):
            st = self._m.export_state(key)
            if st is None:
                continue
            stamp = applier.lw.get(key) or (target_seq, self._m.site_id)
            st.update({"kind": "merge", "target": key, "stamp": stamp,
                       "repair": True})
            self.stats["link_bytes"] += st.pop("_link_bytes", 0)
            msgs.append(st)
        for key, stamp in list(applier.floor.items()):
            msgs.append({"kind": "delete", "target": key, "stamp": stamp,
                         "repair": True})
        if applier.flush_floor[0] > 0:
            msgs.append({"kind": "flush", "target": "",
                         "stamp": applier.flush_floor, "repair": True})
        self.peer.deliver(msgs, self._m.site_id, target_seq)
        self.tail = JournalTail(self._m.journal_path, from_seq=target_seq)
        self.stats["shipped_msgs"] += len(msgs)
        self._last_progress_s = self._m.monotonic()
