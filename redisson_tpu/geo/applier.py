"""Remote-mutation applier: LWW arbitration in front of the delta plane.

Every remote message carries a **stamp** ``(origin_seq, site_id)`` — the
origin's journal sequence number plus its site id. Stamps are totally
ordered (tuple comparison; seqs grow monotonically per site, site ids
break ties), and every site arbitrates with the same order, so any two
sites that have seen the same message set reach the same state — the
convergence contract (geo/__init__.py) reduces to these rules:

  merge    applies iff stamp > floor[key] and stamp > flush_floor
           (semilattice join — commutes with everything it doesn't lose
           to); advances lw[key].
  delete   applies iff stamp > lw[key] — else it LOST to a newer write
           and is *suppressed*, and this site re-ships the key's full
           state as a repair merge so the deleting site resurrects it.
           Applying advances floor[key].
  replace  (full-state LWW overwrite: bitset clears, rename
           destinations, snapshot repair) applies iff stamp > floor[key]
           and stamp >= lw[key]; sets floor = lw = stamp. A replace that
           lost to a newer merge DEGRADES to a merge — its cells still
           join in, the newer write survives.
  flush    raises flush_floor and wipes exactly the local keys whose
           lw < stamp — resolved to a concrete key list under a
           dispatcher barrier so journal replay is deterministic. Keys
           whose lw >= stamp SURVIVE, and are re-shipped to every peer
           as repair merges (the flushing site wiped them locally, so
           the same add-wins resolution as the DEL race resurrects them
           there — without it the mesh would diverge).

``lw[key]`` is the newest applied merge/replace stamp, ``floor[key]``
the newest applied destructive stamp; both are fed by remote applies
AND by the local journal listener (``note_local``), so local writes
take part in the same arbitration.

``vv[origin]`` — the version vector — is the highest origin journal seq
this site has delivered. Senders attach a *watermark* (last origin seq
scanned, shipped or filtered) to every batch so filtered-out records
don't leave vv holes; anti-entropy rewinds a link's cursor to
``peer.vv[self] + 1`` after a restart or drop.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Dict, List, Optional, Tuple

from redisson_tpu import contractwitness
from redisson_tpu.concurrency import make_lock

Stamp = Tuple[int, str]

#: Less than every real stamp (journal seqs start at 1).
NEG_STAMP: Stamp = (0, "")

#: Origin-op kinds a SiteLink ships. Everything else — reads, structure
#: ops, geo_* records journaled by remote applies (the echo-loop cut) —
#: stays site-local.
SEMILATTICE_KINDS = frozenset({"hll_add", "bloom_add", "bitset_set"})
DESTRUCTIVE_KINDS = frozenset({"delete", "rename", "flushall", "bitset_clear"})
SHIP_KINDS = SEMILATTICE_KINDS | DESTRUCTIVE_KINDS

GUARDED_BY = {
    "GeoApplier.vv": "_lock",
    "GeoApplier.lw": "_lock",
    "GeoApplier.floor": "_lock",
    "GeoApplier.flush_floor": "_lock",
    "GeoApplier.applied": "_lock",
    "GeoApplier.suppressed": "_lock",
    "GeoApplier.resurrections": "_lock",
    "GeoApplier._pending": "_lock",
}


def stamp_of(v) -> Stamp:
    """Normalize a stamp from a message / sidecar (lists after JSON or
    codec round-trips) back to a comparable tuple."""
    return (int(v[0]), str(v[1]))


class GeoApplier:
    """One per site. ``apply()`` is called by peer link threads (one
    thread per origin, so per-origin delivery is FIFO); ``note_local``
    by the journal's append path on the dispatcher thread. Decisions are
    made under ``_lock``; dispatches into the engine happen OUTSIDE it
    (the dispatcher thread calls back into ``note_local`` when the geo
    record journals, and holding our lock across that re-entry would
    order ``applier -> executor -> applier``)."""

    def __init__(self, manager):
        self._m = manager
        self._lock = make_lock("geo.GeoApplier._lock")
        self.vv: Dict[str, int] = {}
        self.lw: Dict[str, Stamp] = {}
        self.floor: Dict[str, Stamp] = {}
        self.flush_floor: Stamp = NEG_STAMP
        self.applied = 0
        self.suppressed = 0
        self.resurrections = 0
        self._pending: collections.deque = collections.deque()

    # -- local bookkeeping (journal listener, dispatcher thread) ------------

    def note_local(self, records) -> None:
        """Fold freshly journaled LOCAL records into the LWW maps so local
        writes arbitrate against remote ones. geo_* records only advance
        vv[self] — their LWW effect was recorded at apply() time."""
        site = self._m.site_id
        with self._lock:
            for r in records:
                self.vv[site] = r.seq
                if r.kind.startswith("geo_"):
                    continue
                stamp = (r.seq, site)
                if r.kind == "flushall":
                    if stamp > self.flush_floor:
                        self.flush_floor = stamp
                elif r.kind == "delete":
                    if stamp > self.floor.get(r.target, NEG_STAMP):
                        self.floor[r.target] = stamp
                elif r.kind == "rename":
                    self.floor[r.target] = stamp
                    new = r.payload.get("newkey") if isinstance(
                        r.payload, dict) else None
                    if new:
                        self.floor[new] = stamp
                        self.lw[new] = stamp
                elif r.kind == "bitset_clear":
                    self.floor[r.target] = stamp
                    self.lw[r.target] = stamp
                elif r.target:
                    if stamp > self.lw.get(r.target, NEG_STAMP):
                        self.lw[r.target] = stamp

    def rebuild(self, records) -> None:
        """Restart path: re-derive LWW state from journal records newer
        than the persisted sidecar (the sidecar flushes on the AE cadence,
        so it can trail the journal by one interval). geo_* payloads carry
        their origin stamps, which also claws back vv entries."""
        for r in records:
            payload = r.payload if isinstance(r.payload, dict) else {}
            stamp = payload.get("stamp")
            if r.kind.startswith("geo_") and stamp is not None:
                stamp = stamp_of(stamp)
                with self._lock:
                    self.vv[self._m.site_id] = r.seq
                    if stamp[1]:
                        self.vv[stamp[1]] = max(
                            self.vv.get(stamp[1], 0), stamp[0])
                    if r.kind == "geo_merge":
                        if stamp > self.lw.get(r.target, NEG_STAMP):
                            self.lw[r.target] = stamp
                    elif r.kind == "geo_replace":
                        self.floor[r.target] = stamp
                        self.lw[r.target] = stamp
                    elif r.kind == "geo_delete":
                        if stamp > self.floor.get(r.target, NEG_STAMP):
                            self.floor[r.target] = stamp
                    elif r.kind == "geo_flush":
                        if stamp > self.flush_floor:
                            self.flush_floor = stamp
            else:
                self.note_local([r])

    # -- remote delivery (peer link threads) --------------------------------

    def apply(self, msgs: List[dict], origin: str, watermark: int) -> int:
        """Deliver one shipped batch from ``origin``. Returns the number
        of messages that passed arbitration and were dispatched."""
        dispatched = 0
        for msg in msgs:
            if self._apply_one(msg, origin):
                dispatched += 1
        with self._lock:
            if watermark > self.vv.get(origin, 0):
                self.vv[origin] = watermark
        return dispatched

    def _apply_one(self, msg: dict, origin: str) -> bool:
        stamp = stamp_of(msg["stamp"])
        kind = msg["kind"]
        repair = bool(msg.get("repair"))
        resurrect: Optional[str] = None
        action: Optional[str] = None
        with self._lock:
            # Dedup redelivery (anti-entropy rewinds): a non-repair stamp
            # from the origin's own journal at or below vv is already in.
            if (not repair and stamp[1] == origin
                    and stamp[0] <= self.vv.get(origin, 0)):
                return False
            if kind == "merge":
                key = msg["target"]
                if (stamp > self.floor.get(key, NEG_STAMP)
                        and stamp > self.flush_floor):
                    action = "geo_merge"
                    if stamp > self.lw.get(key, NEG_STAMP):
                        self.lw[key] = stamp
                else:
                    self.suppressed += 1
            elif kind == "delete":
                key = msg["target"]
                if stamp <= self.flush_floor:
                    self.suppressed += 1
                elif stamp > self.lw.get(key, NEG_STAMP):
                    action = "geo_delete"
                    if stamp > self.floor.get(key, NEG_STAMP):
                        self.floor[key] = stamp
                else:
                    # Lost to a newer write: suppress, then resurrect the
                    # key at the deleting site by re-shipping full state.
                    self.suppressed += 1
                    self.resurrections += 1
                    resurrect = key
            elif kind == "replace":
                key = msg["target"]
                if (stamp <= self.floor.get(key, NEG_STAMP)
                        or stamp <= self.flush_floor):
                    self.suppressed += 1
                elif stamp >= self.lw.get(key, NEG_STAMP):
                    action = "geo_replace"
                    self.floor[key] = stamp
                    self.lw[key] = stamp
                else:
                    # Lost LWW to a newer merge: degrade to a join so its
                    # cells survive alongside the newer write.
                    action = "geo_merge"
            elif kind == "flush":
                if stamp > self.flush_floor:
                    self.flush_floor = stamp
                    action = "geo_flush"
                else:
                    self.suppressed += 1
        if action == "geo_flush":
            self._dispatch_flush(stamp)
            return True
        if action is not None:
            payload = {k: v for k, v in msg.items()
                       if k not in ("kind", "target", "repair")}
            payload["stamp"] = stamp
            with contractwitness.surface("geo"):
                fut = self._m.execute_async(
                    msg["target"], action, payload,
                    nkeys=int(msg.get("nkeys", 0) or 0))
            self._track(fut)
        if resurrect is not None:
            self._m.broadcast_repair(resurrect)
        return action is not None

    def _dispatch_flush(self, stamp: Stamp) -> None:
        """Resolve the flush to a concrete key list (keys whose newest
        write predates the flush stamp) under a dispatcher barrier —
        the barrier is a consistency cut over every in-flight write, and
        journaling the explicit list keeps crash replay deterministic.
        Survivors (lw >= stamp: they beat the flush on the LWW order)
        are re-shipped as repair merges, because the flushing site wiped
        them locally — same add-wins resolution as a lost DEL."""
        keys = self._m.local_keys()
        with self._lock:
            doomed = [k for k in keys
                      if self.lw.get(k, NEG_STAMP) < stamp]
        with contractwitness.surface("geo"):
            fut = self._m.execute_async(
                "", "geo_flush", {"keys": doomed, "stamp": stamp})
        self._track(fut)
        survivors = keys.difference(doomed)
        shipped = sum(1 for k in sorted(survivors)
                      if self._m.broadcast_repair(k))
        if shipped:
            with self._lock:
                self.resurrections += shipped

    # -- settle support -----------------------------------------------------

    def _track(self, fut) -> None:
        with self._lock:
            self.applied += 1
            self._pending.append(fut)
            while len(self._pending) > 4096 and self._pending[0].done():
                self._pending.popleft()

    def pending(self) -> int:
        """Dispatched-but-unretired remote applies (converge() polls it)."""
        with self._lock:
            while self._pending and self._pending[0].done():
                self._pending.popleft()
            return len(self._pending)

    # -- sidecar snapshot ---------------------------------------------------

    def state(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "vv": dict(self.vv),
                "lw": {k: list(v) for k, v in self.lw.items()},
                "floor": {k: list(v) for k, v in self.floor.items()},
                "flush_floor": list(self.flush_floor),
            }

    def load_state(self, state: Dict[str, Any]) -> None:
        with self._lock:
            self.vv.update({k: int(v) for k, v in
                            (state.get("vv") or {}).items()})
            self.lw.update({k: stamp_of(v) for k, v in
                            (state.get("lw") or {}).items()})
            self.floor.update({k: stamp_of(v) for k, v in
                               (state.get("floor") or {}).items()})
            ff = state.get("flush_floor")
            if ff is not None:
                self.flush_floor = max(self.flush_floor, stamp_of(ff))
