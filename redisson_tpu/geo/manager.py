"""GeoManager: one site's geo-replication root.

Owns the site identity, the LWW applier, one SiteLink per connected
peer, and the anti-entropy loop. Wired by the client after the replica
fleet (client.py) when ``Config.use_geo()`` is set; peering happens at
runtime — ``connect_sites([c1, c2, ...])`` meshes a set of clients
all-pairs, or ``client.geo.connect(peer_manager)`` adds one direction.

Durability sidecar: the applier's LWW state (vv / lw / floor /
flush_floor) persists as ``geo_state.json`` next to the journal,
atomically (write + os.replace) on the anti-entropy cadence and at
close. After a restart the sidecar seeds the applier and the journal
suffix past the sidecar's seq is re-folded (``GeoApplier.rebuild``), so
arbitration state never trails the replayed engine state.

Remote applies dispatch through the RAW executor waist
(``client._executor``) — geo traffic is internal maintenance like lock
watchdog renewals and durability flushes: it must not be shed or
deadline-expired by the serve layer, and it must bypass replica read
routing (it is all writes).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Set

import numpy as np

from redisson_tpu.concurrency import make_lock
from redisson_tpu.geo.applier import GeoApplier, NEG_STAMP
from redisson_tpu.geo.link import SiteLink
from redisson_tpu.ingest import delta as delta_mod
from redisson_tpu.persist.journal import iter_records
from redisson_tpu.store import ObjectType, WrongTypeError

SIDECAR_NAME = "geo_state.json"

GUARDED_BY = {
    "GeoManager.links": "_links_lock",
    "GeoManager._closed": "thread:wiring — set once by close(); the AE "
        "thread observes it via the Event, links via join()",
}


class GeoManager:
    """Per-site replication root (one per client with ``Config.geo``)."""

    def __init__(self, client, cfg):
        self.client = client
        self.cfg = cfg
        journal = client._executor.journal
        if journal is None:
            raise ValueError(
                "Config.geo requires Config.persist with a dir — the "
                "persist journal IS the geo replication transport")
        self._journal = journal
        self.journal_path = journal.path
        self.site_id = cfg.site_id or os.path.basename(
            os.path.dirname(os.path.abspath(journal.path))) or "site"
        self.applier = GeoApplier(self)
        self.links: Dict[str, SiteLink] = {}
        self._links_lock = make_lock("geo.GeoManager._links_lock")
        self._stop = threading.Event()
        self._ae_thread = threading.Thread(
            target=self._ae_loop,
            name=f"redisson-tpu-geo-ae-{self.site_id}", daemon=True)
        self._closed = False
        self._load_sidecar()
        journal.add_listener(self._on_records)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self._ae_thread.start()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        with self._links_lock:
            links = list(self.links.values())
            self.links.clear()
        for link in links:
            link.close()
        if self._ae_thread.is_alive():
            self._ae_thread.join(timeout=5.0)
        try:
            self._journal.remove_listener(self._on_records)
        except Exception:
            pass
        self._persist_sidecar()

    # -- peering ------------------------------------------------------------

    def connect(self, peer: "GeoManager") -> None:
        """Start shipping this site's journal to ``peer`` (one
        direction; call on both managers — or use connect_sites — for
        active-active)."""
        if peer is self or peer.site_id == self.site_id:
            raise ValueError(
                f"peer site id {peer.site_id!r} collides with this site")
        with self._links_lock:
            old = self.links.get(peer.site_id)
            if old is not None and old.peer is peer:
                return
            link = SiteLink(self, peer)
            self.links[peer.site_id] = link
        if old is not None:
            # Same site id, new manager instance: the peer restarted.
            # Retire the link to its dead predecessor.
            old.close()
        link.start()

    def deliver(self, msgs: List[dict], origin: str, watermark: int) -> int:
        """Entry point peer links call into (the receive half)."""
        return self.applier.apply(msgs, origin, watermark)

    # -- executor facade (applier + links dispatch through these) -----------

    def execute_async(self, target: str, kind: str, payload,
                      nkeys: int = 0):
        return self.client._executor.execute_async(
            target, kind, payload, nkeys=nkeys)

    def monotonic(self) -> float:
        return time.monotonic()

    def journal_last_seq(self) -> int:
        return self._journal.last_seq

    @property
    def seed(self) -> int:
        return int(getattr(self._sketch(), "seed", 0))

    def _sketch(self):
        return getattr(self.client._routing, "sketch", None)

    def _on_records(self, records) -> None:
        self.applier.note_local(records)

    # -- state reads (ship-time exports, flush key resolution) ---------------

    def local_keys(self) -> Set[str]:
        """Every live sketch-tier key, read under a dispatcher barrier (a
        consistency cut against in-flight writes)."""
        sketch = self._sketch()
        store = self.client._store

        def cut():
            keys = set(getattr(sketch, "_rows", ()) or ())
            with store._lock:
                keys.update(store._objects)
            return keys

        # graftlint: allow-g006(barrier read on a link/applier thread — blocking here is the consistency cut; the dispatcher never calls local_keys)
        return self.client._executor.execute_barrier(cut).result()

    def bloom_meta(self, target: str) -> Optional[dict]:
        exported = self._export(target)
        if exported is None or exported[0] != ObjectType.BLOOM:
            return None
        return dict(exported[2])

    def _export(self, key: str):
        """(otype, cells uint8[n], meta) for a live key, else None."""
        ex = self.client._executor
        try:
            # graftlint: allow-g006(ship-time state read on the link thread; the export is dispatcher-serialized with the donating kernels)
            hll = ex.execute_sync(key, "hll_export", None)
        except WrongTypeError:
            hll = None  # store-typed key: fall through to bits_export
        if hll is not None:
            return (ObjectType.HLL, hll[0], {})
        # graftlint: allow-g006(same ship-time read, bitset/bloom half)
        bits = ex.execute_sync(key, "bits_export", None)
        if bits is None:
            return None
        return (bits[0], bits[1], bits[2])

    def export_state(self, key: str) -> Optional[dict]:
        """Full-state message body for ``key`` (merge/replace/repair
        shipping): the key's whole plane, sparse-encoded when that wins.
        ``_link_bytes`` rides along for the sender's byte accounting."""
        exported = self._export(key)
        if exported is None:
            return None
        otype, cells, meta = exported
        if otype == ObjectType.HLL:
            inner, plane = "hll_add", np.asarray(cells, np.uint8)
            n, packed, meta = delta_mod.HLL_M, False, None
        else:
            host = np.asarray(cells, np.uint8)
            plane = np.packbits(host)
            n, packed = int(host.shape[0]), True
            if otype == ObjectType.BLOOM:
                inner = "bloom_add"
                meta = {k: meta[k] for k in
                        ("size", "hash_iterations", "expected_insertions",
                         "false_probability", "blocked") if k in meta}
            else:
                inner = "bitset_set"
                meta = {"max_idx": n - 1,
                        "extent_bits": meta.get("extent_bits", n)}
        dp = delta_mod.encode(inner, key, plane, cells=n, packed=packed,
                              nkeys=0, raw_bytes=0)
        msg: Dict[str, Any] = {
            "inner": inner, "cells": dp.cells,
            "plane_bytes": dp.plane_bytes, "_link_bytes": dp.link_bytes,
        }
        if meta:
            msg["meta"] = meta
        if dp.sparse:
            msg["idx"], msg["val"] = dp.idx, dp.val
        else:
            msg["plane"] = dp.dense
        return msg

    def broadcast_repair(self, key: str) -> bool:
        """A remote delete/flush lost to this site's newer write:
        re-ship the key's full state to every peer (stamped with our
        last-write stamp) so the wiping site resurrects it — the
        documented add-wins resolution. Returns whether anything
        shipped (the key may have been removed in the meantime)."""
        st = self.export_state(key)
        if st is None:
            return False
        stamp = self.applier.lw.get(key)
        if stamp is None or stamp == NEG_STAMP:
            stamp = (self._journal.last_seq, self.site_id)
        st.pop("_link_bytes", None)
        st.update({"kind": "merge", "target": key, "stamp": stamp,
                   "repair": True})
        with self._links_lock:
            links = list(self.links.values())
        for link in links:
            try:
                link.peer.deliver([st], self.site_id, 0)
            except Exception:
                link.stats["errors"] += 1
        return True

    # -- anti-entropy loop ---------------------------------------------------

    def _ae_loop(self) -> None:
        """Cursor repair lives in the link ticks (rewind to peer vv);
        this loop owns the durable half: flushing the LWW sidecar so a
        restarted site resumes with arbitration state instead of
        re-deciding from nothing."""
        while not self._stop.wait(self.cfg.anti_entropy_interval_s):
            try:
                self._persist_sidecar()
            except Exception:
                pass

    def _persist_sidecar(self) -> None:
        state = self.applier.state()
        state["seq"] = self._journal.last_seq
        state["site_id"] = self.site_id
        path = os.path.join(self.journal_path, SIDECAR_NAME)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(state, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _load_sidecar(self) -> None:
        path = os.path.join(self.journal_path, SIDECAR_NAME)
        seq = 0
        try:
            with open(path) as f:
                state = json.load(f)
            self.applier.load_state(state)
            seq = int(state.get("seq", 0))
        except FileNotFoundError:
            pass
        except Exception:
            seq = 0  # corrupt sidecar: rebuild everything from the journal
        tail = self._journal.last_seq
        if tail > seq:
            self.applier.rebuild(
                r for r in iter_records(self.journal_path)
                if r.seq > seq)

    # -- introspection (INFO replication / metrics) ---------------------------

    def info(self) -> Dict[str, Any]:
        with self._links_lock:
            links = dict(self.links)
        peers: Dict[str, Any] = {}
        for pid, link in links.items():
            lag = link.lag()
            peers[pid] = {
                "acked_seq": link.peer.applier.vv.get(self.site_id, 0),
                "lag_records": lag["records"],
                "lag_seconds": round(lag["seconds"], 3),
                "link_bytes": lag["link_bytes"],
                "raw_bytes": lag["raw_bytes"],
                "partitions": link.stats["partitions"],
                "repairs": link.stats["repairs"],
            }
        return {
            "role": "active",
            "site_id": self.site_id,
            "local_seq": self._journal.last_seq,
            "version_vector": dict(self.applier.vv),
            "applied": self.applier.applied,
            "suppressed": self.applier.suppressed,
            "resurrections": self.applier.resurrections,
            "peers": peers,
        }

    def staleness(self) -> Dict[str, float]:
        """Per-peer replication staleness in seconds, as exposed to
        reads: how far behind each peer's acknowledged cursor is."""
        with self._links_lock:
            links = dict(self.links)
        return {pid: link.lag()["seconds"] for pid, link in links.items()}


# ---------------------------------------------------------------------------
# Module helpers (tests / benchmarks / embedders)
# ---------------------------------------------------------------------------


def connect_sites(clients) -> None:
    """Mesh a set of geo-enabled clients all-pairs (active-active)."""
    managers = [c.geo for c in clients]
    for m in managers:
        if m is None:
            raise ValueError("every client needs Config.use_geo()")
    for a in managers:
        for b in managers:
            if a is not b:
                a.connect(b)


def converge(clients, timeout_s: float = 30.0) -> bool:
    """Block until every site has delivered every other site's journal
    head and retired every dispatched remote apply — the all-quiet
    fixpoint tests and the smoke gate assert digests at. Returns False
    on timeout."""
    managers = [c.geo for c in clients]
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        settled = True
        for a in managers:
            head = a.journal_last_seq()
            for b in managers:
                if a is b:
                    continue
                if b.applier.vv.get(a.site_id, 0) < head:
                    settled = False
                    break
                if b.applier.pending():
                    settled = False
                    break
            if not settled:
                break
        if settled:
            return True
        time.sleep(0.005)
    return False
