"""Higher services tier (the reference's L5b): RPC + cache manager."""

from redisson_tpu.services.remote import (RemoteInvocationOptions,
                                          RemoteServiceAckTimeoutError,
                                          RemoteServiceTimeoutError,
                                          RRemoteService)
from redisson_tpu.services.cache_manager import CacheConfig, CacheManager

__all__ = [
    "RRemoteService", "RemoteInvocationOptions",
    "RemoteServiceTimeoutError", "RemoteServiceAckTimeoutError",
    "CacheConfig", "CacheManager",
]
