"""Higher services tier (the reference's L5b): RPC + cache manager."""

from redisson_tpu.services.remote import (RemoteInvocationOptions,
                                          RemoteServiceAckTimeoutError,
                                          RemoteServiceError,
                                          RemoteServiceTimeoutError,
                                          RRemoteService)
from redisson_tpu.services.cache_manager import CacheConfig, CacheManager

__all__ = [
    "RRemoteService", "RemoteInvocationOptions", "RemoteServiceError",
    "RemoteServiceTimeoutError", "RemoteServiceAckTimeoutError",
    "CacheConfig", "CacheManager",
]
