"""RPC over blocking queues — the RRemoteService analogue.

Reference design (RedissonRemoteService.java:96-226 + remote/, SURVEY.md §2
L4/L5): the service side runs N workers blocking-taking RemoteServiceRequest
payloads from a request queue named `{service}:{interface}` (hashtag ⇒ one
slot), optionally acks within the ack timeout, invokes the method
reflectively, and pushes a RemoteServiceResponse onto a per-request response
queue. The client side is a dynamic proxy that enqueues the request and
blocking-polls its response queue. Modes (RemoteInvocationOptions): ack or
no-ack, result-aware or fire-and-forget.

Here the queues are our structure-tier blocking queues, the "reflective
invoke" is getattr, and the dynamic proxy is __getattr__; worker pools are
daemon threads. Async invocation (the @RRemoteAsync analogue) returns
concurrent futures from `get_async()`.
"""

from __future__ import annotations

import threading
import traceback
import uuid
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Optional


@dataclass
class RemoteInvocationOptions:
    """Invocation mode knobs (reference remote/RemoteInvocationOptions)."""

    ack_timeout_s: Optional[float] = 1.0      # None = no ack expected
    execution_timeout_s: Optional[float] = 30.0  # None = fire-and-forget

    @classmethod
    def defaults(cls) -> "RemoteInvocationOptions":
        return cls()

    def no_ack(self) -> "RemoteInvocationOptions":
        return RemoteInvocationOptions(None, self.execution_timeout_s)

    def no_result(self) -> "RemoteInvocationOptions":
        return RemoteInvocationOptions(self.ack_timeout_s, None)

    def with_timeouts(self, ack_s: Optional[float],
                      exec_s: Optional[float]) -> "RemoteInvocationOptions":
        return RemoteInvocationOptions(ack_s, exec_s)

    # -- reference accessor/builder surface ---------------------------------

    def expect_ack_within(self, ack_s: float) -> "RemoteInvocationOptions":
        return RemoteInvocationOptions(ack_s, self.execution_timeout_s)

    def expect_result_within(self, exec_s: float) -> "RemoteInvocationOptions":
        return RemoteInvocationOptions(self.ack_timeout_s, exec_s)

    def is_ack_expected(self) -> bool:
        return self.ack_timeout_s is not None

    def is_result_expected(self) -> bool:
        return self.execution_timeout_s is not None

    def get_ack_timeout_in_millis(self) -> Optional[int]:
        return (None if self.ack_timeout_s is None
                else int(self.ack_timeout_s * 1000))

    def get_execution_timeout_in_millis(self) -> Optional[int]:
        return (None if self.execution_timeout_s is None
                else int(self.execution_timeout_s * 1000))


class RemoteServiceTimeoutError(TimeoutError):
    """No response inside execution_timeout_s."""


class RemoteServiceAckTimeoutError(TimeoutError):
    """No worker acked inside ack_timeout_s (no service instance alive)."""


class RemoteServiceError(RuntimeError):
    """The remote method raised; message carries the remote traceback."""


def _req_queue_name(service: str, iface: str) -> str:
    # hashtag for slot colocation, mirroring `name:{iface}` in the reference
    return f"{service}:{{{iface}}}"


class _Invoker:
    """Client-side dynamic proxy: attribute access -> remote call."""

    def __init__(self, service: "RRemoteService", iface: str,
                 options: RemoteInvocationOptions, as_async: bool):
        self._service = service
        self._iface = iface
        self._options = options
        self._async = as_async

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)

        def call(*args, **kwargs):
            if self._async:
                return self._service._pool.submit(
                    self._service._invoke, self._iface, method, args, kwargs,
                    self._options)
            return self._service._invoke(self._iface, method, args, kwargs,
                                         self._options)

        call.__name__ = method
        return call


class RRemoteService:
    """Register service implementations and obtain client proxies.

    One instance wraps one RedissonTPU client; server and clients may live
    in different processes when the structure tier is shared (or the same
    process in tests — same as the reference's in-JVM usage).
    """

    def __init__(self, client, name: str = "remote_service"):
        self._client = client
        self._name = name
        self._workers: list = []
        self._stop = threading.Event()
        self._pool = ThreadPoolExecutor(max_workers=8,
                                        thread_name_prefix="rtpu-rs-client")

    # -- service side -------------------------------------------------------

    def register(self, iface: str, impl: Any, workers: int = 1) -> None:
        """Start `workers` daemon threads serving `iface` with `impl`
        (RedissonRemoteService.register analogue)."""
        qname = _req_queue_name(self._name, iface)
        for i in range(workers):
            t = threading.Thread(
                target=self._worker_loop, args=(qname, impl),
                name=f"rtpu-rs-{iface}-{i}", daemon=True)
            t.start()
            self._workers.append(t)

    def _worker_loop(self, qname: str, impl: Any) -> None:
        q = self._client.get_blocking_queue(qname)
        while not self._stop.is_set():
            try:
                req = q.poll(timeout_s=0.2)
                if req is None:
                    continue
                self._serve_one(req, impl)
            except RuntimeError:
                # Client executor shut down under us (possibly mid-serve,
                # e.g. while offering the response) — exit quietly instead
                # of raising into a daemon thread (VERDICT r2 weak #6).
                return

    def _serve_one(self, req: dict, impl: Any) -> None:
        rid = req["id"]
        if req.get("ack"):
            # SETNX-style ack so exactly one worker claims the request and
            # the client learns a server is alive (reference Lua ack,
            # RedissonRemoteService.java:96-160). TTL'd so a vanished
            # client can't leak it forever.
            acked = self._client.get_bucket(
                f"{self._name}:ack:{rid}").try_set(1, ttl_s=60.0)
            if not acked:
                return
        try:
            method = getattr(impl, req["method"])
            result = method(*req.get("args", ()), **req.get("kwargs", {}))
            resp = {"id": rid, "result": result, "error": None}
        except Exception as e:  # noqa: BLE001 - errors cross the wire
            resp = {"id": rid, "result": None,
                    "error": f"{type(e).__name__}: {e}",
                    "traceback": traceback.format_exc()}
        if req.get("want_result", True):
            rq = self._client.get_blocking_queue(f"{self._name}:resp:{rid}")
            rq.offer(resp)
            # TTL the response (reference RemoteInvocationOptions response
            # timeToLive): a client that already gave up never drains it,
            # so it must expire rather than leak.
            rq.expire(60.0)

    # -- client side --------------------------------------------------------

    def get(self, iface: str,
            options: Optional[RemoteInvocationOptions] = None) -> _Invoker:
        """Synchronous proxy for `iface`."""
        return _Invoker(self, iface, options or RemoteInvocationOptions(),
                        as_async=False)

    def get_async(self, iface: str,
                  options: Optional[RemoteInvocationOptions] = None) -> _Invoker:
        """Async proxy: every method returns a concurrent Future
        (the @RRemoteAsync mapping analogue)."""
        return _Invoker(self, iface, options or RemoteInvocationOptions(),
                        as_async=True)

    def _invoke(self, iface: str, method: str, args, kwargs,
                options: RemoteInvocationOptions) -> Any:
        rid = uuid.uuid4().hex
        want_ack = options.ack_timeout_s is not None
        want_result = options.execution_timeout_s is not None
        req = {"id": rid, "method": method, "args": list(args),
               "kwargs": kwargs, "ack": want_ack, "want_result": want_result}
        req_queue = self._client.get_blocking_queue(
            _req_queue_name(self._name, iface))
        req_queue.offer(req)

        if want_ack:
            ack_bucket = self._client.get_bucket(f"{self._name}:ack:{rid}")
            deadline = options.ack_timeout_s
            import time
            t0 = time.monotonic()
            while ack_bucket.get() is None:
                if time.monotonic() - t0 > deadline:
                    # Withdraw the request so a worker that appears later
                    # does not execute a call the caller saw fail (the
                    # reference's ack-timeout Lua removal). If a worker
                    # already dequeued it, win or lose the ack atomically:
                    # our tombstone try_set vs the worker's ack try_set —
                    # exactly one succeeds, so the worker either never
                    # executes (we won) or executes with a TTL'd response
                    # (it won; bounded leak, same as the reference).
                    req_queue.remove(req)
                    tombstoned = ack_bucket.try_set("cancelled", ttl_s=60.0)
                    if not tombstoned:
                        self._cleanup(rid, want_ack)
                    raise RemoteServiceAckTimeoutError(
                        f"no worker acked {iface}.{method} within {deadline}s")
                time.sleep(0.005)
        if not want_result:
            if want_ack:  # observed: the ack key is ours to clean up
                self._client.delete(f"{self._name}:ack:{rid}")
            return None
        resp = self._client.get_blocking_queue(
            f"{self._name}:resp:{rid}").poll(
                timeout_s=options.execution_timeout_s)
        self._cleanup(rid, want_ack)
        if resp is None:
            raise RemoteServiceTimeoutError(
                f"{iface}.{method} gave no response within "
                f"{options.execution_timeout_s}s")
        if resp["error"] is not None:
            raise RemoteServiceError(resp["error"])
        return resp["result"]

    def _cleanup(self, rid: str, want_ack: bool) -> None:
        self._client.delete(f"{self._name}:resp:{rid}")
        if want_ack:
            self._client.delete(f"{self._name}:ack:{rid}")

    # -- lifecycle ----------------------------------------------------------

    def shutdown(self, wait: bool = True) -> None:
        self._stop.set()
        if wait:
            for t in self._workers:
                t.join(timeout=2)
        self._workers.clear()
        self._pool.shutdown(wait=wait)
