"""Cache manager — the RedissonSpringCacheManager analogue.

Reference (spring/cache/, SURVEY.md §2 L4/L5): maps cache name -> RMap or
RMapCache, with per-cache TTL / max-idle taken from a JSON-loadable
CacheConfig. Without Spring, the manager is a plain registry + a
`@cached` decorator standing in for @Cacheable.
"""

from __future__ import annotations

import functools
import json
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


_MISS = object()


@dataclass
class CacheConfig:
    """Per-cache policy (reference spring/cache/CacheConfig.java)."""

    ttl_s: Optional[float] = None       # 0/None = eternal
    max_idle_s: Optional[float] = None

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "CacheConfig":
        return cls(ttl_s=d.get("ttl_s"), max_idle_s=d.get("max_idle_s"))


class Cache:
    """One named cache over RMapCache (or RMap when no policy is set)."""

    def __init__(self, name: str, backing, config: CacheConfig):
        self.name = name
        self._map = backing
        self._config = config

    def get(self, key: Any, default: Any = None) -> Any:
        v = self._map.get(key)
        if v is None and not self._map.contains_key(key):
            return default  # absent, vs a legitimately cached None
        return v

    def put(self, key: Any, value: Any) -> None:
        if self._config.ttl_s or self._config.max_idle_s:
            self._map.put(key, value, ttl_s=self._config.ttl_s,
                          max_idle_s=self._config.max_idle_s)
        else:
            self._map.put(key, value)

    def put_if_absent(self, key: Any, value: Any) -> Any:
        if self._config.ttl_s or self._config.max_idle_s:
            return self._map.put_if_absent(
                key, value, ttl_s=self._config.ttl_s,
                max_idle_s=self._config.max_idle_s)
        return self._map.put_if_absent(key, value)

    def evict(self, key: Any) -> None:
        self._map.remove(key)

    def clear(self) -> None:
        self._map.clear()

    def size(self) -> int:
        return self._map.size()


class CacheManager:
    """Registry of named caches with per-name policies.

    configs: {"users": {"ttl_s": 60, "max_idle_s": 30}, ...} — the same
    shape the reference loads from JSON/YAML (CacheConfigSupport).
    """

    def __init__(self, client, configs: Optional[Dict[str, Dict]] = None):
        self._client = client
        self._configs: Dict[str, CacheConfig] = {
            name: CacheConfig.from_dict(c) for name, c in (configs or {}).items()
        }
        self._caches: Dict[str, Cache] = {}

    @classmethod
    def from_json(cls, client, text: str) -> "CacheManager":
        return cls(client, json.loads(text))

    def set_config(self, name: str, config: CacheConfig) -> None:
        self._configs[name] = config

    def get_cache(self, name: str) -> Cache:
        cache = self._caches.get(name)
        if cache is None:
            cfg = self._configs.get(name, CacheConfig())
            # Policy'd caches need the eviction-capable map; plain caches
            # use the cheaper RMap (reference picks RMapCache vs RMap the
            # same way, spring/cache/RedissonSpringCacheManager.java).
            if cfg.ttl_s or cfg.max_idle_s:
                backing = self._client.get_map_cache(f"cache:{name}")
            else:
                backing = self._client.get_map(f"cache:{name}")
            cache = self._caches[name] = Cache(name, backing, cfg)
        return cache

    def cache_names(self):
        return sorted(set(self._configs) | set(self._caches))

    def cached(self, cache_name: str,
               key_fn: Optional[Callable[..., Any]] = None):
        """@Cacheable analogue: memoize a function through a named cache."""

        def deco(fn):
            @functools.wraps(fn)
            def wrapper(*args, **kwargs):
                cache = self.get_cache(cache_name)
                key = (key_fn(*args, **kwargs) if key_fn
                       else repr((args, tuple(sorted(kwargs.items())))))
                hit = cache.get(key, _MISS)
                if hit is not _MISS:
                    return hit
                value = fn(*args, **kwargs)
                cache.put(key, value)
                return value

            return wrapper

        return deco
