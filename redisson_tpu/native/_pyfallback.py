"""Pure-python scalar hash implementations — fallback when the native
library cannot be built. Algorithm specs: smhasher MurmurHash3_x64_128,
xxhash.com XXH64 (same contracts as native/redisson_native.cpp)."""

MASK64 = (1 << 64) - 1


def _rotl64(x, n):
    return ((x << n) | (x >> (64 - n))) & MASK64


def _fmix64(k):
    k ^= k >> 33
    k = (k * 0xFF51AFD7ED558CCD) & MASK64
    k ^= k >> 33
    k = (k * 0xC4CEB9FE1A85EC53) & MASK64
    k ^= k >> 33
    return k


def murmur3_x64_128(data: bytes, seed: int = 0):
    c1 = 0x87C37B91114253D5
    c2 = 0x4CF5AD432745937F
    length = len(data)
    h1 = h2 = seed & MASK64
    nblocks = length // 16
    for i in range(nblocks):
        k1 = int.from_bytes(data[i * 16:i * 16 + 8], "little")
        k2 = int.from_bytes(data[i * 16 + 8:i * 16 + 16], "little")
        k1 = (k1 * c1) & MASK64
        k1 = _rotl64(k1, 31)
        k1 = (k1 * c2) & MASK64
        h1 ^= k1
        h1 = _rotl64(h1, 27)
        h1 = (h1 + h2) & MASK64
        h1 = (h1 * 5 + 0x52DCE729) & MASK64
        k2 = (k2 * c2) & MASK64
        k2 = _rotl64(k2, 33)
        k2 = (k2 * c1) & MASK64
        h2 ^= k2
        h2 = _rotl64(h2, 31)
        h2 = (h2 + h1) & MASK64
        h2 = (h2 * 5 + 0x38495AB5) & MASK64
    tail = data[nblocks * 16:]
    k1 = k2 = 0
    for j in range(len(tail) - 1, 7, -1):
        k2 |= tail[j] << (8 * (j - 8))
    if len(tail) > 8:
        k2 = (k2 * c2) & MASK64
        k2 = _rotl64(k2, 33)
        k2 = (k2 * c1) & MASK64
        h2 ^= k2
    for j in range(min(len(tail), 8) - 1, -1, -1):
        k1 |= tail[j] << (8 * j)
    if len(tail) > 0:
        k1 = (k1 * c1) & MASK64
        k1 = _rotl64(k1, 31)
        k1 = (k1 * c2) & MASK64
        h1 ^= k1
    h1 ^= length
    h2 ^= length
    h1 = (h1 + h2) & MASK64
    h2 = (h2 + h1) & MASK64
    h1 = _fmix64(h1)
    h2 = _fmix64(h2)
    h1 = (h1 + h2) & MASK64
    h2 = (h2 + h1) & MASK64
    return h1, h2


_P1 = 0x9E3779B185EBCA87
_P2 = 0xC2B2AE3D27D4EB4F
_P3 = 0x165667B19E3779F9
_P4 = 0x85EBCA77C2B2AE63
_P5 = 0x27D4EB2F165667C5


def _xx_round(acc, lane):
    acc = (acc + lane * _P2) & MASK64
    acc = _rotl64(acc, 31)
    return (acc * _P1) & MASK64


def xxhash64(data: bytes, seed: int = 0) -> int:
    length = len(data)
    pos = 0
    if length >= 32:
        v1 = (seed + _P1 + _P2) & MASK64
        v2 = (seed + _P2) & MASK64
        v3 = seed & MASK64
        v4 = (seed - _P1) & MASK64
        while pos + 32 <= length:
            v1 = _xx_round(v1, int.from_bytes(data[pos:pos + 8], "little"))
            v2 = _xx_round(v2, int.from_bytes(data[pos + 8:pos + 16], "little"))
            v3 = _xx_round(v3, int.from_bytes(data[pos + 16:pos + 24], "little"))
            v4 = _xx_round(v4, int.from_bytes(data[pos + 24:pos + 32], "little"))
            pos += 32
        h = (_rotl64(v1, 1) + _rotl64(v2, 7) + _rotl64(v3, 12) + _rotl64(v4, 18)) & MASK64
        for v in (v1, v2, v3, v4):
            h = ((h ^ _xx_round(0, v)) * _P1 + _P4) & MASK64
    else:
        h = (seed + _P5) & MASK64
    h = (h + length) & MASK64
    while pos + 8 <= length:
        h ^= _xx_round(0, int.from_bytes(data[pos:pos + 8], "little"))
        h = (_rotl64(h, 27) * _P1 + _P4) & MASK64
        pos += 8
    if pos + 4 <= length:
        h ^= (int.from_bytes(data[pos:pos + 4], "little") * _P1) & MASK64
        h = (_rotl64(h, 23) * _P2 + _P3) & MASK64
        pos += 4
    while pos < length:
        h ^= (data[pos] * _P5) & MASK64
        h = (_rotl64(h, 11) * _P1) & MASK64
        pos += 1
    h ^= h >> 33
    h = (h * _P2) & MASK64
    h ^= h >> 29
    h = (h * _P3) & MASK64
    h ^= h >> 32
    return h
