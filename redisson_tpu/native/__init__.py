"""ctypes bindings for the C++ native runtime (native/redisson_native.cpp).

The native library covers the reference's two external native components
(SURVEY.md §2: openhft hash intrinsics + the Netty transport codec):

  * ``murmur3_x64_128`` / ``xxhash64`` — batch hashing of variable-length
    byte keys on host, the ingest path that ships only u64 lanes to the TPU;
  * ``keyslot`` — CRC16 % 16384 with {hashtag} extraction
    (cluster/ClusterConnectionManager.java:543-558 semantics);
  * ``resp_encode_pipeline`` / ``RespParser`` — RESP2 wire codec for the
    durability / Redis-interop client;
  * ``hll_fold`` — one-pass hash+fold into 16384 registers (CPU engine).

The library is compiled on first use (g++, ~1 s) and cached next to the
source. Every entry point has a pure-Python fallback so the package works
on hosts without a toolchain; ``AVAILABLE`` reports which path is live.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple, Union

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC_DIR = os.path.join(_REPO, "native")
_SO_PATH = os.path.join(_SRC_DIR, "librtpu.so")

_lib = None
_lib_lock = threading.Lock()
AVAILABLE = False


def _build() -> Optional[str]:
    src = os.path.join(_SRC_DIR, "redisson_native.cpp")
    if not os.path.exists(src):
        return None
    if os.path.exists(_SO_PATH) and os.path.getmtime(_SO_PATH) >= os.path.getmtime(src):
        return _SO_PATH
    try:
        # The Makefile is the single source of compile flags.
        subprocess.run(
            ["make", "-C", _SRC_DIR, "librtpu.so"],
            check=True, capture_output=True, timeout=120,
        )
        return _SO_PATH if os.path.exists(_SO_PATH) else None
    except (OSError, subprocess.SubprocessError):
        return None


def _load():
    global _lib, AVAILABLE
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        path = _build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i64p = ctypes.POINTER(ctypes.c_int64)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.rtpu_murmur3_x64_128_batch.argtypes = [
            u8p, i64p, ctypes.c_int64, ctypes.c_uint64, u64p, u64p]
        lib.rtpu_xxhash64_batch.argtypes = [
            u8p, i64p, ctypes.c_int64, ctypes.c_uint64, u64p]
        lib.rtpu_crc16.argtypes = [u8p, ctypes.c_int64]
        lib.rtpu_crc16.restype = ctypes.c_uint16
        lib.rtpu_keyslot_batch.argtypes = [u8p, i64p, ctypes.c_int64, i32p]
        lib.rtpu_resp_encode_pipeline.argtypes = [
            u8p, i64p, i32p, ctypes.c_int64, i64p]
        lib.rtpu_resp_encode_pipeline.restype = ctypes.c_void_p
        lib.rtpu_free.argtypes = [ctypes.c_void_p]
        lib.rtpu_resp_parser_new.restype = ctypes.c_void_p
        lib.rtpu_resp_parser_free.argtypes = [ctypes.c_void_p]
        lib.rtpu_resp_parser_feed.argtypes = [ctypes.c_void_p, u8p, ctypes.c_int64]
        lib.rtpu_resp_parser_feed.restype = ctypes.c_int64
        lib.rtpu_resp_parser_pending.argtypes = [ctypes.c_void_p]
        lib.rtpu_resp_parser_pending.restype = ctypes.c_int64
        lib.rtpu_resp_parser_take.argtypes = [ctypes.c_void_p, u8p, ctypes.c_int64]
        lib.rtpu_resp_parser_take.restype = ctypes.c_int64
        lib.rtpu_hll_fold_batch.argtypes = [
            u8p, i64p, ctypes.c_int64, ctypes.c_uint64, u8p]
        lib.rtpu_hll_fold_u64.argtypes = [
            u64p, ctypes.c_int64, ctypes.c_uint64, u8p, ctypes.c_int32]
        lib.rtpu_hll_fold_rows.argtypes = [
            u8p, ctypes.c_int64, i32p, ctypes.c_int64, ctypes.c_uint64, u8p]
        lib.rtpu_hll_fold_u64_rows.argtypes = [
            u64p, i32p, ctypes.c_int64, ctypes.c_uint64, u8p,
            ctypes.c_int64]
        lib.rtpu_bloom_fold_u64.argtypes = [
            u64p, ctypes.c_int64, ctypes.c_uint64, ctypes.c_int32,
            ctypes.c_uint64, u8p, u8p, ctypes.c_int32]
        lib.rtpu_bloom_contains_u64.argtypes = [
            u64p, ctypes.c_int64, ctypes.c_uint64, ctypes.c_int32,
            ctypes.c_uint64, u8p, u8p, ctypes.c_int32]
        lib.rtpu_bloom_fold_rows.argtypes = [
            u8p, ctypes.c_int64, i32p, ctypes.c_int64, ctypes.c_uint64,
            ctypes.c_int32, ctypes.c_uint64, u8p, u8p]
        lib.rtpu_bloom_contains_rows.argtypes = [
            u8p, ctypes.c_int64, i32p, ctypes.c_int64, ctypes.c_uint64,
            ctypes.c_int32, ctypes.c_uint64, u8p, u8p]
        lib.rtpu_popcount.argtypes = [u8p, ctypes.c_int64]
        lib.rtpu_popcount.restype = ctypes.c_uint64
        lib.rtpu_version.restype = ctypes.c_char_p
        _lib = lib
        AVAILABLE = True
    return _lib


def _concat(keys: Sequence[bytes]) -> Tuple[np.ndarray, np.ndarray]:
    """Concatenate byte keys into (data u8[], offsets i64[n+1])."""
    offsets = np.zeros(len(keys) + 1, np.int64)
    if keys:
        np.cumsum(np.fromiter((len(k) for k in keys), np.int64, len(keys)),
                  out=offsets[1:])
    data = np.frombuffer(b"".join(keys), np.uint8) if keys else np.zeros(0, np.uint8)
    return np.ascontiguousarray(data), offsets


def _u8p(a: np.ndarray):
    if a.size == 0:
        # NULL is fine: every native loop guards on n/len first.
        return ctypes.cast(0, ctypes.POINTER(ctypes.c_uint8))
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def murmur3_x64_128(keys: Sequence[bytes], seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Batch MurmurHash3 x64 128 -> (h1, h2) uint64 arrays."""
    lib = _load()
    if lib is None:
        from redisson_tpu.native._pyfallback import murmur3_x64_128 as g
        pairs = [g(k, seed) for k in keys]
        return (np.array([p[0] for p in pairs], np.uint64),
                np.array([p[1] for p in pairs], np.uint64))
    data, offsets = _concat(keys)
    n = len(keys)
    h1 = np.empty(n, np.uint64)
    h2 = np.empty(n, np.uint64)
    lib.rtpu_murmur3_x64_128_batch(
        _u8p(data), offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n, ctypes.c_uint64(seed),
        h1.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        h2.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
    return h1, h2


def xxhash64(keys: Sequence[bytes], seed: int = 0) -> np.ndarray:
    lib = _load()
    if lib is None:
        from redisson_tpu.native._pyfallback import xxhash64 as g
        return np.array([g(k, seed) for k in keys], np.uint64)
    data, offsets = _concat(keys)
    out = np.empty(len(keys), np.uint64)
    lib.rtpu_xxhash64_batch(
        _u8p(data), offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(keys), ctypes.c_uint64(seed),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
    return out


def crc16(data: bytes) -> int:
    lib = _load()
    if lib is None:
        from redisson_tpu.ops import crc16 as _pycrc
        return _pycrc.crc16(data)
    buf = np.frombuffer(data, np.uint8)
    return int(lib.rtpu_crc16(_u8p(np.ascontiguousarray(buf)), len(data)))


def keyslot(key: Union[str, bytes]) -> int:
    """CRC16({hashtag-or-key}) % 16384 — Redis cluster slot."""
    if isinstance(key, str):
        key = key.encode()
    lib = _load()
    if lib is None:
        from redisson_tpu.ops import crc16 as _pycrc
        return _pycrc.key_slot(key)
    data, offsets = _concat([key])
    out = np.empty(1, np.int32)
    lib.rtpu_keyslot_batch(
        _u8p(data), offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        1, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return int(out[0])


def keyslot_batch(keys: Sequence[bytes]) -> np.ndarray:
    lib = _load()
    if lib is None:
        from redisson_tpu.ops import crc16 as _pycrc
        return np.array([_pycrc.key_slot(k) for k in keys], np.int32)
    data, offsets = _concat(keys)
    out = np.empty(len(keys), np.int32)
    lib.rtpu_keyslot_batch(
        _u8p(data), offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(keys), out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return out


# ---------------------------------------------------------------------------
# RESP2 codec
# ---------------------------------------------------------------------------

def _as_arg(a) -> bytes:
    if isinstance(a, bytes):
        return a
    if isinstance(a, str):
        return a.encode()
    if isinstance(a, (int, float)):
        return repr(a).encode() if isinstance(a, float) else str(a).encode()
    return bytes(a)


def resp_encode(*args) -> bytes:
    """Encode one command (RESP array of bulk strings)."""
    return resp_encode_pipeline([args])


def resp_encode_pipeline(commands: Sequence[Sequence]) -> bytes:
    """Encode many commands into one wire buffer (pipeline)."""
    flat: List[bytes] = []
    counts = np.empty(len(commands), np.int32)
    for i, cmd in enumerate(commands):
        enc = [_as_arg(a) for a in cmd]
        counts[i] = len(enc)
        flat.extend(enc)
    lib = _load()
    if lib is None:
        out = bytearray()
        k = 0
        for i in range(len(commands)):
            out += b"*%d\r\n" % counts[i]
            for _ in range(counts[i]):
                a = flat[k]; k += 1
                out += b"$%d\r\n" % len(a) + a + b"\r\n"
        return bytes(out)
    data, offsets = _concat(flat)
    out_len = ctypes.c_int64()
    ptr = lib.rtpu_resp_encode_pipeline(
        _u8p(data), offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        len(commands), ctypes.byref(out_len))
    try:
        return ctypes.string_at(ptr, out_len.value)
    finally:
        lib.rtpu_free(ptr)


class RespError(Exception):
    """A Redis `-ERR ...` reply."""


class RespParser:
    """Incremental RESP2 parser. feed(data) -> list of completed replies.

    Replies decode as: bytes (bulk/simple strings), int, None (null bulk /
    null array), list (arrays, recursively), RespError instances for error
    replies (returned, not raised — the client decides).
    """

    def __init__(self):
        lib = _load()
        self._lib = lib
        self._h = lib.rtpu_resp_parser_new() if lib is not None else None
        self._pybuf = bytearray()  # fallback path buffer
        self._pypos = 0  # parse cursor into _pybuf (avoids O(N^2) re-slicing)
        self._poisoned = False  # fallback protocol-violation latch

    def close(self):
        if self._h is not None:
            self._lib.rtpu_resp_parser_free(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass

    def feed(self, data: bytes) -> List:
        if self._lib is None:
            return self._feed_py(data)
        if self._h is None:
            raise ValueError("RespParser is closed")
        buf = np.frombuffer(data, np.uint8)
        n = self._lib.rtpu_resp_parser_feed(
            self._h, _u8p(np.ascontiguousarray(buf)), len(data))
        if n == 0:
            return []
        pend = self._lib.rtpu_resp_parser_pending(self._h)
        out = np.empty(pend, np.uint8)
        got = self._lib.rtpu_resp_parser_take(self._h, _u8p(out), pend)
        assert got == pend
        return self._unflatten(out.tobytes(), n)

    @staticmethod
    def _unflatten(stream: bytes, count: int) -> List:
        pos = 0

        def one():
            nonlocal pos
            t = stream[pos:pos + 1]
            payload = int.from_bytes(stream[pos + 1:pos + 9], "little", signed=True)
            pos += 9
            if t == b":":
                return payload
            if t in (b"+", b"$", b"-"):
                if t == b"$" and payload < 0:
                    return None
                body = stream[pos:pos + payload]
                pos += payload
                if t == b"-":
                    return RespError(body.decode("utf-8", "replace"))
                return body
            if t == b"*":
                if payload < 0:
                    return None
                return [one() for _ in range(payload)]
            raise ValueError(f"bad flat type {t!r}")

        return [one() for _ in range(count)]

    # Pure-python incremental parser (fallback).
    def _feed_py(self, data: bytes) -> List:
        if self._poisoned:
            return []
        self._pybuf += data
        out = []
        try:
            while True:
                item, consumed = self._parse_py(self._pybuf, self._pypos)
                if consumed == 0:
                    break
                out.append(item)
                self._pypos += consumed
        except ValueError:
            # Framing lost: surface one in-band error (matching the native
            # parser's poisoning) and drop the rest of the stream.
            self._poisoned = True
            self._pybuf = bytearray()
            self._pypos = 0
            out.append(RespError("ERR protocol violation (bad header or nesting)"))
            return out
        if self._pypos > (1 << 16) and self._pypos * 2 > len(self._pybuf):
            del self._pybuf[:self._pypos]
            self._pypos = 0
        return out

    _MAX_DEPTH = 64  # mirror the native parser's nesting cap

    def _parse_py(self, b: bytes, pos: int, depth: int = 0):
        if pos >= len(b):
            return None, 0
        eol = b.find(b"\r\n", pos + 1)
        if eol < 0:
            return None, 0
        t = bytes(b[pos:pos + 1])
        line = bytes(b[pos + 1:eol])
        after = eol + 2
        if t == b"+":
            return line, after - pos
        if t == b"-":
            return RespError(line.decode("utf-8", "replace")), after - pos
        if t == b":":
            return int(line), after - pos
        if t == b"$":
            n = int(line)
            if n < 0:
                return None, after - pos
            if len(b) < after + n + 2:
                return None, 0
            return bytes(b[after:after + n]), after - pos + n + 2
        if t == b"*":
            if depth >= self._MAX_DEPTH:
                raise ValueError("RESP nesting too deep")
            n = int(line)
            if n < 0:
                return None, after - pos
            items = []
            cur = after
            for _ in range(n):
                item, consumed = self._parse_py(b, cur, depth + 1)
                if consumed == 0:
                    return None, 0
                items.append(item)
                cur += consumed
            return items, cur - pos
        raise ValueError(f"bad RESP header {t!r}")


def hll_fold(keys: Sequence[bytes], regs: np.ndarray, seed: int = 0) -> np.ndarray:
    """Hash keys and fold max-ranks into a 16384-register uint8 array
    in-place (native) — the CPU twin of the device insert kernel."""
    assert regs.dtype == np.uint8 and regs.shape == (16384,)
    lib = _load()
    if lib is None:
        from redisson_tpu.native._pyfallback import murmur3_x64_128 as g
        for k in keys:
            h1, _ = g(k, seed)
            bucket = h1 & 16383
            rest = h1 >> 14
            rank = 1
            while rank <= 50 and not (rest & 1):
                rest >>= 1
                rank += 1
            if rank > regs[bucket]:
                regs[bucket] = rank
        return regs
    data, offsets = _concat(keys)
    lib.rtpu_hll_fold_batch(
        _u8p(data), offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        len(keys), ctypes.c_uint64(seed), _u8p(regs))
    return regs


def hll_fold_u64(
    keys: np.ndarray, regs: np.ndarray, seed: int = 0, nthreads: int = 0
) -> np.ndarray:
    """Fold u64 keys (hashed as 8-byte LE murmur3_x64_128) into a
    16384-register uint8 array in-place — the transfer-adaptive ingest
    path's host half (ship the 16 KB sketch, not 8 B/key; the merge runs
    on device). Accepts uint64 [n] or the pack_u64 uint32 [n, 2] layout
    (same memory). Releases the GIL for the native call, so the fold
    overlaps the submitting thread. nthreads=0 -> os.cpu_count()."""
    assert regs.dtype == np.uint8 and regs.shape == (16384,)
    if keys.dtype == np.uint64:
        keys = np.ascontiguousarray(keys)
    elif keys.dtype == np.uint32 and keys.ndim == 2 and keys.shape[1] == 2:
        keys = np.ascontiguousarray(keys).view(np.uint64).reshape(-1)
    else:
        # Anything else (e.g. default int64) would truncate through a u32
        # cast and pair adjacent values into garbage keys — a silently
        # skewed estimate. Refuse.
        raise TypeError(
            f"hll_fold_u64 wants uint64 [n] or packed uint32 [n, 2] keys, "
            f"got {keys.dtype} {keys.shape}"
        )
    if nthreads <= 0:
        nthreads = os.cpu_count() or 1
    lib = _load()
    if lib is None:
        from redisson_tpu.native._pyfallback import murmur3_x64_128 as g
        for k in keys.tolist():
            h1, _ = g(int(k).to_bytes(8, "little"), seed)
            bucket = h1 & 16383
            rest = (h1 >> 14) | (1 << 50)
            rank = 1
            while not (rest & 1):
                rest >>= 1
                rank += 1
            if rank > regs[bucket]:
                regs[bucket] = rank
        return regs
    lib.rtpu_hll_fold_u64(
        keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        keys.shape[0], ctypes.c_uint64(seed), _u8p(regs),
        ctypes.c_int32(nthreads))
    return regs


def hll_fold_u64_rows(keys: np.ndarray, rows: np.ndarray,
                      bank: np.ndarray, seed: int = 0) -> np.ndarray:
    """Fold u64 keys into per-row sketches of a host bank mirror
    ([nrows, 16384] uint8, in place) — the host half of the sharded-bank
    streaming ingest (ship the folded bank periodically, not 8 B/key).
    Requires the native library (callers gate on available())."""
    assert bank.dtype == np.uint8 and bank.ndim == 2 and bank.shape[1] == 16384
    # in-place raw-pointer writes: a strided view would be corrupted at
    # wrong offsets (and a copy would lose the caller's updates) — refuse
    assert bank.flags.c_contiguous, "bank mirror must be C-contiguous"
    keys = _norm_u64_keys(keys, "hll_fold_u64_rows")
    rows = np.ascontiguousarray(rows, np.int32)
    assert rows.shape[0] == keys.shape[0]
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    lib.rtpu_hll_fold_u64_rows(
        keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        keys.shape[0], ctypes.c_uint64(seed), _u8p(bank), bank.shape[0])
    return bank


def hll_fold_rows(
    data: np.ndarray, lengths: np.ndarray, regs: np.ndarray, seed: int = 0
) -> Optional[np.ndarray]:
    """Fold padded byte-key rows ([n, w] uint8 + [n] int32 lengths) into a
    16384-register uint8 array in-place. Returns None when the native
    library is unavailable (callers fall back to the device path; unlike
    hll_fold_u64 there is no python fallback worth running per-key here)."""
    assert regs.dtype == np.uint8 and regs.shape == (16384,)
    lib = _load()
    if lib is None:
        return None
    data = np.ascontiguousarray(data, np.uint8)
    lengths = np.ascontiguousarray(lengths, np.int32)
    lib.rtpu_hll_fold_rows(
        _u8p(data), data.shape[1],
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        data.shape[0], ctypes.c_uint64(seed), _u8p(regs))
    return regs


def _norm_u64_keys(keys: np.ndarray, who: str) -> np.ndarray:
    """uint64 [n] or pack_u64 uint32 [n, 2] -> contiguous uint64 [n]."""
    if keys.dtype == np.uint64:
        return np.ascontiguousarray(keys)
    if keys.dtype == np.uint32 and keys.ndim == 2 and keys.shape[1] == 2:
        return np.ascontiguousarray(keys).view(np.uint64).reshape(-1)
    raise TypeError(
        f"{who} wants uint64 [n] or packed uint32 [n, 2] keys, "
        f"got {keys.dtype} {keys.shape}"
    )


def bloom_fold_u64(keys: np.ndarray, bits: np.ndarray, k: int, m: int,
                   seed: int = 0, want_newly: bool = True,
                   nthreads: int = 0) -> Optional[np.ndarray]:
    """Fold u64 keys into a packed bloom bitmap in-place (numpy packbits
    big-endian layout; index walk identical to ops/bloom.py indexes()).
    Returns the per-key newly-set mask (uint8 [n]) when want_newly, else
    None. The transfer-adaptive bloom ingest's host half: ship/OR the
    bitmap once instead of 8 B/key + per-key bools over a slow link.
    Requires the native library (callers gate on available())."""
    assert bits.dtype == np.uint8 and bits.shape == ((m + 7) // 8,)
    keys = _norm_u64_keys(keys, "bloom_fold_u64")
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    if nthreads <= 0:
        nthreads = os.cpu_count() or 1
    newly = np.empty(keys.shape[0], np.uint8) if want_newly else None
    lib.rtpu_bloom_fold_u64(
        keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        keys.shape[0], ctypes.c_uint64(seed), ctypes.c_int32(k),
        ctypes.c_uint64(m), _u8p(bits),
        _u8p(newly) if newly is not None else None,
        ctypes.c_int32(nthreads))
    return newly


def bloom_contains_u64(keys: np.ndarray, bits: np.ndarray, k: int, m: int,
                       seed: int = 0, nthreads: int = 0) -> np.ndarray:
    """Membership probe of u64 keys against a packed bitmap -> uint8 [n]."""
    assert bits.dtype == np.uint8 and bits.shape == ((m + 7) // 8,)
    keys = _norm_u64_keys(keys, "bloom_contains_u64")
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    if nthreads <= 0:
        nthreads = os.cpu_count() or 1
    out = np.empty(keys.shape[0], np.uint8)
    lib.rtpu_bloom_contains_u64(
        keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        keys.shape[0], ctypes.c_uint64(seed), ctypes.c_int32(k),
        ctypes.c_uint64(m), _u8p(bits), _u8p(out), ctypes.c_int32(nthreads))
    return out


def bloom_fold_rows(data: np.ndarray, lengths: np.ndarray, bits: np.ndarray,
                    k: int, m: int, seed: int = 0,
                    want_newly: bool = True) -> Optional[np.ndarray]:
    """Byte-key ([n, w] + lengths) bloom fold into a packed bitmap."""
    assert bits.dtype == np.uint8 and bits.shape == ((m + 7) // 8,)
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    data = np.ascontiguousarray(data, np.uint8)
    lengths = np.ascontiguousarray(lengths, np.int32)
    newly = np.empty(data.shape[0], np.uint8) if want_newly else None
    lib.rtpu_bloom_fold_rows(
        _u8p(data), data.shape[1],
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        data.shape[0], ctypes.c_uint64(seed), ctypes.c_int32(k),
        ctypes.c_uint64(m), _u8p(bits),
        _u8p(newly) if newly is not None else None)
    return newly


def bloom_contains_rows(data: np.ndarray, lengths: np.ndarray,
                        bits: np.ndarray, k: int, m: int,
                        seed: int = 0) -> np.ndarray:
    """Byte-key membership probe against a packed bitmap -> uint8 [n]."""
    assert bits.dtype == np.uint8 and bits.shape == ((m + 7) // 8,)
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    data = np.ascontiguousarray(data, np.uint8)
    lengths = np.ascontiguousarray(lengths, np.int32)
    out = np.empty(data.shape[0], np.uint8)
    lib.rtpu_bloom_contains_rows(
        _u8p(data), data.shape[1],
        lengths.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        data.shape[0], ctypes.c_uint64(seed), ctypes.c_int32(k),
        ctypes.c_uint64(m), _u8p(bits), _u8p(out))
    return out


def popcount(bits: np.ndarray) -> int:
    """Population count of a packed uint8 buffer (host BITCOUNT)."""
    lib = _load()
    bits = np.ascontiguousarray(bits, np.uint8)
    if lib is None:
        return int(np.unpackbits(bits).sum())
    return int(lib.rtpu_popcount(_u8p(bits), bits.shape[0]))


def version() -> str:
    lib = _load()
    if lib is None:
        return "python-fallback"
    return lib.rtpu_version().decode()


def available() -> bool:
    _load()
    return AVAILABLE
