"""Local checkpoint / resume of the sketch store.

The reference has no client-side checkpointing (SURVEY.md §5 — durability
is the Redis server's job); for a framework that OWNS its state in HBM,
snapshots are first-class. Format: one directory per checkpoint,

    manifest.json   {"version": 1, "objects": {name: {otype, meta, version,
                     dtype, shape}}, "written_at": ...}
    state.npz       name -> array (numpy, host copy)

Writes are atomic (tmp dir + rename). `save` reads consistent per-object
snapshots (jax arrays are immutable — a handle IS a consistent snapshot);
`load` device_puts back and bumps versions. Works for any backend exposing
a SketchStore; the structure tier persists separately via its engine.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Dict, List, Optional

import numpy as np

from redisson_tpu.store import SketchStore

import threading
from collections import defaultdict

MANIFEST = "manifest.json"
STATE = "state.npz"
FORMAT_VERSION = 1
_KEY_PREFIX = "obj:"

# In-process serialization of the swap per target path; cross-process
# concurrent saves to one path are NOT supported (callers coordinate).
_path_locks: dict = defaultdict(threading.Lock)
_path_locks_guard = threading.Lock()


def _swap_lock(path: str) -> threading.Lock:
    with _path_locks_guard:
        return _path_locks[os.path.abspath(path)]


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    """Directory fsync: makes the rename/creation itself durable. No-op
    where directories can't be opened (exotic filesystems)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def save(store: SketchStore, path: str,
         names: Optional[List[str]] = None,
         extra_objects: Optional[Dict] = None,
         manifest_extra: Optional[Dict] = None,
         extra_files: Optional[Dict[str, bytes]] = None) -> int:
    """Snapshot the named objects (default all) into `path`. Returns count.

    extra_objects: {name: (otype, host_array, meta, version)} for state
    living outside the store — pod-mode bank rows exported by the client
    (dispatcher-serialized). Saved identically, so checkpoints are portable
    between pod and single-chip modes.

    manifest_extra: extra top-level manifest keys (the persist snapshotter
    records its journal watermark here); load() ignores unknown keys.

    extra_files: {filename: bytes} written beside the manifest — opaque
    sidecar state (the structure tier's pickled keyspace). Read back via
    `extra_file()`. Pass names=[] to skip the store walk entirely and save
    only extra_objects/extra_files (a pre-captured consistent cut)."""
    if names is None:
        names = store.keys()
    objs = {}
    arrays: Dict[str, np.ndarray] = {}
    for name in names:
        obj = store.get(name)
        if obj is None:
            continue
        host = np.asarray(obj.state)
        arrays[name] = host
        objs[name] = {
            "otype": obj.otype,
            "meta": obj.meta,
            "version": obj.version,
            "dtype": str(host.dtype),
            "shape": list(host.shape),
        }
    for name, (otype, host, meta, version) in (extra_objects or {}).items():
        host = np.asarray(host)
        arrays[name] = host
        objs[name] = {
            "otype": otype,
            "meta": meta,
            "version": version,
            "dtype": str(host.dtype),
            "shape": list(host.shape),
        }
    import tempfile

    parent = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(parent, exist_ok=True)
    # Unique tmp dir: concurrent save() calls never clobber each other.
    tmp = tempfile.mkdtemp(prefix=os.path.basename(path) + ".tmp.", dir=parent)
    try:
        manifest = {"version": FORMAT_VERSION, "written_at": time.time(),
                    "objects": objs}
        manifest.update(manifest_extra or {})
        with open(os.path.join(tmp, MANIFEST), "w") as f:
            json.dump(manifest, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        # Prefix array keys: a sketch literally named "file" would collide
        # with savez's first positional parameter as a bare kwarg.
        np.savez_compressed(os.path.join(tmp, STATE),
                            **{_KEY_PREFIX + k: v for k, v in arrays.items()})
        for fname, blob in (extra_files or {}).items():
            with open(os.path.join(tmp, fname), "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
        # Durability before the swap: the rename below is atomic against a
        # crash of THIS process, but after power loss the directory entry
        # may point at files whose data never left the page cache — fsync
        # every payload file and the tmp directory first, and the parent
        # after the swap so the rename itself is durable.
        _fsync_file(os.path.join(tmp, STATE))
        _fsync_dir(tmp)
        # Exchange-style swap: the previous good checkpoint survives (as
        # `.old`) through every crash point; load() falls back to it.
        # In-process concurrent saves serialize here.
        with _swap_lock(path):
            old = path + ".old"
            if os.path.exists(old):
                shutil.rmtree(old)
            if os.path.exists(path):
                os.replace(path, old)
            os.replace(tmp, path)
            _fsync_dir(parent)
            if os.path.exists(old):
                shutil.rmtree(old)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return len(objs)


def load(store: SketchStore, path: str,
         names: Optional[List[str]] = None, put=None) -> int:
    """Restore objects from a checkpoint into the store (overwriting
    same-named objects). Returns the number restored. Falls back to the
    `.old` sibling if a crash interrupted the last save's swap.

    put: optional hook ``put(name, otype, host_array, meta) -> bool`` that
    claims an object (returning True) instead of the default store path —
    the client uses it to route HLLs into the pod bank."""
    import jax

    if not os.path.exists(os.path.join(path, MANIFEST)):
        old = path + ".old"
        if os.path.exists(os.path.join(old, MANIFEST)):
            path = old
    with open(os.path.join(path, MANIFEST)) as f:
        manifest = json.load(f)
    if manifest.get("version") != FORMAT_VERSION:
        raise ValueError(f"unsupported checkpoint version {manifest.get('version')}")
    with np.load(os.path.join(path, STATE)) as z:
        count = 0
        for name, info in manifest["objects"].items():
            if names is not None and name not in names:
                continue
            host = z[_KEY_PREFIX + name]
            meta = info.get("meta") or {}
            if info["otype"] == "bitset":
                # Legacy checkpoints predate extent tracking: default the
                # written extent to the array length so size() stays sane.
                meta.setdefault("extent_bits", int(np.prod(host.shape)))
            if info["otype"] == "bloom":
                # Layout flag is merge-unsafe (only written when true): an
                # absent key must clear any stale blocked=True on a live
                # object, or blocked kernels would run over classic bits.
                meta.setdefault("blocked", False)
            if put is not None and put(name, info["otype"], host, meta):
                count += 1
                continue
            arr = jax.device_put(host, store.device)
            obj = store.get_or_create(name, info["otype"], lambda: arr, meta)
            store.swap(name, arr)
            obj.meta.update(meta)
            count += 1
    return count


def info(path: str) -> Dict:
    """Read a checkpoint's manifest without loading state. Falls back to
    the `.old` sibling exactly like load() — a crash between the two
    os.replace calls leaves only `.old` valid, and callers probing "is
    there a checkpoint here?" must see the same answer load() would act
    on."""
    if not os.path.exists(os.path.join(path, MANIFEST)):
        old = path + ".old"
        if os.path.exists(os.path.join(old, MANIFEST)):
            path = old
    with open(os.path.join(path, MANIFEST)) as f:
        return json.load(f)


def extra_file(path: str, name: str) -> Optional[bytes]:
    """Read a sidecar file written via save(extra_files=...), honoring the
    same `.old` fallback as load()/info(). None when absent."""
    if not os.path.exists(os.path.join(path, MANIFEST)):
        old = path + ".old"
        if os.path.exists(os.path.join(old, MANIFEST)):
            path = old
    full = os.path.join(path, name)
    if not os.path.exists(full):
        return None
    with open(full, "rb") as f:
        return f.read()
