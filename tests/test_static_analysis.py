"""graftlint as a tier-1 gate + unit coverage for its rules.

Three layers:

1. Gate tests — the committed tree must be clean under both tiers (Tier A
   AST rules over ``redisson_tpu/`` and the Tier B jaxpr audit of ``ops/``),
   with an empty baseline. A regression that introduces an unchunked int32
   reduction, a hidden host sync, or an x64 leak fails CI here.
2. Rule unit tests — each rule is exercised on small seeded sources via
   ``FileLinter(source=...)`` so detection (and non-detection of the blessed
   idioms) is pinned independently of the repo's current contents.
3. Plumbing — suppression comments, baseline roundtrip, and the module CLI.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from tools.graftlint import run_lint
from tools.graftlint.astlint import FileLinter
from tools.graftlint import baseline as baseline_mod
from tools.graftlint.findings import RULES, SUPPRESS_ALIASES

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENGINE_DIR = os.path.join(REPO, "redisson_tpu")


def lint_src(src, filename="scratch.py", explicit=True):
    """Lint an in-memory source string with full rule coverage."""
    return FileLinter(filename, repo_root=None, explicit=explicit,
                      source=textwrap.dedent(src)).run()


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------------------
# 1. gate: the committed tree is clean
# ---------------------------------------------------------------------------

def test_repo_tier_a_clean():
    dicts = run_lint([ENGINE_DIR], jaxpr=False)
    assert dicts == [], (
        "graftlint Tier A findings in redisson_tpu/ — fix or suppress with "
        "a reasoned `# graftlint: allow-<rule>(why)` comment:\n"
        + "\n".join(f"{d['file']}:{d['line']} {d['rule']} {d['message']}"
                    for d in dicts)
    )


def test_jaxpr_audit_clean():
    from tools.graftlint.jaxpr_audit import run_audits

    findings = run_audits()
    assert findings == [], (
        "jaxpr audit findings:\n"
        + "\n".join(f"{f.file} {f.rule} {f.message}" for f in findings)
    )


def test_jaxpr_registry_covers_public_ops():
    """Every public function in the audited ops modules is either traced by
    the registry or explicitly declared host-side in HOST_SIDE."""
    import importlib
    import inspect

    from tools.graftlint.jaxpr_audit import HOST_SIDE, build_registry

    audited = {}  # module short name -> set of audited fn names
    for name, _thunk, _allow in build_registry():
        mod, _, fn = name.partition(".")
        audited.setdefault(mod, set()).add(fn.split("(")[0])
    # pallas wrappers in the registry are named "pallas.*"
    audited["pallas_kernels"] = audited.pop("pallas", set())

    missing = []
    for short in ["bitset", "bloom", "hll", "hashing", "u64"]:
        mod = importlib.import_module(f"redisson_tpu.ops.{short}")
        for fname, fn in vars(mod).items():
            if fname.startswith("_") or not inspect.isfunction(fn):
                continue
            if getattr(fn, "__module__", None) != mod.__name__:
                continue
            if fname.endswith("_jit"):  # jit alias of an audited base fn
                continue
            if fname in HOST_SIDE.get(short, set()):
                continue
            if fname not in audited.get(short, set()):
                missing.append(f"{short}.{fname}")
    assert not missing, (
        "public ops with no jaxpr-audit registry entry (add one in "
        f"tools/graftlint/jaxpr_audit.py or list in HOST_SIDE): {missing}"
    )


def test_baseline_is_empty():
    path = os.path.join(REPO, "tools", "graftlint", "baseline.json")
    assert baseline_mod.load(path) == set(), (
        "the committed baseline must stay empty — fix findings instead of "
        "grandfathering them"
    )


# ---------------------------------------------------------------------------
# 2. rule unit tests on seeded sources
# ---------------------------------------------------------------------------

def test_g001_unchunked_int_reduction():
    findings = lint_src("""
        import jax.numpy as jnp

        def total(bits):
            return jnp.sum(bits.astype(jnp.int32))
    """)
    assert [f.rule for f in findings] == ["G001"]
    assert findings[0].line == 5


def test_g001_chunk_partials_idiom_ok():
    findings = lint_src("""
        import jax.numpy as jnp

        def partials(chunks):
            return jnp.sum(chunks.astype(jnp.int32), axis=1)

        def total(x):
            return jnp.sum(x.astype(jnp.float32))
    """)
    assert findings == []  # axis= reduction and float reduction both fine


def test_g002_host_sync_on_device_value():
    findings = lint_src("""
        import jax.numpy as jnp

        def count(bits):
            return int(jnp.sum(bits, axis=0)[0])
    """)
    assert "G002" in rules_of(findings)


def test_g002_scoped_to_dispatch_paths():
    src = """
        import jax.numpy as jnp

        def count(bits):
            return int(jnp.max(bits, axis=0))
    """
    # engine.py is in the sync-sensitive scope; models/ is not.
    hot = FileLinter(os.path.join(REPO, "redisson_tpu", "engine.py"),
                     repo_root=REPO, source=textwrap.dedent(src)).run()
    cold = FileLinter(os.path.join(REPO, "redisson_tpu", "models", "foo.py"),
                      repo_root=REPO, source=textwrap.dedent(src)).run()
    assert "G002" in rules_of(hot)
    assert "G002" not in rules_of(cold)


def test_g002_one_hop_name_provenance():
    """`x = engine_call(...); int(x)` is flagged, not just direct nesting —
    the shape the pipelined executor's staging code must never contain."""
    findings = lint_src("""
        import jax.numpy as jnp

        def count(bits):
            est = jnp.sum(bits, axis=0)
            return int(est)
    """)
    assert "G002" in rules_of(findings)


def test_g002_provenance_host_assignment_ok():
    """A Name assigned from host-only math does not trip the hop."""
    findings = lint_src("""
        import jax.numpy as jnp

        def count(bits):
            est = len(bits) * 2
            return int(est)
    """)
    assert "G002" not in rules_of(findings)


def test_g002_executor_in_sync_scope():
    """executor.py staging code is now inside the G002 scope."""
    src = """
        import jax.numpy as jnp

        def stage(bits):
            return int(jnp.max(bits, axis=0))
    """
    hot = FileLinter(os.path.join(REPO, "redisson_tpu", "executor.py"),
                     repo_root=REPO, source=textwrap.dedent(src)).run()
    assert "G002" in rules_of(hot)


def test_g003_python_scalar_missing_static():
    findings = lint_src("""
        import jax

        @jax.jit
        def scale(x, n: int):
            return x * n
    """)
    assert "G003" in rules_of(findings)


def test_g003_static_argnames_ok():
    findings = lint_src("""
        import functools

        import jax

        @functools.partial(jax.jit, static_argnames=("n",))
        def scale(x, n: int):
            return x * n
    """)
    assert findings == []


def test_g003_jit_constructed_per_call():
    findings = lint_src("""
        import jax

        def hot_loop(xs):
            f = jax.jit(lambda x: x + 1)
            return [f(x) for x in xs]
    """)
    assert "G003" in rules_of(findings)


def test_g004_raw_lane_arithmetic():
    findings = lint_src("""
        def widen(x):
            return (x.hi << 32) | x.lo
    """)
    assert "G004" in rules_of(findings)


def test_g004_big_literal_in_jax_module():
    findings = lint_src("""
        import jax.numpy as jnp

        def mask(x):
            return x & 0x1FFFFFFFF
    """)
    assert "G004" in rules_of(findings)


def test_g004_allowed_inside_u64_module():
    findings = FileLinter(
        "redisson_tpu/ops/u64.py",
        source="def shl(x):\n    return x.hi << 1\n").run()
    assert "G004" not in rules_of(findings)


def test_g005_pallas_call_contract():
    findings = lint_src("""
        from jax.experimental import pallas as pl

        def run(x):
            return pl.pallas_call(kernel, out_shape=shape)(x)
    """)
    assert "G005" in rules_of(findings)  # interpret= missing


def test_g006_unbounded_future_result():
    findings = lint_src("""
        def wait_all(futures):
            return [f.result() for f in futures]
    """)
    assert "G006" in rules_of(findings)


def test_g006_timeout_bounded_result_ok():
    findings = lint_src("""
        def wait_all(futures):
            return [f.result(timeout=30) for f in futures]
    """)
    assert "G006" not in rules_of(findings)


def test_g006_scoped_to_dispatch_and_serve_paths():
    src = """
        def wait(f):
            return f.result()
    """
    hot = FileLinter(
        os.path.join(REPO, "redisson_tpu", "serve", "scheduler.py"),
        repo_root=REPO, source=textwrap.dedent(src)).run()
    hot2 = FileLinter(
        os.path.join(REPO, "redisson_tpu", "executor.py"),
        repo_root=REPO, source=textwrap.dedent(src)).run()
    cold = FileLinter(
        os.path.join(REPO, "redisson_tpu", "models", "foo.py"),
        repo_root=REPO, source=textwrap.dedent(src)).run()
    assert "G006" in rules_of(hot)
    assert "G006" in rules_of(hot2)
    assert "G006" not in rules_of(cold)


def test_g006_g009_scoped_to_wire():
    """The wire tier is hot-path: an untimed .result() would park the event
    loop for every connection, and a time.time() stamp would poison the
    admitted_at duration math — both scopes cover redisson_tpu/wire/."""
    block_src = """
        def wait(f):
            return f.result()
    """
    clock_src = """
        import time

        def stamp():
            return time.time()
    """
    wire = os.path.join(REPO, "redisson_tpu", "wire", "server.py")
    blocked = FileLinter(wire, repo_root=REPO,
                         source=textwrap.dedent(block_src)).run()
    clocked = FileLinter(wire, repo_root=REPO,
                         source=textwrap.dedent(clock_src)).run()
    assert "G006" in rules_of(blocked)
    assert "G009" in rules_of(clocked)


def test_g006_g009_scoped_to_geo():
    """geo/ link and applier threads sit between the journal and the
    dispatcher: an untimed .result() there wedges replication behind one
    slow apply, and a time.time() lag stamp would let NTP slew corrupt
    staleness math — both scopes must cover redisson_tpu/geo/."""
    block_src = """
        def wait(f):
            return f.result()
    """
    clock_src = """
        import time

        def stamp():
            return time.time()
    """
    geo = os.path.join(REPO, "redisson_tpu", "geo", "newfile.py")
    blocked = FileLinter(geo, repo_root=REPO,
                         source=textwrap.dedent(block_src)).run()
    clocked = FileLinter(geo, repo_root=REPO,
                         source=textwrap.dedent(clock_src)).run()
    assert "G006" in rules_of(blocked)
    assert "G009" in rules_of(clocked)


def test_g006_suppression_with_reason():
    findings = lint_src("""
        def wait(f):
            # graftlint: allow-g006(done-callback: f is already resolved)
            return f.result()
    """)
    assert "G006" not in rules_of(findings)


def test_g007_literal_write_run_flagged():
    findings = lint_src("""
        def evict(backend, target, ops):
            backend.run("delete", target, ops)
    """)
    assert "G007" in rules_of(findings)


def test_g007_read_kind_not_flagged():
    findings = lint_src("""
        def peek(backend, target, ops):
            backend.run("exists", target, ops)
            backend.run("hll_export", target, ops)
    """)
    assert "G007" not in rules_of(findings)


def test_g007_variable_kind_not_flagged():
    """The executor's own dispatch (`run(kind, ...)` with a variable) is the
    sanctioned path — only literal kinds are a bypass signature."""
    findings = lint_src("""
        def dispatch(backend, kind, target, ops):
            backend.run(kind, target, ops)
    """)
    assert "G007" not in rules_of(findings)


def test_g007_scoped_outside_executor():
    src = """
        def evict(backend, target, ops):
            backend.run("delete", target, ops)
    """
    hot = FileLinter(os.path.join(REPO, "redisson_tpu", "routing.py"),
                     repo_root=REPO, source=textwrap.dedent(src)).run()
    commit_point = FileLinter(
        os.path.join(REPO, "redisson_tpu", "executor.py"),
        repo_root=REPO, source=textwrap.dedent(src)).run()
    outside = FileLinter(os.path.join(REPO, "benchmarks", "bench.py"),
                         repo_root=REPO, source=textwrap.dedent(src)).run()
    assert "G007" in rules_of(hot)
    assert "G007" not in rules_of(commit_point)
    assert "G007" not in rules_of(outside)


def test_g007_suppression_with_reason():
    findings = lint_src("""
        def evict(backend, target, ops):
            # graftlint: allow-journal(below the commit point: delegate fan-out)
            backend.run("delete", target, ops)
    """)
    assert "G007" not in rules_of(findings)


def test_g007_covers_cluster_write_kinds():
    """The slot-migration kinds are write=True in OP_TABLE, so G007's
    registry-derived write set must flag a direct `.run()` of any of them
    — ownership changes that bypass the journal would silently diverge on
    recovery (tests/test_cluster.py proves the replay depends on them)."""
    from redisson_tpu.cluster.shard import CLUSTER_KINDS
    from tools.graftlint.astlint import _write_kinds

    assert CLUSTER_KINDS <= _write_kinds()
    for kind in sorted(CLUSTER_KINDS):
        findings = lint_src(f"""
            def flip(backend, ops):
                backend.run("{kind}", "", ops)
        """)
        assert "G007" in rules_of(findings), kind


def lint_scoped(src, filename="redisson_tpu/executor.py"):
    """Lint an in-memory source under an in-repo relpath (G008 and the
    other scope-gated rules key on the repo-relative location)."""
    return FileLinter(os.path.join(REPO, filename), repo_root=REPO,
                      source=textwrap.dedent(src)).run()


def test_g008_broad_except_without_classify_flagged():
    for handler in ("except Exception as exc:", "except BaseException:",
                    "except:"):
        findings = lint_scoped(f"""
            def complete(ops):
                try:
                    launch(ops)
                {handler}
                    for op in ops:
                        op.future.set_exception(ValueError("boom"))
        """)
        assert "G008" in rules_of(findings), handler


def test_g008_classify_in_body_ok():
    findings = lint_scoped("""
        from redisson_tpu.fault.taxonomy import classify

        def complete(ops):
            try:
                launch(ops)
            except Exception as exc:
                exc = classify(exc, seam="kernel_launch")
                for op in ops:
                    op.future.set_exception(exc)
    """)
    assert "G008" not in rules_of(findings)
    # attribute form too (taxonomy.classify)
    findings = lint_scoped("""
        from redisson_tpu.fault import taxonomy

        def complete(ops):
            try:
                launch(ops)
            except Exception as exc:
                raise taxonomy.classify(exc, seam="d2h_complete")
    """, filename="redisson_tpu/backend_tpu.py")
    assert "G008" not in rules_of(findings)


def test_g008_narrow_except_not_flagged():
    findings = lint_scoped("""
        def load(path):
            try:
                return open(path).read()
            except (OSError, ValueError):
                return None
    """, filename="redisson_tpu/persist/journal.py")
    assert "G008" not in rules_of(findings)


def test_g008_scoped_to_fault_boundaries():
    src = """
        def f(ops):
            try:
                g(ops)
            except Exception:
                pass
    """
    in_scope = [
        os.path.join(REPO, "redisson_tpu", "executor.py"),
        os.path.join(REPO, "redisson_tpu", "backend_tpu.py"),
        os.path.join(REPO, "redisson_tpu", "persist", "journal.py"),
        os.path.join(REPO, "redisson_tpu", "parallel", "backend_pod.py"),
    ]
    out_of_scope = [
        os.path.join(REPO, "redisson_tpu", "models", "foo.py"),
        os.path.join(REPO, "redisson_tpu", "serve", "scheduler.py"),
        os.path.join(REPO, "redisson_tpu", "interop", "backend_redis.py"),
    ]
    for path in in_scope:
        findings = FileLinter(path, repo_root=REPO,
                              source=textwrap.dedent(src)).run()
        assert "G008" in rules_of(findings), path
    for path in out_of_scope:
        findings = FileLinter(path, repo_root=REPO,
                              source=textwrap.dedent(src)).run()
        assert "G008" not in rules_of(findings), path
    # `explicit` (a directly-named CLI target, e.g. bench.py) must NOT
    # enable G008: outside the fault boundary a broad except is usually
    # deliberate best-effort isolation, not a taxonomy leak.
    findings = FileLinter(os.path.join(REPO, "bench.py"), repo_root=REPO,
                          explicit=True, source=textwrap.dedent(src)).run()
    assert "G008" not in rules_of(findings)


def test_g008_suppression_with_reason():
    findings = lint_scoped("""
        def f(ops):
            try:
                g(ops)
            except Exception:
                # graftlint: allow-bare(thread-isolation backstop: closures own their futures)
                pass
    """)
    assert "G008" not in rules_of(findings)


def test_g009_wallclock_in_latency_path_flagged():
    findings = lint_src("""
        import time

        def measure(op):
            t0 = time.time()
            op()
            return time.time() - t0
    """)
    assert "G009" in rules_of(findings)


def test_g009_from_import_alias_flagged():
    findings = lint_src("""
        from time import time as now

        def measure(op):
            t0 = now()
            op()
            return now() - t0
    """)
    assert "G009" in rules_of(findings)


def test_g009_monotonic_ok():
    findings = lint_src("""
        import time

        def measure(op):
            t0 = time.monotonic()
            op()
            return time.monotonic() - t0
    """)
    assert "G009" not in rules_of(findings)


def test_g009_scoped_to_latency_paths():
    src = """
        import time

        def stamp():
            return time.time()
    """
    trace = FileLinter(
        os.path.join(REPO, "redisson_tpu", "trace", "spans.py"),
        repo_root=REPO, source=textwrap.dedent(src)).run()
    persist = FileLinter(
        os.path.join(REPO, "redisson_tpu", "persist", "journal.py"),
        repo_root=REPO, source=textwrap.dedent(src)).run()
    serve = FileLinter(
        os.path.join(REPO, "redisson_tpu", "serve", "scheduler.py"),
        repo_root=REPO, source=textwrap.dedent(src)).run()
    cold = FileLinter(
        os.path.join(REPO, "redisson_tpu", "models", "foo.py"),
        repo_root=REPO, source=textwrap.dedent(src)).run()
    assert "G009" in rules_of(trace)
    assert "G009" in rules_of(persist)
    assert "G009" in rules_of(serve)
    assert "G009" not in rules_of(cold)


def test_g009_suppression_with_reason():
    findings = lint_src("""
        import time

        def stamp():
            return time.time()  # graftlint: allow-wallclock(display-only entry timestamp)
    """)
    assert "G009" not in rules_of(findings)


def test_g010_objects_mutation_flagged():
    """Every direct `._objects` mutation shape is a ledger bypass."""
    shapes = [
        'store._objects[name] = obj',
        'del store._objects[name]',
        'store._objects.pop(name, None)',
        'store._objects.clear()',
        'store._objects.update(other)',
        'store._objects.setdefault(name, obj)',
    ]
    for stmt in shapes:
        findings = lint_src(f"def f(store, name, obj, other):\n    {stmt}\n")
        assert "G010" in rules_of(findings), stmt


def test_g010_device_put_to_state_flagged():
    findings = lint_src("""
        import jax

        def install(obj, host):
            obj.state = jax.device_put(host)
    """)
    assert "G010" in rules_of(findings)
    # nested inside an expression too
    findings = lint_src("""
        import jax

        def install(obj, host, mask):
            obj.state = jax.device_put(host) * mask
    """)
    assert "G010" in rules_of(findings)


def test_g010_accounted_idioms_not_flagged():
    # device_put routed through the store seam (the sanctioned shape)
    findings = lint_src("""
        import jax

        def load(store, name, host):
            arr = jax.device_put(host)
            store.get_or_create(name, "hll", lambda: arr)
    """)
    assert "G010" not in rules_of(findings)
    # host-side .state assignment (no device bytes involved)
    findings = lint_src("""
        def reset(self):
            self.state = ClusterState()
    """)
    assert "G010" not in rules_of(findings)
    # read access to ._objects is fine; only mutation is a bypass
    findings = lint_src("""
        def peek(store, name):
            return store._objects.get(name)
    """)
    assert "G010" not in rules_of(findings)


def test_g010_scoped_outside_accounted_seams():
    src = """
        import jax

        def f(store, name, obj, host):
            store._objects[name] = obj
            obj.state = jax.device_put(host)
    """
    in_scope = [
        os.path.join(REPO, "redisson_tpu", "client.py"),
        os.path.join(REPO, "redisson_tpu", "serve", "scheduler.py"),
        os.path.join(REPO, "redisson_tpu", "interop", "fake_server.py"),
    ]
    out_of_scope = [
        os.path.join(REPO, "redisson_tpu", "store.py"),
        os.path.join(REPO, "redisson_tpu", "backend_tpu.py"),
        os.path.join(REPO, "redisson_tpu", "parallel", "backend_pod.py"),
        os.path.join(REPO, "redisson_tpu", "memstat", "accounting.py"),
        os.path.join(REPO, "benchmarks", "bench.py"),
    ]
    for path in in_scope:
        findings = FileLinter(path, repo_root=REPO,
                              source=textwrap.dedent(src)).run()
        assert "G010" in rules_of(findings), path
    for path in out_of_scope:
        findings = FileLinter(path, repo_root=REPO,
                              source=textwrap.dedent(src)).run()
        assert "G010" not in rules_of(findings), path


def test_g010_suppression_with_reason():
    findings = lint_src("""
        def evict(store, name):
            # graftlint: allow-mem(recovery path: ledger rebuilt wholesale after replay)
            store._objects.pop(name, None)
    """)
    assert "G010" not in rules_of(findings)


def test_g010_registry_coverage():
    assert "G010" in RULES
    alias, _desc = RULES["G010"]
    assert alias == "mem"
    assert SUPPRESS_ALIASES["mem"] == "G010"
    assert SUPPRESS_ALIASES["g010"] == "G010"


def test_g007_registry_coverage():
    """Every OP_TABLE kind behaves per its write flag: all write kinds are
    flagged when dispatched as a literal `.run`, no read kind ever is. Pins
    the rule to the registry so new commands are covered automatically."""
    from redisson_tpu.commands import OP_TABLE

    write_kinds = {k for k, d in OP_TABLE.items() if d.write}
    read_kinds = set(OP_TABLE) - write_kinds
    assert len(write_kinds) > 50  # sanity: the registry actually loaded

    def flagged(kind):
        src = f'def f(b, t, ops):\n    b.run("{kind}", t, ops)\n'
        return "G007" in rules_of(lint_src(src))

    missed = sorted(k for k in write_kinds if not flagged(k))
    spurious = sorted(k for k in read_kinds if flagged(k))
    assert missed == [], f"write kinds not flagged by G007: {missed}"
    assert spurious == [], f"read kinds wrongly flagged by G007: {spurious}"


def test_serve_package_lints_clean():
    dicts = run_lint([os.path.join(ENGINE_DIR, "serve")], jaxpr=False)
    assert dicts == [], dicts


def test_g005_blockspec_index_map_arity():
    findings = lint_src("""
        from jax.experimental import pallas as pl

        def run(x, shape):
            grid = (4, 4)
            return pl.pallas_call(
                kernel,
                grid=grid,
                in_specs=[pl.BlockSpec((128, 128), lambda i: (i, 0))],
                out_shape=shape,
                interpret=False,
            )(x)
    """)
    assert "G005" in rules_of(findings)  # lambda i: ... under a 2-d grid


# ---------------------------------------------------------------------------
# 3. suppressions, baseline, CLI
# ---------------------------------------------------------------------------

def test_suppression_with_reason():
    findings = lint_src("""
        import jax.numpy as jnp

        def total(bits):
            # graftlint: allow-int-reduce(bounded by construction in this test)
            return jnp.sum(bits.astype(jnp.int32))
    """)
    assert findings == []


def test_suppression_without_reason_is_ignored():
    findings = lint_src("""
        import jax.numpy as jnp

        def total(bits):
            # graftlint: allow-int-reduce()
            return jnp.sum(bits.astype(jnp.int32))
    """)
    assert "G001" in rules_of(findings)


def test_suppression_wrong_rule_does_not_mask():
    findings = lint_src("""
        import jax.numpy as jnp

        def total(bits):
            # graftlint: allow-sync(wrong rule for this line)
            return jnp.sum(bits.astype(jnp.int32))
    """)
    assert "G001" in rules_of(findings)


def test_every_rule_has_a_suppression_alias():
    for rid, (alias, _desc) in RULES.items():
        assert SUPPRESS_ALIASES[alias] == rid
        assert SUPPRESS_ALIASES[rid.lower()] == rid


def test_baseline_roundtrip_filters_findings(tmp_path):
    scratch = tmp_path / "seeded.py"
    scratch.write_text(
        "import jax.numpy as jnp\n\n"
        "def total(bits):\n"
        "    return jnp.sum(bits.astype(jnp.int32))\n"
    )
    dicts = run_lint([str(scratch)], jaxpr=False, repo_root=str(tmp_path))
    assert [d["rule"] for d in dicts] == ["G001"]

    bl = tmp_path / "baseline.json"
    baseline_mod.write(str(bl), dicts)
    grandfathered = baseline_mod.load(str(bl))
    assert {d["fingerprint"] for d in dicts} == grandfathered

    # a baselined finding no longer gates; a new one still does
    scratch.write_text(
        scratch.read_text()
        + "\ndef sync(bits):\n    return int(jnp.max(bits, axis=0))\n"
    )
    dicts2 = run_lint([str(scratch)], jaxpr=False, repo_root=str(tmp_path))
    fresh = [d for d in dicts2 if d["fingerprint"] not in grandfathered]
    assert [d["rule"] for d in fresh] == ["G002"]


def test_fingerprint_survives_line_moves(tmp_path):
    a = tmp_path / "mod.py"
    a.write_text("import jax.numpy as jnp\n\n"
                 "def f(b):\n    return jnp.sum(b.astype(jnp.int32))\n")
    d1 = run_lint([str(a)], jaxpr=False, repo_root=str(tmp_path))
    a.write_text("import jax.numpy as jnp\n\n\n\n# padding\n\n"
                 "def f(b):\n    return jnp.sum(b.astype(jnp.int32))\n")
    d2 = run_lint([str(a)], jaxpr=False, repo_root=str(tmp_path))
    assert d1[0]["fingerprint"] == d2[0]["fingerprint"]
    assert d1[0]["line"] != d2[0]["line"]


@pytest.mark.slow
def test_cli_module_clean_json():
    """`python -m tools.graftlint redisson_tpu/ --json` exits 0 with no
    findings — the exact CI gate invocation (both tiers)."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "redisson_tpu", "--json"],
        cwd=REPO, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    assert payload["findings"] == []
    assert payload["baselined"] == []


def test_cli_seeded_violations_gate(tmp_path):
    scratch = tmp_path / "viol.py"
    scratch.write_text(
        "import jax.numpy as jnp\n\n"
        "def bad_total(bits):\n"
        "    return jnp.sum(bits.astype(jnp.int32))\n\n"
        "def bad_sync(bits):\n"
        "    return int(jnp.max(bits, axis=0))\n"
    )
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", str(scratch),
         "--json", "--no-jaxpr"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    payload = json.loads(proc.stdout)
    got = {(d["rule"], d["line"]) for d in payload["findings"]}
    assert got == {("G001", 4), ("G002", 7)}


# ---------------------------------------------------------------------------
# 4. Tier B checker unit tests (on synthetic jaxprs, not the repo registry)
# ---------------------------------------------------------------------------

def test_j001_flags_x64_leak():
    import jax
    import jax.numpy as jnp

    from tools.graftlint.jaxpr_audit import _check_one

    with jax.experimental.enable_x64():
        closed = jax.make_jaxpr(lambda x: x.astype(jnp.int64) + 1)(
            jnp.zeros((4,), jnp.int32))
    findings = _check_one("synthetic", closed, {})
    assert "J001" in {f.rule for f in findings}


def test_j002_flags_narrowing_after_reduction():
    import jax
    import jax.numpy as jnp

    from tools.graftlint.jaxpr_audit import _check_one

    def narrow(x):
        return jnp.sum(x.astype(jnp.uint32).reshape(2, 8),
                       axis=1).astype(jnp.uint8)

    closed = jax.make_jaxpr(narrow)(jnp.zeros((16,), jnp.uint8))
    findings = _check_one("synthetic", closed, {})
    assert "J002" in {f.rule for f in findings}
    # a registered allow_narrow bound silences exactly that dtype
    assert _check_one("synthetic", closed,
                      {"uint8": "sum of 8 values <= 255"}) == []


def test_j002_widening_is_fine():
    import jax
    import jax.numpy as jnp

    from tools.graftlint.jaxpr_audit import _check_one

    def widen(x):
        return jnp.sum(x.astype(jnp.int32).reshape(2, 8), axis=1)

    closed = jax.make_jaxpr(widen)(jnp.zeros((16,), jnp.uint8))
    assert _check_one("synthetic", closed, {}) == []


# ---------------------------------------------------------------------------
# 5. Tier C: concurrency discipline (G011-G014)
# ---------------------------------------------------------------------------

from tools.graftlint.concurrency import (ConcurrencyLinter,  # noqa: E402
                                         analyze_paths)


def clint_src(src, filename="scratch.py"):
    """Tier C lint of an in-memory source (explicit scope: always scanned)."""
    return ConcurrencyLinter(filename, repo_root=None, explicit=True,
                             source=textwrap.dedent(src)).run()


def test_repo_tier_c_clean():
    findings, _linters, graph = analyze_paths([ENGINE_DIR], repo_root=REPO)
    assert findings == [], (
        "graftlint Tier C findings in redisson_tpu/ — fix, register the "
        "discipline in GUARDED_BY, or suppress with a reasoned "
        "`# graftlint: allow-<rule>(why)`:\n"
        + "\n".join(f"{f.file}:{f.line} {f.rule} {f.message}"
                    for f in findings)
    )
    assert graph["cycles"] == [], graph["cycles"]


def test_g011_unlocked_access_to_registered_attr():
    findings = clint_src("""
        import threading

        GUARDED_BY = {"Box.items": "_lock"}

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []

            def bad_add(self, x):
                self.items.append(x)

            def good_add(self, x):
                with self._lock:
                    self.items.append(x)
    """)
    assert rules_of(findings) == ["G011"]
    assert len(findings) == 1
    assert "Box.items" in findings[0].message


def test_tier_c_wire_window_discipline_seeded():
    """The wire reply window's GUARDED_BY contract is enforceable: dropping
    the lock around the slots deque is a G011 — the same table
    serve/windows.py registers for the real ConnectionWindow."""
    findings = clint_src("""
        import threading

        GUARDED_BY = {"ConnectionWindow._slots": "_lock"}

        class ConnectionWindow:
            def __init__(self):
                self._lock = threading.Lock()
                self._slots = []

            def drain(self):
                out = list(self._slots)
                return out

            def complete(self, data):
                with self._lock:
                    self._slots.append(data)
    """)
    assert "G011" in rules_of(findings)


def test_tier_c_wire_files_in_scope():
    """serve/windows.py and wire/server.py must stay under Tier C analysis
    (they import the concurrency seam / threading) — a refactor that drops
    them out of scope silently un-checks the wire tier's shared state."""
    import ast as _ast
    for rel in (os.path.join("redisson_tpu", "serve", "windows.py"),
                os.path.join("redisson_tpu", "wire", "server.py")):
        path = os.path.join(REPO, rel)
        linter = ConcurrencyLinter(path, repo_root=REPO, explicit=False)
        with open(path) as f:
            tree = _ast.parse(f.read())
        assert linter.in_scope(tree), rel


def test_tier_c_geo_files_in_scope():
    """The geo applier/link/manager mutate shared LWW maps and link
    tables from journal-listener, link, and anti-entropy threads — all
    three files must stay under Tier C analysis."""
    import ast as _ast
    for rel in (os.path.join("redisson_tpu", "geo", "applier.py"),
                os.path.join("redisson_tpu", "geo", "link.py"),
                os.path.join("redisson_tpu", "geo", "manager.py")):
        path = os.path.join(REPO, rel)
        linter = ConcurrencyLinter(path, repo_root=REPO, explicit=False)
        with open(path) as f:
            tree = _ast.parse(f.read())
        assert linter.in_scope(tree), rel


def test_tier_c_geo_applier_discipline_seeded():
    """The geo applier's GUARDED_BY contract is enforceable: touching the
    version vector without the lock is a G011 — the same table
    geo/applier.py registers for the real GeoApplier."""
    findings = clint_src("""
        import threading

        GUARDED_BY = {"GeoApplier.vv": "_lock"}

        class GeoApplier:
            def __init__(self):
                self._lock = threading.Lock()
                self.vv = {}

            def bad_watermark(self, origin, seq):
                self.vv[origin] = seq

            def good_watermark(self, origin, seq):
                with self._lock:
                    self.vv[origin] = seq
    """)
    assert rules_of(findings) == ["G011"]
    assert "GeoApplier.vv" in findings[0].message


def test_g011_locked_suffix_convention():
    # *_locked methods are analyzed as if the caller already holds every
    # convention lock of the class — no finding inside them.
    findings = clint_src("""
        import threading

        GUARDED_BY = {"Box.items": "_lock"}

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []

            def _add_locked(self, x):
                self.items.append(x)

            def add(self, x):
                with self._lock:
                    self._add_locked(x)
    """)
    assert findings == []


def test_g011_inline_guarded_by_comment():
    findings = clint_src("""
        import threading

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []  # guarded-by: _lock

            def bad(self):
                self.items.append(1)
    """)
    assert rules_of(findings) == ["G011"]


def test_g011_writes_mode_exempts_reads():
    findings = clint_src("""
        import threading

        GUARDED_BY = {"Box.flag": "_lock:writes"}

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.flag = False

            def peek(self):
                return self.flag  # unlocked read: fine under :writes

            def trip(self):
                self.flag = True  # unlocked write: flagged
    """)
    assert rules_of(findings) == ["G011"]
    assert len(findings) == 1


def test_g011_thread_and_racy_modes_exempt():
    findings = clint_src("""
        import threading

        GUARDED_BY = {
            "Box.a": "thread:loop-confined, mutated only pre-start",
            "Box.b": "racy:diagnostics string, stale reads fine",
        }

        class Box:
            def __init__(self):
                self._lock = threading.Lock()
                self.a = 0
                self.b = ""

            def loop(self):
                self.a += 1
                self.b = "x"
    """)
    assert findings == []


def test_g012_two_roots_no_lock():
    findings = clint_src("""
        import threading

        class Svc:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
                self._t = threading.Thread(target=self._loop)

            def _loop(self):
                self.count += 1

            def bump(self):
                self.count += 1
    """)
    assert rules_of(findings) == ["G012"]
    assert "Svc.count" in findings[0].message


def test_g012_common_lock_is_clean():
    findings = clint_src("""
        import threading

        class Svc:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
                self._t = threading.Thread(target=self._loop)

            def _loop(self):
                with self._lock:
                    self.count += 1

            def bump(self):
                with self._lock:
                    self.count += 1
    """)
    assert findings == []


def test_g012_registered_discipline_is_clean():
    findings = clint_src("""
        import threading

        GUARDED_BY = {"Svc.count": "thread:loop and bump never overlap"}

        class Svc:
            def __init__(self):
                self.count = 0
                self._t = threading.Thread(target=self._loop)

            def _loop(self):
                self.count += 1

            def bump(self):
                self.count += 1
    """)
    assert findings == []


def test_g012_callback_arg_is_a_root():
    # a bound method handed to another object as a callback is a thread
    # entry root even without a Thread(...) constructor.
    findings = clint_src("""
        import threading

        class Svc:
            def __init__(self, bus):
                self._lock = threading.Lock()
                self.seen = 0
                bus.subscribe(self._on_event)

            def _on_event(self, ev):
                self.seen += 1

            def poll(self):
                self.seen += 1
    """)
    assert rules_of(findings) == ["G012"]


def test_g013_future_result_under_lock():
    findings = clint_src("""
        import threading

        class Svc:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self, fut):
                with self._lock:
                    return fut.result()

            def good(self, fut):
                res = fut.result()
                with self._lock:
                    return res
    """)
    assert rules_of(findings) == ["G013"]
    assert len(findings) == 1


def test_g013_event_wait_under_lock():
    findings = clint_src("""
        import threading

        class Svc:
            def __init__(self):
                self._lock = threading.Lock()
                self._ev = threading.Event()

            def bad(self):
                with self._lock:
                    self._ev.wait()
    """)
    assert rules_of(findings) == ["G013"]


def test_g013_condition_wait_is_exempt():
    # Condition.wait releases the lock it wraps — not a hold-and-block.
    findings = clint_src("""
        import threading

        class Svc:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)

            def park(self):
                with self._cv:
                    self._cv.wait(timeout=1.0)
    """)
    assert findings == []


def test_g013_queue_get_under_lock():
    findings = clint_src("""
        import queue
        import threading

        class Svc:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()

            def bad(self):
                with self._lock:
                    return self._q.get()
    """)
    assert rules_of(findings) == ["G013"]


def test_g013_one_hop_through_private_method():
    findings = clint_src("""
        import threading

        class Svc:
            def __init__(self):
                self._lock = threading.Lock()

            def _drain(self, fut):
                return fut.result()

            def bad(self, fut):
                with self._lock:
                    return self._drain(fut)
    """)
    assert "G013" in rules_of(findings)


def test_g013_suppression_with_reason():
    findings = clint_src("""
        import threading

        class Svc:
            def __init__(self):
                self._lock = threading.Lock()

            def serialized(self, fut):
                with self._lock:
                    # graftlint: allow-hold(serialization is the design; nothing else takes _lock)
                    return fut.result()
    """)
    assert findings == []


def test_g014_two_lock_inversion(tmp_path):
    mod = tmp_path / "tangle.py"
    mod.write_text(textwrap.dedent("""
        import threading

        class Tangle:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def forward(self):
                with self._a:
                    with self._b:
                        pass

            def backward(self):
                with self._b:
                    with self._a:
                        pass
    """))
    findings, _linters, graph = analyze_paths([str(mod)], repo_root=None)
    assert "G014" in {f.rule for f in findings}
    assert len(graph["cycles"]) == 1
    nodes = set(graph["cycles"][0]["nodes"])
    assert {"tangle.Tangle._a", "tangle.Tangle._b"} <= nodes
    # consistent ordering in a second module must NOT cycle
    ok = tmp_path / "ordered.py"
    ok.write_text(textwrap.dedent("""
        import threading

        class Ordered:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._a:
                    with self._b:
                        pass
    """))
    findings, _linters, graph = analyze_paths([str(ok)], repo_root=None)
    assert findings == []
    assert graph["edges"] and graph["cycles"] == []


def test_g014_one_hop_edge(tmp_path):
    # lock held across a self-call whose body takes another lock still
    # contributes an order edge.
    mod = tmp_path / "hop.py"
    mod.write_text(textwrap.dedent("""
        import threading

        class Hop:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def _inner(self):
                with self._b:
                    pass

            def outer(self):
                with self._a:
                    self._inner()

            def backward(self):
                with self._b:
                    with self._a:
                        pass
    """))
    findings, _linters, graph = analyze_paths([str(mod)], repo_root=None)
    assert "G014" in {f.rule for f in findings}


def test_tier_c_suppression_requires_reason():
    src = """
        import threading

        class Svc:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
                self._t = threading.Thread(target=self._loop)

            def _loop(self):
                self.count += 1  # graftlint: allow-shared()

            def bump(self):
                self.count += 1
    """
    findings = clint_src(src)
    assert rules_of(findings) == ["G012"], "empty reason must not suppress"
    findings = clint_src(src.replace(
        "allow-shared()", "allow-shared(loop and bump never overlap)"))
    assert findings == []


def test_tier_c_rules_registered():
    for rule in ("G011", "G012", "G013", "G014"):
        assert rule in RULES
    for alias in ("guarded", "shared", "hold", "lockcycle"):
        assert alias in SUPPRESS_ALIASES


def test_tier_c_findings_are_baselinable():
    # Tier C findings carry the same fingerprint scheme as Tier A, so the
    # --baseline machinery covers them uniformly.
    from tools.graftlint.cli import collect_full

    src = textwrap.dedent("""
        import threading

        class Svc:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self, fut):
                with self._lock:
                    return fut.result(timeout=5)
    """)
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "svc.py")
        with open(p, "w") as fh:
            fh.write(src)
        dicts, tier_c = collect_full([p], jaxpr=False, repo_root=td)
        assert [d["rule"] for d in dicts] == ["G013"]
        assert dicts[0]["fingerprint"]
        assert tier_c["rules"]["G013"] == 1
        bl = os.path.join(td, "bl.json")
        baseline_mod.write(bl, dicts)
        grandfathered = baseline_mod.load(bl)
        assert dicts[0]["fingerprint"] in grandfathered


def test_cli_json_tier_c_block():
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--json", "--no-jaxpr",
         os.path.join(ENGINE_DIR, "persist", "journal.py")],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["findings"] == []
    assert set(payload["tier_c"]["rules"]) == {"G011", "G012", "G013", "G014"}
    assert "edges" in payload["tier_c"]["lock_graph"]
    assert "cycles" in payload["tier_c"]["lock_graph"]


def test_interop_is_out_of_tier_c_scope():
    # asyncio interop runs single-writer on the event loop — documented
    # exclusion, no thread-lock discipline to check.
    sub = os.path.join(ENGINE_DIR, "interop")
    if not os.path.isdir(sub):
        pytest.skip("no interop package")
    findings, _linters, _graph = analyze_paths([sub], repo_root=REPO)
    assert findings == []


# ---------------------------------------------------------------------------
# 5. Tier D: asyncio/event-loop discipline (asynclint)
# ---------------------------------------------------------------------------

from tools.graftlint.asynclint import (AsyncLinter,  # noqa: E402
                                       analyze_paths as analyze_async)
from tools.graftlint.findings import tier_of  # noqa: E402


def alint_src(src, filename="scratch.py"):
    """Tier D lint of an in-memory source (explicit scope: always scanned)."""
    return AsyncLinter(filename, repo_root=None, explicit=True,
                       source=textwrap.dedent(src)).run()


def test_repo_tier_d_clean():
    findings, _linters = analyze_async([ENGINE_DIR], repo_root=REPO)
    assert findings == [], (
        "graftlint Tier D findings in redisson_tpu/ — fix, declare the "
        "affinity in LOOP_CONFINED (lifecycle= for setup/teardown), or "
        "suppress with a reasoned `# graftlint: allow-<rule>(why)`:\n"
        + "\n".join(f"{f.file}:{f.line} {f.rule} {f.message}"
                    for f in findings)
    )


def test_tier_d_scans_wire_and_interop():
    # Tier D's implicit scope is exactly the event-loop packages; the Tier C
    # exclusion of interop/ is complemented here, not contradicted.
    for sub in ("wire", "interop"):
        d = os.path.join(ENGINE_DIR, sub)
        if not os.path.isdir(d):
            pytest.skip(f"no {sub} package")
        _findings, linters = analyze_async([d], repo_root=REPO)
        assert any(lt.scoped for lt in linters), f"{sub}/ not scanned"


def test_g015_blocking_call_in_coroutine():
    findings = alint_src("""
        import asyncio
        import time

        class Conn:
            async def handle(self):
                time.sleep(0.5)
    """)
    assert "G015" in rules_of(findings)


def test_g015_one_hop_through_private_sync_helper():
    findings = alint_src("""
        import asyncio
        import time

        class Conn:
            async def handle(self):
                self._drain()

            def _drain(self):
                time.sleep(0.1)
    """)
    assert "G015" in rules_of(findings)
    assert any("_drain" in f.message for f in findings)


def test_g015_await_and_executor_dispatch_exempt():
    findings = alint_src("""
        import asyncio
        import time

        class Conn:
            async def handle(self, loop):
                await asyncio.sleep(0.1)
                await loop.run_in_executor(None, self._fsync_all)
                await asyncio.to_thread(self._fsync_all)

            def _fsync_all(self):
                import os
                os.fsync(3)
    """)
    assert "G015" not in rules_of(findings)


def test_g015_lock_provenance_thread_vs_asyncio():
    # Only locks with threading provenance block the loop; an asyncio.Lock
    # acquire is loop-native and must not be flagged.
    findings = alint_src("""
        import asyncio
        import threading

        class Mixed:
            def __init__(self):
                self._alock = asyncio.Lock()
                self._tlock = threading.Lock()

            async def bad(self):
                self._tlock.acquire()

            async def fine(self):
                self._alock.acquire()
    """)
    g015 = [f for f in findings if f.rule == "G015"]
    assert len(g015) == 1
    assert "lock.acquire" in g015[0].message


def test_g016_discarded_coroutine():
    findings = alint_src("""
        import asyncio

        class Svc:
            async def _notify(self):
                pass

            def kick(self):
                self._notify()
    """)
    assert "G016" in rules_of(findings)


def test_g016_dropped_task_reference():
    findings = alint_src("""
        import asyncio

        async def work():
            pass

        class Svc:
            def kick(self):
                asyncio.ensure_future(work())
    """)
    assert "G016" in rules_of(findings)
    assert any("weak reference" in f.message for f in findings)


def test_g016_held_reference_pattern_clean():
    # The blessed idiom: keep a strong ref, discard on completion.
    findings = alint_src("""
        import asyncio

        class Svc:
            def __init__(self):
                self._tasks = set()

            async def _notify(self):
                pass

            def kick(self):
                t = asyncio.ensure_future(self._notify())
                self._tasks.add(t)
                t.add_done_callback(self._tasks.discard)
    """)
    assert "G016" not in rules_of(findings)


def test_g017_mutation_from_thread_root():
    findings = alint_src("""
        import asyncio
        import threading

        LOOP_CONFINED = {"Srv._conns": "connection registry; lifecycle=start"}

        class Srv:
            def __init__(self):
                self._conns = {}

            def start(self):
                self._conns = {}
                threading.Thread(target=self._bg, daemon=True).start()

            def _bg(self):
                self._conns["x"] = 1

            async def register(self, c):
                self._conns["c"] = c
    """)
    g017 = [f for f in findings if f.rule == "G017"]
    # only the thread-entry mutation fires: __init__ and lifecycle=start are
    # exempt, and the async method IS the loop.
    assert len(g017) == 1
    assert "_bg" in g017[0].message


def test_g017_mutation_from_done_callback_root():
    findings = alint_src("""
        import asyncio

        LOOP_CONFINED = {"Srv._pending": "in-flight ops; loop-owned"}

        class Srv:
            def __init__(self):
                self._pending = {}

            def submit(self, ex, op):
                f = ex.submit(op)
                f.add_done_callback(self._done)

            def _done(self, f):
                self._pending.pop(id(f), None)
    """)
    assert "G017" in rules_of(findings)


def test_g017_var_based_key_flags_cross_thread_facade():
    findings = alint_src("""
        import asyncio

        LOOP_CONFINED = {"_pool._listeners": "listener list; loop-owned"}

        class Facade:
            def add_listener(self, fn):
                self._pool._listeners.append(fn)

            def add_listener_ok(self, fn):
                self._loop.call_soon_threadsafe(
                    self._pool._listeners.append, fn)
    """)
    g017 = [f for f in findings if f.rule == "G017"]
    assert len(g017) == 1
    assert "add_listener" in g017[0].message


def test_g018_future_completion_from_done_callback():
    findings = alint_src("""
        import asyncio

        class Bridge:
            def submit(self, ex, fut, op):
                cf = ex.submit(op)
                cf.add_done_callback(self._done)
                self._fut = fut

            def _done(self, cf):
                self._fut.set_result(cf.result())
    """)
    assert "G018" in rules_of(findings)


def test_g018_marshalled_completion_clean():
    findings = alint_src("""
        import asyncio

        class Bridge:
            def submit(self, ex, fut, op):
                cf = ex.submit(op)
                cf.add_done_callback(self._done)
                self._fut = fut

            def _done(self, cf):
                self._loop.call_soon_threadsafe(
                    self._fut.set_result, cf.result())
    """)
    assert "G018" not in rules_of(findings)


def test_g018_asyncio_task_done_callback_is_loop_context():
    # add_done_callback on an asyncio Task runs ON the loop — completing a
    # future there is fine; only concurrent.futures callbacks are off-loop.
    findings = alint_src("""
        import asyncio

        class T:
            def start(self):
                self._t = asyncio.create_task(self._run())
                self._t.add_done_callback(self._finish)

            async def _run(self):
                pass

            def _finish(self, t):
                self._fut.set_result(1)
    """)
    assert "G018" not in rules_of(findings)


def test_tier_d_suppression_requires_reason():
    base = """
        import asyncio
        import time

        class Conn:
            async def handle(self):
                time.sleep(0.5){allow}
    """
    bare = alint_src(base.format(allow="  # graftlint: allow-loop"))
    assert "G015" in rules_of(bare)
    reasoned = alint_src(
        base.format(allow="  # graftlint: allow-loop(startup probe only)"))
    assert "G015" not in rules_of(reasoned)


def test_tier_d_rules_registered():
    for rule in ("G015", "G016", "G017", "G018"):
        assert rule in RULES
        assert tier_of(rule) == "d"
    for alias in ("loop", "unawaited", "affinity", "handoff"):
        assert alias in SUPPRESS_ALIASES
    assert tier_of("G011") == "c"
    assert tier_of("G002") == "a"
    assert tier_of("J001") == "b"


def test_tier_d_findings_are_baselinable():
    from tools.graftlint.cli import collect_tiers

    src = textwrap.dedent("""
        import asyncio
        import time

        class Conn:
            async def handle(self):
                time.sleep(0.5)
    """)
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "conn.py")
        with open(p, "w") as fh:
            fh.write(src)
        dicts, tiers = collect_tiers([p], jaxpr=False, repo_root=td)
        assert [d["rule"] for d in dicts] == ["G015"]
        assert dicts[0]["fingerprint"]
        assert tiers["tier_d"]["rules"]["G015"] == 1
        assert tiers["tier_d"]["modules"] >= 1
        bl = os.path.join(td, "bl.json")
        baseline_mod.write(bl, dicts)
        assert dicts[0]["fingerprint"] in baseline_mod.load(bl)


def test_tier_scoped_baseline_update_preserves_other_tiers():
    # The satellite-6 pin: `--update-baseline --tier d` must not launder a
    # Tier A regression into the baseline, and must not drop entries the
    # other tiers already hold.
    from tools.graftlint.cli import collect_full

    a_src = textwrap.dedent("""
        import jax.numpy as jnp

        def count(bits):
            return int(jnp.sum(bits, axis=0)[0])
    """)
    d_src = textwrap.dedent("""
        import asyncio
        import time

        class Conn:
            async def handle(self):
                time.sleep(0.5)
    """)
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        pa = os.path.join(td, "hot.py")
        pd = os.path.join(td, "conn.py")
        with open(pa, "w") as fh:
            fh.write(a_src)
        with open(pd, "w") as fh:
            fh.write(d_src)
        dicts, _ = collect_full([pa, pd], jaxpr=False, repo_root=td)
        by_rule = {d["rule"]: d for d in dicts}
        assert "G002" in by_rule and "G015" in by_rule
        bl = os.path.join(td, "bl.json")

        # A d-only update must NOT baseline the seeded G002.
        baseline_mod.write(bl, dicts, tiers=("d",))
        grand = baseline_mod.load(bl)
        assert by_rule["G015"]["fingerprint"] in grand
        assert by_rule["G002"]["fingerprint"] not in grand

        # And once tier A holds entries, a d-only rewrite keeps them.
        baseline_mod.write(bl, dicts)
        assert by_rule["G002"]["fingerprint"] in baseline_mod.load(bl)
        baseline_mod.write(bl, [by_rule["G015"]], tiers=("d",))
        grand2 = baseline_mod.load(bl)
        assert by_rule["G002"]["fingerprint"] in grand2
        assert by_rule["G015"]["fingerprint"] in grand2


def test_baseline_v1_flat_format_still_loads():
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        bl = os.path.join(td, "bl.json")
        with open(bl, "w") as fh:
            json.dump({"findings": [{"fingerprint": "abc123",
                                     "rule": "G002", "file": "x.py"}]}, fh)
        assert "abc123" in baseline_mod.load(bl)


def test_cli_json_tier_d_block():
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--json", "--no-jaxpr",
         os.path.join(ENGINE_DIR, "interop", "pool.py")],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["findings"] == []
    assert set(payload["tier_d"]["rules"]) == {"G015", "G016", "G017", "G018"}
    assert payload["tier_d"]["modules"] >= 1
    assert payload["tier_d"]["async_defs"] >= 1
    assert payload["tier_d"]["confined_keys"] >= 1


def test_cli_no_async_skips_tier_d():
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--json", "--no-jaxpr",
         "--no-async", os.path.join(ENGINE_DIR, "interop", "pool.py")],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["tier_d"]["modules"] == 0


# ---------------------------------------------------------------------------
# Tier E: whole-program op-contract analysis (G019-G022)
# ---------------------------------------------------------------------------

def _contract_universe():
    """A minimal self-consistent op universe: four kinds, every registry
    agreeing. Each seeded-violation test perturbs exactly one key."""
    from redisson_tpu.commands import _d

    ops = {d.kind: d for d in [
        _d("hll_add", "PFADD", True, "engine tpu"),
        _d("hll_count", "PFCOUNT", False, "engine tpu"),
        _d("delete", "DEL", True, "engine tpu"),
        _d("geo_merge", "-", True, "engine"),
    ]}
    return {
        "op_table": ops,
        "cluster_kinds": frozenset(),
        "semilattice_kinds": frozenset({"hll_add"}),
        "destructive_kinds": frozenset({"delete"}),
        "ship_kinds": frozenset({"hll_add", "delete"}),
        "coalesce_groups": {"hll_add": "delta"},
        "global_coalesce": frozenset(),
        "read_kinds": frozenset({"hll_count"}),
        "pinned_kinds": frozenset(),
        "lint_write_kinds": frozenset({"hll_add", "delete", "geo_merge"}),
        "both_kinds": frozenset({"delete"}),
        "foldable_kinds": frozenset({"hll_add"}),
        "wire_kinds": frozenset({"hll_add", "hll_count", "delete"}),
        "facade_kinds": {"hll_add": ("models/hll.py", 1),
                         "hll_count": ("models/hll.py", 2)},
        "engine_handlers": {"hll_add", "hll_count", "geo_merge"},
        "tpu_handlers": {"hll_add", "hll_count"},
        "applier_local_branches": {"delete", "flushall"},
        "applier_rebuild_branches": {"geo_merge"},
    }


def contract_findings(**perturb):
    from tools.graftlint.contracts import analyze

    u = _contract_universe()
    u.update(perturb)
    findings, _, stats = analyze(**u)
    return findings, stats


def test_contract_universe_is_clean():
    findings, stats = contract_findings()
    assert findings == [], [f.message for f in findings]
    assert stats["kinds"] == 4 and stats["write_kinds"] == 3


def test_g019_registry_kind_not_in_op_table():
    findings, stats = contract_findings(
        cluster_kinds=frozenset({"warp_flip"}))
    assert [f.rule for f in findings] == ["G019"]
    assert "warp_flip" in findings[0].message
    assert "CLUSTER_KINDS" in findings[0].message
    assert stats["rules"]["G019"] == 1


def test_g019_foldable_kind_missing_from_coalesce():
    findings, _ = contract_findings(coalesce_groups={})
    assert [f.rule for f in findings] == ["G019"]
    assert "hll_add" in findings[0].message
    assert "COALESCE_GROUPS" in findings[0].message


def test_g019_kind_classified_both_semilattice_and_destructive():
    findings, _ = contract_findings(
        destructive_kinds=frozenset({"delete", "hll_add"}),
        applier_local_branches={"delete", "hll_add"})
    assert [f.rule for f in findings] == ["G019"]
    assert "BOTH" in findings[0].message


def test_g019_shipped_kind_unclassified():
    findings, _ = contract_findings(
        ship_kinds=frozenset({"hll_add", "delete", "hll_count"}))
    rules = [f.rule for f in findings]
    assert set(rules) == {"G019"}
    msgs = " | ".join(f.message for f in findings)
    assert "neither" in msgs          # unclassified
    assert "never journals" in msgs   # hll_count is not write=True


def test_g019_geo_record_kind_in_ship_set():
    findings, _ = contract_findings(
        ship_kinds=frozenset({"hll_add", "delete", "geo_merge"}),
        semilattice_kinds=frozenset({"hll_add", "geo_merge"}))
    assert any("echo-loop" in f.message for f in findings)
    assert all(f.rule == "G019" for f in findings)


def test_g019_g007_write_set_drift():
    findings, _ = contract_findings(
        lint_write_kinds=frozenset({"hll_add", "delete"}))  # geo_merge lost
    assert [f.rule for f in findings] == ["G019"]
    assert "G007" in findings[0].message
    assert findings[0].file == "tools/graftlint/astlint.py"


def test_g020_facade_kind_not_in_op_table():
    findings, _ = contract_findings(
        facade_kinds={"hll_add": ("models/hll.py", 1),
                      "mystery_op": ("models/hll.py", 9)})
    assert [f.rule for f in findings] == ["G020"]
    assert "mystery_op" in findings[0].message
    assert findings[0].file == "models/hll.py"
    assert findings[0].line == 9


def test_g020_facade_read_kind_unroutable():
    findings, _ = contract_findings(read_kinds=frozenset())
    assert [f.rule for f in findings] == ["G020"]
    assert "hll_count" in findings[0].message
    assert "READ_KINDS" in findings[0].message


def test_g020_wire_hole_without_contract_escape():
    findings, _ = contract_findings(wire_kinds=frozenset())
    assert {f.rule for f in findings} == {"G020"}
    flagged = {f.message.split("'")[1] for f in findings}
    assert flagged == {"hll_add", "hll_count", "delete"}


def test_g020_contract_escape_clears_wire_hole():
    from redisson_tpu.commands import _d

    u = _contract_universe()
    ops = dict(u["op_table"])
    ops["delete"] = _d("delete", "DEL", True, "engine tpu",
                       "engine-only(facade composite; router owns DEL)")
    findings, _ = contract_findings(
        op_table=ops, wire_kinds=frozenset({"hll_add", "hll_count"}))
    assert findings == [], [f.message for f in findings]
    # ... but an EMPTY reason is not an escape
    ops["delete"] = _d("delete", "DEL", True, "engine tpu", "engine-only( )")
    findings, _ = contract_findings(
        op_table=ops, wire_kinds=frozenset({"hll_add", "hll_count"}))
    assert [f.rule for f in findings] == ["G020"]


def test_g021_journaled_kind_without_replay_handler():
    findings, _ = contract_findings(
        tpu_handlers=frozenset({"hll_count"}))  # hll_add lost its handler
    assert [f.rule for f in findings] == ["G021"]
    assert "hll_add" in findings[0].message
    assert "tpu backend" in findings[0].message


def test_g021_both_kinds_satisfy_dispatch():
    # delete has NO _op_delete in either backend in the fixture — the
    # RoutingBackend._BOTH fan-out is its dispatch path, and that counts.
    findings, _ = contract_findings(both_kinds=frozenset())
    assert [f.rule for f in findings] == ["G021"]
    assert "delete" in findings[0].message


def test_g022_destructive_kind_missing_lww_branch():
    findings, _ = contract_findings(applier_local_branches={"flushall"})
    assert [f.rule for f in findings] == ["G022"]
    assert "delete" in findings[0].message
    assert "note_local" in findings[0].message


def test_g022_geo_kind_missing_rebuild_branch():
    findings, _ = contract_findings(applier_rebuild_branches=set())
    assert [f.rule for f in findings] == ["G022"]
    assert "geo_merge" in findings[0].message
    assert "rebuild" in findings[0].message


def test_tier_e_suppression_requires_reason():
    from tools.graftlint import contracts

    base = 'OP_TABLE = [\n    _d("delete", "DEL", True, "engine tpu"),{allow}\n]\n'
    rel = contracts.OP_TABLE_FILE

    def run(allow):
        src = contracts._Src(rel, base.format(allow=allow))
        findings, _ = contract_findings(
            wire_kinds=frozenset({"hll_add", "hll_count"}),
            sources={rel: src})
        return findings

    assert [f.rule for f in run("")] == ["G020"]
    # bare allow (no reason) does not suppress
    assert [f.rule for f in run("  # graftlint: allow-contract")] == ["G020"]
    assert [f.rule for f in run("  # graftlint: allow-contract()")] == ["G020"]
    # tier-wide escape with a reason does
    assert run("  # graftlint: allow-contract(router owns DEL)") == []
    # ... as does the per-rule alias and the rule id
    assert run("  # graftlint: allow-hole(router owns DEL)") == []
    assert run("  # graftlint: allow-g020(router owns DEL)") == []
    # a DIFFERENT rule's alias does not
    assert [f.rule for f in
            run("  # graftlint: allow-drift(router owns DEL)")] == ["G020"]


def test_tier_e_rules_registered():
    for rule in ("G019", "G020", "G021", "G022"):
        assert rule in RULES
        assert tier_of(rule) == "e"
    for alias in ("drift", "hole", "replay", "arbiter"):
        assert alias in SUPPRESS_ALIASES
    assert tier_of("G018") == "d"


def test_tier_e_findings_are_baselinable():
    from tools.graftlint import contracts

    findings, sources, _ = contracts.analyze(
        **{**_contract_universe(),
           "cluster_kinds": frozenset({"warp_flip"})})
    assert [f.rule for f in findings] == ["G019"]
    lines = sources.get(findings[0].file, [])
    text = lines[findings[0].line - 1] if findings[0].line <= len(lines) else ""
    d = findings[0].to_dict(text)
    assert d["fingerprint"]
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        bl = os.path.join(td, "bl.json")
        baseline_mod.write(bl, [d])
        assert d["fingerprint"] in baseline_mod.load(bl)
        with open(bl) as fh:
            data = json.load(fh)
        assert data["version"] == 3
        assert [e["fingerprint"] for e in data["tiers"]["e"]] == \
            [d["fingerprint"]]


def test_seeded_g002_survives_e_only_baseline_update():
    # The satellite-3 pin: `--update-baseline --tier e` must not launder a
    # Tier A regression into the baseline, and v1/v2 files still load.
    from tools.graftlint.cli import collect_full

    a_src = textwrap.dedent("""
        import jax.numpy as jnp

        def count(bits):
            return int(jnp.sum(bits, axis=0)[0])
    """)
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        pa = os.path.join(td, "hot.py")
        with open(pa, "w") as fh:
            fh.write(a_src)
        dicts, _ = collect_full([pa], jaxpr=False, repo_root=td)
        by_rule = {d["rule"]: d for d in dicts}
        assert "G002" in by_rule
        e_dict = {"rule": "G019", "file": "redisson_tpu/commands.py",
                  "line": 1, "message": "seeded", "hint": "",
                  "fingerprint": "feedc0de00000000"}
        bl = os.path.join(td, "bl.json")

        # An e-only update must NOT baseline the seeded G002 ...
        baseline_mod.write(bl, dicts + [e_dict], tiers=("e",))
        grand = baseline_mod.load(bl)
        assert e_dict["fingerprint"] in grand
        assert by_rule["G002"]["fingerprint"] not in grand

        # ... and once tier A holds entries, an e-only rewrite keeps them.
        baseline_mod.write(bl, dicts)
        assert by_rule["G002"]["fingerprint"] in baseline_mod.load(bl)
        baseline_mod.write(bl, [e_dict], tiers=("e",))
        grand2 = baseline_mod.load(bl)
        assert by_rule["G002"]["fingerprint"] in grand2
        assert e_dict["fingerprint"] in grand2


def test_baseline_v2_format_still_loads():
    # A pre-Tier-E baseline (version 2, no "e" section) must keep loading.
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        bl = os.path.join(td, "bl.json")
        with open(bl, "w") as fh:
            json.dump({"version": 2,
                       "tiers": {"a": [{"fingerprint": "aaa111"}],
                                 "d": [{"fingerprint": "ddd444"}]}}, fh)
        grand = baseline_mod.load(bl)
        assert {"aaa111", "ddd444"} <= grand


def test_repo_tier_e_clean():
    from tools.graftlint.contracts import analyze

    findings, _, stats = analyze()
    assert findings == [], (
        "graftlint Tier E findings — the op contract drifted; fix the "
        "registry or declare a reasoned escape:\n"
        + "\n".join(f"{f.file}:{f.line} {f.rule} {f.message}"
                    for f in findings)
    )
    assert stats["kinds"] > 100
    assert stats["surfaces"]["wire"] >= 14
    assert stats["declared_cells"] >= 14


def test_tier_e_covers_live_registries():
    # The default gather() must see the real registries, not stand-ins.
    from redisson_tpu.cluster.shard import CLUSTER_KINDS
    from redisson_tpu.geo.applier import SHIP_KINDS
    from tools.graftlint.contracts import gather

    u = gather()
    assert u["cluster_kinds"] == CLUSTER_KINDS
    assert u["ship_kinds"] == SHIP_KINDS
    assert "hll_add" in u["wire_kinds"]        # wire AST extraction
    assert "bitset_clear" in u["wire_kinds"]   # incl. conditional-kind SETBIT
    assert "hll_add" in u["facade_kinds"]      # facade AST extraction
    assert "delete" in u["applier_local_branches"]
    assert "geo_merge" in u["applier_rebuild_branches"]
    assert "hll_add" in u["foldable_kinds"]


def test_cli_json_tier_e_block():
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--json", "--no-jaxpr",
         os.path.join(ENGINE_DIR, "interop", "pool.py")],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["findings"] == []
    assert set(payload["tier_e"]["rules"]) == {"G019", "G020", "G021", "G022"}
    assert all(v == 0 for v in payload["tier_e"]["rules"].values())
    assert payload["tier_e"]["kinds"] > 100
    assert payload["tier_e"]["declared_cells"] >= 14


def test_cli_no_contracts_skips_tier_e():
    out = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--json", "--no-jaxpr",
         "--no-contracts", os.path.join(ENGINE_DIR, "interop", "pool.py")],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stdout + out.stderr
    payload = json.loads(out.stdout)
    assert payload["tier_e"]["kinds"] == 0
    assert payload["tier_e"]["declared_cells"] == 0
