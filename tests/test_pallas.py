"""Pallas kernels vs their XLA/numpy references (interpret mode on CPU)."""

import numpy as np
import jax.numpy as jnp
import pytest

from redisson_tpu import engine
from redisson_tpu.ops import hll
from redisson_tpu.ops import pallas_kernels as pk


class TestMergeStack:
    def test_matches_xla_max(self):
        rng = np.random.default_rng(0)
        stack = rng.integers(0, 52, size=(37, hll.M), dtype=np.int32)
        got = np.asarray(pk.merge_stack(jnp.asarray(stack), block=8))
        np.testing.assert_array_equal(got, stack.max(axis=0))

    def test_single_sketch(self):
        rng = np.random.default_rng(1)
        stack = rng.integers(0, 52, size=(1, hll.M), dtype=np.int32)
        got = np.asarray(pk.merge_stack(jnp.asarray(stack), block=8))
        np.testing.assert_array_equal(got, stack[0])

    def test_exact_block_multiple(self):
        rng = np.random.default_rng(2)
        stack = rng.integers(0, 52, size=(16, hll.M), dtype=np.int32)
        got = np.asarray(pk.merge_stack(jnp.asarray(stack), block=8))
        np.testing.assert_array_equal(got, stack.max(axis=0))

    def test_empty_stack(self):
        got = np.asarray(pk.merge_stack(jnp.zeros((0, hll.M), jnp.int32)))
        np.testing.assert_array_equal(got, np.zeros(hll.M, np.int32))

    def test_count_of_merge_matches(self):
        rng = np.random.default_rng(3)
        stack = rng.integers(0, 30, size=(10, hll.M), dtype=np.int32)
        merged = pk.merge_stack(jnp.asarray(stack), block=4)
        a = float(hll.count_jit(merged))
        b = float(hll.count_jit(jnp.max(jnp.asarray(stack), axis=0)))
        assert a == pytest.approx(b, rel=1e-6)


class TestPopcountCells:
    def test_matches_numpy(self):
        rng = np.random.default_rng(4)
        cells = (rng.random(100_000) < 0.3).astype(np.uint8)
        got = int(pk.popcount_cells(jnp.asarray(cells), block=4096))
        assert got == int(cells.sum())

    def test_ragged_tail(self):
        cells = np.ones(5001, np.uint8)
        assert int(pk.popcount_cells(jnp.asarray(cells), block=4096)) == 5001

    def test_empty(self):
        assert int(pk.popcount_cells(jnp.zeros((0,), jnp.uint8))) == 0


class TestBitopCells:
    @pytest.mark.parametrize("op,fn", [
        ("and", np.bitwise_and), ("or", np.bitwise_or), ("xor", np.bitwise_xor),
    ])
    def test_matches_numpy(self, op, fn):
        rng = np.random.default_rng(5)
        stack = (rng.random((3, 7001)) < 0.5).astype(np.uint8)
        got = np.asarray(pk.bitop_cells(jnp.asarray(stack), op, block=2048))
        want = fn(fn(stack[0], stack[1]), stack[2])
        np.testing.assert_array_equal(got, want)

    def test_two_operands(self):
        rng = np.random.default_rng(6)
        stack = (rng.random((2, 512)) < 0.5).astype(np.uint8)
        got = np.asarray(pk.bitop_cells(jnp.asarray(stack), "xor", block=256))
        np.testing.assert_array_equal(got, stack[0] ^ stack[1])


class TestEngineWiring:
    """The engine routes bank ops through the kernels (XLA path off-TPU,
    pallas on TPU — semantics must agree, asserted here via the engine)."""

    def test_merge_all_stacked(self):
        rng = np.random.default_rng(7)
        arrays = [jnp.asarray(rng.integers(0, 52, hll.M, dtype=np.int32))
                  for _ in range(5)]
        got = np.asarray(engine.hll_merge_all(arrays))
        want = np.max(np.stack([np.asarray(a) for a in arrays]), axis=0)
        np.testing.assert_array_equal(got, want)

    def test_bitset_bitop(self):
        rng = np.random.default_rng(8)
        stack = (rng.random((3, 300)) < 0.5).astype(np.uint8)
        got = np.asarray(engine.bitset_bitop(jnp.asarray(stack), "or"))
        np.testing.assert_array_equal(got, stack[0] | stack[1] | stack[2])
