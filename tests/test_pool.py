"""Connection pool: min-idle fill, freeze/unfreeze on kill-restart,
dedicated-connection blocking pops (VERDICT r1 item #6).

Shapes mirror the reference's pool machinery
(`connection/pool/ConnectionPool.java:73-130` init, `:184-186, 283-295`
freeze, `:297-386` re-probe) and the kill/restart fault-injection tests
(`RedissonTest.testConnectionListener`, SURVEY.md §4).
"""

from __future__ import annotations

import threading
import time

import pytest

from redisson_tpu.client import RedissonTPU
from redisson_tpu.config import Config
from redisson_tpu.interop.fake_server import EmbeddedRedis
from redisson_tpu.interop.pool import EndpointFrozen, RespConnectionPool


def test_pool_min_idle_fill():
    with EmbeddedRedis() as server:
        pool = RespConnectionPool(port=server.port, size=4, min_idle=3)
        pool.connect()
        try:
            assert pool.live_count == 3
            assert pool.execute("PING") == b"PONG"
        finally:
            pool.close()


def test_pool_multiplexes_across_connections():
    with EmbeddedRedis() as server:
        pool = RespConnectionPool(port=server.port, size=3, min_idle=3)
        pool.connect()
        try:
            for i in range(30):
                pool.execute("SET", f"k{i}", str(i))
            assert pool.execute("GET", "k7") == b"7"
            assert pool.pipeline([("GET", "k1"), ("GET", "k2")]) == [b"1", b"2"]
            # server saw all three sockets
            assert server.server.connections >= 3
        finally:
            pool.close()


def test_pool_freeze_and_unfreeze_on_kill_restart():
    """Endpoint dies -> failed attempts accumulate -> freeze; restart ->
    ping re-probe unfreezes and refills."""
    server = EmbeddedRedis()
    port = server.port
    events = []
    pool = RespConnectionPool(
        port=port, size=2, min_idle=1, failed_attempts=2,
        reconnection_timeout=0.2, timeout=0.5, retry_attempts=0,
        retry_interval=0.05)
    pool.add_listener(events.append)
    pool.connect()
    try:
        assert pool.execute("PING") == b"PONG"
        server.stop()  # kill
        # Commands now fail; enough failures freeze the endpoint.
        for _ in range(4):
            with pytest.raises(Exception):
                pool.execute("PING")
            if pool.frozen:
                break
        assert pool.frozen
        assert "freeze" in events
        with pytest.raises(EndpointFrozen):
            pool.execute("PING")

        # Restart on the SAME port (the fake binds it explicitly).
        server2 = EmbeddedRedis.on_port(port)
        try:
            deadline = time.time() + 10
            while pool.frozen and time.time() < deadline:
                time.sleep(0.1)
            assert not pool.frozen, "re-probe loop never unfroze the endpoint"
            assert "unfreeze" in events
            assert pool.execute("PING") == b"PONG"
            assert pool.live_count >= 1
        finally:
            server2.stop()
    finally:
        pool.close()


def test_pool_blocking_does_not_stall_ordinary_traffic():
    """A parked BLPOP holds a dedicated connection; PING on the pool still
    answers immediately (the reference's dedicated blocking handling,
    CommandAsyncService.java:514-577)."""
    with EmbeddedRedis() as server:
        pool = RespConnectionPool(port=server.port, size=2, min_idle=1)
        pool.connect()
        try:
            got = {}

            def blocker():
                got["v"] = pool.execute_blocking(
                    "BLPOP", "bq", "5", response_timeout=10.0)

            t = threading.Thread(target=blocker)
            t.start()
            time.sleep(0.2)  # parked
            t0 = time.time()
            assert pool.execute("PING") == b"PONG"
            assert time.time() - t0 < 1.0  # not stuck behind the BLPOP
            pool.execute("RPUSH", "bq", "x")
            t.join(timeout=5)
            assert got["v"] == [b"bq", b"x"]
        finally:
            pool.close()


# -- blocking queue through the client in redis mode ------------------------


@pytest.fixture()
def rclient():
    with EmbeddedRedis() as server:
        cfg = Config()
        cfg.use_redis().address = f"redis://127.0.0.1:{server.port}"
        c = RedissonTPU.create(cfg)
        yield c
        c.shutdown()


def test_blocking_queue_redis_mode_poll_timeout(rclient):
    q = rclient.get_blocking_queue("bq:a")
    t0 = time.time()
    assert q.poll(timeout_s=0.3) is None
    assert time.time() - t0 >= 0.25


def test_blocking_queue_redis_mode_take_and_wakeup(rclient):
    q = rclient.get_blocking_queue("bq:b")
    got = {}

    def taker():
        got["v"] = q.take()

    t = threading.Thread(target=taker)
    t.start()
    time.sleep(0.2)
    q.offer("hello")
    t.join(timeout=10)
    assert not t.is_alive()
    assert got["v"] == "hello"


def test_blocking_queue_redis_mode_immediate(rclient):
    q = rclient.get_blocking_queue("bq:c")
    q.offer("x")
    q.offer("y")
    assert q.poll(timeout_s=1.0) == "x"
    assert q.take() == "y"


def test_brpoplpush_redis_mode(rclient):
    q = rclient.get_blocking_queue("bq:src")
    q.offer("m1")
    assert q.poll_last_and_offer_first_to("bq:dst", timeout_s=1.0) == "m1"
    assert rclient.get_queue("bq:dst").peek() == "m1"


def test_idle_connections_reaped_above_min_idle():
    """Connections idle past idle_timeout are retired down to min_idle
    (IdleConnectionWatcher.java:42-60)."""
    from redisson_tpu.interop.fake_server import EmbeddedRedis
    from redisson_tpu.interop.pool import RespConnectionPool

    with EmbeddedRedis() as er:
        pool = RespConnectionPool(
            host="127.0.0.1", port=er.port, size=4, min_idle=1,
            idle_timeout=0.2)
        pool.connect()
        try:
            # Grow the pool via exclusive checkouts returned to rotation.
            for _ in range(3):
                pool.execute_blocking("BLPOP", "nope", "0.05",
                                      response_timeout=5.0)
            assert pool.live_count >= 2
            deadline = time.time() + 5
            while time.time() < deadline and pool.live_count > 1:
                time.sleep(0.1)
            assert pool.live_count == 1       # reaped to the min-idle floor
            assert pool.reaped >= 1
            assert pool.execute("PING") == b"PONG"  # still serves traffic
        finally:
            pool.close()
