"""Functional tests for the long-tail structure objects, modeled on the
reference's per-object suites (RedissonMapTest, RedissonSetTest,
RedissonListTest, RedissonScoredSortedSetTest, ...)."""

import time

import pytest

from redisson_tpu.client import RedissonTPU
from redisson_tpu.config import Config


@pytest.fixture(scope="module", params=["local", "redis"])
def client(request):
    """Every structure test runs twice: engine mode and redis passthrough
    against the embedded fake server (VERDICT r2 next #3 — no
    UnsupportedInRedisMode left on the structure surface)."""
    if request.param == "redis":
        from redisson_tpu.interop.fake_server import EmbeddedRedis

        with EmbeddedRedis() as er:
            cfg = Config()
            cfg.use_redis().address = f"redis://127.0.0.1:{er.port}"
            c = RedissonTPU.create(cfg)
            try:
                yield c
            finally:
                c.shutdown()
        return
    c = RedissonTPU.create(Config())
    yield c
    c.shutdown()


@pytest.fixture(autouse=True)
def _flush(client):
    client.flushall()
    yield


# ---- bucket / atomics -----------------------------------------------------


def test_bucket_set_get(client):
    b = client.get_bucket("b1")
    assert b.get() is None
    b.set({"a": 1})
    assert b.get() == {"a": 1}
    assert b.is_exists()
    assert b.delete()
    assert b.get() is None


def test_bucket_try_set_and_cas(client):
    b = client.get_bucket("b2")
    assert b.try_set("v1")
    assert not b.try_set("v2")
    assert b.get() == "v1"
    assert b.compare_and_set("v1", "v3")
    assert not b.compare_and_set("v1", "v4")
    assert b.get() == "v3"
    assert b.get_and_set("v5") == "v3"


def test_bucket_ttl(client):
    b = client.get_bucket("b3")
    b.set("x", ttl_s=0.05)
    assert b.get() == "x"
    time.sleep(0.08)
    assert b.get() is None


def test_buckets_multi(client):
    client.get_bucket("m1").set(1)
    client.get_bucket("m2").set(2)
    bs = client.get_buckets()
    assert bs.get("m1", "m2", "m3") == {"m1": 1, "m2": 2}
    bs.set({"m4": 4, "m5": 5})
    assert client.get_bucket("m4").get() == 4
    assert not bs.try_set({"m5": 9, "m6": 6})  # m5 exists -> all-or-nothing
    assert client.get_bucket("m6").get() is None


def test_atomic_long(client):
    al = client.get_atomic_long("al")
    assert al.get() == 0
    assert al.increment_and_get() == 1
    assert al.add_and_get(10) == 11
    assert al.get_and_increment() == 11
    assert al.get() == 12
    assert al.compare_and_set(12, 100)
    assert not al.compare_and_set(12, 200)
    assert al.get_and_set(7) == 100
    assert al.decrement_and_get() == 6


def test_atomic_double(client):
    ad = client.get_atomic_double("ad")
    assert ad.get() == 0.0
    assert ad.add_and_get(1.5) == pytest.approx(1.5)
    assert ad.increment_and_get() == pytest.approx(2.5)
    assert ad.compare_and_set(2.5, 10.0)
    assert ad.get() == pytest.approx(10.0)


# ---- map ------------------------------------------------------------------


def test_map_basic(client):
    m = client.get_map("m")
    assert m.put("k1", "v1") is None
    assert m.put("k1", "v2") == "v1"
    assert m.get("k1") == "v2"
    assert m.size() == 1
    assert m.fast_put("k2", {"x": [1, 2]})
    assert not m.fast_put("k2", "other")
    assert m.contains_key("k2")
    assert m.contains_value("v2")
    assert not m.contains_value("nope")
    assert sorted(m.key_set()) == ["k1", "k2"]
    assert m.remove("k1") == "v2"
    assert m.remove("k1") is None
    assert m.fast_remove("k2", "kx") == 1
    assert m.size() == 0


def test_map_compound_ops(client):
    m = client.get_map("m2")
    assert m.put_if_absent("a", 1) is None
    assert m.put_if_absent("a", 2) == 1
    assert m.replace("a", 5) == 1
    assert m.replace("missing", 5) is None
    assert m.replace("a", 5, 6)
    assert not m.replace("a", 5, 7)
    assert m.remove("a", 99) is False
    assert m.remove("a", 6) is True
    m.put_all({"x": 1, "y": 2, "z": 3})
    assert m.get_all(["x", "z", "nope"]) == {"x": 1, "z": 3}
    assert m.read_all_map() == {"x": 1, "y": 2, "z": 3}
    assert m.add_and_get("ctr", 5) == 5
    assert m.add_and_get("ctr", -2) == 3
    assert m.add_and_get("fctr", 0.5) == pytest.approx(0.5)


def test_map_iteration_and_sugar(client):
    m = client.get_map("m3")
    for i in range(25):
        m[f"k{i}"] = i
    assert len(m) == 25
    assert dict(m.iter_entries(count=7)) == {f"k{i}": i for i in range(25)}
    assert m["k3"] == 3
    assert "k3" in m
    del m["k3"]
    assert "k3" not in m
    with pytest.raises(KeyError):
        m["k3"]


# ---- set ------------------------------------------------------------------


def test_set_basic(client):
    s = client.get_set("s")
    assert s.add("a")
    assert not s.add("a")
    assert s.add_all(["b", "c"])
    assert s.size() == 3
    assert s.contains("b")
    assert s.read_all() == {"a", "b", "c"}
    assert s.remove("b")
    assert not s.remove("b")
    assert s.contains_all(["a", "c"])
    assert not s.contains_all(["a", "zz"])
    got = s.remove_random(1)
    assert len(got) == 1 and got[0] in {"a", "c"}


def test_set_algebra(client):
    s1, s2 = client.get_set("sa"), client.get_set("sb")
    s1.add_all([1, 2, 3, 4])
    s2.add_all([3, 4, 5])
    assert s1.read_intersection("sb") == {3, 4}
    assert s1.read_union("sb") == {1, 2, 3, 4, 5}
    assert s1.read_diff("sb") == {1, 2}
    assert s1.retain_all([1, 2, 3])  # changed
    assert s1.read_all() == {1, 2, 3}
    assert not s1.retain_all([1, 2, 3])  # unchanged
    # union() OVERWRITES this set with the named sets' union (the
    # destination is not a source — RedissonSet.java:244-251, pinned by
    # conformance vs RedissonSetTest.java:294-307).
    assert s1.union("sb") == 3
    assert s1.read_all() == {3, 4, 5}


def test_set_move_and_iter(client):
    s1, s2 = client.get_set("mv1"), client.get_set("mv2")
    s1.add_all(range(20))
    assert s1.move("mv2", 7)
    assert not s1.contains(7)
    assert s2.contains(7)
    assert set(s1.iterator(count=6)) == set(range(20)) - {7}


# ---- list / queue ---------------------------------------------------------


def test_list_basic(client):
    lst = client.get_list("l")
    assert lst.add("a")
    lst.add_all(["b", "c", "d"])
    assert lst.size() == 4
    assert lst.get(0) == "a"
    assert lst.get(-1) == "d"
    assert lst.read_all() == ["a", "b", "c", "d"]
    assert lst.index_of("c") == 2
    assert lst.index_of("zz") == -1
    lst.insert(1, "x")
    assert lst.read_all() == ["a", "x", "b", "c", "d"]
    assert lst.set(0, "A") == "a"
    assert lst.remove_at(1) == "x"
    assert lst.remove("c")
    assert lst.read_all() == ["A", "b", "d"]
    lst.trim(0, 1)
    assert lst.read_all() == ["A", "b"]


def test_list_duplicates_lrem(client):
    lst = client.get_list("l2")
    lst.add_all(["a", "b", "a", "c", "a"])
    assert lst.last_index_of("a") == 4
    assert lst.remove("a", count=2)
    assert lst.read_all() == ["b", "c", "a"]


def test_queue_deque(client):
    q = client.get_queue("q")
    assert q.offer("1")
    q.offer("2")
    assert q.peek() == "1"
    assert q.poll() == "1"
    assert q.poll() == "2"
    assert q.poll() is None

    d = client.get_deque("dq")
    d.add_last("m")
    d.add_first("f")
    d.add_last("l")
    assert d.peek_first() == "f"
    assert d.peek_last() == "l"
    assert d.poll_last() == "l"
    assert d.poll_first() == "f"


def test_rpoplpush(client):
    q1, q2 = client.get_queue("qa"), client.get_queue("qb")
    q1.offer("x")
    q1.offer("y")
    assert q1.poll_last_and_offer_first_to("qb") == "y"
    assert q2.peek() == "y"


# ---- zset -----------------------------------------------------------------


def test_scored_sorted_set(client):
    z = client.get_scored_sorted_set("z")
    assert z.add(3.0, "c")
    assert z.add(1.0, "a")
    assert z.add(2.0, "b")
    assert not z.add(5.0, "a")  # update, not add
    assert z.size() == 3
    assert z.get_score("b") == 2.0
    assert z.rank("b") == 0
    assert z.value_range(0, -1) == ["b", "c", "a"]  # a moved to score 5
    assert z.entry_range(0, 0) == [("b", 2.0)]
    assert z.rev_rank("a") == 0
    assert z.first() == "b"
    assert z.last() == "a"
    assert z.count(min=2.0, max=5.0, min_inc=True, max_inc=False) == 2
    assert z.add_score("b", 10.0) == 12.0
    assert z.poll_first() == "c"
    assert z.remove("a")
    assert not z.remove("a")


def test_zset_range_by_score_and_remove(client):
    z = client.get_scored_sorted_set("z2")
    z.add_all([(float(i), f"m{i}") for i in range(10)])
    assert z.value_range_by_score(2.0, True, 5.0, False) == ["m2", "m3", "m4"]
    assert z.value_range_by_score(None, True, 3.0, True, offset=1, count=2) == ["m1", "m2"]
    assert z.remove_range_by_score(0.0, True, 4.0, True) == 5
    assert z.size() == 5
    assert z.remove_range_by_rank(0, 1) == 2
    assert z.value_range(0, -1) == ["m7", "m8", "m9"]
    assert z.union("z_missing") == 3


def test_lex_sorted_set(client):
    lx = client.get_lex_sorted_set("lex")
    assert lx.add_all(["b", "a", "d", "c"]) == 4
    assert lx.read_all() == ["a", "b", "c", "d"]
    assert lx.lex_range(from_element="b", from_inclusive=True) == ["b", "c", "d"]
    assert lx.lex_range(from_element="b", from_inclusive=False) == ["c", "d"]
    assert lx.lex_range_head("c", inclusive=False) == ["a", "b"]
    assert lx.lex_count(from_element="a", from_inclusive=False, to_element="d", to_inclusive=False) == 2
    assert lx.remove_range(from_element="a", from_inclusive=True, to_element="b", to_inclusive=True) == 2
    assert lx.read_all() == ["c", "d"]


def test_sorted_set_comparator(client):
    ss = client.get_sorted_set("ss")
    assert ss.add(5)
    assert ss.add(1)
    assert ss.add(3)
    assert not ss.add(3)
    assert ss.read_all() == [1, 3, 5]
    assert ss.first() == 1 and ss.last() == 5
    assert ss.contains(3)
    assert not ss.contains(4)
    assert ss.remove(3)
    assert ss.read_all() == [1, 5]
    # custom key: reverse order
    ss2 = client.get_sorted_set("ss2", key=lambda v: -v)
    ss2.add_all([1, 5, 3])
    assert ss2.read_all() == [5, 3, 1]


# ---- multimap -------------------------------------------------------------


def test_set_multimap(client):
    mm = client.get_set_multimap("smm")
    assert mm.put("k1", "a")
    assert mm.put("k1", "b")
    assert not mm.put("k1", "a")  # set semantics
    assert mm.get_all("k1") == {"a", "b"}
    assert mm.size() == 2
    assert mm.key_size() == 1
    assert mm.contains_key("k1")
    assert mm.contains_entry("k1", "a")
    assert not mm.contains_entry("k1", "zz")
    assert mm.contains_value("b")
    assert mm.remove("k1", "a")
    assert sorted(mm.remove_all("k1")) == ["b"]
    assert mm.size() == 0


def test_list_multimap(client):
    mm = client.get_list_multimap("lmm")
    mm.put("k", "a")
    mm.put("k", "a")
    mm.put("k", "b")
    assert mm.get_all("k") == ["a", "a", "b"]  # duplicates preserved
    assert mm.size() == 3
    assert mm.remove("k", "a")
    assert mm.get_all("k") == ["a", "b"]
    entries = mm.entries()
    assert ("k", "b") in entries


# ---- geo ------------------------------------------------------------------


def test_geo(client):
    g = client.get_geo("geo")
    assert g.add_entries(
        (13.361389, 38.115556, "Palermo"), (15.087269, 37.502669, "Catania")
    ) == 2
    d = g.dist("Palermo", "Catania", unit="km")
    assert d == pytest.approx(166.27, abs=1.0)
    pos = g.pos("Palermo")
    assert pos["Palermo"][0] == pytest.approx(13.361389)
    hits = g.radius(15.0, 37.0, 200, unit="km")
    assert set(hits) == {"Palermo", "Catania"}
    assert g.radius(15.0, 37.0, 100, unit="km") == ["Catania"]
    with_dist = g.radius_with_distance(15.0, 37.0, 200, unit="km")
    assert with_dist["Catania"] < with_dist["Palermo"]
    assert g.radius_by_member("Palermo", 200, unit="km") == ["Palermo", "Catania"]


# ---- keys / expiry --------------------------------------------------------


def test_keys_facade(client):
    client.get_bucket("kx:1").set(1)
    client.get_map("kx:2").fast_put("a", 1)
    client.get_hyper_log_log("kx:3").add("v")
    keys = client.get_keys()
    assert set(keys.get_keys("kx:*")) == {"kx:1", "kx:2", "kx:3"}
    assert keys.count() >= 3
    assert keys.delete("kx:1", "kx:nope") == 1
    assert keys.delete_by_pattern("kx:*") == 2
    assert keys.get_keys("kx:*") == []


def test_expirable_surface(client):
    m = client.get_map("exp")
    m.fast_put("a", 1)
    assert m.remain_time_to_live() == -1
    assert m.expire(2.0)
    ttl = m.remain_time_to_live()
    assert 0 < ttl <= 2000
    assert m.clear_expire()
    assert m.remain_time_to_live() == -1
    assert m.expire(0.03)
    time.sleep(0.06)
    assert not m.is_exists()
    assert m.remain_time_to_live() == -2


def test_rename(client):
    b = client.get_bucket("rn1")
    b.set("v")
    b.rename("rn2")
    assert b.name == "rn2"
    assert client.get_bucket("rn2").get() == "v"
    assert client.get_bucket("rn1").get() is None


def test_wrongtype_guard(client):
    client.get_bucket("wt").set("v")
    from redisson_tpu.store import WrongTypeError

    with pytest.raises(WrongTypeError):
        client.get_map("wt").fast_put("a", 1)


# ---- scan cursor stability (VERDICT r2 weak #3) ---------------------------


def test_sscan_cursor_stable_under_mutation(client):
    """Elements present for the whole scan are returned exactly once even
    when other elements are deleted between pages (positional cursors skip
    on delete-before-cursor)."""
    s = client.get_set("scan:mut")
    stable = {f"stable-{i}" for i in range(30)}
    doomed = {f"doomed-{i}" for i in range(30)}
    s.add_all(stable | doomed)
    seen = []
    cursor = 0
    first = True
    while True:
        cursor, page = s._executor.execute_sync(
            s.name, "sscan", {"cursor": cursor, "count": 7}
        )
        seen.extend(page)
        if first:
            # Delete a batch of other members mid-scan; stable ones stay.
            s.remove_all([d for d in doomed])
            first = False
        if cursor == 0:
            break
    decoded = {s._d(m) for m in seen}
    assert stable <= decoded
    counts = {}
    for m in seen:
        counts[m] = counts.get(m, 0) + 1
    stable_raw = {m for m in seen if s._d(m) in stable}
    assert all(counts[m] == 1 for m in stable_raw)


def test_hscan_readd_and_add_mid_scan(client):
    m = client.get_map("scan:h")
    m.put_all({f"k{i}": i for i in range(25)})
    cursor, page = m._executor.execute_sync(m.name, "hscan", {"cursor": 0, "count": 10})
    # Add new fields mid-scan: they must appear at most once in the remainder.
    m.put_all({f"new{i}": i for i in range(5)})
    seen = list(page)
    while cursor != 0:
        cursor, page = m._executor.execute_sync(
            m.name, "hscan", {"cursor": cursor, "count": 10}
        )
        seen.extend(page)
    fields = [f for f, _ in seen]
    assert len(fields) == len(set(fields))  # no duplicates at all here
    stable = {m._ek(f"k{i}") for i in range(25)}
    assert stable <= set(fields)


def test_zscan_cursor_stable(client):
    z = client.get_scored_sorted_set("scan:z")
    z.add_all([(float(i), f"m{i}") for i in range(20)])
    cursor, page = z._executor.execute_sync(z.name, "zscan", {"cursor": 0, "count": 6})
    z.remove(f"m0")  # already returned or not — either way no skip of others
    seen = list(page)
    while cursor != 0:
        cursor, page = z._executor.execute_sync(
            z.name, "zscan", {"cursor": cursor, "count": 6}
        )
        seen.extend(page)
    members = {z._d(mm) for mm, _ in seen}
    assert {f"m{i}" for i in range(1, 20)} <= members


def test_srandmember_is_random(client):
    s = client.get_set("scan:rand")
    s.add_all(range(64))
    draws = {tuple(sorted(s.random(3))) for _ in range(12)}
    assert len(draws) > 1  # r2: same-millisecond calls were identical
    with_rep = s._executor.execute_sync(s.name, "srandmember", {"count": -200})
    assert len(with_rep) == 200


# ---- set cache (both modes; redis tier stores a zset scored by expiry,
# the reference's own layout — RedissonSetCache.java) ------------------------


def test_set_cache_ttl(client):
    sc = client.get_set_cache("scttl")
    assert sc.add("keep")
    assert sc.add("fleeting", ttl_s=0.15)
    assert not sc.add("keep")          # already present
    assert sc.contains("fleeting")
    assert sc.size() == 2
    time.sleep(0.25)
    assert not sc.contains("fleeting")
    assert sc.size() == 1
    assert set(sc.read_all()) == {"keep"}
    assert sc.remove("keep")
    assert not sc.remove("keep")


# ---- multimap cache (per-key TTL, RedissonMultimapCache contract) ----------


def test_set_multimap_cache_expire_key(client):
    mm = client.get_set_multimap_cache("mmc")
    mm.put("k1", "a")
    mm.put("k1", "b")
    mm.put("k2", "z")
    assert not mm.expire_key("missing", 1.0)   # only existing keys
    assert mm.expire_key("k1", 0.15)
    assert mm.get_all("k1") == {"a", "b"}      # still live
    time.sleep(0.25)
    assert mm.get_all("k1") == set()           # key expired wholesale
    assert not mm.contains_key("k1")
    assert mm.get_all("k2") == {"z"}           # untouched
    assert mm.size() == 1
    # TTL cleared before deadline keeps the key alive.
    mm.put("k3", "v")
    assert mm.expire_key("k3", 0.15)
    assert mm.expire_key("k3", 0)              # clear
    time.sleep(0.25)
    assert mm.get_all("k3") == {"v"}


def test_list_multimap_cache_expire_key(client):
    mm = client.get_list_multimap_cache("lmmc")
    mm.put("k", "a")
    mm.put("k", "a")
    assert mm.expire_key("k", 0.15)
    time.sleep(0.25)
    assert mm.get_all("k") == []
    assert mm.key_size() == 0


def test_multimap_cache_stale_ttl_does_not_kill_reinserted_key(client):
    """remove/remove_all/delete must clear the key's TTL state: a stale
    deadline must never delete a freshly re-inserted key (r3 review pins)."""
    mm = client.get_set_multimap_cache("mmc2")
    # remove_all clears the deadline
    mm.put("k", "a")
    mm.put("other", "x")          # keeps the structure alive
    assert mm.expire_key("k", 0.15)
    mm.remove_all("k")
    mm.put("k", "fresh")
    time.sleep(0.25)
    assert mm.get_all("k") == {"fresh"}
    # remove() of the last value clears the deadline too
    assert mm.expire_key("k", 0.15)
    assert mm.remove("k", "fresh")
    mm.put("k", "fresh2")
    time.sleep(0.25)
    assert mm.get_all("k") == {"fresh2"}
    # delete() clears everything including TTL state
    assert mm.expire_key("k", 0.15)
    assert mm.delete()
    mm2 = client.get_set_multimap_cache("mmc2")
    mm2.put("k", "reborn")
    time.sleep(0.25)
    assert mm2.get_all("k") == {"reborn"}


def test_multimap_cache_all_keys_expired_drops_structure(client):
    mm = client.get_set_multimap_cache("mmc3")
    mm.put("k", "v")
    assert mm.expire_key("k", 0.1)
    time.sleep(0.2)
    assert mm.key_size() == 0
    assert "mmc3" not in client.get_keys().get_keys("mmc3")


def test_list_retain_all_preserves_ttl(client):
    """retain_all is one atomic op that keeps the list's expiry (review r5:
    the old delete()+rpush rebuild dropped the TTL)."""
    l = client.get_list("lr:ttl")
    l.add_all([1, 2, 3, 4])
    l.expire(60)
    assert l.retain_all([2, 3]) is True
    assert l.read_all() == [2, 3]
    ttl = l.remain_time_to_live()
    assert ttl is not None and 0 < ttl <= 60_000


def test_set_store_ops_require_sources(client):
    """union()/diff()/intersection() with no names raise instead of wiping
    the destination (review r5)."""
    import pytest

    s = client.get_set("ss:guard")
    s.add(1)
    with pytest.raises(ValueError):
        s.union()
    with pytest.raises(ValueError):
        s.diff()
    with pytest.raises(ValueError):
        s.intersection()
    assert s.read_all() == {1}
