"""Pipelined dispatch semantics (PR 4 tentpole) + epoch read cache.

Pins the acceptance contract: per-target FIFO and read-your-writes hold at
any in-flight window, a randomized op schedule is bit-identical to the
serial (window=1) executor, shutdown drains in-flight runs and cancels
staged-but-undispatched ops without hanging, deadline expiry still fires
pre-dispatch, the cost-model EWMA converges to device-completion time (not
staging time), and the epoch-stamped read cache invalidates on write /
delete / rename / import / absorb / flushall.
"""

import queue
import random
import threading
import time
from concurrent.futures import CancelledError

import numpy as np
import pytest

from redisson_tpu.client import RedissonTPU
from redisson_tpu.config import Config, TpuConfig
from redisson_tpu.executor import CommandExecutor
from redisson_tpu.observability import ExecutorMetrics, MetricsRegistry
from redisson_tpu.serve.errors import DeadlineExceeded
from redisson_tpu.serve.policy import AdaptiveBatchPolicy, CostModel


class AsyncSimBackend:
    """Toy key-value backend with device-like asynchrony: run() commits
    state synchronously on the dispatcher (dispatch-time state, like the
    TPU tier's store swaps) but resolves futures on a worker thread after a
    simulated device delay — the shape the pipeline must stay correct
    against."""

    DISPATCH_TIME_STATE = True

    def __init__(self, device_s: float = 0.0):
        self.device_s = device_s
        self.state = {}  # target -> list of applied payloads
        self.runs = []  # (kind, target) in dispatch order
        self._q = queue.Queue()
        self._thread = threading.Thread(target=self._drain, daemon=True)
        self._thread.start()

    def run(self, kind, target, ops):
        self.runs.append((kind, target))
        staged = []
        for op in ops:
            if op.kind == "set":
                vals = self.state.setdefault(op.target, [])
                vals.append(op.payload)
                staged.append((op, len(vals)))
            elif op.kind == "get":
                # Snapshot at stage time = dispatch-time-state semantics.
                staged.append((op, list(self.state.get(op.target, []))))
            else:
                raise ValueError(op.kind)
        self._q.put(staged)

    def _drain(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            if self.device_s:
                time.sleep(self.device_s)
            for op, val in item:
                if not op.future.done():
                    op.future.set_result(val)

    def close(self):
        self._q.put(None)
        self._thread.join(timeout=5)


class WedgedBackend:
    """run() blocks until released — models a hung device call."""

    def __init__(self):
        self.release = threading.Event()
        self.entered = threading.Event()

    def run(self, kind, target, ops):
        self.entered.set()
        self.release.wait(timeout=30)
        for op in ops:
            if not op.future.done():
                op.future.set_result(None)


def make_executor(backend, window, **kw):
    return CommandExecutor(backend, inflight_runs=window, **kw)


def test_read_your_writes_window_gt1():
    backend = AsyncSimBackend(device_s=0.005)
    ex = make_executor(backend, window=4)
    try:
        futures = []
        for i in range(20):
            ex.execute_async("t", "set", i, nkeys=1)
            futures.append(ex.execute_async("t", "get", None, nkeys=1))
        for i, f in enumerate(futures):
            # The read staged right after write i must observe writes 0..i.
            assert f.result(timeout=10) == list(range(i + 1))
    finally:
        ex.shutdown()
        backend.close()


def test_per_target_fifo_resolution_order():
    backend = AsyncSimBackend(device_s=0.002)
    ex = make_executor(backend, window=4)
    resolved = []
    lock = threading.Lock()
    try:
        futs = []
        for i in range(30):
            target = f"t{i % 3}"
            f = ex.execute_async(target, "set", i, nkeys=1)
            f.add_done_callback(
                lambda _f, t=target, i=i: (lock.acquire(),
                                           resolved.append((t, i)),
                                           lock.release()))
            futs.append(f)
        for f in futs:
            f.result(timeout=10)
        per_target = {}
        for t, i in resolved:
            per_target.setdefault(t, []).append(i)
        for t, seq in per_target.items():
            assert seq == sorted(seq), f"{t} resolved out of order: {seq}"
    finally:
        ex.shutdown()
        backend.close()


def test_randomized_schedule_identical_to_serial():
    """Acceptance pin: dispatch-time-state results are bit-identical between
    the serial executor and a deep pipeline on a randomized schedule."""
    rng = random.Random(7)
    schedule = []
    for _ in range(200):
        target = f"k{rng.randrange(5)}"
        if rng.random() < 0.6:
            schedule.append((target, "set", rng.randrange(1000)))
        else:
            schedule.append((target, "get", None))

    def play(window):
        backend = AsyncSimBackend(device_s=0.001 if window > 1 else 0.0)
        ex = make_executor(backend, window=window)
        try:
            futs = [ex.execute_async(t, k, p, nkeys=1) for t, k, p in schedule]
            results = [f.result(timeout=30) for f in futs]
        finally:
            ex.shutdown()
            backend.close()
        return results, backend.state

    serial_results, serial_state = play(1)
    piped_results, piped_state = play(4)
    assert piped_results == serial_results
    assert piped_state == serial_state


def test_overlap_happens_and_window_bounds_depth():
    reg = MetricsRegistry()
    backend = AsyncSimBackend(device_s=0.02)
    ex = make_executor(backend, window=2, metrics=ExecutorMetrics(reg))
    try:
        futs = [ex.execute_async(f"t{i}", "set", i, nkeys=1)
                for i in range(10)]
        for f in futs:
            f.result(timeout=10)
        stats = ex.pipeline_stats()
        assert stats["window"] == 2
        assert stats["eager_release"] is True
        assert stats["runs_completed"] >= 10
        assert stats["overlap_ratio"] > 0.0
        depth = reg.histogram("executor.inflight_depth").snapshot()
        assert depth["max"] <= 2  # the window is a hard bound
    finally:
        ex.shutdown()
        backend.close()


def test_shutdown_drains_inflight_runs():
    backend = AsyncSimBackend(device_s=0.02)
    ex = make_executor(backend, window=4)
    futs = [ex.execute_async(f"t{i}", "set", i, nkeys=1) for i in range(6)]
    ex.shutdown(wait=True)
    backend.close()
    for f in futs:
        assert f.done()
        assert f.result(timeout=0) is not None


def test_shutdown_cancels_queued_behind_wedged_backend():
    backend = WedgedBackend()
    ex = make_executor(backend, window=1)
    a = ex.execute_async("t", "set", 0, nkeys=1)
    assert backend.entered.wait(timeout=5)
    b = ex.execute_async("t", "set", 1, nkeys=1)
    c = ex.execute_async("u", "set", 2, nkeys=1)
    t0 = time.monotonic()
    ex.shutdown(wait=True, timeout=0.5)
    assert time.monotonic() - t0 < 5.0  # bounded, no hang
    for f in (b, c):
        with pytest.raises(CancelledError):
            f.result(timeout=0)
    backend.release.set()
    a.result(timeout=10)


class ParkingBackend:
    """Non-DTS backend where bpop parks its future and a later op to the
    same target fulfils it — the redis tier's blocking-pop shape."""

    def __init__(self):
        self.parked = []

    def run(self, kind, target, ops):
        for op in ops:
            if op.kind == "bpop":
                self.parked.append(op)
            else:
                while self.parked:
                    self.parked.pop(0).future.set_result(op.payload)
                op.future.set_result(True)


def test_parked_bpop_does_not_wedge_window():
    """Regression: a parked blocking pop must release its target gate AND
    its window slot at run() return, or (window=1, non-DTS backend) the
    push that would fulfil it could never dispatch — the deadlock the
    redis-tier conformance suite hit."""
    backend = ParkingBackend()
    ex = make_executor(backend, window=1)
    try:
        take = ex.execute_async("q", "bpop", {"side": "left"}, nkeys=1)
        take2 = ex.execute_async("q2", "bpop", {"side": "left"}, nkeys=1)
        ex.execute_async("q", "set", b"v", nkeys=1)
        assert take.result(timeout=5) == b"v"
        assert take2.result(timeout=5) == b"v"  # served by the same push
        assert ex.pipeline_stats()["inflight"] == 0
    finally:
        ex.shutdown()


def test_deadline_expiry_fires_pre_dispatch():
    backend = AsyncSimBackend()
    ex = make_executor(backend, window=2)
    try:
        f = ex.execute_async("t", "set", 1, nkeys=1,
                             deadline=time.monotonic() - 1.0)
        with pytest.raises(DeadlineExceeded):
            f.result(timeout=10)
        assert backend.runs == []  # never reached the backend
    finally:
        ex.shutdown()
        backend.close()


def test_ewma_converges_to_device_time_not_staging_time():
    """Satellite regression: with async dispatch the policy's service EWMA
    must feed from completion latency (~device_s here), while the staging
    EWMA stays near the (tiny) host prep cost."""
    device_s = 0.05
    policy = AdaptiveBatchPolicy(CostModel())
    backend = AsyncSimBackend(device_s=device_s)
    ex = make_executor(backend, window=2, policy=policy)
    try:
        for i in range(12):
            ex.execute_async("t", "set", i, nkeys=1).result(timeout=10)
        est = policy.cost_model.estimate("set", 1)
        assert est > device_s / 2, (
            f"service estimate {est:.6f}s collapsed toward staging time")
        stage = policy.cost_model.snapshot()["stage_s"].get("set", 0.0)
        assert stage < device_s / 2, (
            f"staging EWMA {stage:.6f}s absorbed device time")
    finally:
        ex.shutdown()
        backend.close()


# ---------------------------------------------------------------------------
# Epoch-stamped read cache (backend_tpu.EpochReadCache) — client-level
# ---------------------------------------------------------------------------


@pytest.fixture()
def client():
    c = RedissonTPU.create(Config(tpu=TpuConfig(device_index=0)))
    yield c
    c.shutdown()


def _cache_of(client):
    return client._routing.sketch.read_cache


def test_hll_count_cached_and_invalidated_on_write(client):
    h = client.get_hyper_log_log("pipe:hll")
    h.add_all(list(range(1000)))
    first = h.count()
    hits0 = _cache_of(client).hits
    assert h.count() == first
    assert _cache_of(client).hits > hits0  # second read served from cache
    h.add_all(list(range(1000, 3000)))  # write bumps the epoch
    assert h.count() > first  # not the stale cached value


def test_bitset_cardinality_cached_and_delete_invalidates(client):
    b = client.get_bit_set("pipe:bits")
    b.set_bits([1, 5, 9, 300])
    assert b.cardinality() == 4
    hits0 = _cache_of(client).hits
    assert b.cardinality() == 4
    assert _cache_of(client).hits > hits0
    client.delete("pipe:bits")
    b2 = client.get_bit_set("pipe:bits")
    b2.set_bits([2])
    assert b2.cardinality() == 1  # delete invalidated the cached 4


def test_bloom_contains_cached_and_add_invalidates(client):
    f = client.get_bloom_filter("pipe:bloom")
    f.try_init(10_000, 0.01)
    f.add_all([b"a", b"b", b"c"])
    assert list(f.contains_all([b"a", b"b"])) == [True, True]
    hits0 = _cache_of(client).hits
    assert list(f.contains_all([b"a", b"b"])) == [True, True]
    assert _cache_of(client).hits > hits0
    # A write must invalidate: the same probe re-evaluates and d appears.
    f.add_all([b"d"])
    assert list(f.contains_all([b"d"])) == [True]


def test_rename_invalidates_both_names(client):
    h = client.get_hyper_log_log("pipe:src")
    h.add_all(list(range(500)))
    n_src = h.count()
    h.rename("pipe:dst")
    h2 = client.get_hyper_log_log("pipe:dst")
    assert abs(h2.count() - n_src) <= max(2, int(0.05 * n_src))
    # Recreated source must not serve the old cached count.
    h3 = client.get_hyper_log_log("pipe:src")
    h3.add_all([1, 2, 3])
    assert h3.count() < 100


def test_flushall_clears_epochs_and_cache(client):
    h = client.get_hyper_log_log("pipe:flush")
    h.add_all(list(range(2000)))
    h.count()
    h.count()  # populate the cache
    client.flushall()
    assert len(_cache_of(client)) == 0
    h2 = client.get_hyper_log_log("pipe:flush")
    h2.add_all([1])
    assert h2.count() <= 2  # fresh object, no stale epoch hit


def test_bits_import_invalidates(client):
    b = client.get_bit_set("pipe:imp")
    b.set_bits([0, 1, 2, 3])
    assert b.cardinality() == 4
    # Restore a smaller checkpoint over the same name (replication path).
    ex = client._executor
    arr = np.zeros((64,), np.uint8)
    arr[0] = 1
    from redisson_tpu.store import ObjectType

    ex.execute_sync("pipe:imp", "bits_import", {
        "otype": ObjectType.BITSET, "array": arr,
        "meta": {"nbits": 64, "extent_bits": 64}})
    assert b.cardinality() == 1  # import bumped the epoch


def test_read_cache_stats_exposed_in_metrics(client):
    h = client.get_hyper_log_log("pipe:metrics")
    h.add_all(list(range(100)))
    h.count()
    h.count()
    snap = client.metrics.snapshot()["gauges"]
    assert snap["backend.read_cache_hits"] >= 1
    assert 0.0 < snap["backend.read_cache_hit_ratio"] <= 1.0
