"""Bank-backed named HLLs (single-chip): the VERDICT r3 architectural fix.

Every named HLL in the TPU backend is a row of one [S, m] device bank, so
mergeWith/countWith — first-class API in the reference
(`RedissonHyperLogLog.java:40-97`), not internals — compile to ONE
gather+row-max kernel regardless of sketch count, and cross-sketch inserts
coalesce into one device call (per-key row vector, mirroring the pod tier's
bank_insert).
"""

import numpy as np
import pytest

from redisson_tpu.client import RedissonTPU
from redisson_tpu.config import Config, TpuConfig
from redisson_tpu.store import WrongTypeError


@pytest.fixture()
def client():
    c = RedissonTPU.create()
    yield c
    c.shutdown()


def _tpu_backend(c):
    return c._routing.sketch


def test_bank_grows_past_capacity():
    cfg = Config()
    cfg.use_tpu()
    c = RedissonTPU.create(cfg)
    try:
        back = _tpu_backend(c)
        back.bank_capacity = 4  # shrink so growth triggers fast
        back.bank = None
        ests = {}
        for i in range(11):  # 4 -> 8 -> 16 rows: two growths
            h = c.get_hyper_log_log(f"g:{i}")
            h.add_all([b"%d:%d" % (i, j) for j in range(200 + i)])
            ests[i] = h.count()
        assert back.bank_capacity >= 11
        # growth preserved every pre-existing row's registers
        for i in range(11):
            got = c.get_hyper_log_log(f"g:{i}").count()
            assert got == ests[i]
            assert abs(got - (200 + i)) / (200 + i) < 0.05
    finally:
        c.shutdown()


def test_merge_with_many_sketches_through_facade(client):
    # 64 sketches with distinct key spaces; union via the public API.
    per = 300
    names = []
    for s in range(64):
        h = client.get_hyper_log_log(f"m:{s}")
        h.add_all([b"%d/%d" % (s, j) for j in range(per)])
        names.append(f"m:{s}")
    dest = client.get_hyper_log_log("m:dest")
    dest.merge_with(*names)
    est = dest.count()
    true = 64 * per
    assert abs(est - true) / true < 0.03
    # count_with matches the merged estimate without mutating sources
    probe = client.get_hyper_log_log("m:0")
    est2 = probe.count_with(*[f"m:{s}" for s in range(1, 64)])
    assert abs(est2 - true) / true < 0.03
    assert abs(client.get_hyper_log_log("m:0").count() - per) / per < 0.06


def test_merge_with_and_count_fused(client):
    """Fused merge+count == merge_with();count() exactly, in one sync
    (VERDICT r4 next #3)."""
    per = 250
    names = []
    for s in range(16):
        h = client.get_hyper_log_log(f"mc:{s}")
        h.add_all([b"mc%d/%d" % (s, j) for j in range(per)])
        names.append(f"mc:{s}")
    fused = client.get_hyper_log_log("mc:fused")
    est_fused = fused.merge_with_and_count(*names)
    twostep = client.get_hyper_log_log("mc:twostep")
    twostep.merge_with(*names)
    assert est_fused == twostep.count()
    # destination registers were really written (a later count agrees)
    assert fused.count() == est_fused
    # merging on top of existing destination registers participates in max
    fused2 = client.get_hyper_log_log("mc:0")
    est2 = fused2.merge_with_and_count(*[f"mc:{s}" for s in range(1, 16)])
    assert abs(est2 - 16 * per) / (16 * per) < 0.03


def test_cross_sketch_batch_coalesces(client):
    # RBatch staging inserts for many sketches: all land in their own rows.
    batch = client.create_batch()
    per = 500
    for s in range(16):
        keys = np.arange(s * 10_000, s * 10_000 + per, dtype=np.uint64)
        batch.get_hyper_log_log(f"cb:{s}").add_ints_async(keys)
    batch.execute()
    for s in range(16):
        est = client.get_hyper_log_log(f"cb:{s}").count()
        assert abs(est - per) / per < 0.06, (s, est)


def test_changed_flag_is_per_target(client):
    """PFADD's bool is per SKETCH even in a cross-target coalesced run
    (review r4: a shared run-wide flag reported True for sketches whose
    registers did not change)."""
    client.get_hyper_log_log("ch:dup").add_all([b"d1", b"d2"])
    batch = client.create_batch()
    f_dup = batch.get_hyper_log_log("ch:dup").add_all_async([b"d1", b"d2"])
    f_new = batch.get_hyper_log_log("ch:new").add_all_async([b"n1", b"n2"])
    batch.execute()
    assert f_new.result() is True
    assert f_dup.result() is False  # all-duplicate keys: sketch unchanged


def test_wrongtype_does_not_poison_coalesced_run(client):
    """A WRONGTYPE target fails only its own ops; other targets in the same
    coalesced run succeed (review r4)."""
    client.get_bit_set("ps:bits").set(1)
    batch = client.create_batch()
    f_bad = batch.get_hyper_log_log("ps:bits").add_all_async([b"x"])
    f_ok = batch.get_hyper_log_log("ps:ok").add_all_async([b"y"])
    with pytest.raises(WrongTypeError):
        batch.execute()
    assert isinstance(f_bad.exception(), WrongTypeError)
    assert f_ok.result() is True
    assert client.get_hyper_log_log("ps:ok").count() == 1


def test_delete_frees_and_reuses_row(client):
    back = _tpu_backend(client)
    h = client.get_hyper_log_log("rr:a")
    h.add_all([b"x%d" % i for i in range(100)])
    row_a = back._rows["rr:a"]
    assert client.get_keys().delete("rr:a") == 1
    assert client.get_hyper_log_log("rr:a").count() == 0  # row was zeroed
    h2 = client.get_hyper_log_log("rr:b")
    h2.add(b"solo")
    assert back._rows["rr:b"] == row_a  # freed row reused
    assert h2.count() == 1


def test_wrongtype_both_directions(client):
    client.get_bit_set("wt:bits").set(3)
    with pytest.raises(WrongTypeError):
        client.get_hyper_log_log("wt:bits").add(b"x")
    client.get_hyper_log_log("wt:hll").add(b"x")
    with pytest.raises(WrongTypeError):
        client.get_bit_set("wt:hll").set(1)
    with pytest.raises(WrongTypeError):
        client.get_bloom_filter("wt:hll").try_init(100, 0.01)


def test_bitop_with_hll_source_raises_wrongtype(client):
    """BITOP sources that are bank HLLs must raise WRONGTYPE, not be
    silently skipped (review r4: HLLs left the store, so store.get no
    longer guards this path)."""
    client.get_hyper_log_log("bo:h").add(b"x")
    client.get_bit_set("bo:dest").set(1)
    with pytest.raises(WrongTypeError):
        client.get_bit_set("bo:dest").or_("bo:h")


def test_flushall_drops_bank(client):
    back = _tpu_backend(client)
    client.get_hyper_log_log("fa:h").add(b"k")
    assert back.bank is not None
    client.flushall()
    assert back.bank is None and not back._rows
    # lazily reallocated on next touch
    h = client.get_hyper_log_log("fa:h")
    h.add(b"k2")
    assert h.count() == 1


def test_keys_lists_bank_hlls(client):
    client.get_hyper_log_log("kl:h1").add(b"a")
    client.get_bit_set("kl:b1").set(1)
    names = set(client.get_keys().get_keys_by_pattern("kl:*"))
    assert names == {"kl:h1", "kl:b1"}


def test_hostfold_multi_target_run():
    """Force the transfer-adaptive path over a cross-sketch run: per-target
    folds absorb through ONE batched row scatter."""
    from redisson_tpu import native as native_mod

    if not native_mod.available():
        pytest.skip("native library not built")
    cfg = Config(tpu=TpuConfig(ingest="hostfold"))
    c = RedissonTPU.create(cfg)
    try:
        batch = c.create_batch()
        per = 70_000  # above HOSTFOLD_MIN_KEYS in aggregate
        for s in range(4):
            keys = np.arange(s * 1_000_000, s * 1_000_000 + per,
                             dtype=np.uint64)
            batch.get_hyper_log_log(f"hf:{s}").add_ints_async(keys)
        batch.execute()
        for s in range(4):
            est = c.get_hyper_log_log(f"hf:{s}").count()
            assert abs(est - per) / per < 0.02, (s, est)
        # same union through the facade merge
        dest = c.get_hyper_log_log("hf:dest")
        dest.merge_with(*[f"hf:{s}" for s in range(4)])
        u = dest.count()
        assert abs(u - 4 * per) / (4 * per) < 0.02
    finally:
        c.shutdown()
