"""Runtime loop-stall witness (PR 17): install/uninstall hygiene, stall
attribution to call sites, gauges, reset, and snapshot merging.

The witness mirrors the lock witness from the Tier C work: a single
monkeypatch of asyncio's Handle._run, per-callback hold times with
deterministic p99 sampling, and a heartbeat that measures scheduling lag
— the user-visible symptom of a blocked loop."""

import asyncio
import asyncio.events
import copy
import threading
import time

import pytest

from redisson_tpu import loopwitness as lw


@pytest.fixture
def io_loop():
    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    yield loop
    lw.uninstall()
    loop.call_soon_threadsafe(loop.stop)
    t.join(timeout=5)
    loop.close()
    lw.loop_witness_reset()


def _spin(loop):
    """One loop round-trip so queued callbacks have run."""
    asyncio.run_coroutine_threadsafe(asyncio.sleep(0), loop).result(5)


def test_witness_disabled_by_default(io_loop, monkeypatch):
    monkeypatch.delenv(lw.ENV_FLAG, raising=False)
    orig = asyncio.events.Handle._run
    assert lw.watch_loop(io_loop, "off") is False
    assert asyncio.events.Handle._run is orig  # nothing patched
    assert lw.loop_gauges(io_loop) == {"loop_lag_p99_us": 0,
                                       "loop_stalls": 0}


def test_witness_stall_attribution_and_uninstall(io_loop):
    orig = asyncio.events.Handle._run
    assert lw.watch_loop(io_loop, "unit", force=True) is True
    assert asyncio.events.Handle._run is not orig

    def block_the_loop():
        time.sleep(0.05)  # > 20ms default stall threshold

    _spin(io_loop)
    io_loop.call_soon_threadsafe(block_the_loop)
    _spin(io_loop)

    gauges = lw.loop_gauges(io_loop)
    assert gauges["loop_stalls"] >= 1
    assert gauges["loop_lag_p99_us"] >= 0

    snap = lw.loop_witness_snapshot()
    assert "unit" in snap["loops"]
    stats = snap["loops"]["unit"]
    assert any("block_the_loop" in s["site"] and s["ms"] >= 40.0
               for s in stats["stalls"]), stats["stalls"]
    assert any(site.startswith("cb:") and "block_the_loop" in site
               for site in stats["callbacks"]), list(stats["callbacks"])
    assert stats["stall_threshold_ms"] == pytest.approx(20.0)

    # uninstall restores the pristine Handle._run and forgets the loop
    lw.uninstall()
    assert asyncio.events.Handle._run is orig
    assert lw._ORIG_RUN is None
    assert lw.loop_gauges(io_loop) == {"loop_lag_p99_us": 0,
                                       "loop_stalls": 0}


def test_witness_task_sites_and_heartbeat(io_loop):
    assert lw.watch_loop(io_loop, "hb", force=True)

    async def worker():
        for _ in range(3):
            await asyncio.sleep(0.005)

    asyncio.run_coroutine_threadsafe(worker(), io_loop).result(5)
    time.sleep(0.05)  # let a few heartbeats land
    stats = lw.loop_witness_snapshot()["loops"]["hb"]
    assert stats["lag"]["beats"] >= 2
    assert any(site.startswith("task:") and "worker" in site
               for site in stats["callbacks"]), list(stats["callbacks"])


def test_witness_reset_keeps_loop_watched(io_loop):
    assert lw.watch_loop(io_loop, "reset", force=True)

    def stall():
        time.sleep(0.03)

    io_loop.call_soon_threadsafe(stall)
    _spin(io_loop)
    assert lw.loop_gauges(io_loop)["loop_stalls"] >= 1

    lw.loop_witness_reset()
    assert lw.loop_gauges(io_loop) == {"loop_lag_p99_us": 0,
                                       "loop_stalls": 0}
    # still watched: a fresh stall is recorded post-reset
    io_loop.call_soon_threadsafe(stall)
    _spin(io_loop)
    assert lw.loop_gauges(io_loop)["loop_stalls"] >= 1


def test_witness_unwatch_retires_stats(io_loop):
    assert lw.watch_loop(io_loop, "retired", force=True)
    _spin(io_loop)
    lw.unwatch_loop(io_loop)
    # gauges go to zero (loop no longer live-watched)...
    assert lw.loop_gauges(io_loop) == {"loop_lag_p99_us": 0,
                                       "loop_stalls": 0}
    # ...but the stats stay visible to the end-of-run snapshot
    assert "retired" in lw.loop_witness_snapshot()["loops"]


def test_merge_loop_snapshots():
    a = {"version": 1, "loops": {"x": {
        "callbacks": {"cb:f (m.py)": {"runs": 1, "total_s": 0.1,
                                      "max_s": 0.1, "p99_s": 0.1}},
        "lag": {"beats": 10, "max_s": 0.01, "p99_s": 0.005},
        "stalls": [{"site": "cb:f (m.py)", "ms": 50.0}],
        "stall_threshold_ms": 20.0,
    }}}
    b = copy.deepcopy(a)
    b["loops"]["x"]["callbacks"]["cb:f (m.py)"] = {
        "runs": 2, "total_s": 0.3, "max_s": 0.2, "p99_s": 0.15}
    b["loops"]["x"]["lag"] = {"beats": 5, "max_s": 0.03, "p99_s": 0.001}
    b["loops"]["x"]["stalls"] = [{"site": "cb:g (m.py)", "ms": 75.0}]
    b["loops"]["y"] = copy.deepcopy(a["loops"]["x"])

    m = lw.merge_loop_snapshots([a, b])
    x = m["loops"]["x"]
    cb = x["callbacks"]["cb:f (m.py)"]
    assert cb["runs"] == 3
    assert cb["total_s"] == pytest.approx(0.4)
    assert cb["max_s"] == pytest.approx(0.2)
    assert cb["p99_s"] == pytest.approx(0.15)
    assert x["lag"]["beats"] == 15
    assert x["lag"]["max_s"] == pytest.approx(0.03)
    assert len(x["stalls"]) == 2
    assert "y" in m["loops"]  # loop present in only one snapshot survives


def test_dump_writes_mergeable_json(io_loop, tmp_path):
    assert lw.watch_loop(io_loop, "dumped", force=True)
    _spin(io_loop)
    out = tmp_path / "witness.json"
    lw.dump_loop_witness(str(out))
    import json

    snap = json.loads(out.read_text())
    assert snap["version"] == 1
    assert "dumped" in snap["loops"]
    # round-trips through the merge helper unchanged in shape
    merged = lw.merge_loop_snapshots([snap, snap])
    assert "dumped" in merged["loops"]
