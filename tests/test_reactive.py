"""Reactive (asyncio) API tests — the async mirror of the object surface.
Mirrors the reference's Base*ReactiveTest suites (SURVEY.md §4)."""

import asyncio

import pytest

from redisson_tpu.reactive import (AsyncProxy, RedissonTPUReactive,
                                   create_reactive)


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


@pytest.fixture()
def rx():
    client = create_reactive()
    yield client
    client.sync.shutdown()


def test_hll_async(rx):
    async def go():
        h = rx.get_hyper_log_log("rx:hll")
        assert isinstance(h, AsyncProxy)
        await h.add_all([b"k%d" % i for i in range(5000)])
        est = await h.count()
        assert abs(est - 5000) / 5000 < 0.05
        await h.add(b"one-more")
        h2 = rx.get_hyper_log_log("rx:hll2")
        await h2.add_all([b"x%d" % i for i in range(100)])
        union = await h.count_with("rx:hll2")
        assert union >= est
    run(go())


def test_bitset_bloom_async(rx):
    async def go():
        bs = rx.get_bit_set("rx:bits")
        await bs.set(5)
        assert await bs.get(5)
        assert not await bs.get(6)
        assert await bs.cardinality() == 1

        bf = rx.get_bloom_filter("rx:bloom")
        await bf.try_init(expected_insertions=1000, false_probability=0.01)
        await bf.add(b"hello")
        assert await bf.contains(b"hello")
    run(go())


def test_map_and_iteration(rx):
    async def go():
        m = rx.get_map("rx:map")
        await m.put("a", 1)
        await m.put("b", 2)
        assert await m.get("a") == 1
        assert await m.size() == 2
        keys = set()
        async for k in m:
            keys.add(k)
        assert keys == {"a", "b"}
    run(go())


def test_concurrent_ops_interleave(rx):
    async def go():
        # Many concurrent coroutines against one object: all complete,
        # totals add up (per-object FIFO order preserved by the executor).
        counter = rx.get_atomic_long("rx:ctr")
        await asyncio.gather(*(counter.increment_and_get() for _ in range(50)))
        assert await counter.get() == 50
    run(go())


def test_async_lock_context_manager(rx):
    async def go():
        lock = rx.get_lock("rx:lock")
        async with lock:
            assert await lock.is_locked()
        assert not await lock.is_locked()
    run(go())


def test_blocking_queue_producer_consumer(rx):
    async def go():
        q = rx.get_blocking_queue("rx:bq")

        async def producer():
            await asyncio.sleep(0.05)
            await q.offer("payload")

        async def consumer():
            return await q.take()  # runs off-loop; must not block the loop

        got, _ = await asyncio.gather(consumer(), producer())
        assert got == "payload"
    run(go())


def test_topic_pubsub_async(rx):
    async def go():
        topic = rx.get_topic("rx:topic")
        seen = []
        await topic.add_listener(lambda ch, msg: seen.append(msg))
        receivers = await topic.publish("hello")
        assert receivers == 1
        deadline = asyncio.get_event_loop().time() + 2
        while not seen and asyncio.get_event_loop().time() < deadline:
            await asyncio.sleep(0.01)
        assert seen == ["hello"]
    run(go())


def test_facade_keys_flushall(rx):
    async def go():
        await rx.get_bucket("rx:b1").set(1)
        await rx.get_bucket("rx:b2").set(2)
        ks = await rx.keys("rx:b*")
        assert set(ks) == {"rx:b1", "rx:b2"}
        assert await rx.delete("rx:b1")
        await rx.flushall()
        assert await rx.keys() == []
    run(go())


def test_sync_escape_hatch(rx):
    h = rx.get_hyper_log_log("rx:sync")
    h.sync.add(b"v")  # the underlying sync object stays usable
    assert h.sync.count() == 1


def test_batch_async(rx):
    async def go():
        b = rx.create_batch()
        sb = b.sync
        # Staging is async-only (like the reference's RBatch *Async clones).
        sb.get_hyper_log_log("rx:bt").add_all_async([b"a", b"b", b"c"])
        sb.get_bit_set("rx:bb").set_bits_async([7])
        results = await b.execute()
        assert len(results) == 2
        assert await rx.get_bit_set("rx:bb").get(7)
    run(go())


def test_async_lock_owner_is_per_task_not_per_thread(rx):
    # Lock ops run via a shared to_thread pool; ownership must follow the
    # asyncio TASK (owner_context), not whichever worker thread serves the
    # call. Acquire/release inside one task while the pool churns.
    async def go():
        async def churn(i):
            b = rx.get_bucket(f"rx:churn{i}")
            await b.set(i)
            return await b.get()

        lock = rx.get_lock("rx:aff")

        async def lock_cycle():
            for _ in range(5):
                await lock.lock()
                assert await lock.is_locked()
                await lock.unlock()

        await asyncio.gather(lock_cycle(),
                             asyncio.gather(*(churn(i) for i in range(16))))
        assert not await lock.is_locked()
    run(go())


def test_async_lock_mutual_exclusion_between_tasks(rx):
    # Two tasks sharing ONE AsyncLock instance must exclude each other —
    # the regression where a pinned thread gave every task the same owner.
    async def go():
        lock = rx.get_lock("rx:mx")
        inside = []

        async def critical(tag):
            async with lock:
                inside.append(tag)
                assert len(inside) == 1, "both tasks inside the lock!"
                await asyncio.sleep(0.05)
                inside.remove(tag)

        await asyncio.gather(critical("a"), critical("b"))
        assert not await lock.is_locked()
    run(go())


def test_async_rw_lock(rx):
    async def go():
        rw = rx.get_read_write_lock("rx:rw")
        r = rw.read_lock()
        await r.lock()
        assert await r.is_locked()
        await r.unlock()
        w = rw.write_lock()
        async with w:
            assert await w.is_locked()
        assert not await w.is_locked()
    run(go())


def test_map_cache_async_iteration(rx):
    async def go():
        mc = rx.get_map_cache("rx:mc")
        await mc.put("x", 1)
        await mc.put("y", 2)
        seen = set()
        async for k in mc:
            seen.add(k)
        assert seen == {"x", "y"}
    run(go())


def test_lock_instances_share_ownership_by_task(rx):
    # Fresh AsyncLock proxies over the same name still agree on ownership
    # (owner = client:task, not instance identity).
    async def go():
        a = rx.get_lock("same")
        b = rx.get_lock("same")
        await a.lock()
        assert await b.is_locked()
        await b.unlock()  # same task, same owner -> valid release
        assert not await a.is_locked()
    run(go())
