"""QoS serving layer: admission, deadlines, adaptive batching, retry,
breakers — plus the executor regressions that rode along (shutdown
cancellation sweep, global-steal iteration, RBatch concurrency).

Acceptance pins (ISSUE PR 3):
  (a) an op whose deadline already passed completes with DeadlineExceeded
      and NEVER reaches backend.run;
  (b) offered load > capacity against a bounded queue sheds (>0) while the
      ADMITTED ops' p99 queueing delay stays under the configured budget —
      fake clock, fully deterministic;
  (c) the breaker opens after N consecutive faults, fails fast while open,
      half-opens after the reset timeout, and recovers on probe success;
  (d) two tenants with equal rate limits land within 2x of each other's
      admitted throughput when one offers 100x more ops.
"""

import threading
import time
import types
from concurrent.futures import CancelledError

import pytest

from redisson_tpu.config import Config, ServeConfig
from redisson_tpu.executor import CommandExecutor
from redisson_tpu.observability import ExecutorMetrics, MetricsRegistry
from redisson_tpu.serve import (AdaptiveBatchPolicy, AdmissionController,
                                CircuitBreaker, CircuitOpenError, CostModel,
                                DeadlineExceeded, RejectedError,
                                RetryableError, ServingLayer, TokenBucket)


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class RecordingBackend:
    """Instant backend: records every run, resolves futures with payload."""

    def __init__(self):
        self.calls = []
        self.lock = threading.Lock()

    def run(self, kind, target, ops):
        with self.lock:
            self.calls.append((kind, target, [op.target for op in ops]))
        for op in ops:
            op.future.set_result(op.payload)


def _serve(backend, cfg, clock=None, policy=None, registry=None):
    ex = CommandExecutor(backend, policy=policy, clock=clock)
    reg = registry or MetricsRegistry()
    return ServingLayer(ex, cfg, registry=reg), ex, reg


# ---------------------------------------------------------------------------
# (a) deadline propagation
# ---------------------------------------------------------------------------

def test_expired_deadline_never_reaches_backend():
    clock = FakeClock(100.0)
    backend = RecordingBackend()
    ex = CommandExecutor(backend, clock=clock)
    try:
        f = ex.execute_async("t", "noop", "v", deadline=99.0)
        with pytest.raises(DeadlineExceeded):
            f.result(timeout=5)
        assert backend.calls == []  # pre-dispatch filter, no device time
        # a live op behind it still dispatches
        assert ex.execute_async("t", "noop", "w").result(timeout=5) == "w"
    finally:
        ex.shutdown()


def test_serve_expired_deadline_fails_before_submission():
    clock = FakeClock(50.0)
    backend = RecordingBackend()
    serve, ex, reg = _serve(backend, ServeConfig(retry_attempts=0), clock=clock)
    try:
        f = serve.execute_async("t", "noop", "v", deadline=49.0)
        assert f.done()  # failed synchronously, never enqueued
        with pytest.raises(DeadlineExceeded):
            f.result()
        assert backend.calls == []
        assert ex.queue_depth() == 0
        assert reg.counter("serve.deadline_expired_total") == 1
    finally:
        serve.shutdown()


def test_serve_timeout_s_stamps_absolute_deadline():
    clock = FakeClock(10.0)
    backend = RecordingBackend()
    serve, _, _ = _serve(backend, ServeConfig(retry_attempts=0), clock=clock)
    try:
        # ample budget: completes fine
        assert serve.execute_async("t", "noop", "x",
                                   timeout_s=5.0).result(timeout=5) == "x"
        # timeout_s=0 / default_timeout_ms=0 would mean no deadline at all
        assert serve._resolve_deadline(10.0, None, 0) is None
        assert serve._resolve_deadline(10.0, None, 2.5) == 12.5
        assert serve._resolve_deadline(10.0, 11.0, 2.5) == 11.0
    finally:
        serve.shutdown()


# ---------------------------------------------------------------------------
# (b) shed under overload, admitted p99 within budget (deterministic)
# ---------------------------------------------------------------------------

def test_overload_sheds_while_admitted_p99_stays_under_budget():
    """Offered load 2x capacity against a delay-bounded queue: the delay
    gate sheds the excess and every ADMITTED op waits <= the budget.
    Simulated server + fake clock, no threads, no wall time."""
    budget_s = 0.010
    s_per_key = 1e-6  # capacity: 1e6 keys/s
    cm = CostModel(default_s_per_key=s_per_key, default_overhead_s=0.0)
    adm = AdmissionController(cost_model=cm, max_queue_ops=100_000,
                              max_queue_delay_s=budget_s)

    op_keys = 1000          # 1ms service per op
    arrival_dt = 0.0005     # 2000 ops/s offered = 2x capacity
    now = 0.0
    server_free_at = 0.0    # single-server FIFO drain
    in_service = []         # (finish_time, nkeys) not yet released
    delays = []
    shed = 0
    for _ in range(4000):   # 2 simulated seconds
        now += arrival_dt
        while in_service and in_service[0][0] <= now:
            adm.release(in_service.pop(0)[1])
        try:
            adm.admit("tenant", "k", op_keys, now)
        except RejectedError as exc:
            shed += 1
            assert exc.retry_after_s > 0.0
            continue
        start = max(now, server_free_at)
        delays.append(start - now)
        server_free_at = start + op_keys * s_per_key
        in_service.append((server_free_at, op_keys))

    assert shed > 0
    assert len(delays) > 0
    p99 = sorted(delays)[int(0.99 * (len(delays) - 1))]
    assert p99 <= budget_s + 1e-9, f"p99 {p99 * 1e3:.2f}ms over budget"
    # roughly half the offered load fits: shedding is doing real work,
    # not rejecting everything
    assert 0.2 < shed / 4000 < 0.8
    snap = adm.snapshot(now)
    assert snap["shed_by_reason"].get("queue_delay", 0) == shed


def test_queue_depth_watermark_sheds_with_retry_after():
    adm = AdmissionController(max_queue_ops=2)
    adm.admit("t", "k", 1, now=0.0)
    adm.admit("t", "k", 1, now=0.0)
    with pytest.raises(RejectedError) as ei:
        adm.admit("t", "k", 1, now=0.0)
    assert ei.value.reason == "queue_depth"
    adm.release(1)
    adm.admit("t", "k", 1, now=0.0)  # freed capacity admits again


# ---------------------------------------------------------------------------
# (c) circuit breaker
# ---------------------------------------------------------------------------

def test_breaker_state_machine_fake_clock():
    br = CircuitBreaker(failure_threshold=3, reset_timeout_s=1.0,
                        half_open_probes=1)
    for _ in range(3):
        br.allow(now=0.0)
        br.on_failure(now=0.0)
    assert br.state == "open"
    with pytest.raises(CircuitOpenError) as ei:
        br.allow(now=0.5)  # fail fast while open
    assert ei.value.retry_after_s == pytest.approx(0.5)
    # reset elapsed: half-open, one probe slot
    br.allow(now=1.5)
    assert br.state == "half_open"
    with pytest.raises(CircuitOpenError):
        br.allow(now=1.5)  # probe quota in flight
    br.on_success(now=1.6)
    assert br.state == "closed"
    br.allow(now=1.7)  # closed admits freely


def test_breaker_failed_probe_reopens():
    br = CircuitBreaker(failure_threshold=2, reset_timeout_s=1.0)
    for _ in range(2):
        br.allow(now=0.0)
        br.on_failure(now=0.0)
    br.allow(now=1.5)  # half-open probe
    br.on_failure(now=1.5)
    assert br.state == "open"
    with pytest.raises(CircuitOpenError):
        br.allow(now=2.0)  # wait restarted from t=1.5
    br.allow(now=2.6)
    br.on_success(now=2.6)
    assert br.state == "closed"


def test_breaker_release_probe_returns_slot():
    br = CircuitBreaker(failure_threshold=1, reset_timeout_s=1.0)
    br.allow(now=0.0)
    br.on_failure(now=0.0)
    br.allow(now=1.5)  # takes the probe slot
    br.release_probe()  # op shed before the backend: slot returned
    br.allow(now=1.5)  # slot available again
    br.on_success(now=1.5)
    assert br.state == "closed"


class FlakyBackend:
    """Fails the first `fail_n` runs, then succeeds."""

    def __init__(self, fail_n, exc_factory=lambda: RetryableError("flap")):
        self.fail_n = fail_n
        self.calls = 0
        self.exc_factory = exc_factory

    def run(self, kind, target, ops):
        self.calls += 1
        if self.calls <= self.fail_n:
            exc = self.exc_factory()
            for op in ops:
                op.future.set_exception(exc)
            return
        for op in ops:
            op.future.set_result(op.payload)


def test_breaker_end_to_end_open_fast_fail_half_open_recover():
    backend = FlakyBackend(3, exc_factory=lambda: ValueError("down"))
    cfg = ServeConfig(retry_attempts=0, breaker_failure_threshold=3,
                      breaker_reset_timeout_ms=80, default_timeout_ms=0)
    serve, _, reg = _serve(backend, cfg)
    try:
        for _ in range(3):
            with pytest.raises(ValueError):
                serve.execute_async("t", "noop", "x").result(timeout=5)
        assert backend.calls == 3
        # open: the next op fails fast without touching the backend
        with pytest.raises(CircuitOpenError):
            serve.execute_async("t", "noop", "x").result(timeout=5)
        assert backend.calls == 3
        assert reg.counter("serve.breaker_rejected_total") == 1
        assert serve.snapshot()["breakers"]["noop"]["state"] == "open"
        time.sleep(0.12)  # past the reset timeout: half-open probe admitted
        assert serve.execute_async("t", "noop", "ok").result(timeout=5) == "ok"
        assert serve.snapshot()["breakers"]["noop"]["state"] == "closed"
        assert serve.execute_async("t", "noop", "ok2").result(timeout=5) == "ok2"
    finally:
        serve.shutdown()


# ---------------------------------------------------------------------------
# (d) tenant fairness under 100x offered-load skew
# ---------------------------------------------------------------------------

def test_equal_rate_tenants_within_2x_under_100x_skew():
    clock = FakeClock()
    backend = RecordingBackend()
    cfg = ServeConfig(
        tenant_rates={"a": 1000.0, "b": 1000.0},   # keys/s each
        tenant_bursts={"a": 10.0, "b": 10.0},
        default_timeout_ms=0, retry_attempts=0, max_queue_ops=1_000_000)
    serve, _, reg = _serve(backend, cfg, clock=clock)
    futs = {"a": [], "b": []}
    try:
        for _ in range(100):  # 1 simulated second, 10ms steps
            clock.advance(0.01)
            for i in range(100):  # tenant a offers 100x tenant b's rate
                futs["a"].append(serve.execute_async(
                    "t", "noop", i, nkeys=10, tenant="a"))
            futs["b"].append(serve.execute_async(
                "t", "noop", 0, nkeys=10, tenant="b"))
        ok = {}
        for tenant, fs in futs.items():
            n = 0
            for f in fs:
                try:
                    f.result(timeout=5)
                    n += 1
                except RejectedError as exc:
                    assert exc.reason == "tenant_rate"
            ok[tenant] = n
        assert ok["a"] > 0 and ok["b"] > 0
        ratio = max(ok["a"], ok["b"]) / min(ok["a"], ok["b"])
        assert ratio <= 2.0, f"throughput skew {ratio:.2f}x ({ok})"
        assert reg.counter("serve.shed.tenant_rate") > 0
    finally:
        serve.shutdown()


def test_tenant_context_manager_tags_submissions():
    clock = FakeClock()
    backend = RecordingBackend()
    cfg = ServeConfig(tenant_rates={"noisy": 1.0}, tenant_bursts={"noisy": 1.0},
                      default_timeout_ms=0, retry_attempts=0)
    serve, _, _ = _serve(backend, cfg, clock=clock)
    try:
        with serve.tenant("noisy"):
            assert serve.execute_async("t", "noop", 1, nkeys=1) \
                .result(timeout=5) == 1
            f = serve.execute_async("t", "noop", 2, nkeys=1)  # bucket empty
        with pytest.raises(RejectedError):
            f.result(timeout=5)
        # outside the context: default tenant, unlimited
        assert serve.execute_async("t", "noop", 3, nkeys=1) \
            .result(timeout=5) == 3
    finally:
        serve.shutdown()


def test_token_bucket_refill_and_retry_after():
    b = TokenBucket(rate=100.0, burst=10.0)
    assert b.try_acquire(10.0, now=0.0)
    assert not b.try_acquire(5.0, now=0.0)
    assert b.time_to_tokens(5.0, now=0.0) == pytest.approx(0.05)
    assert b.try_acquire(5.0, now=0.06)  # refilled 6 tokens
    assert b.level(now=1.0) == pytest.approx(10.0)  # capped at burst


# ---------------------------------------------------------------------------
# retry with backoff
# ---------------------------------------------------------------------------

def test_retryable_fault_retries_to_success():
    backend = FlakyBackend(2)
    cfg = ServeConfig(retry_attempts=3, retry_interval_ms=1,
                      breaker_failure_threshold=50, default_timeout_ms=0)
    serve, _, reg = _serve(backend, cfg)
    try:
        assert serve.execute_async("t", "noop", "v").result(timeout=5) == "v"
        assert backend.calls == 3
        assert reg.counter("serve.retries_total") == 2
        assert reg.counter("serve.retry_exhausted_total") == 0
    finally:
        serve.shutdown()


def test_retry_exhaustion_surfaces_the_fault():
    backend = FlakyBackend(100)
    cfg = ServeConfig(retry_attempts=2, retry_interval_ms=1,
                      breaker_failure_threshold=50, default_timeout_ms=0)
    serve, _, reg = _serve(backend, cfg)
    try:
        with pytest.raises(RetryableError):
            serve.execute_async("t", "noop", "v").result(timeout=5)
        assert backend.calls == 3  # initial + 2 retries
        assert reg.counter("serve.retry_exhausted_total") == 1
    finally:
        serve.shutdown()


def test_non_retryable_fault_fails_immediately():
    backend = FlakyBackend(100, exc_factory=lambda: ValueError("hard"))
    cfg = ServeConfig(retry_attempts=3, retry_interval_ms=1,
                      breaker_failure_threshold=50, default_timeout_ms=0)
    serve, _, _ = _serve(backend, cfg)
    try:
        with pytest.raises(ValueError):
            serve.execute_async("t", "noop", "v").result(timeout=5)
        assert backend.calls == 1
    finally:
        serve.shutdown()


def test_retries_do_not_recharge_tenant_tokens():
    backend = FlakyBackend(2)
    cfg = ServeConfig(retry_attempts=3, retry_interval_ms=1,
                      breaker_failure_threshold=50, default_timeout_ms=0,
                      tenant_rates={"t1": 1.0}, tenant_bursts={"t1": 1.0})
    serve, _, _ = _serve(backend, cfg)
    try:
        # one token in the bucket: the op (and both its retries) cost 1 total
        f = serve.execute_async("t", "noop", "v", nkeys=1, tenant="t1")
        assert f.result(timeout=5) == "v"
        assert backend.calls == 3
    finally:
        serve.shutdown()


# ---------------------------------------------------------------------------
# cost model + adaptive policy
# ---------------------------------------------------------------------------

def test_cost_model_learns_per_key_rate():
    cm = CostModel(alpha=1.0, default_overhead_s=0.0)
    cm.observe("hll_add", 1_000_000, 0.01)
    assert cm.s_per_key("hll_add") == pytest.approx(1e-8)
    assert cm.estimate("hll_add", 2_000_000) == pytest.approx(0.02)
    # unmeasured kinds fall back to the generic cross-kind rate
    assert cm.s_per_key("bloom_add") == pytest.approx(1e-8)


def test_adaptive_batch_key_limit_tracks_target_service_time():
    cm = CostModel(alpha=1.0, default_overhead_s=0.0)
    cm.observe("k", 1_000_000, 0.01)  # 10ns/key
    tight = AdaptiveBatchPolicy(cm, target_batch_service_s=0.001,
                                min_batch_keys=64)
    loose = AdaptiveBatchPolicy(cm, target_batch_service_s=0.010,
                                min_batch_keys=64)
    cap = 1 << 21
    t, l = tight.batch_key_limit("k", cap), loose.batch_key_limit("k", cap)
    assert 64 <= t < l <= cap
    assert t == pytest.approx(100_000, rel=0.01)


def test_adaptive_linger_bounded_by_deadline_slack():
    cm = CostModel(default_s_per_key=0.0, default_overhead_s=0.0)
    pol = AdaptiveBatchPolicy(cm, max_linger_s=0.1, min_batch_keys=1)
    mk = lambda enq, dl: types.SimpleNamespace(enqueued_at=enq, deadline=dl,
                                               nkeys=1)
    # no deadlines: age bound only
    assert pol.linger_s("k", 1, 100, [mk(10.0, None)], now=10.02) \
        == pytest.approx(0.08)
    # a tight member deadline closes the batch earlier than max_linger
    assert pol.linger_s("k", 1, 100, [mk(10.0, None), mk(10.0, 10.03)],
                        now=10.0) == pytest.approx(0.03)
    # batch full: dispatch now
    assert pol.linger_s("k", 100, 100, [mk(10.0, None)], now=10.0) == 0.0


def test_adaptive_linger_coalesces_late_arrival_into_one_dispatch():
    backend = RecordingBackend()
    pol = AdaptiveBatchPolicy(CostModel(), max_linger_s=0.5,
                              target_batch_service_s=1.0, min_batch_keys=10)
    ex = CommandExecutor(backend, policy=pol)
    try:
        f1 = ex.execute_async("t", "bitset_set", "a", nkeys=1)
        time.sleep(0.1)  # within the linger window
        f2 = ex.execute_async("t", "bitset_set", "b", nkeys=1)
        assert f1.result(timeout=5) == "a"
        assert f2.result(timeout=5) == "b"
        runs = [c for c in backend.calls if c[0] == "bitset_set"]
        assert len(runs) == 1 and len(runs[0][2]) == 2, (
            "the late arrival should have joined the lingering batch")
    finally:
        ex.shutdown()


# ---------------------------------------------------------------------------
# executor regressions: shutdown sweep + global steal + round-robin
# ---------------------------------------------------------------------------

class GatedBackend:
    """First run blocks until released; later runs are instant."""

    def __init__(self, global_kinds=()):
        self.GLOBAL_COALESCE = frozenset(global_kinds)
        self.entered = threading.Event()
        self.release = threading.Event()
        self.calls = []
        self._first = True

    def run(self, kind, target, ops):
        if self._first:
            self._first = False
            self.entered.set()
            self.release.wait(10)
        self.calls.append((kind, target, [op.target for op in ops]))
        for op in ops:
            op.future.set_result(op.payload)


def test_shutdown_cancels_queued_ops_behind_a_wedged_backend():
    backend = GatedBackend()
    ex = CommandExecutor(backend)
    f1 = ex.execute_async("a", "noop", "in-flight")
    assert backend.entered.wait(5)
    f2 = ex.execute_async("b", "noop", "stranded")
    ex.shutdown(wait=True, timeout=0.2)  # join times out: sweep runs
    with pytest.raises(CancelledError):
        f2.result(timeout=1)
    backend.release.set()  # the in-flight run still completes normally
    assert f1.result(timeout=5) == "in-flight"


def test_shutdown_sweep_records_cancelled_metric():
    backend = GatedBackend()
    metrics = ExecutorMetrics()
    ex = CommandExecutor(backend, metrics=metrics)
    ex.execute_async("a", "noop", "x")
    assert backend.entered.wait(5)
    stranded = [ex.execute_async("b", "noop", i) for i in range(3)]
    ex.shutdown(wait=True, timeout=0.2)
    for f in stranded:
        with pytest.raises(CancelledError):
            f.result(timeout=1)
    assert metrics.registry.counter("executor.cancelled_total") == 3
    backend.release.set()


def test_global_steal_interleaved_with_submissions_keeps_all_targets():
    """Cross-target steal empties some queues mid-scan; the round-robin and
    queue map must stay consistent (regression: mutating _ready while
    iterating dropped targets / crashed the dispatcher)."""
    backend = GatedBackend(global_kinds=("gk",))
    ex = CommandExecutor(backend)
    try:
        blocker = ex.execute_async("z", "blk", "hold")
        assert backend.entered.wait(5)
        futs = []
        futs.append(ex.execute_async("t1", "gk", "t1", nkeys=1))
        futs.append(ex.execute_async("t2", "gk", "t2", nkeys=1))
        other = ex.execute_async("t2", "other", "t2-other")  # survives steal
        futs.append(ex.execute_async("t3", "gk", "t3", nkeys=1))
        futs.append(ex.execute_async("t4", "gk", "t4a", nkeys=1))
        futs.append(ex.execute_async("t4", "gk", "t4b", nkeys=1))
        futs.append(ex.execute_async("t5", "gk", "t5", nkeys=1))
        backend.release.set()
        assert blocker.result(timeout=5) == "hold"
        assert [f.result(timeout=5) for f in futs] == \
            ["t1", "t2", "t3", "t4a", "t4b", "t5"]
        assert other.result(timeout=5) == "t2-other"
        gk_runs = [c for c in backend.calls if c[0] == "gk"]
        assert len(gk_runs) == 1  # one steal collected every head
        assert gk_runs[0][2] == ["t1", "t2", "t3", "t4", "t4", "t5"]
        # the dispatcher survived: a fresh op still completes
        assert ex.execute_async("t9", "noop", "alive").result(timeout=5) \
            == "alive"
    finally:
        ex.shutdown()


def test_round_robin_interleaves_targets():
    backend = GatedBackend()
    ex = CommandExecutor(backend)
    try:
        blocker = ex.execute_async("z", "blk", "hold")
        assert backend.entered.wait(5)
        fa = [ex.execute_async("A", "k", f"a{i}") for i in range(3)]
        fb = [ex.execute_async("B", "k", f"b{i}") for i in range(3)]
        backend.release.set()
        blocker.result(timeout=5)
        for f in fa + fb:
            f.result(timeout=5)
        order = [c[1] for c in backend.calls if c[0] == "k"]
        assert order == ["A", "B", "A", "B", "A", "B"]
    finally:
        ex.shutdown()


# ---------------------------------------------------------------------------
# RBatch / BatchCollector under concurrency (satellite s3)
# ---------------------------------------------------------------------------

def test_concurrent_batch_collectors_resolve_in_submission_order():
    backend = RecordingBackend()
    ex = CommandExecutor(backend)
    done_log = []
    log_lock = threading.Lock()
    errors = []

    def worker(tid):
        try:
            batch = ex.batch()
            staged = [batch.add("shared", "bitset_set", (tid, i), nkeys=1)
                      for i in range(20)]
            for i, sf in enumerate(staged):
                sf.add_done_callback(
                    lambda f, tid=tid, i=i: _log(tid, i))
            outs = batch.execute_async()
            for i, f in enumerate(outs):
                assert f.result(timeout=10) == (tid, i)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def _log(tid, i):
        with log_lock:
            done_log.append((tid, i))

    try:
        threads = [threading.Thread(target=worker, args=(t,)) for t in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors
        # execute_many enqueues each batch contiguously under one lock, so
        # every caller's StagedFutures resolve in its own submission order
        per_tid = {}
        for tid, i in done_log:
            per_tid.setdefault(tid, []).append(i)
        for tid, seq in per_tid.items():
            assert seq == sorted(seq), f"thread {tid} resolved out of order"
        assert sum(len(s) for s in per_tid.values()) == 120
    finally:
        ex.shutdown()


def test_staged_future_result_before_execute_raises():
    backend = RecordingBackend()
    ex = CommandExecutor(backend)
    try:
        batch = ex.batch()
        sf = batch.add("t", "noop", 1)
        with pytest.raises(RuntimeError, match="not executed"):
            sf.result()
        assert batch.execute() == [1]
        assert sf.result(timeout=5) == 1
    finally:
        ex.shutdown()


def test_serve_batch_single_admission_decision():
    clock = FakeClock()
    backend = RecordingBackend()
    cfg = ServeConfig(default_timeout_ms=0, retry_attempts=0,
                      max_queue_ops=1000)
    serve, _, reg = _serve(backend, cfg, clock=clock)
    try:
        batch = serve.batch(tenant="bt")
        staged = [batch.add("t", "noop", i, nkeys=5) for i in range(4)]
        assert batch.execute() == [0, 1, 2, 3]
        # one admission for the whole pipeline
        assert reg.counter("serve.admitted_total") == 1
        # completion released the whole key weight
        assert serve._admission.queue_stats() == \
            {"queued_ops": 0, "queued_keys": 0}
    finally:
        serve.shutdown()


def test_serve_batch_fast_fails_on_open_breaker():
    clock = FakeClock()
    backend = RecordingBackend()
    cfg = ServeConfig(default_timeout_ms=0, retry_attempts=0,
                      breaker_failure_threshold=1)
    serve, _, _ = _serve(backend, cfg, clock=clock)
    try:
        br = serve._breakers.get("noop")
        br.allow(now=clock())
        br.on_failure(now=clock())
        assert br.state == "open"
        futs = serve.execute_many([("t", "noop", 1, 1), ("t", "noop", 2, 1)])
        for f in futs:
            with pytest.raises(CircuitOpenError):
                f.result(timeout=5)
        assert backend.calls == []
    finally:
        serve.shutdown()


# ---------------------------------------------------------------------------
# observability + snapshot endpoint
# ---------------------------------------------------------------------------

def test_snapshot_debug_endpoint_shape():
    clock = FakeClock()
    backend = RecordingBackend()
    serve, _, _ = _serve(backend, ServeConfig(default_timeout_ms=0,
                                              retry_attempts=0), clock=clock)
    try:
        serve.execute_async("t", "noop", 1).result(timeout=5)
        snap = serve.snapshot()
        assert snap["admission"]["admitted_total"] == 1
        assert snap["executor_queue_depth"] == 0
        assert snap["counters"]["serve.admitted_total"] == 1
        assert "breakers" in snap and "policy" in snap
    finally:
        serve.shutdown()


def test_queue_delay_and_occupancy_histograms_recorded():
    backend = RecordingBackend()
    metrics = ExecutorMetrics()
    ex = CommandExecutor(backend, metrics=metrics)
    try:
        ex.execute_async("t", "noop", 1, nkeys=4).result(timeout=5)
        snap = metrics.registry.snapshot()["histograms"]
        assert snap["executor.queue_delay_s"]["count"] == 1
        assert snap["executor.batch_occupancy"]["count"] == 1
    finally:
        ex.shutdown()


def test_expired_counter_recorded():
    clock = FakeClock(10.0)
    backend = RecordingBackend()
    metrics = ExecutorMetrics()
    ex = CommandExecutor(backend, metrics=metrics, clock=clock)
    try:
        f = ex.execute_async("t", "noop", 1, deadline=9.0)
        with pytest.raises(DeadlineExceeded):
            f.result(timeout=5)
        assert metrics.registry.counter("executor.expired_total") == 1
        assert metrics.registry.counter("executor.expired.noop") == 1
    finally:
        ex.shutdown()


# ---------------------------------------------------------------------------
# client wiring
# ---------------------------------------------------------------------------

def test_client_serve_mode_end_to_end():
    from redisson_tpu.client import RedissonTPU

    cfg = Config()
    cfg.use_serve()
    client = RedissonTPU.create(cfg)
    try:
        assert client.serve is not None
        bs = client.get_bit_set("serve:bs")
        bs.set(3)
        assert bs.get(3) is True
        assert bs.cardinality() == 1
        snap = client.serve.snapshot()
        assert snap["admission"]["admitted_total"] > 0
        assert snap["policy"]["policy"] == "adaptive"
        # maintenance traffic bypasses admission: the raw executor is NOT
        # the serving layer
        assert client._executor is client.serve.executor
        assert client._dispatch is client.serve
    finally:
        client.shutdown()


def test_client_without_serve_config_keeps_raw_executor():
    from redisson_tpu.client import RedissonTPU

    client = RedissonTPU.create(Config())
    try:
        assert client.serve is None
        assert client._dispatch is client._executor
    finally:
        client.shutdown()
