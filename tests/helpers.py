"""Shared test helpers."""

import numpy as np

from redisson_tpu.ops import hashing, u64 as u


def pack_u64(vals):
    """Python ints -> U64 batch."""
    return u.U64(
        np.array([(v >> 32) & 0xFFFFFFFF for v in vals], np.uint32),
        np.array([v & 0xFFFFFFFF for v in vals], np.uint32),
    )


def hash_ints(vals):
    """Hash python ints via the murmur3 8-byte-LE fast path -> (h1, h2)."""
    return hashing.murmur3_x64_128_u64(pack_u64(vals))


def encode_keys(keys, width):
    """List of bytes -> ([N, width] uint8 zero-padded, [N] int32 lengths)."""
    n = len(keys)
    data = np.zeros((n, width), np.uint8)
    lengths = np.zeros((n,), np.int32)
    for i, k in enumerate(keys):
        data[i, : len(k)] = np.frombuffer(k, np.uint8)
        lengths[i] = len(k)
    return data, lengths
