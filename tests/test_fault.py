"""fault/ — taxonomy, seeded injection, watchdog, self-healing rebuild.

Layers:

1. classify() unit tests — the raw-exception -> taxonomy mapping, seam
   sensitivity (pre-commit retryable vs post-dispatch uncertain), and
   semantic-error passthrough.
2. Injection units — FaultRule/FaultPlan determinism, nth/times firing,
   kind/target matching, disabled-path no-op.
3. Seam integration — each fire() site through the real client: serve
   retries absorb pre-commit faults bit-identically; d2h faults trigger
   quarantine + HBM rebuild; snapshot_io and stage_h2d surface typed.
4. Watchdog — deadline math and a real wedged-run trip through the
   executor (gates release, futures complete, breaker opens).
5. Rebuild — end-to-end self-heal from snapshot+journal, zero-lost-rows
   for acked writes, degraded-write rejection when rebuild is impossible.
6. The chaos property — randomized seeded FaultPlans over an
   hll/bloom/bitset workload: every future completes, and the surviving
   state is bit-identical to the fault-free oracle (retryable plans) or
   to a fresh recovery of the committed journal (uncertain plans).
7. PR-8 satellites — serve timer shutdown cancels pending retries'
   outers; routing rename structures-branch failure resolves the future;
   executor shutdown sweeps staged-but-undispatched ops.
"""

import threading
import time
from concurrent.futures import CancelledError

import pytest

from redisson_tpu.client import RedissonTPU
from redisson_tpu.config import Config, FaultConfig
from redisson_tpu.executor import CommandExecutor
from redisson_tpu.fault import inject, taxonomy
from redisson_tpu.fault.inject import FaultInjector, FaultPlan, FaultRule
from redisson_tpu.fault.taxonomy import (
    DeviceLostFault,
    Fault,
    FatalFault,
    RetryableFault,
    StateUncertainFault,
    TargetDegradedError,
    TargetQuarantinedError,
    classify,
)
from redisson_tpu.fault.watchdog import RunWatchdog
from redisson_tpu.serve.breaker import BreakerBoard
from redisson_tpu.serve.errors import RetryableError

from tests.test_persist import engine_digest


@pytest.fixture(autouse=True)
def _clean_fault_globals():
    """Every test starts with no injector and zeroed taxonomy counters."""
    inject.uninstall()
    taxonomy._reset_stats()
    yield
    inject.uninstall()


def make_client(tmp_path=None, serve=True, plan=None, seed=0,
                watchdog=False, rebuild=True, retry_interval_ms=5,
                **fault_kw):
    cfg = Config()
    cfg.use_local()
    if tmp_path is not None:
        pc = cfg.use_persist(str(tmp_path))
        pc.fsync = "always"
    if serve:
        sc = cfg.use_serve()
        sc.retry_interval_ms = retry_interval_ms
    fc = cfg.use_faults()
    fc.plan = plan or []
    fc.seed = seed
    fc.watchdog = watchdog
    fc.rebuild = rebuild
    for k, v in fault_kw.items():
        setattr(fc, k, v)
    return RedissonTPU.create(cfg)


# ---------------------------------------------------------------------------
# 1. classify()
# ---------------------------------------------------------------------------

class FakeXlaRuntimeError(Exception):
    """Stands in for jaxlib.xla_extension.XlaRuntimeError (matched by
    type NAME, so the stand-in exercises the same code path)."""


# classify keys on the type name; rename the class the way jaxlib spells it
FakeXlaRuntimeError.__name__ = "XlaRuntimeError"


class TestClassify:
    def test_semantic_errors_pass_through(self):
        for exc in (KeyError("k"), ValueError("bad payload"),
                    TypeError("no")):
            assert classify(exc, seam="kernel_launch") is exc
        assert taxonomy.stats()["passthrough"] == 3
        assert taxonomy.stats()["classified"] == 0

    def test_cancelled_and_faults_pass_through(self):
        c = CancelledError()
        assert classify(c, seam="d2h_complete") is c
        f = RetryableFault("x", seam="stage_h2d")
        assert classify(f, seam="d2h_complete") is f

    def test_transient_precommit_is_retryable(self):
        for seam in ("stage_h2d", "kernel_launch", "journal_fsync",
                     "snapshot_io"):
            out = classify(FakeXlaRuntimeError("RESOURCE_EXHAUSTED: oom"),
                           seam=seam)
            assert isinstance(out, RetryableFault), seam
            assert isinstance(out, RetryableError)  # serve retry fires
            assert out.seam == seam
            assert isinstance(out.cause, FakeXlaRuntimeError)

    def test_transient_postdispatch_is_uncertain(self):
        out = classify(FakeXlaRuntimeError("UNAVAILABLE: transfer failed"),
                       seam="d2h_complete")
        assert isinstance(out, StateUncertainFault)
        assert not isinstance(out, RetryableFault)
        out = classify(FakeXlaRuntimeError("ABORTED: preempted"),
                       seam="mesh_collective")
        assert isinstance(out, StateUncertainFault)

    def test_device_lost(self):
        out = classify(FakeXlaRuntimeError("DATA_LOSS: device lost"),
                       seam="d2h_complete")
        assert isinstance(out, DeviceLostFault)
        assert isinstance(out, StateUncertainFault)  # rebuild path applies

    def test_fatal(self):
        out = classify(
            FakeXlaRuntimeError("INVALID_ARGUMENT: shape mismatch"),
            seam="kernel_launch")
        assert isinstance(out, FatalFault)

    def test_oserror_at_io_seam_is_retryable(self):
        out = classify(OSError(28, "No space left on device"),
                       seam="journal_fsync")
        assert isinstance(out, RetryableFault)

    def test_unmatched_runtimeerror_passes_through(self):
        exc = RuntimeError("shape invariant violated: 3 != 4")
        assert classify(exc, seam="kernel_launch") is exc

    def test_stats_accumulate(self):
        classify(FakeXlaRuntimeError("UNAVAILABLE: x"), seam="stage_h2d")
        classify(FakeXlaRuntimeError("UNAVAILABLE: x"), seam="d2h_complete")
        classify(FakeXlaRuntimeError("DATA_LOSS: device lost"), seam="")
        s = taxonomy.stats()
        assert s["classified"] == 3
        assert s["retryable"] == 1
        assert s["state_uncertain"] == 2
        assert s["device_lost"] == 1


# ---------------------------------------------------------------------------
# 2. injection
# ---------------------------------------------------------------------------

class TestInjection:
    def test_rule_validation(self):
        with pytest.raises(ValueError):
            FaultRule(seam="nope")
        with pytest.raises(ValueError):
            FaultRule(seam="stage_h2d", fault="weird")
        with pytest.raises(ValueError):
            FaultRule(seam="stage_h2d", nth=0)

    def test_nth_and_times(self):
        inj = FaultInjector(FaultPlan(rules=[
            FaultRule(seam="kernel_launch", nth=2, times=2)]))
        inj.fire("kernel_launch")  # hit 1: clean
        with pytest.raises(RetryableFault):
            inj.fire("kernel_launch")  # hit 2: fires
        with pytest.raises(RetryableFault):
            inj.fire("kernel_launch")  # hit 3: still inside times=2
        inj.fire("kernel_launch")  # hit 4: clean again
        assert inj.injected == 2
        assert [f["hit"] for f in inj.fired] == [2, 3]

    def test_kind_target_matching(self):
        inj = FaultInjector(FaultPlan(rules=[
            FaultRule(seam="d2h_complete", kind="hll_add", target="h",
                      nth=1)]))
        inj.fire("d2h_complete", kind="bitset_set", target="h")  # kind miss
        inj.fire("d2h_complete", kind="hll_add", target="g")     # target miss
        with pytest.raises(RetryableFault):
            inj.fire("d2h_complete", kind="hll_add", target="h")
        # misses never advanced the hit counter
        assert inj.snapshot()["hits"] == [1]

    def test_random_plan_is_deterministic(self):
        a, b = FaultPlan.random(seed=7), FaultPlan.random(seed=7)
        assert a == b
        assert FaultPlan.random(seed=8) != a
        for rule in a.rules:
            assert rule.seam in inject.SEAMS
            assert rule.fault in inject.FAULT_CLASSES

    def test_fire_disabled_is_noop(self):
        inject.uninstall()
        for _ in range(3):
            inject.fire("kernel_launch", kind="hll_add", target="t")

    def test_install_uninstall(self):
        inj = FaultInjector(FaultPlan())
        inject.install(inj)
        assert inject.installed() is inj
        inject.uninstall()
        assert inject.installed() is None


# ---------------------------------------------------------------------------
# 3. seams through the real client
# ---------------------------------------------------------------------------

class TestSeams:
    def test_kernel_launch_retryable_absorbed_by_serve(self):
        c = make_client(plan=[{"seam": "kernel_launch", "fault": "retryable",
                               "nth": 3, "times": 1}])
        try:
            h = c.get_hyper_log_log("h")
            for i in range(10):
                h.add(f"k{i}")  # one add trips the seam; retry absorbs it
            assert h.count() == 10
            assert c.fault.injector.injected == 1
            assert c.metrics.counter("serve.retries_total") == 1
        finally:
            c.shutdown()

    def test_journal_fsync_retryable_absorbed(self, tmp_path):
        c = make_client(tmp_path, plan=[
            {"seam": "journal_fsync", "fault": "retryable", "nth": 2,
             "times": 1}])
        try:
            bits = c.get_bit_set("bits")
            for i in range(8):
                bits.set(i, True)
            assert bits.cardinality() == 8
            assert c.fault.injector.injected == 1
        finally:
            c.shutdown()

    def test_stage_h2d_seam_in_pipeline(self):
        """The ingest pipeline's worker-thread seam re-raises on the
        dispatcher side of run()."""
        from redisson_tpu.ingest.pipeline import StagingPipeline

        inject.install(FaultInjector(FaultPlan(rules=[
            FaultRule(seam="stage_h2d", nth=2)])))
        pipe = StagingPipeline(depth=2)
        with pytest.raises(RetryableFault):
            pipe.run([1, 2, 3], stage=lambda x: x, dispatch=lambda i, s: s)

    def test_snapshot_io_seam(self, tmp_path):
        c = make_client(tmp_path, plan=[
            {"seam": "snapshot_io", "fault": "retryable", "nth": 1,
             "times": 1}])
        try:
            c.get_hyper_log_log("h").add("a")
            with pytest.raises(RetryableFault):
                c.snapshot_now()
            # the next snapshot (hit 2) succeeds; state was never at risk
            c.snapshot_now()
        finally:
            c.shutdown()

    def test_d2h_uncertain_quarantines_and_rebuilds(self, tmp_path):
        c = make_client(tmp_path, plan=[
            {"seam": "d2h_complete", "fault": "state_uncertain", "nth": 3,
             "times": 1, "kind": "hll_add"}])
        try:
            h = c.get_hyper_log_log("h")
            outcomes = []
            for i in range(30):
                try:
                    h.add(f"k{i}")
                    outcomes.append("ok")
                except Exception as exc:  # noqa: BLE001 - audit the types
                    outcomes.append(type(exc).__name__)
            assert c.fault.rebuild.wait_idle(timeout=30)
            snap = c.fault.rebuild.snapshot()
            assert snap["rebuilt_total"] >= 1
            assert snap["degraded"] == [] and snap["quarantined"] == []
            # every acked add (and the uncertain-but-committed one: DTS
            # backends commit at stage time) survived the rebuild
            n_acked = outcomes.count("ok")
            assert h.count() >= n_acked
            # post-rebuild the target accepts writes again
            h.add("after-rebuild")
        finally:
            c.shutdown()


# ---------------------------------------------------------------------------
# 4. watchdog
# ---------------------------------------------------------------------------

class WedgedBackend:
    """run() blocks until released — a hung device call. Late completion
    respects the executor contract (guards future.done())."""

    def __init__(self):
        self.release = threading.Event()
        self.entered = threading.Event()

    def run(self, kind, target, ops):
        self.entered.set()
        self.release.wait(timeout=30)
        for op in ops:
            if not op.future.done():
                op.future.set_result("late")


class TestWatchdog:
    def test_deadline_floor_and_margin(self):
        ex = CommandExecutor(WedgedBackend())
        try:
            wd = RunWatchdog(ex, estimate=None, margin=8.0, floor_s=2.0)
            assert wd.deadline_s("hll_add", 100) == 2.0
            wd2 = RunWatchdog(ex, estimate=lambda k, n: 1.0, margin=8.0,
                              floor_s=2.0)
            assert wd2.deadline_s("hll_add", 100) == 8.0
            wd3 = RunWatchdog(ex, estimate=lambda k, n: 1 / 0, margin=8.0,
                              floor_s=2.0)
            assert wd3.deadline_s("hll_add", 100) == 2.0  # estimate fault
        finally:
            ex.shutdown(wait=False)

    def test_trip_completes_futures_and_opens_breaker(self):
        backend = WedgedBackend()
        ex = CommandExecutor(backend)
        breakers = BreakerBoard(clock=time.monotonic)
        trips = []
        wd = RunWatchdog(ex, estimate=None, margin=1.0, floor_s=0.05,
                         breakers=breakers,
                         on_trip=lambda k, t, f: trips.append((k, set(t), f)))
        try:
            f = ex.execute_async("t", "noop", "v", nkeys=1)
            assert backend.entered.wait(timeout=5)
            time.sleep(0.1)  # age past the 0.05s floor
            assert wd.check_once() == 1
            with pytest.raises(StateUncertainFault):
                f.result(timeout=5)
            assert wd.check_once() == 0  # no double trip
            assert wd.trips == 1
            assert taxonomy.stats()["watchdog_trips"] == 1
            assert breakers.get("noop").state == "open"
            assert trips and trips[0][0] == "noop" and trips[0][1] == {"t"}
            assert isinstance(trips[0][2], StateUncertainFault)
            # gates released: the next run on the same target dispatches
            # once the backend un-wedges
            backend.release.set()
            assert ex.execute_async("t", "noop", "w",
                                    nkeys=1).result(timeout=10) == "late"
        finally:
            backend.release.set()
            wd.stop()
            ex.shutdown()

    def test_healthy_runs_never_trip(self):
        c = make_client(serve=True, watchdog=True)
        try:
            h = c.get_hyper_log_log("h")
            for i in range(20):
                h.add(f"k{i}")
            assert h.count() == 20
            assert c.fault.watchdog.trips == 0
        finally:
            c.shutdown()


# ---------------------------------------------------------------------------
# 5. rebuild
# ---------------------------------------------------------------------------

class TestRebuild:
    def test_guard_rejects_writes_only(self):
        from redisson_tpu.fault.rebuild import RebuildCoordinator

        rc = RebuildCoordinator(client=None)
        rc._quarantined.add("q")
        rc._degraded.add("d")
        assert isinstance(rc.guard("hll_add", "q"), TargetQuarantinedError)
        assert isinstance(rc.guard("hll_add", "d"), TargetDegradedError)
        assert rc.guard("hll_count", "q") is None      # reads admitted
        assert rc.guard("hll_add", "other") is None    # other targets fine
        assert rc.guard("hll_add", "") is None         # no target, no guard
        # quarantine rejection is retryable; degradation is not
        assert isinstance(rc.guard("hll_add", "q"), RetryableError)
        assert not isinstance(rc.guard("hll_add", "d"), RetryableError)

    def test_rebuild_restores_snapshot_plus_suffix(self, tmp_path):
        c = make_client(tmp_path, plan=[
            {"seam": "d2h_complete", "fault": "device_lost", "nth": 6,
             "times": 1, "kind": "hll_add"}])
        try:
            h = c.get_hyper_log_log("h")
            for i in range(3):
                h.add(f"pre{i}")
            c.snapshot_now()  # targets now live in a snapshot
            for i in range(20):
                try:
                    h.add(f"post{i}")
                except Exception:  # noqa: BLE001 - chaos loop
                    pass
            assert c.fault.rebuild.wait_idle(timeout=30)
            snap = c.fault.rebuild.snapshot()
            assert snap["rebuilt_total"] >= 1 and snap["rebuild_failures"] == 0
            # snapshot content + journal suffix both survived
            assert h.count() >= 3
            h.add("again")  # quarantine lifted
        finally:
            c.shutdown()

    def test_no_persist_degrades_to_read_only(self):
        c = make_client(tmp_path=None, plan=[
            {"seam": "d2h_complete", "fault": "state_uncertain", "nth": 2,
             "times": 1, "kind": "bitset_set"}])
        try:
            bits = c.get_bit_set("bits")
            for i in range(10):
                try:
                    bits.set(i, True)
                except Exception:  # noqa: BLE001 - chaos loop
                    pass
            c.fault.rebuild.wait_idle(timeout=30)
            snap = c.fault.rebuild.snapshot()
            assert snap["degraded"] == ["bits"]
            assert snap["rebuild_failures"] == 1
            # writes fail fast with the distinct non-retryable error...
            with pytest.raises(TargetDegradedError):
                c._executor.execute_async("bits", "bitset_set",
                                          {"offset": 99, "value": 1},
                                          nkeys=1).result(timeout=5)
            # ...while reads keep serving best-effort device state
            assert bits.cardinality() >= 1
            # Other targets stay writable at the executor guard (the serve
            # breaker still sheds the KIND until its reset timeout — per-
            # kind load shedding is deliberate, the guard is per-target).
            assert c.fault.rebuild.guard("bitset_set", "healthy") is None
        finally:
            c.shutdown()

    def test_sweep_queued_rejects_with_factory(self):
        backend = WedgedBackend()
        ex = CommandExecutor(backend)
        try:
            blocker = ex.execute_async("t", "noop", 1, nkeys=1)
            assert backend.entered.wait(timeout=5)
            queued = [ex.execute_async("t", "noop", i, nkeys=1)
                      for i in range(3)]
            other = ex.execute_async("u", "noop", 9, nkeys=1)
            n = ex.sweep_queued({"t"}, lambda op: TargetQuarantinedError(
                f"{op.target} quarantined"))
            assert n == 3
            for f in queued:
                with pytest.raises(TargetQuarantinedError):
                    f.result(timeout=5)
            backend.release.set()
            assert blocker.result(timeout=5) == "late"
            assert other.result(timeout=5) == "late"
        finally:
            backend.release.set()
            ex.shutdown()


# ---------------------------------------------------------------------------
# 6. the chaos property
# ---------------------------------------------------------------------------

def _workload(client, rng_seed=0xC0FFEE, n=120):
    """Deterministic hll/bloom/bitset mix; returns per-op outcomes."""
    import random as _random

    rng = _random.Random(rng_seed)
    h = client.get_hyper_log_log("h")
    bits = client.get_bit_set("bits")
    bloom = client.get_bloom_filter("bloom")
    bloom.try_init(4096, 0.01)
    outcomes = []
    for i in range(n):
        op = rng.choice(("hll", "bits", "bloom"))
        try:
            if op == "hll":
                h.add(f"u{i}")
            elif op == "bits":
                bits.set(rng.randint(0, 512), True)
            else:
                bloom.add(f"b{i}")
            outcomes.append(("ok", op))
        except Exception as exc:  # noqa: BLE001 - the property audits types
            outcomes.append((type(exc).__name__, op))
    return outcomes


PRECOMMIT_SEAMS = ("stage_h2d", "kernel_launch", "journal_fsync")


@pytest.mark.parametrize("seed", [1, 2, 3, 4])
def test_chaos_retryable_plans_are_bit_identical_to_oracle(tmp_path, seed):
    """Pre-commit retryable faults + serve retry: the caller never sees a
    fault and the engine state is bit-identical to a fault-free run."""
    oracle = make_client()
    try:
        assert all(o == "ok" for o, _ in _workload(oracle))
        want = engine_digest(oracle)
    finally:
        oracle.shutdown()

    plan = FaultPlan.random(seed=seed, seams=PRECOMMIT_SEAMS,
                            n_rules=4, max_nth=40, faults=("retryable",))
    c = make_client(tmp_path / "chaos", plan=[
        {"seam": r.seam, "fault": r.fault, "nth": r.nth, "times": r.times}
        for r in plan.rules])
    try:
        outcomes = _workload(c)
        assert all(o == "ok" for o, _ in outcomes), outcomes
        assert engine_digest(c) == want
    finally:
        c.shutdown()


@pytest.mark.parametrize("seed", [11, 12, 13])
def test_chaos_uncertain_plans_recover_committed_state(tmp_path, seed):
    """State-uncertain/device-lost faults at the post-dispatch seam: every
    future completes (success or a typed fault/serve error — never a
    hang), rebuilds settle, and the surviving engine state equals a fresh
    client's recovery of the committed journal bit-for-bit (no acked
    write lost, no torn state)."""
    import random as _random

    rng = _random.Random(seed)
    plan = [{"seam": "d2h_complete",
             "fault": rng.choice(("state_uncertain", "device_lost")),
             "nth": rng.randint(2, 25), "times": 1}
            for _ in range(2)]
    live_dir = tmp_path / "live"
    c = make_client(live_dir, plan=plan)
    try:
        outcomes = _workload(c, rng_seed=seed)
        allowed = {"ok", "StateUncertainFault", "DeviceLostFault",
                   "CircuitOpenError", "TargetQuarantinedError",
                   "DeadlineExceeded", "RetryableFault"}
        assert {o for o, _ in outcomes} <= allowed, outcomes
        assert c.fault.rebuild.wait_idle(timeout=60)
        assert c.fault.rebuild.snapshot()["rebuild_failures"] == 0
        c.persist.journal.sync()
        live = engine_digest(c)
    finally:
        c.shutdown()

    r = RedissonTPU.create(_recover_cfg(live_dir))
    try:
        assert engine_digest(r) == live
    finally:
        r.shutdown()


def _recover_cfg(path):
    cfg = Config()
    cfg.use_local()
    pc = cfg.use_persist(str(path))
    pc.fsync = "always"
    return cfg


# ---------------------------------------------------------------------------
# 7. PR-8 satellites
# ---------------------------------------------------------------------------

class FailNTimesBackend:
    """Fails the first `n` runs with a RetryableError, then succeeds."""

    def __init__(self, n):
        self.n = n
        self.runs = 0

    def run(self, kind, target, ops):
        self.runs += 1
        for op in ops:
            if self.runs <= self.n:
                op.future.set_exception(RetryableError("transient"))
            else:
                op.future.set_result(op.payload)


class TestServeShutdownCancelsRetries:
    def test_pending_retry_outer_cancelled_at_shutdown(self):
        from redisson_tpu.config import ServeConfig
        from redisson_tpu.observability import MetricsRegistry
        from redisson_tpu.serve import ServingLayer

        backend = FailNTimesBackend(n=10)
        ex = CommandExecutor(backend)
        serve = ServingLayer(
            ex, ServeConfig(retry_attempts=3, retry_interval_ms=60_000),
            registry=MetricsRegistry())
        # timeout_s=0 -> no deadline, so the 30-60s backoff IS scheduled
        outer = serve.execute_async("t", "noop", "v", nkeys=1, timeout_s=0)
        # first attempt failed; the retry sits in the timer wheel ~30s out
        deadline = time.monotonic() + 5
        while not serve._timer._heap and time.monotonic() < deadline:
            time.sleep(0.005)
        assert serve._timer._heap, "retry was never scheduled"
        serve.shutdown()
        assert outer.cancelled()
        with pytest.raises(CancelledError):
            outer.result(timeout=0)

    def test_timer_closed_inline_fallback_cancels(self):
        from redisson_tpu.config import ServeConfig
        from redisson_tpu.observability import MetricsRegistry
        from redisson_tpu.serve import ServingLayer

        backend = FailNTimesBackend(n=10)
        ex = CommandExecutor(backend)
        serve = ServingLayer(
            ex, ServeConfig(retry_attempts=3, retry_interval_ms=60_000),
            registry=MetricsRegistry())
        serve._timer.close()  # race shutdown ahead of the attempt
        outer = serve.execute_async("t", "noop", "v", nkeys=1, timeout_s=0)
        with pytest.raises(CancelledError):
            outer.result(timeout=5)
        ex.shutdown()

    def test_entries_without_cancel_still_fire_at_close(self):
        from redisson_tpu.serve.scheduler import _Timer

        t = _Timer()
        fired = []
        t.call_later(60.0, lambda: fired.append("fn"))
        t.close()
        assert fired == ["fn"]  # legacy path: no cancel hook -> fire


class TestRoutingRenameRegression:
    def test_structures_branch_failure_resolves_future(self):
        c = make_client(serve=False)
        try:
            c.get_bucket("src").set("v")  # structures-tier key

            def boom(kind, target, ops):
                raise RuntimeError("structures tier exploded")

            c._routing.structures.run = boom
            f = c._executor.execute_async(
                "src", "rename", {"newkey": "dst"}, nkeys=1)
            with pytest.raises(RuntimeError, match="exploded"):
                f.result(timeout=5)  # resolved, not stranded
        finally:
            del c._routing.structures.run
            c.shutdown()


class TestShutdownSweep:
    def test_staged_but_undispatched_ops_cancel_at_shutdown(self):
        """A wedged in-flight run must not strand the ops queued behind
        it: shutdown's sweep cancels them (delta windows queue the same
        way — per-target FIFOs drained by the dispatcher)."""
        backend = WedgedBackend()
        ex = CommandExecutor(backend)
        inflight = ex.execute_async("t", "hll_add", {"values": ["a"]}, nkeys=1)
        assert backend.entered.wait(timeout=5)
        queued = [ex.execute_async("t", "hll_add", {"values": [f"v{i}"]},
                                   nkeys=1) for i in range(4)]
        ex.shutdown(wait=True, timeout=0.3)  # dispatcher is wedged
        for f in queued:
            assert f.done()
            with pytest.raises(CancelledError):
                f.result(timeout=0)
        backend.release.set()
        assert inflight.result(timeout=10) == "late"


# ---------------------------------------------------------------------------
# config / observability plumbing
# ---------------------------------------------------------------------------

class TestPlumbing:
    def test_config_roundtrip(self):
        cfg = Config()
        fc = cfg.use_faults()
        fc.plan = [{"seam": "kernel_launch", "nth": 2}]
        fc.watchdog = True
        d = cfg.to_dict()
        back = Config.from_dict(d)
        assert isinstance(back.faults, FaultConfig)
        assert back.faults.plan == fc.plan
        assert back.faults.watchdog is True

    def test_fault_gauges_registered(self):
        c = make_client(watchdog=True)
        try:
            gauges = c.metrics.snapshot()["gauges"]
            for name in ("fault.injected", "fault.classified",
                         "fault.retried", "fault.rebuilt",
                         "fault.quarantined", "fault.degraded",
                         "fault.rebuild_s", "fault.watchdog_trips"):
                assert name in gauges, name
        finally:
            c.shutdown()

    def test_manager_stop_uninstalls_injector(self):
        c = make_client(plan=[{"seam": "kernel_launch", "nth": 999}])
        assert inject.installed() is c.fault.injector
        c.shutdown()
        assert inject.installed() is None
