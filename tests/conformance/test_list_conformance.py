"""RList conformance vs the reference's RedissonListTest
(`/root/reference/src/test/java/org/redisson/RedissonListTest.java`)."""

import pytest


def test_add_before(client):
    # RedissonListTest.java:21-30 testAddBefore
    l = client.get_list("list")
    l.add_all(["1", "2", "3"])
    assert l.add_before("2", "0") == 4
    assert l.read_all() == ["1", "0", "2", "3"]


def test_add_after(client):
    # RedissonListTest.java:33-42 testAddAfter
    l = client.get_list("list")
    l.add_all(["1", "2", "3"])
    assert l.add_after("2", "0") == 4
    assert l.read_all() == ["1", "2", "0", "3"]


def test_trim(client):
    # RedissonListTest.java:46-57 testTrim
    l = client.get_list("list1")
    l.add_all(["1", "2", "3", "4", "5", "6"])
    l.trim(0, 3)
    assert l.read_all() == ["1", "2", "3", "4"]


def test_add_all_big_list(client):
    # RedissonListTest.java:60-68 testAddAllBigList
    l = client.get_list("list1")
    l.add_all([str(i) for i in range(10000)])
    l.insert(3, "123123")
    assert l.size() == 10001
    assert l.get(3) == "123123"


def test_equals(client):
    # RedissonListTest.java:72-90 testEquals
    l1 = client.get_list("list1")
    l1.add_all(["1", "2", "3"])
    l2 = client.get_list("list2")
    l2.add_all(["1", "2", "3"])
    l3 = client.get_list("list3")
    l3.add_all(["0", "2", "3"])
    assert l1.read_all() == l2.read_all()
    assert l1.read_all() != l3.read_all()


def test_add_by_index(client):
    # RedissonListTest.java:103-110 testAddByIndex
    l = client.get_list("test2")
    l.add("foo")
    l.insert(0, "bar")
    assert l.read_all() == ["bar", "foo"]


def test_long_values(client):
    # RedissonListTest.java:112-119 testLong
    l = client.get_list("list")
    l.add(1)
    l.add(2)
    assert l.read_all() == [1, 2]


def test_last_index_of_none(client):
    # RedissonListTest.java:356-366 testLastIndexOfNone
    l = client.get_list("list")
    l.add_all([1, 2, 3, 4, 5])
    assert l.last_index_of(10) == -1


def test_last_index_of(client):
    # RedissonListTest.java:368-420 testLastIndexOf/2/1
    l = client.get_list("list")
    l.add_all([1, 2, 3, 3, 3, 3, 3, 3, 3, 3])  # indexes 2..9 hold 3
    assert l.last_index_of(3) == 9
    l2 = client.get_list("list2")
    l2.add_all([1, 2, 3, 4, 3, 6, 3, 8])
    assert l2.last_index_of(3) == 6


def test_sub_list(client):
    # RedissonListTest.java:422-470 testSubListMiddle / testSubListHead
    l = client.get_list("list")
    l.add_all([1, 2, 3, 4, 5, 6, 7, 8])
    assert l.sub_list(2, 6) == [3, 4, 5, 6]
    assert l.sub_list(0, 3) == [1, 2, 3]


def test_index_of(client):
    # RedissonListTest.java:531-543 testIndexOf (value assertions)
    l = client.get_list("list")
    l.add_all(list(range(1, 200)))
    assert l.index_of(56) == 55
    assert l.index_of(100) == 99
    assert l.index_of(200) == -1
    assert l.index_of(0) == -1


def test_remove_at(client):
    # RedissonListTest.java:545-562 testRemove — remove(index) returns value
    l = client.get_list("list")
    l.add_all([1, 2, 3, 4, 5])
    assert l.remove_at(0) == 1
    assert l.read_all() == [2, 3, 4, 5]
    assert l.remove_at(2) == 4
    assert l.read_all() == [2, 3, 5]


def test_set_returns_old(client):
    # RedissonListTest.java:590-602 testSet
    l = client.get_list("list")
    l.add_all([1, 2, 3, 4, 5])
    assert l.set(4, 6) == 5
    assert l.read_all() == [1, 2, 3, 4, 6]


def test_set_out_of_bounds(client):
    # RedissonListTest.java:604-614 testSetFail — IndexOutOfBounds
    l = client.get_list("list")
    l.add_all([1, 2, 3, 4, 5])
    with pytest.raises(Exception):
        l.set(5, 6)


def test_remove_all_empty(client):
    # RedissonListTest.java:631-642 testRemoveAllEmpty
    l = client.get_list("list")
    l.add_all([1, 2, 3, 4, 5])
    assert l.remove_all([]) is False


def test_remove_all(client):
    # RedissonListTest.java:644-665 testRemoveAll
    l = client.get_list("list")
    l.add_all([1, 2, 3, 4, 5])
    assert l.remove_all([]) is False
    assert l.remove_all([3, 2, 10, 6]) is True
    assert l.read_all() == [1, 4, 5]
    assert l.remove_all([4]) is True
    assert l.read_all() == [1, 5]
    assert l.remove_all([1, 5, 1, 5]) is True
    assert l.is_empty()


def test_retain_all(client):
    # RedissonListTest.java:667-680 testRetainAll
    l = client.get_list("list")
    l.add_all([1, 2, 3, 4, 5])
    assert l.retain_all([3, 2, 10, 6]) is True
    assert l.read_all() == [2, 3]
    assert l.size() == 2


def test_fast_set(client):
    # RedissonListTest.java:682-690 testFastSet
    l = client.get_list("list")
    l.add_all([1, 2])
    l.fast_set(0, 3)
    assert l.get(0) == 3


def test_retain_all_empty(client):
    # RedissonListTest.java:692-703 testRetainAllEmpty
    l = client.get_list("list")
    l.add_all([1, 2, 3, 4, 5])
    assert l.retain_all([]) is True
    assert l.size() == 0


def test_retain_all_no_modify(client):
    # RedissonListTest.java:705-713 testRetainAllNoModify
    l = client.get_list("list")
    l.add_all([1, 2])
    assert l.retain_all([1, 2]) is False
    assert l.read_all() == [1, 2]


def test_add_all_index_error(client):
    # RedissonListTest.java:715-719 testAddAllIndexError
    l = client.get_list("list")
    with pytest.raises(Exception):
        l.add_all_at(2, [7, 8, 9])


def test_add_all_index(client):
    # RedissonListTest.java:721-745 testAddAllIndex
    l = client.get_list("list")
    l.add_all([1, 2, 3, 4, 5])
    assert l.add_all_at(2, [7, 8, 9]) is True
    assert l.read_all() == [1, 2, 7, 8, 9, 3, 4, 5]


def test_add_all_index_head_and_tail(client):
    # lsplice edge indexes: 0 (head rebuild) and size (pure append).
    l = client.get_list("list")
    l.add_all([3, 4])
    assert l.add_all_at(0, [1, 2]) is True
    assert l.add_all_at(4, [5, 6]) is True
    assert l.read_all() == [1, 2, 3, 4, 5, 6]


def test_add_all_index_keeps_ttl(client):
    # The splice is one atomic op and must not reset the key's expiry
    # (the old client-side loop went through linsert_at's del+rpush
    # rebuild, which drops the TTL at index 0 on the wire backend).
    l = client.get_list("list")
    l.add_all([1, 2, 3])
    assert l.expire(60) is True
    assert l.add_all_at(0, [0]) is True
    assert l.read_all() == [0, 1, 2, 3]
    assert l.remain_time_to_live() > 0


def test_add_all(client):
    # RedissonListTest.java:772-786 testAddAll
    l = client.get_list("list")
    l.add_all([1, 2, 3])
    assert l.add_all([7, 8, 9]) is True
    assert l.read_all() == [1, 2, 3, 7, 8, 9]


def test_add_all_empty(client):
    # RedissonListTest.java:788-793 testAddAllEmpty
    l = client.get_list("list")
    assert l.add_all([]) is False
    assert l.size() == 0


def test_contains_all(client):
    # RedissonListTest.java:795-816 testContainsAll(+Empty)
    l = client.get_list("list")
    l.add_all(list(range(200)))
    assert all(l.contains(v) for v in [30, 11])
    assert not all(l.contains(v) for v in [30, 711, 11])


def test_to_array(client):
    # RedissonListTest.java:818-832 testToArray
    l = client.get_list("list")
    l.add_all(["1", "4", "2", "5", "3"])
    assert l.read_all() == ["1", "4", "2", "5", "3"]


def test_iterator_sequence(client):
    # RedissonListTest.java:865-890 testIteratorSequence — insertion order
    l = client.get_list("list")
    l.add_all(["1", "4", "2", "5", "3"])
    assert list(iter(l)) == ["1", "4", "2", "5", "3"]


def test_contains(client):
    # RedissonListTest.java:892-904 testContains
    l = client.get_list("list")
    l.add_all(["1", "4", "2", "5", "3"])
    assert l.contains("3")
    assert not l.contains("31")
    assert l.contains("1")


def test_get_fail(client):
    # RedissonListTest.java:906-911 testGetFail — out-of-range index
    l = client.get_list("list")
    assert l.get(0) is None  # deliberate divergence: python None, not throw


def test_add_get(client):
    # RedissonListTest.java:913-927 testAddGet
    l = client.get_list("list")
    l.add_all(["1", "4", "2", "5", "3"])
    assert l.get(0) == "1"
    assert l.get(1) == "4"
    assert l.get(2) == "2"
    assert l.get(3) == "5"
    assert l.get(4) == "3"


def test_duplicates(client):
    # RedissonListTest.java:929-940 testDuplicates — lists keep dupes
    l = client.get_list("list")
    l.add("1")
    l.add("1")
    l.add("2")
    l.add("3")
    assert l.size() == 4
    assert l.read_all() == ["1", "1", "2", "3"]


def test_size(client):
    # RedissonListTest.java:942-962 testSize
    l = client.get_list("list")
    l.add_all(["1", "2", "3", "4", "5", "6"])
    assert l.size() == 6
    l.remove("2")
    assert l.size() == 5
