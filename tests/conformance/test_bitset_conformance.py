"""RBitSet conformance vs the reference's RedissonBitSetTest
(`/root/reference/src/test/java/org/redisson/RedissonBitSetTest.java`).

size()/NOT follow redis STRLEN semantics — the written byte extent, which
the device tiers now track explicitly (the backing allocation is pow2
device cells, an implementation detail size() must not leak)."""


def _bits(bs):
    """Set-bit indexes (the reference asserts via BitSet.toString)."""
    n = bs.length()
    return [i for i, v in enumerate(bs.get_bits(list(range(n)))) if v] if n else []


def test_index_range(client):
    # RedissonBitSetTest.java:12-18 testIndexRange — the reference probes
    # bit 2^32-2; CI memory caps the engine tier at 2^25 (the 2^32 axis is
    # covered by the pod sharded tier, tests/test_parallel.py)
    bs = client.get_bit_set("testbitset")
    top = (1 << 25) - 2
    assert bs.get(top) is False
    bs.set(top)
    assert bs.get(top) is True


def test_length(client):
    # RedissonBitSetTest.java:21-47 testLength
    bs = client.get_bit_set("testbitset")
    bs.set_range(0, 5)
    bs.clear(0, 1)
    assert bs.length() == 5

    bs.clear()
    bs.set(28)
    bs.set(31)
    assert bs.length() == 32

    bs.clear()
    bs.set(3)
    bs.set(7)
    assert bs.length() == 8

    bs.clear()
    bs.set(3)
    bs.set(120)
    bs.set(121)
    assert bs.length() == 122

    bs.clear()
    bs.set(0)
    assert bs.length() == 1


def test_clear_range(client):
    # RedissonBitSetTest.java:49-54 testClear
    bs = client.get_bit_set("testbitset")
    bs.set_range(0, 8)
    bs.clear(0, 3)
    assert _bits(bs) == [3, 4, 5, 6, 7]


def test_not(client):
    # RedissonBitSetTest.java:57-64 testNot — flips the written byte extent
    bs = client.get_bit_set("testbitset")
    bs.set(3)
    bs.set(5)
    bs.not_()
    assert _bits(bs) == [0, 1, 2, 4, 6, 7]


def test_set(client):
    # RedissonBitSetTest.java:66-80 testSet
    bs = client.get_bit_set("testbitset")
    bs.set(3)
    bs.set(5)
    assert _bits(bs) == [3, 5]


def test_set_get(client):
    # RedissonBitSetTest.java:82-96 testSetGet
    bs = client.get_bit_set("testbitset")
    assert bs.cardinality() == 0
    assert bs.size() == 0
    bs.set(10, True)
    bs.set(31, True)
    assert bs.get(0) is False
    assert bs.get(31) is True
    assert bs.get(10) is True
    assert bs.cardinality() == 2
    assert bs.size() == 32


def test_set_range(client):
    # RedissonBitSetTest.java:97-103 testSetRange
    bs = client.get_bit_set("testbitset")
    bs.set_range(3, 10)
    assert bs.cardinality() == 7
    assert bs.size() == 16


def test_as_bitset(client):
    # RedissonBitSetTest.java:105-116 testAsBitSet
    bs = client.get_bit_set("testbitset")
    bs.set(3, True)
    bs.set(41, True)
    assert bs.size() == 48
    arr = bs.to_numpy()
    assert arr[3] and arr[41]
    assert bs.cardinality() == 2


def test_and(client):
    # RedissonBitSetTest.java:118-137 testAnd
    bs1 = client.get_bit_set("testbitset1")
    bs1.set_range(3, 5)
    assert bs1.cardinality() == 2
    assert bs1.size() == 8
    bs2 = client.get_bit_set("testbitset2")
    bs2.set(4)
    bs2.set(10)
    bs1.and_("testbitset2")
    assert bs1.get(3) is False
    assert bs1.get(4) is True
    assert bs1.get(5) is False
    assert bs2.get(10) is True
    assert bs1.cardinality() == 1
    assert bs1.size() == 16
