"""RScoredSortedSet conformance vs the reference's
RedissonScoredSortedSetTest
(`/root/reference/src/test/java/org/redisson/RedissonScoredSortedSetTest.java`)."""

NEG_INF = float("-inf")
POS_INF = float("inf")


def _fill(client, scored):
    z = client.get_scored_sorted_set("simple")
    for score, member in scored:
        z.add(score, member)
    return z


ABC7 = [(0.1, "a"), (0.2, "b"), (0.3, "c"), (0.4, "d"), (0.5, "e"),
        (0.6, "f"), (0.7, "g")]


def test_count(client):
    # RedissonScoredSortedSetTest.java:30-39 testCount
    z = _fill(client, [(0, "1"), (1, "4"), (2, "2"), (3, "5"), (4, "3")])
    assert z.count(0, True, 3, False) == 3


def test_read_all(client):
    # RedissonScoredSortedSetTest.java:42-51 testReadAll
    z = _fill(client, [(0, "1"), (1, "4"), (2, "2"), (3, "5"), (4, "3")])
    assert set(z.read_all()) == {"1", "2", "3", "4", "5"}


def test_add_all(client):
    # RedissonScoredSortedSetTest.java:54-64 testAddAll
    z = client.get_scored_sorted_set("simple")
    assert z.add_all([(0.1, "1"), (0.2, "2"), (0.3, "3")]) == 3
    assert z.entry_range(0, -1) == [("1", 0.1), ("2", 0.2), ("3", 0.3)]


def test_try_add(client):
    # RedissonScoredSortedSetTest.java:67-75 testTryAdd
    z = client.get_scored_sorted_set("simple")
    assert z.try_add(123.81, "1980") is True
    assert z.try_add(99, "1980") is False
    assert z.get_score("1980") == 123.81


def test_poll_last(client):
    # RedissonScoredSortedSetTest.java:77-88 testPollLast
    z = client.get_scored_sorted_set("simple")
    assert z.poll_last() is None
    for s, m in ((0.1, "a"), (0.2, "b"), (0.3, "c")):
        z.add(s, m)
    assert z.poll_last() == "c"
    assert z.read_all() == ["a", "b"]


def test_poll_first(client):
    # RedissonScoredSortedSetTest.java:90-101 testPollFirst
    z = client.get_scored_sorted_set("simple")
    assert z.poll_first() is None
    for s, m in ((0.1, "a"), (0.2, "b"), (0.3, "c")):
        z.add(s, m)
    assert z.poll_first() == "a"
    assert z.read_all() == ["b", "c"]


def test_first_last(client):
    # RedissonScoredSortedSetTest.java:103-113 testFirstLast
    z = _fill(client, [(0.1, "a"), (0.2, "b"), (0.3, "c"), (0.4, "d")])
    assert z.first() == "a"
    assert z.last() == "d"


def test_remove_range_by_score(client):
    # RedissonScoredSortedSetTest.java:116-129 testRemoveRangeByScore
    z = _fill(client, ABC7)
    assert z.remove_range_by_score(0.1, False, 0.3, True) == 2
    assert z.read_all() == ["a", "d", "e", "f", "g"]


def test_remove_range_by_score_negative_inf(client):
    # RedissonScoredSortedSetTest.java:131-144 testRemoveRangeByScoreNegativeInf
    z = _fill(client, ABC7)
    assert z.remove_range_by_score(NEG_INF, False, 0.3, True) == 3
    assert z.read_all() == ["d", "e", "f", "g"]


def test_remove_range_by_score_positive_inf(client):
    # RedissonScoredSortedSetTest.java:146-159 testRemoveRangeByScorePositiveInf
    z = _fill(client, ABC7)
    assert z.remove_range_by_score(0.4, False, POS_INF, True) == 3
    assert z.read_all() == ["a", "b", "c", "d"]


def test_remove_range_by_rank(client):
    # RedissonScoredSortedSetTest.java:161-174 testRemoveRangeByRank
    z = _fill(client, ABC7)
    assert z.remove_range_by_rank(0, 1) == 2
    assert z.read_all() == ["c", "d", "e", "f", "g"]


def test_rank(client):
    # RedissonScoredSortedSetTest.java:176-189 testRank
    z = _fill(client, ABC7)
    assert z.rev_rank("d") == 3
    assert z.rank("abc") is None


def test_rev_rank(client):
    # RedissonScoredSortedSetTest.java:191-205 testRevRank
    z = _fill(client, ABC7)
    assert z.rev_rank("f") == 1
    assert z.rev_rank("abc") is None


def test_retain_all(client):
    # RedissonScoredSortedSetTest.java:306-318 testRetainAll
    z = client.get_scored_sorted_set("simple")
    for i in range(2000):
        z.add(i * 10, i)
    assert z.retain_all([1, 2]) is True
    assert z.read_all() == [1, 2]
    assert z.size() == 2
    assert z.get_score(1) == 10
    assert z.get_score(2) == 20


def test_remove_all(client):
    # RedissonScoredSortedSetTest.java:320-331 testRemoveAll
    z = _fill(client, [(0.1, 1), (0.2, 2), (0.3, 3)])
    assert z.remove_all([1, 2]) is True
    assert z.read_all() == [3]
    assert z.size() == 1


def test_sort_order(client):
    # RedissonScoredSortedSetTest.java:438-450 testSort
    z = client.get_scored_sorted_set("simple")
    for s, m in ((4, 2), (5, 3), (3, 1), (6, 4), (1000, 10), (1, -1), (2, 0)):
        assert z.add(s, m) is True
    assert z.read_all() == [-1, 0, 1, 2, 3, 4, 10]


def test_remove(client):
    # RedissonScoredSortedSetTest.java:452-465 testRemove
    z = _fill(client, [(4, 5), (2, 3), (0, 1), (1, 2), (3, 4)])
    assert z.remove(0) is False
    assert z.remove(3) is True
    assert z.read_all() == [1, 2, 4, 5]


def test_contains_and_duplicates(client):
    # RedissonScoredSortedSetTest.java:493-519 testContains / testDuplicates
    z = _fill(client, [(0, "1"), (1, "4"), (2, "2"), (3, "5"), (4, "3")])
    assert z.contains("3")
    assert not z.contains("31")
    z2 = client.get_scored_sorted_set("simple2")
    assert z2.add(0.1, "a") is True
    assert z2.add(0.2, "a") is False  # re-add updates score, not size
    assert z2.size() == 1
    assert z2.get_score("a") == 0.2


def test_value_range(client):
    # RedissonScoredSortedSetTest.java:535-547 testValueRange
    z = _fill(client, [(0, 1), (1, 2), (2, 3), (3, 4), (4, 5), (4, 5)])
    assert z.value_range(0, -1) == [1, 2, 3, 4, 5]


def test_entry_range(client):
    # RedissonScoredSortedSetTest.java:549-564 testEntryRange
    z = _fill(client, [(10, 1), (20, 2), (30, 3), (40, 4), (50, 5)])
    assert z.entry_range(0, -1) == [
        (1, 10.0), (2, 20.0), (3, 30.0), (4, 40.0), (5, 50.0)]


def test_value_range_by_score_limit(client):
    # RedissonScoredSortedSetTest.java:581-593 testScoredSortedSetValueRangeLimit
    z = _fill(client, [(0, "a"), (1, "b"), (2, "c"), (3, "d"), (4, "e")])
    assert z.value_range_by_score(1, True, 4, False, offset=1, count=2) == ["c", "d"]


def test_value_range_by_score(client):
    # RedissonScoredSortedSetTest.java:595-607 testScoredSortedSetValueRange
    z = _fill(client, [(0, "a"), (1, "b"), (2, "c"), (3, "d"), (4, "e")])
    assert z.value_range_by_score(1, True, 4, False) == ["b", "c", "d"]


def test_value_range_by_score_reversed_limit(client):
    # RedissonScoredSortedSetTest.java:609-621 testScoredSortedSetValueRangeReversedLimit
    z = _fill(client, [(0, "a"), (1, "b"), (2, "c"), (3, "d"), (4, "e")])
    assert z.value_range_by_score(
        1, True, 4, False, offset=1, count=2, reversed=True) == ["c", "b"]


def test_add_score(client):
    # RedissonScoredSortedSetTest.java:741-757 testAddAndGet (addScore)
    z = client.get_scored_sorted_set("simple")
    z.add(1, 100)
    assert z.add_score(100, 11) == 12
    assert z.get_score(100) == 12
    z2 = client.get_scored_sorted_set("simple2")
    z2.add(100.2, 1)
    assert abs(z2.add_score(1, 12.1) - 112.3) < 1e-9
    assert abs(z2.get_score(1) - 112.3) < 1e-9
