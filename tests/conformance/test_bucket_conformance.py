"""RBucket / RAtomicLong / RAtomicDouble / RHyperLogLog conformance vs the
reference's RedissonBucketTest / RedissonAtomicLongTest /
RedissonAtomicDoubleTest / RedissonHyperLogLogTest."""

import time

import pytest


# ---- RBucket (RedissonBucketTest.java) ------------------------------------


def test_bucket_compare_and_set(client):
    # RedissonBucketTest.java:16-31 testCompareAndSet — None = absent
    b = client.get_bucket("testCompareAndSet")
    assert b.compare_and_set(None, ["81"]) is True
    assert b.compare_and_set(None, ["12"]) is False
    assert b.compare_and_set(["81"], ["0"]) is True
    assert b.get() == ["0"]
    assert b.compare_and_set(["1"], ["2"]) is False
    assert b.get() == ["0"]
    assert b.compare_and_set(["0"], None) is True
    assert b.get() is None
    assert b.is_exists() is False


def test_bucket_get_and_set(client):
    # RedissonBucketTest.java:33-43 testGetAndSet
    b = client.get_bucket("testGetAndSet")
    assert b.get_and_set(["81"]) is None
    assert b.get_and_set(["1"]) == ["81"]
    assert b.get() == ["1"]
    assert b.get_and_set(None) == ["1"]
    assert b.get() is None
    assert b.is_exists() is False


def test_bucket_try_set(client):
    # RedissonBucketTest.java:45-51 testTrySet
    b = client.get_bucket("testTrySet")
    assert b.try_set("3") is True
    assert b.try_set("4") is False
    assert b.get() == "3"


def test_bucket_try_set_ttl(client):
    # RedissonBucketTest.java:53-63 testTrySetTTL (scaled down)
    b = client.get_bucket("testTrySetTTL")
    assert b.try_set("3", ttl_s=0.12) is True
    assert b.try_set("4", ttl_s=0.12) is False
    assert b.get() == "3"
    time.sleep(0.25)
    assert b.get() is None


def test_bucket_expire(client):
    # RedissonBucketTest.java:65-73 testExpire (scaled down)
    b = client.get_bucket("test1")
    b.set("someValue", ttl_s=0.1)
    time.sleep(0.22)
    assert b.get() is None


def test_bucket_renamenx(client):
    # RedissonBucketTest.java:75-87 testRenamenx
    b = client.get_bucket("test")
    b.set("someValue")
    b2 = client.get_bucket("test2")
    b2.set("someValue2")
    assert b.renamenx("test1") is True
    assert client.get_bucket("test").get() is None
    new_b = client.get_bucket("test1")
    assert new_b.get() == "someValue"
    assert new_b.renamenx("test2") is False


def test_bucket_rename(client):
    # RedissonBucketTest.java:89-98 testRename
    b = client.get_bucket("test")
    b.set("someValue")
    b.rename("test1")
    assert client.get_bucket("test").get() is None
    assert client.get_bucket("test1").get() == "someValue"


def test_bucket_set_get_delete_exist(client):
    # RedissonBucketTest.java:100-131 testSetGet/testSetDelete/testSetExist
    b = client.get_bucket("test")
    assert b.get() is None
    b.set("somevalue")
    assert b.get() == "somevalue"
    assert b.is_exists() is True
    assert b.delete() is True
    assert b.get() is None
    assert b.delete() is False


# ---- RAtomicLong (RedissonAtomicLongTest.java) ----------------------------


def test_atomic_compare_and_set_zero(client):
    # RedissonAtomicLongTest.java:10-20 testCompareAndSetZero — a missing
    # counter reads 0 and CAS(0, x) succeeds
    al = client.get_atomic_long("test")
    assert al.compare_and_set(0, 2) is True
    assert al.get() == 2
    al2 = client.get_atomic_long("test1")
    al2.set(0)
    assert al2.compare_and_set(0, 2) is True
    assert al2.get() == 2


def test_atomic_compare_and_set(client):
    # RedissonAtomicLongTest.java:23-30 testCompareAndSet
    al = client.get_atomic_long("test")
    assert al.compare_and_set(-1, 2) is False
    assert al.get() == 0
    assert al.compare_and_set(0, 2) is True
    assert al.get() == 2


def test_atomic_set_then_increment(client):
    # RedissonAtomicLongTest.java:32-38 testSetThenIncrement
    al = client.get_atomic_long("test")
    al.set(2)
    assert al.get_and_increment() == 2
    assert al.get() == 3


def test_atomic_increment_and_get(client):
    # RedissonAtomicLongTest.java:40-51 testIncrementAndGet/testGetAndIncrement
    al = client.get_atomic_long("test")
    assert al.increment_and_get() == 1
    assert al.get() == 1
    al2 = client.get_atomic_long("test2")
    assert al2.get_and_increment() == 0
    assert al2.get() == 1


def test_atomic_full_sequence(client):
    # RedissonAtomicLongTest.java:53-73 test — the full op walk incl. a
    # value near Long.MAX_VALUE
    al = client.get_atomic_long("test")
    assert al.get() == 0
    assert al.get_and_increment() == 0
    assert al.get() == 1
    assert al.get_and_decrement() == 1
    assert al.get() == 0
    assert al.get_and_increment() == 0
    assert al.get_and_set(12) == 1
    assert al.get() == 12
    al.set(1)
    assert client.get_atomic_long("test").get() == 1
    big = (1 << 63) - 1 - 1000
    al.set(big)
    assert client.get_atomic_long("test").get() == big


def test_atomic_double(client):
    # RedissonAtomicDoubleTest.java — float counterpart surface
    ad = client.get_atomic_double("testad")
    assert ad.get() == 0.0
    assert ad.add_and_get(1.5) == pytest.approx(1.5)
    assert ad.compare_and_set(1.5, 3.0) is True
    assert ad.compare_and_set(1.5, 9.0) is False
    assert ad.get_and_set(7.5) == pytest.approx(3.0)
    assert ad.increment_and_get() == pytest.approx(8.5)
    assert ad.decrement_and_get() == pytest.approx(7.5)


# ---- RHyperLogLog (RedissonHyperLogLogTest.java) --------------------------


def test_hll_add(client):
    # RedissonHyperLogLogTest.java:10-17 testAdd — tiny cardinalities exact
    log = client.get_hyper_log_log("log")
    log.add(b"1")
    log.add(b"2")
    log.add(b"3")
    assert log.count() == 3


def test_hll_merge(client):
    # RedissonHyperLogLogTest.java:20-38 testMerge — add() True on change,
    # False on a re-add; union of {foo,bar,zap,a} and {a,b,c,foo} counts 6
    hll1 = client.get_hyper_log_log("hll1")
    assert hll1.add(b"foo") is True
    assert hll1.add(b"bar") is True
    assert hll1.add(b"zap") is True
    assert hll1.add(b"a") is True
    hll2 = client.get_hyper_log_log("hll2")
    assert hll2.add(b"a") is True
    assert hll2.add(b"b") is True
    assert hll2.add(b"c") is True
    assert hll2.add(b"foo") is True
    assert hll2.add(b"c") is False
    hll3 = client.get_hyper_log_log("hll3")
    hll3.merge_with("hll1", "hll2")
    assert hll3.count() == 6


def test_bucket_set_none_deletes(client):
    # review r5: setAsync(null) issues DEL in the reference — all four
    # null-write paths (set/trySet/getAndSet/compareAndSet) agree
    b = client.get_bucket("nulls")
    b.set("v")
    b.set(None)
    assert b.get() is None and b.is_exists() is False
    assert b.try_set(None) is True  # absent -> "set" succeeds, writes nothing
    assert b.is_exists() is False
    b.set("w")
    assert b.try_set(None) is False  # present -> fails


def test_bitset_fresh_dest_bitop_size(client):
    # review r5: BITOP into a fresh destination must not leak the pow2
    # device allocation into size() (redis: STRLEN of the widest source)
    a = client.get_bit_set("fd:a")
    a.set(5)
    x = client.get_bit_set("fd:x")
    x.or_("fd:a")
    assert x.size() == 8
    assert x.cardinality() == 1


def test_bitset_not_on_fresh_is_noop(client):
    # review r5: NOT of a never-written string leaves it empty
    bs = client.get_bit_set("fn:x")
    bs.not_()
    assert bs.cardinality() == 0
    assert bs.size() == 0
