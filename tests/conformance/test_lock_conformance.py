"""RLock conformance vs the reference's RedissonLockTest
(`/root/reference/src/test/java/org/redisson/RedissonLockTest.java`).
Thread-identity assertions run a second "thread" via a real thread, as the
reference does."""

import threading
import time


def test_force_unlock(client):
    # RedissonLockTest.java:39-48 testForceUnlock
    lock = client.get_lock("lock")
    lock.lock()
    lock.force_unlock()
    assert not lock.is_locked()
    assert not client.get_lock("lock").is_locked()


def test_expire_releases(client):
    # RedissonLockTest.java:50-70 testExpire — lease expiry frees the lock
    lock = client.get_lock("lock")
    lock.lock(lease_time_s=0.5)
    t0 = time.monotonic()
    other = client.get_lock("lock")
    acquired = []

    def worker():
        l2 = client.get_lock("lock")
        l2.lock()
        acquired.append(time.monotonic() - t0)
        l2.unlock()

    t = threading.Thread(target=worker)
    t.start()
    t.join(timeout=5)
    assert acquired and acquired[0] < 2.0  # freed by expiry, not unlock


def test_get_hold_count(client):
    # RedissonLockTest.java:106-122 testGetHoldCount — reentrancy counter
    lock = client.get_lock("lock")
    assert lock.get_hold_count() == 0
    lock.lock()
    assert lock.get_hold_count() == 1
    lock.unlock()
    assert lock.get_hold_count() == 0
    lock.lock()
    lock.lock()
    assert lock.get_hold_count() == 2
    lock.unlock()
    assert lock.get_hold_count() == 1
    lock.unlock()
    assert lock.get_hold_count() == 0


def test_is_held_by_current_thread_other_thread(client):
    # RedissonLockTest.java:124-141 testIsHeldByCurrentThreadOtherThread
    lock = client.get_lock("lock")
    lock.lock()
    seen = []

    def worker():
        seen.append(client.get_lock("lock").is_held_by_current_thread())

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert seen == [False]
    lock.unlock()


def test_is_held_by_current_thread(client):
    # RedissonLockTest.java:133-142 testIsHeldByCurrentThread
    lock = client.get_lock("lock")
    assert not lock.is_held_by_current_thread()
    lock.lock()
    assert lock.is_held_by_current_thread()
    lock.unlock()
    assert not lock.is_held_by_current_thread()


def test_is_locked_other_thread(client):
    # RedissonLockTest.java:144-170 testIsLockedOtherThread
    lock = client.get_lock("lock")
    lock.lock()
    seen = []

    def worker():
        seen.append(client.get_lock("lock").is_locked())

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert seen == [True]
    lock.unlock()

    seen2 = []

    def worker2():
        seen2.append(client.get_lock("lock").is_locked())

    t2 = threading.Thread(target=worker2)
    t2.start()
    t2.join()
    assert seen2 == [False]


def test_is_locked(client):
    # RedissonLockTest.java:171-180 testIsLocked
    lock = client.get_lock("lock")
    assert not lock.is_locked()
    lock.lock()
    assert lock.is_locked()
    lock.unlock()
    assert not lock.is_locked()


def test_unlock_fail(client):
    # RedissonLockTest.java:181-199 testUnlockFail — unlocking a lock held
    # by another thread raises (IllegalMonitorState in the reference)
    lock = client.get_lock("lock")
    done = threading.Event()
    release = threading.Event()

    def holder():
        l2 = client.get_lock("lock")
        l2.lock()
        done.set()
        release.wait(timeout=5)
        l2.unlock()

    t = threading.Thread(target=holder)
    t.start()
    done.wait(timeout=5)
    try:
        lock.unlock()
        raised = False
    except Exception:
        raised = True
    assert raised
    release.set()
    t.join(timeout=5)
    assert not client.get_lock("lock").is_locked()


def test_lock_unlock_and_reentrancy(client):
    # RedissonLockTest.java:211-241 testLockUnlock / testReentrancy
    lock = client.get_lock("lock1")
    lock.lock()
    lock.unlock()
    lock.lock()
    lock.unlock()
    assert lock.try_lock()
    assert lock.try_lock()  # reentrant
    lock.unlock()
    # still held once: another thread cannot take it
    grabbed = []

    def worker():
        grabbed.append(client.get_lock("lock1").try_lock())

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert grabbed == [False]
    lock.unlock()


def test_concurrency_single_instance(client):
    # RedissonLockTest.java:242-256 testConcurrency_SingleInstance —
    # N threads x lock/increment/unlock: every increment lands
    iterations = 15
    counter = [0]

    def worker():
        l = client.get_lock("testConcurrency_SingleInstance")
        l.lock()
        counter[0] += 1
        l.unlock()

    threads = [threading.Thread(target=worker) for _ in range(iterations)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert counter[0] == iterations
