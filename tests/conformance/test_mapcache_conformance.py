"""RMapCache / RSetCache conformance vs the reference's
RedissonMapCacheTest / RedissonSetCacheTest (TTL/maxIdle semantics scaled
to sub-second leases; the reference sleeps seconds)."""

import time


def test_put_get_ttl(client):
    # RedissonMapCacheTest.java:496-516 testPutGet
    m = client.get_map_cache("simple04")
    assert m.get("33") is None
    m.put("33", "44", ttl_s=0.4)
    assert m.get("33") == "44"
    time.sleep(0.2)
    assert m.size() == 1
    assert m.get("33") == "44"
    time.sleep(0.3)
    assert m.get("33") is None


def test_put_if_absent_ttl(client):
    # RedissonMapCacheTest.java:518-538 testPutIfAbsent
    m = client.get_map_cache("simple")
    m.put("1", "2")
    assert m.put_if_absent("1", "3", ttl_s=0.3) == "2"
    assert m.get("1") == "2"
    m.put_if_absent("4", "4", ttl_s=0.3)
    assert m.get("4") == "4"
    time.sleep(0.4)
    assert m.get("4") is None
    assert m.put_if_absent("2", "4", ttl_s=1) is None
    assert m.get("2") == "4"


def test_size_overwrites(client):
    # RedissonMapCacheTest.java:540-562 testSize
    m = client.get_map_cache("simple")
    m.put("1", "2")
    m.put("3", "4")
    m.put("5", "6")
    assert m.size() == 3
    m.put("1", "2")
    m.put("3", "4")
    assert m.size() == 3
    m.put("1", "21")
    m.put("3", "41")
    assert m.size() == 3
    m.put("51", "6")
    assert m.size() == 4
    m.remove("3")
    assert m.size() == 3


def test_put_idle(client):
    # RedissonMapCacheTest.java:635-649 testPutIdle — touches refresh the
    # idle clock (scaled: maxIdle 0.3s, touch every 0.15s)
    m = client.get_map_cache("simple")
    m.put(1, 2, max_idle_s=0.3)
    for _ in range(4):
        time.sleep(0.15)
        assert m.get(1) == 2  # each read resets the idle timer
    time.sleep(0.45)
    assert m.get(1) is None  # untouched past maxIdle -> gone


def test_fast_put_with_ttl(client):
    # RedissonMapCacheTest.java:683-697 testFastPutWithTTL(+MaxIdle)
    m = client.get_map_cache("simple")
    assert m.fast_put(1, 2, ttl_s=2) is True
    assert m.fast_put(1, 2, ttl_s=2) is False
    assert m.size() == 1
    m2 = client.get_map_cache("simple2")
    assert m2.fast_put(1, 2, ttl_s=200, max_idle_s=100) is True
    assert m2.fast_put(1, 2, ttl_s=200, max_idle_s=100) is False
    assert m2.size() == 1


def test_expire_overwrite(client):
    # RedissonMapCacheTest.java:715-730 testExpireOverwrite — re-put
    # restarts the entry TTL
    m = client.get_map_cache("simple")
    m.put("123", 3, ttl_s=0.3)
    time.sleep(0.2)
    m.put("123", 3, ttl_s=0.3)
    time.sleep(0.2)
    assert m.get("123") == 3
    time.sleep(0.25)
    assert m.contains_key("123") is False


def test_cache_values_skip_expired(client):
    # RedissonMapCacheTest.java:130-156 testCacheValues / testGetAll — an
    # expired entry is invisible to reads and aggregates
    m = client.get_map_cache("simple")
    m.put("a", 1)
    m.put("b", 2, ttl_s=0.15)
    time.sleep(0.25)
    assert m.read_all_map() == {"a": 1}
    assert m.size() == 1
    assert m.contains_key("b") is False


def test_scheduler_sweeps(client):
    # RedissonMapCacheTest.java:479-494 testScheduler — expired entries
    # vanish without an explicit read touching them
    m = client.get_map_cache("simple3")
    assert m.get("33") is None
    m.put("33", "44", ttl_s=0.2)
    m.put("10", "32", ttl_s=0.2, max_idle_s=0.1)
    m.put("01", "92", max_idle_s=0.1)
    assert m.size() == 3
    time.sleep(0.5)
    assert m.size() == 0


def test_set_cache_ttl(client):
    # RedissonSetCacheTest — add with TTL; expired values disappear
    s = client.get_set_cache("setcache")
    assert s.add("eternal") is True
    assert s.add("brief", ttl_s=0.15) is True
    assert s.contains("brief") is True
    time.sleep(0.3)
    assert s.contains("brief") is False
    assert s.contains("eternal") is True
    assert s.size() == 1
