"""RDeque conformance vs the reference's RedissonDequeTest
(`/root/reference/src/test/java/org/redisson/RedissonDequeTest.java`)."""


def test_remove_last_occurrence(client):
    # RedissonDequeTest.java:20-31 testRemoveLastOccurrence
    q = client.get_deque("deque1")
    q.add_first(3)
    q.add_first(1)
    q.add_first(2)
    q.add_first(3)
    q.remove_last_occurrence(3)
    assert list(q.read_all()) == [3, 2, 1]


def test_remove_first_occurrence(client):
    # RedissonDequeTest.java:33-44 testRemoveFirstOccurrence
    q = client.get_deque("deque1")
    q.add_first(3)
    q.add_first(1)
    q.add_first(2)
    q.add_first(3)
    q.remove_first_occurrence(3)
    assert list(q.read_all()) == [2, 1, 3]


def test_remove_last(client):
    # RedissonDequeTest.java:46-56 testRemoveLast
    q = client.get_deque("deque1")
    q.add_first(1)
    q.add_first(2)
    q.add_first(3)
    assert q.remove_last() == 1
    assert q.remove_last() == 2
    assert q.remove_last() == 3


def test_remove_first(client):
    # RedissonDequeTest.java:58-68 testRemoveFirst
    q = client.get_deque("deque1")
    q.add_first(1)
    q.add_first(2)
    q.add_first(3)
    assert q.remove_first() == 3
    assert q.remove_first() == 2
    assert q.remove_first() == 1


def test_peek(client):
    # RedissonDequeTest.java:70-79 testPeek
    q = client.get_deque("deque1")
    assert q.peek_first() is None
    assert q.peek_last() is None
    q.add_first(2)
    assert q.peek_first() == 2
    assert q.peek_last() == 2


def test_poll_last_and_offer_first_to(client):
    # RedissonDequeTest.java:81-95 testPollLastAndOfferFirstTo
    q1 = client.get_deque("deque1")
    q1.add_first(3)
    q1.add_first(2)
    q1.add_first(1)
    q2 = client.get_deque("deque2")
    q2.add_first(6)
    q2.add_first(5)
    q2.add_first(4)
    q1.poll_last_and_offer_first_to("deque2")
    assert list(q2.read_all()) == [3, 4, 5, 6]


def test_add_first_order(client):
    # RedissonDequeTest.java:97-106 testAddFirstOrigin semantics on RDeque
    q = client.get_deque("deque")
    q.add_first(1)
    q.add_first(2)
    q.add_first(3)
    assert list(q.read_all()) == [3, 2, 1]


def test_queue_fifo(client):
    # RedissonQueueTest semantics through the deque's queue face:
    # offer/poll/peek are FIFO (RedissonQueueTest.java testAddOffer)
    q = client.get_queue("queue1")
    assert q.offer(1) is True
    q.offer(2)
    q.offer(3)
    assert q.peek() == 1
    assert q.poll() == 1
    assert q.poll() == 2
    assert q.poll() == 3
    assert q.poll() is None  # empty queue -> null
