"""RMap conformance vs the reference's RedissonMapTest
(`/root/reference/src/test/java/org/redisson/RedissonMapTest.java`).
Each test names the reference @Test it transcribes."""


def test_add_and_get(client):
    # RedissonMapTest.java:132-155 testAddAndGet
    m = client.get_map("getAll")
    m.put(1, 100)
    assert m.add_and_get(1, 12) == 112
    assert m.get(1) == 112
    m2 = client.get_map("getAll2")
    m2.put(1, 100.2)
    assert abs(m2.add_and_get(1, 12.1) - 112.3) < 1e-9
    assert abs(m2.get(1) - 112.3) < 1e-9
    ms = client.get_map("mapStr")
    assert ms.put("1", 100) is None
    assert ms.add_and_get("1", 12) == 112
    assert ms.get("1") == 112


def test_get_all(client):
    # RedissonMapTest.java:157-171 testGetAll
    m = client.get_map("getAll")
    for k, v in ((1, 100), (2, 200), (3, 300), (4, 400)):
        m.put(k, v)
    assert m.get_all({2, 3, 5}) == {2: 200, 3: 300}


def test_get_all_string_keys(client):
    # RedissonMapTest.java:173-187 testGetAllWithStringKeys
    m = client.get_map("getAllStrings")
    for k, v in (("A", 100), ("B", 200), ("C", 300), ("D", 400)):
        m.put(k, v)
    assert m.get_all({"B", "C", "E"}) == {"B": 200, "C": 300}


def test_filter_keys(client):
    # RedissonMapTest.java:189-203 testFilterKeys
    m = client.get_map("filterKeys")
    for k, v in ((1, 100), (2, 200), (3, 300), (4, 400)):
        m.put(k, v)
    assert m.filter_keys(lambda k: 2 <= k <= 3) == {2: 200, 3: 300}


def test_integer_and_long(client):
    # RedissonMapTest.java:224-252 testInteger / testLong
    m = client.get_map("test_int")
    m.put(1, 2)
    m.put(3, 4)
    assert m.size() == 2
    assert m.get(1) == 2
    assert m.get(3) == 4


def test_iterator(client):
    # RedissonMapTest.java:274-299 testIterator
    m = client.get_map("123")
    size = 1000
    for i in range(size):
        m.put(i, i)
    assert m.size() == size
    assert len(list(m.key_iterator())) == size
    assert len(list(m.value_iterator())) == size
    assert len(list(m.entry_iterator())) == size


def test_null_values(client):
    # RedissonMapTest.java:301-316 testNull — a stored null is a real entry
    m = client.get_map("simple12")
    m.put(1, None)
    m.put(2, None)
    m.put(3, "43")
    assert m.size() == 3
    assert m.get(2) is None
    assert m.get(1) is None
    assert m.get(3) == "43"


def test_entry_set(client):
    # RedissonMapTest.java:318-340 testEntrySet / testReadAllEntrySet
    m = client.get_map("simple12")
    m.put(1, "12")
    m.put(2, "33")
    m.put(3, "43")
    assert len(m.entry_set()) == 3
    assert sorted(m.read_all_entry_set()) == [(1, "12"), (2, "33"), (3, "43")]


def test_simple_types(client):
    # RedissonMapTest.java:342-351 testSimpleTypes
    m = client.get_map("simple12")
    m.put(1, "12")
    m.put(2, "33")
    m.put(3, "43")
    assert m.get(2) == "33"


def test_remove(client):
    # RedissonMapTest.java:353-364 testRemove
    m = client.get_map("simple")
    m.put("1", "2")
    m.put("33", "44")
    m.put("5", "6")
    m.remove("33")
    m.remove("5")
    assert m.size() == 1


def test_put_all(client):
    # RedissonMapTest.java:366-380 testPutAll
    m = client.get_map("simple")
    m.put(1, "1")
    m.put(2, "2")
    m.put(3, "3")
    m.put_all({4: "4", 5: "5", 6: "6"})
    assert sorted(m.key_set()) == [1, 2, 3, 4, 5, 6]


def test_key_set_contains(client):
    # RedissonMapTest.java:382-391 testKeySet
    m = client.get_map("simple")
    m.put("1", "2")
    m.put("33", "44")
    m.put("5", "6")
    assert "33" in m.key_set()
    assert "44" not in m.key_set()


def test_read_all_key_set(client):
    # RedissonMapTest.java:393-415 testReadAllKeySet(+HighAmount)
    m = client.get_map("simple")
    for i in range(1000):
        m.put(str(i), str(i))
    assert len(m.read_all_key_set()) == 1000
    assert m.read_all_key_set() == {str(i) for i in range(1000)}


def test_read_all_values(client):
    # RedissonMapTest.java:417-427 testReadAllValues
    m = client.get_map("simple")
    m.put("1", "2")
    m.put("33", "44")
    m.put("5", "6")
    assert sorted(m.read_all_values()) == ["2", "44", "6"]


def test_contains_value(client):
    # RedissonMapTest.java:429-439 testContainsValue
    m = client.get_map("simple")
    m.put("1", "2")
    m.put("33", "44")
    m.put("5", "6")
    assert m.contains_value("2")
    assert not m.contains_value("441")


def test_contains_key(client):
    # RedissonMapTest.java:441-450 testContainsKey
    m = client.get_map("simple")
    m.put("1", "2")
    m.put("33", "44")
    assert m.contains_key("33")
    assert not m.contains_key("34")


def test_remove_value(client):
    # RedissonMapTest.java:452-464 testRemoveValue
    m = client.get_map("simple")
    m.put("1", "2")
    assert m.remove("1", "2") is True
    assert m.get("1") is None
    assert m.size() == 0


def test_remove_value_fail(client):
    # RedissonMapTest.java:466-479 testRemoveValueFail
    m = client.get_map("simple")
    m.put("1", "2")
    assert m.remove("2", "1") is False
    assert m.remove("1", "3") is False
    assert m.get("1") == "2"


def test_replace_old_value_fail(client):
    # RedissonMapTest.java:482-492 testReplaceOldValueFail
    m = client.get_map("simple")
    m.put("1", "2")
    assert m.replace("1", "43", "31") is False
    assert m.get("1") == "2"


def test_replace_old_value_success(client):
    # RedissonMapTest.java:494-507 testReplaceOldValueSuccess
    m = client.get_map("simple")
    m.put("1", "2")
    assert m.replace("1", "2", "3") is True
    assert m.replace("1", "2", "3") is False
    assert m.get("1") == "3"


def test_replace_value(client):
    # RedissonMapTest.java:509-519 testReplaceValue
    m = client.get_map("simple")
    m.put("1", "2")
    assert m.replace("1", "3") == "2"
    assert m.get("1") == "3"


def test_replace_via_put(client):
    # RedissonMapTest.java:522-535 testReplace — put overwrites
    m = client.get_map("simple")
    m.put("33", "44")
    assert m.get("33") == "44"
    m.put("33", "abc")
    assert m.get("33") == "abc"


def test_put_if_absent(client):
    # RedissonMapTest.java:551-564 testPutIfAbsent
    m = client.get_map("simple")
    m.put("1", "2")
    assert m.put_if_absent("1", "3") == "2"
    assert m.get("1") == "2"
    assert m.put_if_absent("2", "4") is None
    assert m.get("2") == "4"


def test_fast_put_if_absent(client):
    # RedissonMapTest.java:566-579 testFastPutIfAbsent
    m = client.get_map("simple")
    m.put("1", "2")
    assert m.fast_put_if_absent("1", "3") is False
    assert m.get("1") == "2"
    assert m.fast_put_if_absent("2", "4") is True
    assert m.get("2") == "4"


def test_size_overwrites(client):
    # RedissonMapTest.java:581-603 testSize — overwrites don't grow size
    m = client.get_map("simple")
    m.put("1", "2")
    m.put("3", "4")
    m.put("5", "6")
    assert m.size() == 3
    m.put("1", "2")
    m.put("3", "4")
    assert m.size() == 3
    m.put("1", "21")
    m.put("3", "41")
    assert m.size() == 3
    m.put("51", "6")
    assert m.size() == 4
    m.remove("3")
    assert m.size() == 3


def test_empty_remove(client):
    # RedissonMapTest.java:605-611 testEmptyRemove
    m = client.get_map("simple")
    assert m.remove(1, 3) is False
    m.put(4, 5)
    assert m.remove(4, 5) is True


def test_put_async(client):
    # RedissonMapTest.java:613-625 testPutAsync — put returns previous value
    m = client.get_map("simple")
    assert m.put_async(2, 3).result() is None
    assert m.get(2) == 3
    assert m.put_async(2, 4).result() == 3
    assert m.get(2) == 4


def test_remove_async(client):
    # RedissonMapTest.java:627-638 testRemoveAsync
    m = client.get_map("simple")
    m.put(1, 3)
    m.put(3, 5)
    m.put(7, 8)
    assert m.remove(1) == 3
    assert m.remove(3) == 5
    assert m.remove(10) is None
    assert m.remove(7) == 8


def test_fast_remove(client):
    # RedissonMapTest.java:640-651 testFastRemoveAsync — count of removed
    m = client.get_map("simple")
    m.put(1, 3)
    m.put(3, 5)
    m.put(4, 6)
    m.put(7, 8)
    assert m.fast_remove(1, 3, 7) == 3
    assert m.size() == 1


def test_key_iterator(client):
    # RedissonMapTest.java:653-671 testKeyIterator
    m = client.get_map("simple")
    m.put(1, 0)
    m.put(3, 5)
    m.put(4, 6)
    m.put(7, 8)
    keys = set(m.key_set())
    assert keys == {1, 3, 4, 7}
    for k in m.key_iterator():
        keys.remove(k)  # raises if a key repeats or is foreign
    assert not keys


def test_value_iterator(client):
    # RedissonMapTest.java:673-691 testValueIterator
    m = client.get_map("simple")
    m.put(1, 0)
    m.put(3, 5)
    m.put(4, 6)
    m.put(7, 8)
    values = sorted(m.values())
    assert values == [0, 5, 6, 8]
    assert sorted(m.value_iterator()) == values


def test_fast_put(client):
    # RedissonMapTest.java:693-699 testFastPut — True iff field was new
    m = client.get_map("simple")
    assert m.fast_put(1, 2) is True
    assert m.fast_put(1, 3) is False
    assert m.size() == 1


def test_equals_plain_dict(client):
    # RedissonMapTest.java:701-715 testEquals
    m = client.get_map("simple")
    m.put("1", "7")
    m.put("2", "4")
    m.put("3", "5")
    assert dict(m.iter_entries()) == {"1": "7", "2": "4", "3": "5"}


def test_fast_remove_empty(client):
    # RedissonMapTest.java:717-724 testFastRemoveEmpty — no keys -> 0
    m = client.get_map("simple")
    m.put(1, 3)
    assert m.fast_remove() == 0
    assert m.size() == 1
