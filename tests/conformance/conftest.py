"""Conformance harness: reference-derived behavioral assertions run against
BOTH execution tiers (VERDICT r4 next #4 — 538/538 hasattr parity proves
surface, not semantics; this suite transcribes the reference's per-object
test corpus, `/root/reference/src/test/java/org/redisson/*Test.java`).

Fixture model mirrors the reference's `BaseTest.java:14-49`: one shared
client per tier per module, flushall between tests. Every test cites the
reference test method it transcribes (file:line of the @Test body)."""

import pytest


@pytest.fixture(scope="package", params=["engine", "redis"])
def tier(request):
    from redisson_tpu.client import RedissonTPU
    from redisson_tpu.config import Config

    if request.param == "engine":
        c = RedissonTPU.create(Config())
        yield c
        c.shutdown()
    else:
        from redisson_tpu.interop.fake_server import EmbeddedRedis

        with EmbeddedRedis() as er:
            cfg = Config()
            cfg.use_redis().address = f"redis://127.0.0.1:{er.port}"
            c = RedissonTPU.create(cfg)
            yield c
            c.shutdown()


@pytest.fixture()
def client(tier):
    tier.get_keys().flushall()
    return tier
