"""RSemaphore / RCountDownLatch / RReadWriteLock / RBlockingQueue / RKeys /
RSetMultimap conformance vs the reference's per-object suites."""

import threading
import time


# ---- RSemaphore (RedissonSemaphoreTest.java) ------------------------------


def test_semaphore_blocking_acquire(client):
    # RedissonSemaphoreTest.java:19-45 testBlockingAcquire
    s = client.get_semaphore("test")
    s.set_permits(1)
    s.acquire()

    def releaser():
        time.sleep(0.2)
        client.get_semaphore("test").release()

    t = threading.Thread(target=releaser)
    t.start()
    assert s.available_permits() == 0
    s.acquire()  # blocks until the thread releases
    assert s.try_acquire() is False
    assert s.available_permits() == 0
    t.join()


def test_semaphore_blocking_n_acquire(client):
    # RedissonSemaphoreTest.java:47-79 testBlockingNAcquire
    s = client.get_semaphore("test")
    s.set_permits(5)
    s.acquire(3)

    def releaser():
        sem = client.get_semaphore("test")
        time.sleep(0.1)
        sem.release()
        time.sleep(0.1)
        sem.release()

    assert s.available_permits() == 2
    t = threading.Thread(target=releaser)
    t.start()
    s.acquire(4)  # needs both releases
    assert s.available_permits() == 0
    t.join()


def test_semaphore_try_n_acquire(client):
    # RedissonSemaphoreTest.java:81-100 testTryNAcquire
    s = client.get_semaphore("test")
    s.set_permits(5)
    assert s.try_acquire(3) is True
    assert s.try_acquire(4) is False
    s.release()
    s.release()
    assert s.try_acquire(4) is True


# ---- RCountDownLatch (RedissonCountDownLatchTest.java) --------------------


def test_latch_count_down(client):
    # RedissonCountDownLatchTest.java:78-118 testCountDown
    latch = client.get_count_down_latch("latch")
    latch.try_set_count(2)
    assert latch.get_count() == 2
    latch.count_down()
    assert latch.get_count() == 1
    latch.count_down()
    assert latch.get_count() == 0
    assert latch.await_(timeout_s=1) is True
    latch.count_down()
    assert latch.get_count() == 0  # never below zero
    # a latch never armed has count 0 and await returns immediately
    latch3 = client.get_count_down_latch("latch3")
    assert latch3.get_count() == 0
    assert latch3.await_(timeout_s=1) is True


def test_latch_await_timeout(client):
    # RedissonCountDownLatchTest.java:15-76 testAwaitTimeout(+Fail)
    latch = client.get_count_down_latch("latch")
    latch.try_set_count(1)

    def opener():
        time.sleep(0.15)
        client.get_count_down_latch("latch").count_down()

    t = threading.Thread(target=opener)
    t.start()
    assert latch.await_(timeout_s=5) is True  # opened well within timeout
    t.join()
    latch2 = client.get_count_down_latch("latch2")
    latch2.try_set_count(1)
    t0 = time.monotonic()
    assert latch2.await_(timeout_s=0.2) is False  # never opened
    assert time.monotonic() - t0 >= 0.18


def test_latch_delete(client):
    # RedissonCountDownLatchTest.java:120-131 testDelete(+Failed)
    latch = client.get_count_down_latch("latch")
    latch.try_set_count(1)
    assert latch.delete() is True
    latch2 = client.get_count_down_latch("latchX")
    assert latch2.delete() is False


# ---- RReadWriteLock (RedissonReadWriteLockTest.java) ----------------------


def test_rw_lock_multiple_readers(client):
    # RedissonReadWriteLockTest — concurrent read locks coexist
    rw = client.get_read_write_lock("rw")
    r1 = rw.read_lock()
    r1.lock()
    got = []

    def reader():
        r = client.get_read_write_lock("rw").read_lock()
        got.append(r.try_lock())
        if got[-1]:
            r.unlock()

    t = threading.Thread(target=reader)
    t.start()
    t.join()
    assert got == [True]
    r1.unlock()


def test_rw_lock_writer_excludes(client):
    # write lock excludes other threads' readers AND writers
    rw = client.get_read_write_lock("rw")
    w = rw.write_lock()
    w.lock()
    got = []

    def contender():
        other = client.get_read_write_lock("rw")
        got.append(other.read_lock().try_lock())
        got.append(other.write_lock().try_lock())

    t = threading.Thread(target=contender)
    t.start()
    t.join()
    assert got == [False, False]
    w.unlock()


# ---- RBlockingQueue (RedissonBlockingQueueTest.java) ----------------------


def test_blocking_queue_take(client):
    # RedissonBlockingQueueTest.java:234-252 testTake (scaled down)
    q = client.get_blocking_queue("queue:take")

    def producer():
        time.sleep(0.2)
        client.get_blocking_queue("queue:take").put(3)

    t = threading.Thread(target=producer)
    t.start()
    t0 = time.monotonic()
    assert q.take() == 3
    assert time.monotonic() - t0 >= 0.15
    t.join()


def test_blocking_queue_poll_timeout(client):
    # RedissonBlockingQueueTest.java:254-262 testPoll
    q = client.get_blocking_queue("queue1")
    q.put(1)
    assert q.poll(timeout_s=2) == 1
    t0 = time.monotonic()
    assert q.poll(timeout_s=0.3) is None
    assert time.monotonic() - t0 >= 0.28


def test_blocking_queue_poll_last_and_offer_first_to(client):
    # RedissonBlockingQueueTest.java:272-291 testPollLastAndOfferFirstTo
    q1 = client.get_blocking_queue("{queue}1")

    def producer():
        time.sleep(0.15)
        client.get_blocking_queue("{queue}1").put(3)

    q2 = client.get_blocking_queue("{queue}2")
    q2.put(4)
    q2.put(5)
    q2.put(6)
    t = threading.Thread(target=producer)
    t.start()
    q1.poll_last_and_offer_first_to("{queue}2", timeout_s=5)
    t.join()
    assert [q2.poll() for _ in range(4)] == [3, 4, 5, 6]


def test_blocking_queue_add_offer(client):
    # RedissonBlockingQueueTest.java:307-319 testAddOffer
    q = client.get_blocking_queue("blocking:queue")
    q.put(1)
    assert q.offer(2) is True
    q.put(3)
    q.offer(4)
    assert [q.poll() for _ in range(4)] == [1, 2, 3, 4]


# ---- RKeys (RedissonKeysTest.java) ----------------------------------------


def test_keys_delete_by_pattern(client):
    # RedissonKeysTest.java:66-86 testDeleteByPattern
    client.get_bucket("test0").set("someValue3")
    client.get_bucket("test9").set("someValue4")
    client.get_map("test2").fast_put("1", "2")
    client.get_map("test3").fast_put("1", "5")
    assert client.get_keys().delete_by_pattern("test?") == 4
    assert client.get_keys().delete_by_pattern("test?") == 0


def test_keys_find_keys(client):
    # RedissonKeysTest.java:89-101 testFindKeys
    client.get_bucket("test1").set("someValue")
    client.get_map("test2").fast_put("1", "2")
    assert set(client.get_keys().find_keys_by_pattern("test?")) == {
        "test1", "test2"}
    assert client.get_keys().find_keys_by_pattern("test") == []


def test_keys_mass_delete(client):
    # RedissonKeysTest.java:103-123 testMassDelete
    for n in ("test0", "test1", "test2", "test3", "test10", "test12"):
        client.get_bucket(n).set("someValue")
    client.get_map("map2").fast_put("1", "2")
    names = ("test0", "test1", "test2", "test3", "test10", "test12", "map2")
    assert client.get_keys().delete(*names) == 7
    assert client.get_keys().delete(*names) == 0


def test_keys_count_and_random(client):
    # RedissonKeysTest.java:51-64,125-133 testRandomKey / testCount
    client.get_bucket("test1").set("someValue1")
    assert client.get_keys().count() == 1
    assert client.get_keys().random_key() == "test1"
    client.get_bucket("test2").set("someValue2")
    assert client.get_keys().count() == 2
    assert client.get_keys().random_key() in ("test1", "test2")


# ---- RSetMultimap (RedissonSetMultimapTest.java) --------------------------


def test_multimap_size(client):
    # RedissonSetMultimapTest.java:121-133 testSize
    mm = client.get_set_multimap("test1")
    mm.put("0", "1")
    mm.put("0", "2")
    assert mm.size() == 2
    mm.fast_remove("0")
    assert mm.get("0") == [] or set(mm.get("0")) == set()
    assert mm.size() == 0


def test_multimap_key_size(client):
    # RedissonSetMultimapTest.java:136-150 testKeySize
    mm = client.get_set_multimap("test1")
    mm.put("0", "1")
    mm.put("0", "2")
    mm.put("1", "3")
    assert mm.key_size() == 2
    assert len(mm.key_set()) == 2
    mm.fast_remove("0")
    assert mm.key_size() == 1


def test_multimap_put(client):
    # RedissonSetMultimapTest.java:153-171 testPut — set semantics dedupe
    mm = client.get_set_multimap("test1")
    assert mm.put("0", "1") is True
    assert mm.put("0", "2") is True
    assert mm.put("0", "3") is True
    assert mm.put("0", "3") is False
    assert mm.put("3", "4") is True
    assert mm.size() == 4
    assert set(mm.get("0")) == {"1", "2", "3"}
    assert set(mm.get_all("0")) == {"1", "2", "3"}
    assert set(mm.get("3")) == {"4"}


def test_multimap_remove_all(client):
    # RedissonSetMultimapTest.java:173-186 testRemoveAll
    mm = client.get_set_multimap("test1")
    mm.put("0", "1")
    mm.put("0", "2")
    mm.put("0", "3")
    assert set(mm.remove_all("0")) == {"1", "2", "3"}
    assert mm.size() == 0
    assert mm.remove_all("0") == []


def test_multimap_fast_remove(client):
    # RedissonSetMultimapTest.java:188-199 testFastRemove — count of keys
    mm = client.get_set_multimap("test1")
    assert mm.put("0", "1") is True
    assert mm.put("0", "2") is True
    assert mm.put("0", "2") is False
    assert mm.put("0", "3") is True
    assert mm.fast_remove("0", "1") == 1
    assert mm.size() == 0


def test_multimap_contains(client):
    # RedissonSetMultimapTest.java:201-225 testContainsKey/Value/Entry
    mm = client.get_set_multimap("test1")
    mm.put("0", "1")
    assert mm.contains_key("0") is True
    assert mm.contains_key("1") is False
    assert mm.contains_value("1") is True
    assert mm.contains_value("0") is False
    assert mm.contains_entry("0", "1") is True
    assert mm.contains_entry("0", "2") is False


def test_multimap_remove(client):
    # RedissonSetMultimapTest.java:227-238 testRemove
    mm = client.get_set_multimap("test1")
    mm.put("0", "1")
    mm.put("0", "2")
    mm.put("0", "3")
    assert mm.remove("0", "2") is True
    assert mm.remove("0", "5") is False
    assert len(mm.get("0")) == 2


def test_multimap_put_all(client):
    # RedissonSetMultimapTest.java:240-248 testPutAll
    mm = client.get_set_multimap("test1")
    assert mm.put_all("0", ["1", "2", "3"]) is True
    assert mm.put_all("0", ["1"]) is False
    assert set(mm.get("0")) == {"1", "2", "3"}


def test_multimap_key_set_values_entries(client):
    # RedissonSetMultimapTest.java:250-280 testKeySet/testValues/testEntrySet
    mm = client.get_set_multimap("test1")
    mm.put("0", "1")
    mm.put("3", "4")
    assert set(mm.key_set()) == {"0", "3"}
    assert sorted(mm.values()) == ["1", "4"]
    assert sorted(mm.entries()) == [("0", "1"), ("3", "4")]


def test_multimap_replace_values(client):
    # RedissonSetMultimapTest.java:282-294 testReplaceValues
    mm = client.get_set_multimap("test1")
    mm.put("0", "1")
    mm.put("3", "4")
    old = mm.replace_values("0", ["11", "12"])
    assert set(old) == {"1"}
    assert set(mm.get_all("0")) == {"11", "12"}
