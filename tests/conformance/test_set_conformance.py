"""RSet conformance vs the reference's RedissonSetTest
(`/root/reference/src/test/java/org/redisson/RedissonSetTest.java`)."""


def test_remove_random(client):
    # RedissonSetTest.java:37-48 testRemoveRandom
    s = client.get_set("simple")
    s.add(1)
    s.add(2)
    s.add(3)
    popped = set(s.remove_random() for _ in range(3))
    assert popped == {1, 2, 3}
    assert s.remove_random() is None  # empty -> null


def test_add_long(client):
    # RedissonSetTest.java:59-66 testAddLong
    s = client.get_set("simple_longs")
    s.add(1 << 40)
    assert s.contains(1 << 40)
    assert s.read_all() == {1 << 40}


def test_add_async_remove_async(client):
    # RedissonSetTest.java:77-103 testAddAsync / testRemoveAsync
    s = client.get_set("simple")
    assert s.add_async(1).result() is True
    assert s.contains(1)
    s.add(3)
    s.add(7)
    assert s.remove(1) is True
    assert not s.contains(1)
    assert s.remove(1) is False  # absent -> False


def test_iterator_sequence(client):
    # RedissonSetTest.java:136-160 testIteratorSequence
    s = client.get_set("set")
    for i in range(1000):
        s.add(i)
    seen = set(s.iterator())
    assert seen == set(range(1000))


def test_long(client):
    # RedissonSetTest.java:162-169 testLong
    s = client.get_set("set")
    s.add(1)
    s.add(2)
    assert s.read_all() == {1, 2}


def test_retain_all(client):
    # RedissonSetTest.java:171-181 testRetainAll
    s = client.get_set("set")
    for i in range(20000):
        s.add(i)
    assert s.retain_all([1, 2]) is True
    assert s.read_all() == {1, 2}
    assert s.size() == 2


def test_contains_all(client):
    # RedissonSetTest.java:201-211 testContainsAll
    s = client.get_set("set")
    for i in range(200):
        s.add(i)
    assert s.contains_all([30, 11])
    assert not s.contains_all([30, 711, 11])


def test_contains(client):
    # RedissonSetTest.java:228-241 testContains
    s = client.get_set("set")
    for v in ("1", "4", "2", "5", "3"):
        s.add(v)
    assert s.contains("3")
    assert not s.contains("31")
    assert s.contains("1")


def test_duplicates(client):
    # RedissonSetTest.java:243-254 testDuplicates — sets dedupe
    s = client.get_set("set")
    assert s.add("1") is True
    assert s.add("1") is False
    s.add("2")
    s.add("3")
    assert s.size() == 3


def test_size(client):
    # RedissonSetTest.java:256-269 testSize
    s = client.get_set("set")
    for i in (1, 2, 3, 3, 4, 5):  # re-adds don't grow
        s.add(i)
    assert s.size() == 5


def test_retain_all_empty(client):
    # RedissonSetTest.java:271-282 testRetainAllEmpty
    s = client.get_set("set")
    for i in (1, 2, 3, 4, 5):
        s.add(i)
    assert s.retain_all([]) is True
    assert s.size() == 0


def test_retain_all_no_modify(client):
    # RedissonSetTest.java:284-292 testRetainAllNoModify
    s = client.get_set("set")
    s.add(1)
    s.add(2)
    assert s.retain_all([1, 2]) is False
    assert s.read_all() == {1, 2}


def test_union(client):
    # RedissonSetTest.java:294-307 testUnion — SINTERSTORE-family semantics
    s = client.get_set("set")
    s.add(5)
    s.add(6)
    s1 = client.get_set("set1")
    s1.add(1)
    s1.add(2)
    s2 = client.get_set("set2")
    s2.add(3)
    s2.add(4)
    assert s.union("set1", "set2") == 4
    assert s.read_all() == {1, 2, 3, 4}


def test_read_union(client):
    # RedissonSetTest.java:309-323 testReadUnion — non-mutating
    s = client.get_set("set")
    s.add(5)
    s.add(6)
    s1 = client.get_set("set1")
    s1.add(1)
    s1.add(2)
    s2 = client.get_set("set2")
    s2.add(3)
    s2.add(4)
    assert s.read_union("set1", "set2") == {1, 2, 3, 4, 5, 6}
    assert s.read_all() == {5, 6}


def test_diff(client):
    # RedissonSetTest.java:326-342 testDiff
    s = client.get_set("set")
    s.add(5)
    s.add(6)
    s1 = client.get_set("set1")
    for v in (1, 2, 3):
        s1.add(v)
    s2 = client.get_set("set2")
    for v in (3, 4, 5):
        s2.add(v)
    assert s.diff("set1", "set2") == 2
    assert s.read_all() == {1, 2}


def test_read_diff(client):
    # RedissonSetTest.java:344-361 testReadDiff
    s = client.get_set("set")
    for v in (5, 7, 6):
        s.add(v)
    s1 = client.get_set("set1")
    for v in (1, 2, 5):
        s1.add(v)
    s2 = client.get_set("set2")
    for v in (3, 4, 5):
        s2.add(v)
    assert s.read_diff("set1", "set2") == {7, 6}
    assert s.read_all() == {6, 5, 7}


def test_intersection(client):
    # RedissonSetTest.java:363-379 testIntersection
    s = client.get_set("set")
    s.add(5)
    s.add(6)
    s1 = client.get_set("set1")
    for v in (1, 2, 3):
        s1.add(v)
    s2 = client.get_set("set2")
    for v in (3, 4, 5):
        s2.add(v)
    assert s.intersection("set1", "set2") == 1
    assert s.read_all() == {3}


def test_read_intersection(client):
    # RedissonSetTest.java:381-399 testReadIntersection
    s = client.get_set("set")
    for v in (5, 7, 6):
        s.add(v)
    s1 = client.get_set("set1")
    for v in (1, 2, 5):
        s1.add(v)
    s2 = client.get_set("set2")
    for v in (3, 4, 5):
        s2.add(v)
    assert s.read_intersection("set1", "set2") == {5}
    assert s.read_all() == {6, 5, 7}


def test_move(client):
    # RedissonSetTest.java:401-416 testMove
    s = client.get_set("set")
    other = client.get_set("otherSet")
    s.add(1)
    s.add(2)
    assert s.move("otherSet", 1) is True
    assert s.size() == 1
    assert s.contains(2)
    assert other.size() == 1
    assert other.contains(1)


def test_move_no_member(client):
    # RedissonSetTest.java:418-429 testMoveNoMember
    s = client.get_set("set")
    other = client.get_set("otherSet")
    s.add(1)
    assert s.move("otherSet", 2) is False
    assert s.size() == 1
    assert other.size() == 0


def test_remove_all(client):
    # RedissonSetTest.java:444-465 testRemoveAll
    s = client.get_set("list")
    for i in (1, 2, 3, 4, 5):
        s.add(i)
    assert s.remove_all([]) is False
    assert s.remove_all([3, 2, 10, 6]) is True
    assert s.read_all() == {1, 4, 5}
    assert s.remove_all([4]) is True
    assert s.read_all() == {1, 5}
    assert s.remove_all([1, 5, 1, 5]) is True
    assert s.size() == 0
