"""Bloom host mirror (transfer-adaptive ingest) — VERDICT r4 item #2.

The filter is dual-resident: a packed host replica absorbs native k-hash
folds and serves native membership with zero link traffic; the device copy
is brought current by the `bloom_sync` barrier only when a device-side op
needs it. These tests force ingest='hostfold' so the mirror path runs on
the CPU suite (the auto policy picks the device path on a fast local link).
"""

import numpy as np
import pytest

from redisson_tpu import native
from redisson_tpu.client import RedissonTPU
from redisson_tpu.config import Config, TpuConfig

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library not built")


@pytest.fixture()
def hclient():
    c = RedissonTPU.create(Config(tpu=TpuConfig(ingest="hostfold")))
    yield c
    c.shutdown()


@pytest.fixture()
def dclient():
    c = RedissonTPU.create(Config(tpu=TpuConfig(ingest="device")))
    yield c
    c.shutdown()


def _backend(c):
    return c._routing.sketch


def test_mirror_matches_device_path(hclient, dclient):
    """Same keys through mirror and device paths -> identical membership
    and identical device bit arrays after a sync barrier."""
    keys = np.random.default_rng(0).integers(0, 2**63, 5000, np.uint64)
    strs = [b"s%d" % i for i in range(1000)]
    for c in (hclient, dclient):
        bf = c.get_bloom_filter("bm:eq")
        assert bf.try_init(20_000, 0.01)
        bf.add_ints(keys)
        bf.add_all(strs)
    hclient._executor.execute_sync("bm:eq", "bloom_sync", None)
    hb = np.asarray(hclient._store.get("bm:eq").state)
    db = np.asarray(dclient._store.get("bm:eq").state)
    assert np.array_equal(hb, db)
    # membership agrees on hits and (statistically) on misses
    assert hclient.get_bloom_filter("bm:eq").contains_ints(keys).all()
    assert dclient.get_bloom_filter("bm:eq").contains_ints(keys).all()
    fresh = np.random.default_rng(9).integers(2**63, 2**64, 5000, np.uint64)
    hm = hclient.get_bloom_filter("bm:eq").contains_ints(fresh)
    dm = dclient.get_bloom_filter("bm:eq").contains_ints(fresh)
    assert np.array_equal(hm, dm)


def test_add_returns_per_key_newly(hclient):
    bf = hclient.get_bloom_filter("bm:newly")
    bf.try_init(10_000, 0.01)
    first = bf.add_all([b"a", b"b", b"c"])
    assert list(first) == [True, True, True]
    again = bf.add_all([b"a", b"b", b"d"])
    assert list(again) == [False, False, True]


def test_count_and_contains_count_use_mirror(hclient):
    bf = hclient.get_bloom_filter("bm:count")
    bf.try_init(50_000, 0.01)
    keys = np.arange(10_000, dtype=np.uint64)
    bf.add_ints(keys)
    est = bf.count()
    assert abs(est - 10_000) / 10_000 < 0.05
    assert bf.contains_count_ints(keys) == 10_000
    # No device work should have happened yet for this filter's bits.
    obj = hclient._store.get("bm:count")
    assert obj.version == 0


def test_device_probe_syncs_pending_mirror(hclient):
    """contains_count_device_async must see host-folded bits (the sync
    barrier ships the packed mirror to the device)."""
    import jax.numpy as jnp

    bf = hclient.get_bloom_filter("bm:dev")
    bf.try_init(10_000, 0.01)
    keys = np.arange(3000, dtype=np.uint64)
    bf.add_ints(keys)
    packed = jnp.asarray(
        np.stack([(keys & np.uint64(0xFFFFFFFF)).astype(np.uint32),
                  (keys >> np.uint64(32)).astype(np.uint32)], axis=1))
    hits = bf.contains_count_device_async(packed).result()
    assert hits == 3000


def test_device_write_invalidates_mirror(hclient):
    """A device-path write after host folds: sync absorbs host bits first,
    then the mirror rebuilds on the next host op — no lost writes in
    either direction."""
    back = _backend(hclient)
    bf = hclient.get_bloom_filter("bm:mix")
    bf.try_init(10_000, 0.01)
    bf.add_all([b"host-side"])
    # Force a device-path write under the mirror's feet.
    hclient._executor.execute_sync("bm:mix", "bloom_sync", None)
    back.ingest = "device"
    bf.add_all([b"device-side"])
    back.ingest = "hostfold"
    assert bf.contains(b"host-side")
    assert bf.contains(b"device-side")
    assert not bf.contains(b"neither")


def test_durability_flush_includes_mirror_bits(hclient):
    from redisson_tpu.interop.durability import DurabilityManager
    from redisson_tpu.interop.fake_server import EmbeddedRedis
    from redisson_tpu.interop.resp_client import SyncRespClient

    bf = hclient.get_bloom_filter("bm:flush")
    bf.try_init(5000, 0.01)
    bf.add_all([b"f%d" % i for i in range(500)])
    with EmbeddedRedis() as er:
        with SyncRespClient(port=er.port) as rc:
            dm = DurabilityManager(
                hclient._store, rc, executor=hclient._executor,
                pod_backend=hclient._pod_backend())
            assert dm.flush(["bm:flush"]) == 1
            raw = bytes(rc.execute("GET", "bm:flush"))
    # the flushed blob must carry exactly the host-folded bits
    flushed_pop = int(np.unpackbits(np.frombuffer(raw, np.uint8)).sum())
    mirror_pop = native.popcount(_backend(hclient)._bloom_mirrors["bm:flush"]["bits"])
    assert flushed_pop == mirror_pop > 0


def test_checkpoint_includes_mirror_bits(tmp_path, hclient):
    bf = hclient.get_bloom_filter("bm:ckpt")
    bf.try_init(5000, 0.01)
    bf.add_all([b"c%d" % i for i in range(300)])
    path = str(tmp_path / "ck")
    hclient.save_checkpoint(path, names=["bm:ckpt"])
    hclient.flushall()
    hclient.load_checkpoint(path)
    bf2 = hclient.get_bloom_filter("bm:ckpt")
    assert bf2.contains_all([b"c%d" % i for i in range(300)]).all()


def test_blocked_filter_stays_on_device_path(hclient):
    """Blocked layout has no host mirror: ops run the device kernels even
    under ingest='hostfold'."""
    bf = hclient.get_bloom_filter("bm:blk")
    bf.try_init(5000, 0.01, blocked=True)
    bf.add_all([b"x%d" % i for i in range(500)])
    assert bf.contains_all([b"x%d" % i for i in range(500)]).all()
    assert "bm:blk" not in _backend(hclient)._bloom_mirrors
