"""Coordination in redis passthrough mode: server-side Lua + pub/sub.

Closes VERDICT r1 missing-item #3/#6: locks/semaphores/latches/topics/
map-cache now execute on the (fake) Redis server, so SEPARATE CLIENT
INSTANCES — the reference's definition of "distributed" — exclude each
other. Test shapes mirror the reference's lock suites
(RedissonLockTest, RedissonSemaphoreTest, RedissonCountDownLatchTest,
RedissonTopicTest; SURVEY.md §4).
"""

from __future__ import annotations

import threading
import time

import pytest

from redisson_tpu.client import RedissonTPU
from redisson_tpu.config import Config
from redisson_tpu.interop.fake_server import EmbeddedRedis


@pytest.fixture(scope="module")
def server():
    with EmbeddedRedis() as s:
        yield s


def make_client(server) -> RedissonTPU:
    cfg = Config.from_dict({
        "redis": {"address": f"redis://127.0.0.1:{server.port}"},
    })
    return RedissonTPU.create(cfg)


@pytest.fixture()
def client(server):
    c = make_client(server)
    yield c
    c.get_keys().flushall()
    c.shutdown()


@pytest.fixture()
def client2(server):
    c = make_client(server)
    yield c
    c.shutdown()


# -- locks ------------------------------------------------------------------


def test_lock_basic_acquire_release(client):
    lock = client.get_lock("rlock:a")
    assert not lock.is_locked()
    lock.lock()
    assert lock.is_locked()
    assert lock.is_held_by_current_thread()
    lock.unlock()
    assert not lock.is_locked()


def test_lock_reentrant(client):
    lock = client.get_lock("rlock:reent")
    lock.lock()
    lock.lock()
    assert lock.get_hold_count() == 2
    lock.unlock()
    assert lock.is_locked()
    lock.unlock()
    assert not lock.is_locked()


def test_lock_two_clients_mutual_exclusion(client, client2):
    """The VERDICT's acceptance shape: two clients on one server exclude
    each other (the reference's cross-JVM contract)."""
    l1 = client.get_lock("rlock:x")
    l2 = client2.get_lock("rlock:x")
    l1.lock()
    assert not l2.try_lock()
    assert l2.is_locked()  # visible cross-client
    assert not l2.is_held_by_current_thread()
    l1.unlock()
    assert l2.try_lock()
    l2.unlock()


def test_lock_wait_wakeup_across_clients(client, client2):
    """A parked waiter on client2 wakes when client1 unlocks (pub/sub
    wake-up, not polling: RedissonLock.java:107-142)."""
    l1 = client.get_lock("rlock:wake")
    l2 = client2.get_lock("rlock:wake")
    l1.lock()
    got = {}

    def waiter():
        got["ok"] = l2.try_lock(wait_time_s=10.0)
        if got["ok"]:
            l2.unlock()  # owner identity is per-thread: release here

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.3)  # let it subscribe and park
    l1.unlock()
    t.join(timeout=10)
    assert not t.is_alive()
    assert got["ok"]


def test_lock_unlock_not_owner_raises(client, client2):
    l1 = client.get_lock("rlock:owner")
    l1.lock()
    with pytest.raises(RuntimeError, match="not locked by current thread"):
        client2.get_lock("rlock:owner").unlock()
    l1.unlock()


def test_lock_force_unlock(client, client2):
    l1 = client.get_lock("rlock:force")
    l1.lock()
    assert client2.get_lock("rlock:force").force_unlock()
    assert not l1.is_locked()


def test_lock_lease_expires_without_watchdog(client, client2):
    """An explicit short lease is NOT renewed: the holder's crash analogue
    (RedissonLock watchdog only renews default-lease holds)."""
    l1 = client.get_lock("rlock:lease")
    assert l1.try_lock(lease_time_s=0.3)
    l2 = client2.get_lock("rlock:lease")
    assert not l2.try_lock()
    assert l2.try_lock(wait_time_s=5.0)
    l2.unlock()


def test_lock_watchdog_renews_default_lease(client):
    lock = client.get_lock("rlock:wd")
    lock.lock()  # default lease; watchdog must keep it alive
    wd = client._redis_watchdog
    assert (lock.name, lock._owner()) in wd._held
    lock.unlock()
    assert (lock.name, lock._owner()) not in wd._held


def test_fair_lock_fifo_across_clients(server, client, client2):
    """Waiters acquire in arrival order (RedissonFairLock queue)."""
    c3 = make_client(server)
    try:
        l1 = client.get_fair_lock("flock:f")
        l2 = client2.get_fair_lock("flock:f")
        l3 = c3.get_fair_lock("flock:f")
        l1.lock()
        order = []
        barrier = threading.Barrier(2)

        def waiter(lk, tag, delay):
            time.sleep(delay)
            barrier.wait()  # both threads running before either enqueues
            if tag == "second":
                time.sleep(0.4)  # enforce arrival order: first enqueues first
            assert lk.try_lock(wait_time_s=15.0)
            order.append(tag)
            time.sleep(0.1)
            lk.unlock()

        t1 = threading.Thread(target=waiter, args=(l2, "first", 0))
        t2 = threading.Thread(target=waiter, args=(l3, "second", 0))
        t1.start(); t2.start()
        time.sleep(1.2)  # both parked in the queue
        l1.unlock()
        t1.join(timeout=20); t2.join(timeout=20)
        assert order == ["first", "second"]
    finally:
        c3.shutdown()


def test_read_write_lock(client, client2):
    rw1 = client.get_read_write_lock("rw:a")
    rw2 = client2.get_read_write_lock("rw:a")
    r1 = rw1.read_lock()
    r2 = rw2.read_lock()
    r1.lock()
    assert r2.try_lock()  # readers share
    assert not rw2.write_lock().try_lock()  # writer excluded
    r1.unlock()
    r2.unlock()
    w1 = rw1.write_lock()
    w1.lock()
    assert not rw2.read_lock().try_lock()  # writer excludes readers
    assert rw1.read_lock().try_lock()  # ... except its own holder
    rw1.read_lock().unlock()
    w1.unlock()


# -- semaphore / latch ------------------------------------------------------


def test_semaphore_across_clients(client, client2):
    s1 = client.get_semaphore("sem:a")
    assert s1.try_set_permits(2)
    s2 = client2.get_semaphore("sem:a")
    assert s2.try_acquire()
    assert s2.try_acquire()
    assert not s2.try_acquire()
    assert s1.available_permits() == 0
    s1.release()
    assert s2.try_acquire()
    s2.release(2)


def test_semaphore_blocking_release_wakeup(client, client2):
    s1 = client.get_semaphore("sem:wake")
    s1.try_set_permits(1)
    assert s1.try_acquire()
    got = {}

    def waiter():
        got["ok"] = client2.get_semaphore("sem:wake").try_acquire(
            timeout_s=10.0)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.3)
    s1.release()
    t.join(timeout=10)
    assert got["ok"]
    client2.get_semaphore("sem:wake").release()


def test_count_down_latch_across_clients(client, client2):
    latch1 = client.get_count_down_latch("latch:a")
    assert latch1.try_set_count(2)
    latch2 = client2.get_count_down_latch("latch:a")
    assert latch2.get_count() == 2
    done = {}

    def waiter():
        done["ok"] = latch2.await_(timeout_s=10.0)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.2)
    latch1.count_down()
    latch1.count_down()
    t.join(timeout=10)
    assert done["ok"]
    assert latch2.get_count() == 0


# -- topics -----------------------------------------------------------------


def test_topic_cross_client_pubsub(client, client2):
    received = []
    event = threading.Event()
    topic2 = client2.get_topic("news")
    topic2.add_listener(lambda ch, msg: (received.append((ch, msg)),
                                         event.set()))
    n = client.get_topic("news").publish({"headline": "tpu"})
    assert n == 1  # one subscriber counted by the server
    assert event.wait(5.0)
    assert received == [("news", {"headline": "tpu"})]
    topic2.remove_all_listeners()


def test_pattern_topic(client, client2):
    received = []
    event = threading.Event()
    pt = client2.get_pattern_topic("evt.*")
    pt.add_listener(lambda pat, ch, msg: (received.append((pat, ch, msg)),
                                          event.set()))
    client.get_topic("evt.user").publish("login")
    assert event.wait(5.0)
    assert received == [("evt.*", "evt.user", "login")]
    pt.remove_all_listeners()


# -- map cache --------------------------------------------------------------


def test_mapcache_ttl(client):
    mc = client.get_map_cache("mc:a")
    assert mc.put("k", "v1", ttl_s=0.25) is None
    assert mc.get("k") == "v1"
    assert mc.contains_key("k")
    assert mc.size() == 1
    time.sleep(0.3)
    assert mc.get("k") is None
    assert mc.size() == 0


def test_mapcache_no_ttl_persists(client):
    mc = client.get_map_cache("mc:b")
    mc.put("k", 42)
    time.sleep(0.2)
    assert mc.get("k") == 42
    assert mc.remove("k") == 42
    assert mc.get("k") is None


def test_mapcache_put_returns_old_and_put_if_absent(client):
    mc = client.get_map_cache("mc:c")
    assert mc.put("k", "a") is None
    assert mc.put("k", "b") == "a"
    assert mc.put_if_absent("k", "c") == "b"  # present: keeps b
    assert mc.get("k") == "b"
    assert mc.put_if_absent("new", "n", ttl_s=10) is None
    assert mc.get("new") == "n"


def test_mapcache_expired_entry_overwritable_by_put_if_absent(client):
    mc = client.get_map_cache("mc:d")
    mc.put("k", "old", ttl_s=0.2)
    time.sleep(0.25)
    assert mc.put_if_absent("k", "fresh") is None
    assert mc.get("k") == "fresh"


def test_mapcache_evict_expired_sweeper(client):
    mc = client.get_map_cache("mc:e")
    for i in range(5):
        mc.put(f"k{i}", i, ttl_s=0.15)
    mc.put("keep", "alive")
    time.sleep(0.25)
    assert mc.evict_expired() == 5
    assert mc.size() == 1
    assert mc.get("keep") == "alive"
    assert mc.delete()


# -- script -----------------------------------------------------------------


def test_get_script_redis_mode(client):
    script = client.get_script()
    sha = script.script_load("return tonumber(ARGV[1]) * 2")
    assert script.script_exists(sha) == [True]
    assert script.eval_sha(sha, args=["21"]) == 42
    assert script.eval(
        "redis.call('set', KEYS[1], ARGV[1]); return redis.call('get', KEYS[1])",
        keys=["sk"], args=["v"]) == b"v"


# -- regression: old gates are gone -----------------------------------------


def test_unsupported_gates_removed(client):
    """VERDICT done-condition: UnsupportedInRedisMode gone for
    locks/topics/mapcache/scripting."""
    client.get_lock("gate:lock")
    client.get_fair_lock("gate:flock")
    client.get_read_write_lock("gate:rw")
    client.get_semaphore("gate:sem")
    client.get_count_down_latch("gate:latch")
    client.get_topic("gate:topic")
    client.get_pattern_topic("gate:*")
    client.get_map_cache("gate:mc")
    client.get_script()


# -- r3 regression pins (ADVICE round-2 findings) ---------------------------


def test_rwlock_write_release_downgrades_to_read(client, client2):
    """Writer that also holds a read lock releases its write hold: mode must
    flip to 'read' (with a wake-up) so other readers proceed instead of
    TTL-polling until the read hold lapses (r2 advisor finding #1)."""
    rw1 = client.get_read_write_lock("rw:downgrade")
    w = rw1.write_lock()
    w.lock()
    r = rw1.read_lock()
    r.lock()          # writer-reads reentry
    w.unlock()        # downgrade: only the read hold remains
    other = client2.get_read_write_lock("rw:downgrade").read_lock()
    assert other.try_lock(wait_time_s=2.0)   # must NOT block until lease expiry
    other.unlock()
    r.unlock()


def test_redis_mapcache_auto_eviction(client, server):
    """TTL'd entries vanish without manual evict_expired: the client's
    EvictionScheduler sweeps redis-mode caches (r2 advisor finding #3)."""
    mc = client.get_map_cache("mc:auto")
    mc.put("gone", 1, ttl_s=0.2)
    mc.put("stay", 2)
    deadline = time.time() + 8
    # Entry must disappear from the SERVER hash (physical removal), not just
    # be filtered on read.
    while time.time() < deadline:
        raw = server.server.data.get(b"mc:auto")
        if raw is not None and len(raw) == 1:
            break
        time.sleep(0.2)
    raw = server.server.data.get(b"mc:auto")
    assert raw is not None and len(raw) == 1, dict(raw or {})
    assert mc.get("stay") == 2


def test_parked_lock_waiter_survives_pubsub_dropconn(client, client2, server):
    """DROPCONN on the subscribe connection while a waiter is parked: the
    pub/sub client reconnects and replays subscriptions, so unlock still
    wakes the waiter well before lease expiry (VERDICT r2 weak #8)."""
    lock1 = client.get_lock("rlock:dropsub")
    lock1.lock()
    acquired = threading.Event()

    def waiter():
        lock2 = client2.get_lock("rlock:dropsub")
        if lock2.try_lock(wait_time_s=20.0):
            acquired.set()
            lock2.unlock()

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    time.sleep(0.4)  # waiter parks on the channel
    # Kill client2's subscribe connection server-side.
    ps = client2._redis_pubsub
    ps.drop_for_test()
    time.sleep(0.5)  # reconnect + resubscribe replay
    lock1.unlock()
    assert acquired.wait(10.0)
    t.join(5.0)


def test_pubsub_idle_drop_then_subscribe_redials(server):
    """Subscribe connection drops while idle (zero subscriptions): a later
    subscribe() must re-dial instead of recording the listener forever
    (r2 advisor finding #2)."""
    c = make_client(server)
    try:
        # Force the pubsub connection up, then drop it while idle.
        scripts, ps, wd = c._redis_coordination()
        ps.drop_for_test()
        time.sleep(0.3)
        got = threading.Event()
        topic = c.get_topic("idle:topic")
        topic.add_listener(lambda ch, msg: got.set())
        deadline = time.time() + 5
        while time.time() < deadline and not got.is_set():
            c.get_topic("idle:topic").publish("ping")
            time.sleep(0.2)
        assert got.is_set()
    finally:
        c.shutdown()


# -- cross-client RPC + cache manager over the (fake) server ----------------
# (VERDICT r2 missing #4: the reference's entire point is two processes
# coordinating through the server — RedissonRemoteService.java:96-226.)


class _Calc:
    def add(self, a, b):
        return a + b

    def boom(self):
        raise ValueError("remote kaboom")


def test_remote_service_cross_client(client, client2):
    rs_server = client.get_remote_service("xrpc")
    rs_server.register("Calc", _Calc(), workers=2)
    try:
        calc = client2.get_remote_service("xrpc").get("Calc")
        assert calc.add(2, 40) == 42
        from redisson_tpu.services.remote import (
            RemoteInvocationOptions, RemoteServiceError)
        with pytest.raises(RemoteServiceError, match="kaboom"):
            calc.boom()
        # Fire-and-forget: returns immediately, still executes server-side.
        ff = client2.get_remote_service("xrpc").get(
            "Calc", RemoteInvocationOptions.defaults().no_result())
        assert ff.add(1, 1) is None
    finally:
        rs_server.shutdown()


def test_remote_service_ack_timeout_no_worker(client2):
    from redisson_tpu.services.remote import (
        RemoteInvocationOptions, RemoteServiceAckTimeoutError)
    ghost = client2.get_remote_service("xrpc-ghost").get(
        "Nobody", RemoteInvocationOptions(ack_timeout_s=0.3,
                                          execution_timeout_s=2.0))
    with pytest.raises(RemoteServiceAckTimeoutError):
        ghost.anything()


def test_cache_manager_cross_client(client, client2):
    cm1 = client.get_cache_manager({"users": {"ttl_s": 30.0}})
    cm2 = client2.get_cache_manager({"users": {"ttl_s": 30.0}})
    c1 = cm1.get_cache("users")
    c1.put("alice", {"age": 30})
    # Visible from the second client through the server.
    c2 = cm2.get_cache("users")
    assert c2.get("alice") == {"age": 30}
    assert c2.put_if_absent("alice", {"age": 99}) == {"age": 30}
    c2.evict("alice")
    assert c1.get("alice") is None
    # Policy-less cache rides a plain RMap.
    p = cm1.get_cache("plain")
    p.put("k", 1)
    assert cm2.get_cache("plain").get("k") == 1
