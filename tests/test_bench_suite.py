"""Smoke: every BASELINE config runs end-to-end at tiny scale and meets its
structural invariants (the full-scale numbers come from the driver run)."""

import os
import subprocess
import sys
import json

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.parametrize("config", [1, 2, 3, 4, 5])
def test_config_smoke(config):
    env = dict(os.environ, RTPU_BENCH_TINY="1",
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "suite.py"),
         "--config", str(config)],
        capture_output=True, text=True, timeout=600, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    result = json.loads(line)
    assert result["config"] == config
    if config == 1:
        assert result["engine"]["error"] < 0.02
        assert result["redis"]["error"] < 0.02
    if config == 2:
        assert result["measured_fpr"] < 0.02
    if config == 4:
        # Both variants publish a validated error against exact ground
        # truth at the same scale (VERDICT r4 next #6).
        assert result["error"] is not None and result["error"] < 0.05
        hi = result["host_ingest"]
        if "skipped" not in hi:
            assert hi["error"] is not None and hi["error"] < 0.05
            assert hi["total_keys"] == result["total_keys"]
    if config == 5:
        assert result["error"] < 0.05
        assert result["devices"] == 8
