"""Concurrency storms + fault injection (VERDICT r2 weak #5 / next #7).

The reference's heavy tier: `RedissonLockHeavyTest`, `BaseConcurrentTest`
N-thread × M-iteration closures, `RedissonConcurrentMapTest` (SURVEY §4).
Same shapes here at CI-reduced N, parametrized over the engine and the
redis passthrough (fake server) tiers, plus DROPCONN mid-traffic.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor

import pytest

from redisson_tpu.client import RedissonTPU
from redisson_tpu.config import Config
from redisson_tpu.interop.fake_server import EmbeddedRedis

THREADS = 8
ITERS = 25


@pytest.fixture(scope="module", params=["local", "redis"])
def client(request):
    if request.param == "redis":
        with EmbeddedRedis() as er:
            cfg = Config()
            cfg.use_redis().address = f"redis://127.0.0.1:{er.port}"
            c = RedissonTPU.create(cfg)
            try:
                yield c
            finally:
                c.shutdown()
        return
    c = RedissonTPU.create(Config())
    yield c
    c.shutdown()


def _storm(n_threads, fn):
    errors = []

    def run(i):
        try:
            fn(i)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=run, args=(i,)) for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors[:3]


def test_lock_storm_counter_invariant(client):
    """N threads × M iterations around one lock: the guarded counter must
    equal N×M (RedissonLockHeavyTest shape)."""
    lock = client.get_lock("heavy:lock")
    counter = {"v": 0}

    def worker(i):
        for _ in range(ITERS):
            lock.lock()
            try:
                v = counter["v"]
                time.sleep(0)  # encourage interleaving
                counter["v"] = v + 1
            finally:
                lock.unlock()

    _storm(THREADS, worker)
    assert counter["v"] == THREADS * ITERS
    assert not lock.is_locked()


def test_fair_lock_storm(client):
    lock = client.get_fair_lock("heavy:fair")
    held = {"n": 0, "max": 0}

    def worker(i):
        for _ in range(ITERS // 5):
            assert lock.try_lock(5.0)
            try:
                held["n"] += 1
                held["max"] = max(held["max"], held["n"])
                time.sleep(0.001)
                held["n"] -= 1
            finally:
                lock.unlock()

    _storm(THREADS, worker)
    assert held["max"] == 1  # never two holders


def test_semaphore_storm_never_oversubscribed(client):
    PERMITS = 3
    sem = client.get_semaphore("heavy:sem")
    sem.try_set_permits(PERMITS)
    inside = {"n": 0, "max": 0}
    guard = threading.Lock()

    def worker(i):
        for _ in range(ITERS // 5):
            assert sem.try_acquire(timeout_s=10.0)
            try:
                with guard:
                    inside["n"] += 1
                    inside["max"] = max(inside["max"], inside["n"])
                time.sleep(0.001)
            finally:
                with guard:
                    inside["n"] -= 1
                sem.release()

    _storm(THREADS, worker)
    assert 1 <= inside["max"] <= PERMITS
    assert sem.available_permits() == PERMITS


def test_map_cache_storm(client):
    mc = client.get_map_cache("heavy:mc")

    def worker(i):
        for j in range(ITERS):
            mc.put(f"k{i}:{j}", j, ttl_s=30.0)
            assert mc.get(f"k{i}:{j}") == j
        for j in range(0, ITERS, 2):
            mc.remove(f"k{i}:{j}")

    _storm(THREADS, worker)
    assert mc.size() == THREADS * (ITERS // 2)


def test_blocking_queue_storm_every_element_exactly_once(client):
    """N producers × N consumers over one blocking queue: every produced
    element is consumed exactly once."""
    q = client.get_blocking_queue("heavy:bq")
    produced = {f"{i}:{j}" for i in range(THREADS) for j in range(ITERS)}
    consumed = []
    consumed_lock = threading.Lock()

    def producer(i):
        for j in range(ITERS):
            assert q.offer(f"{i}:{j}")

    def consumer(i):
        got = []
        for _ in range(ITERS):
            v = q.poll(timeout_s=30.0)
            assert v is not None
            got.append(v)
        with consumed_lock:
            consumed.extend(got)

    with ThreadPoolExecutor(max_workers=THREADS * 2) as pool:
        futs = [pool.submit(producer, i) for i in range(THREADS)]
        futs += [pool.submit(consumer, i) for i in range(THREADS)]
        for f in futs:
            f.result(timeout=120)
    assert sorted(consumed) == sorted(produced)
    assert q.poll(timeout_s=0.05) is None


# -- fault injection (redis tier only: DROPCONN mid-traffic) -----------------


@pytest.fixture()
def rclient():
    with EmbeddedRedis() as er:
        cfg = Config()
        cfg.use_redis().address = f"redis://127.0.0.1:{er.port}"
        rcfg = cfg.redis
        rcfg.timeout_ms = 2000
        rcfg.retry_interval_ms = 50
        c = RedissonTPU.create(cfg)
        try:
            yield c, er
        finally:
            c.shutdown()


def test_dropconn_storm_mid_pipeline(rclient):
    """Connections dropped while N threads hammer idempotent ops: the
    watchdog reconnects and every op eventually succeeds (the reference's
    ConnectionWatchdog + retry machine, ConnectionWatchdog.java:71-114)."""
    c, er = rclient
    m = c.get_map("heavy:drop")
    stop = threading.Event()

    def dropper():
        # Kill sockets server-side a few times while traffic flows.
        for _ in range(5):
            if stop.is_set():
                return
            time.sleep(0.15)
            try:
                c._resp.execute("DROPCONN")
            except Exception:  # noqa: BLE001 - the drop IS the exception
                pass

    d = threading.Thread(target=dropper, daemon=True)
    d.start()

    def worker(i):
        for j in range(ITERS):
            # fast_put is idempotent: blind retry across drops is safe.
            for attempt in range(8):
                try:
                    m.fast_put(f"k{i}:{j}", j)
                    break
                except Exception:  # noqa: BLE001
                    time.sleep(0.05)
            else:
                raise AssertionError(f"put never succeeded for k{i}:{j}")

    _storm(4, worker)
    stop.set()
    d.join(timeout=5)
    assert m.size() == 4 * ITERS


def test_dropconn_mid_blocking_take_recovers(rclient):
    """A parked BLPOP whose connection dies must recover (reattach-or-fail,
    not hang): the offer after the drop is eventually consumed."""
    c, er = rclient
    q = c.get_blocking_queue("heavy:bq2")
    got = []

    def taker():
        deadline = time.time() + 20
        while time.time() < deadline:
            try:
                v = q.poll(timeout_s=2.0)
            except Exception:  # noqa: BLE001 - dropped mid-take
                continue
            if v is not None:
                got.append(v)
                return

    t = threading.Thread(target=taker, daemon=True)
    t.start()
    time.sleep(0.3)  # parked
    # Drop every data connection server-side.
    for w in list(er.server._writers):
        try:
            w.close()
        except Exception:  # noqa: BLE001
            pass
    time.sleep(0.3)
    for attempt in range(8):
        try:
            q.offer("recovered")
            break
        except Exception:  # noqa: BLE001 - the offer itself may hit the drop
            time.sleep(0.1)
    t.join(timeout=25)
    assert got == ["recovered"]
