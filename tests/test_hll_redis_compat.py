"""Redis-compatible HLL hash family (VERDICT r4 missing #3 / next #5).

Real Redis builds HLL registers with MurmurHash64A(seed 0xadc83b19)
(hyperloglog.c hllPatLen); the framework's native family is murmur3 x64
128. These tests pin:

  * the vectorized MurmurHash64A kernel against an independent scalar
    transcription (tests/golden.py);
  * the checked-in fixture (tests/fixtures/redis_hll_10000.hyll — built by
    that independent scalar path, NOT by any repo kernel) decoding to the
    registers the redis-family client kernel produces for the same keys —
    register-exact equality is the server-mergeability proof;
  * the blob tagging + import guard that keeps the two families from
    silently mixing in one sketch;
  * a mixed-writer run against the fake server in real-redis hash mode.
"""

import json
import os

import numpy as np
import pytest

from redisson_tpu.client import RedissonTPU
from redisson_tpu.config import Config
from redisson_tpu.interop import hyll

FIX = os.path.join(os.path.dirname(__file__), "fixtures", "redis_hll_10000.hyll")
FIX_META = json.load(open(FIX.replace(".hyll", ".json")))
KEYS = [b"user:%d" % i for i in range(FIX_META["true_count"])]


def _redis_client():
    cfg = Config()
    cfg.use_tpu().hll_hash = "redis"
    return RedissonTPU.create(cfg)


def test_vector_murmur64a_matches_independent_scalar():
    from tests import golden
    from redisson_tpu.ops import hashing

    rng = np.random.default_rng(42)
    raw = [bytes(rng.integers(0, 256, int(l), dtype=np.uint8))
           for l in rng.integers(0, 40, 64)]
    W = 48
    data = np.zeros((len(raw), W), np.uint8)
    lengths = np.zeros(len(raw), np.int32)
    for i, k in enumerate(raw):
        data[i, : len(k)] = np.frombuffer(k, np.uint8)
        lengths[i] = len(k)
    got = hashing.murmur2_64a(data, lengths)
    got64 = ((np.asarray(got.hi).astype(np.uint64) << np.uint64(32))
             | np.asarray(got.lo).astype(np.uint64))
    want = np.array([golden.murmur2_64a(k) for k in raw], np.uint64)
    assert (got64 == want).all()


def test_fixture_decodes_to_true_count_envelope():
    regs = hyll.decode(open(FIX, "rb").read())
    est = hyll.estimate(regs)
    true = FIX_META["true_count"]
    assert abs(est - true) / true < 0.02
    assert hyll.blob_family(open(FIX, "rb").read()) == "redis"


def test_redis_family_client_matches_fixture_registers_exactly():
    """The register-exact proof: the device kernel in redis-hash mode
    produces BIT-IDENTICAL registers to the independent scalar transcription
    of redis's hllPatLen — so a real server PFADDing the same keys writes
    the same registers, and flushed sketches stay mergeable."""
    c = _redis_client()
    try:
        h = c.get_hyper_log_log("compat:fix")
        h.add_all(KEYS)
        regs, _version = c._executor.execute_sync("compat:fix", "hll_export", None)
        want = hyll.decode(open(FIX, "rb").read())
        assert np.array_equal(np.asarray(regs, np.uint8), want)
        est = h.count()
        assert abs(est - len(KEYS)) / len(KEYS) < 0.02
    finally:
        c.shutdown()


def test_redis_family_int_path_matches_bytes_path_contract():
    """add_ints under the redis family hashes the 8-byte LE encoding —
    same keys via bytes and ints agree register-for-register."""
    ints = np.arange(5000, dtype=np.uint64)
    c = _redis_client()
    try:
        a = c.get_hyper_log_log("compat:int")
        a.add_ints(ints)
        b = c.get_hyper_log_log("compat:bytes")
        b.add_all([int(v).to_bytes(8, "little") for v in ints])
        ra, _ = c._executor.execute_sync("compat:int", "hll_export", None)
        rb, _ = c._executor.execute_sync("compat:bytes", "hll_export", None)
        assert np.array_equal(ra, rb)
    finally:
        c.shutdown()


def test_blob_tagging_round_trip():
    regs = np.zeros(hyll.M, np.uint8)
    regs[7] = 3
    m3 = hyll.encode_dense(regs, family="m3")
    rd = hyll.encode_dense(regs, family="redis")
    assert hyll.blob_family(m3) == "m3"
    assert hyll.blob_family(rd) == "redis"
    assert rd[5:8] == b"\x00\x00\x00"  # byte-exact standard header
    assert np.array_equal(hyll.decode(m3), hyll.decode(rd))


def test_import_guard_cross_family(tmp_path):
    """Certain mismatch (M3-tagged blob into a redis-family client) raises;
    ambiguous (untagged blob into an m3 client — real-server sketch OR
    legacy framework flush) warns and imports (VERDICT r4 next #5 +
    review r5 backward-compat: legacy untagged m3 data must stay
    loadable)."""
    from redisson_tpu.interop.fake_server import EmbeddedRedis

    with EmbeddedRedis() as er:
        cfg = Config()
        cfg.use_tpu()  # murmur3 default
        cfg.use_redis().address = f"redis://127.0.0.1:{er.port}"
        c = RedissonTPU.create(cfg)
        try:
            # Plant a foreign (redis-family / real-server) blob: ambiguous
            # for an m3 client -> warn, import anyway.
            c.durability.client.execute(
                "SET", "foreign", open(FIX, "rb").read())
            with pytest.warns(UserWarning, match="hash-family"):
                assert c.durability.load_hll("foreign")
            est = c.get_hyper_log_log("foreign").count()
            assert abs(est - FIX_META["true_count"]) / FIX_META["true_count"] < 0.02
            # force=True silences the warning
            import warnings

            with warnings.catch_warnings():
                warnings.simplefilter("error")
                assert c.durability.load_hll("foreign", force=True)
        finally:
            c.shutdown()

    # Certain mismatch: M3-tagged blob into a redis-family client -> raise.
    with EmbeddedRedis(hll_hash="redis") as er:
        cfg = Config()
        cfg.use_tpu().hll_hash = "redis"
        cfg.use_redis().address = f"redis://127.0.0.1:{er.port}"
        c = RedissonTPU.create(cfg)
        try:
            regs = np.zeros(hyll.M, np.uint8)
            regs[3] = 2
            c.durability.client.execute(
                "SET", "m3blob", hyll.encode_dense(regs, family="m3"))
            with pytest.raises(ValueError, match="framework-murmur3"):
                c.durability.load_hll("m3blob")
            assert c.durability.load_hll("m3blob", force=True)
        finally:
            c.shutdown()


def test_m3_flush_blob_is_tagged():
    from redisson_tpu.interop.fake_server import EmbeddedRedis

    with EmbeddedRedis() as er:
        cfg = Config()
        cfg.use_tpu()
        cfg.use_redis().address = f"redis://127.0.0.1:{er.port}"
        c = RedissonTPU.create(cfg)
        try:
            c.get_hyper_log_log("tag:me").add_ints(np.arange(100, dtype=np.uint64))
            c.flush_to_redis(["tag:me"])
            blob = bytes(c.durability.client.execute("GET", "tag:me"))
            assert hyll.blob_family(blob) == "m3"
            # same-family reload is accepted
            assert c.durability.load_hll("tag:me")
        finally:
            c.shutdown()


def test_mixed_writer_with_real_redis_semantics():
    """The end-to-end server-mergeability scenario the verdict prescribed:
    a redis-family client flushes a sketch; a server with REAL redis hash
    semantics (fake server in hll_hash='redis' mode) PFADDs more keys into
    the same key; the union estimate stays correct — no silent corruption
    from mixed hash families."""
    from redisson_tpu.interop.fake_server import EmbeddedRedis

    with EmbeddedRedis(hll_hash="redis") as er:
        cfg = Config()
        cfg.use_tpu().hll_hash = "redis"
        cfg.use_redis().address = f"redis://127.0.0.1:{er.port}"
        c = RedissonTPU.create(cfg)
        try:
            h = c.get_hyper_log_log("mix:key")
            h.add_all(KEYS[:6000])  # client writes user:0..5999
            c.flush_to_redis(["mix:key"])
            blob = bytes(c.durability.client.execute("GET", "mix:key"))
            assert hyll.blob_family(blob) == "redis"  # untagged = standard
            # Server-side PFADD of user:4000..9999 (2000 overlap, 4000 new)
            c.durability.client.execute("PFADD", "mix:key", *KEYS[4000:])
            union = int(c.durability.client.execute("PFCOUNT", "mix:key"))
            true = len(KEYS)  # 10_000 distinct across both writers
            assert abs(union - true) / true < 0.02, union
            # reload into the client: same family, accepted, same estimate
            assert c.durability.load_hll("mix:key")
            est = c.get_hyper_log_log("mix:key").count()
            assert abs(est - true) / true < 0.02
        finally:
            c.shutdown()
