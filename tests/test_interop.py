"""Interop tier tests: HYLL codec, RESP client vs the embedded fake server,
durability flush/import round-trips, local checkpoint/resume."""

import asyncio

import numpy as np
import pytest

from redisson_tpu import checkpoint, native
from redisson_tpu.interop import hyll
from redisson_tpu.interop.durability import DurabilityManager
from redisson_tpu.interop.fake_server import EmbeddedRedis, FakeRedisServer
from redisson_tpu.interop.resp_client import (ConnectionClosed, RespClient,
                                              SyncRespClient)

# ---------------------------------------------------------------------------
# HYLL codec
# ---------------------------------------------------------------------------


def test_hyll_dense_roundtrip():
    rng = np.random.default_rng(1)
    regs = rng.integers(0, 52, 16384).astype(np.uint8)
    blob = hyll.encode_dense(regs)
    assert blob[:4] == b"HYLL" and blob[4] == 0
    assert len(blob) == 16 + 12288
    np.testing.assert_array_equal(hyll.decode(blob), regs)


def test_hyll_cached_cardinality_flag():
    regs = np.zeros(16384, np.uint8)
    assert hyll.cached_cardinality(hyll.encode_dense(regs)) is None
    assert hyll.cached_cardinality(hyll.encode_dense(regs, cached_card=123)) == 123


def test_hyll_sparse_roundtrip():
    regs = np.zeros(16384, np.uint8)
    regs[0] = 5
    regs[1] = 5
    regs[100] = 32
    regs[16383] = 1
    blob = hyll.encode_sparse(regs)
    assert blob[4] == 1
    np.testing.assert_array_equal(hyll.decode(blob), regs)


def test_hyll_sparse_rejects_large_values():
    regs = np.zeros(16384, np.uint8)
    regs[7] = 33
    with pytest.raises(ValueError):
        hyll.encode_sparse(regs)


def test_hyll_decode_rejects_garbage():
    with pytest.raises(ValueError):
        hyll.decode(b"NOPE" + b"\x00" * 20)
    with pytest.raises(ValueError):
        hyll.decode(b"HYLL\x00\x00\x00\x00" + b"\x00" * 8)  # dense, short body


def test_hyll_blob_matches_native_fold_estimate():
    # encode registers produced by the native fold; decode; estimate intact
    import jax.numpy as jnp

    from redisson_tpu.ops import hll as hll_ops
    regs = np.zeros(16384, np.uint8)
    native.hll_fold([b"k%d" % i for i in range(50000)], regs)
    back = hyll.decode(hyll.encode_dense(regs))
    est = float(hll_ops.count(jnp.asarray(back.astype(np.int32))))
    assert abs(est - 50000) / 50000 < 0.02


# ---------------------------------------------------------------------------
# RESP client against the fake server
# ---------------------------------------------------------------------------


def run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_client_basic_and_pipeline():
    async def go():
        srv = FakeRedisServer()
        await srv.start()
        c = RespClient(port=srv.port, retry_interval=0.01)
        await c.connect()
        assert await c.execute("PING") == b"PONG"
        assert await c.execute("SET", "a", "1") == b"OK"
        assert await c.execute("GET", "a") == b"1"
        assert await c.execute("GET", "missing") is None
        res = await c.pipeline([("SET", f"k{i}", f"v{i}") for i in range(100)]
                               + [("DBSIZE",)])
        assert res[-1] == 101  # 100 k's + a
        assert await c.execute("EXISTS", "k0", "k99", "nope") == 2
        await c.close()
        await srv.stop()
    run(go())


def test_client_error_replies_raise():
    async def go():
        srv = FakeRedisServer()
        await srv.start()
        c = RespClient(port=srv.port)
        await c.connect()
        with pytest.raises(native.RespError):
            await c.execute("NOSUCHCMD")
        await c.close()
        await srv.stop()
    run(go())


def test_client_auth():
    async def go():
        srv = FakeRedisServer(password="sekrit")
        await srv.start()
        bad = RespClient(port=srv.port)
        await bad.connect()
        with pytest.raises(native.RespError):
            await bad.execute("GET", "x")
        await bad.close()
        good = RespClient(port=srv.port, password="sekrit")
        await good.connect()
        assert await good.execute("SET", "x", "1") == b"OK"
        await good.close()
        await srv.stop()
    run(go())


def test_client_reconnects_after_drop():
    async def go():
        srv = FakeRedisServer()
        await srv.start()
        c = RespClient(port=srv.port, retry_attempts=3, retry_interval=0.01)
        await c.connect()
        await c.execute("SET", "a", "1")
        # Server drops the connection mid-stream (fault injection).
        with pytest.raises((ConnectionClosed, asyncio.TimeoutError, ConnectionError)):
            await c._roundtrip("DROPCONN")
        # Retry path dials a fresh connection; state survives server-side.
        assert await c.execute("GET", "a") == b"1"
        assert c.reconnects >= 1
        await c.close()
        await srv.stop()
    run(go())


def test_sync_client_facade():
    with EmbeddedRedis() as er:
        with SyncRespClient(port=er.port) as c:
            assert c.execute("PING") == b"PONG"
            c.execute("SET", "s", b"\x00\xff")
            assert c.execute("GET", "s") == b"\x00\xff"
            got = c.pipeline([("SET", "p1", "a"), ("GET", "p1")])
            assert got == [b"OK", b"a"]


def test_fake_server_pfadd_pfcount_consistency():
    # The fake's PFCOUNT must agree with the framework's estimator since it
    # uses the same registers + hash.
    with EmbeddedRedis() as er:
        with SyncRespClient(port=er.port) as c:
            keys = [b"u%d" % i for i in range(20000)]
            c.pipeline([["PFADD", "sketch"] + keys[i:i + 1000]
                        for i in range(0, len(keys), 1000)])
            est = c.execute("PFCOUNT", "sketch")
            assert abs(est - 20000) / 20000 < 0.02
            # merge two sketches
            c.execute("PFADD", "s2", *[b"v%d" % i for i in range(1000)])
            c.execute("PFMERGE", "dest", "sketch", "s2")
            est2 = c.execute("PFCOUNT", "dest")
            assert est2 > est


# ---------------------------------------------------------------------------
# Durability flush / import (TPU store <-> fake redis)
# ---------------------------------------------------------------------------


@pytest.fixture()
def local_client():
    from redisson_tpu.client import RedissonTPU
    c = RedissonTPU.create()
    yield c
    c.shutdown()


def _dm(client, rc):
    """DurabilityManager wired the way the client wires it: HLLs live in
    the backend's bank (not the store), so flushing them needs the
    executor + bank-owning backend."""
    return DurabilityManager(client._store, rc, executor=client._executor,
                             pod_backend=client._pod_backend())


def test_durability_hll_roundtrip(local_client):
    h = local_client.get_hyper_log_log("d:hll")
    h.add_all([b"k%d" % i for i in range(30000)])
    est_before = h.count()

    with EmbeddedRedis() as er:
        with SyncRespClient(port=er.port) as rc:
            dm = _dm(local_client, rc)
            assert dm.flush(["d:hll"]) == 1
            # A "real" server can PFCOUNT the flushed blob directly.
            server_est = rc.execute("PFCOUNT", "d:hll")
            assert abs(server_est - est_before) / max(est_before, 1) < 0.01

            # Wipe local state, import back, estimate preserved exactly.
            local_client._executor.execute_sync("d:hll", "delete", None)
            assert dm.load_hll("d:hll")
            h2 = local_client.get_hyper_log_log("d:hll")
            assert abs(h2.count() - est_before) / max(est_before, 1) < 0.005


def test_durability_bitset_roundtrip(local_client):
    bs = local_client.get_bit_set("d:bits")
    idx = [1, 7, 8, 100, 4095]
    for i in idx:
        bs.set(i)
    with EmbeddedRedis() as er:
        with SyncRespClient(port=er.port) as rc:
            dm = DurabilityManager(local_client._store, rc)
            dm.flush(["d:bits"])
            # Server-side GETBIT agrees bit-for-bit (Redis SETBIT order).
            for i in idx:
                assert rc.execute("GETBIT", "d:bits", i) == 1
            assert rc.execute("GETBIT", "d:bits", 2) == 0
            assert rc.execute("BITCOUNT", "d:bits") == len(idx)

            local_client._store.delete("d:bits")
            assert dm.load_bitset("d:bits")
            bs2 = local_client.get_bit_set("d:bits")
            for i in idx:
                assert bs2.get(i)
            assert not bs2.get(2)


def test_durability_bloom_roundtrip(local_client):
    bf = local_client.get_bloom_filter("d:bloom")
    bf.try_init(expected_insertions=5000, false_probability=0.01)
    bf.add_all([b"item%d" % i for i in range(2000)])
    with EmbeddedRedis() as er:
        with SyncRespClient(port=er.port) as rc:
            dm = DurabilityManager(local_client._store, rc)
            dm.flush(["d:bloom"])
            # sidecar key carries hashtag braces, matching the reference's
            # {name}__config (RedissonBloomFilter.java:254-256)
            cfg = rc.execute("HGETALL", "{d:bloom}__config")
            cfgmap = {bytes(cfg[i]): bytes(cfg[i + 1]) for i in range(0, len(cfg), 2)}
            assert b"size" in cfgmap and b"hashIterations" in cfgmap

            local_client._store.delete("d:bloom")
            assert dm.load_bloom("d:bloom")
            bf2 = local_client.get_bloom_filter("d:bloom")
            hits = bf2.contains_all([b"item%d" % i for i in range(2000)])
            assert all(hits), "false negatives after import"


def test_durability_periodic_flush(local_client):
    h = local_client.get_hyper_log_log("d:p")
    h.add_all([b"x%d" % i for i in range(100)])
    with EmbeddedRedis() as er:
        with SyncRespClient(port=er.port) as rc:
            dm = _dm(local_client, rc)
            dm.start_periodic(interval=0.05)
            import time
            deadline = time.time() + 5
            while time.time() < deadline and dm.flushes == 0:
                time.sleep(0.05)
            dm.stop_periodic()
            assert dm.flushes >= 1
            assert rc.execute("EXISTS", "d:p") == 1


# ---------------------------------------------------------------------------
# Checkpoint / resume
# ---------------------------------------------------------------------------


def test_checkpoint_roundtrip(tmp_path, local_client):
    h = local_client.get_hyper_log_log("c:hll")
    h.add_all([b"k%d" % i for i in range(10000)])
    bs = local_client.get_bit_set("c:bits")
    bs.set(42)
    est = h.count()

    path = str(tmp_path / "ckpt")
    n = local_client.save_checkpoint(path)
    assert n == 2
    meta = checkpoint.info(path)
    assert set(meta["objects"]) == {"c:hll", "c:bits"}

    local_client.flushall()
    assert local_client.get_hyper_log_log("c:hll").count() == 0

    assert local_client.load_checkpoint(path) == 2
    assert local_client.get_hyper_log_log("c:hll").count() == est
    assert local_client.get_bit_set("c:bits").get(42)


def test_checkpoint_atomic_overwrite(tmp_path, local_client):
    local_client.get_bit_set("c2:b").set(1)
    path = str(tmp_path / "ck")
    checkpoint.save(local_client._store, path)
    local_client.get_bit_set("c2:b").set(9)
    checkpoint.save(local_client._store, path)  # overwrite in place
    local_client.flushall()
    checkpoint.load(local_client._store, path)
    assert local_client.get_bit_set("c2:b").get(9)


# ---------------------------------------------------------------------------
# Facade wiring: Config.redis as durability tier
# ---------------------------------------------------------------------------


def test_client_facade_durability_wiring():
    from redisson_tpu.client import RedissonTPU
    from redisson_tpu.config import Config

    with EmbeddedRedis() as er:
        cfg = Config()
        cfg.use_local()
        rc = cfg.use_redis()
        rc.address = f"redis://127.0.0.1:{er.port}"
        cfg.flush_interval_s = 0.05

        client = RedissonTPU.create(cfg)
        try:
            assert client.durability is not None
            h = client.get_hyper_log_log("w:hll")
            h.add_all([b"k%d" % i for i in range(5000)])
            est = h.count()
            n = client.flush_to_redis()
            assert n >= 1
        finally:
            client.shutdown()  # also runs the final flush

        # the flushed blob is server-readable
        with SyncRespClient(port=er.port) as probe:
            got = probe.execute("PFCOUNT", "w:hll")
            assert abs(got - est) / max(est, 1) < 0.01


def test_config_roundtrip_with_redis_tier(tmp_path):
    from redisson_tpu.config import Config

    cfg = Config()
    cfg.use_tpu()
    r = cfg.use_redis()
    r.address = "redis://10.0.0.1:6380"
    r.password = "pw"
    cfg.flush_interval_s = 12.5
    text = cfg.to_json()
    back = Config.from_json(text)
    assert back.redis.address == "redis://10.0.0.1:6380"
    assert back.redis.password == "pw"
    assert back.flush_interval_s == 12.5
    assert back.mode() == "tpu"


# ---------------------------------------------------------------------------
# Review regressions
# ---------------------------------------------------------------------------


def test_resp_parser_depth_cap():
    # A hostile stream of deeply nested arrays must not overflow the stack.
    p = native.RespParser()
    try:
        got = p.feed(b"*1\r\n" * 500 + b":1\r\n")
        # Poisoned stream: one top-level in-band error, then nothing —
        # the client treats it as a server error and tears down.
        assert len(got) == 1
        assert isinstance(got[0], native.RespError)
        assert "protocol violation" in str(got[0])
        assert p.feed(b"+OK\r\n") == []  # everything after poison is dropped
    finally:
        p.close()


def test_resp_parser_feed_after_close_raises():
    p = native.RespParser()
    p.close()
    with pytest.raises(ValueError):
        p.feed(b"+OK\r\n")


def test_pipeline_on_closed_client_raises():
    async def go():
        srv = FakeRedisServer()
        await srv.start()
        c = RespClient(port=srv.port)
        await c.connect()
        await c.close()
        with pytest.raises(ConnectionClosed):
            await c.pipeline([("PING",)])
        await srv.stop()
    run(go())


def test_periodic_flush_skips_clean_objects(local_client):
    h = local_client.get_hyper_log_log("dirty:h")
    h.add_all([b"a%d" % i for i in range(100)])
    with EmbeddedRedis() as er:
        with SyncRespClient(port=er.port) as rc:
            dm = _dm(local_client, rc)
            assert dm.flush(only_dirty=True) == 1   # first flush writes
            assert dm.flush(only_dirty=True) == 0   # nothing changed
            h.add(b"new-key")
            assert dm.flush(only_dirty=True) == 1   # mutation re-flushes
            assert dm.flush() == 1                  # full flush ignores tracking


def test_failed_flush_keeps_objects_dirty(local_client):
    h = local_client.get_hyper_log_log("dirty:fail")
    h.add_all([b"q%d" % i for i in range(50)])
    with EmbeddedRedis() as er:
        rc = SyncRespClient(port=er.port)
        rc.connect()
        dm = _dm(local_client, rc)
        rc.close()  # write will fail
        with pytest.raises(Exception):
            dm.flush(only_dirty=True)
        # Object must still be dirty: a fresh client flushes it.
        rc2 = SyncRespClient(port=er.port)
        rc2.connect()
        dm.client = rc2
        assert dm.flush(only_dirty=True) == 1
        rc2.close()


# ---------------------------------------------------------------------------
# retry idempotency, stale TTLs, passthrough checkpoint gating
# ---------------------------------------------------------------------------


def test_non_idempotent_timeout_raises_possibly_executed():
    # A server that swallows commands after the write: response timeout.
    # INCRBY must NOT be blindly retried (double-apply hazard); GET may.
    from redisson_tpu.interop.resp_client import PossiblyExecuted

    async def go():
        async def mute_handler(reader, writer):
            while await reader.read(1 << 12):
                pass  # read and never reply
            # 3.12 Server.wait_closed() waits for handler writers to close
            writer.close()

        srv = await asyncio.start_server(mute_handler, "127.0.0.1", 0)
        port = srv.sockets[0].getsockname()[1]
        c = RespClient(port=port, timeout=0.2, retry_attempts=1,
                       retry_interval=0.01)
        await c.connect()
        t0 = asyncio.get_event_loop().time()
        with pytest.raises(PossiblyExecuted):
            await c.execute("INCRBY", "k", "2")
        one_try = asyncio.get_event_loop().time() - t0
        assert one_try < 1.0  # single attempt, no retry schedule
        with pytest.raises((asyncio.TimeoutError, ConnectionError)):
            await c.execute("GET", "k")  # idempotent: retried, then raises
        await c.close()
        srv.close()
        await srv.wait_closed()

    run(go())


def test_recreated_key_does_not_inherit_stale_ttl():
    # SREM-to-empty (and friends) delete keys without popping their expiry;
    # a re-created key must start TTL-free, as on a real server.
    with EmbeddedRedis() as er:
        with SyncRespClient(port=er.port) as rc:
            rc.execute("SET", "t:k", "v", "PX", "60000")
            rc.execute("DEL", "t:k")
            rc.execute("SETNX", "t:k", "v2")
            assert rc.execute("PTTL", "t:k") == -1
            # FLUSHALL clears deadlines too
            rc.execute("SET", "t:f", "v", "PX", "60000")
            rc.execute("FLUSHALL")
            rc.execute("SET", "t:f", "v")
            assert rc.execute("PTTL", "t:f") == -1


def test_checkpoint_gated_in_redis_mode(tmp_path):
    from redisson_tpu.client import RedissonTPU
    from redisson_tpu.config import Config

    with EmbeddedRedis() as er:
        cfg = Config()
        cfg.use_redis().address = f"redis://127.0.0.1:{er.port}"
        client = RedissonTPU.create(cfg)
        try:
            with pytest.raises(NotImplementedError):
                client.save_checkpoint(str(tmp_path / "cp"))
            with pytest.raises(NotImplementedError):
                client.load_checkpoint(str(tmp_path / "cp"))
        finally:
            client.shutdown()


def test_durability_blocked_bloom_roundtrip(local_client):
    """The blocked-layout flag must survive a flush/reload cycle — without
    it, classic index derivation over blocked-layout bits would produce
    false negatives (review r3)."""
    bf = local_client.get_bloom_filter("d:bblock")
    bf.try_init(expected_insertions=5000, false_probability=0.01, blocked=True)
    bf.add_all([b"bk%d" % i for i in range(2000)])
    with EmbeddedRedis() as er:
        with SyncRespClient(port=er.port) as rc:
            dm = DurabilityManager(local_client._store, rc)
            dm.flush(["d:bblock"])
            local_client._store.delete("d:bblock")
            assert dm.load_bloom("d:bblock")
            bf2 = local_client.get_bloom_filter("d:bblock")
            assert bf2.is_blocked() is True
            hits = bf2.contains_all([b"bk%d" % i for i in range(2000)])
            assert all(hits), "false negatives after blocked import"


def test_blocked_add_padded_lanes_do_not_set_bit_zero(local_client):
    """A padded (invalid) lane must not set absolute bit 0 (review r3:
    unmasked max(1) on masked index 0)."""
    import numpy as np

    bf = local_client.get_bloom_filter("d:bpad")
    bf.try_init(expected_insertions=5000, false_probability=0.01, blocked=True)
    bf.add(b"solo")  # batch of 1 pads up to the bucket size
    obj = local_client._store.get("d:bpad")
    state = np.asarray(obj.state)
    assert state.sum() == bf.get_hash_iterations()  # exactly k bits set


def test_classic_bloom_import_clears_stale_blocked_flag(local_client):
    """Importing a classic dump over a live blocked filter must clear the
    layout flag (review r3: the flag is only written when true, so a
    meta-merge would keep stale blocked=True -> false negatives)."""
    bf = local_client.get_bloom_filter("d:swap")
    bf.try_init(expected_insertions=5000, false_probability=0.01, blocked=True)
    with EmbeddedRedis() as er:
        with SyncRespClient(port=er.port) as rc:
            dm = DurabilityManager(local_client._store, rc)
            # Write a CLASSIC dump under the same name from a scratch store.
            from redisson_tpu.store import SketchStore

            scratch = SketchStore(device=local_client._store.device)
            from redisson_tpu.client import RedissonTPU as _R  # same proc
            import numpy as _np

            c2 = _R.create()
            try:
                src = c2.get_bloom_filter("d:swap")
                src.try_init(expected_insertions=5000, false_probability=0.01)
                src.add_all([b"c%d" % i for i in range(1000)])
                DurabilityManager(c2._store, rc).flush(["d:swap"])
            finally:
                c2.shutdown()
            assert dm.load_bloom("d:swap")
            bf2 = local_client.get_bloom_filter("d:swap")
            assert bf2.is_blocked() is False
            assert all(bf2.contains_all([b"c%d" % i for i in range(1000)]))
