"""Test config: force a virtual 8-device CPU platform BEFORE any backend
initializes.

Two layers of forcing are required in this image:
  * env vars (JAX_PLATFORMS / XLA_FLAGS) for a plain environment;
  * jax.config.update("jax_platforms", ...) because the axon sitecustomize
    registers the TPU plugin at interpreter startup and explicitly sets
    jax_platforms="axon,cpu", which overrides the env var. Without this
    override every pytest process dials the single TPU tunnel and serializes
    behind whichever process holds it (observed as silent multi-minute
    hangs at jax.devices()).

Multi-chip sharding is tested on the virtual CPU mesh; the driver separately
dry-runs the sharded path via __graft_entry__.dryrun_multichip.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=8"

import jax

jax.config.update("jax_platforms", "cpu")
assert all(d.platform == "cpu" for d in jax.devices()), (
    "a backend initialized before conftest could force CPU"
)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy tests (big allocations / long runs) excluded from the "
        "tier-1 `-m 'not slow'` pass")
