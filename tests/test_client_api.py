"""End-to-end API tests over the local (CPU) sketch engine — the analogue of
the reference's per-object functional suites (RedissonHyperLogLogTest,
RedissonBitSetTest, RedissonBloomFilterTest) against its embedded fixture."""

import numpy as np
import pytest

from redisson_tpu.client import RedissonTPU
from redisson_tpu.config import Config


@pytest.fixture(scope="module")
def client():
    c = RedissonTPU.create(Config())
    yield c
    c.shutdown()


@pytest.fixture(autouse=True)
def _flush(client):
    client.flushall()


class TestHyperLogLog:
    def test_add_count(self, client):
        hll = client.get_hyper_log_log("hll:basic")
        assert hll.add("a") is True
        hll.add_all(["b", "c", "d", 17, (1, 2)])
        n = hll.count()
        assert 5 <= n <= 7  # 6 distinct, small-range exactness not guaranteed

    def test_add_duplicates_not_counted(self, client):
        hll = client.get_hyper_log_log("hll:dup")
        hll.add_all(["x"] * 100)
        assert hll.count() == 1

    def test_count_with_and_merge_with(self, client):
        a = client.get_hyper_log_log("hll:a")
        b = client.get_hyper_log_log("hll:b")
        a.add_all([f"a{i}" for i in range(3000)])
        b.add_all([f"b{i}" for i in range(3000)])
        union = a.count_with("hll:b")
        assert abs(union - 6000) / 6000 < 0.05
        a.merge_with("hll:b")
        merged = a.count()
        assert abs(merged - 6000) / 6000 < 0.05
        # b unchanged
        assert abs(b.count() - 3000) / 3000 < 0.05

    def test_int_fast_path_same_as_string_of_bytes(self, client):
        hll = client.get_hyper_log_log("hll:ints")
        hll.add_ints(np.arange(50_000, dtype=np.uint64))
        est = hll.count()
        assert abs(est - 50_000) / 50_000 < 0.03

    def test_delete_exists(self, client):
        hll = client.get_hyper_log_log("hll:del")
        assert not hll.is_exists()
        hll.add("x")
        assert hll.is_exists()
        assert hll.delete() is True
        assert not hll.is_exists()
        assert hll.count() == 0


class TestBitSet:
    def test_set_get(self, client):
        bs = client.get_bit_set("bs:basic")
        assert bs.set(3) is False  # previous value
        assert bs.set(3) is True
        assert bs.get(3) is True
        assert bs.get(4) is False
        assert bs.set(3, False) is True
        assert bs.get(3) is False

    def test_batch_and_aggregates(self, client):
        bs = client.get_bit_set("bs:agg")
        old = bs.set_bits([1, 5, 9, 5])
        assert old.tolist() == [False, False, False, False]
        assert bs.cardinality() == 3
        assert bs.length() == 10
        assert bs.size() >= 10

    def test_auto_grow(self, client):
        bs = client.get_bit_set("bs:grow")
        bs.set(100_000)
        assert bs.get(100_000) is True
        assert bs.length() == 100_001
        assert bs.get(1_000_000) is False  # out of allocated range reads 0

    def test_set_range_and_clear(self, client):
        bs = client.get_bit_set("bs:range")
        bs.set_range(10, 20)
        assert bs.cardinality() == 10
        bs.clear(12, 15)
        assert bs.cardinality() == 7
        assert bs.get(12) is False
        assert bs.get(15) is True

    def test_bitops(self, client):
        a = client.get_bit_set("bs:opA")
        b = client.get_bit_set("bs:opB")
        a.set_bits([1, 2, 3])
        b.set_bits([2, 3, 4])
        a.or_("bs:opB")
        assert np.flatnonzero(a.to_numpy()).tolist() == [1, 2, 3, 4]
        a2 = client.get_bit_set("bs:opC")
        a2.set_bits([1, 2])
        a2.and_("bs:opB")
        assert np.flatnonzero(a2.to_numpy()).tolist() == [2]

    def test_to_numpy_roundtrip(self, client):
        bs = client.get_bit_set("bs:np")
        bs.set_bits([0, 7, 63])
        arr = bs.to_numpy()
        assert arr.shape[0] == 64
        assert np.flatnonzero(arr).tolist() == [0, 7, 63]


class TestBloomFilter:
    def test_try_init_once(self, client):
        bf = client.get_bloom_filter("bf:init")
        assert bf.try_init(1000, 0.01) is True
        assert bf.try_init(1000, 0.01) is False  # already exists
        assert bf.get_expected_insertions() == 1000
        assert bf.get_false_probability() == 0.01
        assert bf.get_size() == 9585  # guava sizing for (1000, 0.01)
        assert bf.get_hash_iterations() == 7

    def test_add_contains(self, client):
        bf = client.get_bloom_filter("bf:basic")
        bf.try_init(10_000, 0.02)
        members = [f"user:{i}" for i in range(2000)]
        added = bf.add_all(members)
        assert added.all()
        assert bf.contains("user:0")
        assert bf.contains_all(members).all()
        added2 = bf.add_all(members)
        assert not added2.any()
        fresh = [f"ghost:{i}" for i in range(2000)]
        fpr = bf.contains_all(fresh).mean()
        assert fpr < 0.06

    def test_count(self, client):
        bf = client.get_bloom_filter("bf:count")
        bf.try_init(10_000, 0.01)
        bf.add_all([f"k{i}" for i in range(5000)])
        assert abs(bf.count() - 5000) / 5000 < 0.05

    def test_uninitialized_raises(self, client):
        bf = client.get_bloom_filter("bf:raw")
        with pytest.raises(RuntimeError, match="not initialized"):
            bf.add("x")

    def test_try_init_rejects_nonpositive_insertions(self, client):
        bf = client.get_bloom_filter("bf:zero")
        with pytest.raises(ValueError, match="positive"):
            bf.try_init(0, 0.01)
        with pytest.raises(ValueError, match="positive"):
            bf.try_init(-5, 0.01)

    def test_try_init_rejects_unrepresentable_geometry(self, client):
        # (300M, 0.01) derives m = 2_875_517_513 bits: past 2^31 and not a
        # power of two, so ops/bloom._mod_u64 index math would be inexact.
        # Must fail fast at sizing time, before any allocation.
        bf = client.get_bloom_filter("bf:huge")
        with pytest.raises(ValueError, match="power of two"):
            bf.try_init(300_000_000, 0.01)
        with pytest.raises(ValueError, match="power of two"):
            bf.try_init(300_000_000, 0.01, blocked=True)
        # the failed attempts must not have created the object
        assert bf.try_init(1000, 0.01) is True


class TestBatch:
    def test_pipelined_hll_and_merge(self, client):
        # BASELINE config #3 shape: pipelined PFADD across sketches + merge.
        batch = client.create_batch()
        for s in range(16):
            batch.get_hyper_log_log(f"batch:hll:{s}").add_all_async(
                [f"s{s}:k{i}" for i in range(200)]
            )
        results = batch.execute()
        assert len(results) == 16
        main = client.get_hyper_log_log("batch:hll:0")
        main.merge_with(*[f"batch:hll:{s}" for s in range(1, 16)])
        est = main.count()
        assert abs(est - 3200) / 3200 < 0.05

    def test_results_in_staging_order(self, client):
        bs = client.get_bit_set("batch:bs")
        bs.set_bits([0, 1, 2])
        batch = client.create_batch()
        batch.get_bit_set("batch:bs").get_bits_async([0])
        batch.get_hyper_log_log("batch:h").add_all_async(["x"])
        batch.get_bit_set("batch:bs").get_bits_async([9])
        r = batch.execute()
        assert r[0].tolist() == [True]
        assert r[1] is True
        assert r[2].tolist() == [False]

    def test_batch_reuse_rejected(self, client):
        batch = client.create_batch()
        batch.get_hyper_log_log("batch:r").add_all_async(["x"])
        batch.execute()
        with pytest.raises(RuntimeError):
            batch.execute()

    def test_staged_future_before_execute_raises(self, client):
        batch = client.create_batch()
        fut = batch.get_hyper_log_log("batch:f").add_all_async(["x"])
        with pytest.raises(RuntimeError, match="not executed"):
            fut.result()


class TestExecutorSemantics:
    def test_per_object_fifo_read_your_writes(self, client):
        bs = client.get_bit_set("sem:fifo")
        futs = []
        for i in range(50):
            futs.append(bs.set_bits_async([i]))
            futs.append(bs.get_bits_async([i]))
        for i in range(50):
            assert futs[2 * i + 1].result().tolist() == [True]

    def test_wrong_type_error(self, client):
        client.get_hyper_log_log("sem:type").add("x")
        with pytest.raises(TypeError):
            client.get_bit_set("sem:type").set(1)

    def test_concurrent_adds_from_threads(self, client):
        import threading

        hll = client.get_hyper_log_log("sem:threads")

        def work(t):
            hll.add_all([f"t{t}:k{i}" for i in range(500)])

        threads = [threading.Thread(target=work, args=(t,)) for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        est = hll.count()
        assert abs(est - 4000) / 4000 < 0.05


class TestConfig:
    def test_json_yaml_roundtrip(self):
        cfg = Config()
        cfg.use_tpu().hll_impl = "scatter"
        cfg.flush_interval_s = 5.0
        as_json = cfg.to_json()
        back = Config.from_json(as_json)
        assert back.tpu.hll_impl == "scatter"
        assert back.flush_interval_s == 5.0
        back2 = Config.from_yaml(cfg.to_yaml())
        assert back2.tpu.hll_impl == "scatter"

    def test_mode_exclusivity(self):
        cfg = Config()
        cfg.use_local()
        cfg.use_tpu()
        with pytest.raises(ValueError):
            cfg.mode()


def test_batch_covers_structure_objects(client):
    # Reference RedissonBatch clones every object family; mixed staged ops
    # resolve in staging order.
    b = client.create_batch()
    b.get_bucket("bt:b").set_async(1)
    b.get_map("bt:m").put_async("k", "v")
    b.get_atomic_long("bt:a").increment_and_get_async()
    b.get_set("bt:s").add_async("x")
    b.get_list("bt:l").add_async("item")
    b.get_scored_sorted_set("bt:z").add_async(1.5, "m")
    b.get_hyper_log_log("bt:h").add_all_async([b"1", b"2"])
    results = b.execute()
    assert len(results) == 7
    assert client.get_bucket("bt:b").get() == 1
    assert client.get_map("bt:m").get("k") == "v"
    assert client.get_atomic_long("bt:a").get() == 1
    assert client.get_set("bt:s").contains("x")
    assert client.get_list("bt:l").get(0) == "item"
    assert client.get_scored_sorted_set("bt:z").get_score("m") == 1.5
    assert client.get_hyper_log_log("bt:h").count() == 2


def test_add_device_resident(client):
    """Device-resident ingest (add_device) matches the host packed path."""
    import jax
    import numpy as np

    from redisson_tpu.models.object import pack_u64

    h = client.get_hyper_log_log("hll:dev")
    keys = np.arange(1, 50_001, dtype=np.uint64) * np.uint64(0x9E3779B97F4A7C15)
    dev_arr = jax.device_put(pack_u64(keys))
    assert h.add_device(dev_arr) is True
    est_dev = h.count()
    h2 = client.get_hyper_log_log("hll:host")
    h2.add_ints(keys)
    assert h2.count() == est_dev  # identical registers -> identical estimate
    # Ragged (non-bucket) device batch pads on device.
    h3 = client.get_hyper_log_log("hll:devragged")
    assert h3.add_device(jax.device_put(pack_u64(keys[:1111]))) is True
    assert abs(h3.count() - 1111) / 1111 < 0.05


def test_add_device_larger_than_max_bucket(client, monkeypatch):
    """Device batches above the chunk cap split like the host path."""
    import jax
    import numpy as np

    from redisson_tpu import engine
    from redisson_tpu.models.object import pack_u64

    monkeypatch.setattr(engine, "MAX_BUCKET", 1 << 12)
    h = client.get_hyper_log_log("hll:devbig")
    n = (1 << 12) * 2 + 77
    keys = np.arange(1, n + 1, dtype=np.uint64) * np.uint64(0x2545F4914F6CDD1D)
    assert h.add_device(jax.device_put(pack_u64(keys))) is True
    assert abs(h.count() - n) / n < 0.05


class TestHostfoldIngest:
    """Transfer-adaptive ingest (backend_tpu hostfold path): forced on, the
    client must produce the same estimates and changed-bits as the device
    path — they are drop-in replacements chosen by the link probe."""

    @pytest.fixture(scope="class")
    def hf_client(self):
        from redisson_tpu import native
        from redisson_tpu.config import TpuConfig

        # Check availability BEFORE create(): forced hostfold without the
        # native lib raises by contract, and this guard exists to skip
        # (not error) on hosts that cannot build it.
        if not native.available():
            pytest.skip("native library unavailable")
        c = RedissonTPU.create(Config(tpu=TpuConfig(ingest="hostfold")))
        yield c
        c.shutdown()

    def test_add_ints_roundtrip(self, hf_client):
        h = hf_client.get_hyper_log_log("hf:ints")
        keys = np.random.default_rng(3).integers(
            0, 2**63, size=200_000, dtype=np.uint64)
        assert h.add_ints(keys) is True
        assert h.add_ints(keys) is False  # replay raises nothing
        err = abs(h.count() - 200_000) / 200_000
        assert err < 0.02

    def test_matches_device_path(self, hf_client):
        from redisson_tpu.config import TpuConfig

        dev_client = RedissonTPU.create(Config(tpu=TpuConfig(ingest="device")))
        try:
            keys = np.random.default_rng(5).integers(
                0, 2**63, size=150_000, dtype=np.uint64)
            a = hf_client.get_hyper_log_log("hf:match")
            b = dev_client.get_hyper_log_log("hf:match")
            a.add_ints(keys)
            b.add_ints(keys)
            assert a.count() == b.count()
        finally:
            dev_client.shutdown()

    def test_byte_keys_roundtrip(self, hf_client):
        h = hf_client.get_hyper_log_log("hf:bytes")
        # Force the rows fold by exceeding HOSTFOLD_MIN_KEYS in one call.
        from redisson_tpu import backend_tpu

        n = backend_tpu.HOSTFOLD_MIN_KEYS + 5
        h.add_all([f"k{i}" for i in range(n)])
        assert abs(h.count() - n) / n < 0.03


def test_bucket_batch_helpers_and_lifecycle():
    """findBuckets / loadBucketValues / saveBuckets / getConfig /
    isShutdown facade parity (RedissonClient.java:174-192, 686, 708-715)."""
    c = RedissonTPU.create()
    try:
        c.save_buckets({"fb:a": 1, "fb:b": 2, "other": 3})
        assert {b.name for b in c.find_buckets("fb:*")} == {"fb:a", "fb:b"}
        assert c.load_bucket_values("fb:a", "fb:b") == {"fb:a": 1, "fb:b": 2}
        assert c.load_bucket_values(["fb:a", "missing"]) == {"fb:a": 1}
        assert c.get_config() is c.config
        assert c.get_cluster_nodes_group() is not None
        assert not c.is_shutdown()
        assert not c.is_shutting_down()
    finally:
        c.shutdown()
    assert c.is_shutdown()
    assert not c.is_shutting_down()
