"""Multi-endpoint redis topology: master/slave routing, failover promotion,
MOVED/ASK redirects (VERDICT r2 missing #2 / next #4).

Reference shapes: `connection/MasterSlaveEntry.java:53-250` (write/read
split + changeMaster), `balancer/LoadBalancerManagerImpl.java:39-90`,
`command/CommandAsyncService.java:593-685` (redirects). The reference never
CI-tests real topologies (SURVEY §4 weak spot); these run against two
in-process fake servers with write replication.
"""

from __future__ import annotations

import time

import pytest

from redisson_tpu.client import RedissonTPU
from redisson_tpu.config import Config
from redisson_tpu.interop.fake_server import EmbeddedRedis
from redisson_tpu.interop.pool import RespConnectionPool
from redisson_tpu.interop.topology_redis import MasterSlaveRouter
from redisson_tpu.ops import crc16


def _fast_factory(host: str, port: int) -> RespConnectionPool:
    return RespConnectionPool(
        host=host, port=port, timeout=1.0, retry_attempts=1,
        retry_interval=0.05, size=2, min_idle=1, failed_attempts=2,
        reconnection_timeout=0.3)


@pytest.fixture()
def pair():
    master, slave = EmbeddedRedis.pair()
    try:
        yield master, slave
    finally:
        slave.stop()
        master.stop()


def _patient_factory(host: str, port: int) -> RespConnectionPool:
    """For routing-only tests: generous timeouts so a loaded 1-core CI host
    can't trip the freeze threshold and silently fall reads back to the
    master (which is exactly what these tests assert does NOT happen)."""
    return RespConnectionPool(
        host=host, port=port, timeout=5.0, retry_attempts=2,
        retry_interval=0.1, size=2, min_idle=1, failed_attempts=10,
        reconnection_timeout=0.3)


def test_write_to_master_read_from_slave(pair):
    master, slave = pair
    router = MasterSlaveRouter(
        _patient_factory, f"127.0.0.1:{master.port}",
        [f"127.0.0.1:{slave.port}"], read_mode="SLAVE")
    router.connect()
    try:
        router.execute("SET", "k", "v")
        # Write landed on master and replicated to slave.
        assert master.server.data.get(b"k") == b"v"
        assert slave.server.data.get(b"k") == b"v"
        # Read served by the slave: poison the value there to prove routing.
        slave.server.data[b"k"] = b"from-slave"
        assert router.execute("GET", "k") == b"from-slave"
        assert master.server.data.get(b"k") == b"v"  # master untouched
    finally:
        router.close()


def test_read_mode_master_never_touches_slave(pair):
    master, slave = pair
    router = MasterSlaveRouter(
        _fast_factory, f"127.0.0.1:{master.port}",
        [f"127.0.0.1:{slave.port}"], read_mode="MASTER")
    router.connect()
    try:
        router.execute("SET", "k2", "v")
        slave.server.data[b"k2"] = b"poison"
        assert router.execute("GET", "k2") == b"v"
    finally:
        router.close()


def test_kill_master_promotes_slave_reads_survive_writes_resume(pair):
    """The VERDICT's done-criterion: kill-master shows reads surviving and
    writes resuming after promotion."""
    master, slave = pair
    router = MasterSlaveRouter(
        _fast_factory, f"127.0.0.1:{master.port}",
        [f"127.0.0.1:{slave.port}"], read_mode="SLAVE")
    router.connect()
    try:
        router.execute("SET", "fk", "before")
        master.kill()  # kill the master server (loop stays up for the slave)
        # Reads keep working off the slave throughout.
        assert router.execute("GET", "fk") == b"before"
        # Writes fail over: promotion happens on the first failed write.
        deadline = time.time() + 10
        wrote = False
        while time.time() < deadline:
            try:
                router.execute("SET", "fk", "after")
                wrote = True
                break
            except Exception:
                time.sleep(0.2)
        assert wrote
        assert router.promotions >= 1
        assert router.master_address.endswith(str(slave.port))
        assert router.execute("GET", "fk") == b"after"
    finally:
        router.close()


def test_moved_redirect_follows_and_caches(pair):
    master, slave = pair
    # master disowns key "mk"'s slot; the slave owns it.
    slot = crc16.key_slot("mk")
    master.server.moved_slots[slot] = f"127.0.0.1:{slave.port}"
    router = MasterSlaveRouter(
        _fast_factory, f"127.0.0.1:{master.port}", [], read_mode="MASTER")
    router.connect()
    try:
        router.execute("SET", "mk", "v1")
        assert router.redirects == 1
        assert slave.server.data.get(b"mk") == b"v1"
        assert b"mk" not in master.server.data
        # Slot now cached: the next command goes direct, no new redirect.
        router.execute("SET", "mk", "v2")
        assert router.redirects == 1
        assert slave.server.data.get(b"mk") == b"v2"
        assert router.execute("GET", "mk") == b"v2"
    finally:
        router.close()


def test_ask_redirect_is_one_shot_with_asking(pair):
    master, slave = pair
    key = b"ak"
    master.server.ask_keys[key] = f"127.0.0.1:{slave.port}"
    slave.server.importing.add(key)  # target demands the ASKING prefix
    router = MasterSlaveRouter(
        _fast_factory, f"127.0.0.1:{master.port}", [], read_mode="MASTER")
    router.connect()
    try:
        router.execute("SET", "ak", "mig")
        assert router.redirects == 1
        assert slave.server.data.get(key) == b"mig"
        # ASK does not cache: migration ends, key is served by master again.
        del master.server.ask_keys[key]
        router.execute("SET", "ak", "home")
        assert master.server.data.get(key) == b"home"
        assert router.redirects == 1
    finally:
        router.close()


def test_client_facade_over_master_slave(pair):
    """End-to-end: RedissonTPU in redis mode with slave_addresses routes
    through the router transparently."""
    master, slave = pair
    cfg = Config.from_dict({"redis": {
        "address": f"redis://127.0.0.1:{master.port}",
        "slave_addresses": [f"redis://127.0.0.1:{slave.port}"],
        "read_mode": "SLAVE",
        "timeout_ms": 1000, "failed_attempts": 2,
    }})
    c = RedissonTPU.create(cfg)
    try:
        m = c.get_map("tm")
        m.fast_put("a", 1)
        assert m.get("a") == 1            # read rides the slave (replicated)
        assert b"tm" in master.server.data
        assert b"tm" in slave.server.data
        h = c.get_hyper_log_log("th")
        h.add_all([f"k{i}" for i in range(100)])
        assert abs(h.count() - 100) <= 2  # PFCOUNT served from the slave
    finally:
        c.shutdown()


def test_topic_wakeups_survive_failover(pair):
    """Pub/sub follows master promotion: the subscribe connection re-dials
    the router's CURRENT master, so topic messages published after failover
    still arrive (reference: pub/sub reattach on changeMaster,
    MasterSlaveEntry.java:158-250)."""
    import threading

    master, slave = pair

    def make(port_master):
        cfg = Config.from_dict({"redis": {
            "address": f"redis://127.0.0.1:{port_master}",
            "slave_addresses": [f"redis://127.0.0.1:{slave.port}"],
            "timeout_ms": 1000, "failed_attempts": 1,
            "retry_attempts": 1, "retry_interval_ms": 50,
        }})
        return RedissonTPU.create(cfg)

    c1, c2 = make(master.port), make(master.port)
    try:
        got = threading.Event()
        c2.get_topic("ft").add_listener(lambda ch, msg: got.set())
        master.kill()
        # Drive both clients through promotion with a write each.
        for c in (c1, c2):
            deadline = time.time() + 10
            while time.time() < deadline:
                try:
                    c.get_bucket(f"poke:{id(c)}").set(1)
                    break
                except Exception:
                    time.sleep(0.1)
        assert c1._resp.promotions >= 1 and c2._resp.promotions >= 1
        # Publish after failover: the subscriber must get it via the NEW
        # master within the reconnect window.
        deadline = time.time() + 10
        while time.time() < deadline and not got.is_set():
            try:
                c1.get_topic("ft").publish("hello")
            except Exception:
                pass
            time.sleep(0.2)
        assert got.is_set()
    finally:
        c1.shutdown()
        c2.shutdown()


# -- sentinel mode ----------------------------------------------------------


@pytest.fixture()
def sentinel_setup():
    """master + slave (replicating pair) + one sentinel server that
    monitors them — three in-process servers, the topology the reference
    can only test with disabled hardcoded configs (SURVEY §4)."""
    master, slave = EmbeddedRedis.pair()
    sentinel = EmbeddedRedis(share_with=master)
    sentinel.server.sentinel_masters["mymaster"] = f"127.0.0.1:{master.port}"
    sentinel.server.sentinel_slaves["mymaster"] = [f"127.0.0.1:{slave.port}"]
    try:
        yield master, slave, sentinel
    finally:
        sentinel.stop()
        slave.stop()
        master.stop()


def test_sentinel_bootstrap_and_routing(sentinel_setup):
    """SentinelManager discovers master/slaves by name
    (SentinelConnectionManager.java:74-105) and routes like the
    master/slave router."""
    master, slave, sentinel = sentinel_setup
    cfg = Config.from_dict({"redis": {
        "address": "redis://ignored:1",     # sentinel mode overrides this
        "sentinel_addresses": [f"redis://127.0.0.1:{sentinel.port}"],
        "master_name": "mymaster",
        "timeout_ms": 1000, "failed_attempts": 2,
    }})
    c = RedissonTPU.create(cfg)
    try:
        assert c._resp.master_address.endswith(str(master.port))
        b = c.get_bucket("sb")
        b.set("v")
        assert b.get() == "v"
        assert b"sb" in master.server.data       # write hit the real master
        assert b"sb" in slave.server.data        # replicated
    finally:
        c.shutdown()


def test_sentinel_switch_master_event(sentinel_setup):
    """+switch-master published by the sentinel re-points writes at the new
    master without any failed command (SentinelConnectionManager.java:
    143-192 event path)."""
    master, slave, sentinel = sentinel_setup
    cfg = Config.from_dict({"redis": {
        "sentinel_addresses": [f"redis://127.0.0.1:{sentinel.port}"],
        "master_name": "mymaster",
        "timeout_ms": 1000, "failed_attempts": 2,
    }})
    c = RedissonTPU.create(cfg)
    try:
        c.get_bucket("sw").set(1)
        # Sentinel announces the switch (as after a real failover vote).
        from redisson_tpu.interop.resp_client import SyncRespClient

        pub = SyncRespClient(port=sentinel.port)
        pub.connect()
        try:
            pub.execute(
                "PUBLISH", "+switch-master",
                f"mymaster 127.0.0.1 {master.port} 127.0.0.1 {slave.port}")
        finally:
            pub.close()
        deadline = time.time() + 5
        while time.time() < deadline and not c._resp.master_address.endswith(
                str(slave.port)):
            time.sleep(0.05)
        assert c._resp.master_address.endswith(str(slave.port))
        # Real failover re-points replication (REPLICAOF): the promoted
        # node now feeds the demoted one, so slave-routed reads see writes.
        slave.server.replicas.append(master.server)
        master.server.replicas.clear()
        # Writes now land on the promoted node.
        c.get_bucket("after").set(2)
        assert b"after" in slave.server.data
        assert c.get_bucket("after").get() == 2
    finally:
        c.shutdown()


def test_sentinel_slave_events_update_rotation(sentinel_setup):
    """+sdown drops a replica from the read rotation; -sdown / +slave
    re-admit it (SentinelConnectionManager slave up/down handling)."""
    master, slave, sentinel = sentinel_setup
    cfg = Config.from_dict({"redis": {
        "sentinel_addresses": [f"redis://127.0.0.1:{sentinel.port}"],
        "master_name": "mymaster",
        "timeout_ms": 1000, "failed_attempts": 2,
    }})
    c = RedissonTPU.create(cfg)
    try:
        from redisson_tpu.interop.resp_client import SyncRespClient

        router = c._resp.router
        assert any(a.endswith(str(slave.port)) for a in router._slaves)
        pub = SyncRespClient(port=sentinel.port)
        pub.connect()
        try:
            pub.execute("PUBLISH", "+sdown",
                        f"slave s1 127.0.0.1 {slave.port} @ mymaster "
                        f"127.0.0.1 {master.port}")
            deadline = time.time() + 5
            while time.time() < deadline and router._slaves:
                time.sleep(0.05)
            assert not router._slaves
            pub.execute("PUBLISH", "-sdown",
                        f"slave s1 127.0.0.1 {slave.port} @ mymaster "
                        f"127.0.0.1 {master.port}")
            deadline = time.time() + 5
            while time.time() < deadline and not router._slaves:
                time.sleep(0.05)
            assert any(a.endswith(str(slave.port)) for a in router._slaves)
        finally:
            pub.close()
    finally:
        c.shutdown()


def test_role_polling_detects_external_promotion(pair):
    """No sentinel, no failed write: an external role flip (the AWS-side
    Elasticache promotion) is detected by INFO-replication polling and the
    router re-points (ElasticacheConnectionManager.java behavior)."""
    from redisson_tpu.interop.topology_redis import RolePollingMonitor

    master, slave = pair
    router = MasterSlaveRouter(
        _fast_factory, f"127.0.0.1:{master.port}",
        [f"127.0.0.1:{slave.port}"], read_mode="SLAVE")
    router.connect()
    mon = RolePollingMonitor(router, scan_interval_s=0.2)
    try:
        router.execute("SET", "rp", "v")
        # External promotion: roles flip without any client-side failure.
        slave.server.replicating_from = None            # now a master
        master.server.replicating_from = f"127.0.0.1:{slave.port}"
        deadline = time.time() + 10
        while time.time() < deadline and not router.master_address.endswith(
                str(slave.port)):
            time.sleep(0.1)
        assert router.master_address.endswith(str(slave.port))
        assert mon.scans >= 1
    finally:
        mon.close()
        router.close()


def test_balancer_strategies_distribute_reads(pair):
    """Random / weighted balancers (reference connection/balancer/): reads
    distribute per strategy across two slaves of one master."""
    from redisson_tpu.interop.topology_redis import (
        RandomBalancer, WeightedRoundRobinBalancer, make_balancer)

    master, s1 = pair
    from redisson_tpu.interop.fake_server import EmbeddedRedis

    s2 = EmbeddedRedis(share_with=master)
    try:
        master.server.replicas.append(s2.server)
        s2.server.replicating_from = f"127.0.0.1:{master.port}"
        slaves = [f"127.0.0.1:{s1.port}", f"127.0.0.1:{s2.port}"]

        # weighted 3:1 — the heavier slave serves ~3x the reads
        router = MasterSlaveRouter(
            _patient_factory, f"127.0.0.1:{master.port}", slaves,
            read_mode="SLAVE",
            balancer=WeightedRoundRobinBalancer({slaves[0]: 3}, 1))
        router.connect()
        try:
            router.execute("SET", "bk", "v")
            picks = [router._endpoint_for(("GET", "bk"), write=False)
                     for _ in range(40)]
            assert picks.count(slaves[0]) == 30
            assert picks.count(slaves[1]) == 10
        finally:
            router.close()

        # random — both slaves picked eventually
        router = MasterSlaveRouter(
            _patient_factory, f"127.0.0.1:{master.port}", slaves,
            read_mode="SLAVE", balancer=RandomBalancer(seed=7))
        router.connect()
        try:
            picks = {router._endpoint_for(("GET", "bk"), write=False)
                     for _ in range(60)}
            assert picks == set(slaves)
        finally:
            router.close()

        with pytest.raises(ValueError):
            make_balancer("bogus")
    finally:
        s2.kill()


def test_blpop_parked_on_master_survives_failover(pair):
    """VERDICT r4 item #5: a blocking take parked on the master completes
    on the promoted master after the original dies, without element loss
    (reference reattaches in-flight blocking commands,
    connection/MasterSlaveEntry.java:158-250)."""
    from redisson_tpu.executor import Op
    from redisson_tpu.interop.backend_redis import RedisBackend

    master, slave = pair
    router = MasterSlaveRouter(
        _fast_factory, f"127.0.0.1:{master.port}",
        [f"127.0.0.1:{slave.port}"], read_mode="MASTER")
    router.connect()
    backend = RedisBackend(router)
    try:
        op = Op(target="fo:q", kind="bpop",
                payload={"side": "left", "timeout_s": None})
        backend.run("bpop", "fo:q", [op])
        time.sleep(0.3)  # the BLPOP is parked server-side on the master
        assert not op.future.done()
        master.kill()
        # The worker's re-drive promotes the slave and re-parks there.
        deadline = time.time() + 10
        while time.time() < deadline and router.promotions == 0:
            time.sleep(0.1)
        assert router.promotions >= 1
        # An element pushed to the promoted master completes the take.
        router.execute("LPUSH", "fo:q", "survived")
        assert op.future.result(timeout=10) == b"survived"
    finally:
        router.close()


def test_blpop_timeout_preserved_across_failover(pair):
    """The re-driven blocking pop keeps the ORIGINAL deadline: a timed
    poll across a failover still returns None on schedule, not after a
    fresh full window."""
    from redisson_tpu.executor import Op
    from redisson_tpu.interop.backend_redis import RedisBackend

    master, slave = pair
    router = MasterSlaveRouter(
        _fast_factory, f"127.0.0.1:{master.port}",
        [f"127.0.0.1:{slave.port}"], read_mode="MASTER")
    router.connect()
    backend = RedisBackend(router)
    try:
        t0 = time.time()
        op = Op(target="fo:t", kind="bpop",
                payload={"side": "left", "timeout_s": 3.0})
        backend.run("bpop", "fo:t", [op])
        time.sleep(0.2)
        master.kill()
        assert op.future.result(timeout=15) is None
        # 3s window + promotion/backoff slack, NOT 3s + a fresh 3s park.
        assert time.time() - t0 < 9.0
    finally:
        router.close()


def test_blocking_pop_loss_window_counter_exposed():
    """The silent-loss window (reply window expires exactly as the server
    pops) is observable: counted on the backend and exported as a client
    metrics gauge (r2 advisor low, VERDICT r3 weak #7)."""
    from redisson_tpu.interop.fake_server import EmbeddedRedis

    with EmbeddedRedis() as er:
        cfg = Config()
        cfg.use_redis().address = f"redis://127.0.0.1:{er.port}"
        c = RedissonTPU.create(cfg)
        try:
            snap = c.metrics.snapshot()
            assert snap["gauges"]["redis.blocking_pop_loss_windows"] == \
                c._backend.blocking_pop_loss_windows == 0
        finally:
            c.shutdown()
