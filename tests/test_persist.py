"""persist/ — journal, snapshots, crash recovery, warm standby.

Layers:

1. Codec round-trips — every payload shape the op table produces.
2. Journal unit tests — framing, group commit, rotation, compaction,
   torn-tail repair, tailing.
3. The central durability property — a journal truncated at an ARBITRARY
   byte offset recovers to exactly a committed prefix of the op stream,
   bit-identical to executing that prefix serially on a fresh engine.
4. Kill-and-recover + snapshot integration through the real client.
5. Follower tailing + mid-stream promotion convergence.
6. checkpoint `.old` fallback (crash between the two swap renames).
"""

import hashlib
import os
import pickle
import random
import shutil

import numpy as np
import pytest

from redisson_tpu import checkpoint
from redisson_tpu.client import RedissonTPU
from redisson_tpu.config import Config, PersistConfig
from redisson_tpu.persist import codec
from redisson_tpu.persist.journal import (
    Journal,
    JournalGap,
    JournalTail,
    _list_segments,
    iter_records,
    last_seq_in_dir,
)
from redisson_tpu.persist.follower import JournalFollower


def make_client(tmp_path=None, fsync="always", **persist_kw):
    cfg = Config()
    cfg.use_local()
    if tmp_path is not None:
        pc = cfg.use_persist(str(tmp_path))
        pc.fsync = fsync
        for k, v in persist_kw.items():
            setattr(pc, k, v)
    return RedissonTPU.create(cfg)


def _canon(obj, h):
    """Feed a canonical, identity-free rendering of `obj` into hash `h`.
    Raw pickle bytes are NOT a sound digest basis: pickle memoizes by
    object identity, so two EQUAL graphs with different internal sharing
    (leader vs snapshot-restored replica) serialize differently."""
    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        h.update(repr(obj).encode())
    elif isinstance(obj, (bytearray, memoryview)):
        h.update(b"B" + bytes(obj))
    elif isinstance(obj, dict):
        h.update(b"{")
        for k, v in obj.items():  # insertion order is semantic (hash fields)
            _canon(k, h)
            h.update(b":")
            _canon(v, h)
        h.update(b"}")
    elif isinstance(obj, (list, tuple)):
        h.update(b"[")
        for v in obj:
            _canon(v, h)
            h.update(b",")
        h.update(b"]")
    elif isinstance(obj, (set, frozenset)):
        h.update(b"<")
        for r in sorted(repr(v) for v in obj):
            h.update(r.encode() + b",")
        h.update(b">")
    elif isinstance(obj, np.ndarray):
        h.update(str(obj.dtype).encode() + str(obj.shape).encode())
        h.update(obj.tobytes())
    else:
        h.update(type(obj).__name__.encode())
        state = getattr(obj, "__dict__", None)
        _canon(state if state is not None else repr(obj), h)


def engine_digest(client) -> str:
    """Bit-identical fingerprint of engine state: every sketch-store array
    (host copy) plus the structure tier's dump. Version counters are
    excluded — the property under test is about DATA."""
    h = hashlib.sha256()
    store = client._store
    for name in sorted(store.keys()):
        obj = store.get(name)
        if obj is None:
            continue
        arr = np.asarray(obj.state)
        h.update(name.encode())
        h.update(str(obj.otype).encode())
        h.update(str(arr.dtype).encode() + str(arr.shape).encode())
        h.update(arr.tobytes())
        h.update(repr(sorted(obj.meta.items())).encode())
    structures = getattr(client._routing, "structures", None)
    if structures is not None:
        _canon(pickle.loads(structures.dump_state()), h)
    return h.hexdigest()


# ---------------------------------------------------------------------------
# 1. codec
# ---------------------------------------------------------------------------

class TestCodec:
    @pytest.mark.parametrize("value", [
        None, True, False, 0, -1, 2**80, -(2**80), 1.5, float("inf"),
        "", "héllo", b"", b"\x00\xff" * 9,
        [1, "two", b"3", None], (4, (5, 6)), {"k": [1, 2], b"b": {"n": None}},
    ])
    def test_roundtrip_scalars_containers(self, value):
        out = codec.decode_payload(codec.encode_payload(value))
        assert out == value
        assert type(out) is type(value)

    @pytest.mark.parametrize("arr", [
        np.arange(7, dtype=np.uint32),
        np.zeros((3, 5), np.uint8),
        np.array([[1.5, -2.5]], np.float64),
        np.array([], np.int64),
    ])
    def test_roundtrip_ndarray(self, arr):
        out = codec.decode_payload(codec.encode_payload(arr))
        assert out.dtype == arr.dtype and out.shape == arr.shape
        assert np.array_equal(out, arr)

    def test_numpy_scalar_decays_to_python(self):
        assert codec.decode_payload(codec.encode_payload(np.uint32(7))) == 7

    def test_nested_payload_like_real_ops(self):
        payload = {"field": b"f1", "value": b"v1", "nx": False,
                   "items": [b"a", b"b"],
                   "scores": np.arange(3, dtype=np.float64)}
        out = codec.decode_payload(codec.encode_payload(payload))
        assert out["field"] == b"f1" and out["nx"] is False
        assert np.array_equal(out["scores"], payload["scores"])


# ---------------------------------------------------------------------------
# 2. journal unit tests
# ---------------------------------------------------------------------------

class _Op:
    def __init__(self, target, kind, payload):
        self.target, self.kind, self.payload = target, kind, payload


class TestJournal:
    def test_append_read_roundtrip(self, tmp_path):
        j = Journal(str(tmp_path), fsync="always")
        j.append_run("set", [_Op("b1", "set", {"value": b"v"})])
        j.append_run("hput", [_Op("m1", "hput", {"field": b"f", "value": b"1"})])
        j.close()
        recs = list(iter_records(str(tmp_path)))
        assert [(r.seq, r.target, r.kind) for r in recs] == [
            (1, "b1", "set"), (2, "m1", "hput")]
        assert recs[0].payload == {"value": b"v"}

    def test_read_kinds_are_not_journaled(self, tmp_path):
        j = Journal(str(tmp_path), fsync="always")
        assert j.append_run("num_get", [_Op("b1", "num_get", {})]) == 0
        assert j.append_run("exists", [_Op("b1", "exists", {})]) == 0
        assert j.last_seq == 0
        j.close()
        assert list(iter_records(str(tmp_path))) == []

    def test_group_commit_defers_then_syncs_on_fill(self, tmp_path):
        j = Journal(str(tmp_path), fsync="always", group_commit_runs=2,
                    fsync_interval_s=60.0)  # long linger: only the fill syncs
        j.append_run("set", [_Op("a", "set", {"value": b"1"})], defer=True)
        assert j.durable_seq == 0  # deferred, group not full
        j.append_run("set", [_Op("b", "set", {"value": b"2"})], defer=True)
        assert j.durable_seq == 2  # group filled -> inline fsync
        j.close()

    def test_rotation_and_compaction(self, tmp_path):
        j = Journal(str(tmp_path), fsync="always")
        for i in range(3):
            j.append_run("set", [_Op(f"k{i}", "set", {"value": b"x"})])
        j.rotate()
        j.rotate()  # idempotent on an empty active segment
        j.append_run("set", [_Op("k3", "set", {"value": b"x"})])
        assert [b for b, _ in _list_segments(str(tmp_path))] == [1, 4]
        j.remove_segments_below(3)
        assert [b for b, _ in _list_segments(str(tmp_path))] == [4]
        assert [r.seq for r in iter_records(str(tmp_path), from_seq=3)] == [4]
        j.close()
        # reopen continues the sequence
        j2 = Journal(str(tmp_path), fsync="always")
        assert j2.last_seq == 4
        j2.append_run("set", [_Op("k4", "set", {"value": b"x"})])
        assert j2.last_seq == 5
        j2.close()

    def test_segment_size_rotation(self, tmp_path):
        # 1 << 16 is the enforced floor for segment_max_bytes
        j = Journal(str(tmp_path), fsync="off", segment_max_bytes=1 << 16)
        for i in range(20):
            j.append_run("set", [_Op(f"k{i}", "set", {"value": b"x" * 5000})])
        j.close()
        assert len(_list_segments(str(tmp_path))) > 1
        assert [r.seq for r in iter_records(str(tmp_path))] == list(range(1, 21))

    def test_torn_tail_truncated_on_reopen(self, tmp_path):
        j = Journal(str(tmp_path), fsync="always")
        for i in range(4):
            j.append_run("set", [_Op(f"k{i}", "set", {"value": b"y" * 100})])
        j.close()
        _, seg = _list_segments(str(tmp_path))[-1]
        size = os.path.getsize(seg)
        with open(seg, "r+b") as f:
            f.truncate(size - 37)  # mid-frame
        j2 = Journal(str(tmp_path), fsync="always")
        assert j2.last_seq == 3
        assert j2.stats()["recovered_tail_bytes"] > 0
        j2.append_run("set", [_Op("k", "set", {"value": b"z"})])
        j2.close()
        assert [r.seq for r in iter_records(str(tmp_path))] == [1, 2, 3, 4]

    def test_corrupt_crc_stops_replay_at_prefix(self, tmp_path):
        j = Journal(str(tmp_path), fsync="always")
        for i in range(3):
            j.append_run("set", [_Op(f"k{i}", "set", {"value": b"v" * 50})])
        j.close()
        _, seg = _list_segments(str(tmp_path))[-1]
        with open(seg, "r+b") as f:
            f.seek(os.path.getsize(seg) - 10)
            f.write(b"\xde\xad")
        assert [r.seq for r in iter_records(str(tmp_path))] == [1, 2]

    def test_tail_poll_and_gap(self, tmp_path):
        j = Journal(str(tmp_path), fsync="always")
        tail = JournalTail(str(tmp_path))
        j.append_run("set", [_Op("a", "set", {"value": b"1"})])
        assert [r.seq for r in tail.poll()] == [1]
        assert tail.poll() == []
        j.rotate()
        j.append_run("set", [_Op("b", "set", {"value": b"2"})])
        assert [r.seq for r in tail.poll()] == [2]  # follows rotation
        j.close()
        stale = JournalTail(str(tmp_path), from_seq=0)
        j2 = Journal(str(tmp_path), fsync="always")
        j2.remove_segments_below(2)
        with pytest.raises(JournalGap):
            stale.poll()
        j2.close()

    def test_last_seq_in_dir(self, tmp_path):
        assert last_seq_in_dir(str(tmp_path)) == 0
        j = Journal(str(tmp_path), fsync="always")
        j.append_run("set", [_Op("a", "set", {"value": b"1"})])
        j.close()
        assert last_seq_in_dir(str(tmp_path)) == 1


# ---------------------------------------------------------------------------
# 3. the durability property: truncate anywhere -> a committed prefix
# ---------------------------------------------------------------------------

def _write_ops(n_mix=3):
    """A deterministic mixed-op script; each call = exactly one journal
    record (all sync singleton dispatches)."""
    ops = []
    for i in range(n_mix):
        ops.append(lambda c, i=i: c.get_bucket(f"b{i}").set({"round": i}))
        ops.append(lambda c, i=i: c.get_map("m").put(f"f{i}", i * 11))
        ops.append(lambda c, i=i: c.get_bit_set("bits").set(i * 7 + 3, True))
        ops.append(lambda c, i=i: c.get_hyper_log_log("h").add_all(
            [f"u{i}-{k}" for k in range(50)]))
        ops.append(lambda c, i=i: c.get_atomic_long("ctr").add_and_get(i + 1))
    return ops


def test_truncate_anywhere_recovers_committed_prefix(tmp_path):
    """THE acceptance property: for random byte offsets t, truncating the
    journal at t and recovering yields state identical to serially
    re-executing the eligible op prefix on a fresh engine."""
    ops = _write_ops()
    lead_dir = tmp_path / "leader"
    c = make_client(lead_dir, fsync="always")
    try:
        for op in ops:
            op(c)
        c.persist.journal.sync()
        committed = list(iter_records(str(lead_dir)))
        assert len(committed) == len(ops)

        # golden digests: digest[k] = state after serially executing ops[:k]
        golden = RedissonTPU.create(Config())
        digests = {0: engine_digest(golden)}
        try:
            for k, op in enumerate(ops, start=1):
                op(golden)
                digests[k] = engine_digest(golden)
        finally:
            golden.shutdown()

        _, seg = _list_segments(str(lead_dir))[0]
        size = os.path.getsize(seg)
        rng = random.Random(0xD15C)
        offsets = sorted(rng.sample(range(1, size - 1), 6)) + [8, size]
        for t in offsets:
            crash_dir = tmp_path / f"crash-{t}"
            shutil.copytree(lead_dir, crash_dir)
            _, cseg = _list_segments(str(crash_dir))[0]
            with open(cseg, "r+b") as f:
                f.truncate(t)
            surviving = list(iter_records(str(crash_dir)))
            k = len(surviving)
            # prefix property at the record level
            assert [r.seq for r in surviving] == list(range(1, k + 1))
            r = make_client(crash_dir, fsync="always")
            try:
                rec = r.persist.last_recovery
                if k:
                    assert rec["replayed"] == k and rec["replay_errors"] == 0
                else:
                    assert rec is None  # nothing survived -> nothing recovers
                assert engine_digest(r) == digests[k], (
                    f"truncate@{t}: recovered state != serial prefix of {k} ops")
            finally:
                r.shutdown()
            shutil.rmtree(crash_dir)
    finally:
        c.shutdown()


# ---------------------------------------------------------------------------
# 4. kill-and-recover + snapshots through the client
# ---------------------------------------------------------------------------

def _crash_image(src, dst):
    """Simulate kill -9: act on a copy of the on-disk state, never a live
    shared directory."""
    if os.path.exists(dst):
        shutil.rmtree(dst)
    shutil.copytree(src, dst)


def test_kill_and_recover_full_replay(tmp_path):
    lead = tmp_path / "lead"
    c = make_client(lead, fsync="always")
    try:
        c.get_bucket("b1").set({"x": 1})
        c.get_hyper_log_log("h1").add_all([f"k{i}" for i in range(1000)])
        bs = c.get_bit_set("bits"); bs.set(5, True); bs.set(100, True)
        m = c.get_map("m1"); m.put("a", 1); m.put("b", 2)
        c.get_bloom_filter("bf").try_init(1000, 0.01)
        c.get_bloom_filter("bf").add("member-1")
        c.persist.journal.sync()
        expect_hll = c.get_hyper_log_log("h1").count()
        _crash_image(lead, tmp_path / "img")
    finally:
        c.shutdown()
    r = make_client(tmp_path / "img", fsync="always")
    try:
        assert r.persist.last_recovery["replay_errors"] == 0
        assert r.get_bucket("b1").get() == {"x": 1}
        assert r.get_map("m1").get("a") == 1 and r.get_map("m1").get("b") == 2
        assert r.get_bit_set("bits").get(5) and r.get_bit_set("bits").get(100)
        assert not r.get_bit_set("bits").get(6)
        assert r.get_hyper_log_log("h1").count() == expect_hll
        assert r.get_bloom_filter("bf").contains("member-1")
        # the recovered leader keeps journaling past the recovered seq
        seq0 = r.persist.journal.last_seq
        r.get_bucket("b2").set("post")
        assert r.persist.journal.last_seq == seq0 + 1
    finally:
        r.shutdown()


def test_snapshot_bounds_recovery_to_suffix(tmp_path):
    lead = tmp_path / "lead"
    c = make_client(lead, fsync="always")
    try:
        for i in range(10):
            c.get_map("m").put(f"f{i}", i)
        snap_path = c.snapshot_now()
        assert os.path.basename(snap_path).startswith("snap-")
        assert checkpoint.info(snap_path)["journal_seq"] == 10
        # pre-snapshot history is compacted away
        assert all(b > 10 for b, _ in _list_segments(str(lead)))
        c.get_bucket("after").set("suffix")
        c.persist.journal.sync()
        _crash_image(lead, tmp_path / "img")
        digest = engine_digest(c)
    finally:
        c.shutdown()
    r = make_client(tmp_path / "img", fsync="always")
    try:
        rec = r.persist.last_recovery
        assert rec["snapshot_seq"] == 10
        assert rec["replayed"] == 1  # ONLY the suffix replays
        assert r.get_bucket("after").get() == "suffix"
        assert r.get_map("m").get("f7") == 7
        assert engine_digest(r) == digest
    finally:
        r.shutdown()


def test_everysec_clean_shutdown_loses_nothing(tmp_path):
    lead = tmp_path / "lead"
    c = make_client(lead, fsync="everysec")
    try:
        for i in range(5):
            c.get_bucket(f"b{i}").set(i)
    finally:
        c.shutdown()  # close() flushes + fsyncs the tail
    assert [r.seq for r in iter_records(str(lead))] == [1, 2, 3, 4, 5]


def test_recovery_stats_and_gauges(tmp_path):
    lead = tmp_path / "lead"
    c = make_client(lead, fsync="always")
    try:
        c.get_bucket("b").set(1)
        _crash_image(lead, tmp_path / "img")
    finally:
        c.shutdown()
    r = make_client(tmp_path / "img", fsync="always")
    try:
        gauges = r.metrics.snapshot()["gauges"]
        assert gauges["persist.last_seq"] == 1
        assert gauges["persist.replayed"] == 1
        assert gauges["persist.segments"] >= 1
        st = r.persist.stats()
        assert st["journal"]["last_seq"] == 1
        assert st["recovery"]["replayed"] == 1
    finally:
        r.shutdown()


def test_persist_config_from_dict_and_redis_mode_guard(tmp_path):
    cfg = Config.from_dict({
        "persist": {"dir": str(tmp_path / "p"), "fsync": "off",
                    "snapshot_keep": 5},
    })
    assert isinstance(cfg.persist, PersistConfig)
    assert cfg.persist.fsync == "off" and cfg.persist.snapshot_keep == 5


# ---------------------------------------------------------------------------
# 5. follower / warm standby
# ---------------------------------------------------------------------------

def test_follower_tails_and_promotes_mid_stream(tmp_path):
    lead = tmp_path / "lead"
    c = make_client(lead, fsync="always")
    follower = None
    promoted = None
    try:
        for i in range(4):
            c.get_map("m").put(f"f{i}", i)
        follower = JournalFollower(str(lead), poll_interval_s=0.01)
        follower.start()
        # keep writing while the follower is live, then promote mid-stream:
        # the drain inside promote() must pick up whatever it hadn't applied
        for i in range(4, 12):
            c.get_map("m").put(f"f{i}", i)
        c.get_bit_set("bits").set(9, True)
        c.persist.journal.sync()
        leader_digest = engine_digest(c)
        promoted = follower.promote(catch_up=True, timeout_s=30)
        assert follower.lag() == 0
        assert engine_digest(promoted) == leader_digest
        assert promoted.get_map("m").get("f11") == 11
        st = follower.stats()
        assert st["applied_seq"] == c.persist.journal.last_seq
        assert st["apply_errors"] == 0
    finally:
        if follower is not None:
            follower.close()
        c.shutdown()


def test_follower_queue_mode_attach(tmp_path):
    lead = tmp_path / "lead"
    c = make_client(lead, fsync="off")  # queue mode needs no disk flushes
    follower = None
    try:
        follower = JournalFollower(str(lead), poll_interval_s=0.01)
        follower.attach(c.persist.journal)
        follower.start()
        for i in range(6):
            c.get_bucket(f"b{i}").set(i * 3)
        promoted = follower.promote(catch_up=True, timeout_s=30)
        for i in range(6):
            assert promoted.get_bucket(f"b{i}").get() == i * 3
        assert follower.stats()["mode"] == "queue"
    finally:
        if follower is not None:
            follower.close()
        c.shutdown()


def test_follower_rejects_persisting_config(tmp_path):
    cfg = Config()
    cfg.use_local()
    cfg.use_persist(str(tmp_path / "f"))
    with pytest.raises(ValueError):
        JournalFollower(str(tmp_path / "lead"), config=cfg)


def test_promote_under_mid_window_crash_equals_committed_prefix(tmp_path):
    """Satellite of the truncate-anywhere property, pointed at PROMOTION:
    the primary dies between journal append and backend apply (simulated
    by truncating its journal at an arbitrary byte — write-ahead order
    makes truncation exactly that interleaving), a follower bootstraps
    from the crash image and promotes; the promoted engine must equal the
    serial execution of the surviving committed prefix, bit-identical."""
    ops = _write_ops(n_mix=3)
    lead_dir = tmp_path / "leader"
    c = make_client(lead_dir, fsync="always")
    try:
        for op in ops:
            op(c)
        c.persist.journal.sync()
    finally:
        c.shutdown()

    golden = RedissonTPU.create(Config())
    digests = {0: engine_digest(golden)}
    try:
        for k, op in enumerate(ops, start=1):
            op(golden)
            digests[k] = engine_digest(golden)
    finally:
        golden.shutdown()

    _, seg = _list_segments(str(lead_dir))[0]
    size = os.path.getsize(seg)
    rng = random.Random(0xFA110)
    for t in sorted(rng.sample(range(1, size - 1), 4)) + [size]:
        crash_dir = tmp_path / f"crash-{t}"
        shutil.copytree(lead_dir, crash_dir)
        _, cseg = _list_segments(str(crash_dir))[0]
        with open(cseg, "r+b") as f:
            f.truncate(t)
        k = len(list(iter_records(str(crash_dir))))
        follower = JournalFollower(str(crash_dir), poll_interval_s=0.01)
        try:
            promoted = follower.promote(catch_up=True, timeout_s=30)
            assert follower.applied_seq == k
            assert follower.stats()["apply_errors"] == 0
            assert engine_digest(promoted) == digests[k], (
                f"truncate@{t}: promoted state != serial prefix of {k} ops")
        finally:
            follower.close()
        shutil.rmtree(crash_dir)


def test_follower_resync_under_rotation_and_compaction(tmp_path):
    """A replica tailing while the leader rotates AND compacts
    (`snapshot_now` truncates covered segments) must either partial-resync
    or cleanly full-resync — never apply a torn suffix — and converge to
    the leader's exact state."""
    lead = tmp_path / "lead"
    c = make_client(lead, fsync="always", segment_max_bytes=1 << 16)
    follower = None
    try:
        for i in range(20):
            c.get_map("m").put(f"f{i}", i)
        follower = JournalFollower(str(lead), poll_interval_s=0.005)
        follower.start()
        # Interleave traffic with rotation + snapshot-compaction; the big
        # payloads force several segment rollovers under the follower.
        for round_ in range(4):
            for i in range(8):
                c.get_bucket(f"r{round_}-{i}").set("x" * 4000)
            c.persist.journal.rotate()
            c.snapshot_now()  # compacts: remove_segments_below(watermark)
        for i in range(5):
            c.get_map("m").put(f"tail{i}", i)
        c.persist.journal.sync()
        leader_digest = engine_digest(c)
        leader_seq = c.persist.journal.last_seq
        promoted = follower.promote(catch_up=True, timeout_s=30)
        st = follower.stats()
        assert st["applied_seq"] == leader_seq
        assert st["apply_errors"] == 0  # a torn suffix would error here
        assert st["full_resyncs"] >= 1  # initial bootstrap counts as full
        assert engine_digest(promoted) == leader_digest
    finally:
        if follower is not None:
            follower.close()
        c.shutdown()


def test_follower_partial_vs_full_resync_counters(tmp_path):
    """PSYNC parity: a resync with the suffix still on disk is partial
    (state kept, tail re-opened at the cursor); one whose suffix was
    compacted away is full (snapshot re-bootstrap). Initial bootstrap
    counts as full, mirroring redis sync_full."""
    lead = tmp_path / "lead"
    c = make_client(lead, fsync="always")
    follower = None
    try:
        for i in range(10):
            c.get_map("m").put(f"f{i}", i)
        c.persist.journal.sync()
        follower = JournalFollower(str(lead), poll_interval_s=0.005)
        follower.start()
        deadline = 30
        import time as _t
        t0 = _t.monotonic()
        while follower.lag() > 0 and _t.monotonic() - t0 < deadline:
            _t.sleep(0.01)
        assert follower._full_resyncs == 1 and follower._partial_resyncs == 0
        # Retarget to the SAME dir: suffix available at the cursor -> partial.
        follower.retarget(str(lead))
        assert follower._partial_resyncs == 1 and follower._full_resyncs == 1
        # Compact history past the cursor while appending more, then force
        # a resync: the suffix is gone -> full snapshot bootstrap.
        follower.close(shutdown_client=False)
        for i in range(10, 16):
            c.get_map("m").put(f"f{i}", i)
        c.persist.journal.rotate()  # seal seqs 1..16 so compaction can drop them
        c.snapshot_now()  # remove_segments_below: history past the cursor gone
        c.get_map("m").put("post", 99)
        c.persist.journal.sync()
        follower.retarget(str(lead))
        assert follower._full_resyncs == 2 and follower._partial_resyncs == 1
        promoted = follower.promote(catch_up=True, timeout_s=30)
        assert promoted.get_map("m").get("post") == 99
        assert engine_digest(promoted) == engine_digest(c)
    finally:
        if follower is not None:
            follower.close()
        c.shutdown()


def test_watermark_scanner_incremental_lag(tmp_path):
    """Satellite: file-mode lag() must not rescan the whole journal per
    call. The incremental scanner tracks appends, rotation, and
    compaction, agreeing with last_seq_in_dir at every step while only
    re-anchoring on actual segment events."""
    from redisson_tpu.persist.follower import _WatermarkScanner

    j = Journal(str(tmp_path), fsync="always")
    scanner = _WatermarkScanner(str(tmp_path))
    assert scanner.last_seq() == 0
    for i in range(5):
        j.append_run("set", [_Op(f"k{i}", "set", {"value": b"x"})])
        assert scanner.last_seq() == i + 1 == last_seq_in_dir(str(tmp_path))
    anchors = scanner.rescans
    j.rotate()
    j.append_run("set", [_Op("k5", "set", {"value": b"y"})])
    assert scanner.last_seq() == 6 == last_seq_in_dir(str(tmp_path))
    # Rotation follows the base==last+1 chain without a re-anchor.
    assert scanner.rescans == anchors
    j.remove_segments_below(5)  # drops the first segment (our history)
    j.append_run("set", [_Op("k6", "set", {"value": b"z"})])
    assert scanner.last_seq() == 7 == last_seq_in_dir(str(tmp_path))
    # Steady state: repeated calls with no appends never re-anchor.
    anchors = scanner.rescans
    for _ in range(10):
        assert scanner.last_seq() == 7
    assert scanner.rescans == anchors
    j.close()


# ---------------------------------------------------------------------------
# 6. checkpoint .old fallback (satellite: crash between the swap renames)
# ---------------------------------------------------------------------------

def test_checkpoint_old_fallback_after_interrupted_swap(tmp_path):
    c = RedissonTPU.create(Config())
    try:
        c.get_bit_set("bits").set(3, True)
        path = str(tmp_path / "ckpt")
        assert c.save_checkpoint(path) >= 1
        # simulate a crash after `path -> path.old` but before `tmp -> path`
        os.replace(path, path + ".old")
        assert checkpoint.info(path)["version"] == 1  # info() falls back
        c.get_bit_set("bits").set(3, False)
        assert c.load_checkpoint(path) >= 1  # load() falls back
        assert c.get_bit_set("bits").get(3)
        assert checkpoint.extra_file(path, "nope.bin") is None
    finally:
        c.shutdown()
