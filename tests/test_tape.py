"""Window megakernel (PR 12 tentpole): one fused launch per pipeline
window, driven by an on-device command tape.

Layers:

1. Kernel — window_merge_pallas (interpret mode) vs window_merge_lax vs
   a numpy oracle: bit-identical merged rows + changed flags on
   randomized mixed dense/packed tapes.
2. Encode — ingest/tape.py tape layout: HLL-first ordering, pow2
   padding with identity rows, sparse-plane re-densification round-trip,
   unknown-kind rejection.
3. Property — randomized mixed hll/bloom/bitset windows through the
   real client with ingest="tape" vs the serial scatter oracle:
   per-op results (PFADD changed, bloom newly incl. intra-window
   duplicates, bitset old-bit reads) and the engine digest must be
   bit-identical — including under kernel_launch fault injection with
   serve retries absorbing the injected tape fault.
4. Satellites — exactly one launch per tape window; the chunked
   fallback when a window overflows the arena budget stays correct;
   per-chunk failure isolation in the delta path (a failed chunk
   commits nothing, other chunks commit and bump epochs); donation in
   the merge kernels + the memstat-ledger no-spike contract.
"""

import time

import numpy as np
import pytest

from redisson_tpu import native
from redisson_tpu.client import RedissonTPU
from redisson_tpu.config import Config, TpuConfig
from redisson_tpu.fault import inject
from redisson_tpu.fault.taxonomy import RetryableFault
from redisson_tpu.ingest import delta as delta_mod
from redisson_tpu.ingest import tape as tape_mod
from redisson_tpu.ops import window_kernel as wk

from tests.test_persist import engine_digest

needs_native = pytest.mark.skipif(
    not native.available(), reason="native fold library unavailable")


@pytest.fixture(autouse=True)
def _clean_fault_globals():
    inject.uninstall()
    yield
    inject.uninstall()


def _mk(ingest, plan=None):
    cfg = Config(tpu=TpuConfig(ingest=ingest))
    if plan is not None:
        sc = cfg.use_serve()
        sc.retry_interval_ms = 5
        fc = cfg.use_faults()
        fc.plan = plan
    return RedissonTPU.create(cfg)


def _backend(c):
    return c._routing.sketch


# ---------------------------------------------------------------------------
# 1. kernel: pallas-interpret vs lax vs numpy oracle
# ---------------------------------------------------------------------------


def _random_tape(rng, t2, lanes):
    """A randomized raw tape: mixed op codes, random lengths, random
    old/wire rows, plus the numpy-oracle expected outputs."""
    table = np.zeros((t2, wk.TABLE_COLS), np.int32)
    old = np.zeros((t2, lanes), np.uint8)
    wire = np.zeros((t2, lanes), np.uint8)
    want = np.zeros((t2, lanes), np.uint8)
    want_changed = np.zeros((t2,), bool)
    for t in range(t2):
        op = rng.choice([wk.OP_PAD, wk.OP_HLL, wk.OP_BLOOM, wk.OP_BITSET])
        if op == wk.OP_PAD:
            length = 0
        else:
            length = int(rng.integers(1, lanes + 1))
        table[t] = (op, -1, 0, length, 0)
        if op == wk.OP_HLL:
            old[t] = rng.integers(0, 65, lanes, np.uint8)
            wire[t, :length] = rng.integers(0, 65, length, np.uint8)
            delta = wire[t].copy()
            delta[length:] = 0
        else:
            old[t] = rng.integers(0, 2, lanes, np.uint8)
            cells = rng.integers(0, 2, lanes, np.uint8)
            cells[length:] = 0
            wire[t, : lanes // 8] = np.packbits(cells)
            delta = cells
        want[t] = np.maximum(old[t], delta)
        want_changed[t] = bool((want[t] != old[t]).any())
    return table, old, wire, want, want_changed


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_window_kernel_interpret_lax_oracle_identical(seed):
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    t2, lanes = 4, 256
    table, old, wire, want, want_changed = _random_tape(rng, t2, lanes)
    m_lax, c_lax = wk.window_merge_lax(
        jnp.asarray(old), jnp.asarray(wire), jnp.asarray(table))
    np.testing.assert_array_equal(np.asarray(m_lax), want)
    np.testing.assert_array_equal(np.asarray(c_lax), want_changed)
    m_pl, c_pl = wk.window_merge_pallas(
        jnp.asarray(old), jnp.asarray(wire), jnp.asarray(table),
        block=128, interpret=True)
    np.testing.assert_array_equal(np.asarray(m_pl), want)
    np.testing.assert_array_equal(np.asarray(c_pl), want_changed)


def test_window_kernel_pad_rows_are_identity():
    import jax.numpy as jnp

    old = np.full((2, 64), 7, np.uint8)
    wire = np.full((2, 64), 255, np.uint8)  # garbage: length 0 masks it
    table = np.array([[wk.OP_PAD, -1, 0, 0]] * 2, np.int32)
    merged, changed = wk.window_merge_lax(
        jnp.asarray(old), jnp.asarray(wire), jnp.asarray(table))
    np.testing.assert_array_equal(np.asarray(merged), old)
    assert not np.asarray(changed).any()


# ---------------------------------------------------------------------------
# 2. encode_window
# ---------------------------------------------------------------------------


def _plane(kind, target, dense, cells, packed):
    return delta_mod.encode(kind, target, dense, cells=cells, packed=packed,
                            nkeys=1, raw_bytes=8)


def test_encode_window_orders_hll_first_and_pads_pow2():
    bits = np.zeros(128, np.uint8)
    bits[0] = 255
    planes = [
        _plane("bitset_set", "b", bits, 1024, True),
        _plane("hll_add", "h", np.full(16384, 3, np.uint8), 16384, False),
        _plane("bloom_add", "f", bits, 1024, True),
    ]
    tp = tape_mod.encode_window(planes, lambda name: 5)
    assert [p.kind for p in tp.planes] == [
        "hll_add", "bitset_set", "bloom_add"]
    assert tp.table.shape == (4, wk.TABLE_COLS)  # 3 entries pad to pow2
    assert tp.n_hll == 1 and tp.hll_rows.tolist() == [5]
    assert tp.table[0].tolist()[:2] == [wk.OP_HLL, 5]
    assert tp.table[3, 0] == wk.OP_PAD and tp.table[3, 3] == 0
    assert tp.lanes == 16384
    # Wire width: pow2 of the max plane_bytes (the 16 KB HLL plane).
    assert tp.wire.shape == (4, 16384)
    assert tp.link_bytes == tp.table.nbytes + tp.wire.nbytes


def test_encode_window_redensifies_sparse_planes():
    dense = np.zeros(16384, np.uint8)
    dense[[7, 99, 5000]] = 9
    p = _plane("hll_add", "h", dense, 16384, False)
    assert p.sparse  # 3 entries << dense plane
    tp = tape_mod.encode_window([p], lambda name: 0)
    np.testing.assert_array_equal(tp.wire[0, :16384], dense)


def test_encode_window_rejects_unknown_kind():
    p = _plane("hll_add", "h", np.zeros(16384, np.uint8), 16384, False)
    object.__setattr__(p, "kind", "zadd")
    with pytest.raises(ValueError, match="no op code"):
        tape_mod.encode_window([p], lambda name: 0)


# ---------------------------------------------------------------------------
# 3. property: tape vs serial scatter oracle, with and without faults
# ---------------------------------------------------------------------------


def _play_workload(c, rng, sync, disjoint=False):
    """One randomized mixed workload; returns every per-op result. Same
    rng seed -> identical op stream, so tape and oracle see the same
    submissions in the same order. `sync` submits op-by-op (the serial
    oracle); async submits each round as one burst (one tape window of
    mixed kinds, including TWO bloom ops on one target in one window —
    the intra-window duplicate case). `disjoint` draws the two bloom
    batches from the full key space instead of a shared pool: serve
    retries replay failed ops individually and do not promise to keep
    two same-target ops in their original relative order, so a
    fault-injection run can only pin per-op results when no key's
    "newly" answer depends on which sibling op folded first."""
    results = []
    hs = [c.get_hyper_log_log(f"tp:h{i}") for i in range(2)]
    bf = c.get_bloom_filter("tp:bloom")
    bf.try_init(expected_insertions=50_000, false_probability=0.01)
    bs = c.get_bit_set("tp:bits")
    for _ in range(3):
        hll_keys = rng.integers(0, 2**61, 1500, np.uint64)
        pool = rng.integers(0, 2**61, 400, np.uint64)
        # Cross-op duplicates INSIDE one window: both bloom ops draw from
        # one small pool, so op b's "newly" must see op a's bits (the
        # in-order evolving fold). Batches stay duplicate-free internally:
        # the device-scatter oracle evaluates a batch against pre-op
        # state, so intra-BATCH duplicate semantics are pinned separately
        # (test_tape_intra_batch_bloom_duplicates_are_serial).
        if disjoint:
            bloom_a = np.unique(rng.integers(0, 2**61, 300, np.uint64))
            bloom_b = np.unique(rng.integers(0, 2**61, 300, np.uint64))
        else:
            bloom_a = np.unique(rng.choice(pool, 300))
            bloom_b = np.unique(rng.choice(pool, 300))
        bits_idx = rng.integers(0, 1 << 14, 200, np.int64)
        bits_idx[:20] = bits_idx[20:40]  # duplicate indices in one op
        if sync:
            results.append(bool(hs[0].add_ints(hll_keys)))
            results.append(bool(hs[1].add_ints(hll_keys[:700])))
            results.append(np.asarray(bf.add_ints(bloom_a)).tolist())
            results.append(np.asarray(bf.add_ints(bloom_b)).tolist())
            results.append(np.asarray(bs.set_bits(bits_idx)).tolist())
        else:
            futs = [
                hs[0].add_ints_async(hll_keys),
                hs[1].add_ints_async(hll_keys[:700]),
                bf.add_ints_async(bloom_a),
                bf.add_ints_async(bloom_b),
                bs.set_bits_async(bits_idx),
            ]
            out = [f.result(timeout=120) for f in futs]
            results.append(bool(out[0]))
            results.append(bool(out[1]))
            results.extend(np.asarray(o).tolist() for o in out[2:])
    return results


def _digest(c):
    _backend(c)._bloom_device_sync("tp:bloom")  # host-mirror path parity
    return engine_digest(c)


@needs_native
@pytest.mark.parametrize("seed", [11, 12])
def test_tape_window_matches_serial_scatter_oracle(seed):
    ct, cs = _mk("tape"), _mk("scatter")
    try:
        res_t = _play_workload(ct, np.random.default_rng(seed), sync=False)
        res_s = _play_workload(cs, np.random.default_rng(seed), sync=True)
        assert res_t == res_s
        assert _digest(ct) == _digest(cs)
        stats = _backend(ct).ingest_stats()
        assert stats["tape_runs"] >= 1
        assert stats["delta_runs"] == 0  # every window fit the tape arena
        assert stats["launches_per_window"] == 1.0
    finally:
        ct.shutdown()
        cs.shutdown()


@needs_native
def test_tape_intra_batch_bloom_duplicates_are_serial():
    """Duplicates INSIDE one bloom op fold serially (key i sees keys < i
    of its own batch), matching one-key-at-a-time semantics — same
    contract the delta path pins."""
    c = _mk("tape")
    try:
        f = c.get_bloom_filter("dup:bloom")
        f.try_init(expected_insertions=10_000, false_probability=0.01)
        got = np.asarray(f.add_ints(np.array([11, 22, 11], np.uint64)))
        assert got[0] and got[1] and not got[2]
    finally:
        c.shutdown()


@needs_native
def test_tape_window_fault_injection_retries_to_oracle_state():
    """An injected kernel_launch fault at the tape seam fires BEFORE the
    window commits anything, so serve retries replay the ops and the
    final state + per-op results stay bit-identical to the fault-free
    serial oracle."""
    plan = [{"seam": "kernel_launch", "kind": "tape", "nth": 1},
            {"seam": "kernel_launch", "kind": "tape", "nth": 3}]
    ct, cs = _mk("tape", plan=plan), _mk("scatter")
    try:
        rt, rs = np.random.default_rng(31), np.random.default_rng(31)
        res_t = _play_workload(ct, rt, sync=False, disjoint=True)
        res_s = _play_workload(cs, rs, sync=True, disjoint=True)
        assert res_t == res_s
        assert _digest(ct) == _digest(cs)
        inj = inject.installed()
        assert inj is not None and inj.injected >= 1
    finally:
        ct.shutdown()
        cs.shutdown()


@needs_native
def test_tape_fault_without_retry_fails_window_whole():
    """No serve tier: the injected tape fault surfaces on EVERY op of the
    window (whole-window failure unit) and nothing commits — the bank
    row stays empty and the store objects keep their pre-window state."""
    c = _mk("tape")
    try:
        inject.install(inject.FaultInjector(inject.FaultPlan(rules=[
            inject.FaultRule(seam="kernel_launch", kind="tape", nth=1)])))
        be = _backend(c)
        futs = [
            c.get_hyper_log_log("tf:h").add_ints_async(
                np.arange(2000, dtype=np.uint64)),
            c.get_bit_set("tf:b").set_bits_async([1, 2, 3]),
        ]
        for f in futs:
            with pytest.raises(RetryableFault):
                f.result(timeout=60)
        assert be._epochs.get("tf:h", 0) == 0
        assert be._epochs.get("tf:b", 0) == 0
        # Retry after the fault: clean state, normal tape retire.
        inject.uninstall()
        assert c.get_hyper_log_log("tf:h").add_ints(
            np.arange(2000, dtype=np.uint64)) is True
    finally:
        c.shutdown()


# ---------------------------------------------------------------------------
# 4a. one fused launch per window / overflow fallback
# ---------------------------------------------------------------------------


@needs_native
def test_tape_retires_mixed_window_in_one_launch():
    rng = np.random.default_rng(7)
    c = _mk("tape")
    try:
        be = _backend(c)
        f = c.get_bloom_filter("t1:bloom")
        f.try_init(expected_insertions=50_000, false_probability=0.01)
        futs = [
            c.get_hyper_log_log("t1:h").add_ints_async(
                rng.integers(0, 2**63, 2000, np.uint64)),
            f.add_ints_async(rng.integers(0, 2**62, 1000, np.uint64)),
            c.get_bit_set("t1:bits").set_bits_async([1, 4, 900]),
        ]
        for fu in futs:
            fu.result(timeout=60)
        stats = be.ingest_stats()
        assert stats["tape_runs"] >= 1
        assert stats["window_launches"] == stats["tape_runs"]
        assert stats["launches_per_window"] == 1.0
        assert stats["launch_us"] > 0.0
    finally:
        c.shutdown()


@needs_native
def test_tape_overflow_falls_back_to_chunked_and_stays_correct():
    """A window too large for one tape arena retires through the chunked
    delta path — including the deferred bitset pre-merge packs the tape
    folds skipped — and stays bit-identical to the oracle."""
    c, cs = _mk("tape"), _mk("scatter")
    try:
        be = _backend(c)
        # Budget below one 16K-lane HLL plane: any window containing an
        # HLL plane overflows the tape arena and falls back.
        be.DELTA_STACK_CELLS = 1 << 13
        for cl in (c, cs):
            b = cl.get_bit_set("ov:bits")
            first = np.asarray(b.set_bits([3, 9, 3000]))
            np.testing.assert_array_equal(first, [False, False, False])
        # Mixed hll+bitset burst -> ONE window that overflows: the
        # fallback must issue the deferred bitset pre-merge pack, so the
        # old-bit reads still see pre-window state.
        fh = c.get_hyper_log_log("ov:h").add_ints_async(
            np.arange(3000, dtype=np.uint64))
        fb = c.get_bit_set("ov:bits").set_bits_async([3, 10, 5000])
        hot = bool(fh.result(timeout=60))
        old_bits = np.asarray(fb.result(timeout=60))
        hos = bool(cs.get_hyper_log_log("ov:h").add_ints(
            np.arange(3000, dtype=np.uint64)))
        old_s = np.asarray(cs.get_bit_set("ov:bits").set_bits([3, 10, 5000]))
        assert hot == hos
        np.testing.assert_array_equal(old_bits, old_s)
        np.testing.assert_array_equal(old_bits, [True, False, False])
        assert be.counters["delta_runs"] >= 1  # the fallback engaged
        assert be.counters["tape_runs"] >= 1  # the small window taped
        np.testing.assert_array_equal(
            np.asarray(be.store.get("ov:bits").state),
            np.asarray(_backend(cs).store.get("ov:bits").state))
    finally:
        c.shutdown()
        cs.shutdown()


# ---------------------------------------------------------------------------
# 4b. per-chunk failure isolation in the chunked delta path
# ---------------------------------------------------------------------------


@needs_native
def test_delta_chunk_failure_isolated_to_its_own_targets():
    """Two HLL targets forced into two merge chunks; an injected
    kernel_launch fault on the second chunk must leave the first chunk
    COMMITTED (registers live, epoch bumped) and fail only the second
    chunk's ops, with its bank row untouched and epoch unbumped."""
    c = _mk("delta")
    try:
        be = _backend(c)
        # One 16384-lane HLL plane fills the whole budget -> one plane
        # per chunk, two chunks per window.
        be.DELTA_STACK_CELLS = 1 << 14
        inject.install(inject.FaultInjector(inject.FaultPlan(rules=[
            inject.FaultRule(seam="kernel_launch", kind="delta_merge",
                             nth=2)])))
        ha = c.get_hyper_log_log("iso:a")
        hb = c.get_hyper_log_log("iso:b")
        fa = ha.add_ints_async(np.arange(2000, dtype=np.uint64))
        fb = hb.add_ints_async(np.arange(5000, 7000, dtype=np.uint64))
        outcomes = {}
        for name, fut in (("iso:a", fa), ("iso:b", fb)):
            try:
                outcomes[name] = bool(fut.result(timeout=60))
            except RetryableFault:
                outcomes[name] = "failed"
        committed = [n for n, v in outcomes.items() if v is True]
        failed = [n for n, v in outcomes.items() if v == "failed"]
        assert len(committed) == 1 and len(failed) == 1, outcomes
        bank = np.asarray(be._ensure_bank())
        assert bank[be._rows[committed[0]]].any()
        assert not bank[be._rows[failed[0]]].any()
        assert be._epochs.get(committed[0], 0) >= 1
        assert be._epochs.get(failed[0], 0) == 0
        # Exactly one chunk merged before the fault killed the other.
        assert be.counters["merge_launches"] == 1
    finally:
        c.shutdown()


# ---------------------------------------------------------------------------
# 4c. donation + memstat-ledger no-spike
# ---------------------------------------------------------------------------


def test_merge_kernels_declare_donation():
    """delta_merge_stack / merge_stack / tape_apply donate their old
    stacks so the merge lands in place — peak HBM never holds two copies
    of the old state. Donation shows up either as an input->output alias
    in the lowering (where the backend honors it) or as the
    donated-buffers-unusable warning (CPU) — its absence in BOTH means
    the donate_argnums declaration was dropped."""
    import warnings

    import jax.numpy as jnp

    from redisson_tpu import engine
    from redisson_tpu.ops import pallas_kernels as pk

    def donates(lower_fn):
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            txt = lower_fn().as_text()
        warned = any("donated buffers were not usable" in str(x.message)
                     for x in w)
        return warned or "tf.aliasing_output" in txt

    assert donates(lambda: engine.delta_merge_stack.lower(
        jnp.zeros((2, 2048), jnp.uint8), jnp.zeros((2, 2048), jnp.uint8)))
    assert donates(lambda: pk.merge_stack.lower(
        jnp.zeros((8, 1024), jnp.int32)))
    assert donates(lambda: engine.tape_apply.lower(
        jnp.zeros((4, 16384), jnp.int32),          # bank
        jnp.zeros((2, 16384), jnp.uint8),          # wire
        jnp.zeros((2, 4), jnp.int32),              # table
        jnp.zeros((1,), jnp.int32),                # hll_rows
        (),                                        # store_old
        n_hll=1, lanes=16384, want_old=False))


@needs_native
@pytest.mark.parametrize("ingest", ["delta", "tape"])
def test_merge_ledger_no_spike_and_scratch_drains(ingest):
    """Repeated same-shape merges must not move the ledger at all: the
    donated in-place merge swaps same-size arrays (on_resize is a no-op),
    so live_bytes stays flat, the peak high-water never exceeds the
    steady live total, verify() reports zero drift, and the in-flight
    delta scratch meter drains back to zero."""
    c = _mk(ingest)
    try:
        be = _backend(c)
        b = c.get_bit_set("ms:bits")
        b.set_bits(np.arange(0, 4096, 2, dtype=np.int64))
        live0 = c.memstat.live_bytes()
        peak0 = c.memstat.peak_bytes()
        for i in range(4):
            b.set_bits(np.arange(i, 4096, 3, dtype=np.int64))
        assert c.memstat.live_bytes() == live0
        assert c.memstat.peak_bytes() == peak0  # no transient ledger spike
        v = c.memstat.verify(c._store, be)
        assert v["drift_bytes"] == 0 and not v["mismatched"]
        for _ in range(100):  # completer decrements after futures resolve
            if be.scratch_bytes()["delta_scratch"] == 0:
                break
            time.sleep(0.01)
        assert be.scratch_bytes()["delta_scratch"] == 0
    finally:
        c.shutdown()


# ---------------------------------------------------------------------------
# derived metrics
# ---------------------------------------------------------------------------


def test_ingest_stats_derived_window_metrics():
    # Pure-arithmetic check through a real backend instance.
    c = _mk("scatter")
    try:
        sk = _backend(c)
        sk.counters["delta_runs"] = 3
        sk.counters["tape_runs"] = 1
        sk.counters["window_launches"] = 13
        sk.counters["launch_us"] = 800.0
        stats = sk.ingest_stats()
        assert stats["launches_per_window"] == 13 / 4
        assert stats["launch_us_per_window"] == 200.0
    finally:
        c.shutdown()
