"""Streaming ingest subsystem (redisson_tpu/ingest/).

Covers the three pieces the subsystem owns — the Pallas segmented-scatter
insert kernel (vs its lax fallback AND the pure-python golden oracle),
the measured-at-first-use path planner, and the double-buffered staging
pipeline (results ordered, batch N+1 staged while batch N dispatches) —
plus the 64-bit BITCOUNT guard (>2^31 set bits on both tiers) and a
tier-1-safe smoke of ``bench.py --quick``.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from redisson_tpu.ingest import kernels
from redisson_tpu.ingest.pipeline import StagingPipeline
from redisson_tpu.ingest.planner import IngestPlan, IngestPlanner
from tests import golden

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# segmented-scatter kernel
# ---------------------------------------------------------------------------


def _golden_bucket_rank(keys, p=14):
    """Per-key (bucket, rank) from the golden redis hash — independent of
    every repo kernel, so kernel-vs-oracle equality breaks the
    self-consistency cycle."""
    m = 1 << p
    idx, rank = [], []
    for key in keys:
        h = golden.murmur2_64a(key)
        idx.append(h & (m - 1))
        rest = (h >> p) | (1 << (64 - p))
        r = 1
        while rest & 1 == 0:
            r += 1
            rest >>= 1
        rank.append(r)
    return np.array(idx, np.int32), np.array(rank, np.int32)


def test_hll_segmented_matches_golden_oracle():
    keys = [b"key:%d" % i for i in range(3000)]
    expect = golden.redis_hll_registers(keys)
    bucket, rank = _golden_bucket_rank(keys)
    regs = np.zeros(1 << 14, np.int32)
    out_pallas = np.asarray(
        kernels.hll_insert_segmented(regs, bucket, rank, interpret=True))
    out_lax = np.asarray(kernels.hll_insert_segmented_lax(regs, bucket, rank))
    np.testing.assert_array_equal(out_pallas, expect.astype(np.int32))
    np.testing.assert_array_equal(out_lax, expect.astype(np.int32))


def test_hll_segmented_matches_lax_fallback():
    rng = np.random.default_rng(0)
    m = 1 << 14
    regs = rng.integers(0, 20, m, np.int32)
    for n in (1, 127, 4096, 20011):
        bucket = rng.integers(0, m, n, np.int32)
        rank = rng.integers(1, 51, n, np.int32)
        got = np.asarray(kernels.hll_insert_segmented(
            regs, bucket, rank, interpret=True))
        want = np.asarray(kernels.hll_insert_segmented_lax(regs, bucket, rank))
        np.testing.assert_array_equal(got, want)


def test_hll_segmented_empty_batch():
    regs = np.arange(1 << 14, dtype=np.int32) % 7
    empty = np.zeros((0,), np.int32)
    out = np.asarray(kernels.hll_insert_segmented(
        regs, empty, empty, interpret=True))
    np.testing.assert_array_equal(out, regs)


def test_bits_segmented_matches_lax_and_numpy():
    rng = np.random.default_rng(1)
    ncells = 70001  # deliberately not a tile multiple
    cells = (rng.random(ncells) < 0.01).astype(np.uint8)
    for n in (1, 500, 8192):
        idx = rng.integers(0, ncells, n, np.int32)
        want = cells.copy()
        want[idx] = 1
        got = np.asarray(kernels.bits_insert_segmented(
            cells, idx, interpret=True))
        lax_got = np.asarray(kernels.bits_insert_segmented_lax(cells, idx))
        np.testing.assert_array_equal(got, want)
        np.testing.assert_array_equal(lax_got, want)


def test_engine_segment_impl_matches_scatter():
    # The engine-level wiring: forcing impl="segment" through the public
    # batch entrypoints must land the same registers as the scatter path.
    from redisson_tpu import engine

    rng = np.random.default_rng(2)
    keys = rng.integers(0, 2**63, 5000, np.uint64)
    packed = keys.view(np.uint32).reshape(-1, 2)
    import jax.numpy as jnp

    out = {}
    for impl in ("scatter", "segment"):
        # fresh bank per impl: the batch entrypoint donates its input
        bank = jnp.zeros((4, 16384), jnp.int32)
        new, changed = engine.hll_bank_add_packed(
            bank, packed, np.int32(keys.size), np.int32(1), 0, "murmur3",
            impl=impl)
        out[impl] = np.asarray(new)
        assert bool(np.asarray(changed)[1])
    np.testing.assert_array_equal(out["scatter"], out["segment"])


def test_client_forced_segment_estimate_matches_scatter():
    # The config knob end to end: ingest="segment" and "scatter" are the
    # same sketch, so the estimates must be identical (not just close).
    from redisson_tpu.client import RedissonTPU
    from redisson_tpu.config import Config

    keys = np.random.default_rng(3).integers(0, 2**63, 40000, np.uint64)
    counts = {}
    for path in ("scatter", "segment"):
        cfg = Config()
        cfg.use_tpu().ingest = path
        c = RedissonTPU.create(cfg)
        try:
            h = c.get_hyper_log_log("ingest:%s" % path)
            h.add_ints(keys)
            counts[path] = h.count()
        finally:
            c.shutdown()
    assert counts["scatter"] == counts["segment"]
    assert abs(counts["scatter"] - keys.size) / keys.size < 0.05


# ---------------------------------------------------------------------------
# planner
# ---------------------------------------------------------------------------


def _fixed_measure(costs):
    calls = []

    def measure(structure, n):
        calls.append((structure, n))
        return dict(costs)

    return measure, calls


def test_planner_picks_cheapest_device_path():
    measure, _ = _fixed_measure({"scatter": 9.0, "sort": 5.0, "segment": 2.0})
    p = IngestPlanner(platform="cpu", measure=measure)
    plan = p.plan("hll", 1 << 16)
    assert isinstance(plan, IngestPlan)
    assert plan.path == "segment"
    assert plan.measured


def test_planner_forced_short_circuits_measurement():
    measure, calls = _fixed_measure({"scatter": 1.0})
    p = IngestPlanner(platform="cpu", measure=measure)
    plan = p.plan("hll", 1 << 16, forced="sort")
    assert plan.path == "sort"
    assert not plan.measured
    assert not calls  # forced paths never trigger a measurement


def test_planner_measures_once_per_size_class():
    measure, calls = _fixed_measure({"scatter": 1.0, "segment": 2.0})
    p = IngestPlanner(platform="cpu", measure=measure)
    for n in (1 << 16, 1 << 16, (1 << 16) - 100):  # same bucket
        p.plan("hll", n)
    assert len(calls) == 1
    p.plan("hll", 1 << 18)  # different bucket -> one more measurement
    assert len(calls) == 2
    assert "hll@16" in p.table() and "hll@18" in p.table()


def test_planner_hostfold_wins_on_slow_links():
    # Device paths pay the per-key transfer overhead; the injected
    # hostfold candidate does not. A slow link must flip the decision.
    measure, _ = _fixed_measure({"scatter": 10.0, "segment": 12.0})
    p = IngestPlanner(platform="cpu", measure=measure)
    fast = p.plan("hll", 1 << 20, extra_costs={"hostfold": 25.0},
                  device_overhead=1.0)
    slow = p.plan("hll", 1 << 20, extra_costs={"hostfold": 25.0},
                  device_overhead=400.0)
    assert fast.path == "scatter"
    assert slow.path == "hostfold"


def test_planner_size_class_clamps_to_engine_buckets():
    assert IngestPlanner.size_class(1) == 10
    assert IngestPlanner.size_class(1 << 12) == 12
    assert IngestPlanner.size_class((1 << 12) + 1) == 13
    assert IngestPlanner.size_class(1 << 30) == 21


def test_planner_real_measurement_on_cpu():
    # The real timing loop end to end (tiny batch): every advertised path
    # gets a positive finite cost and the winner is one of them.
    p = IngestPlanner()
    plan = p.plan("bits", 1 << 10)
    assert set(plan.costs) == {"scatter", "segment"}
    assert all(0 < v < float("inf") for v in plan.costs.values())
    assert plan.path in plan.costs


# ---------------------------------------------------------------------------
# staging pipeline
# ---------------------------------------------------------------------------


def test_pipeline_results_ordered():
    pipe = StagingPipeline(depth=2)
    out = pipe.run(list(range(7)),
                   stage=lambda c: c * 10,
                   dispatch=lambda i, staged: staged + i)
    assert out == [c * 10 + i for i, c in enumerate(range(7))]


def test_pipeline_overlaps_stage_with_dispatch():
    # The double-buffer contract: chunk N+1 must be STAGED (host prep +
    # transfer) before chunk N's dispatch completes.
    trace = []
    pipe = StagingPipeline(depth=2, trace=trace)

    def stage(c):
        time.sleep(0.01)
        return c

    def dispatch(i, staged):
        time.sleep(0.05)
        return staged

    pipe.run([0, 1, 2], stage, dispatch)
    t = {(ev, i): ts for ev, i, ts in trace}
    assert t[("stage_start", 1)] < t[("dispatch_end", 0)]
    assert t[("stage_end", 1)] < t[("dispatch_end", 0)] + 0.05


def test_pipeline_dispatch_serial_and_on_caller_thread():
    caller = threading.get_ident()
    seen = []
    pipe = StagingPipeline(depth=2)

    def dispatch(i, staged):
        assert threading.get_ident() == caller
        seen.append(i)
        return staged

    pipe.run([5, 6, 7], stage=lambda c: c, dispatch=dispatch)
    assert seen == [0, 1, 2]


def test_pipeline_propagates_stage_error():
    pipe = StagingPipeline(depth=2)

    def stage(c):
        if c == 2:
            raise ValueError("boom in stage")
        return c

    with pytest.raises(ValueError, match="boom in stage"):
        pipe.run([0, 1, 2, 3], stage, lambda i, s: s)


def test_pipeline_propagates_dispatch_error():
    pipe = StagingPipeline(depth=2)

    def dispatch(i, staged):
        if i == 1:
            raise RuntimeError("boom in dispatch")
        return staged

    with pytest.raises(RuntimeError, match="boom in dispatch"):
        pipe.run([0, 1, 2, 3], lambda c: c, dispatch)


def test_pipeline_empty_input():
    assert StagingPipeline().run([], lambda c: c, lambda i, s: s) == []


# ---------------------------------------------------------------------------
# 64-bit BITCOUNT (satellite: popcount past 2^31 set bits)
# ---------------------------------------------------------------------------


def test_bitset_combine_partials_past_int31():
    from redisson_tpu.ops import bitset

    # 4096 chunks of 2^20 set bits each = 2^32 total: overflows int32 (and
    # even its absolute value) but each PARTIAL is chunk-bounded. The
    # combine must run in 64 bits host-side.
    partials = np.full((4096, 1), 1 << 20, np.int32)
    assert bitset.combine_partials(partials) == 1 << 32


def test_sharded_combine_partials_past_int31():
    from redisson_tpu.parallel import sharded_bits

    partials = np.full((5000,), 1 << 20, np.int32)
    assert sharded_bits.combine_partials(partials) == 5000 * (1 << 20)


def test_bitset_cardinality_chunked_partials_agree():
    from redisson_tpu.ops import bitset

    rng = np.random.default_rng(4)
    cells = (rng.random(3_000_000) < 0.37).astype(np.uint8)
    expect = int(cells.sum(dtype=np.int64))
    assert bitset.cardinality(cells) == expect
    parts = np.asarray(bitset.cardinality_partials(cells))
    assert parts.dtype == np.int32
    assert bitset.combine_partials(parts) == expect


def test_pallas_popcount_partials_combine():
    from redisson_tpu.ops import bitset, pallas_kernels

    rng = np.random.default_rng(5)
    cells = (rng.random(600_000) < 0.5).astype(np.uint8)
    parts = np.asarray(pallas_kernels.popcount_partials(cells))
    assert bitset.combine_partials(parts) == int(cells.sum(dtype=np.int64))


@pytest.mark.slow
def test_bitset_cardinality_real_past_int31():
    # Real >2^31 allocation (2.2 GB of cells) — slow tier only.
    from redisson_tpu.ops import bitset

    n = (1 << 31) + (1 << 20)
    cells = np.ones(n, np.uint8)
    assert bitset.cardinality(cells) == n


# ---------------------------------------------------------------------------
# bench smoke (tier-1 safe: CPU, tiny batches)
# ---------------------------------------------------------------------------


def test_bench_quick_smoke():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--quick"],
        capture_output=True, text=True, timeout=600, env=env)
    assert proc.returncode == 0, proc.stderr[-2000:]
    result = json.loads(proc.stdout.strip().splitlines()[-1])
    # The roofline must price the segmented kernel too, and the recorded
    # ingest decision must come from the planner's measured cost table.
    assert result["kernel_segment_inserts_per_sec"] > 0
    assert "pct_of_roofline" in result
    assert "pct_of_roofline_segment" in result
    assert result["ingest"]["path"] in (
        "scatter", "sort", "segment", "hostfold")
    assert result["ingest"]["costs_ns_per_key"]
    assert result["ingest_cost_table_ns_per_key"]
