"""Native C++ runtime tests: hashes vs golden, RESP codec round-trips,
keyslot vs reference CRC16 semantics, HLL fold vs the JAX kernel."""

import os

import numpy as np
import pytest

from redisson_tpu import native
from tests import golden


KEYS = [
    b"",
    b"a",
    b"hello",
    b"0123456789abcde",      # 15 (full tail)
    b"0123456789abcdef",     # 16 (exact block)
    b"0123456789abcdef0",    # 17
    b"The quick brown fox jumps over the lazy dog",
    bytes(range(256)),
    b"x" * 1000,
]


def test_native_compiles():
    # This image has g++; the native path must be live here (the python
    # fallback exists for toolchain-less hosts, not for CI).
    assert native.available(), "native library failed to build"
    assert "native" in native.version()


@pytest.mark.parametrize("seed", [0, 1, 0xDEADBEEF])
def test_murmur3_matches_golden(seed):
    h1, h2 = native.murmur3_x64_128(KEYS, seed)
    for i, k in enumerate(KEYS):
        g1, g2 = golden.murmur3_x64_128(k, seed)
        assert int(h1[i]) == g1, f"h1 mismatch key={k!r}"
        assert int(h2[i]) == g2, f"h2 mismatch key={k!r}"


@pytest.mark.parametrize("seed", [0, 7, 2**64 - 1])
def test_xxhash64_matches_golden(seed):
    out = native.xxhash64(KEYS, seed)
    for i, k in enumerate(KEYS):
        assert int(out[i]) == golden.xxhash64(k, seed), f"key={k!r}"


def test_pyfallback_matches_golden():
    from redisson_tpu.native import _pyfallback
    for k in KEYS:
        assert _pyfallback.murmur3_x64_128(k, 3) == golden.murmur3_x64_128(k, 3)
        assert _pyfallback.xxhash64(k, 3) == golden.xxhash64(k, 3)


def test_crc16_known_vectors():
    # "123456789" -> 0x31C3 is the published check value for the Redis
    # (XMODEM) CRC16 variant, cited in the cluster spec.
    assert native.crc16(b"123456789") == 0x31C3
    assert native.crc16(b"") == 0


def test_keyslot_hashtag_rules():
    # {hashtag} extraction per cluster spec (ClusterConnectionManager.java:543-558).
    assert native.keyslot("foo") == native.crc16(b"foo") % 16384
    assert native.keyslot("{user1000}.following") == native.keyslot("{user1000}.followers")
    assert native.keyslot("foo{}{bar}") == native.crc16(b"foo{}{bar}") % 16384  # empty tag -> whole key
    assert native.keyslot("foo{{bar}}zap") == native.crc16(b"{bar") % 16384
    assert native.keyslot("foo{bar}{zap}") == native.crc16(b"bar") % 16384


def test_keyslot_batch_agrees_with_store():
    from redisson_tpu.ops import crc16
    keys = [f"key:{i}".encode() for i in range(200)] + [b"{tag}a", b"{tag}b"]
    slots = native.keyslot_batch(keys)
    for k, s in zip(keys, slots):
        assert int(s) == crc16.key_slot(k.decode())


def test_resp_encode_single():
    assert native.resp_encode("PING") == b"*1\r\n$4\r\nPING\r\n"
    assert (native.resp_encode("SET", "k", b"\x00\xff") ==
            b"*3\r\n$3\r\nSET\r\n$1\r\nk\r\n$2\r\n\x00\xff\r\n")
    assert native.resp_encode("EXPIRE", "k", 30) == b"*3\r\n$6\r\nEXPIRE\r\n$1\r\nk\r\n$2\r\n30\r\n"


def test_resp_encode_pipeline_is_concatenation():
    one = native.resp_encode("GET", "a")
    two = native.resp_encode("GET", "b")
    assert native.resp_encode_pipeline([("GET", "a"), ("GET", "b")]) == one + two


def _roundtrip(wire, chunk=None):
    p = native.RespParser()
    try:
        if chunk is None:
            return p.feed(wire)
        out = []
        for i in range(0, len(wire), chunk):
            out.extend(p.feed(wire[i:i + chunk]))
        return out
    finally:
        p.close()


@pytest.mark.parametrize("chunk", [None, 1, 3, 7])
def test_resp_parser_all_types(chunk):
    wire = (b"+OK\r\n"
            b"-ERR nope\r\n"
            b":42\r\n"
            b"$5\r\nhello\r\n"
            b"$-1\r\n"
            b"*3\r\n:1\r\n$2\r\nab\r\n*2\r\n+x\r\n:-7\r\n"
            b"*-1\r\n"
            b"*0\r\n")
    got = _roundtrip(wire, chunk)
    assert got[0] == b"OK"
    assert isinstance(got[1], native.RespError) and "nope" in str(got[1])
    assert got[2] == 42
    assert got[3] == b"hello"
    assert got[4] is None
    assert got[5] == [1, b"ab", [b"x", -7]]
    assert got[6] is None
    assert got[7] == []
    assert len(got) == 8


def test_resp_parser_binary_safe_bulk():
    payload = bytes(range(256)) * 4
    wire = b"$%d\r\n" % len(payload) + payload + b"\r\n"
    assert _roundtrip(wire, 13) == [payload]


def test_resp_parser_partial_then_complete():
    p = native.RespParser()
    assert p.feed(b"*2\r\n$3\r\nfo") == []
    assert p.feed(b"o\r\n:9\r") == []
    assert p.feed(b"\n") == [[b"foo", 9]]
    p.close()


def test_resp_roundtrip_encode_parse():
    cmds = [("SET", f"k{i}", f"v{i}") for i in range(50)]
    wire = native.resp_encode_pipeline(cmds)
    # Parse our own encoding back (commands are themselves RESP arrays).
    got = _roundtrip(wire, 11)
    assert got == [[b"SET", b"k%d" % i, b"v%d" % i] for i in range(50)]


def test_hll_fold_matches_jax_kernel():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax.numpy as jnp

    from redisson_tpu.ops import hashing, hll
    from redisson_tpu.ops.u64 import U64

    keys = [f"user:{i}".encode() for i in range(5000)]
    regs = np.zeros(16384, np.uint8)
    native.hll_fold(keys, regs)

    # Same fold on the JAX path: hash 8-byte-LE? No — the JAX ingest hashes
    # raw byte keys; use the native murmur3 as the hash and the kernel's
    # bucket/rank + scatter for the fold.
    h1, _ = native.murmur3_x64_128(keys)
    hi = (h1 >> np.uint64(32)).astype(np.uint32)
    lo = (h1 & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    bucket, rank = hll.bucket_rank(U64(jnp.asarray(hi), jnp.asarray(lo)))
    jregs = hll.insert_scatter(hll.make(), bucket, rank)
    np.testing.assert_array_equal(regs.astype(np.int32), np.asarray(jregs))


def test_hll_fold_estimate_sane():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax.numpy as jnp

    from redisson_tpu.ops import hll

    n = 200_000
    keys = [b"k%d" % i for i in range(n)]
    regs = np.zeros(16384, np.uint8)
    native.hll_fold(keys, regs)
    est = float(hll.count(jnp.asarray(regs.astype(np.int32))))
    assert abs(est - n) / n < 0.02


def test_hll_fold_u64_matches_device_path():
    """The native u64 fold must be register-identical to the device ingest
    kernel (engine.hll_add_packed) — the transfer-adaptive path swaps them
    freely, so any divergence would silently skew estimates."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax.numpy as jnp

    from redisson_tpu import engine
    from redisson_tpu.models.object import pack_u64

    rng = np.random.default_rng(11)
    keys = rng.integers(0, 2**64, size=50_000, dtype=np.uint64)
    for seed in (0, 7):
        dev, _ = engine.hll_add_packed(
            jnp.zeros((16384,), jnp.int32), pack_u64(keys),
            np.int32(keys.shape[0]), "scatter", seed)
        host = np.zeros(16384, np.uint8)
        native.hll_fold_u64(keys, host, seed=seed)
        np.testing.assert_array_equal(np.asarray(dev).astype(np.uint8), host)
    # packed [n, 2] uint32 layout is the same memory as uint64 [n]
    host2 = np.zeros(16384, np.uint8)
    native.hll_fold_u64(pack_u64(keys), host2, seed=0)
    ref = np.zeros(16384, np.uint8)
    native.hll_fold_u64(keys, ref, seed=0)
    np.testing.assert_array_equal(host2, ref)


def test_hll_fold_u64_threads_match_single():
    rng = np.random.default_rng(12)
    keys = rng.integers(0, 2**64, size=300_000, dtype=np.uint64)
    a = np.zeros(16384, np.uint8)
    b = np.zeros(16384, np.uint8)
    native.hll_fold_u64(keys, a, nthreads=1)
    native.hll_fold_u64(keys, b, nthreads=4)
    np.testing.assert_array_equal(a, b)


def test_hll_fold_rows_matches_byte_fold():
    if not native.available():
        return
    keys = [f"user:{i}".encode() for i in range(8000)]
    w = 16
    data = np.zeros((len(keys), w), np.uint8)
    lengths = np.zeros((len(keys),), np.int32)
    for i, k in enumerate(keys):
        data[i, : len(k)] = np.frombuffer(k, np.uint8)
        lengths[i] = len(k)
    rows = np.zeros(16384, np.uint8)
    assert native.hll_fold_rows(data, lengths, rows) is not None
    ref = np.zeros(16384, np.uint8)
    native.hll_fold(keys, ref)
    np.testing.assert_array_equal(rows, ref)
