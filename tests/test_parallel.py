"""Sharded-bank tests on the virtual 8-device CPU mesh (conftest forces
xla_force_host_platform_device_count=8)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from redisson_tpu.parallel import sharded
from redisson_tpu.parallel.mesh import build_mesh
from tests.helpers import pack_u64


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8
    return build_mesh(8)


def _keys(n, seed=0):
    return (np.arange(n, dtype=np.uint64) * np.uint64(0x9E3779B97F4A7C15)
            + np.uint64(seed * 1_000_003 + 1))


def _split(keys):
    return ((keys >> np.uint64(32)).astype(np.uint32),
            (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32))


def test_bank_is_sharded(mesh):
    bank = sharded.make_bank(mesh, 64)
    assert bank.shape == (64, 16384)
    # Each device holds exactly 8 rows.
    shard_shapes = {s.data.shape for s in bank.addressable_shards}
    assert shard_shapes == {(8, 16384)}


def test_insert_routes_to_correct_rows(mesh):
    bank = sharded.make_bank(mesh, 16)
    n = 4096
    keys = _keys(n)
    hi, lo = _split(keys)
    row = (np.arange(n) % 16).astype(np.int32)
    valid = np.ones((n,), bool)
    bank, changed = sharded.bank_insert(bank, hi, lo, row, valid, mesh)
    # changed is per-row (each target's own PFADD bool): every row got keys.
    assert np.asarray(changed).all()
    # Every row received ~256 distinct keys.
    for r in (0, 7, 15):
        est = float(sharded.bank_count_row(bank, jnp.int32(r)))
        assert abs(est - 256) / 256 < 0.2, (r, est)
    # Rows hold disjoint keysets: union ~ n.
    est_all = float(sharded.bank_count_all(bank, mesh))
    assert abs(est_all - n) / n < 0.05


def test_sharded_matches_single_device_semantics(mesh):
    """The sharded insert must produce exactly the registers the single-chip
    kernel produces for the same (key, row) assignment."""
    from redisson_tpu.ops import hashing, hll

    bank = sharded.make_bank(mesh, 8)
    n = 2048
    keys = _keys(n, 3)
    hi, lo = _split(keys)
    row = (np.arange(n) % 8).astype(np.int32)
    valid = np.ones((n,), bool)
    bank, changed = sharded.bank_insert(bank, hi, lo, row, valid, mesh)
    assert np.asarray(changed).any()

    h1, _ = hashing.murmur3_x64_128_u64(pack_u64([int(k) for k in keys]))
    bucket, rank = hll.bucket_rank(h1)
    want = np.zeros((8, 16384), np.int32)
    b_np, r_np = np.asarray(bucket), np.asarray(rank)
    for i in range(n):
        rr = row[i]
        want[rr, b_np[i]] = max(want[rr, b_np[i]], r_np[i])
    assert np.array_equal(np.asarray(bank), want)


def test_merge_all_is_ici_pmax(mesh):
    bank = sharded.make_bank(mesh, 32)
    n = 8192
    keys = _keys(n, 9)
    hi, lo = _split(keys)
    row = (np.arange(n) % 32).astype(np.int32)
    bank, _ = sharded.bank_insert(bank, hi, lo, row, np.ones((n,), bool), mesh)
    merged = np.asarray(sharded.bank_merge_all(bank, mesh))
    assert np.array_equal(merged, np.asarray(bank).max(axis=0))


def test_padded_lanes_are_noops(mesh):
    bank = sharded.make_bank(mesh, 8)
    hi = np.zeros((64,), np.uint32)
    lo = np.zeros((64,), np.uint32)
    row = np.zeros((64,), np.int32)
    valid = np.zeros((64,), bool)  # all padding
    bank, changed = sharded.bank_insert(bank, hi, lo, row, valid, mesh)
    assert not np.asarray(changed).any()
    assert int(np.asarray(bank).sum()) == 0


def test_dryrun_multichip_entry():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_single_chip_entry():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    new_regs, est = jax.jit(fn)(*args)
    assert abs(float(est) - 1024) / 1024 < 0.1
    assert int(np.asarray(new_regs).max()) >= 1


def test_pod_byte_keys_match_local_mode_exactly():
    """Byte keys produce IDENTICAL estimates in local (single-chip) and pod
    (sharded bank) modes: pod pre-hashes bytes with the native batch
    murmur3 — the same h1 the single-chip device path computes — instead
    of the round-1 FNV-1a id fold (VERDICT r1 item #7)."""
    from redisson_tpu.client import RedissonTPU
    from redisson_tpu.config import Config

    keys = [f"user:{i}:söme-bytes" for i in range(4096)]

    local = RedissonTPU.create()
    try:
        h = local.get_hyper_log_log("xmode")
        h.add_all(keys)
        local_est = h.count()
    finally:
        local.shutdown()

    cfg = Config()
    cfg.use_pod().bank_capacity = 64
    pod = RedissonTPU.create(cfg)
    try:
        h = pod.get_hyper_log_log("xmode")
        h.add_all(keys)
        pod_est = h.count()
    finally:
        pod.shutdown()

    assert pod_est == local_est
    assert abs(pod_est - len(keys)) / len(keys) < 0.05


def test_pod_int_and_byte_key_groups_coalesce():
    """One microbatch mixing raw-u64 and byte-key ops lands correctly in
    both insert groups."""
    from redisson_tpu.client import RedissonTPU
    from redisson_tpu.config import Config

    cfg = Config()
    cfg.use_pod().bank_capacity = 64
    pod = RedissonTPU.create(cfg)
    try:
        a = pod.get_hyper_log_log("grp:a")
        b = pod.get_hyper_log_log("grp:b")
        fa = a.add_ints_async(np.arange(2048, dtype=np.uint64))
        fb = b.add_all_async([f"k{i}" for i in range(2048)])
        assert fa.result() in (True, False)
        assert fb.result() in (True, False)
        assert abs(a.count() - 2048) / 2048 < 0.1
        assert abs(b.count() - 2048) / 2048 < 0.1
        # fused merge+count over the sharded bank: one program, one sync,
        # same value as the two-step path
        dest = pod.get_hyper_log_log("grp:dest")
        got = dest.merge_with_and_count("grp:a", "grp:b")
        assert got == a.count_with("grp:b")
        assert dest.count() == got
    finally:
        pod.shutdown()


# -- sharded bit tier (VERDICT r4 missing #1) -------------------------------


@pytest.fixture(scope="module")
def podc():
    from redisson_tpu.client import RedissonTPU
    from redisson_tpu.config import Config

    cfg = Config()
    cfg.use_pod().bank_capacity = 16
    c = RedissonTPU.create(cfg)
    yield c
    c.shutdown()


@pytest.fixture(scope="module")
def localc():
    from redisson_tpu.client import RedissonTPU
    from redisson_tpu.config import Config

    c = RedissonTPU.create(Config())
    yield c
    c.shutdown()


def test_pod_bitset_is_mesh_sharded(podc):
    """Pod bitsets live as bit-range-sharded arrays, not in the single-chip
    delegate store."""
    bs = podc.get_bit_set("sb:shardcheck")
    bs.set(100_000)
    back = podc._routing.sketch
    obj = back._bits["sb:shardcheck"]
    ndev = back.mesh.devices.size
    assert len({s.data.shape for s in obj.state.addressable_shards}) == 1
    assert len(list(obj.state.addressable_shards)) == ndev
    assert back.store.get("sb:shardcheck") is None  # NOT delegated


def test_pod_bitset_matches_single_chip(podc, localc):
    """Same op sequence -> identical observable state across tiers."""
    rng = np.random.default_rng(17)
    idx = rng.integers(0, 50_000, 400)
    for c in (podc, localc):
        bs = c.get_bit_set("sb:eq")
        bs.set_bits([int(i) for i in idx[:200]])
        bs.clear_bits([int(i) for i in idx[100:250]])
        bs.set_bits([int(i) for i in idx[250:]])
    p, l = podc.get_bit_set("sb:eq"), localc.get_bit_set("sb:eq")
    assert p.cardinality() == l.cardinality()
    assert p.length() == l.length()
    assert p.size() == l.size()
    probe = [int(i) for i in rng.integers(0, 60_000, 300)]
    assert list(p.get_bits(probe)) == list(l.get_bits(probe))


def test_pod_bitop_matches_single_chip(podc, localc):
    for c in (podc, localc):
        a = c.get_bit_set("sb:a")
        b = c.get_bit_set("sb:b")
        a.set_bits(list(range(0, 3000, 3)))
        b.set_bits(list(range(0, 3000, 5)))
        d = c.get_bit_set("sb:and")
        d.or_("sb:a")
        d.and_("sb:b")
        x = c.get_bit_set("sb:xor")
        x.or_("sb:a")
        x.xor("sb:b")
    assert (podc.get_bit_set("sb:and").cardinality()
            == localc.get_bit_set("sb:and").cardinality() == 200)
    assert (podc.get_bit_set("sb:xor").cardinality()
            == localc.get_bit_set("sb:xor").cardinality())


def test_pod_bitset_not_and_range(podc, localc):
    for c in (podc, localc):
        bs = c.get_bit_set("sb:not")
        bs.set_bits([0, 10, 100])
        bs.not_()
    p, l = podc.get_bit_set("sb:not"), localc.get_bit_set("sb:not")
    assert p.cardinality() == l.cardinality()
    for c in (podc, localc):
        r = c.get_bit_set("sb:rng")
        r.set_range(1000, 5000)
        r.clear(1200, 1300)
    assert (podc.get_bit_set("sb:rng").cardinality()
            == localc.get_bit_set("sb:rng").cardinality() == 3900)


def test_pod_bloom_bit_identical_and_fpr(podc, localc):
    """Pod bloom over the sharded array: identical add/contains results to
    the single-chip filter for the same keys, and a sane FPR."""
    rng = np.random.default_rng(23)
    keys = rng.integers(0, 2**63, 3000, np.uint64)
    fresh = rng.integers(0, 2**63, 3000, np.uint64)
    for c in (podc, localc):
        bf = c.get_bloom_filter("sb:bloom")
        assert bf.try_init(3000, 0.01) in (True, False)
        bf.add_ints(keys)
    pb, lb = podc.get_bloom_filter("sb:bloom"), localc.get_bloom_filter("sb:bloom")
    assert pb.contains_count_ints(keys) == 3000
    assert lb.contains_count_ints(keys) == 3000
    p_fp = pb.contains_count_ints(fresh)
    l_fp = lb.contains_count_ints(fresh)
    assert p_fp == l_fp  # same hash family, same bits -> identical FPs
    assert p_fp / 3000 < 0.03
    assert pb.count() == lb.count()


def test_pod_bloom_byte_keys_match(podc, localc):
    for c in (podc, localc):
        bf = c.get_bloom_filter("sb:bloomb")
        bf.try_init(500, 0.02)
        bf.add_all([b"key-%d" % i for i in range(300)])
    pb = podc.get_bloom_filter("sb:bloomb")
    lb = localc.get_bloom_filter("sb:bloomb")
    probe = [b"key-%d" % i for i in range(0, 600, 7)]
    assert list(pb.contains_all(probe)) == list(lb.contains_all(probe))


def test_pod_bits_lifecycle(podc):
    bs = podc.get_bit_set("sb:life")
    bs.set(7)
    assert podc.get_keys().delete("sb:life") == 1
    assert podc.get_bit_set("sb:life").cardinality() == 0
    bs = podc.get_bit_set("sb:ren")
    bs.set(3)
    bs.rename("sb:ren2")
    assert podc.get_bit_set("sb:ren2").get(3)
    assert "sb:ren2" in podc.get_keys().get_keys("sb:ren*")
    # wrongtype guards hold across the bank/bits tiers
    from redisson_tpu.store import WrongTypeError

    podc.get_hyper_log_log("sb:h").add(b"x")
    with pytest.raises(WrongTypeError):
        podc.get_bit_set("sb:h").set(1)
    with pytest.raises(WrongTypeError):
        podc.get_hyper_log_log("sb:ren2").add(b"x")
    with pytest.raises(WrongTypeError):
        podc.get_bloom_filter("sb:ren2").try_init(100, 0.01)


def test_pod_bits_checkpoint_roundtrip(tmp_path, podc):
    from redisson_tpu.client import RedissonTPU
    from redisson_tpu.config import Config

    bs = podc.get_bit_set("sb:ck")
    bs.set_bits([5, 17, 40_000])
    bf = podc.get_bloom_filter("sb:ckb")
    bf.try_init(1000, 0.01)
    keys = np.arange(500, dtype=np.uint64)
    bf.add_ints(keys)
    path = str(tmp_path / "podbits")
    podc.save_checkpoint(path, names=["sb:ck", "sb:ckb"])

    # restore into a FRESH pod client
    cfg = Config()
    cfg.use_pod().bank_capacity = 16
    c2 = RedissonTPU.create(cfg)
    try:
        assert c2.load_checkpoint(path) == 2
        assert list(c2.get_bit_set("sb:ck").get_bits([5, 17, 40_000, 6])) == [
            True, True, True, False]
        assert c2.get_bloom_filter("sb:ckb").contains_count_ints(keys) == 500
    finally:
        c2.shutdown()

    # and into a single-chip client (portability across modes)
    c3 = RedissonTPU.create(Config())
    try:
        assert c3.load_checkpoint(path) == 2
        assert c3.get_bit_set("sb:ck").cardinality() == 3
        assert c3.get_bloom_filter("sb:ckb").contains_count_ints(keys) == 500
    finally:
        c3.shutdown()


def test_pod_bitset_growth_preserves_bits(podc):
    bs = podc.get_bit_set("sb:grow")
    bs.set(10)
    for hi in (2_000, 60_000, 300_000):
        bs.set(hi)
    assert bs.cardinality() == 4
    assert bs.length() == 300_001
    assert list(bs.get_bits([10, 2_000, 60_000, 300_000])) == [True] * 4


def test_pod_bits_durability_flush_and_restore(podc):
    """Mesh-sharded bitsets/blooms flush to the wire tier and restore into
    sharded arrays (review r5: they were invisible to durability, and a
    restore landed in the delegate store where the keyspace guards made
    the name unusable)."""
    from redisson_tpu.interop.durability import DurabilityManager
    from redisson_tpu.interop.fake_server import EmbeddedRedis
    from redisson_tpu.interop.resp_client import SyncRespClient

    bs = podc.get_bit_set("dur:bits")
    bs.set_bits([3, 999, 40_000])
    bf = podc.get_bloom_filter("dur:bloom")
    bf.try_init(1000, 0.01)
    keys = np.arange(600, dtype=np.uint64)
    bf.add_ints(keys)

    back = podc._routing.sketch
    with EmbeddedRedis() as er:
        with SyncRespClient(port=er.port) as rc:
            dm = DurabilityManager(
                back.store, rc, executor=podc._executor, pod_backend=back)
            assert dm.flush(["dur:bits", "dur:bloom"]) == 2
            # wipe local state, restore, verify sharded-tier residency
            podc.get_keys().delete("dur:bits")
            podc.get_keys().delete("dur:bloom")
            assert dm.load_bitset("dur:bits")
            assert dm.load_bloom("dur:bloom")
            assert "dur:bits" in back._bits and "dur:bloom" in back._bits
            assert back.store.get("dur:bits") is None
            assert podc.get_bit_set("dur:bits").cardinality() == 3
            assert list(podc.get_bit_set("dur:bits").get_bits(
                [3, 999, 40_000, 5])) == [True, True, True, False]
            assert podc.get_bloom_filter("dur:bloom").contains_count_ints(keys) == 600
            # restored object keeps serving writes
            podc.get_bit_set("dur:bits").set(41_000)
            assert podc.get_bit_set("dur:bits").cardinality() == 4
            # dirty tracking: an unchanged bloom is skipped on the next
            # only_dirty flush, the touched bitset is not
            dm.flush(["dur:bits", "dur:bloom"])
            n = dm.flush(["dur:bits", "dur:bloom"], only_dirty=True)
            assert n == 0
            podc.get_bit_set("dur:bits").set(42_000)
            assert dm.flush(["dur:bits", "dur:bloom"], only_dirty=True) == 1
