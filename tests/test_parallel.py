"""Sharded-bank tests on the virtual 8-device CPU mesh (conftest forces
xla_force_host_platform_device_count=8)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from redisson_tpu.parallel import sharded
from redisson_tpu.parallel.mesh import build_mesh
from tests.helpers import pack_u64


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8
    return build_mesh(8)


def _keys(n, seed=0):
    return (np.arange(n, dtype=np.uint64) * np.uint64(0x9E3779B97F4A7C15)
            + np.uint64(seed * 1_000_003 + 1))


def _split(keys):
    return ((keys >> np.uint64(32)).astype(np.uint32),
            (keys & np.uint64(0xFFFFFFFF)).astype(np.uint32))


def test_bank_is_sharded(mesh):
    bank = sharded.make_bank(mesh, 64)
    assert bank.shape == (64, 16384)
    # Each device holds exactly 8 rows.
    shard_shapes = {s.data.shape for s in bank.addressable_shards}
    assert shard_shapes == {(8, 16384)}


def test_insert_routes_to_correct_rows(mesh):
    bank = sharded.make_bank(mesh, 16)
    n = 4096
    keys = _keys(n)
    hi, lo = _split(keys)
    row = (np.arange(n) % 16).astype(np.int32)
    valid = np.ones((n,), bool)
    bank, changed = sharded.bank_insert(bank, hi, lo, row, valid, mesh)
    # changed is per-row (each target's own PFADD bool): every row got keys.
    assert np.asarray(changed).all()
    # Every row received ~256 distinct keys.
    for r in (0, 7, 15):
        est = float(sharded.bank_count_row(bank, jnp.int32(r)))
        assert abs(est - 256) / 256 < 0.2, (r, est)
    # Rows hold disjoint keysets: union ~ n.
    est_all = float(sharded.bank_count_all(bank, mesh))
    assert abs(est_all - n) / n < 0.05


def test_sharded_matches_single_device_semantics(mesh):
    """The sharded insert must produce exactly the registers the single-chip
    kernel produces for the same (key, row) assignment."""
    from redisson_tpu.ops import hashing, hll

    bank = sharded.make_bank(mesh, 8)
    n = 2048
    keys = _keys(n, 3)
    hi, lo = _split(keys)
    row = (np.arange(n) % 8).astype(np.int32)
    valid = np.ones((n,), bool)
    bank, changed = sharded.bank_insert(bank, hi, lo, row, valid, mesh)
    assert np.asarray(changed).any()

    h1, _ = hashing.murmur3_x64_128_u64(pack_u64([int(k) for k in keys]))
    bucket, rank = hll.bucket_rank(h1)
    want = np.zeros((8, 16384), np.int32)
    b_np, r_np = np.asarray(bucket), np.asarray(rank)
    for i in range(n):
        rr = row[i]
        want[rr, b_np[i]] = max(want[rr, b_np[i]], r_np[i])
    assert np.array_equal(np.asarray(bank), want)


def test_merge_all_is_ici_pmax(mesh):
    bank = sharded.make_bank(mesh, 32)
    n = 8192
    keys = _keys(n, 9)
    hi, lo = _split(keys)
    row = (np.arange(n) % 32).astype(np.int32)
    bank, _ = sharded.bank_insert(bank, hi, lo, row, np.ones((n,), bool), mesh)
    merged = np.asarray(sharded.bank_merge_all(bank, mesh))
    assert np.array_equal(merged, np.asarray(bank).max(axis=0))


def test_padded_lanes_are_noops(mesh):
    bank = sharded.make_bank(mesh, 8)
    hi = np.zeros((64,), np.uint32)
    lo = np.zeros((64,), np.uint32)
    row = np.zeros((64,), np.int32)
    valid = np.zeros((64,), bool)  # all padding
    bank, changed = sharded.bank_insert(bank, hi, lo, row, valid, mesh)
    assert not np.asarray(changed).any()
    assert int(np.asarray(bank).sum()) == 0


def test_dryrun_multichip_entry():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_single_chip_entry():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    new_regs, est = jax.jit(fn)(*args)
    assert abs(float(est) - 1024) / 1024 < 0.1
    assert int(np.asarray(new_regs).max()) >= 1


def test_pod_byte_keys_match_local_mode_exactly():
    """Byte keys produce IDENTICAL estimates in local (single-chip) and pod
    (sharded bank) modes: pod pre-hashes bytes with the native batch
    murmur3 — the same h1 the single-chip device path computes — instead
    of the round-1 FNV-1a id fold (VERDICT r1 item #7)."""
    from redisson_tpu.client import RedissonTPU
    from redisson_tpu.config import Config

    keys = [f"user:{i}:söme-bytes" for i in range(4096)]

    local = RedissonTPU.create()
    try:
        h = local.get_hyper_log_log("xmode")
        h.add_all(keys)
        local_est = h.count()
    finally:
        local.shutdown()

    cfg = Config()
    cfg.use_pod().bank_capacity = 64
    pod = RedissonTPU.create(cfg)
    try:
        h = pod.get_hyper_log_log("xmode")
        h.add_all(keys)
        pod_est = h.count()
    finally:
        pod.shutdown()

    assert pod_est == local_est
    assert abs(pod_est - len(keys)) / len(keys) < 0.05


def test_pod_int_and_byte_key_groups_coalesce():
    """One microbatch mixing raw-u64 and byte-key ops lands correctly in
    both insert groups."""
    from redisson_tpu.client import RedissonTPU
    from redisson_tpu.config import Config

    cfg = Config()
    cfg.use_pod().bank_capacity = 64
    pod = RedissonTPU.create(cfg)
    try:
        a = pod.get_hyper_log_log("grp:a")
        b = pod.get_hyper_log_log("grp:b")
        fa = a.add_ints_async(np.arange(2048, dtype=np.uint64))
        fb = b.add_all_async([f"k{i}" for i in range(2048)])
        assert fa.result() in (True, False)
        assert fb.result() in (True, False)
        assert abs(a.count() - 2048) / 2048 < 0.1
        assert abs(b.count() - 2048) / 2048 < 0.1
        # fused merge+count over the sharded bank: one program, one sync,
        # same value as the two-step path
        dest = pod.get_hyper_log_log("grp:dest")
        got = dest.merge_with_and_count("grp:a", "grp:b")
        assert got == a.count_with("grp:b")
        assert dest.count() == got
    finally:
        pod.shutdown()
