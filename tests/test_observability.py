"""Observability tests: metrics registry, executor instrumentation,
profiler hook, nodes/health API."""

import pytest

from redisson_tpu.client import RedissonTPU
from redisson_tpu.observability import (Histogram, MetricsRegistry, NodesGroup,
                                        profile)


@pytest.fixture()
def client():
    c = RedissonTPU.create()
    yield c
    c.shutdown()


def test_registry_counters_and_gauges():
    r = MetricsRegistry()
    r.inc("a.b")
    r.inc("a.b", 4)
    assert r.counter("a.b") == 5
    r.gauge("g", lambda: 7.5)
    snap = r.snapshot()
    assert snap["counters"]["a.b"] == 5
    assert snap["gauges"]["g"] == 7.5


def test_histogram_stats():
    h = Histogram()
    for v in (0.001, 0.01, 0.01, 1.0):
        h.observe(v)
    s = h.snapshot()
    assert s["count"] == 4
    assert s["min"] == 0.001 and s["max"] == 1.0
    assert abs(s["mean"] - (0.001 + 0.01 + 0.01 + 1.0) / 4) < 1e-9


def test_prometheus_rendering():
    r = MetricsRegistry()
    r.inc("ops.total", 3)
    r.gauge("queue.depth", lambda: 2)
    r.observe("lat", 0.005)
    text = r.render_prometheus()
    assert "ops_total 3" in text
    assert "queue_depth 2" in text
    assert "lat_count 1" in text
    assert 'lat_bucket{le="0.01"}' in text


def test_prometheus_histogram_exposition():
    r = MetricsRegistry()
    r.observe("lat", 0.005)
    r.observe("lat", 2.0)
    text = r.render_prometheus()
    assert "# TYPE lat histogram" in text
    # The overflow bucket must be spelled +Inf, never Python's "inf".
    assert 'lat_bucket{le="+Inf"} 2' in text
    assert 'le="inf"' not in text
    counts = [int(line.rsplit(" ", 1)[1])
              for line in text.splitlines() if line.startswith("lat_bucket")]
    assert counts == sorted(counts)  # cumulative => non-decreasing
    assert counts[-1] == 2  # +Inf bucket equals _count
    assert "lat_count 2" in text
    assert "lat_sum" in text


def test_registry_snapshot_mutation_safe():
    r = MetricsRegistry()
    r.inc("ops.total", 3)
    r.observe("lat", 0.005)
    snap = r.snapshot()
    snap["counters"]["ops.total"] = 999
    snap["histograms"]["lat"]["buckets"].clear()
    snap["histograms"]["lat"]["count"] = 0
    snap2 = r.snapshot()
    assert snap2["counters"]["ops.total"] == 3
    assert snap2["histograms"]["lat"]["count"] == 1
    assert sum(snap2["histograms"]["lat"]["buckets"].values()) == 1


def test_fault_injector_snapshot_mutation_safe():
    from redisson_tpu.fault.inject import FaultInjector, FaultPlan, FaultRule

    inj = FaultInjector(FaultPlan(rules=[FaultRule(seam="journal_fsync")]))
    with pytest.raises(Exception):
        inj.fire("journal_fsync")
    snap = inj.snapshot()
    snap["fired"][0]["seam"] = "corrupted"
    snap["hits"][0] = 999
    snap2 = inj.snapshot()
    assert snap2["fired"][0]["seam"] == "journal_fsync"
    assert snap2["hits"][0] == 1


def test_executor_metrics_flow(client):
    h = client.get_hyper_log_log("obs:h")
    h.add_all([b"k%d" % i for i in range(1000)])
    h.count()
    snap = client.metrics.snapshot()
    assert snap["counters"]["executor.ops_total"] >= 2
    assert snap["counters"]["executor.keys_total"] >= 1000
    assert snap["counters"].get("executor.ops.hll_add", 0) >= 1
    assert snap["histograms"]["executor.batch_keys"]["count"] >= 1
    assert snap["gauges"]["executor.queue_depth"] == 0  # drained


def test_executor_error_metric(client):
    bf = client.get_bloom_filter("obs:bloom")
    with pytest.raises(Exception):
        bf.add(b"x")  # not initialized -> backend error
    assert client.metrics.counter("executor.errors_total") >= 1


def test_nodes_group_ping(client):
    ng = client.get_nodes_group()
    nodes = ng.nodes()
    assert any(n.kind == "device" for n in nodes)
    assert ng.ping_all()


def test_nodes_group_with_redis_tier():
    from redisson_tpu.config import Config
    from redisson_tpu.interop.fake_server import EmbeddedRedis

    with EmbeddedRedis() as er:
        cfg = Config()
        cfg.use_local()
        cfg.use_redis().address = f"redis://127.0.0.1:{er.port}"
        c = RedissonTPU.create(cfg)
        try:
            ng = c.get_nodes_group()
            kinds = {n.kind for n in ng.nodes()}
            assert kinds == {"device", "redis"}
            assert ng.ping_all()
        finally:
            c.shutdown()


def test_connection_listener_fanout(client):
    ng = client.get_nodes_group()
    events = []
    ng.add_connection_listener(lambda e, ident: events.append((e, ident)))
    ng.fire("connect", "node-1")
    ng.fire("disconnect", "node-1")
    assert events == [("connect", "node-1"), ("disconnect", "node-1")]


def test_profile_context_manager(tmp_path, client):
    # Must not raise whether or not the platform supports tracing.
    with profile(str(tmp_path / "trace")):
        client.get_hyper_log_log("obs:p").add_all([b"a", b"b"])
