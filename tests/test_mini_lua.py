"""mini-Lua interpreter + fake-server EVAL / pub-sub / blocking-pop tests.

The scripts exercised here are shaped like the reference's server-side
coordination scripts (RedissonLock.java:236-252 tryAcquire,
RedissonLock.java:324-343 unlock, RedissonMapCache.java TTL puts) — run
against the fake server through a real RESP connection.
"""

from __future__ import annotations

import threading
import time

import pytest

from redisson_tpu.interop import mini_lua
from redisson_tpu.interop.fake_server import EmbeddedRedis
from redisson_tpu.interop.resp_client import SyncRespClient
from redisson_tpu.native import RespError


# ---------------------------------------------------------------------------
# Interpreter unit tests (no server: a dict-backed redis.call stub)
# ---------------------------------------------------------------------------


class FakeCall:
    """Minimal redis.call target: a few commands over a plain dict."""

    def __init__(self):
        self.kv = {}
        self.hashes = {}

    def __call__(self, args):
        name = bytes(args[0]).upper()
        if name == b"SET":
            self.kv[args[1]] = args[2]
            return {"ok": b"OK"}
        if name == b"GET":
            return self.kv.get(args[1])
        if name == b"EXISTS":
            return int(args[1] in self.kv or args[1] in self.hashes)
        if name == b"HSET":
            self.hashes.setdefault(args[1], {})[args[2]] = args[3]
            return 1
        if name == b"HEXISTS":
            return int(args[2] in self.hashes.get(args[1], {}))
        if name == b"HINCRBY":
            h = self.hashes.setdefault(args[1], {})
            v = int(h.get(args[2], b"0")) + int(args[3])
            h[args[2]] = str(v).encode()
            return v
        if name == b"DEL":
            n = int(args[1] in self.kv) + int(args[1] in self.hashes)
            self.kv.pop(args[1], None)
            self.hashes.pop(args[1], None)
            return n
        raise mini_lua.LuaError(b"unknown command " + name)


def run(src, keys=(), argv=(), call=None):
    return mini_lua.run_script(
        src if isinstance(src, bytes) else src.encode(),
        [k if isinstance(k, bytes) else k.encode() for k in keys],
        [a if isinstance(a, bytes) else a.encode() for a in argv],
        call or FakeCall(),
    )


def test_literals_and_arithmetic():
    assert run("return 1 + 2 * 3") == 7
    assert run("return (1 + 2) * 3") == 9
    assert run("return 7 % 3") == 1
    assert run("return 2 ^ 10") == 1024
    assert run("return -(-5)") == 5
    assert run("return 10 / 4") == 2  # Lua->RESP truncates to integer


def test_strings_concat_compare():
    assert run("return 'a' .. 'b' .. 1") == b"ab1"
    assert run("return tostring(3)") == b"3"
    assert run("return tostring(3.5)") == b"3.5"
    assert run("return tonumber('12') + 1") == 13
    assert run("return tonumber('nope')") is None
    assert run("if 'abc' < 'abd' then return 1 else return 0 end") == 1


def test_keys_argv_and_locals():
    assert run("return KEYS[1]", keys=["k1"]) == b"k1"
    assert run("return ARGV[2]", argv=["a", "b"]) == b"b"
    assert run("local x = 5; local y = x + 1; return y") == 6
    assert run("local a, b = 1; return tostring(b)") == b"nil"
    assert run("return #ARGV", argv=["a", "b", "c"]) == 3


def test_control_flow():
    src = """
    local total = 0
    for i = 1, 10 do
        if i % 2 == 0 then total = total + i end
    end
    return total
    """
    assert run(src) == 30
    src = """
    local i = 0
    while true do
        i = i + 1
        if i >= 4 then break end
    end
    return i
    """
    assert run(src) == 4
    src = """
    local n = 0
    repeat n = n + 1 until n >= 3
    return n
    """
    assert run(src) == 3


def test_tables():
    assert run("local t = {10, 20, 30}; return t[2]") == 20
    assert run("local t = {}; table.insert(t, 'x'); table.insert(t, 'y'); return t") == [
        b"x",
        b"y",
    ]
    assert run("local t = {a = 7}; return t.a") == 7
    src = """
    local out = {}
    for i, v in ipairs({'p', 'q'}) do
        table.insert(out, v .. i)
    end
    return out
    """
    assert run(src) == [b"p1", b"q2"]


def test_stdlib():
    assert run("return string.sub('hello', 2, 3)") == b"el"
    assert run("return string.sub('hello', -3)") == b"llo"
    assert run("return string.rep('ab', 3)") == b"ababab"
    assert run("return string.format('%s=%d', 'n', 42)") == b"n=42"
    assert run("return math.floor(3.9)") == 3
    assert run("return math.max(1, 9, 4)") == 9
    assert run("return type('x')") == b"string"
    with pytest.raises(mini_lua.LuaError, match="boom"):
        run("error('boom')")


def test_redis_call_roundtrip():
    call = FakeCall()
    assert run("return redis.call('set', KEYS[1], ARGV[1])", ["k"], ["v"], call) == {
        "ok": b"OK"
    }
    assert run("return redis.call('get', KEYS[1])", ["k"], [], call) == b"v"
    # nil bulk converts to Lua false -> RESP nil
    assert run("return redis.call('get', 'missing')", [], [], call) is None
    assert (
        run(
            "if redis.call('get', 'missing') == false then return 'was-nil' end",
            [],
            [],
            call,
        )
        == b"was-nil"
    )


def test_lock_shaped_script():
    """The reference's tryAcquire contract (RedissonLock.java:236-252):
    nil => acquired; number => remaining ttl of the holder."""
    call = FakeCall()
    acquire = """
    if (redis.call('exists', KEYS[1]) == 0) then
        redis.call('hset', KEYS[1], ARGV[2], 1)
        return nil
    end
    if (redis.call('hexists', KEYS[1], ARGV[2]) == 1) then
        redis.call('hincrby', KEYS[1], ARGV[2], 1)
        return nil
    end
    return 42
    """
    assert run(acquire, ["L"], ["30000", "owner:1"], call) is None  # acquired
    assert run(acquire, ["L"], ["30000", "owner:1"], call) is None  # reentrant
    assert run(acquire, ["L"], ["30000", "owner:2"], call) == 42  # contended
    assert call.hashes[b"L"][b"owner:1"] == b"2"


def test_execution_budget():
    with pytest.raises(mini_lua.LuaError, match="budget"):
        run("while true do end")


# ---------------------------------------------------------------------------
# Fake-server integration: EVAL over the wire
# ---------------------------------------------------------------------------


@pytest.fixture()
def server():
    with EmbeddedRedis() as s:
        yield s


@pytest.fixture()
def client(server):
    c = SyncRespClient(port=server.port, timeout=5.0)
    c.connect()
    yield c
    c.close()


def test_eval_over_wire(client):
    assert client.execute("EVAL", "return 1 + 1", "0") == 2
    assert (
        client.execute("EVAL", "return redis.call('set', KEYS[1], ARGV[1])",
                       "1", "k", "v")
        == b"OK"
    )
    assert client.execute("GET", "k") == b"v"
    assert client.execute("EVAL", "return {1, 'two', 3}", "0") == [1, b"two", 3]


def test_evalsha_and_script_load(client):
    sha = client.execute("SCRIPT", "LOAD", "return ARGV[1]")
    assert len(sha) == 40
    assert client.execute("EVALSHA", sha, "0", "hi") == b"hi"
    assert client.execute("SCRIPT", "EXISTS", sha, "0" * 40) == [1, 0]
    with pytest.raises(RespError, match="NOSCRIPT"):
        client.execute("EVALSHA", "f" * 40, "0")


def test_eval_atomic_counter_script(client):
    src = """
    local v = redis.call('incrby', KEYS[1], ARGV[1])
    if v > tonumber(ARGV[2]) then
        redis.call('set', KEYS[1], ARGV[2])
        return tonumber(ARGV[2])
    end
    return v
    """
    assert client.execute("EVAL", src, "1", "ctr", "7", "10") == 7
    assert client.execute("EVAL", src, "1", "ctr", "7", "10") == 10


def test_eval_error_surfaces(client):
    with pytest.raises(RespError, match="(?i)script"):
        client.execute("EVAL", "error('custom failure')", "0")


def test_eval_pexpire_pttl(client):
    src = """
    redis.call('set', KEYS[1], 'v')
    redis.call('pexpire', KEYS[1], ARGV[1])
    return redis.call('pttl', KEYS[1])
    """
    ttl = client.execute("EVAL", src, "1", "tkey", "30000")
    assert 0 < ttl <= 30000


def test_zrangebyscore(client):
    client.execute("ZADD", "z", "1", "a", "2", "b", "3", "c")
    assert client.execute("ZRANGEBYSCORE", "z", "-inf", "2") == [b"a", b"b"]
    assert client.execute("ZRANGEBYSCORE", "z", "(1", "+inf") == [b"b", b"c"]
    assert client.execute("ZCOUNT", "z", "1", "3") == 3
    assert client.execute("ZREMRANGEBYSCORE", "z", "-inf", "1") == 1
    assert client.execute("ZRANGEBYSCORE", "z", "-inf", "+inf") == [b"b", b"c"]
    assert client.execute(
        "ZRANGEBYSCORE", "z", "-inf", "+inf", "LIMIT", "1", "1"
    ) == [b"c"]


def test_blocking_pop_immediate(client):
    client.execute("RPUSH", "q", "x")
    assert client.execute("BLPOP", "q", "0") == [b"q", b"x"]
    # empty + timeout -> nil after ~the timeout
    t0 = time.time()
    assert client.execute("BLPOP", "q", "0.1") is None
    assert time.time() - t0 >= 0.09


def test_blocking_pop_wakeup(server, client):
    """A parked BLPOP wakes when another connection pushes."""
    got = {}

    def waiter():
        c2 = SyncRespClient(port=server.port, timeout=10.0)
        c2.connect()
        try:
            got["v"] = c2.execute("BLPOP", "wq", "5")
        finally:
            c2.close()

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.15)  # let it park
    client.execute("RPUSH", "wq", "payload")
    t.join(timeout=5)
    assert not t.is_alive()
    assert got["v"] == [b"wq", b"payload"]


def test_pubsub_publish_counts_receivers(server, client):
    """PUBLISH with no subscribers returns 0; with one connection in
    subscribe mode, 1 (frame delivery is exercised by the PubSub client
    tests in test_redis_coordination)."""
    assert client.execute("PUBLISH", "chan", "m") == 0
