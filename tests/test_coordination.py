"""Coordination + caching tests, modeled on the reference's concurrency
suites (RedissonLockTest, RedissonSemaphoreTest,
RedissonCountDownLatchConcurrentTest, RedissonTopicTest,
RedissonBlockingQueueTest, RedissonMapCacheTest)."""

import threading
import time

import pytest

from redisson_tpu.client import RedissonTPU
from redisson_tpu.config import Config


@pytest.fixture(scope="module")
def client():
    c = RedissonTPU.create(Config())
    yield c
    c.shutdown()


@pytest.fixture(autouse=True)
def _flush(client):
    client.flushall()
    yield


# ---- locks ----------------------------------------------------------------


def test_lock_basic(client):
    lk = client.get_lock("lk")
    assert not lk.is_locked()
    lk.lock()
    assert lk.is_locked()
    assert lk.is_held_by_current_thread()
    assert lk.get_hold_count() == 1
    lk.lock()  # reentrant
    assert lk.get_hold_count() == 2
    lk.unlock()
    assert lk.is_locked()
    lk.unlock()
    assert not lk.is_locked()


def test_lock_unlock_not_owner_raises(client):
    lk = client.get_lock("lk2")
    with pytest.raises(RuntimeError):
        lk.unlock()


def test_lock_contention_across_threads(client):
    lk = client.get_lock("lk3")
    order = []

    def worker(i):
        with client.get_lock("lk3"):
            order.append(("in", i))
            time.sleep(0.02)
            order.append(("out", i))

    lk.lock()
    threads = [threading.Thread(target=worker, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    time.sleep(0.05)
    assert order == []  # all blocked while we hold it
    lk.unlock()
    for t in threads:
        t.join(timeout=5)
    # mutual exclusion: in/out strictly alternate
    assert len(order) == 6
    for j in range(0, 6, 2):
        assert order[j][0] == "in" and order[j + 1][0] == "out"
        assert order[j][1] == order[j + 1][1]


def test_try_lock_timeout(client):
    lk = client.get_lock("lk4")
    lk.lock()

    result = {}

    def attempt():
        other = client.get_lock("lk4")
        t0 = time.monotonic()
        result["ok"] = other.try_lock(wait_time_s=0.1)
        result["dt"] = time.monotonic() - t0

    t = threading.Thread(target=attempt)
    t.start()
    t.join(timeout=5)
    assert result["ok"] is False
    assert result["dt"] >= 0.09
    lk.unlock()


def test_lock_lease_expiry_allows_takeover(client):
    lk = client.get_lock("lk5")
    assert lk.try_lock(lease_time_s=0.05)
    done = {}

    def taker():
        done["ok"] = client.get_lock("lk5").try_lock(wait_time_s=2.0, lease_time_s=1.0)

    t = threading.Thread(target=taker)
    t.start()
    t.join(timeout=5)
    assert done["ok"] is True  # lease expired -> orphan reaped


def test_force_unlock(client):
    lk = client.get_lock("lk6")
    lk.lock()
    assert lk.force_unlock()
    assert not lk.is_locked()


def test_fair_lock_fifo(client):
    lk = client.get_fair_lock("flk")
    lk.lock()
    acquired = []

    def worker(i):
        w = client.get_fair_lock("flk")
        w.lock()
        acquired.append(i)
        w.unlock()

    threads = []
    for i in range(3):
        t = threading.Thread(target=worker, args=(i,))
        t.start()
        threads.append(t)
        time.sleep(0.05)  # establish queue order
    lk.unlock()
    for t in threads:
        t.join(timeout=5)
    assert acquired == [0, 1, 2]


def test_read_write_lock(client):
    rw = client.get_read_write_lock("rw")
    r1 = rw.read_lock()
    r1.lock()
    # second reader (other thread) may enter
    got = {}

    def reader():
        r = client.get_read_write_lock("rw").read_lock()
        got["r"] = r.try_lock(wait_time_s=0.5)
        if got["r"]:
            r.unlock()

    t = threading.Thread(target=reader)
    t.start()
    t.join(timeout=5)
    assert got["r"] is True

    def writer():
        w = client.get_read_write_lock("rw").write_lock()
        got["w"] = w.try_lock(wait_time_s=0.2)

    t = threading.Thread(target=writer)
    t.start()
    t.join(timeout=5)
    assert got["w"] is False  # writer blocked by reader
    r1.unlock()


def test_multi_lock(client):
    locks = [client.get_lock(f"ml{i}") for i in range(3)]
    ml = client.get_multi_lock(*locks)
    assert ml.try_lock()
    assert all(lk.is_locked() for lk in locks)
    ml.unlock()
    assert not any(lk.is_locked() for lk in locks)

    # if one child is held elsewhere, acquisition fails and rolls back
    blocker = {}

    def hold():
        lk = client.get_lock("ml1")
        lk.lock()
        blocker["ev"].wait()
        lk.unlock()

    blocker["ev"] = threading.Event()
    t = threading.Thread(target=hold)
    t.start()
    time.sleep(0.05)
    assert not ml.try_lock(wait_time_s=0.1)
    assert not locks[0].is_locked()  # rolled back
    blocker["ev"].set()
    t.join(timeout=5)


# ---- semaphore / latch ----------------------------------------------------


def test_semaphore(client):
    sem = client.get_semaphore("sem")
    assert sem.try_set_permits(2)
    assert not sem.try_set_permits(5)
    assert sem.try_acquire()
    assert sem.try_acquire()
    assert not sem.try_acquire()
    sem.release()
    assert sem.available_permits() == 1
    assert sem.try_acquire(permits=1, timeout_s=0.1)
    assert sem.drain_permits() == 0
    sem.add_permits(3)
    assert sem.available_permits() == 3
    sem.reduce_permits(1)
    assert sem.available_permits() == 2


def test_semaphore_blocking_release(client):
    sem = client.get_semaphore("sem2")
    sem.try_set_permits(0)
    got = {}

    def acq():
        got["ok"] = sem.try_acquire(timeout_s=2.0)

    t = threading.Thread(target=acq)
    t.start()
    time.sleep(0.05)
    sem.release()
    t.join(timeout=5)
    assert got["ok"] is True


def test_count_down_latch(client):
    latch = client.get_count_down_latch("cdl")
    assert latch.try_set_count(3)
    assert latch.get_count() == 3
    done = {}

    def waiter():
        done["ok"] = latch.await_(timeout_s=3.0)

    t = threading.Thread(target=waiter)
    t.start()
    for _ in range(3):
        latch.count_down()
    t.join(timeout=5)
    assert done["ok"] is True
    assert latch.get_count() == 0
    assert latch.await_(timeout_s=0.01)  # already zero


# ---- topic ----------------------------------------------------------------


def test_topic_pubsub(client):
    topic = client.get_topic("news")
    got = []
    ev = threading.Event()

    def listener(channel, msg):
        got.append((channel, msg))
        ev.set()

    lid = topic.add_listener(listener)
    n = topic.publish({"headline": "hello"})
    assert n == 1
    assert ev.wait(timeout=2)
    assert got == [("news", {"headline": "hello"})]
    topic.remove_listener(lid)
    assert topic.publish("ignored") == 0


def test_pattern_topic(client):
    pt = client.get_pattern_topic("evt:*")
    got = []
    ev = threading.Event()

    def listener(pattern, channel, msg):
        got.append((pattern, channel, msg))
        ev.set()

    pt.add_listener(listener)
    client.get_topic("evt:a").publish("m1")
    assert ev.wait(timeout=2)
    assert got == [("evt:*", "evt:a", "m1")]
    pt.remove_all_listeners()
    assert client.get_topic("evt:b").publish("m2") == 0


# ---- blocking queue -------------------------------------------------------


def test_blocking_queue_immediate(client):
    q = client.get_blocking_queue("bq")
    q.offer("a")
    assert q.take() == "a"


def test_blocking_queue_poll_timeout(client):
    q = client.get_blocking_queue("bq2")
    t0 = time.monotonic()
    assert q.poll(timeout_s=0.15) is None
    assert time.monotonic() - t0 >= 0.14


def test_blocking_queue_take_waits_for_push(client):
    q = client.get_blocking_queue("bq3")
    got = {}

    def taker():
        got["v"] = q.take()

    t = threading.Thread(target=taker)
    t.start()
    time.sleep(0.05)
    client.get_blocking_queue("bq3").offer("pushed")
    t.join(timeout=5)
    assert got["v"] == "pushed"


def test_blocking_queue_fifo_waiters(client):
    q = client.get_blocking_queue("bq4")
    got = []
    lock = threading.Lock()

    def taker(i):
        v = q.poll(timeout_s=5.0)
        with lock:
            got.append((i, v))

    threads = []
    for i in range(2):
        t = threading.Thread(target=taker, args=(i,))
        t.start()
        threads.append(t)
        time.sleep(0.05)
    q.offer("first")
    q.offer("second")
    for t in threads:
        t.join(timeout=5)
    assert {v for _, v in got} == {"first", "second"}
    # FIFO: the first-parked waiter gets the first element
    assert dict(got)[0] == "first"


def test_blocking_deque_and_brpoplpush(client):
    d = client.get_blocking_deque("bd")
    d.add_first("x")
    assert d.take_last() == "x"

    q = client.get_blocking_queue("bsrc")
    got = {}

    def mover():
        got["v"] = q.poll_last_and_offer_first_to("bdst", timeout_s=3.0)

    t = threading.Thread(target=mover)
    t.start()
    time.sleep(0.05)
    q.offer("moved")
    t.join(timeout=5)
    assert got["v"] == "moved"
    assert client.get_queue("bdst").peek() == "moved"


# ---- caches ---------------------------------------------------------------


def test_map_cache_ttl(client):
    mc = client.get_map_cache("mc")
    assert mc.put("k", "v", ttl_s=0.05) is None
    assert mc.get("k") == "v"
    assert mc.contains_key("k")
    time.sleep(0.08)
    assert mc.get("k") is None
    assert not mc.contains_key("k")

    mc.put("p", "forever")
    assert mc.get("p") == "forever"
    assert mc.put_if_absent("p", "nope") == "forever"
    assert mc.put_if_absent("q", "yes") is None
    assert mc.size() == 2
    assert mc.remove("q") == "yes"


def test_map_cache_max_idle(client):
    mc = client.get_map_cache("mc2")
    mc.put("k", "v", max_idle_s=0.1)
    for _ in range(3):  # touches keep it alive
        time.sleep(0.04)
        assert mc.get("k") == "v"
    time.sleep(0.15)  # no touch -> idles out
    assert mc.get("k") is None


def test_map_cache_eviction_sweep(client):
    mc = client.get_map_cache("mc3")
    for i in range(10):
        mc.put(f"k{i}", i, ttl_s=0.03)
    mc.put("keep", "alive")
    time.sleep(0.06)
    removed = mc.evict_expired()
    assert removed == 10
    assert mc.read_all_map() == {"keep": "alive"}


def test_set_cache(client):
    sc = client.get_set_cache("sc")
    assert sc.add("a", ttl_s=0.05)
    assert sc.add("b")
    assert sc.contains("a")
    assert sc.size() == 2
    time.sleep(0.08)
    assert not sc.contains("a")
    assert sc.size() == 1
    assert sc.read_all() == {"b"}
    assert sc.remove("b")
    assert not sc.remove("b")


# ---- cross-tier sanity ----------------------------------------------------


def test_sketch_and_structures_coexist(client):
    hll = client.get_hyper_log_log("mix:hll")
    hll.add_all([f"u{i}" for i in range(100)])
    m = client.get_map("mix:map")
    m.fast_put("count", 100)
    assert abs(hll.count() - 100) <= 3
    assert m.get("count") == 100
    assert set(client.keys("mix:*")) == {"mix:hll", "mix:map"}
    client.flushall()
    assert client.keys("mix:*") == []
