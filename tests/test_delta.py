"""Delta ingest (PR 7 tentpole): host-folded register/bit deltas with one
fused multi-target merge per pipeline window.

Pins the acceptance contract: for hll_add/bloom_add/bitset_set the delta
path is bit-identical to the serial scatter path (device state AND per-op
results — PFADD "changed", bloom try_add "newly", bitset old bits), mixed
hll+bloom+bitset windows retire in ONE fused merge launch, the sparse
(idx, val) encoding kicks in exactly when it is smaller than the dense
plane, link bytes/key collapse below 1/8 of raw at large batches, the
planner's measured row overrides a stale dominated prior (the `sort`
regression), and delta merges bump read-cache epochs exactly like scatter.
"""

import numpy as np
import pytest

from redisson_tpu import native
from redisson_tpu.client import RedissonTPU
from redisson_tpu.config import Config, TpuConfig
from redisson_tpu.ingest import delta as delta_mod
from redisson_tpu.ingest.planner import IngestPlanner

needs_native = pytest.mark.skipif(
    not native.available(), reason="native fold library unavailable")


def _mk(ingest="delta"):
    return RedissonTPU.create(Config(tpu=TpuConfig(ingest=ingest)))


def _backend(c):
    return c._routing.sketch


def _bank_row(c, name):
    be = _backend(c)
    return np.asarray(be._ensure_bank())[be._rows[name]].copy()


# ---------------------------------------------------------------------------
# encoding: sparse-vs-dense crossover
# ---------------------------------------------------------------------------


def test_encode_picks_sparse_when_smaller():
    dense = np.zeros(1 << 14, np.uint8)
    dense[[3, 77, 9000]] = 5
    p = delta_mod.encode("hll_add", "t", dense, cells=1 << 14, packed=False,
                         nkeys=3, raw_bytes=24)
    assert p.sparse  # 3 * 5 B << 16384 B dense
    assert p.link_bytes < p.plane_bytes
    # Sparse entries are (idx, val) pairs padded to a pow2 with (0, 0);
    # real entries must round-trip.
    got = dict(zip(np.asarray(p.idx).tolist(), np.asarray(p.val).tolist()))
    assert got[3] == 5 and got[77] == 5 and got[9000] == 5


def test_encode_picks_dense_when_touched_fraction_large():
    dense = np.arange(1 << 14, dtype=np.uint8) % 50 + 1  # every cell touched
    p = delta_mod.encode("hll_add", "t", dense, cells=1 << 14, packed=False,
                         nkeys=1 << 14, raw_bytes=8 << 14)
    assert not p.sparse
    assert p.link_bytes == p.plane_bytes


# ---------------------------------------------------------------------------
# host folds vs numpy oracles
# ---------------------------------------------------------------------------


def test_fold_bitset_matches_numpy_packbits():
    rng = np.random.default_rng(3)
    idx = rng.integers(0, 4096, 700, np.int64)
    plane = delta_mod.fold_bitset([{"idx": idx}], 4096)
    want = np.zeros(4096, np.uint8)
    want[idx] = 1
    np.testing.assert_array_equal(plane, np.packbits(want))


@needs_native
def test_fold_hll_matches_device_registers():
    rng = np.random.default_rng(4)
    keys = rng.integers(0, 2**63, 5000, np.uint64)
    cd = _mk("delta")
    cs = _mk("device")
    try:
        cd.get_hyper_log_log("d:fold").add_ints(keys)
        cs.get_hyper_log_log("d:fold").add_ints(keys)
        np.testing.assert_array_equal(
            _bank_row(cd, "d:fold"), _bank_row(cs, "d:fold"))
    finally:
        cd.shutdown()
        cs.shutdown()


# ---------------------------------------------------------------------------
# golden: delta vs serial op-by-op, per-op result parity
# ---------------------------------------------------------------------------


@needs_native
def test_hll_pfadd_changed_parity():
    rng = np.random.default_rng(5)
    keys = rng.integers(0, 2**63, 3000, np.uint64)
    c = _mk("delta")
    try:
        h = c.get_hyper_log_log("d:pfadd")
        assert h.add_ints(keys) is True  # fresh registers: changed
        assert h.add_ints(keys) is False  # identical re-add: no register moved
        assert h.add_ints(rng.integers(0, 2**63, 64, np.uint64)) is True
    finally:
        c.shutdown()


@needs_native
def test_bloom_try_add_newly_parity_and_intra_batch_duplicates():
    rng = np.random.default_rng(6)
    a = rng.integers(0, 2**62, 500, np.uint64)
    cd, cs = _mk("delta"), _mk("device")
    try:
        for c in (cd, cs):
            f = c.get_bloom_filter("d:bloom")
            f.try_init(expected_insertions=50_000, false_probability=0.01)
        rd = cd.get_bloom_filter("d:bloom").add_ints(a)
        rs = cs.get_bloom_filter("d:bloom").add_ints(a)
        np.testing.assert_array_equal(np.asarray(rd), np.asarray(rs))
        # Re-add: every key already present on both paths.
        rd2 = cd.get_bloom_filter("d:bloom").add_ints(a)
        rs2 = cs.get_bloom_filter("d:bloom").add_ints(a)
        assert not np.asarray(rd2).any()
        np.testing.assert_array_equal(np.asarray(rd2), np.asarray(rs2))
        np.testing.assert_array_equal(
            np.asarray(_backend(cd).store.get("d:bloom").state),
            np.asarray(_backend(cs).store.get("d:bloom").state))
    finally:
        cd.shutdown()
        cs.shutdown()
    # Intra-batch duplicate: the fold is evolving (key i sees keys < i of
    # its own batch), matching serial one-key-at-a-time semantics.
    c = _mk("delta")
    try:
        f = c.get_bloom_filter("d:dup")
        f.try_init(expected_insertions=10_000, false_probability=0.01)
        dup = np.array([11, 22, 11], np.uint64)
        got = np.asarray(f.add_ints(dup))
        assert got[0] and got[1] and not got[2]
    finally:
        c.shutdown()


@needs_native
def test_bitset_old_bits_parity_across_windows():
    cd, cs = _mk("delta"), _mk("device")
    try:
        for c, out in ((cd, []), (cs, [])):
            b = c.get_bit_set("d:bits")
            out.append(np.asarray(b.set_bits([3, 9, 3000])))
            out.append(np.asarray(b.set_bits([3, 10])))  # 3 already set
            first, second = out
            np.testing.assert_array_equal(first, [False, False, False])
            np.testing.assert_array_equal(second, [True, False])
        np.testing.assert_array_equal(
            np.asarray(_backend(cd).store.get("d:bits").state),
            np.asarray(_backend(cs).store.get("d:bits").state))
    finally:
        cd.shutdown()
        cs.shutdown()


# ---------------------------------------------------------------------------
# mixed window: one fused merge launch for all three kinds
# ---------------------------------------------------------------------------


@needs_native
def test_mixed_window_single_fused_launch():
    rng = np.random.default_rng(7)
    c = _mk("delta")
    try:
        f = c.get_bloom_filter("d:mixb")
        f.try_init(expected_insertions=50_000, false_probability=0.01)
        be = _backend(c)
        runs0 = be.counters["delta_runs"]
        launches0 = be.counters["merge_launches"]
        # Submit all three kinds async in one burst: the executor's
        # delta-group steal stacks them into one window.
        futs = [
            c.get_hyper_log_log("d:mixh").add_ints_async(
                rng.integers(0, 2**63, 2000, np.uint64)),
            f.add_ints_async(rng.integers(0, 2**62, 1000, np.uint64)),
            c.get_bit_set("d:mixs").set_bits_async([1, 4, 900]),
        ]
        for fut in futs:
            fut.result(timeout=60)
        runs = be.counters["delta_runs"] - runs0
        launches = be.counters["merge_launches"] - launches0
        assert runs >= 1
        # Every window here fits one chunk: launches == windows, never
        # one launch per target/kind.
        assert launches == runs
        assert be.counters["delta_keys"] >= 3003
    finally:
        c.shutdown()


@needs_native
def test_link_bytes_collapse_below_eighth_of_raw():
    rng = np.random.default_rng(8)
    c = _mk("delta")
    try:
        c.get_hyper_log_log("d:link").add_ints(
            rng.integers(0, 2**63, 1 << 17, np.uint64))
        stats = _backend(c).ingest_stats()
        assert stats["raw_bytes"] == 8 << 17
        assert stats["link_bytes"] * 8 < stats["raw_bytes"]
        assert stats["delta_bytes_per_key"] < 1.0
    finally:
        c.shutdown()


# ---------------------------------------------------------------------------
# satellite 1: planner priors never outlive the first-use measurement
# ---------------------------------------------------------------------------


def test_planner_measured_row_overrides_dominated_prior():
    def fake_measure(structure, n):
        return {"scatter": 1.0, "sort": 5.0, "segment": 2.0}

    p = IngestPlanner(platform="cpu", measure=fake_measure)
    # A stale prior claims `sort` is 10x cheaper than it really is — the
    # historical BENCH_r05 regression.
    p.set_prior("hll", 1 << 16, {"sort": 0.1})
    plan = p.plan("hll", 1 << 16)
    assert plan.path == "scatter"  # measured winner, never the stale prior
    assert plan.costs["sort"] == 5.0  # measurement overrode the prior value


def test_planner_prior_only_fills_unmeasured_paths():
    def fake_measure(structure, n):
        return {"scatter": 3.0, "sort": 5.0, "segment": 4.0}

    p = IngestPlanner(platform="cpu", measure=fake_measure)
    # `delta` cannot be timed by the device loop; the prior supplies it.
    p.set_prior("hll", 1 << 16, {"delta": 0.5})
    plan = p.plan("hll", 1 << 16)
    assert plan.path == "delta"
    assert plan.costs["scatter"] == 3.0


def test_planner_auto_never_picks_dominated_path():
    def fake_measure(structure, n):
        return {"scatter": 1.0, "sort": 50.0, "segment": 2.0}

    p = IngestPlanner(platform="cpu", measure=fake_measure)
    for nkeys in (1 << 10, 1 << 14, 1 << 18, 1 << 21):
        plan = p.plan("hll", nkeys)
        assert plan.costs[plan.path] == min(plan.costs.values())
        assert plan.path != "sort"


# ---------------------------------------------------------------------------
# satellite 2: delta merges bump read-cache epochs exactly like scatter
# ---------------------------------------------------------------------------


@needs_native
class TestDeltaEpochInvalidation:
    def test_hll(self):
        c = _mk("delta")
        try:
            h = c.get_hyper_log_log("d:ep:h")
            h.add_ints(np.arange(1000, dtype=np.uint64))
            first = h.count()
            cache = _backend(c).read_cache
            hits0 = cache.hits
            assert h.count() == first
            assert cache.hits > hits0  # second read served from cache
            h.add_ints(np.arange(1000, 4000, dtype=np.uint64))
            assert h.count() > first  # delta merge bumped the epoch
        finally:
            c.shutdown()

    def test_bitset(self):
        c = _mk("delta")
        try:
            b = c.get_bit_set("d:ep:b")
            b.set_bits([1, 5, 9])
            assert b.cardinality() == 3
            cache = _backend(c).read_cache
            hits0 = cache.hits
            assert b.cardinality() == 3
            assert cache.hits > hits0
            b.set_bits([100, 200])
            assert b.cardinality() == 5  # not the stale cached 3
        finally:
            c.shutdown()

    def test_bloom(self):
        c = _mk("delta")
        try:
            f = c.get_bloom_filter("d:ep:f")
            f.try_init(expected_insertions=10_000, false_probability=0.01)
            f.add_ints(np.array([7, 8], np.uint64))
            assert f.count() >= 1
            cache = _backend(c).read_cache
            hits0 = cache.hits
            f.count()
            assert cache.hits > hits0
            f.add_ints(np.array([9, 10, 11], np.uint64))
            assert f.count() >= 3  # delta merge invalidated the count
        finally:
            c.shutdown()


# ---------------------------------------------------------------------------
# satellite 6: backend gauges reach the metrics registry
# ---------------------------------------------------------------------------


@needs_native
def test_delta_gauges_in_metrics_snapshot():
    c = _mk("delta")
    try:
        c.get_hyper_log_log("d:gauge").add_ints(
            np.arange(50_000, dtype=np.uint64) * 2654435761 % (2**61))
        snap = c.metrics.snapshot()["gauges"]
        assert snap["backend.link_bytes"] > 0
        assert snap["backend.raw_bytes"] == 50_000 * 8
        assert snap["backend.merge_launches"] >= 1
        assert snap["backend.delta_fold_s"] > 0.0
        assert snap["backend.delta_bytes_per_key"] > 0.0
    finally:
        c.shutdown()
