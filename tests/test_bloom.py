import numpy as np

from redisson_tpu.ops import bitset, bloom
from tests import golden
from tests.helpers import hash_ints


def test_reference_sizing_vector():
    # Mirrors RedissonBloomFilterTest.java:10-17 — expectedInsertions=100,
    # falseProbability=0.03 must size to m=729, k=5.
    m = bloom.optimal_num_of_bits(100, 0.03)
    k = bloom.optimal_num_of_hash_functions(100, m)
    assert m == 729
    assert k == 5


def test_indexes_match_python_mod():
    for m in (729, 16384, 1 << 20, (1 << 31) - 1, 1 << 31):
        vals = [v * 0x9E3779B97F4A7C15 + 1 for v in range(64)]
        h1, h2 = hash_ints(vals)
        idx = np.asarray(bloom.indexes(h1, h2, 5, m))
        for row, v in zip(idx, vals):
            g1, g2 = golden.murmur3_x64_128(int(v & ((1 << 64) - 1)).to_bytes(8, "little"))
            want = [((g1 + i * g2) % (1 << 64)) % m for i in range(5)]
            assert row.tolist() == want


def test_add_contains_no_false_negatives():
    m, k = 1 << 16, 7
    bits = bitset.make(m)
    members = list(range(1000))
    h1, h2 = hash_ints(members)
    idx = bloom.indexes(h1, h2, k, m)
    bits, added = bloom.add(bits, idx)
    assert bool(np.all(np.asarray(added)))  # fresh filter: every key new
    assert bool(np.all(np.asarray(bloom.contains(bits, idx))))
    # Re-adding the same keys reports no change.
    _, added2 = bloom.add(bits, idx)
    assert not bool(np.any(np.asarray(added2)))


def test_false_positive_rate_near_design_point():
    n, p = 5000, 0.02
    m = bloom.optimal_num_of_bits(n, p)
    k = bloom.optimal_num_of_hash_functions(n, m)
    bits = bitset.make(m)
    members = [v * 2654435761 + 7 for v in range(n)]
    h1, h2 = hash_ints(members)
    bits, _ = bloom.add(bits, bloom.indexes(h1, h2, k, m))
    probes = [v * 2654435761 + 7 for v in range(n, n + 20000)]
    ph1, ph2 = hash_ints(probes)
    hits = np.asarray(bloom.contains(bits, bloom.indexes(ph1, ph2, k, m)))
    fpr = hits.mean()
    assert fpr < 3 * p, fpr


def test_count_estimate():
    n = 5000
    m = bloom.optimal_num_of_bits(n, 0.01)
    k = bloom.optimal_num_of_hash_functions(n, m)
    bits = bitset.make(m)
    h1, h2 = hash_ints(list(range(n)))
    bits, _ = bloom.add(bits, bloom.indexes(h1, h2, k, m))
    est = float(bloom.count_estimate(bitset.cardinality(bits), m, k))
    assert abs(est - n) / n < 0.05


def test_int_fast_path_matches_byte_path():
    """add_ints/contains_ints hash uint64 keys as their 8-byte LE encodings
    on device — membership must be bit-identical to the byte path."""
    import numpy as np

    from redisson_tpu.client import RedissonTPU

    c = RedissonTPU.create()
    try:
        bf = c.get_bloom_filter("bloom:ints")
        bf.try_init(50_000, 0.01)
        keys = np.arange(1, 3001, dtype=np.uint64) * np.uint64(0x9E3779B97F4A7C15)
        added = bf.add_ints(keys)
        assert added.all()
        assert not bf.add_ints(keys[:100]).any()  # re-add: nothing new
        assert bf.contains_ints(keys).all()
        # Byte path sees exactly the same membership for the same encodings.
        assert bf.contains_all([k.tobytes() for k in keys[:200]]).all()
        fresh = keys + np.uint64(1)
        assert bf.contains_ints(fresh).mean() < 0.05
    finally:
        c.shutdown()


def test_contains_count_matches_per_key():
    """contains_count (the scalar reduce) must equal sum(contains) for the
    same batch, on both the host-packed and device-resident payloads."""
    import jax
    import numpy as np

    from redisson_tpu.client import RedissonTPU
    from redisson_tpu.models.object import pack_u64

    client = RedissonTPU.create()
    try:
        bf = client.get_bloom_filter("bloom:cc")
        bf.try_init(expected_insertions=10_000, false_probability=0.01)
        rng = np.random.default_rng(21)
        ins = rng.integers(0, 2**62, 5_000, np.uint64)
        bf.add_ints(ins)
        probe = np.concatenate([ins[:2_000],
                                rng.integers(2**62, 2**63, 3_000, np.uint64)])
        per_key = int(bf.contains_ints(probe).sum())
        assert bf.contains_count_ints(probe) == per_key
        dev = jax.device_put(pack_u64(probe))
        assert bf.contains_count_device_async(dev).result() == per_key
        assert per_key >= 2_000  # no false negatives on the inserted prefix
    finally:
        client.shutdown()


def test_blocked_bloom_membership_and_fpr():
    """Blocked layout: no false negatives, FPR within ~2x of the classic
    filter at the same sizing (512-bit blocks keep the Putze penalty small),
    count reduce agrees with per-key contains."""
    import jax

    from redisson_tpu.client import RedissonTPU
    from redisson_tpu.models.object import pack_u64

    c = RedissonTPU.create()
    try:
        bf = c.get_bloom_filter("bloom:blk")
        assert bf.try_init(50_000, 0.01, blocked=True) is True
        assert bf.is_blocked() is True
        assert bf.get_size() % 512 == 0
        rng = np.random.default_rng(31)
        ins = rng.integers(0, 2**62, 50_000, np.uint64)
        added = bf.add_ints(ins)
        assert added.all()
        assert bf.contains_ints(ins).all(), "false negatives!"
        # byte-key path hits the same registers as int path on same encodings
        assert bf.contains_all([int(ins[0]).to_bytes(8, "little")])[0]

        fresh = rng.integers(2**62, 2**63, 100_000, np.uint64)
        fpr = bf.contains_ints(fresh).mean()
        assert fpr < 0.03, fpr  # sized for 1%; blocked penalty bounded

        per_key = int(bf.contains_ints(fresh).sum())
        assert bf.contains_count_ints(fresh) == per_key
        dev = jax.device_put(pack_u64(fresh))
        assert bf.contains_count_device_async(dev).result() == per_key

        # classic filter at same sizing: different layout, same answers for
        # inserted keys
        cf = c.get_bloom_filter("bloom:classic")
        cf.try_init(50_000, 0.01)
        assert cf.is_blocked() is False
        cf.add_ints(ins[:1000])
        assert cf.contains_ints(ins[:1000]).all()
    finally:
        c.shutdown()


def test_blocked_indexes_properties():
    """All k positions inside one block and pairwise distinct (odd step)."""
    import jax.numpy as jnp

    from redisson_tpu.ops import bloom as b
    from tests.helpers import hash_ints

    m = b.blocked_geometry(1 << 20)
    h1, h2 = hash_ints([v * 0x9E3779B97F4A7C15 + 3 for v in range(256)])
    block, pos = b.blocked_indexes(h1, h2, 7, m)
    assert np.asarray(block).min() >= 0
    assert np.asarray(block).max() < m // 512
    p = np.asarray(pos)
    assert p.min() >= 0 and p.max() < 512
    for row in p:
        assert len(set(row.tolist())) == 7  # distinct positions per key
    absolute = np.asarray(b.blocked_absolute(jnp.asarray(block), jnp.asarray(pos)))
    assert (absolute // 512 == np.asarray(block)[:, None]).all()
