"""Cluster tier tests: CRC16 KEYSLOT vectors, slot routing, guard
accept/reject, MOVED retry, batch splitting, keyspace fan-out, cross-shard
PFMERGE, live slot migration under concurrent writes, and crash recovery
of the slot table.

Runs single-process on the virtual 8-device CPU platform (conftest).
"""

import threading
import time

import pytest

from redisson_tpu.cluster import (
    ClusterCrossSlotError,
    SlotMovedError,
    contiguous_assignment,
    slot_ranges,
    split_by_owner,
)
from redisson_tpu.ops.crc16 import MAX_SLOT, crc16, key_slot


# ---------------------------------------------------------------------------
# CLUSTER KEYSLOT vectors (redis-cli golden) + hashtag semantics
# ---------------------------------------------------------------------------


def test_crc16_known_vector():
    # The check value from redis's crc16.c: CRC-CCITT (XModem) of the
    # standard test string.
    assert crc16(b"123456789") == 0x31C3


@pytest.mark.parametrize("key,slot", [
    # redis-cli CLUSTER KEYSLOT golden values (cluster tutorial / docs).
    ("foo", 12182),
    ("hello", 866),
    ("somekey", 11058),
    ("foo{hash_tag}", 2515),
    ("bar{hash_tag}", 2515),
])
def test_cluster_keyslot_vectors(key, slot):
    assert key_slot(key) == slot


def test_hashtag_routes_to_tag_slot():
    # `{user1000}.following` and `.followers` co-locate on user1000's slot.
    assert key_slot("{user1000}.following") == key_slot("user1000")
    assert key_slot("{user1000}.followers") == key_slot("user1000")


def test_empty_hashtag_falls_back_to_whole_key():
    # `foo{}{bar}`: the FIRST brace pair is empty, so the whole key hashes
    # (the second pair is never considered — redis hashtag rules).
    assert key_slot("foo{}{bar}") == crc16(b"foo{}{bar}") % MAX_SLOT
    assert key_slot("foo{}{bar}") != key_slot("bar")


def test_first_brace_pair_wins():
    assert key_slot("foo{bar}{zap}") == key_slot("bar")
    # `foo{{bar}}zap`: tag is `{bar` (first "{" to first "}").
    assert key_slot("foo{{bar}}zap") == crc16(b"{bar") % MAX_SLOT


def test_unclosed_brace_hashes_whole_key():
    assert key_slot("foo{bar") == crc16(b"foo{bar") % MAX_SLOT


# ---------------------------------------------------------------------------
# splitter + assignment helpers
# ---------------------------------------------------------------------------


def test_split_by_owner_preserves_order():
    items = ["a", "b", "c", "d", "e"]
    groups = split_by_owner(items, lambda i, it: i % 2)
    assert groups == {0: [0, 2, 4], 1: [1, 3]}


def test_contiguous_assignment_covers_all_slots():
    table = contiguous_assignment(MAX_SLOT, 4)
    assert len(table) == MAX_SLOT
    assert set(table) == {0, 1, 2, 3}
    ranges = slot_ranges(table)
    assert ranges[0][0] == 0 and ranges[-1][1] == MAX_SLOT - 1
    # Contiguous: each range starts where the previous ended + 1.
    for (s0, e0, _), (s1, _, _) in zip(ranges, ranges[1:]):
        assert s1 == e0 + 1


# ---------------------------------------------------------------------------
# 4-shard cluster (no persist) — routing, fan-out, redirects
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def cluster4():
    from redisson_tpu.client import RedissonTPU
    from redisson_tpu.config import Config

    cfg = Config()
    cfg.use_cluster(num_shards=4)
    c = RedissonTPU.create(cfg)
    yield c
    c.shutdown()


def _key_on_shard(client, sid, prefix="k", start=0):
    table = client.cluster.router.slot_table()
    i = start
    while True:
        k = f"{prefix}{i}"
        if table[key_slot(k)] == sid:
            return k
        i += 1


def test_cluster_mode_facade(cluster4):
    c = cluster4
    assert c._mode == "cluster"
    assert c.cluster_keyslot("foo") == 12182
    ranges = c.cluster_slots()
    assert ranges[0][0] == 0 and ranges[-1][1] == MAX_SLOT - 1
    assert {r[2] for r in ranges} == {0, 1, 2, 3}
    info = c.cluster_info()
    assert info["cluster_enabled"] == 1
    assert info["cluster_state"] == "ok"
    assert info["cluster_slots_assigned"] == MAX_SLOT
    assert info["cluster_known_nodes"] == 4
    # INFO surfaces the cluster section.
    assert "cluster" in c.info()


def test_keyed_ops_route_per_slot(cluster4):
    c = cluster4
    for sid in range(4):
        k = _key_on_shard(c, sid, prefix=f"route{sid}:")
        c.get_bucket(k).set(f"v{sid}")
        assert c.get_bucket(k).get() == f"v{sid}"
    # No shard saw a misrouted (rejected) op.
    assert all(s.guard.rejected_ops == 0 for s in c.cluster.shards.values())


def test_atomic_long_and_map_route(cluster4):
    c = cluster4
    al = c.get_atomic_long("cl:counter")
    al.set(10)
    assert al.add_and_get(5) == 15
    m = c.get_map("cl:map")
    m.put("f", "x")
    assert m.get("f") == "x"


def test_cross_shard_buckets_mget_mset(cluster4):
    c = cluster4
    keys = [_key_on_shard(c, sid, prefix=f"mg{sid}:") for sid in range(4)]
    c.get_buckets().set({k: f"mv{i}" for i, k in enumerate(keys)})
    got = c.get_buckets().get(*keys)
    assert got == {k: f"mv{i}" for i, k in enumerate(keys)}


def test_msetnx_cross_shard_rejected(cluster4):
    c = cluster4
    k0 = _key_on_shard(c, 0, prefix="nx0:")
    k1 = _key_on_shard(c, 1, prefix="nx1:")
    with pytest.raises(ClusterCrossSlotError):
        c.get_buckets().try_set({k0: "a", k1: "b"})
    # Same-shard msetnx works.
    k0b = _key_on_shard(c, 0, prefix="nx0b:")
    assert c.get_buckets().try_set({k0: "a", k0b: "b"}) is True


def test_cokey_crossslot_check(cluster4):
    c = cluster4
    # rename to a key on a different shard: -CROSSSLOT.
    src = _key_on_shard(c, 0, prefix="rn:")
    dst = _key_on_shard(c, 1, prefix="rnd:")
    c.get_bucket(src).set("x")
    fut = c.cluster.router.execute_async(src, "rename", {"newkey": dst})
    with pytest.raises(ClusterCrossSlotError):
        fut.result(10)
    # Hashtags co-locate: rename succeeds.
    c.get_bucket("{rnt}a").set("y")
    c.cluster.router.execute_sync("{rnt}a", "rename", {"newkey": "{rnt}b"})
    assert c.get_bucket("{rnt}b").get() == "y"


def test_keys_and_delete_fan_out(cluster4):
    c = cluster4
    keys = [_key_on_shard(c, sid, prefix=f"fan{sid}:") for sid in range(4)]
    for k in keys:
        c.get_bucket(k).set("1")
    found = c.cluster.router.execute_sync("", "keys", {"pattern": "fan*"})
    assert sorted(found) == sorted(keys)
    for k in keys:
        c.get_bucket(k).set(None)  # DEL
    assert c.cluster.router.execute_sync("", "keys", {"pattern": "fan*"}) == []


def test_execute_many_splits_per_owner(cluster4):
    c = cluster4
    keys = [_key_on_shard(c, i % 4, prefix=f"em{i}:") for i in range(12)]
    staged = [(k, "set", {"value": b"b%d" % i}, 0)
              for i, k in enumerate(keys)]
    futs = c.cluster.router.execute_many(staged)
    for f in futs:
        f.result(30)
    for i, k in enumerate(keys):
        assert c.cluster.router.execute_sync(k, "get", None) == b"b%d" % i


def test_batch_collector_via_router(cluster4):
    c = cluster4
    b = c.create_batch()
    k0 = _key_on_shard(c, 0, prefix="bat0:")
    k3 = _key_on_shard(c, 3, prefix="bat3:")
    b.get_bucket(k0).set_async("p")
    b.get_bucket(k3).set_async("q")
    b.execute()
    assert c.get_bucket(k0).get() == "p"
    assert c.get_bucket(k3).get() == "q"


def test_cross_shard_pfmerge_matches_single_shard_oracle(cluster4):
    c = cluster4
    # Three HLLs guaranteed to live on three different shards.
    names = [_key_on_shard(c, sid, prefix=f"pf{sid}:") for sid in range(3)]
    vals = [[b"a%d" % i for i in range(300)],
            [b"b%d" % i for i in range(300)],
            [b"a%d" % i for i in range(150)]]  # overlaps set 0
    for n, vs in zip(names, vals):
        c.get_hyper_log_log(n).add_all(vs)
    merged = c.get_hyper_log_log(names[0]).merge_with_and_count(*names[1:])
    # Oracle: same values in ONE hll on one shard (hashtag co-location).
    oracle = c.get_hyper_log_log("{pforacle}")
    for vs in vals:
        oracle.add_all(vs)
    assert merged == oracle.count()
    assert c.cluster.router.cross_shard_merges > 0
    # count_with does not mutate the target.
    before = c.get_hyper_log_log(names[1]).count()
    c.get_hyper_log_log(names[1]).count_with(names[2])
    assert c.get_hyper_log_log(names[1]).count() == before


def test_guard_rejects_foreign_slot_with_moved(cluster4):
    c = cluster4
    # Submit a key owned by shard 1 DIRECTLY to shard 0's dispatch: the
    # ownership guard must reject it on the future with SlotMovedError.
    k = _key_on_shard(c, 1, prefix="rej:")
    shard0 = c.cluster.shards[0]
    fut = shard0.dispatch.execute_async(k, "set", {"value": b"x"})
    with pytest.raises(SlotMovedError):
        fut.result(10)
    assert shard0.guard.rejected_ops > 0


def test_moved_retry_lands_on_new_owner(cluster4):
    """Deterministic MOVED retry: hold shard 0's dispatcher with a barrier,
    enqueue a flip followed by keyed writes (they pass the router's resolve
    while the table still says shard 0), open the ASK window, release. The
    writes dispatch after the flip, get rejected with SlotMovedError, the
    redirect worker re-resolves — parking on the window — and lands them on
    the new owner after the table commit. Zero lost acks."""
    c = cluster4
    router = c.cluster.router
    src, tgt = c.cluster.shards[0], c.cluster.shards[1]
    keys = [_key_on_shard(c, 0, prefix=f"mvd{i}:") for i in range(8)]
    slots = sorted({key_slot(k) for k in keys})

    entered, release = threading.Event(), threading.Event()

    def hold():
        entered.set()
        release.wait(30)

    redirects0 = router.redirects
    bfut = src.executor.execute_barrier(hold)
    assert entered.wait(10)
    # Everything below enqueues behind the barrier on shard 0.
    fflip = src.executor.execute_async("", "migrate_flip", {"slots": slots})
    wfuts = [router.execute_async(k, "set", {"value": b"mv%d" % i})
             for i, k in enumerate(keys)]
    tgt.adopt(slots)
    router.begin_cutover(slots)
    release.set()
    bfut.result(30)
    fflip.result(30)
    time.sleep(0.05)
    router.commit_cutover(slots, tgt.shard_id)
    for f in wfuts:
        f.result(30)  # every ack lands despite the mid-flight move
    assert router.redirects > redirects0
    for i, k in enumerate(keys):
        assert router.execute_sync(k, "get", None) == b"mv%d" % i
        assert router.slot_table()[key_slot(k)] == tgt.shard_id


def test_shard_stats_surface(cluster4):
    stats = cluster4.cluster.stats()
    assert set(stats["shards"]) == {0, 1, 2, 3}
    for s in stats["shards"].values():
        assert s["owned_slots"] > 0
        assert not s["quarantined"]


def test_topology_quarantine_round_trip():
    from redisson_tpu.client import RedissonTPU
    from redisson_tpu.config import Config

    cfg = Config()
    cfg.use_cluster(num_shards=2)
    cfg.cluster.auto_heal = False  # no journal: drain would be refused
    c = RedissonTPU.create(cfg)
    try:
        mgr = c.cluster
        down = {"ok": True}
        mgr.set_pinger(1, lambda: down["ok"])
        down["ok"] = False
        for _ in range(mgr.topology.failed_attempts):
            mgr.topology.scan_once()
        assert mgr.shards[1].quarantined
        assert c.cluster_info()["cluster_state"] == "degraded"
        down["ok"] = True
        mgr.topology.scan_once()
        assert not mgr.shards[1].quarantined
        assert c.cluster_info()["cluster_state"] == "ok"
    finally:
        c.shutdown()


# ---------------------------------------------------------------------------
# live migration (persisted shards) + recovery
# ---------------------------------------------------------------------------


def _make_persisted_cluster(tmp_path, num_shards=3):
    from redisson_tpu.client import RedissonTPU
    from redisson_tpu.config import Config

    cfg = Config()
    cfg.use_cluster(num_shards=num_shards, dir=str(tmp_path / "cl"))
    return RedissonTPU.create(cfg)


def test_live_migration_under_concurrent_writes(tmp_path):
    c = _make_persisted_cluster(tmp_path, num_shards=3)
    try:
        mgr = c.cluster
        table = mgr.router.slot_table()
        keys = []
        i = 0
        while len(keys) < 60:
            k = f"lm{i}"
            if table[key_slot(k)] == 0:
                keys.append(k)
            i += 1
        for k in keys:
            c.get_bucket(k).set("v0")
        move_slots = sorted({key_slot(k) for k in keys})
        hll_key = next(k for k in keys)  # reuse a migrating slot's tag
        h = c.get_hyper_log_log("{%s}hll" % hll_key)
        h.add_all([b"h%d" % j for j in range(500)])
        est0 = h.count()

        errs, acked = [], {}
        stop = threading.Event()

        def writer():
            n = 0
            while not stop.is_set():
                k = keys[n % len(keys)]
                v = f"w{n}"
                try:
                    c.get_bucket(k).set(v)
                    acked[k] = v
                except Exception as e:  # noqa: BLE001 — any lost ack fails the test below
                    errs.append((k, repr(e)))
                n += 1

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        time.sleep(0.2)
        stats = mgr.migrate_slots(move_slots, 2, timeout_s=60)
        time.sleep(0.2)
        stop.set()
        t.join(10)

        assert errs == []  # zero lost acks
        assert stats["apply_errors"] == 0
        # Digest: every acked write reads back its final acked value.
        for k, v in acked.items():
            assert c.get_bucket(k).get() == v
        # Ownership flipped for every migrated slot.
        post = mgr.router.slot_table()
        assert all(post[s] == 2 for s in move_slots)
        # The co-located HLL migrated with its slot, count preserved.
        assert c.get_hyper_log_log("{%s}hll" % hll_key).count() == est0
        assert mgr.migrations == 1
    finally:
        c.shutdown()


def test_add_shard_and_migrate_into_it(tmp_path):
    c = _make_persisted_cluster(tmp_path, num_shards=2)
    try:
        mgr = c.cluster
        k = _key_on_shard(c, 0, prefix="grow:")
        c.get_bucket(k).set("here")
        new_id = mgr.add_shard()
        assert new_id == 2
        assert mgr.shards[new_id].owned_count() == 0
        mgr.migrate_slots([key_slot(k)], new_id, timeout_s=60)
        assert mgr.router.slot_table()[key_slot(k)] == new_id
        assert c.get_bucket(k).get() == "here"
        info = c.cluster_info()
        assert info["cluster_known_nodes"] == 3
    finally:
        c.shutdown()


def test_slot_table_recovers_after_restart(tmp_path):
    c = _make_persisted_cluster(tmp_path, num_shards=2)
    k = _key_on_shard(c, 0, prefix="rec:")
    slot = key_slot(k)
    try:
        c.get_bucket(k).set("durable")
        c.cluster.migrate_slots([slot], 1, timeout_s=60)
        assert c.cluster.router.slot_table()[slot] == 1
        table_before = c.cluster.router.slot_table()
    finally:
        c.shutdown()

    c2 = _make_persisted_cluster(tmp_path, num_shards=2)
    try:
        # Journal replay rebuilt each guard's ownership; the manager's
        # recovered table must agree — including the migrated slot.
        assert c2.cluster.router.slot_table() == table_before
        assert c2.cluster.router.slot_table()[slot] == 1
        assert c2.get_bucket(k).get() == "durable"
    finally:
        c2.shutdown()
