import random

import numpy as np

from redisson_tpu.ops import hashing, u64 as u
from tests import golden

# Lengths straddling every block/tail boundary of both hashes.
BOUNDARY_LENGTHS = [0, 1, 3, 4, 5, 7, 8, 9, 15, 16, 17, 23, 24, 31, 32, 33, 40, 47, 48, 63, 64]


def _batch(keys, width):
    n = len(keys)
    data = np.zeros((n, width), np.uint8)
    lengths = np.zeros((n,), np.int32)
    for i, k in enumerate(keys):
        data[i, : len(k)] = np.frombuffer(k, np.uint8)
        lengths[i] = len(k)
    return data, lengths


def _rand_keys(seed=0):
    rng = random.Random(seed)
    keys = [bytes(rng.getrandbits(8) for _ in range(ln)) for ln in BOUNDARY_LENGTHS]
    keys += [bytes(rng.getrandbits(8) for _ in range(rng.randrange(0, 64))) for _ in range(40)]
    return keys


def test_murmur3_x64_128_matches_golden():
    keys = _rand_keys(1)
    data, lengths = _batch(keys, 64)
    for seed in (0, 0x9747B28C):
        h1, h2 = hashing.murmur3_x64_128_jit(data, lengths, seed)
        got = list(zip(u.to_python(h1).tolist(), u.to_python(h2).tolist()))
        want = [golden.murmur3_x64_128(k, seed) for k in keys]
        assert got == want


def test_murmur3_u64_fast_path_matches_bytes_path():
    rng = random.Random(3)
    vals = [rng.getrandbits(64) for _ in range(50)]
    x = u.U64(
        np.array([v >> 32 for v in vals], np.uint32),
        np.array([v & 0xFFFFFFFF for v in vals], np.uint32),
    )
    h1, h2 = hashing.murmur3_x64_128_u64(x)
    want = [golden.murmur3_x64_128(v.to_bytes(8, "little")) for v in vals]
    got = list(zip(u.to_python(h1).tolist(), u.to_python(h2).tolist()))
    assert got == want


def test_xxhash64_known_vector_empty():
    # Canonical xxh64("") seed 0.
    data = np.zeros((1, 32), np.uint8)
    lengths = np.zeros((1,), np.int32)
    h = hashing.xxhash64_jit(data, lengths, 0)
    assert int(u.to_python(h)[0]) == 0xEF46DB3751D8E999


def test_xxhash64_matches_golden():
    keys = _rand_keys(7)
    data, lengths = _batch(keys, 64)
    for seed in (0, 2654435761):
        h = hashing.xxhash64_jit(data, lengths, seed)
        got = u.to_python(h).tolist()
        want = [golden.xxhash64(k, seed) for k in keys]
        assert got == want


def test_padding_garbage_is_ignored():
    # Bytes beyond each key's length must not affect the hash.
    keys = [b"hello", b"a-longer-key-123"]
    data, lengths = _batch(keys, 48)
    dirty = data.copy()
    for i, k in enumerate(keys):
        dirty[i, len(k):] = 0xAB
    clean1 = hashing.murmur3_x64_128_jit(data, lengths, 0)
    dirty1 = hashing.murmur3_x64_128_jit(dirty, lengths, 0)
    assert u.to_python(clean1[0]).tolist() == u.to_python(dirty1[0]).tolist()
    assert u.to_python(hashing.xxhash64_jit(data, lengths, 0)).tolist() == \
        u.to_python(hashing.xxhash64_jit(dirty, lengths, 0)).tolist()
