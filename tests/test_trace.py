"""Trace subsystem tests: spans, sampling, histograms, SLOWLOG/MONITOR/
LATENCY parity surfaces, exports, and the end-to-end client wiring.

Layers:

1. Unit — Tracer/Span lifecycle on a fake clock, counter-stride sampling
   determinism, histogram quantile error bounds and merge algebra,
   slowlog threshold + stage attribution, monitor drop-and-count.
2. Export — Chrome trace-event JSON schema, Prometheus exposition shape.
3. Integration — a real client with ``use_trace(sample_every=1)``: spans
   stamped across executor/backend, read-cache hit annotation, registry
   gauges; and a journal-fsync stall (fault/inject "stall" rule) whose
   slowlog entry attributes the latency to the journal stage.
"""

import json

import pytest

from redisson_tpu.client import RedissonTPU
from redisson_tpu.config import Config
from redisson_tpu.fault import inject
from redisson_tpu.trace import (HistogramSet, LatencyHistogram, Monitor,
                                SlowLog, TraceManager, Tracer, chrome_trace,
                                format_event, prometheus_exposition)
from redisson_tpu.trace.hist import bucket_index, bucket_upper_ticks
from redisson_tpu.trace.manager import LatencyEvents


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture(autouse=True)
def _clean_injector():
    inject.uninstall()
    yield
    inject.uninstall()


# ---------------------------------------------------------------------------
# 1. spans + tracer
# ---------------------------------------------------------------------------

def test_span_lifecycle_and_stage_breakdown():
    clk = FakeClock()
    tr = Tracer(clock=clk, sample_every=1)
    tr.annotate_next(admitted_at=clk.t)
    clk.advance(0.001)
    s = tr.maybe_begin("hll_add", "t:h", nkeys=4)
    assert s is not None
    clk.advance(0.002)
    s.event("dispatched")
    clk.advance(0.003)
    s.event("journaled")
    clk.advance(0.004)
    s.event("staged")
    clk.advance(0.005)
    s.event("completed")
    s.finish()
    st = s.stages()
    assert st["admission"] == pytest.approx(0.001)
    assert st["queue"] == pytest.approx(0.002)
    assert st["journal"] == pytest.approx(0.003)
    assert st["stage"] == pytest.approx(0.004)
    assert st["device"] == pytest.approx(0.005)
    assert st["total"] == pytest.approx(0.015)
    assert s.duration_s == pytest.approx(0.015)
    assert s.t1 == pytest.approx(0.015)
    d = s.to_dict()
    assert d["kind"] == "hll_add" and d["stages"]["journal"] == st["journal"]


def test_missing_marks_collapse_into_next_stage():
    # No journal configured: the dispatched->completed gap is all "device"
    # via the staged mark's absence collapsing into the next present one.
    clk = FakeClock()
    tr = Tracer(clock=clk, sample_every=1)
    s = tr.maybe_begin("get", "t")
    clk.advance(0.001)
    s.event("dispatched")
    clk.advance(0.010)
    s.event("completed")
    s.finish()
    st = s.stages()
    assert "journal" not in st and "stage" not in st
    assert st["device"] == pytest.approx(0.010)


def test_sampling_stride_is_deterministic():
    def run():
        tr = Tracer(clock=FakeClock(), sample_every=4, seed=2)
        hits = [i for i in range(16)
                if tr.maybe_begin("k", "t") is not None]
        return hits, tr.sampled, tr.skipped

    hits, sampled, skipped = run()
    assert hits == [2, 6, 10, 14]
    assert sampled == 4 and skipped == 12
    assert run() == (hits, sampled, skipped)  # reproducible under the seed


def test_ring_is_bounded_and_finish_idempotent():
    tr = Tracer(clock=FakeClock(), sample_every=1, ring=8)
    spans = []
    for _ in range(20):
        s = tr.maybe_begin("k", "t")
        s.finish()
        s.finish()  # double finish must not double-count
        spans.append(s)
    assert len(tr.ring()) == 8
    assert tr.finished == 20
    assert tr.ring()[-1] is spans[-1]


def test_pending_annotations_never_leak_across_ops():
    tr = Tracer(clock=FakeClock(), sample_every=2, seed=0)
    assert tr.maybe_begin("k", "t") is not None  # i=0 sampled
    tr.annotate_next(admitted_at=0.5, attempt=3)
    assert tr.maybe_begin("k", "t") is None  # i=1 unsampled, consumes pending
    s2 = tr.maybe_begin("k", "t")  # i=2 sampled
    assert "attempt" not in s2.annotations
    assert s2.first("admitted") is None


def test_admitted_at_extends_span_start():
    clk = FakeClock(t=10.0)
    tr = Tracer(clock=clk, sample_every=1)
    tr.annotate_next(admitted_at=9.5, attempt=1)
    s = tr.maybe_begin("k", "t")
    assert s.t0 == pytest.approx(9.5)
    assert s.first("admitted") == pytest.approx(9.5)
    assert s.annotations["attempt"] == 1


def test_sink_errors_never_propagate():
    tr = Tracer(clock=FakeClock(), sample_every=1)
    tr.add_sink(lambda span: 1 / 0)
    s = tr.maybe_begin("k", "t")
    s.finish()  # must not raise
    assert tr.finished == 1


# ---------------------------------------------------------------------------
# 1b. histograms
# ---------------------------------------------------------------------------

def test_bucket_index_roundtrip_and_monotone():
    prev = 0
    for ticks in list(range(0, 5000)) + [10 ** 5, 10 ** 6, 10 ** 8]:
        idx = bucket_index(ticks)
        assert idx >= prev
        assert bucket_upper_ticks(idx) >= ticks
        assert bucket_index(bucket_upper_ticks(idx)) == idx
        prev = idx


def test_quantile_error_bound():
    h = LatencyHistogram()
    vals = [i * 0.0001 for i in range(1, 1001)]  # 0.1ms .. 100ms
    for v in vals:
        h.record(v)
    for q in (0.50, 0.95, 0.99, 0.999):
        exact = vals[max(0, int(q * len(vals) + 0.999999) - 1)]
        est = h.quantile(q)
        assert est >= exact - 1e-6  # upper-bound estimator
        assert est <= exact * 1.04 + 1e-6  # 2^-5 sub-bucket error (~3.1%)


def test_histogram_merge_equals_combined():
    a, b, c = LatencyHistogram(), LatencyHistogram(), LatencyHistogram()
    va = [0.001 * i for i in range(1, 100)]
    vb = [0.01 * i for i in range(1, 50)]
    for v in va:
        a.record(v)
        c.record(v)
    for v in vb:
        b.record(v)
        c.record(v)
    a.merge(b)
    assert a.count == c.count
    assert a.sum_s == pytest.approx(c.sum_s)
    assert a.min_s == c.min_s and a.max_s == c.max_s
    for q in (0.5, 0.9, 0.99):
        assert a.quantile(q) == c.quantile(q)


def test_histogram_set_keying_and_merged_views():
    hs = HistogramSet()
    hs.record("get", "tenant_a", 0.001)
    hs.record("get", "tenant_b", 0.002)
    hs.record("put", "tenant_a", 0.003)
    assert hs.get("get", "tenant_a").count == 1
    assert hs.kinds() == ["get", "put"]
    assert hs.merged("get").count == 2  # across tenants
    assert hs.merged().count == 3  # global
    snap = hs.snapshot()
    assert snap["get|tenant_a"]["count"] == 1


# ---------------------------------------------------------------------------
# 1c. slowlog
# ---------------------------------------------------------------------------

def _finished_span(journal_s=0.0, device_s=0.001, kind="hll_add"):
    clk = FakeClock()
    tr = Tracer(clock=clk, sample_every=1)
    s = tr.maybe_begin(kind, "t")
    clk.advance(0.0005)
    s.event("dispatched")
    clk.advance(journal_s)
    s.event("journaled")
    clk.advance(device_s)
    s.event("completed")
    s.finish()
    return s


def test_slowlog_threshold_and_stage_attribution():
    slog = SlowLog(threshold_s=0.010, maxlen=4)
    assert slog.offer(_finished_span(0.0, 0.001)) is None  # fast: ignored
    e = slog.offer(_finished_span(journal_s=0.050, device_s=0.002))
    assert e is not None
    assert e.worst_stage == "journal"
    assert e.stages["journal"] >= 0.5 * e.duration_s
    assert e.to_dict()["worst_stage"] == "journal"


def test_slowlog_newest_first_bounded_reset():
    slog = SlowLog(threshold_s=0.001, maxlen=3)
    for _ in range(5):
        assert slog.offer(_finished_span(device_s=0.01)) is not None
    assert len(slog) == 3
    assert slog.total_logged == 5
    ids = [e.entry_id for e in slog.get()]
    assert ids == sorted(ids, reverse=True)  # newest first
    assert slog.get(2) == slog.get()[:2]
    slog.reset()
    assert len(slog) == 0
    assert slog.total_logged == 5  # lifetime counter survives reset


# ---------------------------------------------------------------------------
# 1d. monitor
# ---------------------------------------------------------------------------

def test_monitor_drop_and_count_never_blocks():
    m = Monitor(default_maxlen=4)
    m.publish({"i": -1})  # no subscribers: free no-op
    assert m.published == 0
    tap = m.subscribe()
    for i in range(6):
        m.publish({"i": i})
    assert len(tap) == 4
    assert tap.dropped == 2
    assert m.dropped() == 2
    assert [e["i"] for e in tap.poll()] == [0, 1, 2, 3]
    m.unsubscribe(tap)
    assert m.active() == 0
    assert m.dropped() == 2  # folded into the monitor's lifetime total
    assert not tap.offer({"i": 9})  # closed tap refuses events


def test_monitor_format_event():
    line = format_event({"ts": 1.5, "tenant": "", "kind": "hll_add",
                         "target": "t:h", "nkeys": 3, "event": "enqueue"})
    assert line == '1.500000 [-] "HLL_ADD" "t:h" 3 (enqueue)'


# ---------------------------------------------------------------------------
# 1e. LATENCY parity
# ---------------------------------------------------------------------------

def test_latency_events_threshold_history_reset_doctor():
    clk = FakeClock()
    lat = LatencyEvents(threshold_s=0.100, history_len=3, clock=clk)
    assert not lat.observe("device", 0.050)  # below threshold
    for d in (0.2, 0.3, 0.4, 0.5):
        clk.advance(1.0)
        assert lat.observe("journal_fsync", d)
    hist = lat.history("journal_fsync")
    assert len(hist) == 3  # bounded ring
    assert [d for _, d in hist] == [0.3, 0.4, 0.5]
    assert lat.latest()["journal_fsync"][2] == pytest.approx(0.5)
    report = lat.doctor()
    assert "journal_fsync" in report and "Worst offender" in report
    assert lat.reset("journal_fsync") == 1
    assert "no latency spikes" in lat.doctor()


# ---------------------------------------------------------------------------
# 2. exports
# ---------------------------------------------------------------------------

def test_chrome_trace_schema_and_window():
    clk = FakeClock()
    tr = Tracer(clock=clk, sample_every=1)
    s = tr.maybe_begin("hll_add", "t:h", nkeys=2)
    clk.advance(0.001)
    s.event("stolen")
    s.event("dispatched")
    clk.advance(0.002)
    s.event("completed")
    s.finish()
    doc = chrome_trace([s])
    json.dumps(doc)  # must be JSON-serializable as-is
    assert doc["displayTimeUnit"] == "ms"
    evs = doc["traceEvents"]
    assert all({"name", "ph", "ts", "pid", "tid"} <= set(e) for e in evs)
    assert [e["ts"] for e in evs] == sorted(e["ts"] for e in evs)
    ops = [e for e in evs if e["ph"] == "X" and e["cat"] == "op"]
    assert len(ops) == 1 and ops[0]["name"] == "hll_add"
    assert ops[0]["dur"] == pytest.approx(3000.0)  # 3ms in us
    marks = [e for e in evs if e["ph"] == "i"]
    assert [m["name"] for m in marks] == ["stolen"]
    stages = [e for e in evs if e["cat"] == "stage"]
    assert {e["name"] for e in stages} == {"hll_add:queue", "hll_add:device"}
    # window clipping: a window entirely before the span excludes it
    assert chrome_trace([s], t0=10.0)["traceEvents"] == []
    # unfinished spans are skipped
    open_span = tr.maybe_begin("get", "t")
    assert open_span is not None
    assert len(chrome_trace([open_span])["traceEvents"]) == 0


def test_prometheus_exposition_shape():
    hs = HistogramSet()
    hs.record("get", "", 0.0005)
    hs.record("get", "", 0.05)
    text = prometheus_exposition(hs, bounds_s=(0.001, 0.1))
    assert "# TYPE trace_op_latency_seconds histogram" in text
    assert ('trace_op_latency_seconds_bucket{kind="get",tenant="",'
            'le="0.001"} 1') in text
    assert ('trace_op_latency_seconds_bucket{kind="get",tenant="",'
            'le="0.1"} 2') in text
    assert ('trace_op_latency_seconds_bucket{kind="get",tenant="",'
            'le="+Inf"} 2') in text
    assert 'trace_op_latency_seconds_count{kind="get",tenant=""} 2' in text
    assert 'le="inf"' not in text


# ---------------------------------------------------------------------------
# 2b. manager fan-out (fake clock)
# ---------------------------------------------------------------------------

def test_manager_fanout_hist_slowlog_monitor():
    from types import SimpleNamespace

    clk = FakeClock()
    cfg = SimpleNamespace(sample_every=1, slowlog_threshold_ms=1.0)
    mgr = TraceManager(cfg, clock=clk)
    tap = mgr.monitor.subscribe()
    s = mgr.begin_op("hll_add", "t:h", nkeys=2)
    assert s is not None
    clk.advance(0.002)
    s.event("completed")
    s.finish()
    assert mgr.hist.get("hll_add", "").count == 1
    assert len(mgr.slowlog) == 1  # 2ms > 1ms threshold
    assert [e["event"] for e in tap.poll()] == ["enqueue", "complete"]
    cs = mgr.commandstats()
    assert cs["cmdstat_hll_add"]["calls"] == 1
    assert cs["cmdstat_hll_add"]["usec"] == pytest.approx(2000.0, rel=0.05)
    snap = mgr.snapshot()
    assert snap["tracer"]["sampled"] == 1
    assert snap["slowlog"]["len"] == 1


def test_manager_retry_and_fsync_hooks():
    from types import SimpleNamespace

    clk = FakeClock()
    mgr = TraceManager(SimpleNamespace(sample_every=1), clock=clk)
    tap = mgr.monitor.subscribe()
    mgr.retry_event("hll_add", "t", "", attempt=1, delay_s=0.005)
    assert mgr.retries == 1
    assert [e["event"] for e in tap.poll()] == ["retry"]
    mgr.record_fsync(0.150)  # above the 100ms LATENCY threshold
    assert mgr.fsync_hist.get("journal_fsync", "").count == 1
    assert len(mgr.latency.history("journal_fsync")) == 1
    assert "journal_fsync" in mgr.render_prometheus()


# ---------------------------------------------------------------------------
# 3. integration: real client
# ---------------------------------------------------------------------------

def test_client_trace_end_to_end():
    cfg = Config()
    tc = cfg.use_trace()
    tc.sample_every = 1
    c = RedissonTPU.create(cfg)
    try:
        h = c.get_hyper_log_log("tr:e2e")
        h.add_all([b"k%d" % i for i in range(64)])
        assert h.count() > 0
        assert h.count() > 0  # second count rides the read cache
        snap = c.trace.snapshot()
        assert snap["tracer"]["sampled"] >= 3
        ops = [s for s in c.trace.tracer.ring() if s.span_type == "op"]
        assert ops and all(s.t1 is not None for s in ops)
        names = {n for s in ops for n, _ in s.events}
        assert {"queued", "dispatched", "completed"} <= names
        assert any(s.annotations.get("read_cache") == "hit" for s in ops)
        doc = c.trace.chrome_trace()
        json.dumps(doc)
        assert doc["traceEvents"]
        assert 'le="+Inf"' in c.trace.render_prometheus()
        assert "cmdstat_hll_add" in c.trace.commandstats()
        gauges = c.metrics.snapshot()["gauges"]
        assert gauges["trace.sampled"] >= 3
        assert gauges["trace.spans_finished"] >= 3
    finally:
        c.shutdown()


def test_client_trace_export_chrome(tmp_path):
    cfg = Config()
    cfg.use_trace().sample_every = 1
    c = RedissonTPU.create(cfg)
    try:
        c.get_hyper_log_log("tr:x").add_all([b"a", b"b"])
        path = str(tmp_path / "trace.json")
        n = c.trace.export_chrome(path)
        assert n > 0
        with open(path) as f:
            doc = json.load(f)
        assert len(doc["traceEvents"]) == n
    finally:
        c.shutdown()


def test_journal_stall_attributed_to_journal_stage(tmp_path):
    cfg = Config()
    cfg.use_local()
    pc = cfg.use_persist(str(tmp_path))
    pc.fsync = "always"
    pc.group_commit_runs = 1
    tc = cfg.use_trace()
    tc.sample_every = 1
    tc.slowlog_threshold_ms = 5.0
    fc = cfg.use_faults()
    # Stall (not fail) the SECOND fsync: the first add warms the kernel
    # cache so compile time can't drown out the journal stage.
    fc.plan = [{"seam": "journal_fsync", "fault": "stall", "nth": 2,
                "times": 2, "delay_s": 0.08}]
    c = RedissonTPU.create(cfg)
    try:
        h = c.get_hyper_log_log("tr:stall")
        h.add_all([b"warm%d" % i for i in range(8)])  # fsync #1, no stall
        c.trace.slowlog.reset()
        h.add_all([b"hot%d" % i for i in range(8)])  # fsync #2: stalled
        h.count()
        entries = c.trace.slowlog.get()
        assert entries, "stalled op never crossed the slowlog threshold"
        worst = max(entries, key=lambda e: e.duration_s)
        assert worst.worst_stage == "journal"
        assert worst.stages["journal"] >= 0.5 * worst.duration_s
        assert worst.duration_s >= 0.08
        # the fsync histogram saw the stall too (unsampled-path hook)
        fh = c.trace.fsync_hist.get("journal_fsync", "")
        assert fh is not None and fh.max_s >= 0.08
    finally:
        c.shutdown()


def test_trace_disabled_costs_nothing():
    c = RedissonTPU.create()
    try:
        assert c.trace is None
        assert getattr(c._executor, "trace", "missing") is None
        c.get_hyper_log_log("tr:off").add_all([b"a"])
        assert "trace.sampled" not in c.metrics.snapshot()["gauges"]
    finally:
        c.shutdown()
