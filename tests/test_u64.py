import random

import numpy as np
import pytest

from redisson_tpu.ops import u64 as u

MASK64 = (1 << 64) - 1


def _rand64(n, seed=0):
    rng = random.Random(seed)
    return [rng.getrandbits(64) for _ in range(n)]


def _pack(vals):
    hi = np.array([v >> 32 for v in vals], np.uint32)
    lo = np.array([v & 0xFFFFFFFF for v in vals], np.uint32)
    return u.U64(hi, lo)


def _unpack(x):
    return [int(v) for v in np.atleast_1d(u.to_python(x))]


@pytest.mark.parametrize("op,pyop", [
    (u.add, lambda a, b: (a + b) & MASK64),
    (u.mul, lambda a, b: (a * b) & MASK64),
    (u.xor, lambda a, b: a ^ b),
    (u.and_, lambda a, b: a & b),
    (u.or_, lambda a, b: a | b),
])
def test_binary_ops(op, pyop):
    a_vals = _rand64(64, 1)
    b_vals = _rand64(64, 2)
    got = _unpack(op(_pack(a_vals), _pack(b_vals)))
    want = [pyop(a, b) for a, b in zip(a_vals, b_vals)]
    assert got == want


@pytest.mark.parametrize("n", [0, 1, 7, 31, 32, 33, 50, 63])
def test_shifts_and_rot(n):
    vals = _rand64(32, n + 10)
    x = _pack(vals)
    assert _unpack(u.shl(x, n)) == [(v << n) & MASK64 for v in vals]
    assert _unpack(u.shr(x, n)) == [v >> n for v in vals]
    assert _unpack(u.rotl(x, n)) == [((v << n) | (v >> (64 - n))) & MASK64 if n else v for v in vals]


def test_ctz_clz_popcount():
    vals = [0, 1, 2, 0x8000000000000000, 0x100000000, 0xF0F0, (1 << 64) - 1] + _rand64(20, 5)
    x = _pack(vals)

    def pyctz(v):
        if v == 0:
            return 64
        c = 0
        while not (v >> c) & 1:
            c += 1
        return c

    def pyclz(v):
        if v == 0:
            return 64
        return 64 - v.bit_length()

    assert list(np.asarray(u.ctz(x))) == [pyctz(v) for v in vals]
    assert list(np.asarray(u.clz(x))) == [pyclz(v) for v in vals]
    assert list(np.asarray(u.popcount(x))) == [bin(v).count("1") for v in vals]


def test_mul32():
    rng = random.Random(9)
    a = [rng.getrandbits(32) for _ in range(64)]
    b = [rng.getrandbits(32) for _ in range(64)]
    got = _unpack(u.mul32(np.array(a, np.uint32), np.array(b, np.uint32)))
    assert got == [x * y for x, y in zip(a, b)]


# Crafted boundary words: all-zero lanes, single-lane-only values, the
# 2^32 lane seam, the sign-bit position, and all-ones.
EDGE_VALS = [
    0,
    1,
    0xFFFFFFFF,          # lo lane saturated, hi zero
    0x100000000,         # exactly 2^32: hi=1, lo=0
    0x100000001,
    0x7FFFFFFFFFFFFFFF,
    0x8000000000000000,  # bit 63 only (hi nonzero, lo zero)
    0xFFFFFFFF00000000,  # hi saturated, lo zero
    (1 << 64) - 1,
]


@pytest.mark.parametrize("n", [0, 31, 32, 63])
def test_shift_edges_on_boundary_words(n):
    """Shift-count boundaries (0 / lane-1 / lane seam / 63) against python
    ints on words chosen to stress the hi/lo spill paths."""
    x = _pack(EDGE_VALS)
    assert _unpack(u.shl(x, n)) == [(v << n) & MASK64 for v in EDGE_VALS]
    assert _unpack(u.shr(x, n)) == [v >> n for v in EDGE_VALS]
    rot = [((v << n) | (v >> (64 - n))) & MASK64 if n else v
           for v in EDGE_VALS]
    assert _unpack(u.rotl(x, n)) == rot


def test_mul_wraparound_at_2_64():
    """Products straddling 2^64 must wrap exactly (mod-2^64 semantics)."""
    cases = [
        (0xFFFFFFFFFFFFFFFF, 0xFFFFFFFFFFFFFFFF),  # (2^64-1)^2
        (0xFFFFFFFFFFFFFFFF, 2),                   # 2^65 - 2
        (0x8000000000000000, 2),                   # exactly 2^64 -> 0
        (0x100000000, 0x100000000),                # 2^64 -> 0 via lane cross
        (0xFFFFFFFF, 0xFFFFFFFF),                  # stays under 2^64
        (0xDEADBEEFCAFEBABE, 0x123456789ABCDEF1),
    ]
    a = _pack([c[0] for c in cases])
    b = _pack([c[1] for c in cases])
    assert _unpack(u.mul(a, b)) == [(x * y) & MASK64 for x, y in cases]


def test_add_carry_across_lane_seam():
    cases = [
        (0xFFFFFFFF, 1),                            # carry out of lo
        (0xFFFFFFFFFFFFFFFF, 1),                    # wrap to 0
        (0xFFFFFFFF00000000, 0x100000000),          # hi-lane wrap
        (0x7FFFFFFFFFFFFFFF, 0x7FFFFFFFFFFFFFFF),
    ]
    a = _pack([c[0] for c in cases])
    b = _pack([c[1] for c in cases])
    assert _unpack(u.add(a, b)) == [(x + y) & MASK64 for x, y in cases]


def test_ctz_clz_zero_lanes():
    """Per-lane zero patterns: ctz/clz must handle hi=0, lo=0, and both
    zero (-> 64) without the per-lane 32-count leaking through wrong."""
    vals = [
        0,                   # both lanes zero -> 64
        1,                   # lo nonzero
        0x80000000,          # lo's top bit
        0x100000000,         # lo zero, hi nonzero -> ctz 32
        0x8000000000000000,  # hi's top bit -> ctz 63, clz 0
        0xFFFFFFFF,          # hi zero -> clz 32
    ]
    x = _pack(vals)
    want_ctz = [64 if v == 0 else (v & -v).bit_length() - 1 for v in vals]
    want_clz = [64 - v.bit_length() for v in vals]
    assert list(np.asarray(u.ctz(x))) == want_ctz
    assert list(np.asarray(u.clz(x))) == want_clz


def test_compare_across_lanes():
    """lt/eq must order by hi lane first — lo-lane magnitude is a decoy."""
    a_vals = [0x100000000, 0x1FFFFFFFF, 0xFFFFFFFF, 5]
    b_vals = [0xFFFFFFFF, 0x200000000, 0x100000000, 5]
    a, b = _pack(a_vals), _pack(b_vals)
    assert list(np.asarray(u.lt(a, b))) == [x < y for x, y in zip(a_vals, b_vals)]
    assert list(np.asarray(u.eq(a, b))) == [x == y for x, y in zip(a_vals, b_vals)]


def test_const_and_compare():
    assert _unpack(u.const(0xDEADBEEFCAFEBABE)) == [0xDEADBEEFCAFEBABE]
    a = _pack([5, 10, 10])
    b = _pack([10, 10, 5])
    assert list(np.asarray(u.lt(a, b))) == [True, False, False]
    assert list(np.asarray(u.eq(a, b))) == [False, True, False]
