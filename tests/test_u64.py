import random

import numpy as np
import pytest

from redisson_tpu.ops import u64 as u

MASK64 = (1 << 64) - 1


def _rand64(n, seed=0):
    rng = random.Random(seed)
    return [rng.getrandbits(64) for _ in range(n)]


def _pack(vals):
    hi = np.array([v >> 32 for v in vals], np.uint32)
    lo = np.array([v & 0xFFFFFFFF for v in vals], np.uint32)
    return u.U64(hi, lo)


def _unpack(x):
    return [int(v) for v in np.atleast_1d(u.to_python(x))]


@pytest.mark.parametrize("op,pyop", [
    (u.add, lambda a, b: (a + b) & MASK64),
    (u.mul, lambda a, b: (a * b) & MASK64),
    (u.xor, lambda a, b: a ^ b),
    (u.and_, lambda a, b: a & b),
    (u.or_, lambda a, b: a | b),
])
def test_binary_ops(op, pyop):
    a_vals = _rand64(64, 1)
    b_vals = _rand64(64, 2)
    got = _unpack(op(_pack(a_vals), _pack(b_vals)))
    want = [pyop(a, b) for a, b in zip(a_vals, b_vals)]
    assert got == want


@pytest.mark.parametrize("n", [0, 1, 7, 31, 32, 33, 50, 63])
def test_shifts_and_rot(n):
    vals = _rand64(32, n + 10)
    x = _pack(vals)
    assert _unpack(u.shl(x, n)) == [(v << n) & MASK64 for v in vals]
    assert _unpack(u.shr(x, n)) == [v >> n for v in vals]
    assert _unpack(u.rotl(x, n)) == [((v << n) | (v >> (64 - n))) & MASK64 if n else v for v in vals]


def test_ctz_clz_popcount():
    vals = [0, 1, 2, 0x8000000000000000, 0x100000000, 0xF0F0, (1 << 64) - 1] + _rand64(20, 5)
    x = _pack(vals)

    def pyctz(v):
        if v == 0:
            return 64
        c = 0
        while not (v >> c) & 1:
            c += 1
        return c

    def pyclz(v):
        if v == 0:
            return 64
        return 64 - v.bit_length()

    assert list(np.asarray(u.ctz(x))) == [pyctz(v) for v in vals]
    assert list(np.asarray(u.clz(x))) == [pyclz(v) for v in vals]
    assert list(np.asarray(u.popcount(x))) == [bin(v).count("1") for v in vals]


def test_mul32():
    rng = random.Random(9)
    a = [rng.getrandbits(32) for _ in range(64)]
    b = [rng.getrandbits(32) for _ in range(64)]
    got = _unpack(u.mul32(np.array(a, np.uint32), np.array(b, np.uint32)))
    assert got == [x * y for x, y in zip(a, b)]


def test_const_and_compare():
    assert _unpack(u.const(0xDEADBEEFCAFEBABE)) == [0xDEADBEEFCAFEBABE]
    a = _pack([5, 10, 10])
    b = _pack([10, 10, 5])
    assert list(np.asarray(u.lt(a, b))) == [True, False, False]
    assert list(np.asarray(u.eq(a, b))) == [False, True, False]
