"""memstat subsystem tests: the byte ledger, pressure gate, and MEMORY
command-family parity.

Layers:

1. Unit — MemLedger lifecycle events on synthetic entries (create/
   resize/delete/rename-clobber/flushall), peak monotonicity, meter
   isolation, and verify() drift detection against a fake store.
2. Seam — a real SketchStore with the ledger attached: every store
   mutation keeps the invariant (ledger == sum of live Array.nbytes);
   plus the keys(pattern) / rename-overwrites-dest store semantics the
   ledger's clobber debit depends on.
3. Pressure — EWMA forecasting on a fake clock, watermark shedding with
   hysteresis, reclaim/read kinds always admitted.
4. Integration — a real client: MEMORY USAGE/STATS/DOCTOR parity,
   INFO folding, zero-drift verify after randomized churn on both HLL
   engine tiers, end-to-end write shedding under a tiny watermark while
   reads keep flowing, trace counter export, and registry gauges.
"""

import random

import jax.numpy as jnp
import pytest

from redisson_tpu.client import RedissonTPU
from redisson_tpu.config import Config, MemConfig
from redisson_tpu.memstat import MemLedger, MemoryReport, PressureMonitor
from redisson_tpu.memstat.accounting import BANK_ENTRY
from redisson_tpu.observability import MetricsRegistry
from redisson_tpu.serve.errors import RejectedError
from redisson_tpu.store import SketchStore


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class FakeStore:
    """Just enough store for verify(): a name -> nbytes mapping."""

    def __init__(self, sizes):
        self.sizes = dict(sizes)

    def live_nbytes(self):
        return dict(self.sizes)


# ---------------------------------------------------------------------------
# 1. ledger unit tests
# ---------------------------------------------------------------------------

def test_ledger_lifecycle_and_totals():
    led = MemLedger()
    led.on_create("a", "bitset", 1024, slot=3, tenant="t1")
    led.on_create("b", "hll", 4096, slot=7)
    assert led.live_bytes() == 5120
    assert led.keys_count() == 2
    assert led.kind_bytes() == {"bitset": 1024, "hll": 4096}
    led.on_resize("a", 2048)
    assert led.live_bytes() == 6144
    led.on_delete("b")
    assert led.live_bytes() == 2048
    assert led.kind_bytes() == {"bitset": 2048}
    e = led.entry("a")
    assert e == {"kind": "bitset", "tenant": "t1", "slot": 3,
                 "nbytes": 2048}
    # events are counted; unknown-name resize/delete are no-ops
    n = led.events()
    led.on_resize("ghost", 512)
    led.on_delete("ghost")
    assert led.events() == n and led.live_bytes() == 2048


def test_ledger_recreate_is_idempotent():
    led = MemLedger()
    led.on_create("a", "bitset", 1024)
    led.on_create("a", "bloom", 4096)  # re-create: debit old, credit new
    assert led.live_bytes() == 4096
    assert led.kind_bytes() == {"bloom": 4096}
    assert led.keys_count() == 1


def test_ledger_rename_clobbers_destination():
    led = MemLedger()
    led.on_create("src", "bitset", 1000, slot=1)
    led.on_create("dst", "bitset", 2000, slot=2)
    led.on_rename("src", "dst", slot=2)
    # dest bytes debited (Redis RENAME overwrites), source entry moved
    assert led.live_bytes() == 1000
    assert led.keys_count() == 1
    assert led.entry("dst")["nbytes"] == 1000
    assert led.entry("dst")["slot"] == 2
    assert led.entry("src") is None


def test_ledger_flushall_and_peak_monotone():
    led = MemLedger()
    peaks = []
    led.on_create("a", "bitset", 10_000)
    peaks.append(led.peak_bytes())
    led.on_create("b", "hll", 50_000)
    peaks.append(led.peak_bytes())
    led.on_delete("b")
    peaks.append(led.peak_bytes())
    led.on_flushall()
    peaks.append(led.peak_bytes())
    assert led.live_bytes() == 0 and led.keys_count() == 0
    assert led.kind_bytes() == {}
    assert peaks == sorted(peaks)  # never decreases
    assert led.peak_bytes() == 60_000


def test_ledger_bank_entry_tracking():
    led = MemLedger()
    led.set_bank_bytes(1 << 20)
    assert led.bank_bytes() == 1 << 20
    assert led.live_bytes() == 1 << 20
    assert led.kind_bytes() == {"hll": 1 << 20}
    led.set_bank_bytes(1 << 21)  # grow
    assert led.live_bytes() == 1 << 21
    led.set_bank_bytes(0)  # dropped at flushall
    assert led.bank_bytes() == 0 and led.live_bytes() == 0
    assert led.keys_count() == 0


def test_ledger_attribution_rollups():
    led = MemLedger()
    led.on_create("a", "bitset", 100, slot=1, tenant="t1")
    led.on_create("b", "bitset", 200, slot=1, tenant="t2")
    led.on_create("c", "hll", 400, slot=2)  # empty tenant -> "-"
    attr = led.attribution()
    assert attr["by_kind"] == {"bitset": 300, "hll": 400}
    assert attr["by_tenant"] == {"t1": 100, "t2": 200, "-": 400}
    assert attr["by_slot"] == {"1": 300, "2": 400}


def test_ledger_meters_isolate_failures():
    led = MemLedger()
    led.register_meter("good", lambda: 4096, "cache")
    led.register_meter("boom", lambda: 1 // 0, "scratch")
    led.register_meter("disk", lambda: 1 << 30, "disk")
    with pytest.raises(ValueError):
        led.register_meter("bad", lambda: 0, "no-such-category")
    m = led.meters()
    assert m["good"] == {"bytes": 4096, "category": "cache"}
    assert m["boom"]["bytes"] == 0  # broken meter reads 0, never raises
    assert led.meter_errors >= 1
    totals = led.meter_totals()
    assert totals == {"cache": 4096, "scratch": 0, "staging": 0,
                      "disk": 1 << 30}
    # disk never counts toward device-adjacent overhead
    assert led.overhead_bytes() == 4096
    led.unregister_meter("disk")
    assert led.meter_totals()["disk"] == 0


def test_ledger_verify_detects_drift():
    led = MemLedger()
    led.on_create("a", "bitset", 100)
    led.on_create("stale", "bitset", 50)
    store = FakeStore({"a": 100, "missing": 70})
    v = led.verify(store)
    assert not v["ok"]
    assert v["missing"] == ["missing"]
    assert v["stale"] == ["stale"]
    assert v["drift_bytes"] == 170 - 150
    # mismatched byte count on a shared name
    led2 = MemLedger()
    led2.on_create("a", "bitset", 100)
    v2 = led2.verify(FakeStore({"a": 120}))
    assert v2["mismatched"] == {"a": {"ledger": 100, "actual": 120}}
    # and the healthy case
    v3 = led2.verify(FakeStore({"a": 100}))
    assert v3["ok"] and v3["drift_bytes"] == 0


# ---------------------------------------------------------------------------
# 2. store seam
# ---------------------------------------------------------------------------

def _mk(nbytes: int):
    return jnp.zeros(nbytes // 4, dtype=jnp.uint32)


def test_store_seam_keeps_invariant():
    store = SketchStore()
    led = MemLedger()
    store.accounting = led
    store.get_or_create("s:a", "bitset", lambda: _mk(1024))
    store.get_or_create("s:b", "bitset", lambda: _mk(2048))
    assert led.live_bytes() == 3072
    # get_or_create on an existing name does NOT double-count
    store.get_or_create("s:a", "bitset", lambda: _mk(1024))
    assert led.live_bytes() == 3072
    # swap resizes
    obj = store.get("s:a")
    assert store.swap("s:a", _mk(4096), expected_version=obj.version)
    assert led.entry("s:a")["nbytes"] == 4096
    # delete debits
    assert store.delete("s:b")
    assert led.live_bytes() == 4096
    v = led.verify(store)
    assert v["ok"], v
    store.flushall()
    assert led.live_bytes() == 0
    assert led.verify(store)["ok"]


def test_store_rename_overwrites_dest_and_ledger_debits():
    """Redis RENAME semantics pinned at the store level: an existing
    destination is silently replaced, and the ledger debits its bytes."""
    store = SketchStore()
    led = MemLedger()
    store.accounting = led
    store.get_or_create("r:src", "bitset", lambda: _mk(1024))
    store.get_or_create("r:dst", "bitset", lambda: _mk(8192))
    assert store.rename("r:src", "r:dst") is True
    assert not store.exists("r:src")
    dst = store.get("r:dst")
    assert int(dst.state.nbytes) == 1024  # source value won
    assert led.live_bytes() == 1024
    assert led.verify(store)["ok"]
    # renaming a missing key is a no-op for both
    assert store.rename("r:ghost", "r:dst") is False
    assert led.live_bytes() == 1024


def test_store_keys_pattern_glob():
    store = SketchStore()
    for name in ("user:1", "user:2", "sess:1", "user:10"):
        store.get_or_create(name, "bitset", lambda: _mk(64))
    assert sorted(store.keys("user:*")) == ["user:1", "user:10", "user:2"]
    assert sorted(store.keys("user:?")) == ["user:1", "user:2"]
    assert store.keys("nope*") == []
    assert len(store.keys()) == 4


# ---------------------------------------------------------------------------
# 3. pressure
# ---------------------------------------------------------------------------

def _pressure(led, clk, high=0, low=0, **kw):
    cfg = MemConfig(high_watermark_bytes=high, low_watermark_bytes=low,
                    **kw)
    return PressureMonitor(led, cfg, clock=clk)


def test_pressure_no_watermark_never_sheds():
    led = MemLedger()
    led.on_create("a", "bitset", 1 << 30)
    p = _pressure(led, FakeClock())
    assert p.should_shed("bitset_set") is False
    p.check_write("bitset_set")  # no raise


def test_pressure_sheds_writes_not_reads_or_reclaims():
    led = MemLedger()
    led.on_create("a", "bitset", 2000)
    p = _pressure(led, FakeClock(), high=1000)
    with pytest.raises(RejectedError) as ei:
        p.check_write("bitset_set")
    assert ei.value.reason == "memory"
    assert ei.value.retry_after_s > 0
    assert p.shed_total == 1
    # reads and reclaiming writes always flow
    for kind in ("bitset_get", "hll_count", "exists",
                 "delete", "flushall", "rename"):
        p.check_write(kind)
    assert p.shed_total == 1


def test_pressure_hysteresis_band():
    led = MemLedger()
    clk = FakeClock()
    p = _pressure(led, clk, high=1000, low=500)
    led.on_create("a", "bitset", 1200)
    assert p.should_shed("bitset_set") is True
    # dipping below high but above low: still shedding (no flapping)
    led.on_resize("a", 800)
    assert p.should_shed("bitset_set") is True
    # below the low watermark: recovered
    led.on_resize("a", 400)
    assert p.should_shed("bitset_set") is False
    # and it re-arms at high again
    led.on_resize("a", 1500)
    assert p.should_shed("bitset_set") is True


def test_pressure_forecast_eta():
    led = MemLedger()
    clk = FakeClock()
    p = _pressure(led, clk, high=100_000, ewma_halflife_s=0.5)
    led.on_create("a", "bitset", 0)
    p.sample()
    # steady growth: 1000 bytes/second for 10 seconds
    for i in range(1, 11):
        clk.advance(1.0)
        led.on_resize("a", i * 1000)
        p.sample()
    fc = p.forecast()
    rate = fc["rate_bytes_s"]["total"]
    assert 500 < rate <= 1100  # EWMA converges toward 1000 B/s
    eta = fc["seconds_to_watermark"]
    assert eta is not None
    # ~90k headroom at ~1k/s
    assert 50 < eta < 200
    # flat usage: rate decays toward zero, eta eventually None or huge
    for _ in range(40):
        clk.advance(1.0)
        p.sample()
    fc2 = p.forecast()
    assert fc2["rate_bytes_s"]["total"] < rate / 4


# ---------------------------------------------------------------------------
# 4. report on a bare ledger
# ---------------------------------------------------------------------------

def test_report_stats_and_info_on_bare_ledger():
    led = MemLedger()
    led.on_create("a", "bitset", 1000, slot=1, tenant="t1")
    led.on_create("b", "hll", 3000, slot=2)
    led.register_meter("rc", lambda: 500, "cache")
    rep = MemoryReport(led)
    st = rep.memory_stats()
    assert st["dataset.bytes"] == 4000
    assert st["total.allocated"] == 4500
    assert st["peak.allocated"] >= st["dataset.bytes"]
    assert st["keys.count"] == 2
    assert st["keys.bytes-per-key"] == 2000
    assert st["bitset.bytes"] == 1000 and st["hll.bytes"] == 3000
    assert st["by_tenant"]["t1"] == 1000
    assert st["fragmentation"] == pytest.approx(4500 / 4000, rel=1e-3)
    info = rep.info_memory()
    assert info["used_memory"] == 4500
    assert info["used_memory_dataset"] == 4000
    assert info["used_memory_peak"] >= 4000
    assert info["maxmemory_policy"] == "noeviction"
    assert info["used_memory_human"].endswith("K")
    # usage falls back to the ledger entry when no store is wired
    assert rep.memory_usage("a") > 1000
    assert rep.memory_usage("ghost") is None


def test_report_doctor_rules():
    # empty instance
    led = MemLedger()
    rep = MemoryReport(led)
    doc = rep.memory_doctor()
    assert doc["findings"] == [] and "empty" in doc["message"]
    # orphaned scratch: meter bytes held with zero live state
    led.register_meter("leak", lambda: 4096, "scratch")
    doc = rep.memory_doctor()
    rules = [f["rule"] for f in doc["findings"]]
    assert "orphaned-scratch" in rules
    # cache dominating the dataset
    led2 = MemLedger()
    led2.on_create("a", "bitset", 100)
    led2.register_meter("rc", lambda: 10_000, "cache")
    rules2 = [f["rule"] for f in MemoryReport(led2).memory_doctor()["findings"]]
    assert "cache-dominates" in rules2
    # near-watermark via an attached pressure monitor
    led3 = MemLedger()
    led3.on_create("a", "bitset", 950)
    p = _pressure(led3, FakeClock(), high=1000)
    rules3 = [f["rule"] for f in
              MemoryReport(led3, pressure=p).memory_doctor()["findings"]]
    assert "near-watermark" in rules3


# ---------------------------------------------------------------------------
# 5. metrics registry (poisoned gauge regression)
# ---------------------------------------------------------------------------

def test_snapshot_drops_poisoned_gauge_and_counts_error():
    reg = MetricsRegistry()
    reg.gauge("good", lambda: 42)
    reg.gauge("poison", lambda: 1 // 0)
    snap = reg.snapshot()
    assert snap["gauges"]["good"] == 42
    # the raising gauge is DROPPED (no None poisoning downstream sums)
    assert "poison" not in snap["gauges"]
    # and the failure is visible in the SAME snapshot's counters
    assert snap["counters"]["metrics.callback_errors"] >= 1
    # subsequent snapshots keep counting
    reg.snapshot()
    assert reg.snapshot()["counters"]["metrics.callback_errors"] >= 3


# ---------------------------------------------------------------------------
# 6. client integration
# ---------------------------------------------------------------------------

def test_client_memory_parity_end_to_end():
    c = RedissonTPU.create(Config())
    try:
        h = c.get_hyper_log_log("mem:h")
        h.add_all([b"k%d" % i for i in range(100)])
        bs = c.get_bit_set("mem:b")
        bs.set(100)
        # MEMORY USAGE: one bank row per HLL name, exact bytes for bitset
        hu = c.memory_usage("mem:h")
        bu = c.memory_usage("mem:b")
        assert hu is not None and bu is not None
        obj = c._store.get("mem:b")
        assert bu > int(obj.state.nbytes)  # value + metadata overhead
        assert c.memory_usage("mem:ghost") is None
        st = c.memory_stats()
        assert st["dataset.bytes"] == c.memstat.live_bytes()
        assert st["bank.bytes"] > 0
        assert st["keys.count"] >= 2
        doc = c.memory_doctor()
        assert isinstance(doc["findings"], list)
        v = c.memory_verify()
        assert v["ok"], v
        assert v["drift_bytes"] == 0
        info = c.info("memory")["memory"]
        assert info["used_memory_dataset"] == c.memstat.live_bytes()
        full = c.info()
        assert {"server", "memory"} <= set(full)
        with pytest.raises(ValueError):
            c.info("replication")
        gauges = c.metrics.snapshot()["gauges"]
        assert gauges["memstat.live_bytes"] == c.memstat.live_bytes()
        assert gauges["memstat.keys"] == c.memstat.keys_count()
    finally:
        c.shutdown()


def test_client_memory_facade_requires_device_mode():
    c = RedissonTPU.create(Config())
    try:
        c._memreport = None  # what redis passthrough mode wires
        c.memstat = None
        with pytest.raises(RuntimeError, match="MEMORY USAGE"):
            c.memory_usage("x")
        with pytest.raises(RuntimeError):
            c.memory_verify()
    finally:
        c._memreport = None
        c.shutdown()


@pytest.mark.parametrize("hll_impl", ["scatter", "sort"])
def test_randomized_churn_zero_drift(hll_impl):
    """The tentpole invariant under randomized churn, on both HLL engine
    tiers: ledger == sum(live Array.nbytes) at every checkpoint, peak is
    monotone, and flushall returns the ledger to exactly zero."""
    cfg = Config()
    cfg.hll_impl = hll_impl
    c = RedissonTPU.create(cfg)
    rng = random.Random(0xC0FFEE + hash(hll_impl) % 1000)
    try:
        live_bs = set()
        peak_seen = 0
        for step in range(60):
            roll = rng.random()
            if roll < 0.35:
                name = "churn:h%d" % rng.randrange(8)
                c.get_hyper_log_log(name).add(b"v%d" % step)
            elif roll < 0.65:
                name = "churn:b%d" % rng.randrange(8)
                c.get_bit_set(name).set(rng.randrange(4096))
                live_bs.add(name)
            elif roll < 0.8 and live_bs:
                name = live_bs.pop()
                c.delete(name)
            elif live_bs:
                src = rng.choice(sorted(live_bs))
                dst = "churn:rn%d" % rng.randrange(4)
                if c._store.exists(src):
                    c._store.rename(src, dst)
                    live_bs.discard(src)
                    live_bs.add(dst)
            if step % 15 == 14:
                v = c.memory_verify()
                assert v["ok"], (hll_impl, step, v)
                pk = c.memstat.peak_bytes()
                assert pk >= peak_seen
                assert pk >= c.memstat.live_bytes()
                peak_seen = pk
        v = c.memory_verify()
        assert v["ok"] and v["drift_bytes"] == 0, v
        c.flushall()
        assert c.memstat.live_bytes() == 0
        assert c.memory_verify()["ok"]
        # seeded leak: scratch bytes with zero live state -> doctor flags
        c.memstat.register_meter("seeded_leak", lambda: 8192, "scratch")
        rules = [f["rule"] for f in c.memory_doctor()["findings"]]
        assert "orphaned-scratch" in rules
    finally:
        c.shutdown()


def test_client_watermark_sheds_writes_reads_flow():
    cfg = Config()
    cfg.use_serve()
    mcfg = cfg.use_memstat()
    mcfg.high_watermark_bytes = 1  # anything live trips the gate
    mcfg.retry_after_s = 2.5
    c = RedissonTPU.create(cfg)
    try:
        bs = c.get_bit_set("wm:b")
        bs.set(7)  # admitted: ledger still empty at the gate
        with pytest.raises(RejectedError) as ei:
            bs.set(8)  # now live bytes >= 1 -> shed
        assert ei.value.reason == "memory"
        assert ei.value.retry_after_s == pytest.approx(2.5)
        # reads keep flowing while writes shed
        assert bs.get(7) is True
        assert bs.cardinality() == 1
        # and reclaiming writes are never shed
        assert c.delete("wm:b") is True
        snap = c.serve.snapshot()
        assert snap["memory"]["pressure"]["shed_total"] >= 1
        assert snap["memory"]["live_bytes"] == c.memstat.live_bytes()
    finally:
        c.shutdown()


def test_client_trace_exports_memstat_counters():
    cfg = Config()
    tc = cfg.use_trace()
    tc.sample_every = 1
    c = RedissonTPU.create(cfg)
    try:
        bs = c.get_bit_set("tr:mem")
        for i in range(8):
            bs.set(i)
        doc = c.trace.chrome_trace()
        counters = [e for e in doc["traceEvents"] if e["ph"] == "C"]
        assert counters, "no memstat counter events in the chrome trace"
        names = {e["name"] for e in counters}
        assert "memstat.live_bytes" in names
        live = [e for e in counters if e["name"] == "memstat.live_bytes"]
        # the closing sample reflects the current ledger
        assert live[-1]["args"]["bytes"] == c.memstat.live_bytes()
        assert all(e["cat"] == "memstat" for e in counters)
    finally:
        c.shutdown()


def test_executor_staging_accounting_drains():
    c = RedissonTPU.create(Config())
    try:
        bs = c.get_bit_set("stg:b")
        for i in range(32):
            bs.set(i)
        assert bs.cardinality() == 32
        # after the pipeline drains, no staged payload bytes remain held
        stats = c._executor.pipeline_stats()
        assert "staging_bytes" in stats
        assert c._executor.staging_bytes() == 0
    finally:
        c.shutdown()


def test_persist_disk_meter_reports_journal_bytes(tmp_path):
    cfg = Config()
    cfg.use_persist(str(tmp_path))
    c = RedissonTPU.create(cfg)
    try:
        bs = c.get_bit_set("pd:b")
        for i in range(16):
            bs.set(i)
        totals = c.memstat.meter_totals()
        assert totals["disk"] > 0  # journal segments on disk
        assert c.memory_stats()["disk.bytes"] == totals["disk"]
    finally:
        c.shutdown()
