"""Shard-level HA: per-shard replica fleets, failover under live
migration, chaos seams, and the history checker.

Layers:

1. histcheck — the consistency checker itself flags synthetic violating
   histories and passes clean ones (the checker is only as good as its
   ability to fail).
2. Failover robustness — retry after an aborted failover (the one-shot
   guard must re-arm), cascading double failover through rejoin with the
   prober re-armed after promotion.
3. Fault seams — replica_tail partitions never violate bounded staleness
   (the router falls back to the primary); a health_probe false-negative
   drives a SPURIOUS failover against a live primary and the fence
   guarantees every acked write lands in exactly one journal.
4. Cluster composition — per-shard fleets surface in CLUSTER SLOTS /
   INFO, the journaled slot table survives promotion, and an aborted
   migration is retryable (nothing stranded in `migrating`).
"""

import json
import threading
import time

import pytest

from redisson_tpu.client import RedissonTPU
from redisson_tpu.config import Config
from redisson_tpu.fault import inject
from redisson_tpu.ops.crc16 import key_slot
from tests.test_replica import make_replicated
from tools import histcheck


# ---------------------------------------------------------------------------
# 1. the history checker itself
# ---------------------------------------------------------------------------

def test_histcheck_clean_history_passes():
    rec = histcheck.HistoryRecorder()
    rec.record_write("w", "k", "v1", acked_seq=1)
    rec.record_write("w", "k", "v2", acked_seq=2)
    rec.record_read("w", "k", "v2", watermark=2, primary_seq=2)
    rec.record_read("r", "k", "v1", watermark=1, primary_seq=1)
    v = histcheck.check(rec, final_state={"k": "v2"})
    assert v.ok, v.issues
    assert v.writes_checked == 2 and v.reads_checked == 2


def test_histcheck_flags_lost_ack():
    rec = histcheck.HistoryRecorder()
    rec.record_write("w", "k", "v1", acked_seq=1)
    v = histcheck.check(rec, final_state={"k": "v0"})
    assert v.lost_acks == 1 and not v.ok
    # ...but an unknown-fate write explains a newer final state
    rec.record_write_unknown("w", "k", "v0")
    assert histcheck.check(rec, final_state={"k": "v0"}).lost_acks == 0
    # a missing key is a lost ack too
    assert histcheck.check(rec, final_state={}).lost_acks == 1


def test_histcheck_flags_staleness_violation():
    rec = histcheck.HistoryRecorder()
    rec.record_write("w", "k", "v1", acked_seq=1)
    rec.record_write("w", "k", "v2", acked_seq=2)
    # serving watermark says >= 2, yet the read returned the seq-1 value:
    # the replica lied about its watermark (or served outside the bound).
    rec.record_read("r", "k", "v1", watermark=2, primary_seq=5)
    v = histcheck.check(rec)
    assert v.staleness_violations == 1 and not v.ok


def test_histcheck_flags_ryw_violation():
    rec = histcheck.HistoryRecorder()
    rec.record_write("t", "k", "v1", acked_seq=1)
    rec.record_write("t", "k", "v2", acked_seq=2)
    # tenant t was acked seq 2 before this read, but read the seq-1 value
    # from a watermark-1 replica: legal staleness, illegal RYW.
    rec.record_read("t", "k", "v1", watermark=1, primary_seq=2)
    v = histcheck.check(rec)
    assert v.ryw_violations == 1 and v.staleness_violations == 0


def test_histcheck_flags_monotonic_violation():
    rec = histcheck.HistoryRecorder()
    rec.record_write("w", "k", "v1", acked_seq=1)
    rec.record_write("w", "k", "v2", acked_seq=2)
    # reader saw v2, then stepped back to v1: monotonic-reads violation
    # (each read alone is within its staleness window).
    rec.record_read("r", "k", "v2", watermark=0, primary_seq=2)
    rec.record_read("r", "k", "v1", watermark=0, primary_seq=2)
    v = histcheck.check(rec)
    assert v.monotonic_violations == 1
    assert v.ryw_violations == 0 and v.staleness_violations == 0


def test_histcheck_absent_reads():
    rec = histcheck.HistoryRecorder()
    # reading a never-written key as absent is clean
    rec.record_read("r", "nope", None, watermark=0, primary_seq=0)
    assert histcheck.check(rec).ok
    # reading absent AFTER the watermark passed the first write is stale
    rec.record_write("w", "k", "v1", acked_seq=3)
    rec.record_read("r", "k", None, watermark=3, primary_seq=3)
    assert histcheck.check(rec).staleness_violations == 1


# ---------------------------------------------------------------------------
# 2. failover robustness (S1 retry-after-abort, S2 cascading + re-arm)
# ---------------------------------------------------------------------------

def test_failover_retry_after_aborted_promotion(tmp_path):
    c = make_replicated(tmp_path, n=2)
    try:
        c.get_bucket("b").set("v")
        assert c.wait_for_replicas(2, timeout_s=10.0) == 2
        mgr = c.replicas
        # first promotion attempt blows up mid-flight on EVERY candidate
        originals = [(r, r.promote) for r in mgr.replicas]

        def boom(*a, **kw):
            raise RuntimeError("injected promote failure")

        for r in mgr.replicas:
            r.promote = boom
        with pytest.raises(RuntimeError, match="injected promote"):
            mgr.failover("first attempt, doomed")
        # the abort re-armed the one-shot guard...
        assert mgr._failed_over is False
        assert mgr.promotions == 0
        # ...but the old journal stays fenced (writes fail cleanly instead
        # of acking into a stream a half-promoted fleet may abandon)
        with pytest.raises(RuntimeError, match="fenced"):
            c.get_bucket("b").set("lost-cause")
        for r, orig in originals:
            r.promote = orig
        # the retry promotes cleanly and service resumes on the promotee
        assert mgr.failover("retry") is not None
        assert mgr.promotions == 1
        c.get_bucket("b").set("post-retry")
        assert c.get_bucket("b").get() == "post-retry"
        assert c.get_bucket("b").get() != "lost-cause"
    finally:
        c.shutdown()


def test_cascading_double_failover_with_prober_rearm(tmp_path):
    # health prober ON: both failovers must fire from the prober thread,
    # which proves the prober re-arms (and keeps running) after the first
    # promotion instead of exiting with the one-shot guard latched.
    c = make_replicated(tmp_path, n=2, health_interval_s=0.02,
                        health_failures=2, auto_failover=True)
    try:
        mgr = c.replicas
        for i in range(10):
            c.get_bucket(f"k{i}").set(f"v{i}")
        assert c.wait_for_replicas(2, timeout_s=10.0) == 2

        def wait_promotions(n, timeout_s=15.0):
            deadline = time.monotonic() + timeout_s
            while mgr.promotions < n and time.monotonic() < deadline:
                time.sleep(0.01)
            assert mgr.promotions == n

        c._executor.shutdown(wait=False)  # primary dies -> prober fires
        wait_promotions(1)
        first = mgr.primary_client
        # demoted slot rejoins; wait for it to catch up off the promotee
        mgr.rejoin()
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            reps = mgr.replicas
            if reps and all(r.lag() == 0 for r in reps):
                break
            time.sleep(0.01)
        # the promotee dies too -> the RE-ARMED prober fires again
        first._executor.shutdown(wait=False)
        wait_promotions(2)
        assert mgr.primary_client is not first
        # every acked write survived two generations of failover
        for i in range(10):
            assert c.get_bucket(f"k{i}").get() == f"v{i}"
        c.get_bucket("post").set("2nd-gen")
        assert c.get_bucket("post").get() == "2nd-gen"
        # second epoch dir derives from the BASE dir, not the first epoch
        # (no -epoch-1-epoch-2 nesting)
        path = mgr.primary_client._persist.journal.path
        assert "epoch-1-epoch" not in path
    finally:
        c.shutdown()


# ---------------------------------------------------------------------------
# 3. fault seams: replica_tail partition + spurious health_probe failover
# ---------------------------------------------------------------------------

def test_replica_tail_partition_never_violates_staleness(tmp_path):
    # Partition replica-0's tail loop for many polls: its watermark
    # freezes while acked writes race ahead. Bounded staleness must hold
    # by PRIMARY FALLBACK, verified with the history checker.
    c = make_replicated(tmp_path, n=1, max_lag_seqs=4,
                        read_your_writes=False)
    inj = inject.FaultInjector(inject.FaultPlan(rules=[
        inject.FaultRule(seam="replica_tail", fault="retryable",
                         nth=1, times=10_000),
    ], seed=7))
    inject.install(inj)
    try:
        m = c.get_map("m")
        m.put("k", "v0")
        rec = histcheck.HistoryRecorder()
        router = c._dispatch
        before = router.primary_fallbacks
        seq = c.persist.journal.last_seq
        rec.record_write("w", "k", "v0", acked_seq=seq)
        for i in range(30):
            m.put("k", f"v{i + 1}")
            seq = c.persist.journal.last_seq
            rec.record_write("w", "k", f"v{i + 1}", acked_seq=seq)
            fut, picked, wm = router.routed_read(
                "m", "hget", {"field": b'"k"'})
            raw = fut.result(timeout=30)
            value = json.loads(raw) if raw is not None else None
            rec.record_read("r", "k", value, watermark=wm,
                            primary_seq=c.persist.journal.last_seq)
        assert inj.injected > 0  # the partition actually fired
        assert router.primary_fallbacks > before  # fallback carried reads
        v = histcheck.check(rec, final_state={"k": "v30"})
        assert v.ok, v.issues
    finally:
        inject.uninstall()
        c.shutdown()


def test_spurious_health_probe_failover_acks_exactly_once(tmp_path):
    # A false-negative prober fails over a LIVE primary while unique
    # writes are in flight. The fence makes split-brain impossible: every
    # acked value must appear in exactly one journal (old primary's or
    # the promotee's epoch journal), never both, never neither.
    c = make_replicated(tmp_path, n=2, health_interval_s=0.02,
                        health_failures=2, auto_failover=True)
    inj = inject.FaultInjector(inject.FaultPlan(rules=[
        # two consecutive false negatives = health_failures -> failover
        inject.FaultRule(seam="health_probe", fault="retryable",
                         nth=5, times=2),
    ], seed=11))
    old_journal_path = c.persist.journal.path
    mgr = c.replicas
    acked = {}      # value -> seq
    unknown = []    # fate uncertain (fence race)
    stop = threading.Event()

    def writer():
        n = 0
        b = c.get_bucket("sb")
        while not stop.is_set():
            v = f"u{n}"
            try:
                b.set(v)
                acked[v] = c.persist.journal.last_seq
            except Exception:  # noqa: BLE001 — fence race: fate checked against journals below
                unknown.append(v)
            n += 1
            time.sleep(0.001)

    try:
        c.get_bucket("sb").set("seed")
        assert c.wait_for_replicas(2, timeout_s=10.0) == 2
        t = threading.Thread(target=writer, daemon=True)
        t.start()
        inject.install(inj)
        deadline = time.monotonic() + 15.0
        while mgr.promotions < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert mgr.promotions == 1  # the spurious failover happened
        time.sleep(0.1)  # let post-failover writes flow
        stop.set()
        t.join(10)
        assert acked  # writes acked on both sides of the fence
        new_journal_path = mgr.primary_client._persist.journal.path
        assert new_journal_path != old_journal_path
        old_vals = [json.loads(v) for _, tgt, v in
                    histcheck.journal_writes(old_journal_path,
                                             kinds=("set",))
                    if tgt == "sb"]
        new_vals = [json.loads(v) for _, tgt, v in
                    histcheck.journal_writes(new_journal_path,
                                             kinds=("set",))
                    if tgt == "sb"]
        dupes = set(old_vals) & set(new_vals)
        assert not dupes  # split-brain: a value acked by BOTH primaries
        landed = set(old_vals) | set(new_vals)
        missing = [v for v in acked if v not in landed]
        assert not missing  # an acked write that no journal carries
    finally:
        inject.uninstall()
        c.shutdown()


# ---------------------------------------------------------------------------
# 4. cluster composition: fleets, slot-table survival, retryable abort
# ---------------------------------------------------------------------------

def _make_ha_cluster(tmp_path, num_shards=2, replicas_per_shard=1):
    cfg = Config()
    cfg.use_cluster(num_shards=num_shards, dir=str(tmp_path / "cl"),
                    replicas_per_shard=replicas_per_shard)
    rc = cfg.use_replicas(replicas_per_shard)  # per-shard tuning template
    rc.health_interval_s = 0.0  # deterministic: failover driven manually
    rc.poll_interval_s = 0.002
    return RedissonTPU.create(cfg)


def _wait_shard_caught_up(shard, timeout_s=10.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        reps = shard.replicas.replicas
        if reps and all(r.lag() == 0 for r in reps):
            return
        time.sleep(0.005)
    raise AssertionError("shard fleet never caught up")


def test_cluster_replicas_surface_in_slots_and_info(tmp_path):
    c = _make_ha_cluster(tmp_path, num_shards=2, replicas_per_shard=1)
    try:
        c.get_bucket("k").set("v")
        ranges = c.cluster_slots()
        assert all(len(r) == 4 for r in ranges)
        entries = [e for _, _, _, reps in ranges for e in reps]
        assert len(entries) == 2  # one fleet member per shard
        for e in entries:
            assert set(e) == {"id", "watermark", "lag"}
            assert e["id"].startswith("shard-")
        info = c.cluster_info()
        # masters + fleet members, like redis counts replicas as nodes
        assert info["cluster_known_nodes"] == 4
        assert info["cluster_replicas"] == 2
        assert info["failovers"] == 0
    finally:
        c.shutdown()


def test_cluster_shard_failover_slot_table_survives(tmp_path):
    c = _make_ha_cluster(tmp_path, num_shards=2, replicas_per_shard=1)
    try:
        mgr = c.cluster
        table = mgr.router.slot_table()
        keys = [f"k{i}" for i in range(400)
                if table[key_slot(f"k{i}")] == 0][:15]
        for k in keys:
            c.get_bucket(k).set("v:" + k)
        s0 = mgr.shards[0]
        _wait_shard_caught_up(s0)
        owned_before = s0.guard.owned_slots()
        assert owned_before  # the shard owns its contiguous range
        s0.client._executor.shutdown(wait=False)  # shard primary dies
        assert s0.replicas.failover("test kill") is not None
        # the journaled slot table replayed on the promotee: same guard
        # decisions as the dead primary, with the data that backs them
        assert s0.guard.owned_slots() == owned_before
        for k in keys:
            assert c.get_bucket(k).get() == "v:" + k
        k0 = keys[0]
        c.get_bucket(k0).set("post-failover")
        assert c.get_bucket(k0).get() == "post-failover"
        # introspection reflects the promotion
        assert mgr.failovers() == 1
        assert c.cluster_info()["failovers"] == 1
        assert s0.stats()["failovers"] == 1
    finally:
        c.shutdown()


def test_migration_abort_is_retryable(tmp_path, monkeypatch):
    from redisson_tpu.cluster import migrator as migrator_mod

    c = _make_ha_cluster(tmp_path, num_shards=2, replicas_per_shard=0)
    try:
        mgr = c.cluster
        table = mgr.router.slot_table()
        k = next(f"ab{i}" for i in range(400)
                 if table[key_slot(f"ab{i}")] == 0)
        slot = key_slot(k)
        c.get_bucket(k).set("keep")
        monkeypatch.setattr(
            migrator_mod.SlotMigrator, "_bootstrap",
            lambda self, p: (_ for _ in ()).throw(
                migrator_mod.MigrationError("injected bootstrap failure")))
        with pytest.raises(migrator_mod.MigrationError):
            mgr.migrate_slots([slot], 1, timeout_s=30)
        # the abort journaled a clean, RETRYABLE state: nothing stranded
        # in `migrating`, ownership still with the source, data intact
        assert not mgr.shards[0].guard.migrating_slots()
        assert not mgr.shards[1].guard.migrating_slots()
        assert mgr.router.slot_table()[slot] == 0
        assert c.get_bucket(k).get() == "keep"
        monkeypatch.undo()
        # the retry completes the move
        mgr.migrate_slots([slot], 1, timeout_s=60)
        assert mgr.router.slot_table()[slot] == 1
        assert c.get_bucket(k).get() == "keep"
    finally:
        c.shutdown()


def test_failover_mid_migration_resumes_and_converges(tmp_path):
    # The tentpole interplay: the migration source's primary dies while
    # slots are mid-flight. The migrator re-subscribes to the promotee's
    # continuing journal, finishes catch-up, and every acked write reads
    # back — verified by digest.
    c = _make_ha_cluster(tmp_path, num_shards=3, replicas_per_shard=1)
    try:
        mgr = c.cluster
        table = mgr.router.slot_table()
        keys = [f"mm{i}" for i in range(4000)
                if table[key_slot(f"mm{i}")] == 0][:30]
        for k in keys:
            c.get_bucket(k).set("v0")
        move_slots = sorted({key_slot(k) for k in keys})
        s0 = mgr.shards[0]
        _wait_shard_caught_up(s0)

        acked, errs = {}, []
        stop = threading.Event()

        def writer():
            n = 0
            while not stop.is_set():
                k = keys[n % len(keys)]
                v = f"w{n}"
                try:
                    c.get_bucket(k).set(v)
                    acked[k] = v
                except Exception:  # noqa: BLE001 — fence race: fate is unknown, digest below only checks acked
                    errs.append((k, v))
                n += 1
                time.sleep(0.001)

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        result = {}

        def migrate():
            try:
                result["stats"] = mgr.migrate_slots(move_slots, 2,
                                                    timeout_s=60)
            except Exception as e:  # noqa: BLE001 — surfaced via the assertion below
                result["err"] = repr(e)

        mt = threading.Thread(target=migrate, daemon=True)
        mt.start()
        deadline = time.monotonic() + 20
        while (not s0.guard.migrating_slots()
               and time.monotonic() < deadline):
            time.sleep(0.001)
        assert s0.guard.migrating_slots(), "migration never started"
        s0.client._executor.shutdown(wait=False)
        assert s0.replicas.failover("chaos: source kill") is not None
        mt.join(70)
        stop.set()
        t.join(10)
        assert "stats" in result, result.get("err")
        # zero acked writes lost across kill + promotion + cutover
        for k, v in acked.items():
            assert c.get_bucket(k).get() == v
        post = mgr.router.slot_table()
        assert all(post[s] == 2 for s in move_slots)
        assert not s0.guard.migrating_slots()
        assert not mgr.shards[2].guard.migrating_slots()
    finally:
        c.shutdown()
