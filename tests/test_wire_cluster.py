"""Cluster-mode wire front-end: per-shard servers, -MOVED/-ASK
rendering, CLUSTER introspection, and redirect-following across a live
slot migration."""

import pytest

from redisson_tpu.client import RedissonTPU
from redisson_tpu.config import Config
from redisson_tpu.interop.resp_client import SyncRespClient
from redisson_tpu.ops.crc16 import key_slot
from redisson_tpu.wire import proto
from redisson_tpu.wire.server import ShardWireContext


def _cluster_wire(tmp_path, num_shards=2):
    cfg = Config()
    cfg.use_cluster(num_shards=num_shards, dir=str(tmp_path / "cl"))
    cfg.use_wire()
    return RedissonTPU.create(cfg)


def _connect_addr(addr):
    host, _, port = addr.rpartition(":")
    cli = SyncRespClient(host or "127.0.0.1", int(port), retry_attempts=1)
    cli.connect()
    return cli


def _key_owned_by(table, shard_id, prefix="wk"):
    i = 0
    while True:
        k = f"{prefix}{i}"
        if table[key_slot(k)] == shard_id:
            return k
        i += 1


def _parse_redirect(exc):
    """'MOVED 8579 127.0.0.1:4447' -> (kind, slot, addr)."""
    kind, slot, addr = str(exc).split()
    return kind, int(slot), addr


class TestClusterWire:
    def test_moved_redirect_is_followable(self, tmp_path):
        c = _cluster_wire(tmp_path)
        try:
            table = c.cluster.router.slot_table()
            key = _key_owned_by(table, 1)
            slot = key_slot(key)

            wrong = _connect_addr(c.wire.addr_of(0))
            try:
                with pytest.raises(proto.RespError) as ei:
                    wrong.execute("PFADD", key, "a", "b")
                kind, got_slot, addr = _parse_redirect(ei.value)
                assert kind == "MOVED"
                assert got_slot == slot
                assert addr == c.wire.addr_of(1)
            finally:
                wrong.close()

            # A redirect-following client lands on the owner and succeeds.
            right = _connect_addr(addr)
            try:
                assert right.execute("PFADD", key, "a", "b") == 1
                assert right.execute("PFCOUNT", key) == 2
            finally:
                right.close()
            # State is visible through the facade too.
            assert c.get_hyper_log_log(key).count() == 2
        finally:
            c.shutdown()

    def test_cluster_introspection_over_wire(self, tmp_path):
        c = _cluster_wire(tmp_path)
        try:
            cli = _connect_addr(c.wire.addr_of(0))
            try:
                assert cli.execute("CLUSTER", "KEYSLOT", "foo") == key_slot(
                    b"foo"
                )
                info = cli.execute("CLUSTER", "INFO")
                assert b"cluster_enabled:1" in info
                assert b"cluster_state:ok" in info

                slots = cli.execute("CLUSTER", "SLOTS")
                assert slots
                covered = set()
                for entry in slots:
                    start, end, master = entry[0], entry[1], entry[2]
                    covered.update(range(start, end + 1))
                    host, port = master[0], master[1]
                    sid = int(master[2].split(b"-")[-1])
                    assert c.wire.addr_of(sid) == (
                        f"{host.decode()}:{port}"
                    )
                assert covered == set(range(16384))

                # HELLO reports cluster mode on a shard server.
                h = cli.execute("HELLO", "2")
                flat = dict(zip(h[::2], h[1::2]))
                assert flat[b"mode"] == b"cluster"
            finally:
                cli.close()
        finally:
            c.shutdown()

    def test_live_migration_moves_ownership_on_the_wire(self, tmp_path):
        c = _cluster_wire(tmp_path)
        try:
            table = c.cluster.router.slot_table()
            key = _key_owned_by(table, 0, prefix="mig")
            slot = key_slot(key)

            old = _connect_addr(c.wire.addr_of(0))
            try:
                assert old.execute("PFADD", key, "x", "y", "z") == 1
                before = old.execute("PFCOUNT", key)

                c.cluster.migrate_slots([slot], 1)

                # The old owner now bounces the key to shard 1...
                with pytest.raises(proto.RespError) as ei:
                    old.execute("PFCOUNT", key)
                kind, got_slot, addr = _parse_redirect(ei.value)
                assert kind == "MOVED"
                assert got_slot == slot
                assert addr == c.wire.addr_of(1)
            finally:
                old.close()

            # ...and the new owner serves the migrated value.
            new = _connect_addr(c.wire.addr_of(1))
            try:
                assert new.execute("PFCOUNT", key) == before
            finally:
                new.close()
            snap = c.wire.snapshot()
            assert snap["redirects_rendered"] >= 1
        finally:
            c.shutdown()

    def test_wire_frontend_snapshot_sums_shards(self, tmp_path):
        c = _cluster_wire(tmp_path)
        try:
            cli = _connect_addr(c.wire.addr_of(0))
            try:
                cli.execute("PING")
            finally:
                cli.close()
            snap = c.wire.snapshot()
            assert snap["shards"] == 2
            assert snap["commands_total"] >= 1
        finally:
            c.shutdown()


class TestAskRendering:
    """-ASK rendering pinned against stub cluster state: the router parks
    the slot in its cutover window while the importing shard's guard
    carries the migrate mark."""

    class _StubGuard:
        def __init__(self, slots):
            self._slots = set(slots)

        def migrating_slots(self):
            return self._slots

    class _StubShard:
        def __init__(self, slots):
            self.guard = TestAskRendering._StubGuard(slots)

    class _StubRouter:
        def __init__(self, table, ask):
            self._table = table
            self._ask = frozenset(ask)

        def slot_table(self):
            return self._table

        def ask_slots(self):
            return self._ask

    class _StubManager:
        def __init__(self, table, ask, importing):
            self.router = TestAskRendering._StubRouter(table, ask)
            self.shards = {
                0: TestAskRendering._StubShard(()),
                1: TestAskRendering._StubShard(importing),
            }

    def _ctx(self, ask=(), importing=()):
        table = [0] * 16384
        table[5] = 1  # slot 5 owned elsewhere
        ctx = ShardWireContext(0, self._StubManager(table, ask, importing))
        ctx.addrs = {0: "127.0.0.1:7000", 1: "127.0.0.1:7001"}
        return ctx

    def test_ask_during_cutover_window(self):
        ctx = self._ctx(ask={7}, importing={7})
        assert ctx.redirect_for(7) == proto.ask(7, "127.0.0.1:7001")

    def test_moved_for_foreign_slot(self):
        ctx = self._ctx()
        assert ctx.redirect_for(5) == proto.moved(5, "127.0.0.1:7001")

    def test_owned_slot_passes(self):
        ctx = self._ctx()
        assert ctx.redirect_for(42) is None

    def test_ask_addr_prefers_import_target(self):
        ctx = self._ctx(ask={7}, importing={7})
        assert ctx.ask_addr(7) == "127.0.0.1:7001"
        # Without an importing shard the ask address degrades to the
        # table owner (slot 7 is still owned by shard 0 mid-cutover).
        ctx2 = self._ctx(ask={7})
        assert ctx2.ask_addr(7) == "127.0.0.1:7000"
