"""Redis passthrough mode: the client executes every op via RESP against a
server (the reference's execution model), tested against the embedded fake."""

import pytest

from redisson_tpu.client import RedissonTPU
from redisson_tpu.config import Config
from redisson_tpu.interop.backend_redis import UnsupportedInRedisMode
from redisson_tpu.interop.fake_server import EmbeddedRedis


@pytest.fixture()
def rclient():
    with EmbeddedRedis() as er:
        cfg = Config()
        cfg.use_redis().address = f"redis://127.0.0.1:{er.port}"
        c = RedissonTPU.create(cfg)
        try:
            yield c
        finally:
            c.shutdown()


def test_bucket_over_redis(rclient):
    b = rclient.get_bucket("rm:b")
    assert b.get() is None
    b.set({"x": 1})
    assert b.get() == {"x": 1}
    assert not b.try_set("other")      # exists
    assert b.get_and_set(2) == {"x": 1}
    assert b.get() == 2
    assert b.delete()
    assert b.get() is None


def test_atomic_long_over_redis(rclient):
    al = rclient.get_atomic_long("rm:ctr")
    assert al.get() == 0
    assert al.increment_and_get() == 1
    assert al.add_and_get(10) == 11
    assert al.get_and_set(5) == 11
    assert al.get() == 5
    assert al.compare_and_set(5, 7)
    assert not al.compare_and_set(5, 9)
    assert al.get() == 7


def test_map_over_redis(rclient):
    m = rclient.get_map("rm:map")
    assert m.put("a", 1) is None
    assert m.put("a", 2) == 1          # old value comes back
    assert m.get("a") == 2
    assert m.put_if_absent("a", 9) == 2
    assert m.put_if_absent("b", 3) is None
    assert m.size() == 2
    assert sorted(m.key_set()) == ["a", "b"]
    assert m.contains_key("a")
    m.put_all({"c": 4, "d": 5})
    assert m.get_all(["c", "d"]) == {"c": 4, "d": 5}
    assert m.remove("a") == 2
    assert m.size() == 3
    assert m.add_and_get("n", 5) == 5


def test_set_list_over_redis(rclient):
    s = rclient.get_set("rm:set")
    assert s.add("x")
    assert not s.add("x")
    assert s.contains("x")
    assert s.size() == 1
    assert s.read_all() == {"x"}
    assert s.remove("x")

    lst = rclient.get_list("rm:list")
    lst.add("a")
    lst.add_all(["b", "c"])
    assert lst.size() == 3
    assert lst.get(0) == "a"
    assert lst.read_all() == ["a", "b", "c"]
    lst.set(1, "B")
    assert lst.get(1) == "B"
    assert lst.remove("B")

    q = rclient.get_queue("rm:q")
    q.offer("1")
    q.offer("2")
    assert q.poll() == "1"
    assert q.poll() == "2"
    assert q.poll() is None


def test_scored_sorted_set_over_redis(rclient):
    z = rclient.get_scored_sorted_set("rm:z")
    z.add(3.0, "c")
    z.add(1.0, "a")
    z.add(2.0, "b")
    assert z.get_score("a") == 1.0
    assert z.size() == 3
    assert [m for m in z.value_range(0, -1)] == ["a", "b", "c"]
    assert z.add_score("a", 5.0) == 6.0
    assert z.remove("b")
    assert z.size() == 2


def test_bitset_over_redis(rclient):
    bs = rclient.get_bit_set("rm:bits")
    assert not bs.set(7)      # returns old value
    assert bs.set(7)
    assert bs.get(7)
    assert not bs.get(8)
    assert bs.cardinality() == 1
    bs.set(100)
    assert bs.cardinality() == 2
    assert bool(bs.clear_bits([7])[0])  # old value was set
    assert bs.cardinality() == 1


def test_hll_over_redis(rclient):
    h = rclient.get_hyper_log_log("rm:hll")
    assert h.add(b"one")
    h.add_all([b"k%d" % i for i in range(5000)])
    est = h.count()
    assert abs(est - 5001) / 5001 < 0.05
    h2 = rclient.get_hyper_log_log("rm:hll2")
    h2.add_all([b"j%d" % i for i in range(100)])
    assert h.count_with("rm:hll2") >= est
    h.merge_with("rm:hll2")
    assert h.count() >= est
    # fused merge+count: one pipelined round trip, same semantics
    h3 = rclient.get_hyper_log_log("rm:hll3")
    got = h3.merge_with_and_count("rm:hll", "rm:hll2")
    assert got == h.count_with("rm:hll2")
    # a WRONGTYPE source surfaces as an error, not a stale count (the
    # pipelined PFMERGE reply is checked, review r5)
    rclient.get_bucket("rm:str").set("plain")
    with pytest.raises(Exception):
        h3.merge_with_and_count("rm:str")


def test_expiry_over_redis(rclient):
    b = rclient.get_bucket("rm:ttl")
    b.set("v")
    assert b.expire(60)
    assert 0 < b.remain_time_to_live() <= 60_000
    assert b.clear_expire()
    assert b.remain_time_to_live() == -1


def test_keys_facade_over_redis(rclient):
    rclient.get_bucket("rm:k1").set(1)
    rclient.get_bucket("rm:k2").set(2)
    assert set(rclient.keys("rm:k*")) == {"rm:k1", "rm:k2"}
    assert rclient.delete("rm:k1")
    rclient.flushall()
    assert rclient.keys() == []


def test_unsupported_ops_raise_cleanly(rclient):
    # Locks/topics are now served by server-side Lua + pub/sub
    # (interop/coordination_redis.py) — the old NotImplementedError gates
    # are gone (VERDICT r1 item #3); test_redis_coordination.py covers them.
    # Checkpointing still needs a device-resident store:
    with pytest.raises(NotImplementedError):
        rclient.save_checkpoint("/tmp/nope")


def test_metrics_work_in_redis_mode(rclient):
    rclient.get_bucket("rm:m").set(1)
    assert rclient.metrics.counter("executor.ops_total") >= 1


def test_reversed_zrange_matches_engine_semantics(rclient):
    z = rclient.get_scored_sorted_set("rm:zrev")
    z.add(1.0, "a")
    z.add(2.0, "b")
    z.add(3.0, "c")
    # engine contract: reverse THEN slice
    assert z.value_range(0, 0, reversed=True) == ["c"]
    assert z.value_range(0, 1, reversed=True) == ["c", "b"]
    assert z.value_range(-1, -1, reversed=True) == ["a"]
    assert z.add_all([]) == 0  # empty ZADD must not hit the wire


def test_multimap_colon_fields_do_not_collide(rclient):
    """(review r3) Fields containing ':' must not collide two (key, field)
    pairs onto one subkey: 'a' + 'b:mm:c' vs 'a:mm:b' + 'c' were one Redis
    key under raw concatenation; the hex-encoded field segment keeps them
    apart and keeps the purge/delete Lua able to rebuild subkey names."""
    m1 = rclient.get_set_multimap("a")
    m2 = rclient.get_set_multimap("a:mm:" + "6263")  # hex('bc')-shaped name
    m1.put("bc", "v1")
    m2.put("bc", "v2")
    assert m1.get_all("bc") == {"v1"}
    assert m2.get_all("bc") == {"v2"}
    assert set(m1.key_set()) == {"bc"}
    assert m1.contains_key("bc")
    m1.delete()
    assert m2.get_all("bc") == {"v2"}  # deleting m1 must not touch m2


def test_multimap_cache_colon_field_ttl(rclient):
    import time

    mm = rclient.get_set_multimap_cache("rm:mmc2")
    mm.put("a:mm:b", "v")
    assert mm.contains_key("a:mm:b")
    assert mm.expire_key("a:mm:b", 0.0005)  # sub-ms rounds up to 1 ms
    time.sleep(0.05)
    assert mm.get_all("a:mm:b") == set()


def test_bloom_filter_over_redis(rclient):
    """The reference's own execution model: k SETBIT/GETBIT per key behind
    a Lua config guard (RedissonBloomFilter.java:80-168), config in the
    {name}__config sidecar (:254-256)."""
    import numpy as np

    bf = rclient.get_bloom_filter("rm:bf")
    assert bf.try_init(10_000, 0.01) is True
    assert bf.try_init(10_000, 0.01) is False
    assert bf.get_hash_iterations() == 7
    members = [f"u{i}" for i in range(400)]
    assert bf.add_all(members).all()
    assert not bf.add_all(members).any()          # re-add reports unchanged
    assert bf.contains_all(members).all()          # no false negatives
    assert bf.contains_all([f"g{i}" for i in range(400)]).mean() < 0.05
    assert abs(bf.count() - 400) / 400 < 0.1       # BITCOUNT estimate
    ints = np.arange(64, dtype=np.uint64)
    bf.add_ints(ints)
    assert bf.contains_count_ints(ints) == 64
    assert bf.is_blocked() is False
    with __import__("pytest").raises(Exception):
        rclient.get_bloom_filter("rm:bfb").try_init(100, 0.01, blocked=True)


def test_bloom_cross_tier_bit_compatible(rclient):
    """A filter built on the TPU tier and flushed via durability serves
    live wire-mode lookups with zero false negatives (identical murmur3
    halves + (h1 + i*h2) mod 2^64 mod m walk on both tiers)."""
    from redisson_tpu.interop.durability import DurabilityManager
    from redisson_tpu.interop.resp_client import SyncRespClient

    local = RedissonTPU.create()
    try:
        bf = local.get_bloom_filter("rm:xt")
        bf.try_init(5000, 0.01)
        bf.add_all([f"m{i}" for i in range(500)])
        port = rclient.config.redis.address.rsplit(":", 1)[1]
        with SyncRespClient(port=int(port)) as rc:
            DurabilityManager(local._store, rc).flush(["rm:xt"])
    finally:
        local.shutdown()
    bf2 = rclient.get_bloom_filter("rm:xt")
    assert bf2.contains_all([f"m{i}" for i in range(500)]).all()
    bf2.add("extra")
    assert bf2.contains("extra")


def test_bloom_cross_tier_with_nonzero_seed():
    """Seeded cross-tier compatibility: TPU tier with hash_seed=9 flushed,
    redis tier with matching hash_seed serves it — and a MISmatched seed
    visibly breaks membership (review r3: the wire path must honor the
    configured seed, not hardcode 0)."""
    from redisson_tpu.config import TpuConfig
    from redisson_tpu.interop.durability import DurabilityManager
    from redisson_tpu.interop.resp_client import SyncRespClient

    with EmbeddedRedis() as er:
        local = RedissonTPU.create(Config(tpu=TpuConfig(hash_seed=9)))
        try:
            bf = local.get_bloom_filter("rm:seed")
            bf.try_init(3000, 0.01)
            bf.add_all([f"s{i}" for i in range(300)])
            with SyncRespClient(port=er.port) as rc:
                DurabilityManager(local._store, rc).flush(["rm:seed"])
        finally:
            local.shutdown()

        cfg = Config()
        r = cfg.use_redis()
        r.address = f"redis://127.0.0.1:{er.port}"
        r.hash_seed = 9
        c = RedissonTPU.create(cfg)
        try:
            hits = c.get_bloom_filter("rm:seed").contains_all(
                [f"s{i}" for i in range(300)])
            assert hits.all()
        finally:
            c.shutdown()

        cfg2 = Config()
        r2 = cfg2.use_redis()
        r2.address = f"redis://127.0.0.1:{er.port}"  # default seed 0
        c2 = RedissonTPU.create(cfg2)
        try:
            hits = c2.get_bloom_filter("rm:seed").contains_all(
                [f"s{i}" for i in range(300)])
            assert not hits.all()  # wrong seed, wrong bits
        finally:
            c2.shutdown()


def test_bloom_wire_accepts_large_non_pow2_size(rclient):
    """The wire path takes any size up to the 2^32 cap (host-side index
    math); the TPU kernel's power-of-two-above-2^31 rule must not apply
    (review r3)."""
    bf = rclient.get_bloom_filter("rm:big")
    # m ~= 2.87e9 > 2^31 and not a power of two. Init + lookups only: a
    # SETBIT near the top would make the fake allocate a ~360MB backing
    # string, which is the server's business, not this contract's.
    assert bf.try_init(300_000_000, 0.01) is True
    assert bf.get_size() > (1 << 31)
    assert not bf.contains("other")


def test_bitset_length_over_redis(rclient):
    """Wire-tier lengthAsync parity (RedissonBitSet.java:181-192): logical
    length = highest set bit + 1, matching the TPU tier's semantics."""
    bs = rclient.get_bit_set("rm:blen")
    assert bs.length() == 0
    bs.set(0)
    assert bs.length() == 1
    bs.set(7)
    assert bs.length() == 8
    bs.set(100)
    assert bs.length() == 101
    bs.set(65_000)
    assert bs.length() == 65_001
    bs.clear_bits([65_000])
    assert bs.length() == 101


def test_bitset_set_range_over_redis(rclient):
    """Range set/clear over the wire (RedissonBitSet.java:203-228) — edge
    bits + aligned SETRANGE middle must agree bit-for-bit with per-bit."""
    bs = rclient.get_bit_set("rm:brange")
    bs.set_range(3, 75)  # spans edges + 8 full bytes
    assert bs.cardinality() == 72
    assert not bs.get(2) and bs.get(3) and bs.get(74) and not bs.get(75)
    bs.set_range(10, 20, value=False)
    assert bs.cardinality() == 72 - 10
    assert bs.get(9) and not bs.get(10) and not bs.get(19) and bs.get(20)
    # clear past the end must not grow the backing string — including the
    # UNALIGNED edge-bit path (review r4: SETBIT 0 zero-pads)
    bs2 = rclient.get_bit_set("rm:brange2")
    bs2.set(5)
    bs2.set_range(1000, 5000, value=False)
    assert bs2.size() <= 8  # still one byte
    bs2.set_range(1001, 5003, value=False)  # unaligned edges
    assert bs2.size() <= 8
    bs2.set_range(3, 5003, value=False)  # straddles the current end
    assert bs2.size() <= 8
    assert bs2.get(5) is False and bs2.cardinality() == 0


def test_hll_export_over_redis(rclient):
    """hll_export decodes the server's HYLL blob into raw registers —
    re-importable (PFCOUNT-stable through an export/import cycle)."""
    h = rclient.get_hyper_log_log("rm:hexp")
    h.add_all([b"e%d" % i for i in range(20_000)])
    est = h.count()
    regs, version = rclient._executor.execute_sync("rm:hexp", "hll_export", None)
    assert regs.shape == (16384,) and regs.dtype.name == "uint8"
    assert int(regs.max()) > 0
    # registers reconstruct the same estimate through the decoder's math
    from redisson_tpu.interop import hyll
    import numpy as np

    blob = hyll.encode_dense(regs)
    back = hyll.decode(blob)
    assert np.array_equal(back.astype(np.uint8), regs)
    assert rclient._executor.execute_sync("rm:none", "hll_export", None) is None
    assert abs(est - 20_000) / 20_000 < 0.05
