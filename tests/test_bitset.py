import jax.numpy as jnp
import numpy as np

from redisson_tpu.ops import bitset


def test_set_get_clear_roundtrip():
    bits = bitset.make(1000)
    idx = np.array([0, 5, 999, 5, 123], np.int32)
    bits, old = bitset.set_bits(bits, idx)
    assert old.tolist() == [0, 0, 0, 0, 0]
    assert bitset.get_bits(bits, np.array([0, 5, 123, 999, 7], np.int32)).tolist() == [1, 1, 1, 1, 0]
    bits2, old2 = bitset.set_bits(bits, np.array([5, 7], np.int32))
    assert old2.tolist() == [1, 0]
    bits3, old3 = bitset.clear_bits(bits2, np.array([5, 11], np.int32))
    assert old3.tolist() == [1, 0]
    assert int(bitset.get_bits(bits3, np.array([5], np.int32))[0]) == 0


def test_cardinality_length_bitpos():
    bits = bitset.make(256)
    bits, _ = bitset.set_bits(bits, np.array([3, 100, 200], np.int32))
    assert int(bitset.cardinality(bits)) == 3
    assert int(bitset.length(bits)) == 201
    assert int(bitset.bitpos(bits, 1)) == 3
    assert int(bitset.bitpos(bits, 0)) == 0
    empty = bitset.make(16)
    assert int(bitset.length(empty)) == 0
    assert int(bitset.bitpos(empty, 1)) == -1
    assert int(bitset.cardinality(empty)) == 0


def test_set_range():
    bits = bitset.make(64)
    bits = bitset.set_range(bits, 10, 20, True)
    assert int(bitset.cardinality(bits)) == 10
    assert int(bitset.bitpos(bits, 1)) == 10
    bits = bitset.set_range(bits, 15, 18, False)
    assert np.asarray(bits)[14:19].tolist() == [1, 0, 0, 0, 1]


def test_bitops():
    a = bitset.make(32)
    b = bitset.make(32)
    a, _ = bitset.set_bits(a, np.array([1, 2, 3], np.int32))
    b, _ = bitset.set_bits(b, np.array([2, 3, 4], np.int32))
    assert np.flatnonzero(np.asarray(bitset.bitop_and(a, b))).tolist() == [2, 3]
    assert np.flatnonzero(np.asarray(bitset.bitop_or(a, b))).tolist() == [1, 2, 3, 4]
    assert np.flatnonzero(np.asarray(bitset.bitop_xor(a, b))).tolist() == [1, 4]
    assert int(bitset.cardinality(jnp.uint8(1) - a)) == 29


def test_pack_unpack_redis_layout():
    # Redis SETBIT 0 -> MSB of byte 0: value b'\x80'.
    bits = bitset.make(9)
    bits, _ = bitset.set_bits(bits, np.array([0], np.int32))
    assert bytes(np.asarray(bitset.pack(bits))) == b"\x80\x00"
    bits2 = bitset.make(16)
    bits2, _ = bitset.set_bits(bits2, np.array([7, 8, 15], np.int32))
    packed = bytes(np.asarray(bitset.pack(bits2)))
    assert packed == b"\x01\x81"
    # Roundtrip.
    back = bitset.unpack(np.frombuffer(packed, np.uint8), 16)
    assert np.array_equal(np.asarray(back), np.asarray(bits2))


def test_combine_length_past_2_31():
    """The 64-bit host combine must report positions beyond int32 range.

    A real 2^31-bit array is too big for CI, so fabricate the per-chunk
    partials the device kernel would emit: zero everywhere except one
    high chunk. The combined position must come back as an exact python
    int past 2^31 (the old single-int32 path wrapped negative here).
    """
    chunk = bitset._CARD_CHUNK
    g = (1 << 31) // chunk + 3  # chunk index whose base offset is > 2^31
    partials = np.zeros((g + 1,), np.int32)
    partials[g] = 7  # highest set bit at local offset 6 -> length 7
    got = bitset.combine_length(partials)
    assert got == g * chunk + 7
    assert got > (1 << 31)
    assert bitset.combine_length(np.zeros((4,), np.int32)) == 0


def test_combine_bitpos_past_2_31():
    chunk = bitset._CARD_CHUNK
    g = (1 << 31) // chunk + 3
    partials = np.full((g + 1,), -1, np.int32)
    partials[g] = 5  # first match lives in the high chunk
    got = bitset.combine_bitpos(partials)
    assert got == g * chunk + 5
    assert got > (1 << 31)
    # earliest chunk wins when several match
    partials[2] = 11
    assert bitset.combine_bitpos(partials) == 2 * chunk + 11
    assert bitset.combine_bitpos(np.full((4,), -1, np.int32)) == -1


def test_bitpos_zero_ignores_chunk_padding():
    """bitpos(.., 0) must not report a hit inside the pad region appended
    to fill the last chunk (pad is filled with the non-matching value)."""
    bits = jnp.ones((10,), jnp.uint8)
    assert bitset.bitpos(bits, 0) == -1
    assert bitset.bitpos(bits, 1) == 0
