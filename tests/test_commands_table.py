"""The command table must match the backends exactly, both directions —
the executable analogue of the reference's static RedisCommands.java table
(VERDICT rows 8: 'op vocabulary still implicit string kinds')."""

import re

from redisson_tpu.commands import OP_TABLE, kinds_for_tier


def _ops_of(path: str) -> set:
    with open(path) as f:
        return set(re.findall(r"def _op_(\w+)\(", f.read()))


def test_engine_tier_complete():
    impl = _ops_of("redisson_tpu/structures/engine.py") | _ops_of(
        "redisson_tpu/structures/extended.py")
    table = kinds_for_tier("engine")
    impl |= {"keys"}  # keyspace scan is served by RoutingBackend/fan-out
    assert impl - table == set(), f"undocumented engine ops: {impl - table}"
    assert table - impl == set(), f"phantom engine ops: {table - impl}"


def test_tpu_tier_complete():
    impl = _ops_of("redisson_tpu/backend_tpu.py")
    table = kinds_for_tier("tpu")
    # delete/exists/flushall/keys route through RoutingBackend for sketches.
    impl |= {"keys"}
    assert impl - table == set(), f"undocumented tpu ops: {impl - table}"
    assert table - impl == set(), f"phantom tpu ops: {table - impl}"


def test_redis_tier_complete():
    impl = _ops_of("redisson_tpu/interop/backend_redis.py") | _ops_of(
        "redisson_tpu/interop/bloom_redis.py")
    table = kinds_for_tier("redis")
    assert impl - table == set(), f"undocumented redis ops: {impl - table}"
    assert table - impl == set(), f"phantom redis ops: {table - impl}"


def test_coord_tier_is_lua_objects():
    """Every coord-tier kind must have an engine implementation (the coord
    tier replaces the executor path with Lua objects in redis mode)."""
    engine = kinds_for_tier("engine")
    for k in kinds_for_tier("coord"):
        assert k in engine, k


def test_descriptor_sanity():
    assert len(OP_TABLE) >= 155
    for k, d in OP_TABLE.items():
        assert d.kind == k
        assert d.redis_name
        assert d.tiers
