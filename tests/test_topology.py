"""Failure detection + elastic recovery tests: topology manager, bank
growth, resharding on the virtual 8-device CPU mesh."""

import time

import numpy as np
import pytest

from redisson_tpu.parallel.topology import TopologyManager


class FlakyNode:
    def __init__(self):
        self.ok = True

    def ping(self):
        return self.ok


def test_freeze_after_failed_attempts_and_unfreeze():
    tm = TopologyManager(failed_attempts=3)
    node = FlakyNode()
    events = []
    tm.add_node("n1", node.ping)
    tm.add_listener(lambda e, i: events.append((e, i)))

    node.ok = False
    assert not tm.scan_once()  # 1st failure: still up
    assert not tm.scan_once()  # 2nd
    assert tm.is_up("n1")
    assert tm.scan_once()      # 3rd: freeze
    assert not tm.is_up("n1")
    assert events == [("node_down", "n1")]

    node.ok = True
    assert tm.scan_once()      # one success unfreezes
    assert tm.is_up("n1")
    assert events == [("node_down", "n1"), ("node_up", "n1")]


def test_transient_blip_does_not_freeze():
    tm = TopologyManager(failed_attempts=3)
    node = FlakyNode()
    tm.add_node("n1", node.ping)
    node.ok = False
    tm.scan_once()
    tm.scan_once()
    node.ok = True
    tm.scan_once()  # consecutive counter resets
    node.ok = False
    tm.scan_once()
    tm.scan_once()
    assert tm.is_up("n1")


def test_on_change_recovery_hook():
    tm = TopologyManager(failed_attempts=1)
    a, b = FlakyNode(), FlakyNode()
    tm.add_node("a", a.ping)
    tm.add_node("b", b.ping)
    seen = []
    tm.on_change(lambda live: seen.append(sorted(live)))
    b.ok = False
    tm.scan_once()
    assert seen == [["a"]]
    b.ok = True
    tm.scan_once()
    assert seen == [["a"], ["a", "b"]]


def test_background_scanner():
    tm = TopologyManager(scan_interval_s=0.02, failed_attempts=1)
    node = FlakyNode()
    tm.add_node("n", node.ping)
    tm.start()
    try:
        node.ok = False
        deadline = time.time() + 3
        while tm.is_up("n") and time.time() < deadline:
            time.sleep(0.02)
        assert not tm.is_up("n")
        assert tm.scans >= 1
    finally:
        tm.shutdown()


def test_exception_in_pinger_counts_as_failure():
    tm = TopologyManager(failed_attempts=1)

    def bad():
        raise RuntimeError("dead")

    tm.add_node("x", bad)
    tm.scan_once()
    assert not tm.is_up("x")


# ---------------------------------------------------------------------------
# Elastic bank: growth + resharding (8 virtual CPU devices via conftest)
# ---------------------------------------------------------------------------


@pytest.fixture()
def pod_client():
    from redisson_tpu.client import RedissonTPU
    from redisson_tpu.config import Config

    cfg = Config()
    pod = cfg.use_pod()
    pod.bank_capacity = 16  # tiny: force growth quickly
    c = RedissonTPU.create(cfg)
    yield c
    c.shutdown()


def test_bank_grows_instead_of_failing(pod_client):
    backend = pod_client._backend.sketch
    cap0 = backend.bank_capacity
    # Allocate more sketches than the initial capacity.
    for i in range(cap0 + 5):
        pod_client.get_hyper_log_log(f"grow:{i}").add_all([b"k%d" % i])
    assert backend.bank_capacity > cap0
    # Pre-growth rows kept their data.
    assert pod_client.get_hyper_log_log("grow:0").count() == 1


def test_reshard_preserves_sketches(pod_client):
    backend = pod_client._backend.sketch
    h = pod_client.get_hyper_log_log("rs:h")
    h.add_all([b"v%d" % i for i in range(10000)])
    est = h.count()
    ndev0 = backend.mesh.devices.size
    assert ndev0 >= 2
    backend.reshard(ndev0 // 2)  # "half the pod went away"
    assert backend.mesh.devices.size == ndev0 // 2
    assert pod_client.get_hyper_log_log("rs:h").count() == est
    backend.reshard(ndev0)  # nodes came back
    assert pod_client.get_hyper_log_log("rs:h").count() == est


def test_device_loss_carries_all_sharded_state(pod_client):
    """Failure-driven reshard (VERDICT r4 next #8): HLL bank + sharded
    bitset/bloom all survive a device loss, keep serving on the degraded
    mesh, and survive re-growth."""
    backend = pod_client._backend.sketch
    h = pod_client.get_hyper_log_log("dl:h")
    h.add_all([b"v%d" % i for i in range(5000)])
    est = h.count()
    bs = pod_client.get_bit_set("dl:bits")
    bs.set_bits(list(range(0, 9000, 3)))
    card = bs.cardinality()
    bf = pod_client.get_bloom_filter("dl:bloom")
    bf.try_init(1000, 0.01)
    keys = np.arange(700, dtype=np.uint64)
    bf.add_ints(keys)

    ndev0 = backend.mesh.devices.size
    backend.on_device_loss(ndev0 // 2)
    assert backend.mesh.devices.size == ndev0 // 2
    assert pod_client.get_hyper_log_log("dl:h").count() == est
    assert pod_client.get_bit_set("dl:bits").cardinality() == card
    assert pod_client.get_bloom_filter("dl:bloom").contains_count_ints(keys) == 700
    # still serving: writes land on the degraded mesh
    bs.set(9001)
    assert pod_client.get_bit_set("dl:bits").cardinality() == card + 1

    backend.reshard(ndev0)  # capacity returned
    assert pod_client.get_bit_set("dl:bits").cardinality() == card + 1
    assert pod_client.get_hyper_log_log("dl:h").count() == est
    assert pod_client.get_bloom_filter("dl:bloom").contains_count_ints(keys) == 700


def test_on_change_drives_pod_reshard(pod_client):
    """End-to-end node_down/node_up round-trip: the TopologyManager's
    on_change hook drives PodBackend.reshard — the failure-driven elastic
    path the cluster tier's quarantine-then-migrate mirrors."""
    backend = pod_client._backend.sketch
    ndev0 = backend.mesh.devices.size
    assert ndev0 >= 2
    nodes = {f"dev{i}": FlakyNode() for i in range(ndev0)}
    tm = TopologyManager(failed_attempts=1)
    for ident, n in nodes.items():
        tm.add_node(ident, n.ping)
    tm.on_change(lambda live: backend.reshard(max(1, len(live))))

    h = pod_client.get_hyper_log_log("oc:h")
    h.add_all([b"v%d" % i for i in range(5000)])
    est = h.count()

    # Half the nodes die: one scan fires node_down events + on_change,
    # which reshards the mesh down. State survives.
    for i in range(ndev0 // 2, ndev0):
        nodes[f"dev{i}"].ok = False
    assert tm.scan_once()
    assert backend.mesh.devices.size == ndev0 // 2
    assert pod_client.get_hyper_log_log("oc:h").count() == est

    # They come back: scan reshards up, state still intact.
    for n in nodes.values():
        n.ok = True
    assert tm.scan_once()
    assert backend.mesh.devices.size == ndev0
    assert pod_client.get_hyper_log_log("oc:h").count() == est


def test_client_topology_manager_facade():
    from redisson_tpu.client import RedissonTPU

    c = RedissonTPU.create()
    try:
        tm = c.get_topology_manager(scan_interval_s=0.1)
        assert tm.live_nodes()  # devices pre-registered
        assert not tm.scan_once()  # all healthy: no change
    finally:
        c.shutdown()
