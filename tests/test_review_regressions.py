"""Regression pins for review findings (oversized ops, BITOP no-source,
pod lifecycle, clear overloads, redis mode guard, flushall serialization,
pod changed contract)."""

import numpy as np
import pytest

from redisson_tpu.client import RedissonTPU
from redisson_tpu.config import Config


@pytest.fixture(scope="module")
def client():
    c = RedissonTPU.create(Config())
    yield c
    c.shutdown()


@pytest.fixture(scope="module")
def pod():
    c = RedissonTPU.create(Config.from_yaml("pod:\n  num_shards: 8\n  bank_capacity: 16\n"))
    yield c
    c.shutdown()


def test_single_op_larger_than_max_bucket(client, monkeypatch):
    # Shrink the chunk cap so the test stays fast while exercising the
    # multi-chunk path a 3M-key op would take.
    from redisson_tpu import engine

    monkeypatch.setattr(engine, "MAX_BUCKET", 1 << 12)
    hll = client.get_hyper_log_log("reg:bigop")
    n = (1 << 12) * 3 + 17  # 3+ chunks, ragged tail
    assert hll.add_ints(np.arange(n, dtype=np.uint64)) is True
    est = hll.count()
    assert abs(est - n) / n < 0.05


def test_bitop_or_with_missing_source_keeps_destination(client):
    a = client.get_bit_set("reg:bitop")
    a.set_bits([1, 2, 3])
    a.or_("reg:does-not-exist")
    assert np.flatnonzero(a.to_numpy()).tolist() == [1, 2, 3]
    a.xor("reg:also-missing")
    assert np.flatnonzero(a.to_numpy()).tolist() == [1, 2, 3]


def test_clear_single_bit_overload(client):
    bs = client.get_bit_set("reg:clear1")
    bs.set_bits([4, 5])
    bs.clear(4)
    assert bs.get(4) is False
    assert bs.get(5) is True


def test_redis_only_mode_unreachable_server_fails_fast():
    # redis mode is implemented now; with nothing listening the constructor
    # must surface a connection error, not hang or half-initialize.
    cfg = Config()
    cfg.use_redis().address = "redis://127.0.0.1:1"  # reserved port, closed
    cfg.redis.timeout_ms = 200
    cfg.redis.retry_attempts = 0
    with pytest.raises((ConnectionError, OSError)):
        RedissonTPU.create(cfg)


def test_pod_lifecycle_delete_exists_flush(pod):
    pod.flushall()
    h = pod.get_hyper_log_log("reg:pod:x")
    assert not h.is_exists()
    h.add_ints(np.arange(1000, dtype=np.uint64))
    assert h.is_exists()
    assert h.count() > 900
    assert h.delete() is True
    assert not h.is_exists()
    assert h.count() == 0
    # Deleted rows are reused: fill to capacity after a delete cycle.
    for i in range(16):
        pod.get_hyper_log_log(f"reg:pod:fill{i}").add("v")
    # Past capacity the bank grows elastically (no more "bank full").
    backend = pod._backend.sketch
    cap_before = backend.bank_capacity
    assert pod.get_hyper_log_log("reg:pod:overflow").add("v") is True
    assert backend.bank_capacity > cap_before
    pod.flushall()
    assert pod.get_hyper_log_log("reg:pod:after").add("v") is True


def test_pod_changed_contract(pod):
    pod.flushall()
    h = pod.get_hyper_log_log("reg:pod:chg")
    assert h.add("x") is True
    assert h.add("x") is False  # same key, no register raised
    assert h.add("y") is True


def test_flushall_serializes_with_inflight_ops(client):
    import threading

    stop = threading.Event()
    errors = []

    def writer():
        bs = client.get_bit_set("reg:flush:bs")
        i = 0
        while not stop.is_set():
            try:
                bs.set_bits([i % 100_000, 100_000 + i % 50_000])
            except Exception as e:  # any backend crash is a failure
                errors.append(e)
                return
            i += 1

    t = threading.Thread(target=writer)
    t.start()
    for _ in range(20):
        client.flushall()
    stop.set()
    t.join()
    assert not errors, errors


# ---- round-1 structures review pins ---------------------------------------


def test_pod_mode_bitset_bloom_route_to_sketch_tier(pod):
    """Pod mode serves bitset/bloom via the delegate; the router must not
    misroute them to the structure engine."""
    pod.flushall()
    bs = pod.get_bit_set("reg:pod:bs")
    bs.set(3)
    assert bs.get(3) is True
    assert bs.cardinality() == 1
    bf = pod.get_bloom_filter("reg:pod:bf")
    assert bf.try_init(1000, 0.01)
    bf.add("k")
    assert bf.contains("k")
    pod.flushall()


def test_fair_lock_abandoned_waiter_does_not_wedge(client):
    import time

    lk = client.get_fair_lock("reg:flk")
    lk.lock()

    import threading

    def failed_waiter():
        client.get_fair_lock("reg:flk").try_lock(wait_time_s=0.05)

    t = threading.Thread(target=failed_waiter)
    t.start()
    t.join(timeout=5)
    lk.unlock()
    # the abandoned waiter dequeued itself on timeout; lock is acquirable
    assert lk.try_lock(wait_time_s=1.0)
    lk.unlock()


def test_write_lock_not_downgraded_by_reentrant_read(client):
    import threading

    rw = client.get_read_write_lock("reg:rw")
    w = rw.write_lock()
    r = rw.read_lock()
    w.lock()
    r.lock()  # read-after-write is legal and must keep exclusion

    got = {}

    def other_reader():
        orr = client.get_read_write_lock("reg:rw").read_lock()
        got["ok"] = orr.try_lock(wait_time_s=0.1)

    t = threading.Thread(target=other_reader)
    t.start()
    t.join(timeout=5)
    assert got["ok"] is False  # still write-excluded
    r.unlock()
    w.unlock()


def test_shutdown_releases_blocked_take():
    import threading

    c = RedissonTPU.create(Config())
    q = c.get_blocking_queue("reg:bqshut")
    res = {}

    def taker():
        try:
            res["v"] = q.take()
        except RuntimeError as e:
            res["exc"] = str(e)

    t = threading.Thread(target=taker)
    t.start()
    import time

    time.sleep(0.1)
    c.shutdown()
    t.join(timeout=5)
    assert not t.is_alive()
    assert "exc" in res


def test_sorted_set_concurrent_adds_stay_sorted(client):
    import random
    import threading

    ss = client.get_sorted_set("reg:ss:conc")
    vals = list(range(120))
    random.shuffle(vals)
    chunks = [vals[i::4] for i in range(4)]

    def adder(chunk):
        s = client.get_sorted_set("reg:ss:conc")
        for v in chunk:
            s.add(v)

    threads = [threading.Thread(target=adder, args=(c,)) for c in chunks]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    out = ss.read_all()
    assert out == sorted(out)
    assert len(out) == 120


def test_lock_renew_does_not_resurrect_key(client):
    # watchdog renewal racing an unlock must not recreate the lock key
    client._executor.execute_sync("reg:ghostlock", "lock_renew", {"owner": "o", "lease_ms": 1000})
    assert "reg:ghostlock" not in client.keys("reg:ghost*")


def test_map_cache_delete_unschedules_sweep(client):
    mc = client.get_map_cache("reg:mc:del")
    assert "reg:mc:del" in client._eviction._timers
    mc.put("a", 1)
    mc.delete()
    assert "reg:mc:del" not in client._eviction._timers


def test_executor_shutdown_race_returns_failed_future():
    """A submission racing shutdown gets a failed future, not an exception
    raised into the submitting (possibly non-test) thread (VERDICT r2 weak
    #6). Ops queued before shutdown still drain."""
    from redisson_tpu.executor import CommandExecutor

    class Backend:
        def run(self, kind, target, ops):
            for op in ops:
                op.future.set_result(kind)

    ex = CommandExecutor(Backend())
    pre = ex.execute_async("t", "noop", None)
    ex.shutdown(wait=True)
    assert pre.result(timeout=5) == "noop"  # drained
    post = ex.execute_async("t", "noop", None)
    assert post.done()
    with pytest.raises(RuntimeError, match="shut down"):
        post.result()


def test_hll_add_empty_batch_returns_false(client):
    """Empty key batch: no chunks dispatch, changed must be False (review
    r3: functools.reduce over zero parts raised TypeError)."""
    import numpy as np

    h = client.get_hyper_log_log("regr:empty")
    assert h.add_ints(np.array([], dtype=np.uint64)) is False
    assert h.count() == 0


def test_multimap_cache_put_after_full_expiry(client):
    """Put into a multimap whose last key just expired must survive (review
    r3: reap-after-create dropped the re-registered KV, losing the write)."""
    import time

    mm = client.get_set_multimap_cache("regr:mmc")
    mm.put("k", "v")
    assert mm.expire_key("k", 0.03)
    time.sleep(0.06)
    assert mm.put("k", "new") is True
    assert mm.get_all("k") == {"new"}
    assert mm.contains_key("k") is True


def test_wire_tier_refuses_blocked_bloom(client):
    """A blocked-layout filter flushed from the TPU tier must be REFUSED by
    the wire tier, not silently mis-answered: the classic index walk over
    blocked-layout bits returns false negatives (advisor r3 medium)."""
    from redisson_tpu.interop.backend_redis import UnsupportedInRedisMode
    from redisson_tpu.interop.durability import DurabilityManager
    from redisson_tpu.interop.fake_server import EmbeddedRedis
    from redisson_tpu.interop.resp_client import SyncRespClient

    bf = client.get_bloom_filter("regr:blk")
    bf.try_init(2000, 0.01, blocked=True)
    bf.add_all([b"b%d" % i for i in range(200)])
    with EmbeddedRedis() as er:
        with SyncRespClient(port=er.port) as rc:
            DurabilityManager(
                client._store, rc, executor=client._executor,
                pod_backend=client._pod_backend()).flush(["regr:blk"])
        cfg = Config()
        cfg.use_redis().address = f"redis://127.0.0.1:{er.port}"
        rcli = RedissonTPU.create(cfg)
        try:
            wire_bf = rcli.get_bloom_filter("regr:blk")
            assert wire_bf.is_blocked() is True  # meta stays readable
            with pytest.raises(UnsupportedInRedisMode):
                wire_bf.contains(b"b0")
            with pytest.raises(UnsupportedInRedisMode):
                wire_bf.add(b"new")
            with pytest.raises(UnsupportedInRedisMode):
                wire_bf.count()
        finally:
            rcli.shutdown()


def test_wire_tier_device_packed_probe_clear_error():
    """contains_count_device_async in redis mode: a clear
    UnsupportedInRedisMode, not an opaque KeyError (advisor r3 low)."""
    from redisson_tpu.interop.backend_redis import UnsupportedInRedisMode
    from redisson_tpu.interop.fake_server import EmbeddedRedis

    with EmbeddedRedis() as er:
        cfg = Config()
        cfg.use_redis().address = f"redis://127.0.0.1:{er.port}"
        rcli = RedissonTPU.create(cfg)
        try:
            bf = rcli.get_bloom_filter("regr:dp")
            bf.try_init(1000, 0.01)
            fake_device_batch = np.zeros((4, 2), np.uint32)
            with pytest.raises(UnsupportedInRedisMode):
                bf.contains_count_device_async(fake_device_batch).result()
        finally:
            rcli.shutdown()


def test_multimap_legacy_raw_members_tolerated():
    """Multimap index members written before the hex-segment layout decode
    as raw bytes instead of raising ValueError (advisor r3 low)."""
    from redisson_tpu.interop.backend_redis import RedisBackend

    assert RedisBackend._mm_dec(b"6162") == b"ab"  # hex path
    assert RedisBackend._mm_dec(b"plain-legacy!") == b"plain-legacy!"
    assert RedisBackend._mm_dec(b"\xff\x00legacy") == b"\xff\x00legacy"


def test_pod_mode_wrongtype_cross_checks(pod):
    """Pod mode enforces the same HLL-vs-store keyspace rule as the
    single-chip tier (review r4: row_of never consulted the delegate store
    and the delegate's guard saw an empty row map)."""
    from redisson_tpu.store import WrongTypeError

    pod.get_bit_set("pw:bits").set(3)
    with pytest.raises(WrongTypeError):
        pod.get_hyper_log_log("pw:bits").add(b"x")
    pod.get_hyper_log_log("pw:hll").add(b"x")
    with pytest.raises(WrongTypeError):
        pod.get_bit_set("pw:hll").set(1)
    with pytest.raises(WrongTypeError):
        pod.get_bit_set("pw:dest").or_("pw:hll")


def test_keys_delete_async_many_names_no_deadlock(client):
    """delete_async over many names must not block inside a done-callback
    (advisor r4 high: the dispatcher thread ran the aggregate and waited on
    sibling futures only it could complete — permanent deadlock)."""
    names = [f"regr:da:{i}" for i in range(24)]
    for n in names[:12]:  # half exist, half don't
        client.get_bit_set(n).set(1)
    fut = client.get_keys().delete_async(*names)
    assert fut.result(timeout=10) == 12
    assert client.get_keys().delete_async() is None


def test_keys_delete_async_sibling_failure_resolves_aggregate():
    """The aggregate future resolves (with the exception) when one sibling
    delete fails — it must not hang or swallow the error."""
    from concurrent.futures import Future

    from redisson_tpu.models.keys import RKeys

    futs = {}

    class StubExecutor:
        def execute_async(self, name, kind, payload):
            f = Future()
            futs[name] = f
            return f

    agg = RKeys(StubExecutor(), None).delete_async("a", "b", "c")
    futs["a"].set_result(True)
    futs["c"].set_result(False)
    futs["b"].set_exception(RuntimeError("boom"))
    with pytest.raises(RuntimeError, match="boom"):
        agg.result(timeout=5)


def test_rename_missing_source_keeps_destination(client):
    """RENAME with a missing source must error and leave the destination
    intact (advisor r4 medium: the tpu tier wiped the destination before
    checking the source)."""
    hll = client.get_hyper_log_log("regr:rn:dest")
    hll.add_ints(np.arange(1000, dtype=np.uint64))
    before = hll.count()
    with pytest.raises(KeyError):
        client.get_hyper_log_log("regr:rn:missing").rename("regr:rn:dest")
    assert client.get_hyper_log_log("regr:rn:dest").count() == before


def test_renamenx_missing_source_raises(client):
    """RENAMENX errors on a missing source even when the destination exists
    (advisor r4 low: the NX check used to short-circuit to False)."""
    client.get_bit_set("regr:rnx:dest").set(5)
    with pytest.raises(KeyError):
        client.get_hyper_log_log("regr:rnx:missing").renamenx("regr:rnx:dest")


def test_pod_rename_missing_source_keeps_destination(pod):
    dest = pod.get_hyper_log_log("regr:prn:dest")
    dest.add_ints(np.arange(500, dtype=np.uint64))
    before = dest.count()
    with pytest.raises(KeyError):
        pod.get_hyper_log_log("regr:prn:missing").rename("regr:prn:dest")
    assert pod.get_hyper_log_log("regr:prn:dest").count() == before


def test_wire_bitset_length_bounded_scan():
    """bitset length over the wire: binary-searched BITCOUNT, and correct
    for all-zero / sparse / trailing-bit bitmaps (advisor r4 low: the old
    backwards GETRANGE scan pulled the whole string for all-zero maps)."""
    from redisson_tpu.interop.fake_server import EmbeddedRedis

    with EmbeddedRedis() as er:
        cfg = Config()
        cfg.use_redis().address = f"redis://127.0.0.1:{er.port}"
        rcli = RedissonTPU.create(cfg)
        try:
            bs = rcli.get_bit_set("regr:len")
            assert bs.length() == 0
            bs.set(0)
            assert bs.length() == 1
            bs.set(12345)
            assert bs.length() == 12346
            bs.clear(12345)
            assert bs.length() == 1
            bs.clear(0)
            assert bs.length() == 0  # zero-suffixed map, no full download
        finally:
            rcli.shutdown()


def test_geo_hash_missing_member_is_none(client):
    """GEOHASH returns a nil entry per missing member (advisor r4 low:
    missing members were silently dropped from the dict)."""
    geo = client.get_geo("regr:geo")
    geo.add(13.361389, 38.115556, "Palermo")
    out = geo.hash("Palermo", "Nowhere")
    assert out["Palermo"] == "sqc8b49rny0"
    assert out["Nowhere"] is None


def test_op_done_token_fields_written_under_lock(client, monkeypatch):
    """Tier C fix: _op_done used to write token.op_failed / token.fault_exc
    WITHOUT token.lock while completer threads raced each other; a lost
    update could drop the StateUncertainFault classification for the run.
    Hammer _op_done from many threads and require exact convergence: the
    failure flag set, the FIRST fault kept, and _run_completed fired once."""
    import threading
    from concurrent.futures import Future

    from redisson_tpu.executor import _InflightRun
    from redisson_tpu.fault.taxonomy import StateUncertainFault

    ex = client._executor
    for _ in range(20):
        token = _InflightRun("hll_add", "regr:tok", frozenset(["regr:tok"]),
                             False)
        n = 16
        token.pending = n
        completed = []
        monkeypatch.setattr(
            ex, "_run_completed", lambda t: completed.append(t))
        futs = []
        for i in range(n):
            f = Future()
            if i % 2:
                f.set_exception(
                    StateUncertainFault(f"boom {i}", seam="test"))
            else:
                f.set_result(None)
            futs.append(f)
        start = threading.Barrier(n)

        def one(f):
            start.wait()
            ex._op_done(token, f, None)

        threads = [threading.Thread(target=one, args=(f,)) for f in futs]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert token.pending == 0
        assert token.op_failed is True
        assert isinstance(token.fault_exc, StateUncertainFault)
        assert len(completed) == 1 and completed[0] is token


def test_journal_last_seq_final_after_fence(tmp_path):
    """Tier C fix: a duplicate lock-free `last_seq` property shadowed the
    locked one, so the post-fence promotion watermark raced in-flight
    appends. Race an appender against fence() and require the watermark
    read after fence() to be final and consistent with what was acked."""
    import threading

    from redisson_tpu.executor import Op
    from redisson_tpu.persist.journal import Journal

    j = Journal(str(tmp_path / "wal"), fsync="off")
    acked = []
    go = threading.Event()

    def appender():
        go.wait()
        i = 0
        while True:
            op = Op(target="regr:fence", kind="hll_add",
                    payload={"values": [i]}, nkeys=1)
            try:
                j.append_run("hll_add", [op])
            except RuntimeError:
                return  # fenced
            acked.append(i)
            i += 1

    t = threading.Thread(target=appender)
    t.start()
    go.set()
    while len(acked) < 50:  # let real contention build
        pass
    j.fence()
    w1 = j.last_seq
    t.join()
    w2 = j.last_seq
    assert w1 == w2, "post-fence watermark must be final"
    # every acked append is <= the watermark (nothing acked past the fence)
    assert len(acked) <= w1
    assert j.durable_seq <= j.last_seq
    j.close()


def test_linger_refill_does_not_strand_round_robin_entry():
    """PR 16 regression: the adaptive-linger loop in _collect_run_locked
    releases the executor lock on every cv.wait; each wakeup re-drains the
    queue to empty, and a submitter refilling it during the NEXT wait
    appends another copy of the target to the round-robin.  The old tail
    logic removed only ONE copy before deleting the queue, leaving a stale
    _ready entry whose queue was gone — the dispatcher's next pick died
    with KeyError and every pending future hung forever.

    Reproduction: serve-mode adaptive batching (linger on) with a single
    submitter steadily refilling a small set of hot targets.  Pre-fix this
    crashed the dispatcher within ~2000 ops."""
    cfg = Config()
    cfg.use_serve()
    c = RedissonTPU(cfg)
    try:
        drain_every = 128
        pending = []
        for i in range(4000):
            if i % 2 == 0:
                h = c.get_hyper_log_log(f"lr:hll{i % 8}")
                pending.append(h.add_all_async([f"v{i}", f"w{i}"]))
            else:
                b = c.get_bit_set(f"lr:bits{i % 4}")
                pending.append(b.set_bits_async([i % 512]))
            if len(pending) >= drain_every:
                for f in pending:
                    # A stranded round-robin entry kills the dispatcher and
                    # this times out instead of hanging the suite.
                    f.result(timeout=60)
                pending.clear()
        for f in pending:
            f.result(timeout=60)
        assert c.get_hyper_log_log("lr:hll0").count() > 0
    finally:
        c.shutdown()


def test_pool_fire_and_forget_close_holds_task_ref():
    # graftlint G016 fix (PR 17): _AsyncPool used to drop the
    # ensure_future(conn.close()) handle, so the GC could collect the task
    # mid-close and leak the socket. The pool now parks it in _bg_tasks
    # until the done-callback discards it.
    import asyncio
    import time

    from redisson_tpu.interop.fake_server import EmbeddedRedis
    from redisson_tpu.interop.pool import RespConnectionPool

    with EmbeddedRedis() as server:
        pool = RespConnectionPool(port=server.port, size=1, min_idle=1)
        pool.connect()
        try:
            ap = pool._pool
            # Dial a spare outside the rotation, then release it: with the
            # rotation already at size budget, _release_exclusive must take
            # the _close_later path.
            fut = asyncio.run_coroutine_threadsafe(
                ap._dial_one(register=False), pool._loop)
            conn = fut.result(5.0)
            assert conn.connected
            pool._loop.call_soon_threadsafe(ap._release_exclusive, conn)
            deadline = time.time() + 5
            while (conn.connected or ap._bg_tasks) and time.time() < deadline:
                time.sleep(0.01)
            assert not conn.connected, "spare connection never closed"
            assert ap._bg_tasks == set(), "close task not discarded when done"
            # ordinary traffic unaffected
            assert pool.execute("PING") == b"PONG"
        finally:
            pool.close()


def test_pool_close_drains_background_close_tasks():
    # Shutdown immediately after a fire-and-forget close: close() must
    # gather _bg_tasks rather than abandon them on a dying loop.
    import asyncio

    from redisson_tpu.interop.fake_server import EmbeddedRedis
    from redisson_tpu.interop.pool import RespConnectionPool

    with EmbeddedRedis() as server:
        pool = RespConnectionPool(port=server.port, size=1, min_idle=1)
        pool.connect()
        ap = pool._pool
        conn = asyncio.run_coroutine_threadsafe(
            ap._dial_one(register=False), pool._loop).result(5.0)
        pool._loop.call_soon_threadsafe(ap._release_exclusive, conn)
        pool.close()  # no wait: close() itself must drain the task
        assert ap._bg_tasks == set()
        assert not conn.connected


def test_pool_add_listener_marshals_to_io_thread():
    # graftlint G017 fix (PR 17): add_listener appended to the loop-confined
    # listener list straight from the caller's thread, racing _fire's
    # iteration on the IO loop. It now marshals via call_soon_threadsafe —
    # and the listener must still observe events end-to-end.
    import time

    from redisson_tpu.interop.fake_server import EmbeddedRedis
    from redisson_tpu.interop.pool import RespConnectionPool

    with EmbeddedRedis() as server:
        events = []
        pool = RespConnectionPool(port=server.port, size=2, min_idle=1)
        pool.add_listener(events.append)  # from this thread, pre-connect
        pool.connect()
        try:
            deadline = time.time() + 5
            while "connect" not in events and time.time() < deadline:
                time.sleep(0.01)
            assert "connect" in events
            # the registration itself landed on the loop-owned list
            assert events.append in pool._pool._listeners
        finally:
            pool.close()


# ---------------------------------------------------------------------------
# Tier E audit pins (PR 20): the contract drift the new tier surfaced was
# a family of tpu-tier ops with RESP analogues that the wire command
# table silently did not serve — bloom_*, bits_export/import, the
# hll_merge_count/hll_export composites, bitset_length/set_range, and
# RENAME. Each is now an *explicit* OpDescriptor contract escape
# (engine-only/internal with a reason) instead of an undeclared hole.
# These pins keep the declarations honest.
# ---------------------------------------------------------------------------

def test_every_tpu_kind_is_wire_served_or_escaped():
    # The G020 invariant, pinned independently of graftlint's own gate:
    # a new tpu-tier kind with a redis_name must either be staged by
    # wire/commands.py or carry a reasoned escape annotation.
    import re

    from redisson_tpu.commands import OP_TABLE
    from tools.graftlint.contracts import gather

    wire_kinds = gather()["wire_kinds"]
    escape = re.compile(r"^(engine-only|internal)\((.+)\)$", re.DOTALL)
    holes = []
    for kind, d in sorted(OP_TABLE.items()):
        if "tpu" not in d.tiers or d.redis_name == "-":
            continue
        if kind in wire_kinds:
            continue
        m = escape.match(d.contract or "")
        if m is None or not m.group(2).strip():
            holes.append(kind)
    assert holes == [], (
        f"tpu-tier kinds invisible to RESP clients with no declared "
        f"escape: {holes}")


def test_bloom_family_escape_is_declared():
    # The audit's concrete finding: the whole bloom surface (added PR 13)
    # never reached the wire table. It is engine-only by design — the
    # reference's RBloomFilter speaks a Lua-object protocol, not plain
    # commands — and that design decision must stay machine-readable.
    from redisson_tpu.commands import OP_TABLE

    for kind in ("bloom_init", "bloom_add", "bloom_contains",
                 "bloom_count", "bloom_meta"):
        assert OP_TABLE[kind].contract.startswith("engine-only("), kind

    # Transport-only kinds are internal, not engine-only: they have no
    # client surface at all (checkpoint / slot migration payloads).
    for kind in ("bits_export", "bits_import", "hll_import"):
        assert OP_TABLE[kind].contract.startswith("internal("), kind


def test_wire_table_extraction_sees_conditional_kinds():
    # SETBIT picks its kind at runtime (`"bitset_set" if value else
    # "bitset_clear"`); the audit's first extraction pass (staged-tuple
    # literals only) missed the clear arm and called bitset_clear a wire
    # hole. Pin the conditional-kind form staying visible.
    from tools.graftlint.contracts import gather

    wire_kinds = gather()["wire_kinds"]
    assert "bitset_set" in wire_kinds
    assert "bitset_clear" in wire_kinds


def test_foldable_kinds_all_coalesce():
    # The delta plane's foldable() dispatcher and the TPU backend's
    # COALESCE_GROUPS must agree, or a foldable kind dispatches one
    # device launch per op instead of riding the fused delta window.
    from redisson_tpu.backend_tpu import TpuBackend
    from redisson_tpu.commands import OP_TABLE
    from tools.graftlint.contracts import gather

    foldable = gather()["foldable_kinds"]
    assert foldable, "foldable() extraction came back empty"
    write_foldable = {k for k in foldable
                     if k in OP_TABLE and OP_TABLE[k].write}
    assert write_foldable <= set(TpuBackend.COALESCE_GROUPS), (
        write_foldable - set(TpuBackend.COALESCE_GROUPS))


def test_contract_witness_tags_replay_and_facade_surfaces(tmp_path):
    # End-to-end pin for the runtime half: the same kind lands in
    # different matrix cells depending on which seam dispatched it.
    from redisson_tpu import contractwitness as cw

    def make(jdir):
        cfg = Config()
        cfg.use_local()
        cfg.use_persist(str(jdir)).fsync = "always"
        return RedissonTPU.create(cfg)

    cw.arm(force=True)
    try:
        cw.contract_witness_reset()
        c = make(tmp_path)
        try:
            c.get_hyper_log_log("cwpin").add_all([b"a", b"b"])
        finally:
            c.shutdown()
        facade = cw.contract_snapshot()["cells"].get("facade", {})
        assert facade.get("hll_add", 0) >= 1

        cw.contract_witness_reset()
        c2 = make(tmp_path)
        try:
            assert c2.get_hyper_log_log("cwpin").count() == 2
        finally:
            c2.shutdown()
        cells = cw.contract_snapshot()["cells"]
        assert cells.get("replay", {}).get("hll_add", 0) >= 1, cells
    finally:
        cw.uninstall()
