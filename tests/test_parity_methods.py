"""Method-level API parity (VERDICT r3 item #3).

tools/gen_parity_methods.py extracts the reference's public method surface
(82 interfaces under /root/reference/.../core) and maps every method to this
framework. The matrix test fails on ANY unmapped method, and the freshness
test fails if PARITY_METHODS.md was not regenerated after an API change —
so the surface cannot silently drift. Functional tests below exercise the
methods this round added to close real gaps.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from redisson_tpu.client import RedissonTPU


@pytest.fixture()
def client():
    c = RedissonTPU.create()
    yield c
    c.shutdown()


def test_matrix_has_no_unmapped_methods():
    import gen_parity_methods as g

    rows = g.build_matrix()
    unmapped = [(i, m) for i, m, s, _ in rows if s == "UNMAPPED"]
    assert not unmapped, f"unmapped reference methods: {unmapped}"
    assert len(rows) > 500  # the extraction itself still works


def test_parity_methods_md_is_fresh():
    import gen_parity_methods as g

    rows = g.build_matrix()
    want = g.render(rows)
    path = os.path.join(os.path.dirname(__file__), "..", "PARITY_METHODS.md")
    assert open(path).read() == want, (
        "PARITY_METHODS.md is stale; run tools/gen_parity_methods.py --write")


# ---------------------------------------------------------------------------
# Functional coverage of the gap-filling methods
# ---------------------------------------------------------------------------


def test_lex_sorted_set_surface(client):
    z = client.get_lex_sorted_set("pm:lex")
    z.add_all(["a", "b", "c", "d"])
    assert z.rank("c") == 2
    assert z.rev_rank("c") == 1
    assert z.first() == "a" and z.last() == "d"
    assert z.range(1, 2) == ["b", "c"]
    assert z.value_range(0, -1) == ["a", "b", "c", "d"]
    assert z.range_head("b") == ["a", "b"]
    assert z.range_tail("c") == ["c", "d"]
    assert z.count_head("b") == 2 and z.count_tail("c") == 2
    assert z.lex_count_head("b") == 2 and z.lex_count_tail("c") == 2
    assert z.poll_first() == "a"
    assert z.poll_last() == "d"
    assert z.remove_range_by_lex(from_element="b", to_element="b") == 1
    assert z.read_all() == ["c"]
    z.add_all(["x", "y"])
    assert z.remove_range_head("x") == 2  # c, x
    assert z.remove_range_tail("y") == 1


def test_scored_sorted_set_surface(client):
    z = client.get_scored_sorted_set("pm:z")
    z.add_all([(1.0, "a"), (2.0, "b"), (3.0, "c")])
    assert not z.is_empty()
    assert z.to_array() == ["a", "b", "c"]
    assert z.contains_all(["a", "c"]) and not z.contains_all(["a", "zz"])
    assert z.value_range_reversed(0, 0) == ["c"]
    assert z.entry_range_reversed(0, 0) == [("c", 3.0)]
    assert z.retain_all(["a", "b"]) is True
    assert z.to_array() == ["a", "b"]
    assert z.retain_all(["a", "b"]) is False
    assert z.clear() is True
    assert z.is_empty()


def test_map_surface(client):
    m = client.get_map("pm:map")
    m.put_all({"a": 1, "b": 2, "c": 3})
    assert m.fast_put_if_absent("d", 4) is True
    assert m.fast_put_if_absent("d", 9) is False
    assert m.read_all_key_set() == {"a", "b", "c", "d"}
    assert sorted(m.read_all_values()) == [1, 2, 3, 4]
    assert dict(m.read_all_entry_set())["b"] == 2
    assert set(m.key_iterator()) == {"a", "b", "c", "d"}
    assert sorted(m.value_iterator()) == [1, 2, 3, 4]
    assert dict(m.entry_iterator())["c"] == 3
    assert m.filter_keys(lambda k: k in ("a", "b")) == {"a": 1, "b": 2}
    assert m.filter_values(lambda v: v > 2) == {"c": 3, "d": 4}
    assert m.filter_entries(lambda k, v: k == "a" or v == 4) == {"a": 1, "d": 4}


def test_multimap_surface(client):
    mm = client.get_set_multimap("pm:mm")
    assert mm.is_empty()
    mm.put_all("k", [1, 2])
    mm.put("j", 9)
    assert not mm.is_empty()
    assert set(mm.get("k")) == {1, 2}
    assert sorted(mm.values()) == [1, 2, 9]
    old = mm.replace_values("k", [7])
    assert set(old) == {1, 2}
    assert set(mm.get_all("k")) == {7}
    assert mm.fast_remove("k", "nope") == 1
    assert mm.clear() is True
    assert mm.is_empty()


def test_list_surface(client):
    lst = client.get_list("pm:list")
    lst.add_all(["a", "c", "d"])
    assert lst.add_before("c", "b") == 4
    assert lst.add_after("d", "e") == 5
    assert lst.read_all() == ["a", "b", "c", "d", "e"]
    assert lst.add_after("missing", "x") == -1
    assert lst.sub_list(1, 4) == ["b", "c", "d"]
    assert lst.sub_list(2, 2) == []
    lst.fast_remove(0, 2)  # drop 'a' and 'c'
    assert lst.read_all() == ["b", "d", "e"]


def test_deque_surface(client):
    d = client.get_deque("pm:dq")
    d.add_all(["x", "y", "x", "z"])
    assert d.get_last() == "z"
    assert d.remove_first() == "x"
    assert d.remove_last() == "z"
    assert d.remove_first_occurrence("x") is True
    assert d.read_all() == ["y"]
    assert d.remove_last_occurrence("nope") is False
    d.add_all(["q", "y"])
    assert d.remove_last_occurrence("y") is True
    assert d.read_all() == ["y", "q"]
    with pytest.raises(IndexError):
        client.get_deque("pm:empty").remove_first()


def test_blocking_poll_from_any(client):
    import threading
    import time

    q1 = client.get_blocking_queue("pm:q1")
    q2 = client.get_blocking_queue("pm:q2")
    q2.offer("from-q2")
    assert q1.poll_from_any(0.2, "pm:q2") == "from-q2"
    # nothing anywhere -> None at deadline
    t0 = time.time()
    assert q1.poll_from_any(0.15, "pm:q2") is None
    assert time.time() - t0 >= 0.1
    # a late push on the OTHER queue is picked up while blocked
    def feed():
        time.sleep(0.15)
        q2.offer("late")
    threading.Thread(target=feed, daemon=True).start()
    assert q1.poll_from_any(3.0, "pm:q2") == "late"
    # deque variants
    dq = client.get_blocking_deque("pm:dq2")
    dq.put_first("h")
    dq.put_last("t")
    assert dq.poll_last_from_any(0.2) == "t"
    assert dq.poll_first_from_any(0.2) == "h"


def test_bitset_export_surface(client):
    bs = client.get_bit_set("pm:bits")
    for i in (0, 3, 9):
        bs.set(i)
    assert bs.as_bit_set() == {0, 3, 9}
    raw = bs.to_byte_array()
    assert np.unpackbits(np.frombuffer(raw, np.uint8))[:10].tolist() == [
        1, 0, 0, 1, 0, 0, 0, 0, 0, 1]


def test_atomic_double_surface(client):
    d = client.get_atomic_double("pm:ad")
    d.set(5.0)
    assert d.get_and_increment() == 5.0
    assert d.get() == 6.0
    assert d.get_and_decrement() == 6.0
    assert d.get() == 5.0


def test_object_rename_surface(client):
    b = client.get_bucket("pm:old")
    b.set("v")
    b.rename("pm:new")
    assert b.get_name() == "pm:new"
    assert client.get_bucket("pm:new").get() == "v"
    assert not client.get_bucket("pm:old").is_exists()
    other = client.get_bucket("pm:other")
    other.set("w")
    assert other.renamenx("pm:new") is False  # destination exists
    assert other.renamenx("pm:fresh") is True
    assert client.get_bucket("pm:fresh").get() == "w"


def test_keys_slot_and_pattern(client):
    from redisson_tpu.ops import crc16

    keys = client.get_keys()
    assert keys.get_slot("foo") == crc16.key_slot("foo")
    assert keys.get_slot("{user}.a") == keys.get_slot("{user}.b")
    client.get_bucket("pm:pat:1").set(1)
    client.get_bucket("pm:pat:2").set(2)
    assert set(keys.find_keys_by_pattern("pm:pat:*")) == {
        "pm:pat:1", "pm:pat:2"}


def test_geo_hash(client):
    g = client.get_geo("pm:geo")
    g.add(13.361389, 38.115556, "Palermo")
    g.add(15.087269, 37.502669, "Catania")
    h = g.hash("Palermo", "Catania")
    # canonical Redis GEOHASH values for these coordinates
    assert h["Palermo"] == "sqc8b49rny0"
    assert h["Catania"] == "sqdtr74hyu0"


def test_semaphore_set_permits(client):
    s = client.get_semaphore("pm:sem")
    s.try_set_permits(2)
    s.set_permits(5)
    assert s.available_permits() == 5
    s.set_permits(1)
    assert s.available_permits() == 1


def test_buckets_find(client):
    client.get_bucket("pm:bf:1").set("a")
    client.get_bucket("pm:bf:2").set("b")
    found = client.get_buckets().find("pm:bf:*")
    assert {b.name for b in found} == {"pm:bf:1", "pm:bf:2"}
    assert sorted(b.get() for b in found) == ["a", "b"]


def test_batch_new_getters(client):
    batch = client.create_batch()
    batch.get_map_cache("pm:bmc").put_async("k", "v")
    batch.get_set_cache("pm:bsc").add_async("m")
    batch.get_blocking_queue("pm:bq").offer_async("x")
    batch.execute()
    assert client.get_map_cache("pm:bmc").get("k") == "v"
    assert client.get_set_cache("pm:bsc").contains("m")
    assert client.get_blocking_queue("pm:bq").poll() == "x"


def test_sortedset_try_set_comparator(client):
    ss = client.get_sorted_set("pm:ss")
    assert ss.try_set_comparator(lambda v: -ord(v)) is True  # empty: ok
    ss.add("a")
    ss.add("c")
    ss.add("b")
    assert ss.read_all() == ["c", "b", "a"]  # descending per comparator
    assert ss.try_set_comparator(None) is False  # non-empty: refused


def test_remote_invocation_options_surface():
    from redisson_tpu.services.remote import RemoteInvocationOptions

    o = RemoteInvocationOptions.defaults()
    assert o.is_ack_expected() and o.is_result_expected()
    o2 = o.expect_ack_within(0.5).expect_result_within(2.0)
    assert o2.get_ack_timeout_in_millis() == 500
    assert o2.get_execution_timeout_in_millis() == 2000
    assert o.no_ack().is_ack_expected() is False
    assert o.no_result().is_result_expected() is False


def test_nodes_group_surface(client):
    ng = client.get_nodes_group()
    nodes = ng.nodes()
    assert nodes and all(n.get_type() in ("device", "redis") for n in nodes)
    assert all(isinstance(n.get_addr(), str) for n in nodes)
    assert all(n.info()["alive"] in (True, False) for n in nodes)
    calls = []
    fn = lambda e, i: calls.append((e, i))  # noqa: E731
    ng.add_connection_listener(fn)
    ng.fire("connect", "x")
    ng.remove_connection_listener(fn)
    ng.fire("disconnect", "x")
    assert calls == [("connect", "x")]


def test_rename_tpu_tier_objects(client):
    """rename/renamenx work for sketch-tier objects too (review r4: the
    rename op only existed in the structure engine, so renaming a bitset
    or HLL raised KeyError)."""
    bs = client.get_bit_set("pm:rn:bits")
    bs.set_bits([3, 5])
    bs.rename("pm:rn:bits2")
    assert client.get_bit_set("pm:rn:bits2").cardinality() == 2
    assert not client.get_bit_set("pm:rn:bits").is_exists()
    h = client.get_hyper_log_log("pm:rn:h")
    h.add_all([b"a", b"b", b"c"])
    h.rename("pm:rn:h2")
    assert client.get_hyper_log_log("pm:rn:h2").count() == 3
    # RENAME overwrites a destination held by the OTHER tier
    client.get_bucket("pm:rn:x").set("structval")
    client.get_hyper_log_log("pm:rn:h2").rename("pm:rn:x")
    assert client.get_hyper_log_log("pm:rn:x").count() == 3
    # renamenx refuses an occupied destination in either tier
    h3 = client.get_hyper_log_log("pm:rn:h3")
    h3.add(b"z")
    assert h3.renamenx("pm:rn:x") is False
    assert h3.get_name() == "pm:rn:h3"


def test_fast_put_if_absent_none_value(client):
    """A stored None value counts as present (review r4: the decoded-value
    check reported True and the caller believed the write happened)."""
    m = client.get_map("pm:fpia")
    m.put("k", None)
    assert m.fast_put_if_absent("k", "x") is False
    assert m.get("k") is None


def test_poll_from_any_zero_timeout_takes_available(client):
    """timeout must not skip the first sweep: an available element is
    returned even when the deadline math would already have expired
    (review r4)."""
    q = client.get_blocking_queue("pm:pfa0")
    q.offer("hello")
    assert q.poll_from_any(0.001, "pm:pfa0-other") == "hello"


def test_geo_hash_matches_redis_exactly(client):
    """Last geohash character too (review r4: Redis zero-pads 52 bits to
    55; full subdivision differed in the 11th char)."""
    g = client.get_geo("pm:geoh")
    g.add(13.361389, 38.115556, "Palermo")
    g.add(15.087269, 37.502669, "Catania")
    h = g.hash("Palermo", "Catania")
    # canonical `GEOHASH Sicily` outputs from the Redis docs
    assert h["Palermo"] == "sqc8b49rny0"
    assert h["Catania"] == "sqdtr74hyu0"


def test_batch_get_keys_stages_deletes(client):
    client.get_bucket("pm:bk:1").set("a")
    client.get_bucket("pm:bk:2").set("b")
    batch = client.create_batch()
    f = batch.get_keys().delete_async("pm:bk:1", "pm:bk:2")
    batch.execute()
    assert f.result() == 2
    assert not client.get_bucket("pm:bk:1").is_exists()


def test_auto_rows_invoke(client):
    """Every auto-mapped row is CALLED with type-appropriate args against a
    live client (VERDICT r4 weak #3: hasattr parity proved surface, not
    function — a property that raised on call still counted). A call
    passes when the method binds and executes; business-logic exceptions
    (KeyError on a missing rename source, etc.) prove the wiring works.
    AttributeError / NotImplementedError / signature-mismatch TypeError
    fail. Skips carry explicit reasons in SMOKE_SKIP (rendered into the
    matrix); they must stay under 10% of the auto surface."""
    import inspect

    import gen_parity_methods as g

    rows = g.build_matrix()
    auto = {mapping for _, _, s, mapping in rows if s == "auto"}
    factories = g.smoke_factories(client)
    invoked, skipped, failures = 0, 0, []
    for mapping in sorted(auto):
        cls_name, meth = mapping.split(".", 1)
        if mapping in g.SMOKE_SKIP:
            skipped += 1
            continue
        assert cls_name in factories, f"no smoke factory for {cls_name}"
        obj = factories[cls_name]()
        fn = getattr(obj, meth)  # AttributeError here = broken row
        if not callable(fn):
            invoked += 1  # property: reading it IS the invocation
            continue
        sig = inspect.signature(fn)
        args, kwargs = g.smoke_args(cls_name, meth, sig)
        sig.bind(*args, **kwargs)
        try:
            fn(*args, **kwargs)
        except (AttributeError, NotImplementedError) as e:
            failures.append((mapping, repr(e)))
            continue
        except TypeError as e:
            if "argument" in str(e) or "positional" in str(e):
                failures.append((mapping, repr(e)))
                continue
        except Exception:
            pass  # business-logic error: callable and wired
        invoked += 1
    assert not failures, failures
    assert invoked / (invoked + skipped) >= 0.90, (invoked, skipped)
