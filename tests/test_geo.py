"""geo/ — active-active geo-replication over the persist journal.

Layers:

1. Wiring — geo requires persist (the journal IS the transport), config
   round-trip, kind-set contract against OP_TABLE.
2. Convergence — two sites converge to bit-identical sketch state
   through the FUSED delta path (geo_planes > 0, geo_classic == 0), and
   the link ships fewer bytes than the raw journal payloads.
3. Destructive LWW — DEL wins when newer, loses (with add-wins
   resurrection) when older; rename, bitset_clear, flushall all settle
   to the same state everywhere. These pin the documented tombstone
   contract (geo/__init__.py).
4. Repair — geo_link partition + heal, whole-site kill + rejoin on the
   same dir, and journal-gap snapshot fallback after segment GC.
5. Chaos property test — seeded concurrent writers on both sites with a
   partition and a site restart mid-run; final digests are bit-identical
   across sites, equal to a single-site oracle fed the union of acked
   semilattice writes, and histcheck's geo verdict is clean.
"""

import os
import threading
import time

import numpy as np
import pytest

from redisson_tpu.client import RedissonTPU
from redisson_tpu.commands import OP_TABLE
from redisson_tpu.config import Config
from redisson_tpu.fault import inject
from redisson_tpu.fault.inject import FaultInjector, FaultPlan, FaultRule
from redisson_tpu.geo import (DESTRUCTIVE_KINDS, NEG_STAMP, SEMILATTICE_KINDS,
                              SHIP_KINDS, connect_sites, converge, stamp_of)
from tools.histcheck import check_geo


def make_site(root, sid):
    cfg = Config()
    cfg.use_local()
    cfg.use_persist(os.path.join(str(root), sid)).fsync = "always"
    g = cfg.use_geo(sid)
    g.poll_interval_s = 0.005
    g.anti_entropy_interval_s = 0.05
    return RedissonTPU.create(cfg)


@pytest.fixture
def pair(tmp_path):
    a, b = make_site(tmp_path, "A"), make_site(tmp_path, "B")
    connect_sites([a, b])
    sites = [a, b]
    yield sites
    inject.uninstall()
    for c in sites:
        try:
            c.shutdown()
        except Exception:
            pass


def _partition(*targets, times=10_000):
    """Drop every geo_link tick toward the named peer site ids."""
    inject.install(FaultInjector(FaultPlan(rules=[
        FaultRule(seam="geo_link", target=t, nth=1, times=times)
        for t in targets])))


def _digest(client, keys):
    """Opaque per-key state digest (type tag + raw cells) via the same
    export the links ship — what histcheck compares across sites."""
    out = {}
    for k in keys:
        ex = client.geo._export(k)
        if ex is None:
            out[k] = None
        else:
            otype, cells, _meta = ex
            out[k] = (str(otype), np.asarray(cells, np.uint8).tobytes())
    return out


# ---------------------------------------------------------------------------
# 1. wiring
# ---------------------------------------------------------------------------

def test_geo_requires_persist():
    cfg = Config()
    cfg.use_local()
    cfg.use_geo("lonely")
    with pytest.raises(ValueError, match="persist"):
        RedissonTPU.create(cfg)


def test_geo_config_roundtrip():
    cfg = Config.from_dict({
        "geo": {"site_id": "eu-west", "poll_interval_s": 0.5,
                "batch_records": 128, "anti_entropy_interval_s": 2.0},
    })
    assert cfg.geo is not None
    assert cfg.geo.site_id == "eu-west"
    assert cfg.geo.poll_interval_s == 0.5
    assert cfg.geo.batch_records == 128
    assert cfg.geo.anti_entropy_interval_s == 2.0


def test_ship_kind_sets_against_op_table():
    # Every shipped kind is a real write op; the semilattice set is
    # exactly the sketch joins, and the geo_* apply kinds exist as
    # journaled write ops (so crash replay covers remote applies).
    for kind in SHIP_KINDS:
        assert OP_TABLE[kind].write, kind
    assert SEMILATTICE_KINDS == {"hll_add", "bloom_add", "bitset_set"}
    assert "bitset_clear" in DESTRUCTIVE_KINDS  # SETBIT 0 is NOT a join
    for kind in ("geo_merge", "geo_replace", "geo_delete", "geo_flush"):
        assert OP_TABLE[kind].write, kind
        assert kind not in SHIP_KINDS  # echo-loop cut
    assert stamp_of([3, "A"]) == (3, "A") > NEG_STAMP


def test_site_id_collision_rejected(tmp_path):
    a = make_site(tmp_path, "A")
    b = make_site(os.path.join(tmp_path, "other"), "A")
    try:
        with pytest.raises(ValueError, match="collides"):
            a.geo.connect(b.geo)
    finally:
        a.shutdown()
        b.shutdown()


# ---------------------------------------------------------------------------
# 2. convergence through the fused path
# ---------------------------------------------------------------------------

def test_two_sites_converge_bit_identical(pair):
    a, b = pair
    a.get_hyper_log_log("h").add_all([f"a{i}" for i in range(800)])
    b.get_hyper_log_log("h").add_all([f"b{i}" for i in range(800)])
    a.get_bit_set("bits").set_bits(range(0, 400, 3))
    b.get_bit_set("bits").set_bits(range(1, 400, 3))
    fa = a.get_bloom_filter("blm")
    fa.try_init(10_000, 0.01)
    fa.add_all([f"x{i}" for i in range(200)])
    fb = b.get_bloom_filter("blm")
    fb.try_init(10_000, 0.01)
    fb.add_all([f"y{i}" for i in range(200)])

    assert converge(pair, 30), "two-site mesh never settled"
    assert a.get_hyper_log_log("h").count() == b.get_hyper_log_log("h").count()
    want = len(set(range(0, 400, 3)) | set(range(1, 400, 3)))
    assert a.get_bit_set("bits").cardinality() == want
    assert b.get_bit_set("bits").cardinality() == want
    assert all(fb.contains(f"x{i}") for i in range(200))
    assert all(fa.contains(f"y{i}") for i in range(200))

    keys = ["h", "bits", "blm"]
    da, db = _digest(a, keys), _digest(b, keys)
    assert da == db, "converged sites must be bit-identical"

    # Remote applies landed through the fused delta_merge_stack path,
    # never the per-op classic fallback.
    for c in pair:
        sk = c._routing.sketch
        assert sk.counters["geo_planes"] > 0
        assert sk.counters["geo_classic"] == 0

    # The folded/sparse wire encoding beats shipping raw journal payloads.
    for c in pair:
        for link in c.geo.links.values():
            assert 0 < link.stats["link_bytes"] < link.stats["raw_bytes"]


def test_info_replication_and_staleness(pair):
    a, b = pair
    a.get_bit_set("k").set_bits([1, 2, 3])
    assert converge(pair, 30)
    rep = a.info()["replication"]
    assert rep["role"] == "active"
    assert rep["site_id"] == "A"
    assert rep["version_vector"]["A"] == a.geo.journal_last_seq()
    peer = rep["peers"]["B"]
    assert peer["acked_seq"] == a.geo.journal_last_seq()
    assert peer["lag_records"] == 0
    for field in ("lag_seconds", "link_bytes", "raw_bytes",
                  "partitions", "repairs"):
        assert field in peer
    st = a.geo.staleness()
    assert set(st) == {"B"} and st["B"] >= 0.0
    # B's view mirrors it.
    assert b.info()["replication"]["peers"]["A"]["acked_seq"] == \
        b.geo.journal_last_seq()


def test_wire_info_replication_section(tmp_path):
    """Stock `redis-cli INFO replication` observes the geo fleet: the wire
    front-end renders client.info()'s replication section verbatim."""
    from redisson_tpu.interop.resp_client import SyncRespClient

    cfg = Config()
    cfg.use_local()
    cfg.use_persist(os.path.join(str(tmp_path), "A")).fsync = "always"
    g = cfg.use_geo("A")
    g.poll_interval_s = 0.005
    cfg.use_serve()
    cfg.use_wire()
    a = make_site(tmp_path, "B")
    c = RedissonTPU.create(cfg)
    try:
        connect_sites([a, c])
        c.get_bit_set("wk").set_bits([1, 2])
        assert converge([a, c], 30)
        cli = SyncRespClient("127.0.0.1", c.wire.port, retry_attempts=1)
        try:
            text = cli.execute("INFO", "replication")
            if isinstance(text, bytes):
                text = text.decode()
        finally:
            cli.close()
        assert "# replication" in text
        assert "role:active" in text
        assert "site_id:A" in text
        assert "version_vector" in text
        assert "peers_B_acked_seq" in text or "acked_seq" in text
    finally:
        c.shutdown()
        a.shutdown()


# ---------------------------------------------------------------------------
# 3. destructive LWW contract
# ---------------------------------------------------------------------------

def test_delete_wins_when_newer(pair):
    a, b = pair
    ha = a.get_hyper_log_log("h")
    ha.add_all(["x1", "x2", "x3"])
    assert converge(pair, 30)
    a.get_keys().delete("h")  # delete stamp > every write stamp
    assert converge(pair, 30)
    assert a.get_hyper_log_log("h").count() == 0
    assert b.get_hyper_log_log("h").count() == 0
    assert _digest(a, ["h"]) == _digest(b, ["h"]) == {"h": None}


def test_delete_loses_to_newer_write_resurrects(pair):
    a, b = pair
    # Pump B's journal so its stamps outrun A's.
    pump = b.get_bit_set("pump")
    for i in range(20):
        pump.set_bits([i])
    a.get_hyper_log_log("h").add_all(["x1", "x2"])
    assert converge(pair, 30)

    _partition("A", "B")
    b.get_hyper_log_log("h").add_all(["y1", "y2", "y3"])  # high stamp
    a.get_keys().delete("h")                              # low stamp: loses
    time.sleep(0.05)
    inject.uninstall()
    assert converge(pair, 30)

    # Add-wins: the older delete is suppressed, B re-ships full state and
    # A resurrects the key with all five elements.
    ca, cb = a.get_hyper_log_log("h").count(), b.get_hyper_log_log("h").count()
    assert ca == cb == 5, (ca, cb)
    assert (a.geo.applier.resurrections + b.geo.applier.resurrections) >= 1
    assert (a.geo.applier.suppressed + b.geo.applier.suppressed) >= 1


def test_rename_replicates_as_delete_plus_replace(pair):
    a, b = pair
    src = a.get_bit_set("src")
    src.set_bits([1, 5, 9])
    assert converge(pair, 30)
    src.rename("dst")
    assert converge(pair, 30)
    assert b.get_bit_set("dst").cardinality() == 3
    assert b.get_bit_set("src").cardinality() == 0
    assert _digest(a, ["src", "dst"]) == _digest(b, ["src", "dst"])


def test_bitset_clear_is_lww_replace(pair):
    a, b = pair
    ba = a.get_bit_set("c")
    ba.set_bits(range(10))
    assert converge(pair, 30)
    ba.clear_bits([3, 4])
    assert converge(pair, 30)
    assert b.get_bit_set("c").cardinality() == 8
    assert _digest(a, ["c"]) == _digest(b, ["c"])


def test_flushall_replicates(pair):
    a, b = pair
    a.get_hyper_log_log("h").add_all(["x1", "x2"])
    b.get_bit_set("bits").set_bits(range(16))
    assert converge(pair, 30)
    a.get_keys().flushall()
    assert converge(pair, 30)
    assert b.get_hyper_log_log("h").count() == 0
    assert b.get_bit_set("bits").cardinality() == 0
    assert a.get_bit_set("bits").cardinality() == 0


def test_flushall_loses_to_newer_write_resurrects(pair):
    """A flush whose stamp is older than a concurrent write at another
    site wipes the key at the flushing site but not at the peer — the
    peer must re-ship the survivor (same add-wins rule as DEL) or the
    mesh diverges."""
    a, b = pair
    pump = b.get_bit_set("pump")
    for i in range(25):
        pump.set_bits([i])           # push B's stamps ahead of A's
    a.get_hyper_log_log("old").add_all(["o1", "o2"])
    assert converge(pair, 30)

    _partition("A", "B")
    b.get_hyper_log_log("survivor").add_all(["s1", "s2", "s3"])  # high stamp
    a.get_keys().flushall()                                      # low stamp
    time.sleep(0.05)
    inject.uninstall()
    assert converge(pair, 30)

    # "survivor" beat the flush on the LWW order: resurrected at A.
    assert a.get_hyper_log_log("survivor").count() == 3
    assert b.get_hyper_log_log("survivor").count() == 3
    # "old" predates the flush everywhere: wiped at both sites.
    assert a.get_hyper_log_log("old").count() == 0
    assert b.get_hyper_log_log("old").count() == 0
    keys = ["survivor", "old", "pump"]
    assert _digest(a, keys) == _digest(b, keys)


# ---------------------------------------------------------------------------
# 4. repair paths
# ---------------------------------------------------------------------------

def test_partition_heal_converges(pair):
    a, b = pair
    _partition("B", times=200)
    a.get_hyper_log_log("h").add_all([f"p{i}" for i in range(400)])
    b.get_hyper_log_log("h").add_all([f"q{i}" for i in range(400)])
    time.sleep(0.1)  # let the partition bite
    inject.uninstall()
    assert converge(pair, 30), "no convergence after heal"
    assert a.get_hyper_log_log("h").count() == b.get_hyper_log_log("h").count()
    assert a.geo.links["B"].stats["partitions"] > 0


def test_site_kill_and_rejoin(pair, tmp_path):
    a, b = pair
    ha = a.get_hyper_log_log("h")
    ha.add_all([f"r{i}" for i in range(300)])
    assert converge(pair, 30)
    b.shutdown()
    ha.add_all([f"s{i}" for i in range(300)])  # writes while B is down
    b2 = make_site(tmp_path, "B")  # same dir: journal + sidecar recovery
    pair[1] = b2
    connect_sites([a, b2])
    assert converge([a, b2], 30), "no convergence after rejoin"
    c1 = a.get_hyper_log_log("h").count()
    c2 = b2.get_hyper_log_log("h").count()
    assert c1 == c2
    assert _digest(a, ["h"]) == _digest(b2, ["h"])


def test_journal_gap_snapshot_repair(pair):
    a, b = pair
    ha = a.get_hyper_log_log("h")
    ha.add_all(["seed1", "seed2"])
    assert converge(pair, 30)

    _partition("B")
    ha.add_all([f"z{i}" for i in range(200)])
    # GC the journal segments B still needs: the link must fall back to
    # a full snapshot repair instead of replaying the (gone) suffix.
    a.snapshot_now()
    j = a._executor.journal
    j.rotate()
    j.remove_segments_below(j.last_seq)
    inject.uninstall()
    assert converge(pair, 30), "no convergence after gap repair"
    assert a.geo.links["B"].stats["gaps"] >= 1, "snapshot path not exercised"
    assert ha.count() == b.get_hyper_log_log("h").count()
    assert _digest(a, ["h"]) == _digest(b, ["h"])


# ---------------------------------------------------------------------------
# 5. seeded chaos property test
# ---------------------------------------------------------------------------

def test_two_site_chaos_convergence(pair, tmp_path):
    """Concurrent writers on both sites + geo_link partition + whole-site
    kill/rejoin; afterwards every acked semilattice write is visible at
    every site, digests are bit-identical and equal to a single-site
    oracle fed the union of the writes, and histcheck's geo verdict is
    clean. The DEL key pins the tombstone half of the contract."""
    a, b = pair
    rng = np.random.default_rng(0xC0FFEE)
    keys = ["chaos:h", "chaos:bits"]
    writes = {"A": [], "B": []}            # acked semilattice writes
    reads = {"A": [], "B": []}             # (tenant, key, measure, epoch)

    site_seeds = {sid: rng.integers(lo, lo + 1_000_000, size=120)
                  for sid, lo in (("A", 0), ("B", 1 << 20))}

    def writer(client, sid):
        hll = client.get_hyper_log_log("chaos:h")
        bits = client.get_bit_set("chaos:bits")
        for i, s in enumerate(site_seeds[sid]):
            vals = [f"{sid}:{s}:{j}" for j in range(5)]
            hll.add_all(vals)              # sync: acked once it returns
            writes[sid].append(("hll", vals))
            idx = [int(s) % 2048 + j for j in range(4)]
            bits.set_bits(idx)
            writes[sid].append(("bits", idx))
            if i % 10 == 0:
                reads[sid].append(
                    (sid, "chaos:bits", bits.cardinality(), 0))

    t1 = threading.Thread(target=writer, args=(a, "A"))
    t2 = threading.Thread(target=writer, args=(b, "B"))
    t1.start(); t2.start()
    time.sleep(0.05)
    _partition("B", times=40)              # transient one-way partition
    t1.join(); t2.join()
    inject.uninstall()

    # DEL tombstone contract, concurrently with replication of the rest:
    # a newer delete of a settled key stays deleted everywhere.
    a.get_bit_set("chaos:del").set_bits([1, 2, 3])
    assert converge(pair, 30)
    a.get_keys().delete("chaos:del")

    # Whole-site kill + rejoin mid-stream.
    b.shutdown()
    a.get_hyper_log_log("chaos:h").add_all(["post-kill-1", "post-kill-2"])
    writes["A"].append(("hll", ["post-kill-1", "post-kill-2"]))
    b2 = make_site(tmp_path, "B")
    pair[1] = b2
    connect_sites([a, b2])
    assert converge([a, b2], 60), "chaos mesh never settled"

    # Oracle: one fresh site fed the union of every acked write.
    oracle = make_site(tmp_path, "oracle")
    try:
        oh = oracle.get_hyper_log_log("chaos:h")
        ob = oracle.get_bit_set("chaos:bits")
        for site in ("A", "B"):
            for kind, payload in writes[site]:
                if kind == "hll":
                    oh.add_all(payload)
                else:
                    ob.set_bits(payload)
        digests = {"A": _digest(a, keys), "B": _digest(b2, keys),
                   "oracle": _digest(oracle, keys)}
        # The deleted key must be gone at both real sites.
        for sid, client in (("A", a), ("B", b2)):
            digests[sid]["chaos:del"] = _digest(client, ["chaos:del"])[
                "chaos:del"]
            assert digests[sid]["chaos:del"] is None, sid
        digests["oracle"]["chaos:del"] = None
        verdict = check_geo(digests, acked_keys=keys, site_reads=reads)
        assert verdict.ok, verdict.summary() + "\n" + "\n".join(verdict.issues)
        assert verdict.keys_checked == 3
        assert verdict.reads_checked > 0
    finally:
        oracle.shutdown()

    # All remote applies took the fused path.
    for c in (a, b2):
        sk = c._routing.sketch
        assert sk.counters["geo_planes"] > 0
        assert sk.counters["geo_classic"] == 0
