"""Mesh data plane (PR 19): N logical shards served by ONE engine stack
over a device mesh (`data_plane="mesh"`), instead of N Python engine
stacks (`data_plane="stacks"`).

Coverage:

1. collective kernels — shard_map/pmax PFMERGE, count and occupancy over
   a mesh-sharded bank are bit-identical to a host-fold oracle and to the
   single-device stacks kernels;
2. mode parity — a randomized mixed-kind multi-shard workload produces
   bit-identical per-op results AND raw register/cell state between
   data_plane="stacks" and data_plane="mesh";
3. live migration — slots move between logical shards in mesh mode under
   concurrent writers with ZERO lost acks (tools/histcheck verdict), and
   bank rows relocate device-side with their counts preserved;
4. mesh cache — repeated reshards onto an unchanged device set reuse the
   cached Mesh (no rebuild per call: the topology on_change fix);
5. churn + memstat — randomized create/delete/migrate churn on the mesh
   bank keeps the per-(shard, kind) ledger rollups exact (zero drift).
"""

import random
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from redisson_tpu import engine
from redisson_tpu.client import RedissonTPU
from redisson_tpu.config import Config
from redisson_tpu.ops import hll
from redisson_tpu.ops.crc16 import key_slot
from redisson_tpu.parallel import mesh as mesh_mod
from tools import histcheck


# ---------------------------------------------------------------------------
# 1. collective kernels vs host-fold oracle


def _mesh_bank(capacity=64, num_shards=4, seed=3):
    mesh = mesh_mod.get_mesh(axis=mesh_mod.SLOT_AXIS)
    sb = mesh_mod.ShardedBank(mesh, capacity, num_shards)
    host = np.random.default_rng(seed).integers(
        0, 52, size=(sb.capacity, hll.M), dtype=np.int32)
    return mesh, sb, host


def test_collective_merge_matches_host_fold_oracle():
    mesh, sb, host = _mesh_bank()
    rows = [3, 17, 33, 60, 9]  # span several device blocks; includes target
    target = 9
    bank = sb.place(jnp.asarray(host))
    got = np.asarray(engine.hll_bank_merge_rows_collective(
        bank, jnp.asarray(rows, jnp.int32), jnp.int32(target), mesh=mesh))
    oracle = host.copy()
    oracle[target] = host[rows].max(axis=0)
    assert (got == oracle).all()


def test_collective_merge_count_matches_stacks_kernel():
    mesh, sb, host = _mesh_bank(seed=5)
    rows = [0, 21, 42, 63, 11]
    target = 11
    bank = sb.place(jnp.asarray(host))
    new_bank, cnt = engine.hll_bank_merge_count_rows_collective(
        bank, jnp.asarray(rows, jnp.int32), jnp.int32(target), mesh=mesh)
    # Stacks oracle: the same merge+count through the single-device kernel.
    dev = jax.devices("cpu")[0]
    sbank = jax.device_put(host, dev)
    sbank2, scnt = engine.hll_bank_merge_count_rows(
        sbank, jnp.asarray(rows, jnp.int32), jnp.int32(target))
    assert (np.asarray(new_bank) == np.asarray(sbank2)).all()
    assert int(cnt) == int(scnt)


def test_collective_count_and_occupancy_match_oracles():
    mesh, sb, host = _mesh_bank(seed=7)
    rows = [1, 30, 55]
    bank = sb.place(jnp.asarray(host))
    cnt = int(engine.hll_bank_count_rows_collective(
        bank, jnp.asarray(rows, jnp.int32), mesh=mesh))
    dev = jax.devices("cpu")[0]
    scnt = int(engine.hll_bank_count_rows(
        jax.device_put(host, dev), jnp.asarray(rows, jnp.int32)))
    assert cnt == scnt

    # occupancy: zero a few rows, count the non-empty remainder
    host2 = host.copy()
    host2[5] = 0
    host2[40] = 0
    occ = int(engine.hll_bank_occupancy_collective(
        sb.place(jnp.asarray(host2)), mesh=mesh))
    assert occ == int(np.sum(np.any(host2 != 0, axis=1)))


# ---------------------------------------------------------------------------
# 2. mode parity: randomized mixed-kind multi-shard windows


def _mesh_cluster(tmp_path, data_plane, sub="cl"):
    cfg = Config()
    cfg.use_cluster(num_shards=4, dir=str(tmp_path / f"{sub}-{data_plane}"),
                    data_plane=data_plane)
    return RedissonTPU.create(cfg)


def _mixed_workload(c, n_vals=300, seed=0xA11CE):
    """Deterministic randomized mixed-kind workload across all shards;
    returns the per-op result list."""
    rng = random.Random(seed)
    results = []
    f = c.get_bloom_filter("tm:bloom")
    f.try_init(expected_insertions=20_000, false_probability=0.01)
    for rnd in range(2):
        for i in range(6):
            h = c.get_hyper_log_log(f"tm:h{i}")
            h.add_all([b"r%d:%d:%d" % (rnd, i, rng.randrange(1 << 40))
                       for _ in range(n_vals)])
            results.append(("pfcount", i, h.count()))
        for i in range(4):
            bs = c.get_bit_set(f"tm:b{i}")
            bs.set_bits([rng.randrange(1 << 14) for _ in range(32)])
            results.append(("bitcount", i, int(bs.cardinality())))
        added = f.add_all([b"f%d:%d" % (rnd, rng.randrange(1 << 30))
                           for _ in range(100)])
        results.append(("bfadd", rnd, int(np.sum(added))))
    # cross-shard merges exercise the collective path in mesh mode
    results.append(("pfmerge", 0,
                    c.get_hyper_log_log("tm:h0").merge_with_and_count(
                        "tm:h1", "tm:h2")))
    results.append(("pfcountw", 0,
                    c.get_hyper_log_log("tm:h3").count_with("tm:h4")))
    return results


def _state_digest(c):
    """Raw observable state through the facade: HLL registers + bit cells."""
    router = c.cluster.router
    out = {}
    for i in range(6):
        name = f"tm:h{i}"
        exported = router.execute_sync(name, "hll_export", None)
        out[name] = np.asarray(exported[0]).tobytes()
    for name in [f"tm:b{i}" for i in range(4)] + ["tm:bloom"]:
        exported = router.execute_sync(name, "bits_export", None)
        out[name] = np.asarray(exported[1]).tobytes()
    return out


def test_mode_parity_randomized_multi_shard_windows(tmp_path):
    c = _mesh_cluster(tmp_path, "stacks")
    try:
        res_stacks = _mixed_workload(c)
        dig_stacks = _state_digest(c)
    finally:
        c.shutdown()
    c = _mesh_cluster(tmp_path, "mesh")
    try:
        res_mesh = _mixed_workload(c)
        dig_mesh = _state_digest(c)
        backend = c.cluster.mesh_client._routing.sketch
        assert backend.counters["collective_merges"] >= 1
    finally:
        c.shutdown()
    assert res_stacks == res_mesh
    assert dig_stacks == dig_mesh


# ---------------------------------------------------------------------------
# 3. live migration in mesh mode: zero lost acks + device-side row moves


def test_mesh_live_migration_zero_lost_acks(tmp_path):
    c = _mesh_cluster(tmp_path, "mesh", sub="mig")
    try:
        mgr = c.cluster
        table = mgr.router.slot_table()

        # keys pinned to shard 0 so one migration covers them all
        keys, i = [], 0
        while len(keys) < 12:
            k = f"mg{i}"
            if table[key_slot(k)] == 0:
                keys.append(k)
            i += 1
        hll_keys, i = [], 0
        while len(hll_keys) < 2:
            k = f"mh{i}"
            if table[key_slot(k)] == 0:
                hll_keys.append(k)
            i += 1
        for k in keys:
            c.get_bucket(k).set("v0")
        counts_before = {}
        for k in hll_keys:
            h = c.get_hyper_log_log(k)
            h.add_all([b"%s:%d" % (k.encode(), v) for v in range(500)])
            counts_before[k] = h.count()
        move = sorted({key_slot(k) for k in keys + hll_keys})

        rec = histcheck.HistoryRecorder()
        stop = threading.Event()
        # Two writers over DISJOINT key halves (one writer per key, so
        # per-key ack order is real-time order); logical seqs — lost-ack
        # checking needs order only.
        def writer(tenant, my_keys):
            rng = random.Random(hash(tenant) & 0xFFFF)
            seq = 0
            n = 0
            while not stop.is_set():
                k = my_keys[n % len(my_keys)]
                v = f"{tenant}:{n}"
                try:
                    c.get_bucket(k).set(v)
                    seq += 1
                    rec.record_write(tenant, k, v, acked_seq=seq)
                except Exception:  # noqa: BLE001 — fate unknown under the fence
                    rec.record_write_unknown(tenant, k, v)
                n += 1

        threads = [
            threading.Thread(target=writer, args=("wa", keys[:6]),
                             daemon=True),
            threading.Thread(target=writer, args=("wb", keys[6:]),
                             daemon=True),
        ]
        for t in threads:
            t.start()
        try:
            stats = mgr.migrate_slots(move, 2, timeout_s=120)
        finally:
            stop.set()
            for t in threads:
                t.join(10)

        post = mgr.router.slot_table()
        assert all(post[s] == 2 for s in move)
        # device-side bank-row relocation carried the HLL rows
        assert stats.get("bank_rows_relocated", 0) >= len(hll_keys)
        for k in hll_keys:
            assert c.get_hyper_log_log(k).count() == counts_before[k]

        final = {k: c.get_bucket(k).get() for k in keys}
        v = histcheck.check(rec, final_state=final)
        assert rec.acked_count() > 0
        assert v.ok, v.issues
        assert v.lost_acks == 0
    finally:
        c.shutdown()


# ---------------------------------------------------------------------------
# 4. mesh cache: reshard onto an unchanged device set never rebuilds


def test_mesh_cache_pinned_across_repeated_reshards():
    m1 = mesh_mod.get_mesh(4)
    s0 = mesh_mod.mesh_cache_stats()
    assert mesh_mod.get_mesh(4) is m1
    s1 = mesh_mod.mesh_cache_stats()
    assert s1["builds"] == s0["builds"]
    assert s1["hits"] == s0["hits"] + 1

    cfg = Config()
    pod_cfg = cfg.use_pod()
    pod_cfg.bank_capacity = 16
    pod = RedissonTPU.create(cfg)
    try:
        backend = pod._pod_backend()
        assert backend is not None
        h = pod.get_hyper_log_log("mc:h")
        h.add_all([b"v%d" % i for i in range(50)])
        before = h.count()
        ndev = int(backend.mesh.devices.size)
        builds0 = mesh_mod.mesh_cache_stats()["builds"]
        for _ in range(5):
            # topology on_change with an UNCHANGED device set: cached Mesh,
            # zero rebuilds (the recompile-hazard fix this test pins)
            backend.reshard(ndev)
        assert mesh_mod.mesh_cache_stats()["builds"] == builds0
        assert backend.mesh is mesh_mod.get_mesh(ndev)
        assert h.count() == before  # state survived the reshards
    finally:
        pod.shutdown()


# ---------------------------------------------------------------------------
# 5. churn property: mesh bank accounting stays exact


def test_mesh_bank_churn_memstat_exact(tmp_path):
    c = _mesh_cluster(tmp_path, "mesh", sub="churn")
    try:
        mgr = c.cluster
        mc = mgr.mesh_client
        rng = random.Random(0xBEEF)
        live = set()
        for step in range(40):
            roll = rng.random()
            if roll < 0.5:
                name = "ch:h%d" % rng.randrange(10)
                c.get_hyper_log_log(name).add(b"v%d" % step)
                live.add(name)
            elif roll < 0.7:
                name = "ch:b%d" % rng.randrange(4)
                c.get_bit_set(name).set(rng.randrange(2048))
            elif live:
                name = live.pop()
                c.delete(name)
            if step % 10 == 9:
                v = mc.memory_verify()
                assert v["ok"], (step, v)
        # migration-driven relocation churns row placement too
        table = mgr.router.slot_table()
        move = sorted({key_slot(n) for n in live
                       if table[key_slot(n)] != 1})[:8]
        if move:
            mgr.migrate_slots(move, 1, timeout_s=120)
        v = mc.memory_verify()
        assert v["ok"] and v["drift_bytes"] == 0, v
        # per-shard rollups sum exactly to the bank allocation
        acct = mc.memstat
        st = mc.memory_stats()
        assert st["bank.bytes"] == acct.bank_bytes()
        backend = mc._routing.sketch
        assert acct.bank_bytes() == int(backend.bank.nbytes)
    finally:
        c.shutdown()
