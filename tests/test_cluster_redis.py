"""Cluster-mode redis tier: CLUSTER NODES bootstrap, slot-table routing,
topology rescan (failover + live slot migration), per-owner pipelines.

Reference shapes: `cluster/ClusterConnectionManager.java:64-117` (bootstrap
parse), `:265-341` (scheduled topology check), `:429-541` (failover / slot
migration diffs), `:543-558` (CRC16 routing); parse format per
`ClusterNodeInfo.java`. The reference never CI-tests a real cluster (its
cluster tests are @Test-disabled, SURVEY §4) — these run against N
in-process fake masters sharing a ClusterState.
"""

from __future__ import annotations

import time

import pytest

from redisson_tpu.client import RedissonTPU
from redisson_tpu.config import Config
from redisson_tpu.interop.fake_server import ClusterFixture
from redisson_tpu.interop.pool import RespConnectionPool
from redisson_tpu.interop.topology_redis import (
    ClusterRouter, ClusterTopologyManager, parse_cluster_nodes)
from redisson_tpu.ops import crc16


def _factory(host: str, port: int) -> RespConnectionPool:
    return RespConnectionPool(
        host=host, port=port, timeout=5.0, retry_attempts=2,
        retry_interval=0.05, size=2, min_idle=1, failed_attempts=10,
        reconnection_timeout=0.3)


@pytest.fixture()
def cluster():
    with ClusterFixture(n_masters=3) as cf:
        yield cf


def _router(cf, scan_interval_s=0.0):
    r = ClusterRouter(_factory, cf.addresses)
    mgr = ClusterTopologyManager(r, scan_interval_s=scan_interval_s)
    mgr.bootstrap()
    return r, mgr


def _key_for_slot_range(cf, addr):
    """A key whose slot lands in `addr`'s range (probe k0, k1, ...)."""
    for i in range(10000):
        k = f"k{i}"
        if cf.state.owner_of(crc16.key_slot(k)) == addr:
            return k
    raise AssertionError("no key found for range")


def test_parse_cluster_nodes_reference_format():
    text = (
        "07c37dfeb235213a872192d90877d0cd55635b91 127.0.0.1:30004@31004 "
        "slave e7d1eecce10fd6bb5eb35b9f99a514335d9ba9ca 0 1426238317239 4 connected\n"
        "67ed2db8d677e59ec4a4cefb06858cf2a1a89fa1 127.0.0.1:30002 "
        "master - 0 1426238316232 2 connected 5461-10922\n"
        "e7d1eecce10fd6bb5eb35b9f99a514335d9ba9ca 127.0.0.1:30001 "
        "myself,master - 0 0 1 connected 0-5460 15495 [15495->-importing]\n"
        "6ec23923021cf3ffec47632106199cb7f496ce01 127.0.0.1:30005 "
        "slave 67ed2db8d677e59ec4a4cefb06858cf2a1a89fa1 0 1426238316232 5 connected\n"
        "dead0000000000000000000000000000deadbeef 127.0.0.1:30009 "
        "master,fail - 0 1426238317741 9 connected 10923-16383\n"
    )
    parts = parse_cluster_nodes(text)
    by_master = {p["master"]: p for p in parts}
    assert set(by_master) == {"127.0.0.1:30001", "127.0.0.1:30002"}
    assert by_master["127.0.0.1:30001"]["ranges"] == [(0, 5460), (15495, 15495)]
    assert by_master["127.0.0.1:30001"]["slaves"] == ["127.0.0.1:30004"]
    assert by_master["127.0.0.1:30002"]["slaves"] == ["127.0.0.1:30005"]


def test_bootstrap_routes_by_slot_without_redirects(cluster):
    router, mgr = _router(cluster)
    try:
        # One key per shard; each must land on its owner directly.
        for addr in cluster.addresses:
            k = _key_for_slot_range(cluster, addr)
            router.execute("SET", k, f"v@{addr}")
            assert cluster.server_for(addr).data.get(k.encode()) == \
                f"v@{addr}".encode()
        assert router.redirects == 0  # slot table made every hop direct
        assert router.topology_applied == 1
    finally:
        mgr.close()
        router.close()


def test_moved_updates_between_scans(cluster):
    router, mgr = _router(cluster)
    try:
        a0, a1 = cluster.addresses[0], cluster.addresses[1]
        k = _key_for_slot_range(cluster, a0)
        slot = crc16.key_slot(k)
        router.execute("SET", k, "before")
        # Migrate the slot; the stale table entry now draws a MOVED, which
        # the router follows and caches (CommandAsyncService.java:657-685).
        cluster.state.move_slots(slot, slot, a1)
        router.execute("SET", k, "after")
        assert router.redirects == 1
        assert cluster.server_for(a1).data.get(k.encode()) == b"after"
        # Cached: the next hit is direct.
        router.execute("SET", k, "again")
        assert router.redirects == 1
    finally:
        mgr.close()
        router.close()


def test_rescan_applies_slot_migration(cluster):
    router, mgr = _router(cluster, scan_interval_s=0.05)
    try:
        a0, a2 = cluster.addresses[0], cluster.addresses[2]
        k = _key_for_slot_range(cluster, a0)
        slot = crc16.key_slot(k)
        cluster.state.move_slots(slot, slot, a2)
        deadline = time.time() + 5
        while mgr.changes == 0 and time.time() < deadline:
            time.sleep(0.02)
        assert mgr.changes >= 1, "rescan never observed the migration"
        router.execute("SET", k, "v")
        assert cluster.server_for(a2).data.get(k.encode()) == b"v"
        assert router.redirects == 0  # learned from the scan, not a MOVED
    finally:
        mgr.close()
        router.close()


def test_rescan_follows_failover(cluster):
    router, mgr = _router(cluster, scan_interval_s=0.05)
    try:
        a0 = cluster.addresses[0]
        replica = cluster.add_replica(a0)
        k = _key_for_slot_range(cluster, a0)
        router.execute("SET", k, "v1")
        assert cluster.server_for(replica).data.get(k.encode()) == b"v1"

        cluster.state.fail_over(a0, replica)
        deadline = time.time() + 5
        while mgr.changes == 0 and time.time() < deadline:
            time.sleep(0.02)
        assert mgr.changes >= 1
        router.execute("SET", k, "v2")
        assert cluster.server_for(replica).data.get(k.encode()) == b"v2"
    finally:
        mgr.close()
        router.close()


def test_pipeline_splits_per_owner(cluster):
    router, mgr = _router(cluster)
    try:
        keys = [_key_for_slot_range(cluster, a) for a in cluster.addresses]
        cmds = [("SET", k, f"pv{i}") for i, k in enumerate(keys)]
        cmds.append(("GET", keys[0]))
        out = router.pipeline(cmds)
        assert out[3] == b"pv0"  # reassembled in submission order
        for i, (k, addr) in enumerate(zip(keys, cluster.addresses)):
            assert cluster.server_for(addr).data.get(k.encode()) == \
                f"pv{i}".encode()
    finally:
        mgr.close()
        router.close()


def test_client_end_to_end_over_cluster(cluster):
    cfg = Config()
    r = cfg.use_redis()
    r.cluster_addresses = list(cluster.addresses)
    r.cluster_scan_interval_ms = 0  # bootstrap only
    c = RedissonTPU.create(cfg)
    try:
        # Buckets hash across all three shards; everything must route.
        for i in range(30):
            c.get_bucket(f"cb:{i}").set({"i": i})
        for i in range(30):
            assert c.get_bucket(f"cb:{i}").get() == {"i": i}
        # Data actually spread over the shards (not all on one node).
        counts = [len(cluster.server_for(a).data) for a in cluster.addresses]
        assert sum(1 for n in counts if n > 0) >= 2, counts
        # A structure object with Lua-free ops works cross-slot too.
        al = c.get_atomic_long("cb:ctr")
        assert al.increment_and_get() == 1
    finally:
        c.shutdown()


def test_bootstrap_survives_dead_seed(cluster):
    dead = "127.0.0.1:1"  # nothing listens there
    router = ClusterRouter(_factory, [dead] + list(cluster.addresses))
    mgr = ClusterTopologyManager(router)
    try:
        mgr.bootstrap()  # rotates past the dead seed
        assert router.topology_applied == 1
    finally:
        mgr.close()
        router.close()


def test_pipeline_per_command_moved_is_resent(cluster):
    """A stale slot-table entry surfaces as a per-command MOVED inside a
    pipeline reply; the router must resend that command to the owner
    (CommandBatchService.java:184-293) instead of raising it to the caller."""
    router, mgr = _router(cluster)
    try:
        a0, a1 = cluster.addresses[0], cluster.addresses[1]
        k = _key_for_slot_range(cluster, a0)
        slot = crc16.key_slot(k)
        cluster.state.move_slots(slot, slot, a1)  # table now stale
        out = router.pipeline([("SET", k, "pv"), ("GET", k)])
        assert out[0] == b"OK" or out[0] is True or out[0] == "OK", out
        assert out[1] == b"pv"
        assert cluster.server_for(a1).data.get(k.encode()) == b"pv"
        assert router.redirects >= 1
    finally:
        mgr.close()
        router.close()


def test_single_owner_pipeline_goes_direct(cluster):
    """A one-owner pipeline must hit that owner, not masters[0] — sending
    it to the wrong master turns every command into a MOVED resend."""
    router, mgr = _router(cluster)
    try:
        addr = cluster.addresses[2]
        k = _key_for_slot_range(cluster, addr)
        out = router.pipeline([("SET", k, "a"), ("APPEND", k, "b"),
                               ("GET", k)])
        assert out[2] == b"ab"
        assert router.redirects == 0
    finally:
        mgr.close()
        router.close()


def test_create_against_non_cluster_does_not_leak(cluster):
    import threading

    from redisson_tpu.interop.fake_server import EmbeddedRedis

    with EmbeddedRedis() as plain:  # CLUSTER support disabled on this one
        cfg = Config()
        r = cfg.use_redis()
        r.cluster_addresses = [f"127.0.0.1:{plain.port}"]
        before = {t.name for t in threading.enumerate()}
        with pytest.raises(Exception):
            RedissonTPU.create(cfg)
        import time as _t

        deadline = _t.time() + 3
        while _t.time() < deadline:
            leaked = {t.name for t in threading.enumerate()} - before
            if not any("pool" in n or "cluster" in n for n in leaked):
                break
            _t.sleep(0.05)
        leaked = {t.name for t in threading.enumerate()} - before
        assert not any("pool" in n or "cluster" in n for n in leaked), leaked


def test_pipeline_redirected_command_error_stays_in_reply_list(cluster):
    """A MOVED resend that then fails with a genuine error (WRONGTYPE) must
    land in the reply list, not raise away the other commands' results."""
    from redisson_tpu.native import RespError

    router, mgr = _router(cluster)
    try:
        a0, a1 = cluster.addresses[0], cluster.addresses[1]
        k = _key_for_slot_range(cluster, a0)
        slot = crc16.key_slot(k)
        router.execute("SET", k, "str")       # k holds a string on a0
        cluster.state.move_slots(slot, slot, a1)
        router.execute("SET", k, "str")       # follow MOVED; now on a1 too
        # Stale-table pipeline: LPUSH draws MOVED, resend hits WRONGTYPE.
        k2 = _key_for_slot_range(cluster, a1)
        cluster.state.move_slots(crc16.key_slot(k2), crc16.key_slot(k2), a0)
        out = router.pipeline([("SET", k2, "x"), ("LPUSH", k, "v")])
        assert not isinstance(out[0], RespError), out
        assert isinstance(out[1], RespError)
        assert "WRONGTYPE" in str(out[1]).upper() or "wrong" in str(out[1]).lower()
    finally:
        mgr.close()
        router.close()


def test_failed_seed_dial_does_not_leak_pool_thread(cluster):
    import threading

    router = ClusterRouter(_factory, ["127.0.0.1:1"] + list(cluster.addresses))
    mgr = ClusterTopologyManager(router)
    try:
        before = {t for t in threading.enumerate()}
        mgr.bootstrap()  # dials the dead seed first; must reclaim its pool
        time.sleep(0.2)
        leaked = [t.name for t in set(threading.enumerate()) - before
                  if "pool" in t.name.lower()]
        # exactly the live pools' threads may exist; the dead seed's not
        assert len(leaked) <= len(cluster.addresses) + 1, leaked
    finally:
        mgr.close()
        router.close()


def test_weighted_balancer_normalizes_url_forms():
    from redisson_tpu.interop.topology_redis import WeightedRoundRobinBalancer

    b = WeightedRoundRobinBalancer({"redis://h1:6379": 3}, 1)
    picks = [b.choose(["h1:6379", "h2:6379"]) for _ in range(40)]
    assert picks.count("h1:6379") == 30


def test_coordination_pubsub_follows_cluster_topology(cluster):
    """VERDICT r4 item #5b: the coordination subscribe connection follows
    cluster topology — after the node it was dialed to fails over, lock
    wake-ups still arrive via a re-dial to the router's current master
    (the reference migrates pub/sub listeners on any topology change,
    MasterSlaveEntry.java:158-250)."""
    import threading

    cfg = Config()
    r = cfg.use_redis()
    r.cluster_addresses = list(cluster.addresses)
    r.cluster_scan_interval_ms = 50
    r.timeout_ms = 1000
    c = RedissonTPU.create(cfg)
    try:
        # Bring the coordination pub/sub up (lock wake-ups ride it).
        lock = c.get_lock("cl:lk")
        lock.lock()
        lock.unlock()
        # The pubsub is attached to the router's master — fail that node
        # over to a fresh replica.
        a0 = c._resp.master_address
        replica = cluster.add_replica(a0)
        cluster.state.fail_over(a0, replica)
        cluster.server_for(a0)  # still addressable; now kill it outright
        for er in cluster.embedded:
            if f"127.0.0.1:{er.port}" == a0:
                er.kill()
        deadline = time.time() + 10
        while time.time() < deadline and c._resp.master_address == a0:
            time.sleep(0.05)
        assert c._resp.master_address != a0
        # Cross-thread lock handoff needs the wake-up channel: thread B
        # blocks on lock() until thread A unlocks — delivered over the
        # re-dialed subscribe connection.
        lock2 = c.get_lock("cl:lk2")
        lock2.lock()
        got = threading.Event()

        def contender():
            lk = c.get_lock("cl:lk2")
            if lk.try_lock(wait_time_s=10):
                got.set()
                lk.unlock()

        t = threading.Thread(target=contender, daemon=True)
        t.start()
        time.sleep(0.3)
        lock2.unlock()
        assert got.wait(10), "lock wake-up lost after cluster failover"
    finally:
        c.shutdown()
