"""replica/ — read-replica fleet: bounded-staleness routing, RYW pins,
PSYNC partial/full resync accounting, and automatic failover.

Layers:

1. READ_KINDS derivation — the routable read set comes from OP_TABLE, not
   a hand list; parked blocking kinds stay pinned to the primary.
2. Config plumbing — replicas section round-trips; replicas without
   persist is a construction-time error.
3. Routing — reads land on caught-up replicas, fall back to the primary
   when the staleness bound can't be met, and read-your-writes pins a
   tenant above its acked seq.
4. Failover — the highest-watermark replica is promoted with zero acked
   writes lost; survivors retarget (partial or full resync); the demoted
   slot rejoins; WAIT semantics via wait_for_replicas.
"""

import time

import pytest

from redisson_tpu.client import RedissonTPU
from redisson_tpu.commands import OP_TABLE
from redisson_tpu.config import Config, ReplicaConfig
from redisson_tpu.replica import READ_KINDS


def make_replicated(tmp_path, n=2, **replica_kw):
    cfg = Config()
    cfg.use_local()
    cfg.use_serve()
    cfg.use_persist(str(tmp_path / "primary")).fsync = "always"
    rc = cfg.use_replicas(n)
    rc.poll_interval_s = 0.005
    rc.health_interval_s = 0.0  # deterministic tests drive failover manually
    for k, v in replica_kw.items():
        setattr(rc, k, v)
    return RedissonTPU.create(cfg)


def _wait_caught_up(c, n=2, timeout_s=10.0):
    assert c.wait_for_replicas(n, timeout_s=timeout_s) == n


# ---------------------------------------------------------------------------
# 1. read set derivation
# ---------------------------------------------------------------------------

def test_read_kinds_derived_from_op_table():
    assert READ_KINDS  # non-empty: the engine has read ops
    for kind in READ_KINDS:
        assert not OP_TABLE[kind].write
    # every write kind stays on the primary
    assert not any(OP_TABLE[k].write for k in READ_KINDS)
    # parked blocking reads (and their control ops) are pinned to the
    # primary: a bpop on a replica would wait on a frozen snapshot forever.
    assert "bpop" not in READ_KINDS
    assert "bpop_cancel" not in READ_KINDS


# ---------------------------------------------------------------------------
# 2. config plumbing
# ---------------------------------------------------------------------------

def test_config_replicas_roundtrip():
    cfg = Config()
    rc = cfg.use_replicas(3)
    rc.max_lag_seqs = 77
    rc.read_your_writes = False
    d = cfg.to_dict()
    back = Config.from_dict(d)
    assert isinstance(back.replicas, ReplicaConfig)
    assert back.replicas.num_replicas == 3
    assert back.replicas.max_lag_seqs == 77
    assert back.replicas.read_your_writes is False


def test_replicas_require_persist(tmp_path):
    cfg = Config()
    cfg.use_local()
    cfg.use_replicas(1)  # no use_persist: nothing to tail
    with pytest.raises(ValueError, match="persist"):
        RedissonTPU.create(cfg)


# ---------------------------------------------------------------------------
# 3. routing
# ---------------------------------------------------------------------------

def test_reads_route_to_replicas_and_match(tmp_path):
    c = make_replicated(tmp_path, n=2)
    try:
        m = c.get_map("m")
        for i in range(30):
            m.put(f"k{i}", i)
        _wait_caught_up(c, 2)
        for i in range(10):
            assert m.get(f"k{i}") == i
        snap = c._dispatch.snapshot()
        assert snap["replica_reads"] >= 10  # reads left the primary
        assert snap["watermarks"] and all(
            w >= 30 for w in snap["watermarks"].values())
        # writes stayed on the primary journal
        assert c.persist.journal.last_seq >= 30
    finally:
        c.shutdown()


def test_stale_replica_falls_back_to_primary(tmp_path):
    c = make_replicated(tmp_path, n=1, max_lag_seqs=2, read_your_writes=False)
    try:
        m = c.get_map("m")
        m.put("k", 1)
        _wait_caught_up(c, 1)
        rep = c.replicas.replicas[0]
        rep._stop.set()  # freeze the tail loop: watermark stops advancing
        time.sleep(0.05)
        frozen = rep.applied_seq
        for i in range(10):  # push primary_seq > frozen + max_lag
            m.put(f"x{i}", i)
        assert c.persist.journal.last_seq - frozen > 2
        before = c._dispatch.primary_fallbacks
        fut, picked, _ = c._dispatch.routed_read("m", "hget",
                                                 {"field": b'"x9"'})
        fut.result(timeout=30)
        assert picked is None  # outside the bound -> primary served it
        assert c._dispatch.primary_fallbacks == before + 1
        # widening the bound makes the frozen replica eligible again
        _, picked, watermark = c._dispatch.routed_read(
            "m", "hget", {"field": b'"k"'}, max_lag=10_000)
        assert picked is rep and watermark == frozen
    finally:
        c.shutdown()


def test_read_your_writes_pins_above_acked_seq(tmp_path):
    c = make_replicated(tmp_path, n=1, max_lag_seqs=10_000)
    try:
        m = c.get_map("m")
        m.put("k", 1)
        _wait_caught_up(c, 1)
        rep = c.replicas.replicas[0]
        rep._stop.set()  # freeze; subsequent acked writes outrun it
        time.sleep(0.05)
        m.put("k", 2)  # acked (fsync=always) -> RYW pin rises above replica
        assert c._dispatch.acked_seq("") >= c.persist.journal.last_seq - 1
        _, picked, _ = c._dispatch.routed_read("m", "hget",
                                               {"field": b'"k"'})
        assert picked is None  # RYW: stale replica may not serve this tenant
        # the same read with RYW off happily takes the stale replica
        _, picked, _ = c._dispatch.routed_read(
            "m", "hget", {"field": b'"k"'}, read_your_writes=False)
        assert picked is rep
    finally:
        c.shutdown()


# ---------------------------------------------------------------------------
# 4. failover
# ---------------------------------------------------------------------------

def test_failover_promotes_highest_watermark_zero_loss(tmp_path):
    c = make_replicated(tmp_path, n=2)
    try:
        m = c.get_map("m")
        for i in range(10):
            m.put(f"k{i}", i)
        _wait_caught_up(c, 2)
        lagger = c.replicas.replicas[0]
        lagger._stop.set()  # replica-0 freezes; replica-1 keeps tailing
        time.sleep(0.05)
        for i in range(10, 25):
            m.put(f"k{i}", i)  # every one acked under fsync=always
        _wait_caught_up(c, 1)
        mgr = c.replicas
        c._executor.shutdown(wait=False)  # primary dies
        promoted = mgr.failover("test kill")
        assert promoted is not None
        assert mgr._promoted.name == "replica-1"  # highest watermark wins
        assert mgr.promotions == 1
        # a second trigger is a no-op: first one won
        assert mgr.failover("late trigger") is None
        # zero acked writes lost on the promoted primary
        pm = promoted.get_map("m")
        for i in range(25):
            assert pm.get(f"k{i}") == i
        # the promoted journal CONTINUES the global numbering
        assert promoted._persist.journal.last_seq >= 25
        # writes flow through the router to the new primary
        m2 = c.get_map("m")
        m2.put("post", 99)
        assert m2.get("post") == 99
        # the lagging survivor full-resynced from the new snapshot (its
        # suffix lives only in the fenced old journal)
        deadline = time.monotonic() + 10
        while lagger.lag() > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert lagger.applied_seq >= 25
        assert lagger._full_resyncs >= 2
        # the demoted slot rejoins as a fresh replica and catches up
        rejoined = mgr.rejoin()
        assert c.wait_for_replicas(2, timeout_s=10.0) == 2
        assert rejoined.applied_seq >= 26
    finally:
        c.shutdown()


def test_failover_fences_live_old_primary(tmp_path):
    # The DeviceLostFault trigger retires the fault but the executor keeps
    # running: without a fence, writes racing the failover would be acked
    # into the old journal and silently lost. The fence makes them fail.
    c = make_replicated(tmp_path, n=2)
    try:
        m = c.get_map("m")
        for i in range(10):
            m.put(f"k{i}", i)
        _wait_caught_up(c, 2)
        old_journal = c.persist.journal
        mgr = c.replicas
        promoted = mgr.failover("manual, primary still alive")
        assert promoted is not None
        # old journal is fenced: last_seq is final, appends are refused
        assert old_journal.stats()["fenced"]
        fenced_seq = old_journal.last_seq
        assert mgr.last_fence_seq == fenced_seq
        # the promotion watermark reached the fenced tip -> zero acked loss
        assert mgr._promoted.applied_seq == fenced_seq
        pm = promoted.get_map("m")
        for i in range(10):
            assert pm.get(f"k{i}") == i
        # a write straggling onto the OLD (live!) primary fails instead of
        # being acked into the abandoned journal
        with pytest.raises(Exception, match="fenced"):
            c._executor.execute_sync("m", "hput",
                                     {"field": b'"zz"', "value": b"1"})
        assert old_journal.last_seq == fenced_seq
        # router writes flow to the new primary (fence was lifted)
        m.put("post", 1)
        assert m.get("post") == 1
        assert promoted._persist.journal.last_seq > fenced_seq
    finally:
        c.shutdown()


def test_failover_with_empty_fleet_aborts_cleanly(tmp_path):
    c = make_replicated(tmp_path, n=1)
    try:
        mgr = c.replicas
        for rep in list(mgr.replicas):
            rep.close()
        mgr.replicas = []
        assert mgr.failover("nothing to promote") is None
        assert mgr._failed_over is False  # not wedged half-failed-over
        assert "no replicas" in mgr.last_failover_reason
        # the fleet was never fenced: the primary still accepts writes
        c.get_bucket("b").set(1)
        assert c.get_bucket("b").get() == 1
        # a retry after capacity returns can still promote
        mgr.rejoin()
        _wait_caught_up(c, 1)
        assert mgr.failover("retry") is not None
    finally:
        c.shutdown()


def test_batch_writes_advance_ryw_pin_inline_acks(tmp_path):
    # Raw-executor primary (no serve layer): the router itself must attach
    # ack callbacks on the execute_many/batch paths, or batched writes
    # never advance the tenant pin and a stale replica serves the read-back.
    cfg = Config()
    cfg.use_local()
    cfg.use_persist(str(tmp_path / "primary")).fsync = "always"
    rc = cfg.use_replicas(1)
    rc.poll_interval_s = 0.005
    rc.health_interval_s = 0.0
    rc.max_lag_seqs = 10_000
    c = RedissonTPU.create(cfg)
    try:
        router = c._dispatch
        assert router._inline_acks  # no serve layer on this primary
        c.get_map("m").put("k", 0)
        _wait_caught_up(c, 1)
        rep = c.replicas.replicas[0]
        rep._stop.set()  # freeze: batched writes must outrun it
        time.sleep(0.05)
        batch = c.create_batch()
        bm = batch.get_map("m")
        for i in range(5):
            bm.put_async(f"b{i}", i)
        batch.execute()
        assert router.acked_seq("") >= c.persist.journal.last_seq - 1
        _, picked, _ = router.routed_read("m", "hget", {"field": b'"b4"'})
        assert picked is None  # RYW pin: the frozen replica may not serve
    finally:
        c.shutdown()


def test_replicas_inherit_sanitized_primary_config(tmp_path):
    cfg = Config()
    cfg.use_local()
    cfg.use_serve()
    cfg.codec = "pickle"
    cfg.use_persist(str(tmp_path / "primary")).fsync = "always"
    rc = cfg.use_replicas(1)
    rc.health_interval_s = 0.0
    c = RedissonTPU.create(cfg)
    try:
        rep_cfg = c.replicas.replicas[0].client.config
        # engine-affecting settings carry over...
        assert rep_cfg.codec == "pickle"
        assert rep_cfg.serve is not None
        # ...subsystems a replica must not run are stripped
        assert rep_cfg.persist is None
        assert rep_cfg.replicas is None
        assert rep_cfg.faults is None
    finally:
        c.shutdown()


def test_replica_read_honors_deadline_kwarg(tmp_path):
    from redisson_tpu.serve import DeadlineExceeded

    c = make_replicated(tmp_path, n=1, max_lag_seqs=10_000,
                        read_your_writes=False)
    try:
        c.get_map("m").put("k", 1)
        _wait_caught_up(c, 1)
        # an already-expired deadline must fail the read whether a replica
        # or the primary serves it
        fut, picked, _ = c._dispatch.routed_read(
            "m", "hget", {"field": b'"k"'}, deadline=time.monotonic() - 1.0)
        assert picked is not None  # a replica was chosen...
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=30)  # ...and it enforced the deadline
    finally:
        c.shutdown()


def test_wait_for_replicas_semantics(tmp_path):
    c = make_replicated(tmp_path, n=2)
    try:
        c.get_bucket("b").set(1)
        assert c.wait_for_replicas(2, timeout_s=10.0) == 2
        # asking for more replicas than exist times out with the true count
        assert c.wait_for_replicas(3, timeout_s=0.1) == 2
    finally:
        c.shutdown()


def test_replica_gauges_exported(tmp_path):
    c = make_replicated(tmp_path, n=2)
    try:
        c.get_bucket("b").set(1)
        _wait_caught_up(c, 2)
        gauges = c.metrics.snapshot()["gauges"]
        assert gauges["replica.count"] == 2
        assert gauges["replica.full_resyncs"] == 2  # one bootstrap each
        assert gauges["replica.min_watermark"] >= 1
        assert gauges["replica.max_lag"] >= 0
    finally:
        c.shutdown()
