import numpy as np
import pytest

from redisson_tpu.ops import hll
from tests import golden
from tests.helpers import hash_ints


def _insert_python(vals, p=hll.P):
    """Golden scalar insert path."""
    m = 1 << p
    regs = [0] * m
    for v in vals:
        h1, _ = golden.murmur3_x64_128(int(v).to_bytes(8, "little"))
        bucket = h1 & (m - 1)
        rest = (h1 >> p) | (1 << (64 - p))
        rank = 1
        while not rest & 1:
            rank += 1
            rest >>= 1
        regs[bucket] = max(regs[bucket], rank)
    return regs


def test_bucket_rank_matches_golden():
    vals = list(range(1, 200))
    h1, _ = hash_ints(vals)
    bucket, rank = hll.bucket_rank(h1)
    want = _insert_python(vals)
    regs = np.zeros(hll.M, np.int32)
    for b, r in zip(np.asarray(bucket), np.asarray(rank)):
        regs[b] = max(regs[b], r)
    assert regs.tolist() == want


@pytest.mark.parametrize("impl", ["scatter", "sort"])
def test_insert_impls_agree(impl):
    vals = list(range(10_000))
    h1, _ = hash_ints(vals)
    regs = hll.add_hashes_jit(hll.make(), h1, impl)
    want = np.asarray(_insert_python(vals), np.int32)
    assert np.array_equal(np.asarray(regs), want)


@pytest.mark.parametrize("n", [0, 1, 10, 100, 5_000, 200_000])
def test_count_accuracy(n):
    if n == 0:
        est = float(hll.count_jit(hll.make()))
        assert est == 0.0
        return
    vals = [v * 2654435761 + 12345 for v in range(n)]  # distinct keys
    h1, _ = hash_ints(vals)
    regs = hll.add_hashes_jit(hll.make(), h1, "sort")
    est = float(hll.count_jit(regs))
    # p=14 => stderr ~0.81%; allow 4 sigma (+small-n slack).
    tol = max(4 * 0.0081, 0.05 if n <= 100 else 0.04)
    assert abs(est - n) / n < tol, (est, n)


def test_merge_is_register_max_and_count_of_union():
    a_vals = list(range(0, 60_000))
    b_vals = list(range(30_000, 90_000))
    ha, _ = hash_ints(a_vals)
    hb, _ = hash_ints(b_vals)
    ra = hll.add_hashes_jit(hll.make(), ha, "sort")
    rb = hll.add_hashes_jit(hll.make(), hb, "sort")
    merged = hll.merge_jit(ra, rb)
    assert np.array_equal(np.asarray(merged), np.maximum(np.asarray(ra), np.asarray(rb)))
    est = float(hll.count_jit(merged))
    assert abs(est - 90_000) / 90_000 < 0.04
    # Idempotent: merging a sketch with itself changes nothing.
    assert np.array_equal(np.asarray(hll.merge_jit(ra, ra)), np.asarray(ra))


def test_merge_many():
    stacks = []
    for s in range(8):
        vals = list(range(s * 1000, s * 1000 + 2000))
        h1, _ = hash_ints(vals)
        stacks.append(np.asarray(hll.add_hashes_jit(hll.make(), h1, "sort")))
    merged = hll.merge_many(np.stack(stacks))
    assert np.array_equal(np.asarray(merged), np.max(np.stack(stacks), axis=0))
    est = float(hll.count_jit(merged))
    assert abs(est - 9000) / 9000 < 0.05

