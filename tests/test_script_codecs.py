"""RScript atomic-scripting tests + codec matrix (RedissonScript /
RedissonCodecTest analogues)."""

import threading

import pytest

from redisson_tpu.client import RedissonTPU
from redisson_tpu.codecs import (CompressionCodec, JsonCodec, MsgPackCodec,
                                 PickleCodec, get_codec)


@pytest.fixture()
def client():
    c = RedissonTPU.create()
    yield c
    c.shutdown()


def test_eval_basic(client):
    script = client.get_script()

    def put_and_count(ctx, keys, args):
        ctx.set(keys[0], args[0])
        return len(ctx.keys("s:*"))

    assert script.eval(put_and_count, keys=["s:a"], args=["v1"]) == 1
    assert script.eval(put_and_count, keys=["s:b"], args=["v2"]) == 2
    assert client.get_bucket("s:a", codec="string").get() == "v1"


def test_script_load_evalsha(client):
    script = client.get_script()

    def double(ctx, keys, args):
        return ctx.incr(keys[0], int(args[0]))

    sha = script.script_load(double)
    assert script.script_exists(sha) == [True]
    assert script.evalsha(sha, keys=["s:ctr"], args=[5]) == 5
    assert script.evalsha(sha, keys=["s:ctr"], args=[3]) == 8
    script.script_flush()
    assert script.script_exists(sha) == [False]
    with pytest.raises(ValueError, match="NOSCRIPT"):
        script.evalsha(sha, keys=["s:ctr"], args=[1])


def test_script_atomicity_under_concurrency(client):
    # The classic check-then-act that data-races without atomicity: N threads
    # transfer from one account; balance must never go negative.
    script = client.get_script()
    client.get_bucket("s:acct", codec="string").set("100")

    def withdraw(ctx, keys, args):
        bal = int(ctx.get(keys[0]) or 0)
        amount = int(args[0])
        if bal < amount:
            return False
        ctx.set(keys[0], str(bal - amount))
        return True

    sha = script.script_load(withdraw)
    results = []

    def worker():
        for _ in range(10):
            results.append(script.evalsha(sha, keys=["s:acct"], args=[7]))

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    final = int(client.get_bucket("s:acct", codec="string").get())
    granted = sum(1 for r in results if r)
    assert final == 100 - 7 * granted
    assert final >= 0


def test_script_sees_structures(client):
    client.get_map("s:m").put("k", "v")

    def read_map(ctx, keys, args):
        return ctx.hgetall(keys[0])

    raw = client.get_script().eval(read_map, keys=["s:m"])
    assert len(raw) == 1


def test_script_error_propagates(client):
    def boom(ctx, keys, args):
        raise RuntimeError("script exploded")

    with pytest.raises(RuntimeError, match="script exploded"):
        client.get_script().eval(boom)


def test_script_redis_mode_is_server_side_lua():
    """get_script() in redis mode now returns the EVAL/EVALSHA-backed
    RedisScript (server-side Lua via mini_lua on the fake server) — the old
    NotImplementedError gate is gone (VERDICT r1 item #3)."""
    from redisson_tpu.config import Config
    from redisson_tpu.interop.fake_server import EmbeddedRedis

    with EmbeddedRedis() as er:
        cfg = Config()
        cfg.use_redis().address = f"redis://127.0.0.1:{er.port}"
        c = RedissonTPU.create(cfg)
        try:
            script = c.get_script()
            assert script.eval("return 6 * 7") == 42
        finally:
            c.shutdown()


# ---------------------------------------------------------------------------
# Codec matrix
# ---------------------------------------------------------------------------

SAMPLES = [
    {"nested": {"list": [1, 2.5, "x"], "flag": True}},
    [1, 2, 3],
    "plain string",
    42,
]


@pytest.mark.parametrize("name", ["json", "pickle", "zlib", "msgpack"])
def test_codec_roundtrips(name):
    codec = get_codec(name)
    for sample in SAMPLES:
        assert codec.decode(codec.encode(sample)) == sample


def test_compression_codec_shrinks():
    codec = CompressionCodec(JsonCodec())
    value = {"k": "abc" * 1000}
    assert len(codec.encode(value)) < len(JsonCodec().encode(value))
    assert codec.decode(codec.encode(value)) == value


def test_gated_codec_clear_error():
    # cbor2/lz4/snappy are not in this image: must raise a helpful ValueError
    for name in ("cbor", "lz4", "snappy"):
        with pytest.raises(ValueError, match="optional package"):
            get_codec(name)


def test_objects_with_custom_codec(client):
    m = client.get_map("cdc:m", codec=MsgPackCodec())
    m.put("k", {"a": [1, 2]})
    assert m.get("k") == {"a": [1, 2]}
    b = client.get_bucket("cdc:b", codec=get_codec("zlib"))
    b.set({"big": "x" * 5000})
    assert b.get() == {"big": "x" * 5000}


def test_script_sha_distinguishes_closures(client):
    script = client.get_script()

    def make(n):
        def f(ctx, keys, args):
            return n
        return f

    sha1 = script.script_load(make(1))
    sha2 = script.script_load(make(2))
    assert sha1 != sha2
    assert script.evalsha(sha1) == 1
    assert script.evalsha(sha2) == 2
