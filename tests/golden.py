"""Pure-python golden implementations used to validate the JAX kernels.

Written independently from the canonical algorithm specs (smhasher for
MurmurHash3 x64 128, the xxHash spec for xxh64) — slow, scalar, obvious.
"""

MASK64 = (1 << 64) - 1


def _rotl64(x, n):
    return ((x << n) | (x >> (64 - n))) & MASK64


def _fmix64(k):
    k ^= k >> 33
    k = (k * 0xFF51AFD7ED558CCD) & MASK64
    k ^= k >> 33
    k = (k * 0xC4CEB9FE1A85EC53) & MASK64
    k ^= k >> 33
    return k


def murmur3_x64_128(data: bytes, seed: int = 0):
    c1 = 0x87C37B91114253D5
    c2 = 0x4CF5AD432745937F
    length = len(data)
    nblocks = length // 16
    h1 = h2 = seed & MASK64

    for i in range(nblocks):
        k1 = int.from_bytes(data[16 * i : 16 * i + 8], "little")
        k2 = int.from_bytes(data[16 * i + 8 : 16 * i + 16], "little")
        k1 = (k1 * c1) & MASK64
        k1 = _rotl64(k1, 31)
        k1 = (k1 * c2) & MASK64
        h1 ^= k1
        h1 = _rotl64(h1, 27)
        h1 = (h1 + h2) & MASK64
        h1 = (h1 * 5 + 0x52DCE729) & MASK64
        k2 = (k2 * c2) & MASK64
        k2 = _rotl64(k2, 33)
        k2 = (k2 * c1) & MASK64
        h2 ^= k2
        h2 = _rotl64(h2, 31)
        h2 = (h2 + h1) & MASK64
        h2 = (h2 * 5 + 0x38495AB5) & MASK64

    tail = data[nblocks * 16 :]
    k1 = k2 = 0
    for i in range(len(tail)):
        if i < 8:
            k1 |= tail[i] << (8 * i)
        else:
            k2 |= tail[i] << (8 * (i - 8))
    if len(tail) > 8:
        k2 = (k2 * c2) & MASK64
        k2 = _rotl64(k2, 33)
        k2 = (k2 * c1) & MASK64
        h2 ^= k2
    if len(tail) > 0:
        k1 = (k1 * c1) & MASK64
        k1 = _rotl64(k1, 31)
        k1 = (k1 * c2) & MASK64
        h1 ^= k1

    h1 ^= length
    h2 ^= length
    h1 = (h1 + h2) & MASK64
    h2 = (h2 + h1) & MASK64
    h1 = _fmix64(h1)
    h2 = _fmix64(h2)
    h1 = (h1 + h2) & MASK64
    h2 = (h2 + h1) & MASK64
    return h1, h2


_P1 = 0x9E3779B185EBCA87
_P2 = 0xC2B2AE3D27D4EB4F
_P3 = 0x165667B19E3779F9
_P4 = 0x85EBCA77C2B2AE63
_P5 = 0x27D4EB2F165667C5


def _xx_round(acc, lane):
    acc = (acc + lane * _P2) & MASK64
    acc = _rotl64(acc, 31)
    return (acc * _P1) & MASK64


def xxhash64(data: bytes, seed: int = 0):
    length = len(data)
    p = 0
    if length >= 32:
        v1 = (seed + _P1 + _P2) & MASK64
        v2 = (seed + _P2) & MASK64
        v3 = seed & MASK64
        v4 = (seed - _P1) & MASK64
        while p + 32 <= length:
            for i, v in enumerate((v1, v2, v3, v4)):
                lane = int.from_bytes(data[p + 8 * i : p + 8 * i + 8], "little")
                nv = _xx_round(v, lane)
                if i == 0:
                    v1 = nv
                elif i == 1:
                    v2 = nv
                elif i == 2:
                    v3 = nv
                else:
                    v4 = nv
            p += 32
        h = (_rotl64(v1, 1) + _rotl64(v2, 7) + _rotl64(v3, 12) + _rotl64(v4, 18)) & MASK64
        for v in (v1, v2, v3, v4):
            h ^= _xx_round(0, v)
            h = (h * _P1 + _P4) & MASK64
    else:
        h = (seed + _P5) & MASK64
    h = (h + length) & MASK64
    while p + 8 <= length:
        lane = int.from_bytes(data[p : p + 8], "little")
        h ^= _xx_round(0, lane)
        h = (_rotl64(h, 27) * _P1 + _P4) & MASK64
        p += 8
    if p + 4 <= length:
        lane = int.from_bytes(data[p : p + 4], "little")
        h ^= (lane * _P1) & MASK64
        h = (_rotl64(h, 23) * _P2 + _P3) & MASK64
        p += 4
    while p < length:
        h ^= (data[p] * _P5) & MASK64
        h = (_rotl64(h, 11) * _P1) & MASK64
        p += 1
    h ^= h >> 33
    h = (h * _P2) & MASK64
    h ^= h >> 29
    h = (h * _P3) & MASK64
    h ^= h >> 32
    return h


def murmur2_64a(data: bytes, seed: int = 0xADC83B19) -> int:
    """Scalar MurmurHash64A — independent reference for the redis-compat
    HLL hash (transcribed from the public MurmurHash2 spec; redis
    hyperloglog.c hllPatLen calls it with seed 0xadc83b19)."""
    m = 0xC6A4A7935BD1E995
    r = 47
    mask = (1 << 64) - 1
    h = (seed ^ (len(data) * m)) & mask
    nblocks = len(data) // 8
    for i in range(nblocks):
        k = int.from_bytes(data[8 * i : 8 * i + 8], "little")
        k = (k * m) & mask
        k ^= k >> r
        k = (k * m) & mask
        h ^= k
        h = (h * m) & mask
    tail = data[nblocks * 8 :]
    if tail:
        h ^= int.from_bytes(tail, "little")
        h = (h * m) & mask
    h ^= h >> r
    h = (h * m) & mask
    h ^= h >> r
    return h


def redis_hll_registers(keys, p: int = 14):
    """Registers exactly as a real Redis server builds them (hllPatLen):
    index = low p bits of MurmurHash64A(key, 0xadc83b19); rank = trailing
    zeros of (hash >> p | 1<<(64-p)) + 1. Independent of every repo kernel
    — the oracle that breaks the self-consistency cycle."""
    import numpy as np

    m = 1 << p
    regs = np.zeros(m, np.uint8)
    for key in keys:
        h = murmur2_64a(key)
        idx = h & (m - 1)
        rest = (h >> p) | (1 << (64 - p))
        rank = 1
        while rest & 1 == 0:
            rank += 1
            rest >>= 1
        regs[idx] = max(regs[idx], rank)
    return regs
