"""Service-tier tests: RPC remote service + cache manager.
Models the reference's RedissonRemoteServiceTest / spring cache tests."""

import time

import pytest

from redisson_tpu.client import RedissonTPU
from redisson_tpu.services import (CacheConfig, CacheManager,
                                   RemoteInvocationOptions,
                                   RemoteServiceAckTimeoutError,
                                   RemoteServiceTimeoutError, RRemoteService)
from redisson_tpu.services.remote import RemoteServiceError


@pytest.fixture()
def client():
    c = RedissonTPU.create()
    yield c
    c.shutdown()


class Calculator:
    def add(self, a, b):
        return a + b

    def fail(self):
        raise ValueError("boom")

    def slow(self):
        time.sleep(3)
        return "late"

    def echo_kwargs(self, **kw):
        return dict(sorted(kw.items()))


def test_rpc_roundtrip(client):
    rs = client.get_remote_service()
    rs.register("Calculator", Calculator(), workers=2)
    try:
        calc = rs.get("Calculator")
        assert calc.add(2, 3) == 5
        assert calc.echo_kwargs(b=2, a=1) == {"a": 1, "b": 2}
    finally:
        rs.shutdown()


def test_rpc_remote_exception_propagates(client):
    rs = client.get_remote_service()
    rs.register("Calculator", Calculator())
    try:
        calc = rs.get("Calculator")
        with pytest.raises(RemoteServiceError, match="ValueError: boom"):
            calc.fail()
    finally:
        rs.shutdown()


def test_rpc_ack_timeout_when_no_worker(client):
    rs = client.get_remote_service()
    # nothing registered: ack must time out quickly
    calc = rs.get("Calculator",
                  RemoteInvocationOptions(ack_timeout_s=0.2,
                                          execution_timeout_s=1.0))
    with pytest.raises(RemoteServiceAckTimeoutError):
        calc.add(1, 2)
    rs.shutdown()


def test_rpc_execution_timeout(client):
    rs = client.get_remote_service()
    rs.register("Calculator", Calculator())
    try:
        calc = rs.get("Calculator",
                      RemoteInvocationOptions(ack_timeout_s=1.0,
                                              execution_timeout_s=0.3))
        with pytest.raises(RemoteServiceTimeoutError):
            calc.slow()
    finally:
        rs.shutdown()


def test_rpc_fire_and_forget(client):
    hits = []

    class Sink:
        def record(self, x):
            hits.append(x)

    rs = client.get_remote_service()
    rs.register("Sink", Sink())
    try:
        sink = rs.get("Sink", RemoteInvocationOptions().no_result())
        assert sink.record("a") is None  # returns immediately
        deadline = time.time() + 2
        while not hits and time.time() < deadline:
            time.sleep(0.01)
        assert hits == ["a"]
    finally:
        rs.shutdown()


def test_rpc_async_proxy(client):
    rs = client.get_remote_service()
    rs.register("Calculator", Calculator(), workers=2)
    try:
        calc = rs.get_async("Calculator")
        futs = [calc.add(i, i) for i in range(10)]
        assert [f.result(timeout=5) for f in futs] == [2 * i for i in range(10)]
    finally:
        rs.shutdown()


def test_rpc_separate_service_instances_share_structures(client):
    # A second RRemoteService instance over the same engine (the reference's
    # in-JVM server+client pair) reaches the same queues. The facade getter
    # itself caches per name.
    assert client.get_remote_service() is client.get_remote_service()
    rs_server = client.get_remote_service()
    rs_server.register("Calculator", Calculator())
    rs_client = RRemoteService(client)  # independent instance, same queues
    try:
        assert rs_client.get("Calculator").add(10, 5) == 15
    finally:
        rs_server.shutdown()
        rs_client.shutdown()


# ---------------------------------------------------------------------------
# Cache manager
# ---------------------------------------------------------------------------


def test_cache_basic(client):
    cm = client.get_cache_manager({"users": {"ttl_s": None}})
    cache = cm.get_cache("users")
    cache.put("u1", {"name": "ada"})
    assert cache.get("u1") == {"name": "ada"}
    assert cache.get("nope", "dflt") == "dflt"
    cache.evict("u1")
    assert cache.get("u1") is None


def test_cache_ttl_expiry(client):
    cm = CacheManager(client, {"short": {"ttl_s": 0.2}})
    cache = cm.get_cache("short")
    cache.put("k", "v")
    assert cache.get("k") == "v"
    time.sleep(0.4)
    assert cache.get("k") is None


def test_cache_put_if_absent_and_clear(client):
    cache = client.get_cache_manager().get_cache("pia")
    assert cache.put_if_absent("k", 1) is None
    assert cache.put_if_absent("k", 2) == 1
    assert cache.size() == 1
    cache.clear()
    assert cache.size() == 0


def test_cached_decorator(client):
    cm = client.get_cache_manager()
    calls = []

    @cm.cached("memo")
    def expensive(x):
        calls.append(x)
        return x * 10

    assert expensive(3) == 30
    assert expensive(3) == 30
    assert calls == [3]  # second call served from cache
    assert expensive(4) == 40
    assert calls == [3, 4]


def test_cache_manager_from_json(client):
    cm = CacheManager.from_json(client, '{"a": {"ttl_s": 5}, "b": {}}')
    assert cm.cache_names() == ["a", "b"]
    assert cm.get_cache("a")._config.ttl_s == 5


def test_cached_decorator_caches_none(client):
    cm = client.get_cache_manager()
    calls = []

    @cm.cached("memo_none")
    def maybe(x):
        calls.append(x)
        return None

    assert maybe(1) is None
    assert maybe(1) is None
    assert calls == [1]  # None results are cached, not recomputed


def test_cache_clear_with_ttl_policy(client):
    cm = client.get_cache_manager({"t": {"ttl_s": 60}})
    cache = cm.get_cache("t")
    cache.put("a", 1)
    cache.put("b", 2)
    assert cache.size() == 2
    cache.clear()  # RMapCache backing must support clear
    assert cache.size() == 0
    cache.put("c", 3)  # still usable (eviction schedule intact)
    assert cache.get("c") == 3
