"""Wire front-end tests: RESP framing, conformance vs the facade,
pipeline reply ordering, shed paths and connection-drop chaos.

The wire server speaks the same RESP bytes as real Redis, so the
bundled interop client (and redis-py, when importable) should observe
results identical to calling the facade directly.
"""

import socket
import time

import pytest

from redisson_tpu.client import RedissonTPU
from redisson_tpu.config import Config
from redisson_tpu.fault import inject
from redisson_tpu.fault.inject import FaultInjector, FaultPlan, FaultRule
from redisson_tpu.interop.resp_client import SyncRespClient
from redisson_tpu.wire import proto


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _wire_client(**wire_kw):
    cfg = Config()
    cfg.use_serve()
    tr = cfg.use_trace()
    tr.sample_every = 1
    tr.slowlog_threshold_ms = 0.0
    w = cfg.use_wire()
    for k, v in wire_kw.items():
        setattr(w, k, v)
    return RedissonTPU(cfg)


def _connect(c, **kw):
    cli = SyncRespClient("127.0.0.1", c.wire.port, retry_attempts=1, **kw)
    cli.connect()
    return cli


def _raw_connect(c):
    s = socket.create_connection(("127.0.0.1", c.wire.port), timeout=5.0)
    s.settimeout(5.0)
    return s


def _raw_read_frames(sock, parser, n, deadline_s=5.0):
    """Read exactly n frames from a raw socket, or fewer on EOF."""
    frames = []
    end = time.monotonic() + deadline_s
    while len(frames) < n and time.monotonic() < end:
        try:
            data = sock.recv(65536)
        except socket.timeout:
            break
        if not data:
            break
        frames.extend(parser.feed(data))
    return frames


# ---------------------------------------------------------------------------
# proto: frame rendering + single shared codec
# ---------------------------------------------------------------------------


class TestProto:
    def test_simple_frames(self):
        assert proto.ok() == b"+OK\r\n"
        assert proto.simple("PONG") == b"+PONG\r\n"
        assert proto.integer(42) == b":42\r\n"
        assert proto.bulk(b"ab\r\nc") == b"$5\r\nab\r\nc\r\n"
        assert proto.bulk(None) == b"$-1\r\n"
        assert proto.array([proto.integer(1), proto.bulk(b"x")]) == (
            b"*2\r\n:1\r\n$1\r\nx\r\n"
        )

    def test_err_flattens_newlines(self):
        frame = proto.err("bad\r\nthing", code="ERR")
        assert frame.startswith(b"-ERR ")
        assert frame.endswith(b"\r\n")
        assert frame.count(b"\r\n") == 1

    def test_null_per_protocol(self):
        assert proto.null(proto.RESP2) == b"$-1\r\n"
        assert proto.null(proto.RESP3) == b"_\r\n"

    def test_map_reply_resp2_vs_resp3(self):
        pairs = [(b"a", proto.integer(1))]
        assert proto.map_reply(pairs, proto.RESP2).startswith(b"*2\r\n")
        assert proto.map_reply(pairs, proto.RESP3).startswith(b"%1\r\n")

    def test_redirect_and_busy_frames(self):
        assert proto.moved(100, "1.2.3.4:7000") == b"-MOVED 100 1.2.3.4:7000\r\n"
        assert proto.ask(100, "1.2.3.4:7000") == b"-ASK 100 1.2.3.4:7000\r\n"
        busy = proto.busy("shed", 0.05)
        assert busy.startswith(b"-BUSY retry_after=0.050s")

    def test_roundtrip_through_parser(self):
        p = proto.RespParser()
        frames = p.feed(proto.array([proto.integer(7), proto.bulk(b"hi")]))
        assert frames == [[7, b"hi"]]
        p.close()

    def test_fake_server_uses_shared_codec(self):
        # Satellite 1: one RESP implementation per direction.  The fake
        # interop server's render helpers must BE the proto functions.
        from redisson_tpu.interop import fake_server

        assert fake_server._ok is proto.ok
        assert fake_server._err is proto.err
        assert fake_server._int is proto.integer
        assert fake_server._bulk is proto.bulk
        assert fake_server._array is proto.array

    def test_resp_client_uses_shared_codec(self):
        import redisson_tpu.interop.resp_client as rc

        assert rc.proto is proto
        assert rc.RespError is proto.RespError


# ---------------------------------------------------------------------------
# conformance: wire vs facade on golden vectors
# ---------------------------------------------------------------------------


GOLDEN_HLL = [b"alpha", b"beta", b"gamma", b"\x00\xffbin", b"alpha"]
GOLDEN_BITS = [0, 1, 7, 63, 300]


class TestConformance:
    def test_command_table_matches_facade(self):
        c = _wire_client()
        try:
            cli = _connect(c)
            try:
                # HyperLogLog family over the wire...
                assert cli.execute("PFADD", "w:hll", *GOLDEN_HLL) == 1
                assert cli.execute("PFADD", "w:hll2", b"delta", b"beta") == 1
                wire_count = cli.execute("PFCOUNT", "w:hll")
                wire_union = cli.execute("PFCOUNT", "w:hll", "w:hll2")
                assert cli.execute("PFMERGE", "w:dest", "w:hll", "w:hll2") == b"OK"
                wire_merged = cli.execute("PFCOUNT", "w:dest")

                # ...must equal the same vectors pushed through the facade.
                f = c.get_hyper_log_log("f:hll")
                f.add_all([v for v in GOLDEN_HLL])
                f2 = c.get_hyper_log_log("f:hll2")
                f2.add_all([b"delta", b"beta"])
                assert wire_count == f.count()
                assert wire_union == f.count_with("f:hll2")
                dest = c.get_hyper_log_log("f:dest")
                dest.merge_with("f:hll", "f:hll2")
                assert wire_merged == dest.count()

                # Bitset family.
                for i in GOLDEN_BITS:
                    assert cli.execute("SETBIT", "w:bits", str(i), "1") == 0
                assert cli.execute("SETBIT", "w:bits", "1", "0") == 1
                assert cli.execute("GETBIT", "w:bits", "7") == 1
                assert cli.execute("GETBIT", "w:bits", "1") == 0
                fb = c.get_bit_set("f:bits")
                for i in GOLDEN_BITS:
                    fb.set(i)
                fb.clear(1)
                assert cli.execute("BITCOUNT", "w:bits") == fb.cardinality()

                # Keyspace commands agree with the facade's view.
                assert cli.execute("EXISTS", "w:hll", "w:bits", "w:nope") == 2
                assert cli.execute("DBSIZE") == len(c.keys())
                assert cli.execute("DEL", "w:hll2") == 1
                assert cli.execute("EXISTS", "w:hll2") == 0
            finally:
                cli.close()
        finally:
            c.shutdown()

    def test_bitop_over_wire(self):
        c = _wire_client()
        try:
            cli = _connect(c)
            try:
                cli.execute("SETBIT", "a", "0", "1")
                cli.execute("SETBIT", "a", "3", "1")
                cli.execute("SETBIT", "b", "3", "1")
                cli.execute("SETBIT", "b", "9", "1")
                nbytes = cli.execute("BITOP", "AND", "a", "a", "b")
                assert isinstance(nbytes, int) and nbytes >= 1
                assert cli.execute("BITCOUNT", "a") == 1
                assert cli.execute("GETBIT", "a", "3") == 1
            finally:
                cli.close()
        finally:
            c.shutdown()

    def test_introspection_surface(self):
        c = _wire_client()
        try:
            cli = _connect(c)
            try:
                assert cli.execute("PING") == b"PONG"
                assert cli.execute("ECHO", "hey") == b"hey"
                info = cli.execute("INFO")
                assert b"# wire" in info and b"redis_version" in info
                cli.execute("PFADD", "m:k", "x")
                usage = cli.execute("MEMORY", "USAGE", "m:k")
                assert isinstance(usage, int) and usage > 0
                assert cli.execute("MEMORY", "USAGE", "m:missing") is None
                stats = cli.execute("MEMORY", "STATS")
                assert isinstance(stats, list) and stats
                assert isinstance(cli.execute("MEMORY", "DOCTOR"), bytes)
                assert isinstance(cli.execute("SLOWLOG", "LEN"), int)
                assert isinstance(cli.execute("SLOWLOG", "GET"), list)
                assert cli.execute("SLOWLOG", "RESET") == b"OK"
                assert cli.execute("CLUSTER", "KEYSLOT", "m:k") == (
                    __import__(
                        "redisson_tpu.ops.crc16", fromlist=["key_slot"]
                    ).key_slot(b"m:k")
                )
                assert cli.execute("SELECT", "0") == b"OK"
                assert isinstance(cli.execute("COMMAND", "COUNT"), int)
                assert isinstance(cli.execute("CLIENT", "ID"), int)
                assert cli.execute("CLIENT", "SETNAME", "t1") == b"OK"
                assert cli.execute("CLIENT", "GETNAME") == b"t1"
            finally:
                cli.close()
        finally:
            c.shutdown()

    def test_hello_negotiates_resp3(self):
        c = _wire_client()
        try:
            cli = _connect(c)
            try:
                h2 = cli.execute("HELLO", "2")
                assert isinstance(h2, list)  # RESP2 renders map as flat array
                assert b"proto" in h2
            finally:
                cli.close()
            # The bundled parser is RESP2-only, so check the RESP3 map
            # upgrade at the byte level on a raw socket.
            sock = _raw_connect(c)
            try:
                sock.sendall(proto.resp_encode(b"HELLO", b"3"))
                data = b""
                while b"\r\nmodules\r\n" not in data and b"modules" not in data:
                    chunk = sock.recv(65536)
                    if not chunk:
                        break
                    data += chunk
                assert data.startswith(b"%")  # RESP3 map header
                assert b"proto\r\n:3\r\n" in data
            finally:
                sock.close()
        finally:
            c.shutdown()

    def test_slowlog_entries_carry_admitted_stage(self):
        # admitted_at is stamped at socket read; with slowlog threshold 0
        # every wire op lands in the slowlog with an "admitted" event.
        c = _wire_client()
        try:
            cli = _connect(c)
            try:
                cli.execute("PFADD", "sl:k", "v1", "v2")
                cli.execute("PFCOUNT", "sl:k")
            finally:
                cli.close()
            entries = c.trace.slowlog.get(None)
            assert entries
            names = {ev[0] for e in entries for ev in e.events}
            assert "admitted" in names
        finally:
            c.shutdown()

    def test_redis_py_roundtrip(self):
        redis = pytest.importorskip("redis")
        c = _wire_client()
        try:
            r = redis.Redis(host="127.0.0.1", port=c.wire.port)
            assert r.ping()
            assert r.pfadd("rp:hll", "a", "b", "c") == 1
            assert r.pfcount("rp:hll") == c.get_hyper_log_log("rp:hll").count()
            assert r.setbit("rp:bits", 5, 1) == 0
            assert r.getbit("rp:bits", 5) == 1
            assert r.bitcount("rp:bits") == 1
            r.close()
        finally:
            c.shutdown()


# ---------------------------------------------------------------------------
# reply ordering: the CommandsQueue dual
# ---------------------------------------------------------------------------


class TestReplyOrder:
    def test_pipeline_replies_in_submission_order(self):
        c = _wire_client()
        try:
            cli = _connect(c)
            try:
                cmds, expect = [], []
                for i in range(16):
                    cmds.append(("SETBIT", "ord:bits", str(i), "1"))
                    expect.append(0)
                    cmds.append(("ECHO", "m%d" % i))
                    expect.append(b"m%d" % i)
                out = cli.pipeline(cmds)
                assert out == expect
            finally:
                cli.close()
        finally:
            c.shutdown()

    def test_inline_replies_ordered_behind_engine_commands(self):
        # PING after a PFADD in the same pipeline must not jump the queue
        # even though it needs no engine round-trip.
        c = _wire_client()
        try:
            cli = _connect(c)
            try:
                out = cli.pipeline(
                    [
                        ("PFADD", "q:k", "a"),
                        ("PING",),
                        ("PFCOUNT", "q:k"),
                        ("PING",),
                    ]
                )
                assert out == [1, b"PONG", 1, b"PONG"]
            finally:
                cli.close()
        finally:
            c.shutdown()

    def test_two_connections_do_not_cross_replies(self):
        c = _wire_client()
        try:
            a, b = _connect(c), _connect(c)
            try:
                oa = a.pipeline([("ECHO", "from-a%d" % i) for i in range(8)])
                ob = b.pipeline([("ECHO", "from-b%d" % i) for i in range(8)])
                assert oa == [b"from-a%d" % i for i in range(8)]
                assert ob == [b"from-b%d" % i for i in range(8)]
            finally:
                a.close()
                b.close()
        finally:
            c.shutdown()


# ---------------------------------------------------------------------------
# shedding: inflight cap, connection limit, RejectedError rendering
# ---------------------------------------------------------------------------


class TestShedding:
    def test_inflight_cap_sheds_busy_in_position(self):
        cap = 4
        c = _wire_client(max_inflight_per_conn=cap)
        try:
            # One write carrying 12 frames: the read loop reserves slots
            # for all frames before any completion can drain the window,
            # so frames cap+1.. deterministically shed.
            sock = _raw_connect(c)
            parser = proto.RespParser()
            try:
                total = 12
                payload = b"".join(
                    proto.resp_encode(b"SETBIT", b"shed:bits", str(i).encode(), b"1")
                    for i in range(total)
                )
                sock.sendall(payload)
                frames = _raw_read_frames(sock, parser, total)
                assert len(frames) == total
                busy = [f for f in frames if isinstance(f, proto.RespError)]
                okay = [f for f in frames if not isinstance(f, proto.RespError)]
                assert len(okay) == cap and all(f == 0 for f in okay)
                assert len(busy) == total - cap
                assert all(str(e).startswith("BUSY") for e in busy)
                # Position: accepted commands are exactly the first `cap`.
                assert not any(
                    isinstance(f, proto.RespError) for f in frames[:cap]
                )
                # Shed commands never reached the engine.
                bits = c.get_bit_set("shed:bits")
                assert bits.cardinality() == cap
                assert c.wire.snapshot()["sheds_total"] >= total - cap
            finally:
                parser.close()
                sock.close()
        finally:
            c.shutdown()

    def test_connection_limit_shed(self):
        c = _wire_client(max_connections=1)
        try:
            keeper = _connect(c)
            try:
                sock = _raw_connect(c)
                parser = proto.RespParser()
                try:
                    frames = _raw_read_frames(sock, parser, 1)
                    assert frames and isinstance(frames[0], proto.RespError)
                    assert str(frames[0]).startswith("BUSY")
                    # Server closes the shed connection.
                    assert sock.recv(1) == b""
                finally:
                    parser.close()
                    sock.close()
                # Survivor connection still works.
                assert keeper.execute("PING") == b"PONG"
            finally:
                keeper.close()
        finally:
            c.shutdown()

    def test_rejected_error_renders_busy_with_retry_after(self):
        import types

        from redisson_tpu.serve.errors import RejectedError
        from redisson_tpu.wire.server import WireServer

        stub = types.SimpleNamespace(_cluster=None, sheds_total=0,
                                     redirects_rendered=0)
        state = types.SimpleNamespace(
            exc=RejectedError("queue full", retry_after_s=0.25))
        frame = WireServer._render_error(stub, state)
        assert frame.startswith(b"-BUSY retry_after=0.250s")
        assert stub.sheds_total == 1


# ---------------------------------------------------------------------------
# chaos: wire_conn fault seam
# ---------------------------------------------------------------------------


class TestWireChaos:
    def test_dropped_connection_loses_no_acks(self):
        # Rule fires on the 2nd read of the connection: the first pipeline
        # is fully acknowledged, the second write kills the connection
        # before any of its frames are dispatched.  Delivered replies must
        # be an exact, in-order, correctly-valued prefix.
        inj = FaultInjector(
            FaultPlan(rules=[FaultRule(seam="wire_conn", nth=2, times=1)])
        )
        inject.install(inj)
        c = _wire_client()
        try:
            sock = _raw_connect(c)
            parser = proto.RespParser()
            try:
                first = b"".join(
                    proto.resp_encode(b"SETBIT", b"chaos:bits", str(i).encode(), b"1")
                    for i in range(3)
                )
                sock.sendall(first)
                frames = _raw_read_frames(sock, parser, 3)
                assert frames == [0, 0, 0]  # no lost acks, correct values

                # Second write trips the seam: server drops the connection
                # without processing the frame.
                sock.sendall(proto.resp_encode(b"SETBIT", b"chaos:bits", b"9", b"1"))
                tail = _raw_read_frames(sock, parser, 1, deadline_s=3.0)
                assert tail == []  # EOF, no partial/misattributed reply
            finally:
                parser.close()
                sock.close()

            # Engine state reflects exactly the acknowledged prefix.
            bits = c.get_bit_set("chaos:bits")
            assert bits.cardinality() == 3
            assert bits.get(9) is False
            assert c.wire.snapshot()["dropped_conns"] == 1

            # A fresh connection is unaffected (rule consumed its window).
            cli = _connect(c)
            try:
                assert cli.execute("PING") == b"PONG"
                assert cli.execute("GETBIT", "chaos:bits", "2") == 1
            finally:
                cli.close()
        finally:
            inject.uninstall()
            c.shutdown()

    def test_partial_pipeline_never_misattributed(self):
        # Drop mid-stream on the FIRST read of the second connection while
        # an untouched first connection keeps running: replies seen by the
        # survivor must all be its own.
        inj = FaultInjector(
            FaultPlan(rules=[FaultRule(seam="wire_conn", nth=1, times=1)])
        )
        c = _wire_client()
        try:
            survivor = _connect(c)
            try:
                inject.install(inj)
                try:
                    sock = _raw_connect(c)
                    parser = proto.RespParser()
                    try:
                        sock.sendall(proto.resp_encode(b"ECHO", b"victim"))
                        assert _raw_read_frames(sock, parser, 1, 3.0) == []
                    finally:
                        parser.close()
                        sock.close()
                finally:
                    inject.uninstall()
                out = survivor.pipeline(
                    [("ECHO", "sv%d" % i) for i in range(6)]
                )
                assert out == [b"sv%d" % i for i in range(6)]
            finally:
                survivor.close()
        finally:
            inject.uninstall()
            c.shutdown()


# ---------------------------------------------------------------------------
# lifecycle + observability
# ---------------------------------------------------------------------------


class TestLifecycle:
    def test_shutdown_stops_listener(self):
        c = _wire_client()
        port = c.wire.port
        assert port > 0
        c.shutdown()
        with pytest.raises(OSError):
            socket.create_connection(("127.0.0.1", port), timeout=1.0)

    def test_wire_gauges_registered(self):
        c = _wire_client()
        try:
            cli = _connect(c)
            try:
                cli.pipeline([("PFADD", "g:k", "a"), ("PFCOUNT", "g:k")])
            finally:
                cli.close()
            snap = c.metrics.snapshot()["gauges"]
            names = {k for k in snap if k.startswith("wire.")}
            for want in (
                "wire.connections",
                "wire.commands",
                "wire.engine_commands",
                "wire.pipeline_depth",
                "wire.sheds",
                "wire.dropped_conns",
            ):
                assert want in names, want
            assert snap["wire.commands"] >= 2
            assert snap["wire.engine_commands"] >= 2
        finally:
            c.shutdown()

    def test_auth_gate(self):
        c = _wire_client(password="sekret")
        try:
            sock = _raw_connect(c)
            parser = proto.RespParser()
            try:
                sock.sendall(proto.resp_encode(b"PFADD", b"a:k", b"v"))
                frames = _raw_read_frames(sock, parser, 1)
                assert isinstance(frames[0], proto.RespError)
                assert str(frames[0]).startswith("NOAUTH")
                sock.sendall(proto.resp_encode(b"AUTH", b"wrong"))
                frames = _raw_read_frames(sock, parser, 1)
                assert str(frames[0]).startswith("WRONGPASS")
                sock.sendall(proto.resp_encode(b"AUTH", b"sekret"))
                frames = _raw_read_frames(sock, parser, 1)
                assert frames == [b"OK"]
                sock.sendall(proto.resp_encode(b"PFADD", b"a:k", b"v"))
                frames = _raw_read_frames(sock, parser, 1)
                assert frames == [1]
            finally:
                parser.close()
                sock.close()
        finally:
            c.shutdown()
